"""VerdictService: adaptive batching between the host data plane and the
TPU verdict engine.

The reference evaluates rules inline per request (http_listener.rs:
251-264). Here requests enqueue a RequestTuple and await a verdict; a
collector loop drains the queue into fixed-size batches under a latency
deadline (SURVEY.md §7 "Latency vs batching": adaptive window tuned
against the 2ms p99 budget), encodes them (engine/batch.py), runs the
jitted verdict, and resolves per-request futures with (matched_row,
first_action, bot_score).

Fail-open fallback (SURVEY.md §5 failure detection): if the device path
raises, the batch is evaluated on the host interpreter instead — same
verdicts (that is the parity contract), only slower — and the error is
counted on the metrics surface.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..compiler.plan import RulesetPlan
from ..config.schema import Action
from ..expr import execute_as_bool
from ..obs.flightrecorder import (FlightRecorder, register_recorder,
                                  tuple_digest)
from ..obs.perf import (batch_leading_dim, get_compile_ledger,
                        instrument_jit, instrument_megastep,
                        plan_fingerprint, set_dispatch_context,
                        staging_widths)
from ..obs.pipeline import PipelineStats
from ..obs.provenance import (ParityAuditor, PrefilterAttribution,
                              RuleAttribution, provenance_enabled)
from ..obs.timeline import get_timeline
from ..sched import MeshExecutor, MeshUnavailable, Scheduler, SchedulerConfig
from ..sched.scheduler import load_cost_ledger, save_cost_ledger
from .batch import (
    DeviceInputQueue,
    RequestBatch,
    RequestTuple,
    StagingEncoder,
    batch_to_contexts,
    bucket_arrays,
    encode_requests,
    pad_batch,
    pow2_batch_size,
    resolve_stage_caps,
    stage_overflow_thresholds,
    tuple_to_context,
)
from .verdict import (_resolve_megastep_mode, action_lanes, finish_batch,
                      finish_megastep, make_megastep_fn,
                      make_packed_prefilter_fn, make_packed_verdict_fn,
                      make_prefilter_fn, make_verdict_fn, megastep_k_cap,
                      megastep_k_ladder)

# Per-stage slices of the PINGOO_DEADLINE_MS budget (ISSUE 9,
# docs/EXECUTOR.md): cumulative launch-relative fractions a batch may
# have consumed when each HOST stage finishes before the whole batch
# fails open through the PINGOO_SCHED_FAILOPEN route (an overrunning
# encode must not stall the collector into the device dispatch; the
# compute stage's budget is the remainder and is enforced by the
# scheduler's unmeetable/deadline-miss machinery). Only enforced when
# the failopen policy is not `serve` — `serve` (the default) keeps
# verdicts flowing bit-identically and just counts the misses.
PIPELINE_STAGE_BUDGET = {"encode": 0.45, "dispatch": 0.75}


class _PlanSwap:
    """Admission-queue sentinel carrying a prepared ruleset hot-swap
    (ISSUE 11, docs/RESILIENCE.md). It travels the SAME queue as
    requests, so its queue position IS the epoch boundary: requests
    admitted ahead of it resolve on the old plan, requests behind it on
    the new one — no request is dropped or resolved twice."""

    __slots__ = ("plan", "lists", "tenant", "state", "fut")

    def __init__(self, plan, lists, tenant, state, fut):
        self.plan = plan
        self.lists = lists
        self.tenant = tenant
        self.state = state
        self.fut = fut


class _StageBudgetExceeded(RuntimeError):
    """A pipeline stage blew its slice of the deadline budget; the
    batch reroutes through the fail-open machinery instead of holding
    its pipeline slot through a doomed device round trip."""

    def __init__(self, stage: str, elapsed_ms: float):
        super().__init__(
            f"pipeline stage {stage!r} blew its deadline slice "
            f"({elapsed_ms:.3f} ms since launch)")
        self.stage = stage
        self.elapsed_ms = elapsed_ms


def force_cpu_backend() -> None:
    """Pin jax to the CPU platform before any device op runs.

    The ambient environment may pin JAX_PLATFORMS to an accelerator
    plugin that overrides the env var at registration time, so the
    config update (not the env var) is the authoritative pin."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_jax_backend(probe_timeout_s: float | None = None) -> bool:
    """Probe the jax backend, degrading accelerator failures to CPU.

    The ambient environment may pin JAX_PLATFORMS to an accelerator
    backend whose registration failed or whose transport is wedged
    (e.g. a dropped device tunnel). A failed registration makes any jax
    array op raise later; a wedged transport makes backend init HANG —
    so the probe runs `jax.devices()` in a SUBPROCESS with a deadline
    (PINGOO_DEVICE_PROBE_TIMEOUT_S, default 60 s; the first accelerator
    handshake is slow but bounded). On probe failure or timeout the
    process pins the CPU platform BEFORE its own first device op, which
    is what makes the device->CPU-XLA->interpreter degradation ladder
    reachable at all. Returns True if some backend works (possibly
    CPU), False if jax is unusable entirely.
    """
    import os
    import subprocess
    import sys

    try:
        import jax
    except Exception:
        return False

    if probe_timeout_s is None:
        probe_timeout_s = float(
            os.environ.get("PINGOO_DEVICE_PROBE_TIMEOUT_S", "60"))
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms != "cpu":
        # An accelerator may be in play (explicitly requested, or — with
        # the env var unset — auto-registered by an installed PJRT
        # plugin): probe it out-of-process so a hung transport cannot
        # hang us. The probe child inherits our env and so makes the
        # same backend choice this process would.
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print('ok')"],
                timeout=probe_timeout_s, capture_output=True)
            if proc.returncode != 0 or b"ok" not in proc.stdout:
                raise RuntimeError(proc.stderr.decode()[-200:])
        except Exception:
            force_cpu_backend()
    try:
        try:
            jax.devices()
            return True
        except RuntimeError:
            force_cpu_backend()
            jax.devices()
            return True
    except Exception:
        return False


@dataclass
class Verdict:
    action: int  # unverified-client lane: 0 none, 1 block, 2 captcha
    matched: np.ndarray  # [R] bool, original rule order
    bot_score: float = 0.0
    # Verified-client lane: the reference's action loop skips Captcha
    # actions for captcha-verified clients but still blocks on any
    # matched rule carrying Block (http_listener.rs:251-264).
    verified_block: bool = False
    # True when the engine failed and this verdict is the fail-open
    # placeholder: `matched` is all-False garbage, so consumers that
    # read non-action columns (service routing) must fall back to
    # interpretation instead of trusting it.
    degraded: bool = False
    # Ruleset hot-swap (ISSUE 11): which plan epoch evaluated this
    # request. Batches flip plans only at launch boundaries, so every
    # verdict in a batch carries the same epoch — the per-epoch
    # bit-exactness contract tests/test_hotswap.py asserts.
    epoch: int = 0

    @property
    def block(self) -> bool:
        return self.action == 1

    @property
    def captcha(self) -> bool:
        return self.action == 2

    def action_for(self, captcha_verified: bool) -> int:
        """0 none / 1 block / 2 captcha for this client's verification
        state — the decision the reference loop would reach."""
        if captcha_verified:
            return 1 if self.verified_block else 0
        return self.action


@dataclass
class ServiceStats:
    """Per-service counters + the shared-registry instruments.

    The pre-registry `verdict_ms` list grew to 65536 floats and then
    deleted half (unbounded resident memory, O(n) truncation on the hot
    path, and percentile math over a python list per scrape); the
    fixed-bucket registry histograms replace it — O(1) observe, O(1)
    snapshot — while `snapshot()` keeps returning the same percentile
    keys (now bucket-upper-bound estimates, the same convention the
    native plane's histogram percentiles use)."""

    batches: int = 0
    requests: int = 0
    device_errors: int = 0
    score_errors: int = 0
    host_fallback_batches: int = 0
    batch_occupancy_sum: int = 0
    # Batch dedup (ISSUE 4 satellite): identical RequestTuples inside
    # one collector batch are encoded/evaluated once, the verdict fanned
    # out to every duplicate's future.
    dedup_hits: int = 0
    # Literal-prefilter cascade counters (docs/PREFILTER.md).
    prefilter_candidate_rate: float = 0.0
    scan_banks_skipped: int = 0
    # Bitsplit-DFA dispatch counters (docs/DFA.md) — host-static per
    # plan+env, folded once per device batch.
    dfa_banks: int = 0
    dfa_rechecks: int = 0

    def __post_init__(self):
        from ..obs import REGISTRY
        from ..obs.registry import LATENCY_BUCKETS_MS, WAIT_BUCKETS_MS
        from ..obs.schema import DFA_METRICS, PREFILTER_METRICS, VERDICT_STAGES

        self.wait_hist = REGISTRY.histogram(
            "pingoo_verdict_wait_ms",
            "verdict wait: evaluate() -> resolve (ms)",
            buckets=WAIT_BUCKETS_MS, labels={"plane": "python"})
        self.stage_hist = {
            stage: REGISTRY.histogram(
                "pingoo_verdict_stage_ms",
                "verdict pipeline stage latency (ms)",
                buckets=LATENCY_BUCKETS_MS,
                labels={"plane": "python", "stage": stage})
            for stage in VERDICT_STAGES}
        self.pf_rate_gauge = REGISTRY.gauge(
            "pingoo_prefilter_candidate_rate",
            PREFILTER_METRICS["pingoo_prefilter_candidate_rate"],
            labels={"plane": "python"})
        self.pf_skip_counter = REGISTRY.counter(
            "pingoo_scan_banks_skipped_total",
            PREFILTER_METRICS["pingoo_scan_banks_skipped_total"],
            labels={"plane": "python"})
        self.dfa_banks_counter = {
            mode: REGISTRY.counter(
                "pingoo_dfa_banks_total",
                DFA_METRICS["pingoo_dfa_banks_total"],
                labels={"plane": "python", "mode": mode})
            for mode in ("auto", "force")}
        self.dfa_recheck_counter = REGISTRY.counter(
            "pingoo_dfa_recheck_total",
            DFA_METRICS["pingoo_dfa_recheck_total"],
            labels={"plane": "python"})
        # Compact staging (ISSUE 15): bytes actually staged to the
        # device per verdict batch, split by the PINGOO_STAGING arm —
        # the numerator of the dispatch-wall reduction this plane is
        # serving under.
        from ..obs.schema import STAGING_METRICS
        self.staged_bytes_counter = {
            mode: REGISTRY.counter(
                "pingoo_staged_bytes_total",
                STAGING_METRICS["pingoo_staged_bytes_total"],
                labels={"plane": "python", "mode": mode})
            for mode in ("full", "compact")}

    def observe_stage(self, stage: str, ms: float, n: int = 1) -> None:
        h = self.stage_hist[stage]
        if n == 1:
            h.observe(ms)
        else:
            h.observe_n(ms, n)

    def snapshot(self) -> dict:
        return {
            "batches": self.batches,
            "requests": self.requests,
            "device_errors": self.device_errors,
            "score_errors": self.score_errors,
            "host_fallback_batches": self.host_fallback_batches,
            "mean_occupancy": (self.batch_occupancy_sum / self.batches
                               if self.batches else 0.0),
            "dedup_hits": self.dedup_hits,
            "prefilter_candidate_rate": round(
                self.prefilter_candidate_rate, 4),
            "scan_banks_skipped": self.scan_banks_skipped,
            "dfa_banks": self.dfa_banks,
            "dfa_rechecks": self.dfa_rechecks,
            "verdict_p50_ms": self.wait_hist.percentile(0.50),
            "verdict_p99_ms": self.wait_hist.percentile(0.99),
            "stages": {
                stage: {"count": h.count,
                        "p50_ms": h.percentile(0.50),
                        "p99_ms": h.percentile(0.99),
                        "mean_ms": round(h.sum / h.count, 4)
                        if h.count else 0.0}
                for stage, h in self.stage_hist.items()},
        }


class VerdictService:
    """Async facade over the batched engine."""

    def __init__(
        self,
        plan: RulesetPlan,
        lists: dict,
        max_batch: int = 1024,
        max_wait_us: int = 300,
        device: Optional[object] = None,
        use_device: bool = True,
        bot_score_params: Optional[object] = None,
    ):
        self.plan = plan
        self.lists = lists
        self.max_batch = max_batch
        self.max_wait_s = max_wait_us / 1e6
        self.bot_score_params = bot_score_params
        self._score_fn = None
        self.stats = ServiceStats()
        self.use_device = use_device
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._verdict_fn = None
        self._tables = None
        self._pf_fn = None
        self._pf_gated_banks = 0
        self._pf_attr = None
        # Continuous-batching admission scheduler + serving mesh
        # (ISSUE 6, docs/SCHEDULER.md): the scheduler replaces the
        # fixed max_wait_us assembly window with a deadline-slack
        # launch policy (PINGOO_SCHED_MODE=fixed keeps the old
        # behavior); the mesh executor shards tables/batches when
        # PINGOO_MESH asks for more than one device.
        self.sched = Scheduler(SchedulerConfig.from_env(max_batch),
                               plane="python")
        # Perf ledger + cross-plane timeline + durable cost ledger
        # (ISSUE 17, docs/OBSERVABILITY.md): the compile ledger wraps
        # every jitted program this plane builds (zero hot-path delta
        # while PINGOO_PERF_LEDGER is off), the timeline samples
        # batches at PINGOO_TIMELINE_SAMPLE, and the scheduler's
        # CostModel reloads the prior run's measured EWMAs — keyed to
        # this backend + ruleset fingerprint — instead of re-seeding
        # from BENCH_history.
        self._plan_fp = plan_fingerprint(plan)
        self._perf = get_compile_ledger()
        self._perf.ensure_instruments("python")
        self._timeline = get_timeline()
        self._timeline.ensure_instruments("python")
        self._backend_label = "host"
        if use_device:
            try:
                import jax

                self._backend_label = str(jax.default_backend())
            except Exception:
                pass
        self.cost_ledger_result = load_cost_ledger(
            self.sched.cost, backend=self._backend_label,
            fingerprint=self._plan_fp, plane="python")
        # Degradation ladder (ISSUE 10, docs/RESILIENCE.md): this
        # plane's scattered fallbacks (staging->legacy encode,
        # DFA->NFA, mesh->single-device, device->interpreter) report
        # through one state machine — demotions are counted per rung
        # and probed back with exponential backoff.
        from .ladder import DegradationLadder

        self.ladder = DegradationLadder("python")
        self._dfa_probe = False
        self._dfa_mode0 = getattr(plan, "dfa_default_mode", "auto")
        self.mesh: Optional[MeshExecutor] = None
        # Double-buffered dispatch: up to this many batches in flight,
        # so batch N+1 assembles/encodes while batch N computes (the
        # first slice of the ROADMAP's pipelined-executor item).
        self._pipeline_depth = max(1, int(
            os.environ.get("PINGOO_SCHED_PIPELINE", "2")))
        self._inflight: set = set()
        # Overlapped zero-copy executor (ISSUE 9, docs/EXECUTOR.md):
        # PINGOO_PIPELINE=on (the default) encodes into reused staging
        # buffers and runs the evaluate chain as token-guarded stages
        # — batch N+1's encode overlaps batch N's device compute, but
        # two batches never fill staging or issue device work at the
        # same time. =off keeps the legacy per-batch-allocating chain
        # (the bench A/B arm and the bit-identity oracle).
        # PINGOO_PIPELINE_DEPTH overrides the in-flight batch bound.
        mode = os.environ.get("PINGOO_PIPELINE", "on").strip().lower()
        self.pipeline_mode = "off" if mode in ("off", "0", "false") else "on"
        try:
            self._pipeline_depth = max(1, int(os.environ.get(
                "PINGOO_PIPELINE_DEPTH", str(self._pipeline_depth))))
        except ValueError:
            pass
        self._pipe = PipelineStats("python", self._pipeline_depth)
        # Compact staging (ISSUE 15, docs/EXECUTOR.md "Compact
        # staging"): PINGOO_STAGING=compact stages plan-capped field
        # prefixes into ONE packed buffer and ships it in a single
        # device_put; the jitted programs slice the fields back out on
        # device. `full` (the default) keeps the per-field staging path
        # byte-for-byte untouched — the bit-identity oracle.
        self._stage_caps: Optional[dict] = None
        self._packed_verdict_fn = None
        self._packed_pf_fn = None
        self._staging: Optional[StagingEncoder] = None
        if self.pipeline_mode == "on":
            # nbuf = depth + 1: every in-flight batch holds one buffer
            # set and the collector encodes the next into another.
            self._staging = self._make_staging(plan)
        import threading as _threading

        # Per-stage in-flight tokens: host stages are serialized ACROSS
        # batches (the staging encoder's rotating buffers are checked
        # out non-atomically; two concurrent encodes would also just
        # fight over the GIL), while a batch holding no token — i.e.
        # blocked on device compute — lets the next batch's host work
        # run. That asymmetry IS the overlap.
        self._stage_tokens = {
            "encode": _threading.Lock(),
            "dispatch": _threading.Lock(),
        }
        # Verdict provenance (ISSUE 5): per-rule attribution, the
        # flight recorder, and the shadow-parity auditor. PINGOO_
        # PROVENANCE=0 turns the whole layer off; the parity auditor
        # additionally samples nothing until PINGOO_PARITY_SAMPLE > 0.
        self._last_batch_stages: dict = {}
        self.flight_recorder = None
        self._attribution = None
        self.parity = None
        if provenance_enabled():
            self.flight_recorder = register_recorder(FlightRecorder(
                "python", rule_names=plan.rule_names))
            self._attribution = RuleAttribution(plan.rule_names,
                                                plane="python")
            self.parity = ParityAuditor(plan, lists, plane="python",
                                        recorder=self.flight_recorder)
        # Ruleset hot-swap (ISSUE 11, docs/RESILIENCE.md): the plan
        # epoch this plane is serving (0 = boot plan); swap_plan()
        # prepares a new engine state off the serving path and the
        # collector flips to it at a batch boundary.
        self.ruleset_epoch = 0
        self.tenant = "default"
        self._device_hint = device
        from .hotswap import set_epoch_gauge

        set_epoch_gauge("python", 0)
        # Device-resident megastep (ISSUE 12, docs/EXECUTOR.md): the
        # matrix-kind K-slice program + double-buffered device input
        # queue live in the engine state (rebuilt per swap/demotion);
        # mega_echo_mismatch counts per-slice ruleset-epoch echoes that
        # disagreed with the plan the window was staged under.
        self._mega_fn = None
        self._mega_queue: Optional[DeviceInputQueue] = None
        self._mega_rungs = megastep_k_ladder(megastep_k_cap())
        self.mega_echo_mismatch = 0
        # Monotonic megastep window id (ISSUE 17 satellite): stamped
        # into every flight row a window serves, so stranded-slice
        # reconciliation after a mid-window SIGKILL is traceable per
        # window instead of per anonymous batch.
        self._mega_window_seq = 0
        if use_device and ensure_jax_backend():
            state = self._build_engine_state(plan, device)
            if state is None:
                self.use_device = False
            else:
                self._adopt_engine_state(state)
        else:
            self.use_device = False

    def _build_engine_state(self, plan: RulesetPlan,
                            device: Optional[object] = None
                            ) -> Optional[dict]:
        """Compile the plan-derived engine bundle (jitted fns, placed
        tables, mesh, staging buffers) WITHOUT touching the serving
        references. Backs both boot and swap_plan — for a swap it runs
        off the serving path, so admissions never wait on a compile.
        Returns None after a boot/build failure (fail-open: SURVEY.md
        §5 failure detection — a broken accelerator backend degrades to
        the XLA CPU engine, and a broken XLA entirely to the
        interpreter; never crash the data plane)."""
        try:
            import jax

            # Donated request buffers (ISSUE 9): XLA recycles each
            # pipelined batch's upload in place — requested only on
            # real accelerator backends (no-op + warning on cpu).
            from .verdict import donate_batch_buffers

            state: dict = {"plan": plan}
            # Compile-ledger wrapping (ISSUE 17): every jitted program
            # this state holds goes through instrument_jit so each XLA
            # trace/compile becomes a counted, persisted event. The
            # wrapper composes AFTER jax.jit — donation/static_argnums
            # semantics untouched — and is a no-op passthrough while
            # PINGOO_PERF_LEDGER is off.
            fp = plan_fingerprint(plan)
            widths = staging_widths(plan)

            def _wrap(fn, name):
                return instrument_jit(fn, name, plane="python",
                                      fingerprint=fp, widths=widths)

            state["verdict_fn"] = _wrap(make_verdict_fn(
                plan, donate=donate_batch_buffers()), "verdict")
            # Stage-A prefilter as its own dispatch so the pipeline
            # stage is separately timeable (None when the plan has
            # no factors or PINGOO_PREFILTER=off).
            pf = make_prefilter_fn(plan)
            state["pf_fn"] = \
                _wrap(pf.fn, "prefilter") if pf is not None else None
            state["pf_gated_banks"] = \
                len(pf.gated) if pf is not None else 0
            state["pf_attr"] = (
                PrefilterAttribution(pf.masked, plane="python")
                if pf is not None and provenance_enabled() else None)
            # Compact staging (ISSUE 15): the packed twins trace the
            # SAME predicate bodies over unpack_staged's device-side
            # slices; built only under PINGOO_STAGING=compact, so the
            # default path compiles nothing new.
            state["stage_caps"] = resolve_stage_caps(plan)
            state["packed_verdict_fn"] = None
            state["packed_pf_fn"] = None
            if state["stage_caps"] is not None:
                state["packed_verdict_fn"] = _wrap(
                    make_packed_verdict_fn(
                        plan, donate=donate_batch_buffers()), "verdict")
                ppf = make_packed_prefilter_fn(plan)
                state["packed_pf_fn"] = \
                    _wrap(ppf.fn, "prefilter") if ppf is not None \
                    else None
            # Mesh BEFORE table materialization: tp padding must
            # land in plan.np_tables before device_tables() runs.
            mesh = self._build_mesh(plan)
            tables = plan.device_tables()
            if mesh.active:
                tables = mesh.place_tables(tables)
            elif device is not None:
                tables = jax.device_put(tables, device)
            state["mesh"] = mesh
            state["tables"] = tables
            state["staging"] = (self._make_staging(plan)
                                if self.pipeline_mode == "on" else None)
            # Megastep window program (ISSUE 12): built only when
            # PINGOO_MEGASTEP is enabled at state-build time — `off`
            # (the default, and the bit-exact parity oracle) leaves
            # the per-batch dispatch path byte-for-byte untouched.
            state["mega_fn"] = None
            state["mega_queue"] = None
            if _resolve_megastep_mode() != "off":
                state["mega_fn"] = instrument_megastep(
                    make_megastep_fn(plan, kind="matrix"),
                    plane="python", fingerprint=fp, widths=widths)
                state["mega_queue"] = DeviceInputQueue(
                    megastep_k_cap(), self.max_batch,
                    field_specs=plan.field_specs, nbuf=2)
            return state
        except Exception as exc:
            # Boot-time demotion is permanent for this service (no
            # tables to probe against), but still counted/logged
            # through the ladder's device rung.
            self.ladder.note_failure("device", exc)
            return None

    def _adopt_engine_state(self, state: dict) -> None:
        """Install a pre-built engine bundle as the serving references.
        Only called with no batch in flight (boot, or the collector's
        swap point after the drain), so nothing reads these mid-flip."""
        self._verdict_fn = state["verdict_fn"]
        self._pf_fn = state["pf_fn"]
        self._pf_gated_banks = state["pf_gated_banks"]
        self._pf_attr = state["pf_attr"]
        self.mesh = state["mesh"]
        self._tables = state["tables"]
        if state.get("staging") is not None:
            self._staging = state["staging"]
        self._mega_fn = state.get("mega_fn")
        self._mega_queue = state.get("mega_queue")
        # Compact staging (ISSUE 15): the packed fns + caps flip with
        # the plan at the same batch boundary the staging encoder does,
        # so every batch is encoded AND decoded under one cap set.
        self._stage_caps = state.get("stage_caps")
        self._packed_verdict_fn = state.get("packed_verdict_fn")
        self._packed_pf_fn = state.get("packed_pf_fn")
        self._set_cap_gauges()

    def _make_staging(self, plan: RulesetPlan) -> StagingEncoder:
        """The staging encoder for a plan: plain rotating buffers under
        PINGOO_STAGING=full, packed one-copy layout under =compact
        (caps from the plan's compile-time staging pass, overflow
        thresholds keeping the rewrite set exact)."""
        caps = resolve_stage_caps(plan)
        if caps is None:
            return StagingEncoder(self.max_batch, plan.field_specs,
                                  nbuf=self._pipeline_depth + 1)
        return StagingEncoder(
            self.max_batch, plan.field_specs,
            nbuf=self._pipeline_depth + 1, stage_caps=caps,
            overflow_thresholds=stage_overflow_thresholds(plan, caps))

    def _set_cap_gauges(self) -> None:
        """Export the adopted plan's per-field staging caps (host-
        static per epoch; the observable half of the staged-bytes
        reduction)."""
        if not self._stage_caps:
            return
        from ..obs import REGISTRY
        from ..obs.schema import STAGING_METRICS

        for field, cap in self._stage_caps.items():
            REGISTRY.gauge(
                "pingoo_staging_field_cap",
                STAGING_METRICS["pingoo_staging_field_cap"],
                labels={"field": field}).set(int(cap))

    def _build_mesh(self, plan) -> MeshExecutor:
        """The serving mesh for this plane (PINGOO_MESH). Degrades to
        the inactive single-device executor — never crashes the data
        plane — when the spec is malformed or needs more devices than
        the backend has; the failure is logged and visible as
        pingoo_mesh_devices == 1."""
        try:
            return MeshExecutor(plan, plane="python",
                                metrics=self.sched.metrics)
        except (MeshUnavailable, ValueError) as exc:
            self.ladder.note_failure("mesh", exc)
            return MeshExecutor(plan, spec=(1, 1, 1), plane="python",
                                metrics=self.sched.metrics)

    # -- degradation ladder (ISSUE 10, docs/RESILIENCE.md) --------------------

    def _rebuild_verdict_fn(self, dfa_off: bool) -> None:
        """Re-trace the verdict fn with the lowered DFAs in or out
        (plan-level default — what `_resolve_dfa_mode` falls back to
        when PINGOO_DFA is unset). The next batch pays one re-jit."""
        from .verdict import donate_batch_buffers

        self.plan.dfa_default_mode = "off" if dfa_off else self._dfa_mode0
        fp = plan_fingerprint(self.plan)
        widths = staging_widths(self.plan)
        self._verdict_fn = instrument_jit(
            make_verdict_fn(self.plan, donate=donate_batch_buffers()),
            "verdict", plane="python", fingerprint=fp, widths=widths)
        if self._packed_verdict_fn is not None:
            # The packed twin embeds the same DFA dispatch decision;
            # keep it in lockstep with the per-batch program.
            self._packed_verdict_fn = instrument_jit(
                make_packed_verdict_fn(
                    self.plan, donate=donate_batch_buffers()),
                "verdict", plane="python", fingerprint=fp,
                widths=widths)
        if self._mega_fn is not None:
            # The megastep embeds the same DFA dispatch decision; keep
            # it in lockstep with the per-batch program it must stay
            # bit-identical to.
            self._mega_fn = instrument_megastep(
                make_megastep_fn(self.plan, kind="matrix"),
                plane="python", fingerprint=fp, widths=widths)

    def _dfa_rung_tick(self) -> None:
        """Demoted-dfa probe: when the backoff window opens, restore
        the lowered-DFA dispatch for one batch; the device success /
        failure report then promotes or re-demotes."""
        if not self.use_device:
            return
        if not self.ladder.healthy("dfa") and not self._dfa_probe \
                and self.ladder.try_rung("dfa"):
            self._rebuild_verdict_fn(dfa_off=False)
            self._dfa_probe = True

    def _note_device_failure(self, exc: BaseException) -> None:
        """Cheapest-rung-first demotion: a device error with lowered
        DFAs active drops them back to the exact NFA scan before
        giving up on the device; only a failure with the DFAs already
        out (or pinned by PINGOO_DFA) demotes the device rung to the
        host interpreter."""
        from .verdict import dfa_dispatch_counts

        if self._dfa_probe:
            self.ladder.note_failure("dfa", exc)
            self._rebuild_verdict_fn(dfa_off=True)
            self._dfa_probe = False
        elif self.ladder.healthy("dfa") \
                and not os.environ.get("PINGOO_DFA") \
                and dfa_dispatch_counts(self.plan)[1] > 0:
            self.ladder.note_failure("dfa", exc)
            self._rebuild_verdict_fn(dfa_off=True)
        else:
            self.ladder.note_failure("device", exc)

    def _note_device_success(self) -> None:
        if self._dfa_probe:
            self.ladder.note_success("dfa")
            self._dfa_probe = False
        self.ladder.note_success("device")

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._collector())
            # Warm the XLA program off the serving path so the first real
            # request doesn't pay the compile.
            if self.use_device:
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(
                    None, self._evaluate_sync, [RequestTuple()])
            # Device-level tracing (SURVEY.md §5 tracing/profiling): the
            # structured logs + per-batch verdict timings are always on;
            # PINGOO_PROFILE_DIR additionally captures a jax.profiler
            # trace of the serving window for offline kernel analysis
            # (viewable in TensorBoard / xprof).
            profile_dir = os.environ.get("PINGOO_PROFILE_DIR")
            if profile_dir and self.use_device:
                try:
                    import jax

                    jax.profiler.start_trace(profile_dir)
                    self._tracing = True
                except Exception:
                    self._tracing = False

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # Drain the double-buffered in-flight batches: their futures
        # must resolve (fail-open at worst) before callers tear down.
        if self._inflight:
            await asyncio.gather(*list(self._inflight),
                                 return_exceptions=True)
        task = getattr(self, "_profile_task", None)
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            self._profile_task = None
        self.ensure_trace_stopped()
        if self.parity is not None:
            self.parity.stop()
        if self._attribution is not None:
            self._attribution.close()
        # Durable cost ledger (ISSUE 17): persist the measured EWMAs on
        # drain so the next boot estimates from THIS run's costs.
        self.persist_cost_ledger()

    def persist_cost_ledger(self) -> bool:
        """Snapshot the scheduler's CostModel into the durable cost
        ledger (PINGOO_COST_LEDGER). Idempotent + best-effort: also
        safe from the SIGTERM drain path after a blown graceful-stop
        deadline."""
        try:
            return save_cost_ledger(
                self.sched.cost, backend=self._backend_label,
                fingerprint=self._plan_fp, plane="python")
        except Exception:
            return False

    def ensure_trace_stopped(self) -> None:
        """Flush any live jax.profiler trace (the boot-time
        PINGOO_PROFILE_DIR capture or an on-demand /__pingoo/profile
        window). Idempotent and synchronous so the SIGTERM drain path
        can call it even when the graceful-stop deadline expired —
        without the explicit stop_trace the trace files are simply
        never written (the profiler buffers in memory)."""
        if getattr(self, "_tracing", False):
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._tracing = False

    async def capture_profile(self, seconds: float,
                              out_dir: Optional[str] = None) -> dict:
        """On-demand bounded jax.profiler window (the /__pingoo/profile
        endpoint): generalizes the boot-only PINGOO_PROFILE_DIR hook to
        any serving moment. One capture at a time; the window is capped
        at 30 s so a forgotten curl cannot leave tracing overhead on."""
        seconds = max(0.1, min(float(seconds), 30.0))
        if getattr(self, "_tracing", False):
            return {"error": "a profiler trace is already active"}
        out_dir = out_dir or os.environ.get("PINGOO_PROFILE_DIR")
        if not out_dir:
            import tempfile

            out_dir = tempfile.mkdtemp(prefix="pingoo-profile-")
        try:
            import jax

            jax.profiler.start_trace(out_dir)
        except Exception as exc:
            return {"error": f"profiler unavailable: {exc!r}"}
        self._tracing = True

        async def _stop_after_window():
            try:
                await asyncio.sleep(seconds)
            finally:
                # Cancellation (service stop) must still flush.
                self.ensure_trace_stopped()

        self._profile_task = asyncio.create_task(_stop_after_window())
        return {"profiling": True, "dir": out_dir, "seconds": seconds}

    async def evaluate(self, req: RequestTuple) -> Verdict:
        """Await the verdict for one request (the per-request hot call)."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((req, fut, time.monotonic()))
        return await fut

    # -- batching loop -------------------------------------------------------

    async def _collector(self) -> None:
        """Admission loop (ISSUE 6): pop -> assemble under the
        scheduler's launch policy -> hand the batch to a double-
        buffered runner task, so batch N+1 assembles and encodes while
        batch N computes. In `continuous` mode the assembly window is
        the oldest request's remaining deadline slack minus the EWMA
        dispatch estimate — not a fixed timer; `fixed` keeps the
        legacy max_wait_us window (the bench A/B arm)."""
        sched = self.sched
        continuous = sched.config.mode == "continuous"
        sem = asyncio.Semaphore(self._pipeline_depth)
        while True:
            item = await self._queue.get()
            if isinstance(item, _PlanSwap):
                await self._apply_swap(item)
                continue
            t_first = time.monotonic()
            self.stats.observe_stage(
                "queue_wait", (t_first - item[2]) * 1e3)
            # Pending entries are (req, fut, t_enq, t_admit): t_enq
            # anchors the request's deadline (evaluate() entry — the
            # <2 ms budget is end to end), t_admit its collector pop.
            pending = [(item[0], item[1], item[2], t_first)]
            oldest_enq = item[2]
            fixed_deadline = t_first + self.max_wait_s
            # A swap sentinel popped mid-assembly closes the batch: the
            # requests admitted so far launch on the old plan, the flip
            # happens right after the launch (and drains it), and the
            # requests still queued behind the sentinel admit next
            # iteration on the new plan.
            swap = None
            while len(pending) < self.max_batch:
                now = time.monotonic()
                if continuous:
                    timeout = sched.wait_budget_s(
                        len(pending), oldest_enq, now)
                else:
                    timeout = fixed_deadline - now
                if timeout <= 0:
                    break
                try:
                    item = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    break
                if isinstance(item, _PlanSwap):
                    swap = item
                    break
                t_adm = time.monotonic()
                self.stats.observe_stage(
                    "queue_wait", (t_adm - item[2]) * 1e3)
                pending.append((item[0], item[1], item[2], t_adm))
            # Greedy tail drain: whatever is ALREADY queued rides this
            # launch for free (burst traffic batches even when the
            # oldest request's slack is exhausted — launching
            # singletons under overload would only make every
            # follower later).
            while swap is None and len(pending) < self.max_batch:
                try:
                    item = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if isinstance(item, _PlanSwap):
                    swap = item
                    break
                t_adm = time.monotonic()
                self.stats.observe_stage(
                    "queue_wait", (t_adm - item[2]) * 1e3)
                pending.append((item[0], item[1], item[2], t_adm))
            t_launch = time.monotonic()
            # Scheduler hold time: first admit -> launch decision.
            self.stats.observe_stage("sched", (t_launch - t_first) * 1e3)
            # ISSUE 6 satellite (fairness fix): batch_assembly is
            # stamped PER REQUEST from its own admit timestamp — the
            # old single (t_launch - t_first) observation under-
            # reported queue wait for requests admitted late into a
            # large batch.
            for _, _, _, t_adm in pending:
                self.stats.observe_stage(
                    "batch_assembly", (t_launch - t_adm) * 1e3)
            sched.note_launch(len(pending), self._queue.qsize())
            await sem.acquire()
            task = asyncio.create_task(
                self._run_batch_guarded(pending, t_launch, sem))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
            if swap is not None:
                await self._apply_swap(swap)

    # -- ruleset hot-swap (ISSUE 11, docs/RESILIENCE.md) ----------------------

    async def swap_plan(self, plan: RulesetPlan,
                        lists: Optional[dict] = None,
                        tenant: str = "default") -> dict:
        """Hot-swap the serving ruleset at the next batch boundary.

        The new plan's engine state (jitted programs, placed tables,
        staging buffers) is built and warmed HERE, off the serving path
        — compile-ahead; with the artifact cache / TenantPlanStore the
        plan itself was typically already compiled. Then a sentinel
        rides the admission queue: the collector launches everything
        admitted ahead of it on the old plan, awaits the in-flight
        batches, flips the references, and bumps `ruleset_epoch`. The
        returned dict carries {epoch, tenant, pause_ms}; pause_ms is
        the drain+flip wall (the admission stall the swap cost — the
        number bench_regress tracks as swap_pause_p99_ms)."""
        from .hotswap import note_swap

        if self._task is None:
            raise RuntimeError("swap_plan requires a started service")
        loop = asyncio.get_running_loop()
        state = None
        if self.use_device:
            state = await loop.run_in_executor(
                None, self._build_engine_state, plan, self._device_hint)
            if state is None:
                note_swap("python", tenant, "rejected")
                raise RuntimeError(
                    f"hot-swap rejected for tenant {tenant!r}: engine "
                    f"state build failed (old plan keeps serving)")
            # Warm the jitted programs off-path so the first post-swap
            # batch doesn't pay an XLA compile inside its deadline.
            await loop.run_in_executor(None, self._warm_state, state)
        fut: asyncio.Future = loop.create_future()
        await self._queue.put(_PlanSwap(plan, lists, tenant, state, fut))
        return await fut

    def _warm_state(self, state: dict) -> None:
        """Trace/compile the new state's device programs on a dummy
        row (best-effort — a warm failure surfaces later through the
        normal ladder machinery, not as a rejected swap)."""
        try:
            plan = state["plan"]
            batch = encode_requests([RequestTuple()], plan.field_specs)
            fast = pad_batch(
                RequestBatch(size=1, arrays=bucket_arrays(batch.arrays)),
                1)
            dev_arrays = fast.arrays
            mesh = state["mesh"]
            if mesh is not None and mesh.active:
                dev_arrays = mesh.shard_batch(dev_arrays)
            pf_hits = None
            if state["pf_fn"] is not None:
                pf_hits, _ = state["pf_fn"](state["tables"], dev_arrays)
            state["verdict_fn"](state["tables"], dev_arrays, pf_hits)
            # Compact staging (ISSUE 15): warm the packed twins on the
            # new plan's layout rung too — a swap that widens a cap
            # must not pay its re-trace inside a serving deadline.
            if (state.get("packed_verdict_fn") is not None
                    and state.get("staging") is not None):
                import jax

                pb = state["staging"].encode_requests(
                    [RequestTuple()], pad_to=1)
                if pb.packed is not None and not (
                        mesh is not None and mesh.active):
                    dev_packed = jax.device_put(pb.packed)
                    pf_hits = None
                    if state.get("packed_pf_fn") is not None:
                        pf_hits, _ = state["packed_pf_fn"](
                            state["tables"], dev_packed, pb.layout)
                    state["packed_verdict_fn"](
                        state["tables"], dev_packed, pb.layout, pf_hits)
        except Exception:
            pass

    async def _apply_swap(self, swap: _PlanSwap) -> None:
        """The epoch flip, in collector context at a batch boundary.
        Awaiting the in-flight set first is what makes it atomic:
        _run_batch reads self.plan/_tables/_verdict_fn when it runs, so
        no launched batch can observe a half-installed state — and no
        future is dropped (every pending request launched) or resolved
        twice (each launched exactly once)."""
        from .hotswap import note_swap, set_epoch_gauge

        t0 = time.monotonic()
        while self._inflight:
            await asyncio.gather(*list(self._inflight),
                                 return_exceptions=True)
        try:
            self._install_plan(swap)
        except Exception as exc:
            note_swap("python", swap.tenant, "rejected")
            if not swap.fut.done():
                swap.fut.set_exception(exc)
            return
        self.ruleset_epoch += 1
        self.tenant = swap.tenant
        pause_ms = (time.monotonic() - t0) * 1e3
        set_epoch_gauge("python", self.ruleset_epoch)
        note_swap("python", swap.tenant, "ok")
        self.stats.observe_stage("sched", pause_ms)
        if not swap.fut.done():
            swap.fut.set_result({"epoch": self.ruleset_epoch,
                                 "tenant": swap.tenant,
                                 "pause_ms": round(pause_ms, 3)})

    def _install_plan(self, swap: _PlanSwap) -> None:
        plan = swap.plan
        if self.use_device:
            if swap.state is None:
                raise RuntimeError("hot-swap with no prepared state")
            self._adopt_engine_state(swap.state)
        self.plan = plan
        if swap.lists is not None:
            self.lists = swap.lists
        self._dfa_mode0 = getattr(plan, "dfa_default_mode", "auto")
        self._dfa_probe = False
        # Provenance follows the plan: rule names/indices changed, so
        # attribution, the parity oracle, and flight-record annotation
        # restart on the new plan's shape (counters are cumulative
        # across epochs; the per-rule label sets re-seed).
        if self._attribution is not None:
            self._attribution.close()
            self._attribution = RuleAttribution(plan.rule_names,
                                                plane="python")
        if self.parity is not None:
            self.parity.stop()
            self.flight_recorder = register_recorder(FlightRecorder(
                "python", rule_names=plan.rule_names))
            self.parity = ParityAuditor(plan, self.lists, plane="python",
                                        recorder=self.flight_recorder)

    async def _run_batch_guarded(self, pending, t_launch, sem) -> None:
        try:
            await self._run_batch(pending, t_launch)
        except asyncio.CancelledError:
            raise
        except Exception:
            # The runner must never strand futures: resolve this batch
            # fail-open (no-match) and keep serving.
            self.stats.device_errors += 1
            R = len(self.plan.rules)
            for _, fut, _t, _a in pending:
                if not fut.done():
                    fut.set_result(Verdict(
                        action=0, matched=np.zeros(R, dtype=bool),
                        degraded=True, epoch=self.ruleset_epoch))
        finally:
            sem.release()

    @staticmethod
    def _dedup_key(req: RequestTuple) -> tuple:
        # Everything a verdict can depend on; trace_id deliberately
        # excluded (it never reaches the device arrays).
        return (req.method, req.path, req.url, req.host, req.user_agent,
                req.ip, req.remote_port, req.asn, req.country)

    async def _run_batch(self, pending: list, t_launch: float) -> None:
        # Unmeetable deadlines fail open FIRST (per PINGOO_SCHED_
        # FAILOPEN) so a hopeless request never occupies device budget.
        if self.sched.config.failopen != "serve":
            pending = await self._apply_failopen(pending)
            if not pending:
                return
        reqs = [r for r, _, _, _ in pending]
        # Batch dedup: replayed/bursty traffic repeats identical tuples
        # (same method/path/headers/ip); encode + evaluate each distinct
        # tuple once and fan the verdict out to every duplicate.
        seen: dict[tuple, int] = {}
        uniq_rows: list[int] = []
        row_of: list[int] = []
        for i, req in enumerate(reqs):
            key = self._dedup_key(req)
            j = seen.get(key)
            if j is None:
                j = len(uniq_rows)
                seen[key] = j
                uniq_rows.append(i)
            row_of.append(j)
        dups = len(reqs) - len(uniq_rows)
        eval_reqs = [reqs[i] for i in uniq_rows] if dups else reqs
        loop = asyncio.get_running_loop()
        stages: dict = {}  # per-batch (double-buffered batches overlap)
        # The pipeline slot id rides the batch's stage dict into the
        # evaluate chain (note_stage pairing) and every flight record
        # (which batch-in-flight a request's timings belong to).
        pipe_slot = self._pipe.enter(self.pipeline_mode)
        stages["pipeline_slot"] = pipe_slot
        try:
            t_eval = time.monotonic()
            try:
                matched, scores = await loop.run_in_executor(
                    None, self._evaluate_with_scores, eval_reqs, stages,
                    t_launch)
            except _StageBudgetExceeded:
                # A host stage blew its slice of the deadline budget:
                # the whole batch reroutes through the PINGOO_SCHED_
                # FAILOPEN route instead of riding the device.
                await self._failopen_batch(pending)
                return
            # Feed the EWMA cost model the measured encode->result wall
            # for this padded size — what the launch policy trades slack
            # against — plus the per-stage decomposition (ISSUE 9) so
            # wait_budget_s can price encode+dispatch+compute instead of
            # one opaque wall.
            psize = self._pow2_size(len(eval_reqs))
            self.sched.observe_cost(psize,
                                    (time.monotonic() - t_eval) * 1e3)
            if "encode_ms" in stages:
                self.sched.observe_stage_cost(
                    "encode", psize, stages["encode_ms"])
            if "device_dispatch_ms" in stages:
                self.sched.observe_stage_cost(
                    "dispatch", psize,
                    stages.get("prefilter_ms", 0.0)
                    + stages["device_dispatch_ms"])
            if "compute_wall_ms" in stages:
                # Dispatch-end -> results-ready: the honest remaining
                # wall a row's deadline must still cover after launch
                # (NOT the residual block at sync, which goes to ~0
                # exactly when the overlap works).
                self.sched.observe_stage_cost(
                    "compute", psize, stages["compute_wall_ms"])
            if dups:
                self.stats.dedup_hits += dups
                matched = matched[row_of]  # fan out to duplicate rows
                scores = scores[row_of]
            t_resolve = time.monotonic()
            actions, verified_block = action_lanes(self.plan, matched)
            self.stats.batches += 1
            self.stats.requests += len(reqs)
            self.stats.batch_occupancy_sum += len(reqs)
            for i, (_, fut, t_enq, _t_adm) in enumerate(pending):
                # The shared verdict-wait histogram measures the full
                # evaluate() -> resolve wall per REQUEST (queue wait
                # included) — the <2ms p99 budget is about this number.
                self.stats.wait_hist.observe((t_resolve - t_enq) * 1e3)
                self.sched.note_resolved(t_enq, t_resolve)
                if not fut.done():
                    fut.set_result(
                        Verdict(action=int(actions[i]), matched=matched[i],
                                bot_score=float(scores[i]),
                                verified_block=bool(verified_block[i]),
                                epoch=self.ruleset_epoch))
            t_res_end = time.monotonic()
            self.stats.observe_stage(
                "resolve", (t_res_end - t_resolve) * 1e3)
            self._pipe.note_stage(pipe_slot, "resolve",
                                  t_resolve, t_res_end)
            # Provenance AFTER future resolution: attribution fold +
            # flight records + the parity sampling decision never sit
            # between the device result and the waiting requests.
            t_prov = time.monotonic()
            if self._attribution is not None:
                self._observe_provenance(reqs, pending, matched, actions,
                                         t_resolve, t_launch, stages)
            self.stats.observe_stage(
                "provenance", (time.monotonic() - t_prov) * 1e3)
            # Cross-plane timeline (ISSUE 17): per-batch cost while
            # unsampled is the one add+compare inside sample().
            if self._timeline.sample():
                tl_args = {"pipeline_slot": pipe_slot}
                if "megastep_k" in stages:
                    tl_args["megastep_k"] = stages["megastep_k"]
                self._timeline.batch_python(
                    stages_ms=stages, t_launch=t_launch,
                    t_resolve=t_resolve, t_end=t_res_end,
                    rows=[(reqs[i].trace_id or "", pending[i][2],
                           pending[i][3])
                          for i in range(
                              min(len(pending),
                                  self._timeline.rows_per_batch))],
                    args=tl_args)
        finally:
            self._pipe.exit()

    async def _failopen_batch(self, pending: list) -> None:
        """Resolve a whole batch through the PINGOO_SCHED_FAILOPEN
        route after a pipeline stage blew its slice of the deadline
        budget (docs/EXECUTOR.md): `allow` answers every future with
        the degraded no-match verdict immediately; `interpret` gives a
        real verdict off the device path. Only reachable when failopen
        != serve — `serve` never raises _StageBudgetExceeded."""
        self.sched.note_failopen(len(pending))
        R = len(self.plan.rules)
        if self.sched.config.failopen == "interpret":
            loop = asyncio.get_running_loop()
            late_reqs = [r for r, _, _, _ in pending]
            matched = await loop.run_in_executor(
                None, lambda: np.stack(
                    [self._interpret_row(r) for r in late_reqs]))
            acts, vblk = action_lanes(self.plan, matched)
            t_res = time.monotonic()
            for i, (_, fut, t_enq, _t_adm) in enumerate(pending):
                self.stats.wait_hist.observe((t_res - t_enq) * 1e3)
                self.sched.note_resolved(t_enq, t_res)
                if not fut.done():
                    fut.set_result(Verdict(
                        action=int(acts[i]), matched=matched[i],
                        verified_block=bool(vblk[i]),
                        epoch=self.ruleset_epoch))
            return
        t_res = time.monotonic()
        for _, fut, t_enq, _t_adm in pending:
            self.stats.wait_hist.observe((t_res - t_enq) * 1e3)
            self.sched.note_resolved(t_enq, t_res)
            if not fut.done():
                fut.set_result(Verdict(
                    action=0, matched=np.zeros(R, dtype=bool),
                    degraded=True, epoch=self.ruleset_epoch))

    async def _apply_failopen(self, pending: list) -> list:
        """Fail open the requests whose deadline is unmeetable even by
        an immediate launch (sched.unmeetable): `allow` resolves them
        with the fail-open verdict at once; `interpret` evaluates them
        on the host interpreter off the device path. Returns the
        requests that still ride the device batch."""
        now = time.monotonic()
        keep: list = []
        late: list = []
        for item in pending:
            if self.sched.unmeetable(item[2], now, len(pending)):
                late.append(item)
            else:
                keep.append(item)
        if not late:
            return pending
        self.sched.note_failopen(len(late))
        R = len(self.plan.rules)
        if self.sched.config.failopen == "allow":
            t_res = time.monotonic()
            for _, fut, t_enq, _t_adm in late:
                self.stats.wait_hist.observe((t_res - t_enq) * 1e3)
                self.sched.note_resolved(t_enq, t_res)
                if not fut.done():
                    fut.set_result(Verdict(
                        action=0, matched=np.zeros(R, dtype=bool),
                        degraded=True, epoch=self.ruleset_epoch))
            return keep
        # interpret: a real verdict, just off the device path — the
        # same degradation rung the watchdog fallback uses.
        loop = asyncio.get_running_loop()
        late_reqs = [r for r, _, _, _ in late]
        matched = await loop.run_in_executor(
            None, lambda: np.stack(
                [self._interpret_row(r) for r in late_reqs]))
        acts, vblk = action_lanes(self.plan, matched)
        t_res = time.monotonic()
        for i, (_, fut, t_enq, _t_adm) in enumerate(late):
            self.stats.wait_hist.observe((t_res - t_enq) * 1e3)
            self.sched.note_resolved(t_enq, t_res)
            if not fut.done():
                fut.set_result(Verdict(
                    action=int(acts[i]), matched=matched[i],
                    verified_block=bool(vblk[i]),
                    epoch=self.ruleset_epoch))
        return keep

    def _observe_provenance(self, reqs, pending, matched, actions,
                            t_resolve, t_launch, batch_stages) -> None:
        """Per-batch provenance: fold per-rule hit counters, flight-
        record each request, and hand the batch to the parity sampler.
        Runs on the collector path per batch — registered hot in the
        analyze-lint registries, so any device sync creeping in here
        fails `make analyze` (the matrix is already host-resident)."""
        self._attribution.fold_batch(matched.sum(axis=0))
        recorder = self.flight_recorder
        n = len(reqs)
        # Matched-rule ids per row from ONE nonzero pass (per-row
        # nonzero would be n small kernel launches' worth of overhead).
        rows, cols = np.nonzero(matched)
        per_row: dict[int, list] = {}
        # pingoo: allow(sync-tolist): host-resident numpy index vectors
        for r, c in zip(rows.tolist(), cols.tolist()):
            per_row.setdefault(r, []).append(c)
        # Recording more rows than the ring holds is pure wrap-around
        # churn; keep the LAST capacity rows of the batch.
        start = max(0, n - recorder.capacity)
        for i in range(start, n):
            req = reqs[i]
            stages = dict(batch_stages)
            stages["wait_ms"] = round(
                (t_resolve - pending[i][2]) * 1e3, 3)
            # ISSUE 6: admit -> launch slack per request (the share of
            # its wait the SCHEDULER chose, vs. queue/device time).
            stages["admit_to_launch_ms"] = round(
                (t_launch - pending[i][3]) * 1e3, 3)
            recorder.record(
                trace_id=req.trace_id,
                digest=tuple_digest(req.method, req.host, req.path,
                                    req.url, req.user_agent, req.ip),
                stages=stages,
                matched_rules=per_row.get(i, ()),
                action=int(actions[i]))
        if self.parity is not None:
            self.parity.submit_matrix(reqs, matched)

    def _evaluate_with_scores(self, reqs: list[RequestTuple],
                              stages: Optional[dict] = None,
                              t_launch: Optional[float] = None):
        """-> (matched [B, R], bot scores [B]). Scores ride the same
        encoded batch (BASELINE config 5: the vectorized bot head).
        `stages` collects this batch's per-stage timings — a PER-BATCH
        dict, because double-buffered dispatch (ISSUE 6) overlaps two
        batches' evaluations. With PINGOO_PIPELINE=on the encode runs
        into reused staging buffers under the encode token (ISSUE 9):
        already bucketed + padded, value-identical to the legacy
        encode->bucket->pad chain (tests/test_pipeline.py holds the
        bit-identity line)."""
        if stages is None:
            stages = {}
        self._last_batch_stages = stages  # latest batch (introspection)
        pipe_slot = stages.get("pipeline_slot")
        n = len(reqs)
        batch = None
        staged = False
        if self._staging is not None and self.ladder.try_rung("pipeline"):
            try:
                with self._stage_tokens["encode"]:
                    t0 = time.monotonic()
                    batch = self._staging.encode_requests(
                        reqs, pad_to=self._pow2_size(n))
                    t1 = time.monotonic()
                staged = True
                self.ladder.note_success("pipeline")
                if pipe_slot is not None:
                    self._pipe.note_stage(pipe_slot, "encode", t0, t1)
            except Exception as exc:
                # Ladder pipeline rung: a broken staging encoder
                # demotes this plane to the legacy encode chain below
                # (bit-identical, tests/test_pipeline.py) until a
                # backoff probe re-promotes it.
                self.ladder.note_failure("pipeline", exc)
                batch = None
        if batch is None:
            t0 = time.monotonic()
            batch = encode_requests(reqs, self.plan.field_specs)
            t1 = time.monotonic()
        self._batch_stage("encode", (t1 - t0) * 1e3, stages)
        self._check_stage_budget("encode", t_launch)
        # DISPATCH the scorer before the verdict runs: jax dispatch is
        # async, so the bot head computes while the verdict path does
        # its host work + device round trip, instead of serializing
        # after it (analyze-lint surfaced the old ordering, which
        # blocked on the scorer only once the verdict was already done).
        score_dev = None
        if self.bot_score_params is not None:
            try:
                if self._score_fn is None:
                    import jax

                    from ..models import botscore

                    self._score_fn = instrument_jit(
                        jax.jit(botscore.score), "score",
                        plane="python", fingerprint=self._plan_fp)
                # Pad to the same pow2 shape the verdict uses so the
                # jitted scorer compiles once per bucket, not per
                # occupancy.
                padded = pad_batch(batch, self._pow2_size(n))
                set_dispatch_context(batch=self._pow2_size(n))
                score_dev = self._score_fn(self.bot_score_params,
                                           padded.arrays)
            except Exception:
                # Scoring is advisory and never blocks verdicts, but a
                # broken scorer must show up on the metrics surface.
                self.stats.score_errors += 1
        matched = self._evaluate_sync(reqs, batch, stages, t_launch,
                                      staged=staged)
        # pingoo: allow(hot-alloc): [B] f32 default score vector
        scores = np.zeros(n, dtype=np.float32)
        if score_dev is not None:
            try:
                # pingoo: allow(sync-asarray-hot): scores materialize
                scores = np.asarray(  # after overlapping the verdict
                    score_dev, dtype=np.float32)[:n]
            except Exception:
                self.stats.score_errors += 1
        return matched, scores

    def pipeline_snapshot(self) -> dict:
        """Pipelined-executor introspection (ISSUE 9): mode, depth,
        in-flight count, per-stage occupancy and the overlap ratio —
        the JSON twin of the pingoo_pipeline_* registry gauges."""
        snap = self._pipe.snapshot()
        snap["mode"] = self.pipeline_mode
        return snap

    def _pow2_size(self, n: int) -> int:
        """Padded launch size: the shared pow2 ladder, dp-aligned when
        a serving mesh is active (the batch axis must shard evenly)."""
        multiple = self.mesh.dp if self.mesh is not None else 1
        return pow2_batch_size(n, self.max_batch, multiple=multiple)

    def _batch_stage(self, stage: str, ms: float,
                     stages: Optional[dict] = None) -> None:
        """Observe a pipeline stage AND stash it in the batch's stage
        dict the flight recorder attaches to every record (the dict is
        per batch: double-buffered batches overlap)."""
        self.stats.observe_stage(stage, ms)
        if stages is not None:
            stages[f"{stage}_ms"] = round(ms, 3)

    def _check_stage_budget(self, stage: str,
                            t_launch: Optional[float]) -> None:
        """Per-stage fail-open budget (ISSUE 9, docs/EXECUTOR.md):
        after each HOST stage, check the launch-relative elapsed time
        against that stage's cumulative slice of the deadline
        (PIPELINE_STAGE_BUDGET x PINGOO_DEADLINE_MS) and raise
        _StageBudgetExceeded to reroute the batch through the fail-open
        machinery. No-op under the default `serve` policy — serving
        bit-identical verdicts beats enforcing the budget."""
        if t_launch is None or self.sched.config.failopen == "serve":
            return
        frac = PIPELINE_STAGE_BUDGET.get(stage)
        if frac is None:
            return
        elapsed_ms = (time.monotonic() - t_launch) * 1e3
        if elapsed_ms > frac * self.sched.config.deadline_ms:
            raise _StageBudgetExceeded(stage, elapsed_ms)

    def _evaluate_sync(self, reqs: list[RequestTuple],
                       batch: Optional[RequestBatch] = None,
                       stages: Optional[dict] = None,
                       t_launch: Optional[float] = None,
                       staged: bool = False) -> np.ndarray:
        from contextlib import nullcontext

        n = len(reqs)
        if batch is None:
            batch = encode_requests(reqs, self.plan.field_specs)
            staged = False
        pipe_slot = (stages or {}).get("pipeline_slot")
        matched = None
        # Ladder device rung: while demoted, skip the dispatch entirely
        # (the host interpreter serves below) except for backoff probes;
        # a device exception demotes instead of staying an anonymous
        # device_errors increment.
        self._dfa_rung_tick()
        if self.use_device and self.ladder.try_rung("device"):
            try:
                if staged:
                    # Staging path (ISSUE 9): the encoder already
                    # bucketed the field axes and padded the batch axis
                    # — reusing its views IS the zero-copy win.
                    fast = batch
                else:
                    # Stabilize BOTH shape axes: bucket field lengths,
                    # and pad the batch axis to a power of two so
                    # arbitrary collector occupancies don't each
                    # compile a fresh XLA program.
                    arrays = bucket_arrays(batch.arrays)
                    fast = pad_batch(
                        RequestBatch(size=batch.size, arrays=arrays),
                        self._pow2_size(n))
                # Megastep window (ISSUE 12): PINGOO_MEGASTEP=force —
                # or `auto` with a backlog queued behind this batch —
                # scans the batch as K row slices through ONE jitted
                # dispatch instead of the per-batch program below.
                # None = not engaged, or the window failed; either way
                # the per-batch dispatch serves the same rows,
                # bit-identically by construction (the slice body IS
                # the function make_verdict_fn jits).
                matched = self._evaluate_megastep(fast, n, stages,
                                                  t_launch, pipe_slot)
                if matched is not None:
                    self._observe_dfa()
                    self._note_device_success()
                    return self._rewrite_overflow_rows(reqs, batch,
                                                       matched[:n])
                # The dispatch token serializes device issue across
                # in-flight batches (program order stays deterministic)
                # while leaving compute token-free: batch N+1 encodes
                # and dispatches while batch N blocks on its result.
                tok = (self._stage_tokens["dispatch"]
                       if self._staging is not None else nullcontext())
                # True padded launch batch for the compile ledger's
                # surface check (the packed blob hides the batch axis
                # from arg-shape inspection).
                set_dispatch_context(batch=batch_leading_dim(fast.arrays))
                td0 = time.monotonic()
                with tok:
                    # Mesh placement (ISSUE 6): the device programs
                    # read the dp-sharded view; `fast` itself stays
                    # host-resident for the host-rule overlap +
                    # overflow re-interpretation.
                    dev_arrays = fast.arrays
                    if self.mesh is not None and self.mesh.active:
                        dev_arrays = self.mesh.shard_batch(dev_arrays)
                    # Compact staging (ISSUE 15): one device_put of the
                    # packed buffer replaces the per-field transfers —
                    # the bytes-proportional slice of the dispatch
                    # wall. Mesh stays on the per-field path (the
                    # shard plan addresses named arrays).
                    use_packed = (
                        staged and batch.packed is not None
                        and self._packed_verdict_fn is not None
                        and not (self.mesh is not None
                                 and self.mesh.active))
                    if stages is not None:
                        # Flight-row staging mode (ISSUE 17 satellite).
                        stages["staging_mode"] = \
                            "compact" if use_packed else "full"
                    if use_packed:
                        import jax
                        dev_packed = jax.device_put(batch.packed)
                    pf_hits = pf_aux = None
                    if self._pf_fn is not None:
                        # Stage A (always-on, whole batch): factor hits
                        # feed the verdict program's bank gating; the
                        # aux lanes feed the candidate-rate/skip
                        # metrics after the batch's sync point.
                        t0 = time.monotonic()
                        if use_packed and self._packed_pf_fn is not None:
                            pf_hits, pf_aux = self._packed_pf_fn(
                                self._tables, dev_packed, batch.layout)
                        else:
                            pf_hits, pf_aux = self._pf_fn(self._tables,
                                                          dev_arrays)
                        self._batch_stage(
                            "prefilter", (time.monotonic() - t0) * 1e3,
                            stages)
                    t0 = time.monotonic()
                    if use_packed:
                        dev = self._packed_verdict_fn(
                            self._tables, dev_packed, batch.layout,
                            pf_hits)
                    else:
                        dev = self._verdict_fn(self._tables, dev_arrays,
                                               pf_hits)
                    # jax dispatch is async: this stage is issue +
                    # host->device transfer; the on-device execution
                    # residual is timed inside finish_batch via
                    # block_until_ready, AFTER the host-interpreted
                    # rules overlapped it.
                    self._batch_stage(
                        "device_dispatch", (time.monotonic() - t0) * 1e3,
                        stages)
                td1 = time.monotonic()
                # Staged-bytes accounting (ISSUE 15): the transfer
                # volume behind this dispatch window, on the metrics
                # surface AND into the scheduler's bytes-keyed
                # dispatch EWMA.
                if batch.staged_bytes:
                    self.stats.staged_bytes_counter[
                        "compact" if batch.packed is not None
                        else "full"].inc(batch.staged_bytes)
                    self.sched.observe_dispatch_bytes(
                        batch.staged_bytes, (td1 - td0) * 1e3)
                if pipe_slot is not None:
                    self._pipe.note_stage(pipe_slot, "dispatch", td0, td1)
                self._check_stage_budget("dispatch", t_launch)
                matched = finish_batch(
                    self.plan, dev, fast, self.lists,
                    on_device_wait=lambda ms: self._batch_stage(
                        "device_compute", ms, stages))[:n]
                tc1 = time.monotonic()
                # The pipeline's compute window is dispatch-end ->
                # results-ready (the overlap denominator AND the
                # per-stage cost fed to the scheduler) — NOT the
                # residual block at sync, which goes to ~0 exactly
                # when the overlap works.
                if pipe_slot is not None:
                    self._pipe.note_stage(pipe_slot, "compute", td1, tc1)
                if stages is not None:
                    stages["compute_wall_ms"] = round(
                        (tc1 - td1) * 1e3, 3)
                if pf_aux is not None:
                    self._observe_prefilter(pf_aux, fast.size)
                self._observe_dfa()
                self._note_device_success()
            except _StageBudgetExceeded:
                raise
            except Exception as exc:
                self.stats.device_errors += 1
                self._note_device_failure(exc)
                matched = None
        if matched is None:
            self.stats.host_fallback_batches += 1
            # [:n]: the staging batch carries pow2 padding rows the
            # host interpreter evaluates too — slice them off.
            matched = self._evaluate_host(batch)[:n]
        return self._rewrite_overflow_rows(reqs, batch, matched)

    def _evaluate_megastep(self, fast: RequestBatch, n: int,
                           stages: Optional[dict] = None,
                           t_launch: Optional[float] = None,
                           pipe_slot: Optional[int] = None
                           ) -> Optional[np.ndarray]:
        """Device-resident megastep window (ISSUE 12, docs/EXECUTOR.md
        "Device-resident loop"): split the shape-stable batch into K
        contiguous row slices, stage them through the DeviceInputQueue's
        double-buffered host stacks (one async device_put per window),
        and run ONE jitted kind="matrix" scan over all K — one dispatch
        wall amortized across the window. Returns the [P, R] matched
        matrix (device slices overlaid on the host-rule interpretation
        by finish_megastep) or None when the window is not engaged:
        PINGOO_MEGASTEP=off / state built without it, `auto` with no
        backlog queued behind this batch, an active mesh (the
        dp-sharded per-batch path owns placement), K deadline-sized
        down to 1 outside force mode, or the megastep rung demoted with
        its probe window closed. A window that raises demotes the
        megastep rung ONLY (the per-batch dispatch probes device health
        itself) and the caller re-dispatches per batch."""
        if self._mega_fn is None or self._mega_queue is None:
            return None
        mode = _resolve_megastep_mode()
        if mode == "off":
            return None
        if self.mesh is not None and self.mesh.active:
            return None
        if mode != "force" and self._queue.qsize() <= 0:
            return None
        size = fast.size
        k = 1
        for rung in self._mega_rungs:
            if rung <= size and size % rung == 0:
                k = rung
        if mode != "force":
            # Deadline-sized K (auto only — force is the operator
            # pinning the cap): the largest rung whose estimated
            # window wall still fits this batch's remaining slack.
            now = time.monotonic()
            k = min(k, self.sched.size_megastep_k(
                self._mega_rungs, size // k,
                t_launch if t_launch is not None else now, now))
            if k <= 1:
                return None
        rows = size // k
        if rows > self.max_batch:
            # Oversize direct evaluation (> max_batch rows/slice) —
            # outside the queue's capacity contract; per-batch serves.
            return None
        if not self.ladder.try_rung("megastep"):
            return None
        self._mega_window_seq += 1
        if stages is not None:
            # Flight-row window traceability (ISSUE 17 satellite): the
            # window id + staging mode ride the batch stage dict into
            # every flight record this window serves (megastep slices
            # always stage per-field arrays, never the packed buffer).
            stages["megastep_window"] = self._mega_window_seq
            stages["staging_mode"] = "full"
        from contextlib import nullcontext
        try:
            buf = self._mega_queue.checkout()
            for j in range(k):
                off = j * rows
                self._mega_queue.fill_slice(
                    buf, j,
                    {name: arr[off:off + rows]
                     for name, arr in fast.arrays.items()},
                    max(0, min(rows, n - off)), self.ruleset_epoch)
            tok = (self._stage_tokens["dispatch"]
                   if self._staging is not None else nullcontext())
            td0 = time.monotonic()
            with tok:
                stacked, nv, ep = self._mega_queue.device_stack(buf, k)
                set_dispatch_context(batch=rows, k=k)
                dev_out = self._mega_fn.fn(self._tables, stacked, nv, ep)
                self._batch_stage(
                    "device_dispatch", (time.monotonic() - td0) * 1e3,
                    stages)
            td1 = time.monotonic()
            if pipe_slot is not None:
                self._pipe.note_stage(pipe_slot, "dispatch", td0, td1)
            self._check_stage_budget("dispatch", t_launch)
            slices = [(j * rows, max(0, min(rows, n - j * rows)))
                      for j in range(k)]
            matched = finish_megastep(
                self.plan, dev_out[0], slices, fast, self.lists,
                on_device_wait=lambda ms: self._batch_stage(
                    "device_compute", ms, stages))
            tc1 = time.monotonic()
            # Pipeline compute window = dispatch-end -> results-ready,
            # same convention as the per-batch path.
            if pipe_slot is not None:
                self._pipe.note_stage(pipe_slot, "compute", td1, tc1)
            if stages is not None:
                stages["compute_wall_ms"] = round((tc1 - td1) * 1e3, 3)
                stages["megastep_k"] = k
            # Per-slice ruleset-epoch echo (the round-trip proof the
            # hot-swap tests assert on): a mismatch means a window
            # crossed a swap boundary it should have drained at.
            # pingoo: allow(sync-asarray-hot): i32[K], ready post-sync
            ep_echo = np.asarray(dev_out[3])
            self.mega_echo_mismatch += int(
                (ep_echo != self.ruleset_epoch).sum())
            if self._pf_fn is not None and self._mega_fn.aux_len:
                # Stage-A aux lanes are per-slice row counts — additive
                # across the window, observed once over all K*rows.
                # pingoo: allow(sync-asarray-hot): aux ready post-sync
                aux = np.asarray(dev_out[2])
                self._observe_prefilter(aux.sum(axis=0), size)
            self.sched.observe_megastep_cost(k, rows, (tc1 - td0) * 1e3)
            self._pipe.note_megastep(k, mode)
            self.ladder.note_success("megastep")
            return matched
        except _StageBudgetExceeded:
            raise
        except Exception as exc:
            self.ladder.note_failure("megastep", exc)
            return None

    def _observe_prefilter(self, pf_aux, batch_rows: int) -> None:
        """Fold the Stage-A aux lanes into the metrics surface
        (obs/schema.py PREFILTER_METRICS). Called AFTER finish_batch's
        sync point — the aux vector was computed before the verdict even
        dispatched, so this materialization never waits on the device."""
        try:
            # pingoo: allow(sync-asarray-hot): aux int32 lanes resolved
            vals = np.asarray(pf_aux)  # long before the batch's sync
            cand_rows, skipped = int(vals[0]), int(vals[1])
        except Exception:
            return
        denom = batch_rows * self._pf_gated_banks
        self.stats.prefilter_candidate_rate = (
            cand_rows / denom if denom else 0.0)
        self.stats.scan_banks_skipped += skipped
        self.stats.pf_rate_gauge.set(self.stats.prefilter_candidate_rate)
        self.stats.pf_skip_counter.inc(skipped)
        if self._pf_attr is not None:
            # Per-bank candidate-rate/skip attribution (ISSUE 5).
            self._pf_attr.observe(vals, batch_rows)

    def _observe_dfa(self) -> None:
        """Bitsplit-DFA dispatch accounting (obs/schema.py DFA_METRICS):
        how many banks this batch ran through a lowered DFA under the
        resolved PINGOO_DFA mode, and how many of those took the
        approximate-lowering recheck path. Host-static per plan+env
        (engine/verdict.dfa_dispatch_counts), so this never waits on the
        device."""
        from .verdict import dfa_dispatch_counts

        mode, banks, rechecks = dfa_dispatch_counts(self.plan)
        if not banks:
            return
        self.stats.dfa_banks += banks
        self.stats.dfa_rechecks += rechecks
        ctr = self.stats.dfa_banks_counter.get(mode)
        if ctr is not None:
            ctr.inc(banks)
        if rechecks:
            self.stats.dfa_recheck_counter.inc(rechecks)

    def _rewrite_overflow_rows(self, reqs, batch, matched: np.ndarray):
        """Rows whose fields exceeded device capacity are re-evaluated on
        the host interpreter over the UNTRUNCATED strings — the reference
        matches full path/url (pingoo/rules.rs:37-51), so parity for
        over-long requests cannot be defined over the truncated view."""
        overflow = batch.overflow
        if overflow is None or not overflow[: len(reqs)].any():
            return matched
        from .verdict import interpret_rules_row

        for i in np.nonzero(overflow[: len(reqs)])[0]:
            ctx = tuple_to_context(reqs[i], self.lists)
            matched[i, :] = interpret_rules_row(self.plan, ctx)
        return matched

    # -- provenance introspection (the /__pingoo/explain endpoint) -----------

    def _interpret_row(self, req: RequestTuple) -> np.ndarray:
        from .verdict import interpret_rules_row

        return interpret_rules_row(
            self.plan, tuple_to_context(req, self.lists))

    async def explain(self, req: RequestTuple) -> dict:
        """Re-run ONE request end to end (the real batched device path)
        AND through the host interpreter oracle, returning the per-rule
        / per-stage provenance picture (the /__pingoo/explain payload,
        validated against the interpreter's rule trace in tests)."""
        verdict = await self.evaluate(req)
        loop = asyncio.get_running_loop()
        want = await loop.run_in_executor(None, self._interpret_row, req)
        rules = []
        mismatched = []
        for rule in self.plan.rules:
            dev_hit = bool(verdict.matched[rule.index]) \
                if not verdict.degraded else None
            interp_hit = bool(want[rule.index])
            if dev_hit is not None and dev_hit != interp_hit:
                mismatched.append(rule.name)
            rules.append({
                "name": rule.name,
                "index": rule.index,
                "host": rule.host,
                "always": rule.always,
                "actions": [a.value for a in rule.actions],
                "device": dev_hit,
                "interpreter": interp_hit,
            })
        # The flight record for this trace id lands in the provenance
        # stage, AFTER the future resolves — poll briefly for it.
        stages = None
        if self.flight_recorder is not None and req.trace_id:
            for _ in range(10):
                entry = next(
                    (e for e in self.flight_recorder.snapshot()
                     if e["trace_id"] == req.trace_id), None)
                if entry is not None:
                    stages = entry["stages_ms"]
                    break
                await asyncio.sleep(0.01)
        return {
            "trace_id": req.trace_id,
            "digest": tuple_digest(req.method, req.host, req.path,
                                   req.url, req.user_agent, req.ip),
            "request": {
                "method": req.method, "host": req.host,
                "path": req.path, "url": req.url,
                "user_agent": req.user_agent, "ip": req.ip,
                "asn": req.asn, "country": req.country,
            },
            "action": verdict.action,
            "verified_block": verdict.verified_block,
            "bot_score": verdict.bot_score,
            "degraded": verdict.degraded,
            "matched_rules": [
                r.name for r in self.plan.rules
                if bool(want[r.index] if verdict.degraded
                        else verdict.matched[r.index])],
            "rules": rules,
            "parity": {"consistent": not mismatched,
                       "mismatched_rules": mismatched},
            "stages_ms": stages,
        }

    def _evaluate_host(self, batch: RequestBatch) -> np.ndarray:
        """Interpreter path: the CPU engine (also the watchdog fallback)."""
        contexts = batch_to_contexts(batch, self.lists)
        R = len(self.plan.rules)
        out = np.zeros((batch.size, R), dtype=bool)
        for rule in self.plan.rules:
            if rule.always:
                out[:, rule.index] = True
                continue
            prog = rule.program
            for i, ctx in enumerate(contexts):
                try:
                    out[i, rule.index] = execute_as_bool(prog, ctx)
                except Exception:
                    out[i, rule.index] = False  # fail-open, always
        return out
