"""Batched verdict engine: request encoding, jitted verdict, batching."""

from .. import ops as _ops  # noqa: F401  (enables x64 before tracing)
from .batch import RequestBatch, RequestTuple, batch_to_contexts, encode_requests, pad_batch
from .verdict import action_lanes, evaluate_batch, first_action, make_verdict_fn

__all__ = [
    "RequestBatch",
    "RequestTuple",
    "action_lanes",
    "batch_to_contexts",
    "encode_requests",
    "evaluate_batch",
    "first_action",
    "make_verdict_fn",
    "pad_batch",
]
