"""Degradation ladder: one explicit fallback state machine per plane.

(ISSUE 10, docs/RESILIENCE.md.) Both verdict planes used to scatter
their fallbacks across anonymous ``except Exception`` rungs — staging
encoder -> legacy encode, lowered DFA -> NFA scan, serving mesh ->
single device, device -> host interpreter — each silently sticky for
the process lifetime and none of them observable. The ladder
consolidates them: a demotion is counted
(``pingoo_degrade_total{rung=}``), logged with the triggering error,
and probed back with exponential backoff, so a transient fault (device
hiccup, driver reset, chaos injection) degrades service for seconds,
not forever.

Rung order (cheapest first — the order callers demote in):

  ==========  =====================================================
  pipeline    staging encoder -> legacy per-batch encode chain
  megastep    device-resident K-batch megastep -> per-batch dispatch
  dfa         lowered bitsplit DFAs -> exact NFA scan
  mesh        sharded serving mesh -> single-device executor
  device      XLA device programs -> host interpreter
  body        streaming body inspection -> metadata-only verdicts
  ==========  =====================================================

Every rung except ``body`` serves bit-identical verdicts by
construction: each fallback IS the oracle its fast path is tested
against (tests/test_pipeline.py, tests/test_bitsplit_dfa.py,
tests/test_resilience.py), so a demotion changes latency, never
answers. The ``body`` rung is the one deliberate exception (ISSUE 13,
docs/BODY_STREAMING.md): its fallback drops a whole inspection
dimension — body verdicts fail open to action 0 and requests are
judged on metadata alone — because there is no cheaper oracle for
body bytes the sidecar cannot scan. The demotion counter is the
audit trail for that coverage loss.

Caller protocol, per batch::

    if ladder.try_rung("device"):   # healthy, or a backoff probe
        try:
            ... fast path ...
            ladder.note_success("device")
        except Exception as exc:
            ladder.note_failure("device", exc)
            ... fallback ...
    else:
        ... fallback (demoted, probe window not yet open) ...

``try_rung`` on a demoted rung returns True at most once per backoff
window (the probe); a probe that fails reports via ``note_failure``,
which doubles the backoff, and one that succeeds re-promotes via
``note_success``, which resets it.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..logging_utils import get_logger

RUNGS = ("pipeline", "megastep", "dfa", "mesh", "device", "body")

# What each rung falls back TO (log/snapshot surface only).
FALLBACKS = {
    "pipeline": "legacy-encode",
    # ISSUE 12: a failed K-slice megastep window demotes the plane to
    # per-batch device dispatch (every slice still bit-identical);
    # backoff probes re-promote once the device program recovers.
    "megastep": "per-batch-dispatch",
    "dfa": "nfa-scan",
    "mesh": "single-device",
    "device": "host-interpreter",
    # ISSUE 13: a broken body scanner demotes the plane to
    # metadata-only verdicts — body windows fail open (action 0) so
    # held requests never stall; backoff probes re-arm inspection.
    "body": "metadata-only",
}

log = get_logger(__name__)


class _Rung:
    __slots__ = ("name", "healthy", "errors", "demotions", "backoff_s",
                 "next_probe_at", "last_error")

    def __init__(self, name: str, base_backoff_s: float):
        self.name = name
        self.healthy = True
        self.errors = 0        # note_failure calls (lifetime)
        self.demotions = 0     # healthy -> demoted transitions
        self.backoff_s = base_backoff_s
        self.next_probe_at = 0.0
        self.last_error = ""


class DegradationLadder:
    """Per-plane rung registry with exponential-backoff re-promotion.

    Single-threaded by contract — each plane drives its ladder from its
    own drain loop (the same discipline as the scheduler/cost model).
    ``clock`` is injectable so tests can step probe windows without
    sleeping.
    """

    def __init__(self, plane: str, base_backoff_s: float = 1.0,
                 max_backoff_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        if registry is None:
            from ..obs import REGISTRY

            registry = REGISTRY
        from ..obs.schema import RESILIENCE_METRICS

        self.plane = plane
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._clock = clock
        self._rungs = {name: _Rung(name, base_backoff_s)
                       for name in RUNGS}
        self._counters = {
            name: registry.counter(
                "pingoo_degrade_total",
                RESILIENCE_METRICS["pingoo_degrade_total"],
                labels={"plane": plane, "rung": name})
            for name in RUNGS}

    # -- caller protocol ------------------------------------------------------

    def healthy(self, rung: str) -> bool:
        return self._rungs[rung].healthy

    def try_rung(self, rung: str) -> bool:
        """True if the caller should attempt this rung's fast path now:
        the rung is healthy, or it is demoted and its backoff window
        has elapsed (a probe). A probe with no success/failure report
        stays demoted and re-probes next window."""
        r = self._rungs[rung]
        if r.healthy:
            return True
        now = self._clock()
        if now >= r.next_probe_at:
            r.next_probe_at = now + r.backoff_s
            return True
        return False

    def note_failure(self, rung: str, exc: Optional[BaseException] = None
                     ) -> None:
        """Demote (or keep demoted): count, log, double the backoff."""
        r = self._rungs[rung]
        r.errors += 1
        r.last_error = repr(exc) if exc is not None else ""
        self._counters[rung].inc()
        if r.healthy:
            r.demotions += 1
            r.backoff_s = self.base_backoff_s
        else:
            r.backoff_s = min(self.max_backoff_s, r.backoff_s * 2.0)
        r.healthy = False
        r.next_probe_at = self._clock() + r.backoff_s
        log.warning(
            "ladder demote", extra={"fields": {
                "plane": self.plane, "rung": rung,
                "fallback": FALLBACKS[rung],
                "backoff_s": round(r.backoff_s, 3),
                "errors": r.errors, "error": r.last_error}})

    def note_success(self, rung: str) -> None:
        """Re-promote after a successful probe; no-op while healthy."""
        r = self._rungs[rung]
        if r.healthy:
            return
        r.healthy = True
        r.backoff_s = self.base_backoff_s
        r.next_probe_at = 0.0
        log.info(
            "ladder promote", extra={"fields": {
                "plane": self.plane, "rung": rung,
                "errors": r.errors}})

    # -- introspection --------------------------------------------------------

    def demoted(self) -> list[str]:
        return [n for n in RUNGS if not self._rungs[n].healthy]

    def snapshot(self) -> dict:
        """JSON twin of the pingoo_degrade_total series plus the live
        state the counters cannot carry (health, backoff, last error)."""
        return {
            name: {
                "healthy": r.healthy,
                "fallback": FALLBACKS[name],
                "errors": r.errors,
                "demotions": r.demotions,
                "backoff_s": round(r.backoff_s, 3),
                "last_error": r.last_error,
            }
            for name, r in self._rungs.items()
        }
