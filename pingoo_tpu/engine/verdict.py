"""The batched verdict function: one jitted XLA program per ruleset.

`make_verdict_fn(plan)` traces the static plan structure (compiler/
plan.py) into a function of (device_tables, batch_arrays) -> per-rule
match matrix [B, R_device] bool. This replaces the reference's
per-request sequential rules loop (pingoo/listeners/http_listener.rs:
251-264 + pingoo/rules.rs:37-51 tree-walk) with one batched evaluation:

  * string predicate groups run as broadcast byte compares,
  * contains/regex run as one bit-parallel NFA scan per field,
  * ip/list membership via masked compares / sorted-search tables,
  * numeric comparisons as int64 lanes with exact error tracking
    (div-by-zero, i64 overflow) so the fail-open semantics of
    pingoo/rules.rs:41-44 are reproduced bit-exactly.

`evaluate_batch` adds the host-interpreted fallback rules and returns
the full match matrix in original rule order, plus `first_action`
applies the reference's first-match action semantics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.lowering import (
    BAnd,
    BConst,
    BEqBool,
    BErrConst,
    BLeaf,
    BNot,
    BOr,
    NBin,
    NCol,
    NConst,
    NLen,
    NNeg,
    NumCmp,
)
from ..compiler.plan import NfaScanPlan, RulesetPlan, ScanStrategy
from ..config.schema import Action
from ..expr import execute_as_bool
from ..ops.bitsplit_dfa import dfa_row_candidates, dfa_scan, dfa_skip_hits
from ..ops.cidr import cidr_contains, int_set_contains, v4_buckets_contains
from ..ops.match_ops import eq_match, prefix_match, suffix_match
from ..ops.nfa_scan import (extract_slots, halo_split_k, halo_split_scan,
                            init_scan_state, packed_scan_states, scan_chunk)
from ..ops.prefilter import prefilter_scan
from ..ops.window_match import window_hits

I64_MIN = -(2**63)

# Scan execution selection: the default comes from the PLAN-TIME
# strategy selector (compiler/plan.py select_scan_strategy — recorded in
# plan.scan_plans, persisted through the artifact cache, re-tunable from
# measurement via bench.py's autotune hook). The env knobs below are now
# OVERRIDES, not the source of defaults:
#
# PINGOO_SCAN_STRATEGY: force one strategy for every bank — "scan"
# (lax.scan single-byte), "pair" (lax.scan pair lookup), "pallas"
# (fused kernel, pair stepping), "pallas_single", "halo" (keep the
# selected kind, force the halo-split attempt).
#
# PINGOO_SCAN_PACK: legacy lane/row grouping for lax.scan banks
# (ops/nfa_scan.pack_scan_groups / _batch_stacked_states): "field" (one
# scan per field, the default), "length"/"fill" lane-packing, "single",
# "batch" row-stacking. A non-"field" value routes non-split banks
# through the legacy packed path.
#
# PINGOO_HALO_SPLIT: legacy knob forcing the within-device halo-split
# attempt for bounded-memory banks (the strategy's halo_k normally
# gates this).
#
# PINGOO_NFA_LOOKUP (read in ops/nfa_scan.py): byte-class lookup
# strategy per lax.scan step — take / cls_take / oh_f32 / pair / auto.
import os as _os

SCAN_PACK_MODE = _os.environ.get("PINGOO_SCAN_PACK", "field")
HALO_SPLIT = _os.environ.get("PINGOO_HALO_SPLIT", "0") != "0"

_ENV_STRATEGIES = {
    "scan": ("scan", False),
    "pair": ("scan", True),
    "pallas": ("pallas", True),
    "pallas_pair": ("pallas", True),
    "pallas_single": ("pallas", False),
}


def _resolve_strategy(strat: ScanStrategy) -> ScanStrategy:
    """Apply the PINGOO_SCAN_STRATEGY override (read per trace so tests
    can monkeypatch it)."""
    env = _os.environ.get("PINGOO_SCAN_STRATEGY", "")
    if not env:
        return strat
    if env == "halo":
        return ScanStrategy(kind=strat.kind, pair=strat.pair, halo_k=8,
                            source="env")
    kind, pair = _ENV_STRATEGIES[env]
    return ScanStrategy(kind=kind, pair=pair, halo_k=strat.halo_k,
                        source="env")


# -- literal-prefilter cascade (Stage B wiring) -------------------------------
#
# PINGOO_PREFILTER (read per trace; the plan's autotuned default_mode
# applies when unset):
#   off     — Stage A never runs; every bank scans unconditionally (the
#             pre-cascade behavior, the parity baseline).
#   banks   — one packed shift-AND pass per field; a gated NFA bank is
#             SKIPPED (lax.cond, shapes static) when no request in the
#             batch has a candidate for any of its patterns.
#   compact — banks, plus: a sparse gated bank gathers its candidate
#             rows into the smallest power-of-2-ish bucket that holds
#             them (a static ladder -> lax.switch), scans the compacted
#             rows, and scatters the hits back.
# PINGOO_PREFILTER_LEVELS caps the compaction ladder depth (default 4
# halvings); PINGOO_PREFILTER_KERNEL=pallas routes Stage A through the
# fused kernel. Soundness is structural: candidates over-approximate
# matches, so pruning can never change a verdict (tests/test_prefilter).


def _resolve_pf_mode(plan: RulesetPlan) -> str:
    pf = getattr(plan, "prefilter", None)
    if pf is None or not pf.fields:
        return "off"
    mode = _os.environ.get("PINGOO_PREFILTER", "") or pf.default_mode
    return mode if mode in ("off", "banks", "compact") else "banks"


def _pf_backend() -> str | None:
    return _os.environ.get("PINGOO_PREFILTER_KERNEL") or None


# -- bitsplit-DFA lowering dispatch (compiler/nfa.lower_bank_to_dfa) ----------
#
# PINGOO_DFA (read per trace; the plan's dfa_default_mode applies when
# unset):
#   off   — always run the NFA tables (the parity baseline).
#   auto  — use the lowered DFA for a bank when the cost model (or the
#           bench.py micro-autotune) selected it (entry.dfa_auto) and no
#           PINGOO_SCAN_STRATEGY override pins the NFA backend.
#   force — use the DFA for every bank that lowered within budget.
# PINGOO_DFA_KERNEL=pallas routes the byte ladder through the fused
# kernel (ops/bitsplit_dfa._fused_dfa). An EXACT DFA replaces the NFA
# scan outright (bit-identical by construction — tests/test_bitsplit_dfa
# proves parity). An APPROXIMATE DFA (merged states) is gate-only: its
# hits over-approximate per-slot matches, so candidate rows are
# rechecked through the exact NFA bank via the compact argsort-gather
# ladder and pruned rows take the skip base — prefilter prune-only
# soundness, one level deeper.


def _resolve_dfa_mode(plan: RulesetPlan) -> str:
    mode = _os.environ.get("PINGOO_DFA", "") \
        or getattr(plan, "dfa_default_mode", "auto")
    return mode if mode in ("off", "auto", "force") else "auto"


def _dfa_backend() -> str | None:
    return _os.environ.get("PINGOO_DFA_KERNEL") or None


def _dfa_bank_active(plan: RulesetPlan, entry, mode: str) -> bool:
    """Host-static: does this bank run its lowered DFA under `mode`?
    Split banks keep their per-sub-bank NFA strategies (the partition
    already beat the whole-bank scan, and slot recombination happens on
    NFA hits), so lowering only dispatches on non-split entries."""
    if mode == "off" or entry.split is not None:
        return False
    if not entry.dfa_key or entry.dfa_key not in plan.np_tables:
        return False
    if mode == "force":
        return True
    return bool(entry.dfa_auto) \
        and not _os.environ.get("PINGOO_SCAN_STRATEGY")


def _dfa_win_active(plan: RulesetPlan, key: str, mode: str) -> bool:
    """Whether window bank `key` dispatches through its lowered DFA.

    The window conv is deliberately serial-free on the MXU (its whole
    reason to exist — ops/window_match.py), so `auto` only swaps in the
    DFA gather ladder where per-row work dominates the per-step
    dependency chain: the CPU diagnostic backend. `force` takes it
    everywhere (parity/bench A/B)."""
    dkey = getattr(plan, "win_dfa", {}).get(key)
    if not dkey or dkey not in plan.np_tables or mode == "off":
        return False
    if mode == "force":
        return True
    import jax

    return jax.default_backend() == "cpu"


def dfa_dispatch_counts(plan: RulesetPlan) -> tuple[str, int, int]:
    """(resolved mode, banks running their DFA, approx banks taking the
    exact-NFA recheck path) — host-static per plan+env, counted once per
    batch by the service metrics (pingoo_dfa_banks_total{mode=} /
    pingoo_dfa_recheck_total)."""
    mode = _resolve_dfa_mode(plan)
    banks = recheck = 0
    for entry in getattr(plan, "scan_plans", {}).values():
        if not _dfa_bank_active(plan, entry, mode):
            continue
        banks += 1
        if not plan.np_tables[entry.dfa_key].exact:
            recheck += 1
    for key, dkey in getattr(plan, "win_dfa", {}).items():
        if not _dfa_win_active(plan, key, mode):
            continue
        banks += 1
        if not plan.np_tables[dkey].exact:
            recheck += 1
    return mode, banks, recheck


def _pf_compact_sizes(B: int) -> list[int]:
    """Static compaction ladder: [B, B/2, ...] bounded by the level cap
    and a 32-row floor (below that the scan cost is all fixed)."""
    levels = int(_os.environ.get("PINGOO_PREFILTER_LEVELS", "4"))
    sizes = [B]
    while len(sizes) <= levels and sizes[-1] // 2 >= 32:
        sizes.append(sizes[-1] // 2)
    return sizes


# -- numeric IR evaluation ---------------------------------------------------


def _eval_num(ir, arrays, B):
    """-> (val int64 [B], err bool [B]) with Rust-i64 error semantics."""
    if isinstance(ir, NConst):
        return (jnp.full((B,), ir.value, dtype=jnp.int64),
                jnp.zeros((B,), dtype=bool))
    if isinstance(ir, NCol):
        return arrays[ir.name].astype(jnp.int64), jnp.zeros((B,), dtype=bool)
    if isinstance(ir, NLen):
        return (arrays[f"{ir.field}_len"].astype(jnp.int64),
                jnp.zeros((B,), dtype=bool))
    if isinstance(ir, NNeg):
        v, e = _eval_num(ir.x, arrays, B)
        return -v, e | (v == I64_MIN)
    if isinstance(ir, NBin):
        lv, le = _eval_num(ir.left, arrays, B)
        rv, re_ = _eval_num(ir.right, arrays, B)
        err = le | re_
        if ir.op == "+":
            s = lv + rv
            of = ((lv ^ s) & (rv ^ s)) < 0
            return s, err | of
        if ir.op == "-":
            s = lv - rv
            of = ((lv ^ rv) & (lv ^ s)) < 0
            return s, err | of
        if ir.op == "*":
            s = lv * rv
            l_safe = jnp.where(lv == 0, 1, lv)
            of = (lv != 0) & (jax.lax.div(s, l_safe) != rv)
            of = of | ((lv == -1) & (rv == I64_MIN))
            of = of | ((rv == -1) & (lv == I64_MIN))
            return s, err | of
        if ir.op in ("/", "%"):
            zero = rv == 0
            min_neg1 = (lv == I64_MIN) & (rv == -1)
            r_safe = jnp.where(zero | min_neg1, 1, rv)
            if ir.op == "/":
                # I64_MIN / -1 overflows (interp: checked_i64 raises).
                return jax.lax.div(lv, r_safe), err | zero | min_neg1
            # I64_MIN % -1 == 0 in the interpreter (the final checked_i64
            # sees 0), so only division by zero errors here.
            val = jnp.where(min_neg1, 0, jax.lax.rem(lv, r_safe))
            return val, err | zero
        raise AssertionError(ir.op)
    raise AssertionError(f"bad num ir {ir!r}")


_CMP = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


# -- leaf evaluation ---------------------------------------------------------


def _eval_leaves(plan: RulesetPlan, tables, arrays, B, pf_hits=None):
    """Compute every leaf's ([B] val, [B] err) with shared group ops.

    `pf_hits` optionally carries precomputed Stage-A prefilter hit maps
    ({field: [B, F] bool} from make_prefilter_fn — the service path
    dispatches Stage A as its own program so the stage is timeable);
    absent, the prefilter is traced inline into the same XLA program."""
    results: dict[int, tuple] = {}
    no_err = jnp.zeros((B,), dtype=bool)

    group_cols: dict[str, Any] = {}

    def group_result(key, field, kind):
        if key not in group_cols:
            table = tables[key]
            data = arrays[f"{field}_bytes"]
            lens = arrays[f"{field}_len"]
            if kind == "eq":
                group_cols[key] = eq_match(data, lens, table)
            elif kind == "prefix":
                group_cols[key] = prefix_match(data, lens, table)
            else:
                group_cols[key] = suffix_match(data, lens, table)
        return group_cols[key]

    nfa_cache: dict[str, Any] = {}

    def nfa_result(key, field):
        return nfa_cache[key]  # pre-filled by run_packed_scans

    def bank_hits(bank, strat: ScanStrategy, data, lens):
        """One bank's [B, P] hits under its selected strategy: the
        trace-time halo-split attempt (when the strategy's halo_k and
        the bucketed length make it strictly cheaper than the base
        stepping), then the pair/single step on the lax.scan or fused
        Pallas backend."""
        B, L = data.shape
        backend = "pallas" if strat.kind == "pallas" else None
        lookup = "pair" if strat.pair else None
        k_cap = strat.halo_k if strat.halo_k > 1 else (8 if HALO_SPLIT else 1)
        if k_cap > 1:
            k = halo_split_k(bank, int(L), max_k=k_cap)
            base_iters = (L + 1) // 2 if strat.pair else L
            if k > 1 and (L // k + int(bank.max_footprint)) < base_iters:
                return halo_split_scan(bank, data, lens, k,
                                       lookup=lookup, backend=backend)
        state = scan_chunk(bank, data, lens,
                           init_scan_state(B, bank.opt.shape[0]), 0,
                           lookup=lookup, backend=backend)
        return extract_slots(bank, state, lens)

    # -- Stage B: candidate gating over the Stage-A factor hits --------------

    pf = getattr(plan, "prefilter", None)
    pf_mode = _resolve_pf_mode(plan)
    dfa_mode = _resolve_dfa_mode(plan)
    pf_field_hits: dict[str, Any] = dict(pf_hits or {})

    def field_pf(field):
        """This field's [B, F] factor hits (from the caller-provided
        Stage-A pass, or traced inline exactly once per field)."""
        if field not in pf_field_hits:
            ff = pf.fields[field]
            pf_field_hits[field] = prefilter_scan(
                tables[ff.table_key], arrays[f"{field}_bytes"],
                arrays[f"{field}_len"], backend=_pf_backend())
        return pf_field_hits[field]

    def bank_skip_result(bank, lens):
        """A skipped bank's exact result: zero scan state still yields
        the always-match and empty-input lanes; every factor-gated slot
        is False — sound because skipping only happens when no request
        holds any of the bank's factors (candidates ⊇ matches)."""
        Bsz = lens.shape[0]
        state = jnp.zeros((Bsz, bank.opt.shape[0]), dtype=jnp.uint32)
        return extract_slots(bank, state, lens)

    def bank_candidates(key, n_rows):
        """[n_rows] candidate-row vector for bank `key`, or None when
        the bank is ungated (no prefilter, mode off, or a slot without
        an extractable factor)."""
        if pf is None or pf_mode == "off":
            return None
        if not pf.bank_gated.get(key) or key not in pf.bank_masks:
            return None
        field = pf.bank_field[key]
        if field not in pf.fields:
            return None
        mask = pf.bank_masks[key]
        if not mask.any():
            # Only never-match slots: statically no candidates.
            return jnp.zeros((n_rows,), dtype=bool)
        return jnp.any(field_pf(field) & jnp.asarray(mask)[None, :],
                       axis=1)

    def compact_rows(scan_rows, base_fn, data, lens, cand):
        """Gather candidate rows into the smallest ladder bucket that
        holds them, scan the compacted rows, scatter hits back over the
        skipped-bank base. Every branch has static shapes (lax.switch);
        the last branch is the empty-candidate full skip."""
        Bsz = data.shape[0]
        sizes = _pf_compact_sizes(Bsz)
        count = cand.sum(dtype=jnp.int32)
        order = jnp.argsort(jnp.where(cand, 0, 1))  # candidates first

        def full():
            return scan_rows(data, lens)

        def level(sz):
            def br():
                idx = order[:sz]
                h = scan_rows(jnp.take(data, idx, axis=0),
                              jnp.take(lens, idx))
                return base_fn().at[idx].set(h)
            return br

        branches = ([full] + [level(sz) for sz in sizes[1:]] + [base_fn])
        if len(sizes) > 1:
            lev = jnp.sum((jnp.asarray(sizes[1:], dtype=jnp.int32)
                           >= count).astype(jnp.int32))
        else:
            lev = jnp.int32(0)
        lev = jnp.where(count == 0, jnp.int32(len(branches) - 1), lev)
        return jax.lax.switch(lev, branches)

    def gated_scan(key, data, lens, scan_rows, base_fn):
        """Run one bank through the cascade: unconditional when the bank
        is ungated, cond-skipped in banks mode, row-compacted in compact
        mode."""
        cand = bank_candidates(key, data.shape[0])
        if cand is None:
            return scan_rows(data, lens)
        if pf_mode == "compact":
            return compact_rows(scan_rows, base_fn, data, lens, cand)
        return jax.lax.cond(
            jnp.any(cand),
            lambda: scan_rows(data, lens),
            base_fn)

    def gated_bank_hits(key, bank, strat, data, lens):
        return gated_scan(
            key, data, lens,
            lambda d, l: bank_hits(bank, strat, d, l),
            lambda: bank_skip_result(bank, lens))

    def dfa_cascade_hits(key, dtab, data, lens, recheck_rows,
                         recheck_base):
        """One lowered bank's [B, P] hits via its bitsplit DFA.

        Exact DFA: a drop-in replacement for the bank's scan that rides
        the full prefilter cascade unchanged (cond-skip in banks mode,
        argsort-gather compaction in compact mode; the skip base is the
        DFA's own zero-input result — start-state accepts cover the
        always/empty lanes). Approximate DFA: the gather ladder itself
        rides the cascade (compacted onto Stage-A candidate rows —
        sparse end-to-end, the skip base makes pruned rows trivially
        non-candidates), then rows with any non-trivial hit are
        rechecked through the bank's EXACT scan (NFA tables / window
        conv) via a second, smaller compact ladder; pruned rows take
        the exact skip base. Either way the verdict is bit-identical to
        PINGOO_DFA=off (tests/test_bitsplit_dfa)."""
        dfa_rows = lambda d, l: dfa_scan(dtab, d, l,
                                         backend=_dfa_backend())
        dfa_base = lambda: dfa_skip_hits(dtab, lens)
        if dtab.exact:
            return gated_scan(key, data, lens, dfa_rows, dfa_base)
        hits = gated_scan(key, data, lens, dfa_rows, dfa_base)
        cand = dfa_row_candidates(dtab, hits, lens)
        pf_cand = bank_candidates(key, data.shape[0])
        if pf_cand is not None:
            cand = cand & pf_cand
        return compact_rows(recheck_rows, recheck_base, data, lens,
                            cand)

    def dfa_bank_hits(key, entry, bank, data, lens):
        strat = _resolve_strategy(entry.strategy)
        return dfa_cascade_hits(
            key, tables[entry.dfa_key], data, lens,
            lambda d, l: bank_hits(bank, strat, d, l),
            lambda: bank_skip_result(bank, lens))

    def gated_window_hits(key, field):
        """The window bank under the same cascade: a gated win bank's
        slots are all factor-gated or never-match, so the skip base is
        simply all-False (window patterns carry no always/empty lanes
        once gating eligibility excludes min_len == 0 sources). When
        the bank's source patterns lowered to a bitsplit DFA and the
        dispatch mode takes it (_dfa_win_active: force anywhere, auto
        on the row-work-bound CPU backend), the gather ladder replaces
        the conv — guarded on slot-count agreement so the tp mesh path
        (which pads the conv table's pattern axis but not DfaTables)
        falls back to the conv."""
        data = arrays[f"{field}_bytes"]
        lens = arrays[f"{field}_len"]
        # P from the TABLE, not the plan: the tp mesh path pads the
        # pattern axis (parallel/mesh.pad_tables_for_tp) and pad rows
        # never match, so all-False covers them too.
        P = tables[key].kernel.shape[0]
        win_rows = lambda d, l: window_hits(tables[key], d, l)
        win_base = lambda: jnp.zeros((data.shape[0], P), dtype=bool)
        dkey = getattr(plan, "win_dfa", {}).get(key)
        if dkey and dkey in tables \
                and _dfa_win_active(plan, key, dfa_mode) \
                and tables[dkey].num_slots == P:
            return dfa_cascade_hits(key, tables[dkey], data, lens,
                                    win_rows, win_base)
        if pf is None or key not in pf.slot_codes:
            return win_rows(data, lens)
        return gated_scan(key, data, lens, win_rows, win_base)

    def run_packed_scans(groups: dict[str, tuple[str, list]]) -> None:
        """Run every NFA bank through its plan-selected strategy
        (compiler/plan.py scan_plans; module-level knobs override).
        Partitioned banks run their halo-splittable @short sub-bank and
        pair-stepped @rest residual separately and recombine columns by
        the recorded slot permutation."""
        packed: dict[str, tuple] = {}  # legacy lane/row-packing jobs
        for key, (field, _members) in groups.items():
            data = arrays[f"{field}_bytes"]
            lens = arrays[f"{field}_len"]
            entry = plan.scan_plans.get(key) or NfaScanPlan(
                key=key, strategy=ScanStrategy())
            if entry.split is not None:
                skey, rkey = entry.split
                hits = jnp.concatenate(
                    [gated_bank_hits(skey, tables[skey],
                                     _resolve_strategy(entry.short_strategy),
                                     data, lens),
                     gated_bank_hits(rkey, tables[rkey],
                                     _resolve_strategy(entry.rest_strategy),
                                     data, lens)], axis=1)
                perm = jnp.asarray(entry.slot_perm, dtype=jnp.int32)
                nfa_cache[key] = jnp.take(hits, perm, axis=1)
                continue
            if _dfa_bank_active(plan, entry, dfa_mode) \
                    and entry.dfa_key in tables \
                    and tables[entry.dfa_key].num_slots \
                        == tables[key].accept_member.shape[1]:
                nfa_cache[key] = dfa_bank_hits(key, entry, tables[key],
                                               data, lens)
                continue
            strat = _resolve_strategy(entry.strategy)
            if strat.source != "env" and SCAN_PACK_MODE != "field":
                strat = ScanStrategy()  # legacy packed path wants lax.scan
            if strat.kind == "scan" and not strat.pair \
                    and SCAN_PACK_MODE != "field":
                if HALO_SPLIT:  # legacy halo-first, as before packing
                    k = halo_split_k(tables[key], int(data.shape[1]))
                    if k > 1:
                        nfa_cache[key] = halo_split_scan(
                            tables[key], data, lens, k)
                        continue
                packed[key] = (tables[key], data, lens)
                continue
            nfa_cache[key] = gated_bank_hits(key, tables[key], strat,
                                             data, lens)
        if packed:
            states = packed_scan_states(
                {k: v[0] for k, v in packed.items()},
                {k: v[1] for k, v in packed.items()},
                {k: v[2] for k, v in packed.items()},
                mode=SCAN_PACK_MODE)
            for k, (bank, _data, lens) in packed.items():
                nfa_cache[k] = extract_slots(bank, states[k], lens)

    # Per-leaf NFA/window extraction: leaves own contiguous slot spans;
    # doing a per-leaf slice+any would issue hundreds of tiny ops, so
    # instead one [B, P] x [P, n_leaves] matmul reduces every span at
    # once (MXU does the OR as a count > 0).
    leaf_matrix_cache: dict[str, Any] = {}

    def span_leaf_matrix(key, hits_fn, spans):
        if key not in leaf_matrix_cache:
            hits = hits_fn()
            P = hits.shape[1]
            member = np.zeros((P, len(spans)), dtype=np.float32)
            for j, (lo, hi) in enumerate(spans):
                member[lo:hi, j] = 1.0
            counts = jnp.dot(hits.astype(jnp.float32), jnp.asarray(member),
                             preferred_element_type=jnp.float32)
            leaf_matrix_cache[key] = counts > 0.0
        return leaf_matrix_cache[key]

    ip_one_cache: Any = None

    # Group NFA/window leaves per bank so extraction is one matmul each.
    nfa_groups: dict[str, tuple[str, list]] = {}
    win_groups: dict[str, tuple[str, list]] = {}
    for leaf_id, binding in plan.bindings.items():
        if binding.kind == "nfa":
            entry = nfa_groups.setdefault(binding.table_key, (binding.field, []))
            entry[1].append((leaf_id, binding.span))
        elif binding.kind == "window":
            entry = win_groups.setdefault(binding.table_key, (binding.field, []))
            entry[1].append((leaf_id, binding.span))
    if nfa_groups:
        run_packed_scans(nfa_groups)
    nfa_leaf_col = {
        leaf_id: (key, j)
        for key, (field, members) in nfa_groups.items()
        for j, (leaf_id, _) in enumerate(members)
    }
    win_leaf_col = {
        leaf_id: (key, j)
        for key, (field, members) in win_groups.items()
        for j, (leaf_id, _) in enumerate(members)
    }

    for leaf_id, binding in plan.bindings.items():
        k = binding.kind
        if k == "str":
            cols = group_result(binding.table_key, binding.field, binding.group)
            results[leaf_id] = (cols[:, binding.col], no_err)
        elif k == "nfa":
            key, col = nfa_leaf_col[leaf_id]
            field, members = nfa_groups[key]
            mat = span_leaf_matrix(key, lambda key=key, field=field:
                                   nfa_result(key, field),
                                   [span for _, span in members])
            results[leaf_id] = (mat[:, col], no_err)
        elif k == "window":
            key, col = win_leaf_col[leaf_id]
            field, members = win_groups[key]
            mat = span_leaf_matrix(
                key,
                lambda key=key, field=field: gated_window_hits(key, field),
                [span for _, span in members])
            results[leaf_id] = (mat[:, col], no_err)
        elif k == "str_list":
            table = tables[binding.table_key]
            data = arrays[f"{binding.field}_bytes"]
            lens = arrays[f"{binding.field}_len"]
            lo, hi = binding.span
            if hi == lo:  # all entries were non-byte strings
                results[leaf_id] = (jnp.zeros((B,), dtype=bool), no_err)
            else:
                eqs = eq_match(data, lens, table)
                results[leaf_id] = (jnp.any(eqs[:, lo:hi], axis=1), no_err)
        elif k == "ip_one":
            if ip_one_cache is None:
                t = tables["ip_preds"]
                ips = arrays["ip"]
                diff = (ips[:, None, :] & t["masks"][None]) ^ t["nets"][None]
                ip_one_cache = jnp.all(diff == 0, axis=2)  # [B, N]
            results[leaf_id] = (ip_one_cache[:, binding.col], no_err)
        elif k == "ip_list_small":
            results[leaf_id] = (
                cidr_contains(tables[binding.table_key], arrays["ip"]), no_err)
        elif k == "ip_list_large":
            results[leaf_id] = (
                v4_buckets_contains(tables[binding.table_key], arrays["ip"]),
                no_err)
        elif k == "int_list":
            pv, pe = _eval_num(binding.pred, arrays, B)
            hit = int_set_contains(tables[binding.table_key], pv)
            results[leaf_id] = (hit, pe)
        elif k == "num_cmp":
            cmp: NumCmp = binding.pred
            lv, le = _eval_num(cmp.left, arrays, B)
            rv, re_ = _eval_num(cmp.right, arrays, B)
            results[leaf_id] = (_CMP[cmp.op](lv, rv), le | re_)
        else:
            raise AssertionError(k)
    return results


# -- boolean IR evaluation ---------------------------------------------------


def _eval_bool(ir, leaves, B):
    """-> (val [B], err [B]) reproducing interpreter error semantics:
    && / || short-circuit left-to-right; == evaluates both sides."""
    if isinstance(ir, BConst):
        return (jnp.full((B,), ir.value, dtype=bool),
                jnp.zeros((B,), dtype=bool))
    if isinstance(ir, BErrConst):
        return (jnp.zeros((B,), dtype=bool), jnp.ones((B,), dtype=bool))
    if isinstance(ir, BLeaf):
        return leaves[ir.leaf_id]
    if isinstance(ir, BNot):
        v, e = _eval_bool(ir.x, leaves, B)
        return ~v, e
    if isinstance(ir, BAnd):
        lv, le = _eval_bool(ir.left, leaves, B)
        rv, re_ = _eval_bool(ir.right, leaves, B)
        return lv & rv, le | (lv & re_)
    if isinstance(ir, BOr):
        lv, le = _eval_bool(ir.left, leaves, B)
        rv, re_ = _eval_bool(ir.right, leaves, B)
        return lv | rv, le | (~lv & re_)
    if isinstance(ir, BEqBool):
        lv, le = _eval_bool(ir.left, leaves, B)
        rv, re_ = _eval_bool(ir.right, leaves, B)
        val = lv == rv
        if ir.negate:
            val = ~val
        return val, le | re_
    raise AssertionError(f"bad bool ir {ir!r}")


# -- public API --------------------------------------------------------------


def _matched_cols(plan: RulesetPlan, tables, arrays, pf_hits=None):
    """Traced body shared by the verdict/lane functions:
    (tables, arrays) -> [B, R_dev] bool in device_rule_indices order.

    Rules whose IR is a single leaf (the common WAF shape — one
    predicate per rule) read their column straight out of the stacked
    leaf matrix with one gather; only compound rules evaluate their
    boolean tree (error -> no-match per pingoo/rules.rs:41-44 either
    way)."""
    device_rules = [r for r in plan.rules if not r.host]
    n_leaves = len(plan.leaves)
    B = arrays["asn"].shape[0]
    leaves = _eval_leaves(plan, tables, arrays, B, pf_hits=pf_hits)
    # Effective per-leaf match columns (+ const true / false).
    eff = [None] * n_leaves
    for leaf_id, (v, e) in leaves.items():
        eff[leaf_id] = v & ~e
    base = eff + [
        jnp.ones((B,), dtype=bool),  # column n_leaves: const true
        jnp.zeros((B,), dtype=bool),  # column n_leaves + 1: const false
    ]
    extra_cols = []
    rule_col: list[int] = []
    for rule in device_rules:
        if rule.always:
            rule_col.append(n_leaves)
        elif isinstance(rule.ir, BLeaf):
            rule_col.append(rule.ir.leaf_id)
        elif isinstance(rule.ir, BConst):
            rule_col.append(n_leaves if rule.ir.value else n_leaves + 1)
        elif isinstance(rule.ir, BErrConst):
            rule_col.append(n_leaves + 1)
        else:
            v, e = _eval_bool(rule.ir, leaves, B)
            rule_col.append(len(base) + len(extra_cols))
            extra_cols.append(v & ~e)
    if not rule_col:
        return jnp.zeros((B, 0), dtype=bool)
    allmat = jnp.stack(base + extra_cols, axis=1)  # [B, NL + 2 + extra]
    return jnp.take(allmat, jnp.asarray(rule_col, dtype=jnp.int32), axis=1)


def donate_batch_buffers() -> bool:
    """Whether the verdict/lane programs should mark their request
    arrays as donated inputs (ISSUE 9, docs/EXECUTOR.md). Donation
    lets XLA reuse the per-batch upload buffers in place across the
    pipelined executor's in-flight batches instead of allocating fresh
    device memory each launch — but it is only meaningful on a real
    accelerator backend: the CPU engine aliases host buffers and XLA
    just warns that the donation was unusable. So the planes request
    it exactly when the resolved backend is not `cpu` (honest gating —
    no pretend-donation on the diagnostic backend)."""
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:
        return False


def make_verdict_fn(plan: RulesetPlan, donate: bool = False):
    """Jitted device verdict: (tables, arrays) -> [B, R_dev] bool.

    `pf_hits` optionally feeds a separately-dispatched Stage-A prefilter
    pass (make_prefilter_fn); left None, Stage A traces inline under the
    active PINGOO_PREFILTER mode.

    `donate=True` marks the request arrays (arg 1) as donated buffers
    so each pipelined batch's upload can be recycled in place by XLA
    (see donate_batch_buffers for when that is honest to request)."""

    def verdict(tables, arrays, pf_hits=None):
        return _matched_cols(plan, tables, arrays, pf_hits=pf_hits)

    return jax.jit(verdict, donate_argnums=(1,) if donate else ())


# -- compact staging: device-side decode (ISSUE 15) ---------------------------


def unpack_staged(packed, layout):
    """Decode ONE packed staging buffer on device: [B, layout.width]
    uint8 -> the standard per-field arrays dict the traced evaluator
    bodies consume. Every offset/width is a static Python int from the
    (static-argument) PackedLayout, so each field comes out as a
    contiguous XLA slice — no gather, and the downstream predicate
    kernels trace exactly as they do over separately-staged arrays.

    Metadata tail: u16-LE true lens, 16 big-endian IP bytes -> [B, 4]
    uint32 words, i64-LE asn/remote_port reassembled through uint64
    shifts + a bitcast so negative values round-trip exactly."""
    arrays = {}
    for name, off, w in layout.fields:
        arrays[f"{name}_bytes"] = packed[:, off:off + w]
    for name, off in layout.lens:
        lo = packed[:, off].astype(jnp.int32)
        hi = packed[:, off + 1].astype(jnp.int32)
        arrays[f"{name}_len"] = lo | (hi << 8)
    B = packed.shape[0]
    ipb = packed[:, layout.ip_off:layout.ip_off + 16] \
        .astype(jnp.uint32).reshape(B, 4, 4)
    arrays["ip"] = ((ipb[:, :, 0] << 24) | (ipb[:, :, 1] << 16)
                    | (ipb[:, :, 2] << 8) | ipb[:, :, 3])

    def _i64(off):
        b = packed[:, off:off + 8].astype(jnp.uint64)
        v = b[:, 0]
        for k in range(1, 8):
            v = v | (b[:, k] << (8 * k))
        return jax.lax.bitcast_convert_type(v, jnp.int64)

    arrays["asn"] = _i64(layout.asn_off)
    arrays["remote_port"] = _i64(layout.port_off)
    return arrays


def make_packed_verdict_fn(plan: RulesetPlan, donate: bool = False):
    """Compact-staging twin of make_verdict_fn: (tables, packed,
    layout, pf_hits) -> [B, R_dev] bool. `layout` is a STATIC argument
    (engine/batch.PackedLayout is a hashable NamedTuple), so the traced
    body is literally _matched_cols over unpack_staged's slices — full
    and compact mode share every predicate kernel by construction, and
    plans whose caps land on the same rung-tuple share one XLA
    compile."""

    def verdict(tables, packed, layout, pf_hits=None):
        return _matched_cols(plan, tables,
                             unpack_staged(packed, layout),
                             pf_hits=pf_hits)

    return jax.jit(verdict, static_argnums=(2,),
                   donate_argnums=(1,) if donate else ())


class PrefilterProgram(NamedTuple):
    """make_prefilter_fn's bundle: the jitted Stage-A pass plus the
    static bank inventories the observability fold needs (gated = every
    cascade-gated bank; masked = the subset with a non-empty factor
    mask, in the aux vector's per-bank lane order)."""

    fn: Any
    gated: tuple[str, ...]
    masked: tuple[str, ...]


def _make_prefilter_body(plan: RulesetPlan):
    """UNJITTED Stage-A body: (stage_a, gated, masked) or None.

    Shared by make_prefilter_fn (which jits `stage_a` as its own
    dispatch so the stage is separately timeable) and make_megastep_fn
    (which inlines it per slice inside the scanned device loop) — one
    code path, so the megastep's inline prefilter is bit-identical to
    the separately-dispatched Stage-A pass by construction."""
    pf = getattr(plan, "prefilter", None)
    if pf is None or not pf.fields or _resolve_pf_mode(plan) == "off":
        return None
    # Bank keys the evaluator actually scans: NFA banks follow the scan
    # plan (split-aware); window banks are all registered win_* keys.
    scanned: list[str] = []
    for key, entry in plan.scan_plans.items():
        scanned.extend(entry.split if entry.split else (key,))
    scanned.extend(k for k in pf.bank_masks if k.startswith("win_"))
    gated = [k for k in scanned
             if pf.bank_gated.get(k) and k in pf.bank_masks
             and pf.bank_field.get(k) in pf.fields]
    # Hoisted device constants (analyze-lint recompile-const-upload).
    masks = {k: jnp.asarray(pf.bank_masks[k]) for k in gated
             if pf.bank_masks[k].any()}
    backend = _pf_backend()

    def stage_a(tables, arrays):
        hits = {}
        for field, ff in pf.fields.items():
            hits[field] = prefilter_scan(
                tables[ff.table_key], arrays[f"{field}_bytes"],
                arrays[f"{field}_len"], backend=backend)
        cand_rows = jnp.int32(0)
        skipped = jnp.int32(len(gated) - len(masks))  # never-only banks
        bank_cands = []
        bank_skips = []
        for k, mask in masks.items():
            cand = jnp.any(hits[pf.bank_field[k]] & mask[None, :], axis=1)
            n_cand = cand.sum(dtype=jnp.int32)
            skip = jnp.where(jnp.any(cand), 0, 1).astype(jnp.int32)
            cand_rows = cand_rows + n_cand
            skipped = skipped + skip
            bank_cands.append(n_cand)
            bank_skips.append(skip)
        return hits, jnp.stack([cand_rows, skipped]
                               + bank_cands + bank_skips)

    return stage_a, tuple(gated), tuple(masks)


def make_prefilter_fn(plan: RulesetPlan):
    """Jitted Stage-A pass: (tables, arrays) -> (pf_hits, aux), where
    pf_hits is {field: [B, F] bool} (feed to the verdict/lane fn so the
    pipeline stage is separately timeable) and aux is an int32 vector
    [candidate_rows_total, banks_skipped, *per-bank candidate counts,
    *per-bank skip flags] (per-bank lanes in `masked` order — the
    banks-skipped ATTRIBUTION surface, obs/provenance.py). Returns a
    PrefilterProgram or None when the plan has no prefilter / the mode
    is off."""
    body = _make_prefilter_body(plan)
    if body is None:
        return None
    stage_a, gated, masked = body
    return PrefilterProgram(fn=jax.jit(stage_a), gated=gated,
                            masked=masked)


def make_packed_prefilter_fn(plan: RulesetPlan):
    """Compact-staging twin of make_prefilter_fn: the jitted Stage-A
    signature becomes (tables, packed, layout) with `layout` static, so
    the prefilter reads its fields straight out of the one-copy packed
    buffer (ISSUE 15). Same PrefilterProgram contract; None when the
    plan has no prefilter."""
    body = _make_prefilter_body(plan)
    if body is None:
        return None
    stage_a, gated, masked = body

    def stage_a_packed(tables, packed, layout):
        return stage_a(tables, unpack_staged(packed, layout))

    return PrefilterProgram(fn=jax.jit(stage_a_packed, static_argnums=(2,)),
                            gated=gated, masked=masked)


LANE_NONE = np.int32(2**30)  # "no rule": sorts after every real index


def make_lane_fn(plan: RulesetPlan, services: list[str] | None = None,
                 service_groups: list[list[str]] | None = None,
                 with_rule_hits: bool = False, donate: bool = False):
    """Jitted device ACTION-LANE reduction: (tables, arrays) ->
    [3 + max(G, 1), B] i32 rows (first_act_idx, first_act_kind,
    first_block_idx, route lane(s)), indices in ORIGINAL rule-index
    space.

    This is the transfer-thin form of the verdict for the ring sidecar:
    instead of shipping the [B, R_dev] match matrix off the device
    (half a megabyte per 1k batch — which dominates when the chip sits
    behind a network tunnel), the first-match reduction the action
    semantics need runs on device and only a few int32 lanes return.
    Host-interpreted rules merge by index afterwards (merge_lanes).

    `services` (one listener's service names, in order) adds the ROUTE
    lane: the first service order whose route pseudo-column matched
    (the reference's service-selection loop, http_listener.rs:266-270),
    or LANE_NONE. `service_groups` generalizes to G DISTINCT listener
    service orders (the reference binds a service list PER listener,
    config.rs:241-253): one route lane per group, all computed from the
    same [B, C] match matrix in one pass — the sidecar picks each row's
    lane by the ring it came from. Services whose route predicate fell
    back to host interpretation are merged by the sidecar afterwards.

    `with_rule_hits` adds the PER-RULE ATTRIBUTION aux lane (ISSUE 5):
    the [C] int32 per-column hit counts, batch rows folded ON DEVICE
    with padding rows masked by the traced `n_valid` argument, ride the
    same dispatch as the lanes — C extra int32s per batch, so
    provenance costs no extra transfer round trip. The fn then returns
    (lanes, rule_hits); columns map to original rule indices via
    plan.device_rule_indices.

    `donate=True` marks the request arrays (arg 1) as donated buffers
    (ISSUE 9; see donate_batch_buffers for the backend gating)."""
    if service_groups is not None and services is not None:
        raise ValueError("pass services or service_groups, not both")
    groups = (service_groups if service_groups is not None
              else ([services] if services else []))
    lanes = _make_lane_body(plan, groups, with_rule_hits)
    return jax.jit(lanes, donate_argnums=(1,) if donate else ())


def make_packed_lane_fn(plan: RulesetPlan,
                        services: list[str] | None = None,
                        service_groups: list[list[str]] | None = None,
                        with_rule_hits: bool = False,
                        donate: bool = False):
    """Compact-staging twin of make_lane_fn (ISSUE 15): the jitted lane
    reduction takes (tables, packed, layout, pf_hits, n_valid) with
    `layout` static and decodes the one-copy packed buffer on device
    via unpack_staged. The traced body is the SAME _make_lane_body
    closure make_lane_fn jits, so per-batch lanes are bit-identical
    across staging modes by construction."""
    if service_groups is not None and services is not None:
        raise ValueError("pass services or service_groups, not both")
    groups = (service_groups if service_groups is not None
              else ([services] if services else []))
    lanes = _make_lane_body(plan, groups, with_rule_hits)

    def lanes_packed(tables, packed, layout, pf_hits=None, n_valid=None):
        return lanes(tables, unpack_staged(packed, layout),
                     pf_hits=pf_hits, n_valid=n_valid)

    return jax.jit(lanes_packed, static_argnums=(2,),
                   donate_argnums=(1,) if donate else ())


def _make_lane_body(plan: RulesetPlan, groups: list[list[str]],
                    with_rule_hits: bool):
    """UNJITTED lane-reduction body: (tables, arrays, pf_hits, n_valid)
    -> stacked [3 + max(G, 1), B] i32 lanes (+ [C] rule_hits when
    with_rule_hits). Shared by make_lane_fn (which jits it as the
    per-batch dispatch) and make_megastep_fn (which scans it over K
    slices in one device-resident program) — one code path, so the
    megastep's per-slice lanes are bit-identical to the per-batch
    dispatch by construction."""
    device_rules = [r for r in plan.rules if not r.host]
    orig_idx = np.array([r.index for r in device_rules], dtype=np.int32)
    first_kind = np.array(
        [(1 if r.actions[0] == Action.BLOCK else 2) if r.actions else 0
         for r in device_rules], dtype=np.int32)
    has_act = first_kind != 0
    has_block = np.array([Action.BLOCK in r.actions for r in device_rules],
                         dtype=bool)
    col_of_rule = {r.index: j for j, r in enumerate(device_rules)}
    # Per group: [(service order, matched column), ...]
    group_routes: list[list[tuple[int, int]]] = []
    for grp in groups:
        dev_route: list[tuple[int, int]] = []
        for order, name in enumerate(grp):
            ridx = plan.route_index.get(name)
            if ridx is not None and ridx in col_of_rule:
                dev_route.append((order, col_of_rule[ridx]))
        group_routes.append(dev_route)

    # Hoisted device constants (analyze-lint recompile-const-upload):
    # uploading these ONCE here keeps every retrace of `lanes` (one per
    # batch-shape bucket) from re-staging the same host arrays.
    idx_row = jnp.asarray(orig_idx)[None, :]
    has_act_row = jnp.asarray(has_act)[None, :]
    first_kind_vec = jnp.asarray(first_kind)
    has_block_row = jnp.asarray(has_block)[None, :]
    group_consts = [
        (jnp.asarray([c for _, c in dev_route], dtype=jnp.int32),
         jnp.asarray([o for o, _ in dev_route], dtype=jnp.int32))
        if dev_route else None
        for dev_route in group_routes]

    def lanes(tables, arrays, pf_hits=None, n_valid=None):
        matched = _matched_cols(plan, tables, arrays, pf_hits)  # [B, C]
        B = arrays["asn"].shape[0]

        def rule_hits():
            # Attribution fold ON DEVICE: padded batch rows are inert
            # for the lanes (their verdicts are never read) but always-
            # match columns would count them, so mask by n_valid.
            m = matched
            if n_valid is not None:
                m = m & (jnp.arange(B) < n_valid)[:, None]
            return m.sum(axis=0, dtype=jnp.int32)

        def pack(stack):
            return (stack, rule_hits()) if with_rule_hits else stack

        none = jnp.full((B,), LANE_NONE, dtype=jnp.int32)
        n_route = max(len(groups), 1)
        if matched.shape[1] == 0:
            return pack(jnp.stack([none, jnp.zeros((B,), jnp.int32), none]
                                  + [none] * n_route))
        act_idx = jnp.where(matched & has_act_row, idx_row, LANE_NONE)
        first_act_idx = jnp.min(act_idx, axis=1)
        arg = jnp.argmin(act_idx, axis=1)
        kind = jnp.where(first_act_idx < LANE_NONE,
                         jnp.take(first_kind_vec, arg), 0)
        blk_idx = jnp.where(matched & has_block_row, idx_row, LANE_NONE)
        first_block_idx = jnp.min(blk_idx, axis=1)
        route_lanes = []
        for consts in group_consts:
            if consts is not None:
                cols, orders = consts
                rm = jnp.take(matched, cols, axis=1)  # [B, S_dev]
                route_lanes.append(
                    jnp.min(jnp.where(rm, orders[None, :], LANE_NONE),
                            axis=1).astype(jnp.int32))
            else:
                route_lanes.append(none)
        if not route_lanes:
            route_lanes.append(none)
        # One stacked [3 + G, B] array = ONE device->host transfer
        # (plus the [C] attribution lane when with_rule_hits).
        return pack(jnp.stack([first_act_idx, kind, first_block_idx]
                              + route_lanes))

    return lanes


# -- device-resident megastep (ISSUE 12) --------------------------------------
#
# Every per-batch perf layer (prefilter, DFA, pipelining) pushed compute
# down until host->device dispatch became the wall: BENCH_pipeline.json
# showed the dispatch stage at ~0.88 occupancy vs ~0.26 compute. The
# megastep keeps the verdict program RESIDENT on device: one jitted
# lax.scan over K stacked batch slices runs prefilter -> DFA/NFA ->
# action lanes per slice and writes every slice's verdict words into one
# stacked output, so ONE dispatch amortizes over K batches.
#
# PINGOO_MEGASTEP (read per decision point, like PINGOO_DFA):
#   off   — per-batch dispatch, the bit-exact parity oracle.
#   auto  — engage when the executor has >= 2 batches of backlog to
#           amortize over (each plane supplies its own backlog signal).
#   force — always take the megastep path (K may degenerate to 1).
# PINGOO_MEGASTEP_K caps K (default 4); the executor sizes K down the
# pow2 ladder against the oldest slice's deadline slack using the sched
# CostModel's per-K megastep EWMAs (sched/scheduler.py).
#
# Masking, not re-shaping: every slice arrives padded to the SAME batch
# bucket; a device-side n_valid word per slice masks short slices (the
# attribution fold and the host resolve read only the valid prefix) and
# an epoch word per slice rides through the program untouched, so the
# host can assert which ruleset epoch each slice was computed under
# (hot-swaps flip plans only at megastep boundaries — docs/EXECUTOR.md).


MEGASTEP_K_DEFAULT = 4


def _resolve_megastep_mode() -> str:
    """PINGOO_MEGASTEP env knob (read per decision point so tests can
    monkeypatch it): off | auto | force, default off."""
    mode = _os.environ.get("PINGOO_MEGASTEP", "off")
    return mode if mode in ("off", "auto", "force") else "off"


def megastep_k_cap() -> int:
    """PINGOO_MEGASTEP_K: the largest K a single megastep may cover."""
    try:
        return max(1, int(_os.environ.get("PINGOO_MEGASTEP_K",
                                          str(MEGASTEP_K_DEFAULT))))
    except ValueError:
        return MEGASTEP_K_DEFAULT


def megastep_k_ladder(k_max: int) -> list[int]:
    """Static pow2 K rungs [1, 2, 4, ...] bounded by k_max — each rung
    is one compiled megastep variant, so admission can shrink K against
    the deadline budget without retracing."""
    rungs = [1]
    while rungs[-1] * 2 <= max(1, k_max):
        rungs.append(rungs[-1] * 2)
    return rungs


class MegastepProgram(NamedTuple):
    """make_megastep_fn's bundle: the jitted K-slice device loop plus
    the static metadata its callers need to unpack the outputs."""

    fn: Any          # (tables, stacked, n_valid, epoch) -> outs
    kind: str        # "lanes" (sidecar) | "matrix" (python plane)
    aux_len: int     # Stage-A aux lanes per slice (0: no prefilter)
    with_rule_hits: bool


def make_megastep_fn(plan: RulesetPlan, kind: str = "lanes",
                     service_groups: list[list[str]] | None = None,
                     with_rule_hits: bool = False,
                     donate: bool = False) -> MegastepProgram:
    """Jitted MULTI-BATCH megastep: (tables, stacked, n_valid, epoch) ->
    (out, rule_hits, pf_aux, epoch_echo), one lax.scan iteration per
    batch slice so the whole K-batch window is ONE XLA program and one
    host dispatch.

      stacked  {name: [K, B, ...]} — K batch slices, every slice padded
               to the same bucket (DeviceInputQueue, engine/batch.py).
      n_valid  [K] i32 — valid-row count per slice; short slices are
               masked, never re-shaped.
      epoch    [K] i32 — ruleset epoch stamped per slice at fill time,
               echoed back untouched (hot-swap boundary proof).

    `kind="lanes"` scans the sidecar's action-lane reduction
    (_make_lane_body) per slice -> out [K, 3 + max(G, 1), B] i32;
    `kind="matrix"` scans the python plane's match-matrix body
    (_matched_cols) -> out [K, B, C] bool. Either way the per-slice
    bodies are the SAME traced functions the per-batch dispatches jit,
    with Stage A (_make_prefilter_body) inlined per slice under the
    active PINGOO_PREFILTER mode — bit-identity with PINGOO_MEGASTEP=off
    is by construction, and tests/test_pipeline.py proves it.

    rule_hits is [K, C] (zeros-width when with_rule_hits is False),
    pf_aux is [K, aux_len] in make_prefilter_fn's aux layout (width 2
    zeros when the plan has no active prefilter), epoch_echo is [K].

    `donate=True` donates the stacked request arrays (arg 1) so XLA can
    recycle the K-slice upload in place (see donate_batch_buffers)."""
    if kind not in ("lanes", "matrix"):
        raise ValueError(f"bad megastep kind {kind!r}")
    pf_body = _make_prefilter_body(plan)
    aux_len = 2 + 2 * len(pf_body[2]) if pf_body is not None else 0
    groups = service_groups or []
    lane_body = (_make_lane_body(plan, groups, with_rule_hits)
                 if kind == "lanes" else None)
    n_hit_cols = (len([r for r in plan.rules if not r.host])
                  if with_rule_hits else 0)

    def slice_step(tables, arrays, nv, ep):
        if pf_body is not None:
            pf_hits, aux = pf_body[0](tables, arrays)
        else:
            pf_hits, aux = None, jnp.zeros((2,), dtype=jnp.int32)
        if kind == "lanes":
            out = lane_body(tables, arrays, pf_hits=pf_hits, n_valid=nv)
            if with_rule_hits:
                out, hits = out
            else:
                hits = jnp.zeros((n_hit_cols,), dtype=jnp.int32)
        else:
            out = _matched_cols(plan, tables, arrays, pf_hits=pf_hits)
            hits = jnp.zeros((n_hit_cols,), dtype=jnp.int32)
        return out, hits, aux, ep

    def megastep(tables, stacked, n_valid, epoch):
        def step(carry, xs):
            arrays_k, nv, ep = xs
            return carry, slice_step(tables, arrays_k, nv, ep)

        _, outs = jax.lax.scan(step, jnp.int32(0),
                               (stacked, n_valid, epoch))
        return outs

    return MegastepProgram(
        fn=jax.jit(megastep, donate_argnums=(1,) if donate else ()),
        kind=kind, aux_len=aux_len, with_rule_hits=with_rule_hits)


def host_rule_lanes(plan: RulesetPlan, batch, lists):
    """Host-interpreted rules' contribution to the action lanes
    (same triple as make_lane_fn, original-index space)."""
    host_rules = plan.host_rules
    B = batch.size
    first_act = np.full(B, LANE_NONE, dtype=np.int32)
    kind = np.zeros(B, dtype=np.int32)
    first_block = np.full(B, LANE_NONE, dtype=np.int32)
    if not host_rules:
        return first_act, kind, first_block
    from .batch import batch_to_contexts

    contexts = batch_to_contexts(batch, lists)
    for rule in host_rules:
        r_kind = ((1 if rule.actions[0] == Action.BLOCK else 2)
                  if rule.actions else 0)
        r_block = Action.BLOCK in rule.actions
        if not r_kind and not r_block:
            continue
        prog = rule.program
        for i, ctx in enumerate(contexts):
            if rule.index >= first_act[i] and (not r_block
                                               or rule.index >= first_block[i]):
                continue  # cannot improve either lane for this request
            try:
                m = execute_as_bool(prog, ctx)
            except Exception:
                m = False
            if not m:
                continue
            if r_kind and rule.index < first_act[i]:
                first_act[i] = rule.index
                kind[i] = r_kind
            if r_block and rule.index < first_block[i]:
                first_block[i] = rule.index
    return first_act, kind, first_block


def merge_lanes(dev_lanes, host_lanes) -> tuple[np.ndarray, np.ndarray]:
    """Combine device + host lane triples into the per-request action
    pair (unverified 0/1/2, verified_block bool) — reproducing the
    reference loop's first-match order across BOTH rule populations.
    `dev_lanes` is the stacked [3, B] array from make_lane_fn."""
    # pingoo: allow(sync-asarray-hot): the sidecar's one deliberate sync
    stacked = np.asarray(dev_lanes)
    d_act, d_kind, d_blk = stacked[0], stacked[1], stacked[2]
    h_act, h_kind, h_blk = host_lanes
    host_wins = h_act < d_act
    act_idx = np.where(host_wins, h_act, d_act)
    kind = np.where(host_wins, h_kind, d_kind)
    unverified = np.where(act_idx < LANE_NONE, kind, 0).astype(np.int32)
    verified_block = np.minimum(d_blk, h_blk) < LANE_NONE
    return unverified, verified_block


def evaluate_batch(plan, verdict_fn, tables, batch, lists,
                   on_device_wait=None) -> np.ndarray:
    """Full match matrix [B, R] in original rule order (device + host)."""
    dev = verdict_fn(tables, batch.arrays)  # async dispatch (jax)
    return finish_batch(plan, dev, batch, lists,
                        on_device_wait=on_device_wait)


def _host_matrix(plan, batch, lists) -> np.ndarray:
    """[B, R] bool with only the host-interpreted rules' columns filled
    — the interpreter half shared by finish_batch / finish_megastep, run
    FIRST so it overlaps the asynchronous device execution."""
    R = len(plan.rules)
    B = batch.size
    out = np.zeros((B, R), dtype=bool)  # the per-batch result buffer
    host_rules = plan.host_rules
    if host_rules:
        from .batch import batch_to_contexts

        contexts = batch_to_contexts(batch, lists)
        for rule in host_rules:
            prog = rule.program
            col_vals = out[:, rule.index]
            for i, ctx in enumerate(contexts):
                col_vals[i] = execute_as_bool(prog, ctx)
    return out


def _await_device(dev, on_device_wait) -> None:
    if on_device_wait is None:
        return
    import time as _time

    t0 = _time.monotonic()
    block = getattr(dev, "block_until_ready", None)
    if block is not None:
        block()
    on_device_wait((_time.monotonic() - t0) * 1e3)


def finish_batch(plan, dev, batch, lists, on_device_wait=None) -> np.ndarray:
    """Combine an in-flight device verdict with the host-interpreted
    rules. Host rules run FIRST — jax dispatch is asynchronous, so the
    interpreter work overlaps the device execution (and any transport
    latency to a remote chip) instead of serializing after it.

    `on_device_wait(ms)` (optional) receives the residual wall time
    blocked on the device result AFTER the host-rule overlap — the
    per-stage `device_compute` histogram (obs/schema.VERDICT_STAGES)."""
    out = _host_matrix(plan, batch, lists)
    _await_device(dev, on_device_wait)
    # pingoo: allow(sync-asarray-hot): the python plane's one deliberate
    dev = np.asarray(dev)  # sync point, AFTER the host-rule overlap
    for col, idx in enumerate(plan.device_rule_indices):
        out[:, idx] = dev[:, col]
    return out


def finish_megastep(plan, dev, slices, batch, lists,
                    on_device_wait=None) -> np.ndarray:
    """finish_batch for the python plane's megastep path: `dev` is the
    [K, Bs, C] stacked match matrix from a kind="matrix" megastep and
    `slices` maps each scanned slice j to its (row offset, n_valid)
    span of `batch`. Host rules run FIRST (the same async-dispatch
    overlap as finish_batch), then ONE sync unpacks every slice —
    padding rows beyond each slice's n_valid are never read."""
    out = _host_matrix(plan, batch, lists)
    _await_device(dev, on_device_wait)
    # pingoo: allow(sync-asarray-hot): the megastep's one deliberate
    dev = np.asarray(dev)  # sync point, AFTER the host-rule overlap
    for j, (off, nv) in enumerate(slices):
        rows = dev[j, :nv]
        for col, idx in enumerate(plan.device_rule_indices):
            out[off:off + nv, idx] = rows[:, col]
    return out


def interpret_rules_row(plan: RulesetPlan, ctx) -> np.ndarray:
    """One request's full match row via the host interpreter (the parity
    oracle): always-rules match, errors fail open (pingoo/rules.rs:41-44).
    Used for overflow rows whose fields exceeded device capacity."""
    row = np.zeros(len(plan.rules), dtype=bool)
    for rule in plan.rules:
        if rule.always:
            row[rule.index] = True
            continue
        try:
            row[rule.index] = execute_as_bool(rule.program, ctx)
        except Exception:
            row[rule.index] = False
    return row


def action_lanes(plan: RulesetPlan,
                 matched: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-request action decision as TWO lanes, reproducing the
    reference's rules/actions loop (http_listener.rs:251-264) for both
    captcha-verification states — a single collapsed action cannot,
    because the loop *continues past* Captcha actions for verified
    clients (a matched [Captcha, Block] rule or a later Block rule must
    still block them).

      unverified [B] int32: 0 none / 1 block / 2 captcha — the first
        matched rule with actions decides via its first action (for an
        unverified client both Block and Captcha terminate the loop).
      verified_block [B] bool: whether a VERIFIED client is blocked —
        true iff any matched rule carries a Block action anywhere in its
        action list (Captcha actions are skipped for verified clients).
    """
    rule_first = np.zeros(len(plan.rules), dtype=np.int32)
    rule_has_block = np.zeros(len(plan.rules), dtype=bool)
    for r in plan.rules:
        if r.actions:
            rule_first[r.index] = 1 if r.actions[0] == Action.BLOCK else 2
            rule_has_block[r.index] = Action.BLOCK in r.actions
    acting = matched & (rule_first != 0)[None, :]  # [B, R]
    any_hit = acting.any(axis=1)
    first = np.argmax(acting, axis=1)  # first True column (0 if none)
    unverified = np.where(any_hit, rule_first[first], 0).astype(np.int32)
    verified_block = (matched & rule_has_block[None, :]).any(axis=1)
    return unverified, verified_block


def first_action(plan: RulesetPlan, matched: np.ndarray) -> np.ndarray:
    """The unverified-client lane of `action_lanes` (0 none / 1 block /
    2 captcha). Consumers that can see captcha-verified clients must use
    both lanes."""
    return action_lanes(plan, matched)[0]
