// Minimal nghttp2 ABI declarations for the native data plane's HTTP/2
// support. Same situation as ossl_shim.h: the environment ships the
// runtime library (libnghttp2.so.14) but no development headers, so the
// handful of functions/structs used are declared here against the
// stable nghttp2 ABI (cross-checked with the Python ctypes binding in
// host/h2.py, which exercises the same surface). Linked with
// -l:libnghttp2.so.14.

#ifndef PINGOO_NGHTTP2_SHIM_H_
#define PINGOO_NGHTTP2_SHIM_H_

#include <stddef.h>
#include <stdint.h>
#include <sys/types.h>

extern "C" {

typedef struct nghttp2_session nghttp2_session;
typedef struct nghttp2_session_callbacks nghttp2_session_callbacks;

typedef struct {
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
} nghttp2_nv;

// Every member of the nghttp2_frame union begins with this header.
typedef struct {
  size_t length;
  int32_t stream_id;
  uint8_t type;
  uint8_t flags;
  uint8_t reserved;
} nghttp2_frame_hd;

typedef union {
  int fd;
  void* ptr;
} nghttp2_data_source;

typedef ssize_t (*nghttp2_data_source_read_callback)(
    nghttp2_session* session, int32_t stream_id, uint8_t* buf, size_t length,
    uint32_t* data_flags, nghttp2_data_source* source, void* user_data);

typedef struct {
  nghttp2_data_source source;
  nghttp2_data_source_read_callback read_callback;
} nghttp2_data_provider;

#define NGHTTP2_NV_FLAG_NONE 0
#define NGHTTP2_FLAG_NONE 0
#define NGHTTP2_FLAG_END_STREAM 0x1
#define NGHTTP2_FRAME_DATA 0
#define NGHTTP2_FRAME_HEADERS 1
#define NGHTTP2_DATA_FLAG_EOF 0x1
#define NGHTTP2_ERR_CALLBACK_FAILURE -902
#define NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS 3
#define NGHTTP2_INTERNAL_ERROR 2
#define NGHTTP2_FLAG_END_HEADERS 0x4
#define NGHTTP2_FRAME_GOAWAY 7
#define NGHTTP2_ERR_DEFERRED -508

typedef struct {
  int32_t settings_id;
  uint32_t value;
} nghttp2_settings_entry;

typedef int (*on_header_cb)(nghttp2_session*, const void* frame,
                            const uint8_t* name, size_t namelen,
                            const uint8_t* value, size_t valuelen,
                            uint8_t flags, void* user_data);
typedef int (*on_frame_recv_cb)(nghttp2_session*, const void* frame,
                                void* user_data);
typedef int (*on_data_chunk_cb)(nghttp2_session*, uint8_t flags,
                                int32_t stream_id, const uint8_t* data,
                                size_t len, void* user_data);
typedef int (*on_stream_close_cb)(nghttp2_session*, int32_t stream_id,
                                  uint32_t error_code, void* user_data);

int nghttp2_session_callbacks_new(nghttp2_session_callbacks** out);
void nghttp2_session_callbacks_del(nghttp2_session_callbacks* cbs);
void nghttp2_session_callbacks_set_on_header_callback(
    nghttp2_session_callbacks*, on_header_cb);
void nghttp2_session_callbacks_set_on_frame_recv_callback(
    nghttp2_session_callbacks*, on_frame_recv_cb);
void nghttp2_session_callbacks_set_on_data_chunk_recv_callback(
    nghttp2_session_callbacks*, on_data_chunk_cb);
void nghttp2_session_callbacks_set_on_stream_close_callback(
    nghttp2_session_callbacks*, on_stream_close_cb);

int nghttp2_session_client_new(nghttp2_session** out,
                               const nghttp2_session_callbacks* cbs,
                               void* user_data);
int nghttp2_submit_request(nghttp2_session* session, const void* pri_spec,
                           const nghttp2_nv* nva, size_t nvlen,
                           const nghttp2_data_provider* data_prd,
                           void* stream_user_data);
typedef struct nghttp2_option nghttp2_option;
int nghttp2_option_new(nghttp2_option** out);
void nghttp2_option_del(nghttp2_option* opt);
void nghttp2_option_set_no_auto_window_update(nghttp2_option* opt, int val);
int nghttp2_session_server_new2(nghttp2_session** out,
                                const nghttp2_session_callbacks* cbs,
                                void* user_data,
                                const nghttp2_option* opt);
int nghttp2_session_consume(nghttp2_session* session, int32_t stream_id,
                            size_t size);
int nghttp2_session_set_local_window_size(nghttp2_session* session,
                                          uint8_t flags, int32_t stream_id,
                                          int32_t window_size);
int nghttp2_session_consume_connection(nghttp2_session* session,
                                       size_t size);
int nghttp2_session_server_new(nghttp2_session** out,
                               const nghttp2_session_callbacks* cbs,
                               void* user_data);
void nghttp2_session_del(nghttp2_session* session);
ssize_t nghttp2_session_mem_recv(nghttp2_session* session, const uint8_t* in,
                                 size_t inlen);
ssize_t nghttp2_session_mem_send(nghttp2_session* session,
                                 const uint8_t** out);
int nghttp2_submit_settings(nghttp2_session* session, uint8_t flags,
                            const void* iv, size_t niv);
int nghttp2_submit_response(nghttp2_session* session, int32_t stream_id,
                            const nghttp2_nv* nva, size_t nvlen,
                            const nghttp2_data_provider* data_prd);
// pri_spec declared as const void*: we only ever pass NULL, so the
// struct layout never matters on this side of the ABI.
int nghttp2_submit_headers(nghttp2_session* session, uint8_t flags,
                           int32_t stream_id, const void* pri_spec,
                           const nghttp2_nv* nva, size_t nvlen,
                           void* stream_user_data);
int nghttp2_submit_rst_stream(nghttp2_session* session, uint8_t flags,
                              int32_t stream_id, uint32_t error_code);
int nghttp2_session_resume_data(nghttp2_session* session, int32_t stream_id);
int nghttp2_session_want_read(nghttp2_session* session);
int nghttp2_session_want_write(nghttp2_session* session);

}  // extern "C"

#endif  // PINGOO_NGHTTP2_SHIM_H_
