// HTTP load generator for the end-to-end WAF bench: drives the native
// httpd front door (loadgen_http -> httpd -> verdict ring -> sidecar ->
// 403/proxy -> pong) over real sockets with keep-alive connections and
// reports throughput + added-latency percentiles as one JSON line.
//
// Every request is timestamped at send and at response completion, so
// the measured latency covers the WHOLE added path: head parse, ring
// enqueue, sidecar batch, device verdict, verdict application, and (for
// clean traffic) the proxied upstream round trip.
//
// Usage: loadgen_http <port> <n_requests> <concurrency> <attack_permille>
//
// Attack paths match pingoo_tpu/utils/crs.py corpus staples
// (`/etc/passwd`, `\.\./`) so the 403 path is exercised at the given
// permille; 403s close the connection (the data plane's canned
// responses are connection: close) and the generator reconnects.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

const char* kCleanPaths[] = {
    "/api/v1/users?page=2", "/index.html", "/static/app.9f3c2.js",
    "/blog/2026/07/scaling-wafs", "/products/widget-2000?sort=price",
};
// Request-line-legal attack shapes (no raw spaces) hitting CRS corpus
// staples that appear even in small generated rulesets (utils/crs.py
// XSS cores: `(?i)<script`, `(?i)eval\(`).
const char* kAttackPaths[] = {
    "/page?x=<script>alert(1)</script>",
    "/?b=eval(atob('x'))",
};

struct Conn {
  int fd = -1;
  std::string inbuf;
  std::string outreq;   // pending request bytes
  double sent_at = 0;
  bool in_flight = false;
  bool expect_close = false;
  long long content_left = -1;  // -1: head not parsed yet
};

struct Stats {
  long long sent = 0, done = 0, blocked = 0, errors = 0;
  std::vector<double> lat;
};

int connect_nonblock(uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 &&
      errno != EINPROGRESS) {
    close(fd);
    return -1;
  }
  return fd;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <port> <n_requests> <concurrency> "
                 "<attack_permille>\n",
                 argv[0]);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  uint16_t port = static_cast<uint16_t>(std::atoi(argv[1]));
  long long n_requests = std::atoll(argv[2]);
  int concurrency = std::atoi(argv[3]);
  int permille = std::atoi(argv[4]);

  int ep = epoll_create1(0);
  std::vector<Conn> conns(concurrency);
  Stats st;
  st.lat.reserve(static_cast<size_t>(n_requests));
  long long seq = 0;

  auto arm = [&](int slot, uint32_t events) {
    epoll_event e{};
    e.events = events;
    e.data.u32 = static_cast<uint32_t>(slot);
    epoll_ctl(ep, EPOLL_CTL_MOD, conns[slot].fd, &e);
  };

  auto open_conn = [&](int slot) -> bool {
    Conn& c = conns[slot];
    c = Conn();
    c.fd = connect_nonblock(port);
    if (c.fd < 0) return false;
    epoll_event e{};
    e.events = EPOLLOUT | EPOLLIN;
    e.data.u32 = static_cast<uint32_t>(slot);
    epoll_ctl(ep, EPOLL_CTL_ADD, c.fd, &e);
    return true;
  };

  auto queue_request = [&](int slot) {
    Conn& c = conns[slot];
    if (c.in_flight || st.sent >= n_requests) return;
    bool attack = (seq % 1000) < permille;
    const char* path =
        attack ? kAttackPaths[seq % 2] : kCleanPaths[seq % 5];
    ++seq;
    c.outreq = std::string("GET ") + path +
               " HTTP/1.1\r\nhost: bench.test\r\nuser-agent: "
               "pingoo-bench/1.0\r\n\r\n";
    c.sent_at = now_s();
    c.in_flight = true;
    c.content_left = -1;
    c.inbuf.clear();
    ++st.sent;
  };

  for (int i = 0; i < concurrency; ++i) {
    if (!open_conn(i)) return 1;
    queue_request(i);
  }

  double deadline = now_s() + 120.0;
  double t_start = now_s();
  while (st.done + st.errors < n_requests && now_s() < deadline) {
    epoll_event events[256];
    int n = epoll_wait(ep, events, 256, 50);
    for (int i = 0; i < n; ++i) {
      int slot = static_cast<int>(events[i].data.u32);
      Conn& c = conns[slot];
      if (c.fd < 0) continue;
      bool reset = false;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) reset = true;

      if (!reset && (events[i].events & EPOLLOUT) && !c.outreq.empty()) {
        ssize_t w = send(c.fd, c.outreq.data(), c.outreq.size(), MSG_NOSIGNAL);
        if (w > 0) c.outreq.erase(0, static_cast<size_t>(w));
        else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK)
          reset = true;
      }
      if (!reset && (events[i].events & EPOLLIN)) {
        char buf[16384];
        ssize_t r;
        while ((r = read(c.fd, buf, sizeof(buf))) > 0)
          c.inbuf.append(buf, static_cast<size_t>(r));
        if (r == 0) reset = true;  // handled after response parse
        // Parse one response: head + content-length body.
        if (c.in_flight && c.content_left == -1) {
          size_t he = c.inbuf.find("\r\n\r\n");
          if (he != std::string::npos) {
            std::string head = c.inbuf.substr(0, he + 4);
            c.inbuf.erase(0, he + 4);
            int status = 0;
            if (head.size() > 12) status = atoi(head.c_str() + 9);
            c.content_left = 0;
            size_t p = head.find("ontent-length:");
            if (p != std::string::npos)
              c.content_left = atoll(head.c_str() + p + 14);
            c.expect_close =
                head.find("connection: close") != std::string::npos;
            if (status == 403) ++st.blocked;
            if (status == 0) {
              ++st.errors;
              c.in_flight = false;
              reset = true;
            }
          }
        }
        if (c.in_flight && c.content_left >= 0) {
          long long take = std::min<long long>(
              c.content_left, static_cast<long long>(c.inbuf.size()));
          c.inbuf.erase(0, static_cast<size_t>(take));
          c.content_left -= take;
          if (c.content_left == 0) {
            st.lat.push_back(now_s() - c.sent_at);
            ++st.done;
            c.in_flight = false;
            if (c.expect_close) {
              reset = true;
            } else {
              queue_request(slot);
            }
          }
        }
      }
      if (reset) {
        if (c.in_flight) {
          // Count an aborted in-flight request as an error unless the
          // close raced a completed parse above.
          ++st.errors;
          c.in_flight = false;
        }
        epoll_ctl(ep, EPOLL_CTL_DEL, c.fd, nullptr);
        close(c.fd);
        c.fd = -1;
        if (st.sent < n_requests) {
          if (open_conn(slot)) queue_request(slot);
        }
        continue;
      }
      if (c.fd >= 0)
        arm(slot, EPOLLIN | (c.outreq.empty() ? 0 : EPOLLOUT));
    }
  }
  double elapsed = now_s() - t_start;

  std::sort(st.lat.begin(), st.lat.end());
  auto pct = [&](double q) -> double {
    if (st.lat.empty()) return 0;
    size_t idx = static_cast<size_t>(q * (st.lat.size() - 1));
    return st.lat[idx] * 1000.0;
  };
  std::printf(
      "{\"completed\": %lld, \"blocked\": %lld, \"errors\": %lld, "
      "\"elapsed_s\": %.3f, \"req_per_s\": %.1f, \"p50_ms\": %.3f, "
      "\"p90_ms\": %.3f, \"p99_ms\": %.3f}\n",
      st.done, st.blocked, st.errors, elapsed,
      elapsed > 0 ? st.done / elapsed : 0.0, pct(0.50), pct(0.90),
      pct(0.99));
  return st.done > 0 ? 0 : 1;
}
