// Shared-memory verdict ring: the host-data-plane <-> TPU-sidecar
// transport (SURVEY.md §7 architecture split item 4: "lock-free
// shared-memory ring (fixed-size slots mirroring RequestData/ClientData,
// pingoo/rules.rs:17-34) ... batching window tuned against the 2ms p99
// budget; verdict bitmap return").
//
// Layout: one file mapping = [RingHeader][request slots][verdict slots].
// Both rings are Vyukov bounded MPMC queues (per-slot sequence numbers),
// so any number of data-plane threads can enqueue requests while the
// sidecar drains batches, and verdicts flow back keyed by ticket id.
//
// The slot field layout mirrors pingoo_tpu/engine/batch.py field specs
// (method 16 / host 256 / path 2048 / url 2048 / user_agent 256 bytes,
// v6-mapped ip words, asn/port columns) so the Python side can decode a
// whole batch with one numpy structured view, no per-field parsing.
// A request whose field exceeded its cap at enqueue time carries
// PINGOO_SLOT_FLAG_TRUNCATED, and — for path/url — its FULL strings in
// a claimed spill slot (v3): the sidecar re-evaluates such rows over
// the untruncated bytes (native_ring.RingSidecar), mirroring the
// Python listener's overflow re-evaluation (engine/service.py). Only
// when the spill pool is exhausted does a row fall back to slot-view
// matching (still counted via truncated_rows).

#ifndef PINGOO_RING_H_
#define PINGOO_RING_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
#define PINGOO_ALIGN8 alignas(8)
#define PINGOO_ALIGN64 alignas(64)
#else
#define PINGOO_ALIGN8 _Alignas(8)
#define PINGOO_ALIGN64 _Alignas(64)
#endif

#ifdef __cplusplus
extern "C" {
#endif

#define PINGOO_RING_MAGIC 0x50474f52u  // "PGOR"
// v4: slot carries enq_ms (monotonic enqueue timestamp) and the header
// grows an atomic telemetry block (ISSUE 2 observability).
// v5: the header grows a liveness block (ISSUE 10 sidecar supervision):
// sidecar_epoch (monotonically bumped on every sidecar attach, so the
// data plane can tell a restart from a stall), sidecar_heartbeat_ms
// (stamped by the sidecar each poll cycle; the httpd event loop flips
// into the degraded fast-path when it goes stale past
// PINGOO_SIDECAR_TIMEOUT_MS), and posted_floor (all tickets below it
// have verdicts posted — the crash-reattach reconciliation scans
// [posted_floor, req_tail) for orphans).
// v6: body-window ring (ISSUE 13 streaming body inspection). A third
// Vyukov ring of fixed-count bounded slots carries de-framed request
// body bytes as (flow ticket, win_seq, FINAL/ABORT flags) windows from
// the data plane to the sidecar; body verdicts ride the EXISTING
// verdict ring with PINGOO_BODY_VERDICT_BIT set in the ticket. The
// header gains body_slot_size/body_capacity up front and a
// body_head/body_tail cache-line pair at the end.
#define PINGOO_RING_VERSION 6u

#define PINGOO_METHOD_CAP 16
#define PINGOO_HOST_CAP 256
#define PINGOO_PATH_CAP 2048
#define PINGOO_URL_CAP 2048
#define PINGOO_UA_CAP 256

#define PINGOO_SLOT_FLAG_TRUNCATED 0x1u

// Overflow spill: a request whose path/url exceeds the fixed slot caps
// claims one spill slot and carries its FULL strings there, so the
// consumer can evaluate flagged rows over untruncated bytes — matching
// the reference, which matches full strings (http_listener.rs:140-141).
// 64 KiB covers both strings at the data plane's 32 KiB head cap.
// spill_idx == PINGOO_SPILL_NONE means no spill (not truncated, or the
// spill area was exhausted — then the row is matched on the slot view
// and only counted, the pre-v3 behavior).
#define PINGOO_SPILL_SLOTS 64u
#define PINGOO_SPILL_DATA_CAP 65536u
#define PINGOO_SPILL_NONE 0xFFu

typedef struct {
  PINGOO_ALIGN8 uint64_t state;  // 0 free / 1 claimed (CAS by producers)
  uint32_t url_len;
  uint32_t path_len;
  char data[PINGOO_SPILL_DATA_CAP];  // url bytes then path bytes
} PingooSpillSlot;

// Body-window ring (v6, ISSUE 13): the data plane streams each request
// body as bounded windows of DE-FRAMED payload bytes (chunked TE
// already decoded) tagged with the owning request ticket and a per-flow
// sequence number, so the sidecar threads NFA/DFA carry state across
// windows (engine/bodyscan.py) and a payload split across DATA frames
// matches bit-identically to the contiguous scan. Fixed slot count —
// independent of the request-ring capacity — bounds the in-flight body
// bytes at PINGOO_BODY_SLOTS * PINGOO_BODY_WINDOW_CAP = 1 MiB.
#define PINGOO_BODY_SLOTS 256u
#define PINGOO_BODY_WINDOW_CAP 4096u
#define PINGOO_BODY_FLAG_FINAL 0x1u  // last window of the flow
#define PINGOO_BODY_FLAG_ABORT 0x2u  // flow died (client reset): drop state
// Body verdicts share the verdict ring: the sidecar posts them with
// this bit set in the ticket so the data plane demuxes meta vs body
// verdicts without a second return ring.
#define PINGOO_BODY_VERDICT_BIT 0x8000000000000000ull

typedef struct {
  PINGOO_ALIGN8 uint64_t seq;  // Vyukov slot sequence
  uint64_t flow;               // request ticket that owns this body
  uint32_t win_seq;            // 0-based window index within the flow
  uint32_t win_len;            // payload bytes in data[]
  uint64_t total_len;          // body bytes up to + including this window
  uint8_t flags;               // PINGOO_BODY_FLAG_*
  uint8_t _pad[7];
  char data[PINGOO_BODY_WINDOW_CAP];
} PingooBodySlot;

typedef struct {
  // Vyukov slot sequence: slot is writable when seq == pos, readable
  // when seq == pos + 1.
  PINGOO_ALIGN8 uint64_t seq;
  uint64_t ticket;  // request id chosen by the producer
  uint64_t enq_ms;  // CLOCK_MONOTONIC ms at enqueue (set by the ring);
                    // consumers feed it back via pingoo_ring_record_waits
                    // so the telemetry block's verdict-wait histogram
                    // measures enqueue -> verdict-post per request
  uint16_t method_len, host_len, path_len, url_len, ua_len;
  uint16_t remote_port;
  uint8_t ip[16];  // big-endian, v4 addresses v6-mapped (::ffff:a.b.c.d)
  uint32_t asn;
  char country[2];
  uint8_t flags;      // PINGOO_SLOT_FLAG_* (set by enqueue)
  uint8_t spill_idx;  // PINGOO_SPILL_NONE or the claimed spill slot
  char method[PINGOO_METHOD_CAP];
  char host[PINGOO_HOST_CAP];
  char path[PINGOO_PATH_CAP];
  char url[PINGOO_URL_CAP];
  char user_agent[PINGOO_UA_CAP];
} PingooRequestSlot;

typedef struct {
  PINGOO_ALIGN8 uint64_t seq;
  uint64_t ticket;
  // Two-lane encoding (the reference action loop diverges per client
  // captcha state, http_listener.rs:251-264): bits 0-1 = action for an
  // UNVERIFIED client (0 none, 1 block, 2 captcha); bit 2 = a VERIFIED
  // client must be blocked. Consumers mask: (action & 3) / (action & 4).
  uint8_t action;
  uint8_t _pad[3];
  float bot_score;
} PingooVerdictSlot;

// Verdict-wait histogram bucket upper bounds (ms); the last bucket is
// +inf. Shared with both planes' Prometheus exposition
// (pingoo_verdict_wait_ms, pingoo_tpu/obs/schema.py).
#define PINGOO_WAIT_BUCKETS 8u
// bounds: 1, 2, 5, 10, 50, 100, 1000, +inf

// Atomic telemetry block inside the shared header (v4): counters the
// producers/consumers maintain with relaxed fetch-adds so queue health
// (depth high-water mark, full-ring stalls, enqueue->verdict-post wait)
// is visible to BOTH planes' /__pingoo/metrics scrape without any
// side-channel. All fields monotonic except depth (derived).
typedef struct {
  PINGOO_ALIGN64 uint64_t enqueued;     // request slots enqueued
  uint64_t enqueue_full;                // enqueues refused: request ring full
  uint64_t dequeued;                    // request slots dequeued
  uint64_t depth_hwm;                   // high-water mark of queued requests
  uint64_t verdicts_posted;             // verdict slots posted
  uint64_t verdict_post_full;           // posts refused: verdict ring full
  uint64_t wait_sum_ms;                 // sum of recorded waits (ms)
  uint64_t wait_hist[PINGOO_WAIT_BUCKETS];  // enqueue -> verdict-post
} PingooRingTelemetry;

// Flat snapshot order for pingoo_ring_telemetry_snapshot (one uint64
// array keeps the ctypes binding to a single pointer): enqueued,
// enqueue_full, dequeued, depth (head - tail, sampled now), depth_hwm,
// verdicts_posted, verdict_post_full, wait_sum_ms, wait_hist[8].
#define PINGOO_TELEMETRY_WORDS (8u + PINGOO_WAIT_BUCKETS)

typedef struct {
  uint32_t magic;
  uint32_t version;
  uint32_t capacity;  // power of two, same for request+verdict rings
  uint32_t request_slot_size;
  uint32_t verdict_slot_size;
  uint32_t body_slot_size;  // sizeof(PingooBodySlot) (v6)
  uint32_t body_capacity;   // PINGOO_BODY_SLOTS (v6)
  PINGOO_ALIGN64 uint64_t req_head;  // producer ticket counter
  PINGOO_ALIGN64 uint64_t req_tail;  // consumer counter
  PINGOO_ALIGN64 uint64_t ver_head;
  PINGOO_ALIGN64 uint64_t ver_tail;
  PINGOO_ALIGN64 PingooRingTelemetry telemetry;
  // Liveness block (v5, ISSUE 10): its own cache line so heartbeat
  // stores never contend with the head/tail CAS lines.
  PINGOO_ALIGN64 uint64_t sidecar_epoch;   // bumped on sidecar attach
  uint64_t sidecar_heartbeat_ms;           // pingoo_ring_now_ms stamp
  uint64_t posted_floor;                   // tickets < floor have verdicts
  // Body-window ring counters (v6): their own cache lines, same
  // single-producer/single-consumer contention split as req/ver.
  PINGOO_ALIGN64 uint64_t body_head;
  PINGOO_ALIGN64 uint64_t body_tail;
} PingooRingHeader;

// Size of the full mapping for a given capacity.
size_t pingoo_ring_bytes(uint32_t capacity);

// Initialize a fresh ring inside `mem` (caller maps the file/shm).
void pingoo_ring_init(void* mem, uint32_t capacity);

// Validate an existing mapping; returns 0 on success.
int pingoo_ring_attach(void* mem, uint32_t* capacity_out);

// Enqueue one request; returns the ticket id, or UINT64_MAX if full.
uint64_t pingoo_ring_enqueue_request(
    void* mem, const char* method, uint32_t method_len, const char* host,
    uint32_t host_len, const char* path, uint32_t path_len, const char* url,
    uint32_t url_len, const char* ua, uint32_t ua_len, const uint8_t ip[16],
    uint16_t remote_port, uint32_t asn, const char country[2]);

// Dequeue up to `max` requests into `out`; returns the count.
uint32_t pingoo_ring_dequeue_requests(void* mem, PingooRequestSlot* out,
                                      uint32_t max);

// Post a verdict; returns 0 on success, -1 if the verdict ring is full.
int pingoo_ring_post_verdict(void* mem, uint64_t ticket, uint8_t action,
                             float bot_score);

// Post a batch of verdicts in one call (one ctypes/FFI hop for the
// Python sidecar instead of one per ticket); returns how many were
// posted — fewer than `n` only when the verdict ring filled up, in
// which case the caller retries from that index.
uint32_t pingoo_ring_post_verdicts(void* mem, const uint64_t* tickets,
                                   const uint8_t* actions, uint32_t n);

// Poll one verdict; returns 0 on success, -1 if empty.
int pingoo_ring_poll_verdict(void* mem, uint64_t* ticket_out,
                             uint8_t* action_out, float* score_out);

// Enqueue one body window (v6). `len` must be <= PINGOO_BODY_WINDOW_CAP
// (-2 otherwise); returns 0 on success, -1 when the body ring is full —
// the producer then fails the flow open to metadata-only verdicts
// rather than stalling the event loop.
int pingoo_ring_enqueue_body(void* mem, uint64_t flow, uint32_t win_seq,
                             uint64_t total_len, const char* data,
                             uint32_t len, uint8_t flags);

// Dequeue up to `max` body windows into `out`; returns the count.
uint32_t pingoo_ring_dequeue_bodies(void* mem, PingooBodySlot* out,
                                    uint32_t max);

// Read a claimed spill slot's full strings. Returns 0 on success and
// fills the pointers/lengths (data stays valid until release).
int pingoo_ring_spill_read(void* mem, uint8_t idx, const char** url,
                           uint32_t* url_len, const char** path,
                           uint32_t* path_len);

// Release a spill slot back to the free pool (consumer side, after the
// row's verdict was computed over the untruncated strings).
void pingoo_ring_spill_release(void* mem, uint8_t idx);

// Copy the telemetry block into out[PINGOO_TELEMETRY_WORDS] (flat
// order documented at PINGOO_TELEMETRY_WORDS above). Relaxed loads:
// a scrape-time snapshot, not a linearization point.
void pingoo_ring_telemetry_snapshot(void* mem, uint64_t* out);

// Record n enqueue->now waits into the telemetry wait histogram; the
// consumer passes the dequeued slots' enq_ms values at verdict-post
// time (one FFI hop per batch for the Python sidecar).
void pingoo_ring_record_waits(void* mem, const uint64_t* enq_ms,
                              uint32_t n);

// CLOCK_MONOTONIC milliseconds — the enq_ms time base, exported so
// out-of-process consumers compute waits against the same clock.
uint64_t pingoo_ring_now_ms(void);

// -- Liveness / supervision protocol (v5, ISSUE 10) --------------------------

// Sidecar attach: bump the epoch (release), stamp the first heartbeat,
// and return the NEW epoch. Called once per sidecar boot/reattach; a
// data plane observing the epoch change knows the previous consumer is
// gone and any reconciliation is the new epoch's responsibility.
uint64_t pingoo_ring_sidecar_attach(void* mem);

// Stamp the heartbeat with pingoo_ring_now_ms() (relaxed store; the
// sidecar calls this every poll cycle — staleness, not ordering, is
// the signal).
void pingoo_ring_heartbeat(void* mem);

// Snapshot the liveness block into out[5]: epoch, heartbeat_ms,
// posted_floor, req_tail, now_ms — one call so the data plane's event
// loop reads a consistent-enough picture with a single FFI/shm touch.
void pingoo_ring_liveness(void* mem, uint64_t out[5]);

// Advance the posted floor to `ticket` (monotonic max; relaxed CAS
// loop so late batch completions can't move it backwards). All tickets
// below the floor have verdicts posted.
void pingoo_ring_set_posted_floor(void* mem, uint64_t ticket);

// Reclaim one orphaned request ticket during crash-reattach
// reconciliation (tickets in [posted_floor, req_tail)). Returns 0 and
// copies the slot into `out` when the request bytes are still intact
// (the new sidecar re-evaluates them); returns -1 when the bytes are
// gone (a producer reclaimed the slot — the caller fail-opens the
// ticket instead). Also releases slots wedged by a consumer that died
// between its tail-CAS and seq-release, which would otherwise stall
// the ring forever at that position.
int pingoo_ring_reclaim_request(void* mem, uint64_t ticket,
                                PingooRequestSlot* out);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // PINGOO_RING_H_
