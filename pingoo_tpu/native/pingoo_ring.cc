// Vyukov bounded MPMC rings over a shared mapping. See pingoo_ring.h.

#include "pingoo_ring.h"

#include <time.h>

#include <atomic>
#include <cstring>

namespace {

inline std::atomic<uint64_t>* as_atomic(uint64_t* p) {
  return reinterpret_cast<std::atomic<uint64_t>*>(p);
}

inline void tel_add(uint64_t* field, uint64_t n) {
  as_atomic(field)->fetch_add(n, std::memory_order_relaxed);
}

// CAS-max: racing producers may publish interleaved highs; the final
// value is the max of all observed depths, which is what a high-water
// mark means.
inline void tel_max(uint64_t* field, uint64_t v) {
  auto* a = as_atomic(field);
  uint64_t cur = a->load(std::memory_order_relaxed);
  while (v > cur &&
         !a->compare_exchange_weak(cur, v, std::memory_order_relaxed)) {
  }
}

// Verdict-wait bucket upper bounds in ms (last bucket +inf); keep in
// sync with PINGOO_WAIT_BUCKETS and obs/schema.SHARED_WAIT_BUCKETS_MS.
const uint64_t kWaitBoundsMs[PINGOO_WAIT_BUCKETS - 1] = {1,  2,   5,   10,
                                                         50, 100, 1000};

inline uint32_t wait_bucket(uint64_t ms) {
  for (uint32_t i = 0; i < PINGOO_WAIT_BUCKETS - 1; ++i) {
    if (ms < kWaitBoundsMs[i]) return i;
  }
  return PINGOO_WAIT_BUCKETS - 1;
}

struct Layout {
  PingooRingHeader* header;
  PingooRequestSlot* req;
  PingooVerdictSlot* ver;
  PingooSpillSlot* spill;
  PingooBodySlot* body;
};

Layout layout(void* mem, uint32_t capacity) {
  Layout l;
  l.header = static_cast<PingooRingHeader*>(mem);
  l.req = reinterpret_cast<PingooRequestSlot*>(
      static_cast<char*>(mem) + sizeof(PingooRingHeader));
  l.ver = reinterpret_cast<PingooVerdictSlot*>(
      reinterpret_cast<char*>(l.req) + sizeof(PingooRequestSlot) * capacity);
  l.spill = reinterpret_cast<PingooSpillSlot*>(
      reinterpret_cast<char*>(l.ver) + sizeof(PingooVerdictSlot) * capacity);
  l.body = reinterpret_cast<PingooBodySlot*>(
      reinterpret_cast<char*>(l.spill) +
      sizeof(PingooSpillSlot) * PINGOO_SPILL_SLOTS);
  return l;
}

// Claim a free spill slot (CAS over the small fixed pool); returns
// PINGOO_SPILL_NONE when every slot is in flight.
uint8_t spill_claim(Layout& l) {
  for (uint32_t i = 0; i < PINGOO_SPILL_SLOTS; ++i) {
    auto* st = as_atomic(&l.spill[i].state);
    uint64_t expect = 0;
    if (st->compare_exchange_strong(expect, 1, std::memory_order_acquire))
      return static_cast<uint8_t>(i);
  }
  return PINGOO_SPILL_NONE;
}

// Returns true if the source exceeded the cap (the slot then carries a
// truncated view and must be flagged for off-device re-evaluation).
inline bool copy_capped(char* dst, uint32_t cap, const char* src, uint32_t len,
                        uint16_t* len_out) {
  uint32_t n = len < cap ? len : cap;
  std::memcpy(dst, src, n);
  if (n < cap) std::memset(dst + n, 0, cap - n);
  *len_out = static_cast<uint16_t>(n);
  return len > cap;
}

}  // namespace

extern "C" {

size_t pingoo_ring_bytes(uint32_t capacity) {
  return sizeof(PingooRingHeader) +
         capacity * (sizeof(PingooRequestSlot) + sizeof(PingooVerdictSlot)) +
         PINGOO_SPILL_SLOTS * sizeof(PingooSpillSlot) +
         PINGOO_BODY_SLOTS * sizeof(PingooBodySlot);
}

void pingoo_ring_init(void* mem, uint32_t capacity) {
  std::memset(mem, 0, pingoo_ring_bytes(capacity));
  Layout l = layout(mem, capacity);
  l.header->magic = PINGOO_RING_MAGIC;
  l.header->version = PINGOO_RING_VERSION;
  l.header->capacity = capacity;
  l.header->request_slot_size = sizeof(PingooRequestSlot);
  l.header->verdict_slot_size = sizeof(PingooVerdictSlot);
  l.header->body_slot_size = sizeof(PingooBodySlot);
  l.header->body_capacity = PINGOO_BODY_SLOTS;
  for (uint32_t i = 0; i < capacity; ++i) {
    as_atomic(&l.req[i].seq)->store(i, std::memory_order_relaxed);
    as_atomic(&l.ver[i].seq)->store(i, std::memory_order_relaxed);
  }
  for (uint32_t i = 0; i < PINGOO_BODY_SLOTS; ++i)
    as_atomic(&l.body[i].seq)->store(i, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
}

int pingoo_ring_attach(void* mem, uint32_t* capacity_out) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  if (header->magic != PINGOO_RING_MAGIC ||
      header->version != PINGOO_RING_VERSION ||
      header->request_slot_size != sizeof(PingooRequestSlot) ||
      header->verdict_slot_size != sizeof(PingooVerdictSlot) ||
      header->body_slot_size != sizeof(PingooBodySlot) ||
      header->body_capacity != PINGOO_BODY_SLOTS) {
    return -1;
  }
  if (capacity_out) *capacity_out = header->capacity;
  return 0;
}

uint64_t pingoo_ring_enqueue_request(
    void* mem, const char* method, uint32_t method_len, const char* host,
    uint32_t host_len, const char* path, uint32_t path_len, const char* url,
    uint32_t url_len, const char* ua, uint32_t ua_len, const uint8_t ip[16],
    uint16_t remote_port, uint32_t asn, const char country[2]) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  uint32_t cap = header->capacity;
  Layout l = layout(mem, cap);
  auto* head = as_atomic(&header->req_head);

  uint64_t pos = head->load(std::memory_order_relaxed);
  for (;;) {
    PingooRequestSlot* slot = &l.req[pos & (cap - 1)];
    uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
    intptr_t diff = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
    if (diff == 0) {
      if (head->compare_exchange_weak(pos, pos + 1,
                                      std::memory_order_relaxed)) {
        slot->ticket = pos;
        slot->enq_ms = pingoo_ring_now_ms();
        bool truncated = false;
        truncated |= copy_capped(slot->method, PINGOO_METHOD_CAP, method,
                                 method_len, &slot->method_len);
        truncated |= copy_capped(slot->host, PINGOO_HOST_CAP, host, host_len,
                                 &slot->host_len);
        truncated |= copy_capped(slot->path, PINGOO_PATH_CAP, path, path_len,
                                 &slot->path_len);
        truncated |= copy_capped(slot->url, PINGOO_URL_CAP, url, url_len,
                                 &slot->url_len);
        truncated |= copy_capped(slot->user_agent, PINGOO_UA_CAP, ua, ua_len,
                                 &slot->ua_len);
        std::memcpy(slot->ip, ip, 16);
        slot->remote_port = remote_port;
        slot->asn = asn;
        slot->country[0] = country[0];
        slot->country[1] = country[1];
        slot->flags = truncated ? PINGOO_SLOT_FLAG_TRUNCATED : 0;
        slot->spill_idx = PINGOO_SPILL_NONE;
        // Over-cap path/url: park the FULL strings in a spill slot so
        // the consumer evaluates this row over untruncated bytes
        // (method/host/ua overflows are normalized before enqueue by
        // both data planes: host empties, UA 403s).
        if ((path_len > PINGOO_PATH_CAP || url_len > PINGOO_URL_CAP) &&
            url_len + path_len <= PINGOO_SPILL_DATA_CAP) {
          uint8_t sidx = spill_claim(l);
          if (sidx != PINGOO_SPILL_NONE) {
            PingooSpillSlot* sp = &l.spill[sidx];
            sp->url_len = url_len;
            sp->path_len = path_len;
            std::memcpy(sp->data, url, url_len);
            std::memcpy(sp->data + url_len, path, path_len);
            slot->spill_idx = sidx;
          }
        }
        as_atomic(&slot->seq)->store(pos + 1, std::memory_order_release);
        PingooRingTelemetry* tel = &header->telemetry;
        tel_add(&tel->enqueued, 1);
        uint64_t tail =
            as_atomic(&header->req_tail)->load(std::memory_order_relaxed);
        if (pos + 1 > tail) tel_max(&tel->depth_hwm, pos + 1 - tail);
        return pos;
      }
    } else if (diff < 0) {
      tel_add(&header->telemetry.enqueue_full, 1);
      return UINT64_MAX;  // full
    } else {
      pos = head->load(std::memory_order_relaxed);
    }
  }
}

uint32_t pingoo_ring_dequeue_requests(void* mem, PingooRequestSlot* out,
                                      uint32_t max) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  uint32_t cap = header->capacity;
  Layout l = layout(mem, cap);
  auto* tail = as_atomic(&header->req_tail);

  uint32_t count = 0;
  while (count < max) {
    uint64_t pos = tail->load(std::memory_order_relaxed);
    PingooRequestSlot* slot = &l.req[pos & (cap - 1)];
    uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
    intptr_t diff =
        static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
    if (diff == 0) {
      if (tail->compare_exchange_weak(pos, pos + 1,
                                      std::memory_order_relaxed)) {
        std::memcpy(&out[count], slot, sizeof(PingooRequestSlot));
        as_atomic(&slot->seq)->store(pos + cap, std::memory_order_release);
        ++count;
      }
    } else {
      break;  // empty
    }
  }
  if (count) tel_add(&header->telemetry.dequeued, count);
  return count;
}

int pingoo_ring_post_verdict(void* mem, uint64_t ticket, uint8_t action,
                             float bot_score) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  uint32_t cap = header->capacity;
  Layout l = layout(mem, cap);
  auto* head = as_atomic(&header->ver_head);

  uint64_t pos = head->load(std::memory_order_relaxed);
  for (;;) {
    PingooVerdictSlot* slot = &l.ver[pos & (cap - 1)];
    uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
    intptr_t diff = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
    if (diff == 0) {
      if (head->compare_exchange_weak(pos, pos + 1,
                                      std::memory_order_relaxed)) {
        slot->ticket = ticket;
        slot->action = action;
        slot->bot_score = bot_score;
        as_atomic(&slot->seq)->store(pos + 1, std::memory_order_release);
        tel_add(&header->telemetry.verdicts_posted, 1);
        return 0;
      }
    } else if (diff < 0) {
      tel_add(&header->telemetry.verdict_post_full, 1);
      return -1;  // full
    } else {
      pos = head->load(std::memory_order_relaxed);
    }
  }
}

int pingoo_ring_spill_read(void* mem, uint8_t idx, const char** url,
                           uint32_t* url_len, const char** path,
                           uint32_t* path_len) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  Layout l = layout(mem, header->capacity);
  if (idx >= PINGOO_SPILL_SLOTS) return -1;
  PingooSpillSlot* sp = &l.spill[idx];
  if (as_atomic(&sp->state)->load(std::memory_order_acquire) != 1) return -1;
  if (sp->url_len + sp->path_len > PINGOO_SPILL_DATA_CAP) return -1;
  *url = sp->data;
  *url_len = sp->url_len;
  *path = sp->data + sp->url_len;
  *path_len = sp->path_len;
  return 0;
}

void pingoo_ring_spill_release(void* mem, uint8_t idx) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  Layout l = layout(mem, header->capacity);
  if (idx >= PINGOO_SPILL_SLOTS) return;
  as_atomic(&l.spill[idx].state)->store(0, std::memory_order_release);
}

uint32_t pingoo_ring_post_verdicts(void* mem, const uint64_t* tickets,
                                   const uint8_t* actions, uint32_t n) {
  for (uint32_t i = 0; i < n; ++i) {
    if (pingoo_ring_post_verdict(mem, tickets[i], actions[i], 0.0f) != 0)
      return i;  // ring full: caller resumes from index i
  }
  return n;
}

uint64_t pingoo_ring_now_ms(void) {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 +
         static_cast<uint64_t>(ts.tv_nsec) / 1000000;
}

void pingoo_ring_record_waits(void* mem, const uint64_t* enq_ms,
                              uint32_t n) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  PingooRingTelemetry* tel = &header->telemetry;
  uint64_t now = pingoo_ring_now_ms();
  uint64_t sum = 0;
  uint64_t bucket_n[PINGOO_WAIT_BUCKETS] = {0};
  for (uint32_t i = 0; i < n; ++i) {
    // A clock-skewed (or zero) enq_ms clamps to 0 rather than wrapping
    // into the +inf bucket.
    uint64_t ms = enq_ms[i] && now > enq_ms[i] ? now - enq_ms[i] : 0;
    sum += ms;
    bucket_n[wait_bucket(ms)]++;
  }
  tel_add(&tel->wait_sum_ms, sum);
  for (uint32_t b = 0; b < PINGOO_WAIT_BUCKETS; ++b) {
    if (bucket_n[b]) tel_add(&tel->wait_hist[b], bucket_n[b]);
  }
}

void pingoo_ring_telemetry_snapshot(void* mem, uint64_t* out) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  PingooRingTelemetry* tel = &header->telemetry;
  auto rd = [](uint64_t* p) {
    return as_atomic(p)->load(std::memory_order_relaxed);
  };
  uint64_t head = rd(&header->req_head);
  uint64_t tail = rd(&header->req_tail);
  out[0] = rd(&tel->enqueued);
  out[1] = rd(&tel->enqueue_full);
  out[2] = rd(&tel->dequeued);
  out[3] = head > tail ? head - tail : 0;  // current depth
  out[4] = rd(&tel->depth_hwm);
  out[5] = rd(&tel->verdicts_posted);
  out[6] = rd(&tel->verdict_post_full);
  out[7] = rd(&tel->wait_sum_ms);
  for (uint32_t b = 0; b < PINGOO_WAIT_BUCKETS; ++b)
    out[8 + b] = rd(&tel->wait_hist[b]);
}

// -- Liveness / supervision protocol (v5, ISSUE 10) --------------------------

uint64_t pingoo_ring_sidecar_attach(void* mem) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  uint64_t epoch =
      as_atomic(&header->sidecar_epoch)->fetch_add(1, std::memory_order_acq_rel)
      + 1;
  as_atomic(&header->sidecar_heartbeat_ms)
      ->store(pingoo_ring_now_ms(), std::memory_order_release);
  return epoch;
}

void pingoo_ring_heartbeat(void* mem) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  as_atomic(&header->sidecar_heartbeat_ms)
      ->store(pingoo_ring_now_ms(), std::memory_order_relaxed);
}

void pingoo_ring_liveness(void* mem, uint64_t out[5]) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  out[0] = as_atomic(&header->sidecar_epoch)->load(std::memory_order_acquire);
  out[1] = as_atomic(&header->sidecar_heartbeat_ms)
               ->load(std::memory_order_relaxed);
  out[2] = as_atomic(&header->posted_floor)->load(std::memory_order_relaxed);
  out[3] = as_atomic(&header->req_tail)->load(std::memory_order_relaxed);
  out[4] = pingoo_ring_now_ms();
}

void pingoo_ring_set_posted_floor(void* mem, uint64_t ticket) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  // CAS-max: batches complete FIFO on one drain thread today, but a
  // monotonic floor must survive any future completion reordering.
  auto* a = as_atomic(&header->posted_floor);
  uint64_t cur = a->load(std::memory_order_relaxed);
  while (ticket > cur &&
         !a->compare_exchange_weak(cur, ticket, std::memory_order_release)) {
  }
}

int pingoo_ring_reclaim_request(void* mem, uint64_t ticket,
                                PingooRequestSlot* out) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  uint32_t cap = header->capacity;
  Layout l = layout(mem, cap);
  PingooRequestSlot* slot = &l.req[ticket & (cap - 1)];
  uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
  if (seq == ticket + 1) {
    // The dead consumer CASed req_tail past this position but died
    // before releasing the slot seq: the bytes are intact, and nothing
    // else will ever touch this slot (a producer needs seq == ticket +
    // cap) — copy, then release, or the ring wedges here forever on
    // wraparound.
    std::memcpy(out, slot, sizeof(PingooRequestSlot));
    as_atomic(&slot->seq)->store(ticket + cap, std::memory_order_release);
    tel_add(&header->telemetry.dequeued, 1);
    return 0;
  }
  if (seq == ticket + cap) {
    // Cleanly consumed and released. The bytes survive until a producer
    // claims position ticket+cap, so guard the copy seqlock-style: the
    // producer CASes req_head past ticket+cap BEFORE writing, so an
    // unmoved head after the copy proves the bytes were stable.
    uint64_t head =
        as_atomic(&header->req_head)->load(std::memory_order_acquire);
    if (head <= ticket + cap) {
      std::memcpy(out, slot, sizeof(PingooRequestSlot));
      std::atomic_thread_fence(std::memory_order_acquire);
      uint64_t head2 =
          as_atomic(&header->req_head)->load(std::memory_order_acquire);
      uint64_t seq2 = as_atomic(&slot->seq)->load(std::memory_order_acquire);
      if (head2 <= ticket + cap && seq2 == ticket + cap &&
          out->ticket == ticket) {
        return 0;
      }
    }
  }
  return -1;  // bytes gone (slot reused): the caller fail-opens
}

int pingoo_ring_poll_verdict(void* mem, uint64_t* ticket_out,
                             uint8_t* action_out, float* score_out) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  uint32_t cap = header->capacity;
  Layout l = layout(mem, cap);
  auto* tail = as_atomic(&header->ver_tail);

  for (;;) {
    uint64_t pos = tail->load(std::memory_order_relaxed);
    PingooVerdictSlot* slot = &l.ver[pos & (cap - 1)];
    uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
    intptr_t diff =
        static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
    if (diff == 0) {
      if (tail->compare_exchange_weak(pos, pos + 1,
                                      std::memory_order_relaxed)) {
        *ticket_out = slot->ticket;
        *action_out = slot->action;
        *score_out = slot->bot_score;
        as_atomic(&slot->seq)->store(pos + cap, std::memory_order_release);
        return 0;
      }
    } else {
      return -1;  // empty
    }
  }
}

// -- Body-window ring (v6, ISSUE 13) -----------------------------------------

int pingoo_ring_enqueue_body(void* mem, uint64_t flow, uint32_t win_seq,
                             uint64_t total_len, const char* data,
                             uint32_t len, uint8_t flags) {
  if (len > PINGOO_BODY_WINDOW_CAP) return -2;
  auto* header = static_cast<PingooRingHeader*>(mem);
  Layout l = layout(mem, header->capacity);
  auto* head = as_atomic(&header->body_head);
  const uint32_t bcap = PINGOO_BODY_SLOTS;

  uint64_t pos = head->load(std::memory_order_relaxed);
  for (;;) {
    PingooBodySlot* slot = &l.body[pos & (bcap - 1)];
    uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
    intptr_t diff = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
    if (diff == 0) {
      if (head->compare_exchange_weak(pos, pos + 1,
                                      std::memory_order_relaxed)) {
        slot->flow = flow;
        slot->win_seq = win_seq;
        slot->win_len = len;
        slot->total_len = total_len;
        slot->flags = flags;
        if (len) std::memcpy(slot->data, data, len);
        as_atomic(&slot->seq)->store(pos + 1, std::memory_order_release);
        return 0;
      }
    } else if (diff < 0) {
      return -1;  // full: producer fails the flow open to metadata-only
    } else {
      pos = head->load(std::memory_order_relaxed);
    }
  }
}

uint32_t pingoo_ring_dequeue_bodies(void* mem, PingooBodySlot* out,
                                    uint32_t max) {
  auto* header = static_cast<PingooRingHeader*>(mem);
  Layout l = layout(mem, header->capacity);
  auto* tail = as_atomic(&header->body_tail);
  const uint32_t bcap = PINGOO_BODY_SLOTS;

  uint32_t count = 0;
  while (count < max) {
    uint64_t pos = tail->load(std::memory_order_relaxed);
    PingooBodySlot* slot = &l.body[pos & (bcap - 1)];
    uint64_t seq = as_atomic(&slot->seq)->load(std::memory_order_acquire);
    intptr_t diff =
        static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
    if (diff == 0) {
      if (tail->compare_exchange_weak(pos, pos + 1,
                                      std::memory_order_relaxed)) {
        std::memcpy(&out[count], slot, sizeof(PingooBodySlot));
        as_atomic(&slot->seq)->store(pos + bcap, std::memory_order_release);
        ++count;
      }
    } else {
      break;  // empty
    }
  }
  return count;
}

}  // extern "C"
