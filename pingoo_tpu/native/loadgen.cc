// Native load generator / data-plane stand-in for the verdict ring.
//
// Role of pong/pong.rs in the reference ("a Simple HTTP server to test
// Pingoo's capabilities") but for the ring transport: produce synthetic
// request tuples at full speed, await verdicts, report throughput +
// latency. This is the C++ side of the host<->sidecar seam until the
// native listener lands; it doubles as the transport benchmark.
//
// Usage: loadgen <ring-file> <num-requests> [attack_permille]
// Writes one JSON line with results to stdout; exits nonzero on error.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "pingoo_ring.h"

namespace {

struct Sample {
  const char* method;
  const char* path;
  const char* url;
  const char* ua;
  bool attack;
};

const Sample kClean[] = {
    {"GET", "/", "/", "Mozilla/5.0 (X11; Linux x86_64)", false},
    {"GET", "/index.html", "/index.html?utm=1", "Mozilla/5.0 (Macintosh)",
     false},
    {"GET", "/api/v1/users", "/api/v1/users?page=2", "Mozilla/5.0 (iPhone)",
     false},
    {"POST", "/api/v1/orders", "/api/v1/orders", "Mozilla/5.0 (Windows NT)",
     false},
};
const Sample kAttack[] = {
    {"GET", "/.env", "/.env", "Mozilla/5.0 (X11)", true},
    {"GET", "/search", "/search?q=1%27%20UNION%20SELECT%20pass", "sqlmap/1.8",
     true},
    {"GET", "/dl", "/dl?f=../../../etc/passwd", "Mozilla/5.0", true},
};

uint64_t splitmix(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <ring-file> <num-requests> [permille]\n",
                 argv[0]);
    return 2;
  }
  const char* ring_path = argv[1];
  long total = std::strtol(argv[2], nullptr, 10);
  long attack_permille = argc > 3 ? std::strtol(argv[3], nullptr, 10) : 50;

  int fd = open(ring_path, O_RDWR);
  if (fd < 0) {
    std::perror("open ring");
    return 1;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    std::perror("fstat");
    return 1;
  }
  void* mem =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    std::perror("mmap");
    return 1;
  }
  uint32_t capacity = 0;
  if (pingoo_ring_attach(mem, &capacity) != 0) {
    std::fprintf(stderr, "ring attach failed\n");
    return 1;
  }

  uint64_t rng = 0x1234;
  long sent = 0, received = 0, blocked = 0, captcha = 0;
  auto t0 = std::chrono::steady_clock::now();

  std::vector<uint64_t> outstanding;
  outstanding.reserve(1024);
  while (received < total) {
    // Fill the ring as far as possible.
    while (sent < total) {
      bool attack = (splitmix(&rng) % 1000) < (uint64_t)attack_permille;
      const Sample& s = attack ? kAttack[splitmix(&rng) % 3]
                               : kClean[splitmix(&rng) % 4];
      uint8_t ip[16] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0xff, 0xff, 0, 0, 0, 0};
      uint32_t v4 = static_cast<uint32_t>(splitmix(&rng));
      std::memcpy(ip + 12, &v4, 4);
      char country[2] = {'U', 'S'};
      uint64_t ticket = pingoo_ring_enqueue_request(
          mem, s.method, std::strlen(s.method), "bench.local", 11, s.path,
          std::strlen(s.path), s.url, std::strlen(s.url), s.ua,
          std::strlen(s.ua), ip, 40000, 15169, country);
      if (ticket == UINT64_MAX) break;  // ring full
      ++sent;
    }
    // Drain verdicts.
    uint64_t ticket;
    uint8_t action;
    float score;
    while (pingoo_ring_poll_verdict(mem, &ticket, &action, &score) == 0) {
      ++received;
      // Bits 0-1 = unverified-client action; bit 2 = verified-block
      // lane (native_ring.py RingSidecar).
      if ((action & 3) == 1) ++blocked;
      if ((action & 3) == 2) ++captcha;
    }
  }
  auto t1 = std::chrono::steady_clock::now();
  double secs = std::chrono::duration<double>(t1 - t0).count();
  std::printf(
      "{\"sent\": %ld, \"received\": %ld, \"blocked\": %ld, "
      "\"captcha\": %ld, \"seconds\": %.3f, \"req_per_s\": %.0f}\n",
      sent, received, blocked, captcha, secs, received / secs);
  munmap(mem, st.st_size);
  close(fd);
  return 0;
}
