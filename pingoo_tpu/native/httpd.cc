// Native HTTP(S) data plane: epoll listener -> verdict ring -> action.
//
// The C++ half of the architecture split (SURVEY.md §7 item 1: "Host
// data plane (C++): listeners ... proxying"): a non-blocking epoll event
// loop accepts plain-TCP or TLS connections, parses HTTP/1.1 requests,
// enqueues each request's tuple into the shared-memory verdict ring
// (pingoo_ring.h), and on the TPU sidecar's verdict either serves
// 403 / a captcha redirect or proxies the request upstream.
//
// Per-REQUEST policy (reference hyper serves each request through the
// rules loop, http_listener.rs:133-274): connections are keep-alive and
// every request on them is framed (Content-Length / chunked), verdicted
// through the ring, and proxied on its own upstream connection with
// `connection: close` injected — bytes beyond the current request's
// body are never forwarded, so pipelining cannot bypass the WAF.
//
// Captcha gate (reference http_listener.rs:200-236): requests under
// /__pingoo/captcha are proxied to the control-plane upstream (the
// Python listener serving the PoW API); the __pingoo_captcha_verified
// cookie is verified HERE (Ed25519 JWT against the shared JWKS file,
// claims exp/iss/challenge_passed/client_id — client_id =
// b64url(SHA256(ip||ua||host)), captcha.rs:409-421). The verdict byte's
// two lanes (bits 0-1 unverified action, bit 2 verified-block,
// native_ring.py) are applied according to the client's verified state —
// a verified client skips Captcha actions but still blocks on Block.
//
// TLS (reference listeners/mod.rs:112-154 LazyConfigAcceptor): a
// client-hello callback inspects SNI + ALPN before any config is
// chosen; `acme-tls/1` handshakes get the ephemeral tls-alpn-01
// challenge certificate for the requested domain (RFC 8737; reference
// acme.rs:180-242) and close after the handshake; everything else gets
// the SNI-matched certificate (exact, then wildcard, then default).
// Certificates live as <name>.pem/<name>.key pairs in --tls-dir
// ("default" = fallback; "_.example.com" = *.example.com); challenge
// certs as <domain>.pem/.key in --alpn-dir, re-read per handshake
// because they are ephemeral.
//
// Event-loop invariants:
//   * epoll data carries SockRef (conn, side); closes are deferred to
//     the end of the batch so stale events for a reused fd can never
//     touch a fresh connection.
//   * SIGPIPE is ignored; short writes buffer and arm EPOLLOUT.
//   * A sidecar stall fails OPEN three times over: ring-full -> proxy
//     without a verdict immediately; a verdict never arriving -> the
//     per-iteration deadline sweep fails the request open after
//     kVerdictTimeoutMs (mirrors the reference's rule-error fail-open,
//     pingoo/rules.rs:41-44); a stale heartbeat (older than
//     kSidecarTimeoutMs, ring header v5) -> degraded mode: every
//     awaiting ticket fails open at once and new requests bypass the
//     ring until a fresh heartbeat lifts it (docs/RESILIENCE.md).
//   * Idle sweeps cover every state: head/handshake after
//     kIdleTimeoutS, awaiting-verdict via sweep_verdict_deadlines()
//     (fail open), proxying after kProxyIdleTimeoutS.
//
// Usage: httpd <listen-port> <ring-file> <upstream-host> <upstream-port>
//          [--captcha-upstream host:port] [--jwks path]
//          [--tls-dir dir] [--alpn-dir dir]
// TLS is enabled iff --tls-dir is given.

#include <arpa/inet.h>
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

#include "nghttp2_shim.h"
#include "up_h2_link.h"
#include "ossl_shim.h"
#include "pingoo_ring.h"

namespace {

const char kH2Preface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kH2PrefaceLen = 24;

constexpr size_t kMaxHead = 32 * 1024;
constexpr size_t kMaxBufferedDefault = 1 << 20;  // per-direction backlog

// Request-head byte cap, env-tunable (PINGOO_MAX_HEADER_BYTES) and
// shared with the Python listener plane (host/httpd.py reads the same
// knob) so oversized-head handling is identical on both: exceed it and
// the request gets 431, not a parser-dependent mix of 400/close
// (ISSUE 11 fuzzer parity). Response heads from upstreams keep the
// compile-time kMaxHead — that bound protects us from the upstream,
// not the client, and is not part of the request-parse surface.
inline size_t parse_max_req_head() {
  const char* e = getenv("PINGOO_MAX_HEADER_BYTES");
  if (e == nullptr || *e == '\0') return kMaxHead;
  long n = atol(e);
  if (n < 256) {
    fprintf(stderr,
            "PINGOO_MAX_HEADER_BYTES=%s out of range (< 256); using %zu\n",
            e, kMaxHead);
    return kMaxHead;
  }
  return static_cast<size_t>(n);
}
const size_t kMaxReqHead = parse_max_req_head();

// Request-body byte cap (PINGOO_MAX_BODY_BYTES, default 16 MiB — the
// Python listener's historical MAX_BODY_BYTES). A Content-Length
// beyond it is refused up front with 413. Chunked uploads stream
// through under the proxy backpressure gates instead of buffering, so
// they are bounded by PINGOO_MAX_BUFFER rather than this knob — a
// documented delta vs the Python plane, which buffers the whole body
// (docs/FUZZING.md known-deltas).
inline long long parse_max_body_bytes() {
  const char* e = getenv("PINGOO_MAX_BODY_BYTES");
  long long def = 16LL * 1024 * 1024;
  if (e == nullptr || *e == '\0') return def;
  long long n = atoll(e);
  if (n < 1) {
    fprintf(stderr, "PINGOO_MAX_BODY_BYTES=%s out of range (< 1); using %lld\n",
            e, def);
    return def;
  }
  return n;
}
const long long kMaxBodyBytes = parse_max_body_bytes();

// Streaming request-body inspection (ISSUE 13, docs/BODY_STREAMING.md):
// PINGOO_BODY_INSPECT=on streams h1 request bodies through the ring's
// body slots so the sidecar can scan payloads across chunk boundaries;
// the request holds until the body verdict merges with the metadata
// verdict. off (the default) is the bit-exact status quo. Every error
// path fails OPEN to metadata-only, never closed.
inline bool parse_body_inspect() {
  const char* e = getenv("PINGOO_BODY_INSPECT");
  return e != nullptr && (strcmp(e, "on") == 0 || strcmp(e, "1") == 0);
}
const bool kBodyInspect = parse_body_inspect();

// Buffering cap, env-tunable (PINGOO_MAX_BUFFER) so tests can exercise
// the backpressure/re-pump paths without multi-MB payloads. Resolved
// once at process start; out-of-range values warn and fall back.
inline size_t parse_max_buffered() {
  const char* e = getenv("PINGOO_MAX_BUFFER");
  if (e == nullptr || *e == '\0') return kMaxBufferedDefault;
  long n = atol(e);
  if (n < 4096) {
    fprintf(stderr, "PINGOO_MAX_BUFFER=%s out of range (< 4096); using %zu\n",
            e, kMaxBufferedDefault);
    return kMaxBufferedDefault;
  }
  return static_cast<size_t>(n);
}
const size_t kMaxBuffered = parse_max_buffered();
constexpr time_t kIdleTimeoutS = 30;
constexpr time_t kTunnelIdleS = 300;     // upgraded (WebSocket) tunnels

// Per-request verdict fail-open deadline (ISSUE 10). Defaulted from
// the scheduler's deadline budget — 1500 x PINGOO_DEADLINE_MS, which
// keeps the historical 3 s at the 2 ms default (the first sidecar
// batch can sit behind a multi-second XLA compile) while configuring
// both knobs in one place. PINGOO_VERDICT_TIMEOUT_MS overrides it
// directly; out-of-range values warn and fall back.
inline uint64_t parse_verdict_timeout_ms() {
  double deadline_ms = 2.0;
  if (const char* d = getenv("PINGOO_DEADLINE_MS")) {
    double v = atof(d);
    if (v > 0) deadline_ms = v;
  }
  uint64_t def = static_cast<uint64_t>(deadline_ms * 1500.0);
  if (def == 0) def = 1;
  const char* e = getenv("PINGOO_VERDICT_TIMEOUT_MS");
  if (e == nullptr || *e == '\0') return def;
  long n = atol(e);
  if (n <= 0) {
    fprintf(stderr,
            "PINGOO_VERDICT_TIMEOUT_MS=%s out of range (<= 0); using %llu\n",
            e, static_cast<unsigned long long>(def));
    return def;
  }
  return static_cast<uint64_t>(n);
}
const uint64_t kVerdictTimeoutMs = parse_verdict_timeout_ms();

// Sidecar liveness window (ISSUE 10, docs/RESILIENCE.md): with a ring
// attached, a heartbeat older than this flips the plane into the
// degraded fast-path (immediate fail-open, no per-request stall) until
// a fresh heartbeat arrives. 0 disables detection.
inline uint64_t parse_sidecar_timeout_ms() {
  const char* e = getenv("PINGOO_SIDECAR_TIMEOUT_MS");
  if (e == nullptr || *e == '\0') return 500;
  long n = atol(e);
  return n > 0 ? static_cast<uint64_t>(n) : 0;
}
const uint64_t kSidecarTimeoutMs = parse_sidecar_timeout_ms();
// TCP proxy mode (reference tcp_proxy_service.rs:30-84): 3 connect
// tries, 3 s timeout each. The reference sleeps 5 ms between tries;
// this plane re-dials immediately on a failed connect (a fresh random
// upstream each time), which only tightens the retry window.
constexpr int kTcpConnectRetriesDefault = 3;
constexpr time_t kTcpConnectTimeoutS = 3;

inline int tcp_connect_retries() {
  static int v = [] {
    const char* e = getenv("PINGOO_TCP_RETRIES");
    int n = e != nullptr ? atoi(e) : 0;
    return n > 0 ? n : kTcpConnectRetriesDefault;
  }();
  return v;
}
constexpr size_t kMaxReplay = 64 * 1024;  // pooled-retry replay budget
// nghttp2 data-provider sentinel: no DATA available now; the session
// parks the stream until nghttp2_session_resume_data.
constexpr ssize_t kNghttp2ErrDeferred = -508;  // NGHTTP2_ERR_DEFERRED
// Streamed h2 responses buffer at most this much de-framed body before
// the upstream read side is paused (per stream).
constexpr size_t kH2PendingCap = 256 * 1024;
constexpr int kH2MaxStreamUpstreams = 32;  // concurrent upstreams per conn
// Connection-level receive window: 8x the (default 64KB) per-stream
// window, so one debt-parked upload stream cannot exhaust the window
// shared by its siblings (see start_h2).
constexpr int32_t kH2ConnRecvWindow = 8 * 65535;
constexpr time_t kProxyIdleTimeoutS = 60;
constexpr int kMaxRequestsPerConn = 1000;

// ---------------------------------------------------------------------------
// small utils

int b64url_val(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '-') return 62;
  if (c == '_') return 63;
  return -1;
}

bool b64url_decode(const std::string& in, std::string* out) {
  out->clear();
  int bits = 0, acc = 0;
  for (char c : in) {
    if (c == '=') break;
    int v = b64url_val(c);
    if (v < 0) return false;
    acc = (acc << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back(static_cast<char>((acc >> bits) & 0xFF));
    }
  }
  return true;
}

std::string b64url_encode(const unsigned char* data, size_t len) {
  static const char tab[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";
  std::string out;
  size_t i = 0;
  while (i + 3 <= len) {
    unsigned v = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2];
    out += tab[(v >> 18) & 63];
    out += tab[(v >> 12) & 63];
    out += tab[(v >> 6) & 63];
    out += tab[v & 63];
    i += 3;
  }
  if (len - i == 1) {
    unsigned v = data[i] << 16;
    out += tab[(v >> 18) & 63];
    out += tab[(v >> 12) & 63];
  } else if (len - i == 2) {
    unsigned v = (data[i] << 16) | (data[i + 1] << 8);
    out += tab[(v >> 18) & 63];
    out += tab[(v >> 12) & 63];
    out += tab[(v >> 6) & 63];
  }
  return out;
}

std::string lower(std::string s) {
  for (auto& ch : s) ch = static_cast<char>(tolower(ch));
  return s;
}

std::string trim(const std::string& s) {
  size_t a = 0, b = s.size();
  while (a < b && (s[a] == ' ' || s[a] == '\t')) ++a;
  while (b > a && (s[b - 1] == ' ' || s[b - 1] == '\t' || s[b - 1] == '\r'))
    --b;
  return s.substr(a, b - a);
}

// Flat-JSON string field extraction ("key":"value"). Sufficient for the
// JWT payloads and JWKS files this framework itself writes (no escapes
// in base64url/id values; a token with escapes simply fails the gate,
// which fails SAFE — the client is treated as unverified).
bool json_str(const std::string& j, const std::string& key, std::string* out) {
  std::string pat = "\"" + key + "\"";
  size_t p = j.find(pat);
  if (p == std::string::npos) return false;
  p = j.find(':', p + pat.size());
  if (p == std::string::npos) return false;
  ++p;
  while (p < j.size() && (j[p] == ' ')) ++p;
  if (p >= j.size() || j[p] != '"') return false;
  size_t e = j.find('"', p + 1);
  if (e == std::string::npos) return false;
  *out = j.substr(p + 1, e - p - 1);
  return out->find('\\') == std::string::npos;
}

bool json_num(const std::string& j, const std::string& key, long long* out) {
  std::string pat = "\"" + key + "\"";
  size_t p = j.find(pat);
  if (p == std::string::npos) return false;
  p = j.find(':', p + pat.size());
  if (p == std::string::npos) return false;
  ++p;
  while (p < j.size() && j[p] == ' ') ++p;
  char* end = nullptr;
  long long v = strtoll(j.c_str() + p, &end, 10);
  if (end == j.c_str() + p) return false;
  *out = v;
  return true;
}

bool json_true(const std::string& j, const std::string& key) {
  std::string pat = "\"" + key + "\"";
  size_t p = j.find(pat);
  if (p == std::string::npos) return false;
  p = j.find(':', p + pat.size());
  if (p == std::string::npos) return false;
  ++p;
  while (p < j.size() && j[p] == ' ') ++p;
  return j.compare(p, 4, "true") == 0;
}

// ---------------------------------------------------------------------------
// captcha-verified gate: Ed25519 JWT against the shared JWKS file

class CaptchaGate {
 public:
  // Loads the first EdDSA key from the JWKS file (written by the Python
  // CaptchaManager, host/captcha.py). Returns false if unavailable —
  // the gate then treats every client as unverified (fail safe).
  bool load(const char* jwks_path) {
    path_ = jwks_path;
    return reload();
  }

  bool reload() {
    struct stat st;
    if (stat(path_.c_str(), &st) != 0) return pkey_ != nullptr;
    if (pkey_ != nullptr && st.st_mtime == loaded_mtime_) return true;
    FILE* f = fopen(path_.c_str(), "r");
    if (!f) return pkey_ != nullptr;
    std::string text;
    char buf[4096];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), f)) > 0) text.append(buf, n);
    fclose(f);
    std::string x;
    std::string raw;
    if (!json_str(text, "x", &x) || !b64url_decode(x, &raw) ||
        raw.size() != 32)
      return pkey_ != nullptr;
    EVP_PKEY* pk = EVP_PKEY_new_raw_public_key(
        EVP_PKEY_ED25519, nullptr,
        reinterpret_cast<const unsigned char*>(raw.data()), raw.size());
    if (pk == nullptr) return pkey_ != nullptr;
    if (pkey_ != nullptr) EVP_PKEY_free(pkey_);
    pkey_ = pk;
    loaded_mtime_ = st.st_mtime;
    return true;
  }

  // Re-stat the JWKS periodically so a control plane that starts (or
  // rotates keys) AFTER this process does not leave every client
  // permanently unverified — the same freshness discipline as the
  // per-handshake challenge-cert reads.
  void maybe_reload(time_t now) {
    if (path_.empty() || now - last_check_ < 5) return;
    last_check_ = now;
    reload();
  }

  bool available() const { return pkey_ != nullptr; }

  // Mirrors host/jwt.py parse_and_verify + captcha.py is_verified:
  // EdDSA alg, valid signature, exp within 5s drift, iss == "pingoo",
  // challenge_passed == true, client_id constant-time-equals ours.
  bool verify(const std::string& token, const std::string& client_id,
              time_t now) const {
    if (!pkey_) return false;
    size_t d1 = token.find('.');
    if (d1 == std::string::npos) return false;
    size_t d2 = token.find('.', d1 + 1);
    if (d2 == std::string::npos || token.find('.', d2 + 1) != std::string::npos)
      return false;
    std::string header_json, payload_json, sig;
    if (!b64url_decode(token.substr(0, d1), &header_json)) return false;
    if (!b64url_decode(token.substr(d1 + 1, d2 - d1 - 1), &payload_json))
      return false;
    if (!b64url_decode(token.substr(d2 + 1), &sig) || sig.size() != 64)
      return false;
    std::string alg;
    if (!json_str(header_json, "alg", &alg) || alg != "EdDSA") return false;

    EVP_MD_CTX* ctx = EVP_MD_CTX_new();
    if (!ctx) return false;
    bool ok = false;
    if (EVP_DigestVerifyInit(ctx, nullptr, nullptr, nullptr, pkey_) == 1) {
      const std::string signed_part = token.substr(0, d2);
      ok = EVP_DigestVerify(
               ctx, reinterpret_cast<const unsigned char*>(sig.data()),
               sig.size(),
               reinterpret_cast<const unsigned char*>(signed_part.data()),
               signed_part.size()) == 1;
    }
    EVP_MD_CTX_free(ctx);
    if (!ok) return false;

    // exp is REQUIRED here (the CaptchaManager always sets it; a signed
    // token without one would otherwise never expire on this plane).
    long long exp = 0;
    if (!json_num(payload_json, "exp", &exp) || exp + 5 < now) return false;
    long long nbf = 0;
    if (json_num(payload_json, "nbf", &nbf) && nbf - 5 > now) return false;
    std::string iss;
    if (!json_str(payload_json, "iss", &iss) || iss != "pingoo") return false;
    if (!json_true(payload_json, "challenge_passed")) return false;
    std::string cid;
    if (!json_str(payload_json, "client_id", &cid)) return false;
    if (cid.size() != client_id.size()) return false;
    return CRYPTO_memcmp(cid.data(), client_id.data(), cid.size()) == 0;
  }

 private:
  std::string path_;
  EVP_PKEY* pkey_ = nullptr;
  time_t loaded_mtime_ = 0;
  time_t last_check_ = 0;
};

std::string captcha_client_id(const std::string& ip, const std::string& ua,
                              const std::string& host) {
  std::string input = ip + ua + host;
  unsigned char md[32];
  unsigned int mdlen = 0;
  EVP_Digest(input.data(), input.size(), md, &mdlen, EVP_sha256(), nullptr);
  return b64url_encode(md, mdlen);
}

// ---------------------------------------------------------------------------
// TLS: cert store + client-hello SNI/ALPN inspection

struct TlsStore {
  SSL_CTX* fallback = nullptr;                       // "default" pair
  std::unordered_map<std::string, SSL_CTX*> exact;   // domain -> ctx
  std::unordered_map<std::string, SSL_CTX*> wildcard;  // parent -> ctx
  std::string alpn_dir;  // tls-alpn-01 challenge certs, may be empty

  SSL_CTX* match(const std::string& name) const {
    auto it = exact.find(name);
    if (it != exact.end()) return it->second;
    size_t dot = name.find('.');
    if (dot != std::string::npos) {
      auto w = wildcard.find(name.substr(dot + 1));
      if (w != wildcard.end()) return w->second;
    }
    return fallback;
  }
};

SSL_CTX* make_server_ctx(const std::string& cert, const std::string& key) {
  SSL_CTX* ctx = SSL_CTX_new(TLS_server_method());
  if (!ctx) return nullptr;
  // Partial-write + moving-buffer + auto-retry (SSL_CTRL_MODE): the
  // event loop retries writes from a std::string that may reallocate.
  SSL_CTX_ctrl(ctx, /*SSL_CTRL_MODE=*/33, 7, nullptr);
  SSL_CTX_set_min_proto_version_shim(ctx, TLS1_2_VERSION);
  if (SSL_CTX_use_certificate_chain_file(ctx, cert.c_str()) != 1 ||
      SSL_CTX_use_PrivateKey_file(ctx, key.c_str(), SSL_FILETYPE_PEM) != 1 ||
      SSL_CTX_check_private_key(ctx) != 1) {
    SSL_CTX_free(ctx);
    ERR_clear_error();
    return nullptr;
  }
  return ctx;
}

bool load_tls_store(const char* dir, TlsStore* store) {
  DIR* d = opendir(dir);
  if (!d) return false;
  dirent* ent;
  while ((ent = readdir(d)) != nullptr) {
    std::string fname = ent->d_name;
    if (fname.size() < 5 || fname.compare(fname.size() - 4, 4, ".pem") != 0)
      continue;
    std::string base = fname.substr(0, fname.size() - 4);
    std::string cert = std::string(dir) + "/" + fname;
    std::string key = std::string(dir) + "/" + base + ".key";
    SSL_CTX* ctx = make_server_ctx(cert, key);
    if (!ctx) continue;
    if (base == "default") {
      store->fallback = ctx;
    } else if (base.size() > 2 && base[0] == '_' && base[1] == '.') {
      store->wildcard[base.substr(2)] = ctx;
    } else {
      store->exact[base] = ctx;
    }
  }
  closedir(d);
  return store->fallback != nullptr || !store->exact.empty() ||
         !store->wildcard.empty();
}

// A hostname safe to use as a lookup key AND a file-name component
// (the tls-alpn-01 challenge path is built from it): DNS charset only,
// no dot-runs — rejects "../" traversal outright.
bool valid_sni_name(const std::string& s) {
  if (s.empty() || s.size() > 253 || s[0] == '.' || s[0] == '-') return false;
  char prev = 0;
  for (char ch : s) {
    bool ok = (ch >= 'a' && ch <= 'z') || (ch >= 'A' && ch <= 'Z') ||
              (ch >= '0' && ch <= '9') || ch == '.' || ch == '-';
    if (!ok) return false;
    if (ch == '.' && prev == '.') return false;
    prev = ch;
  }
  return true;
}

// Parse SNI host out of the raw server_name ClientHello extension.
std::string parse_sni_ext(const unsigned char* p, size_t len) {
  if (len < 5) return "";
  size_t list_len = (p[0] << 8) | p[1];
  if (list_len + 2 > len || p[2] != 0) return "";  // type 0 = host_name
  size_t name_len = (p[3] << 8) | p[4];
  if (5 + name_len > len) return "";
  std::string name(reinterpret_cast<const char*>(p + 5), name_len);
  return valid_sni_name(name) ? name : "";
}

bool alpn_ext_offers(const unsigned char* p, size_t len, const char* proto) {
  if (len < 2) return false;
  size_t list_len = (p[0] << 8) | p[1];
  size_t plen = strlen(proto);
  size_t i = 2;
  if (2 + list_len > len) return false;
  while (i < 2 + list_len) {
    size_t n = p[i];
    if (i + 1 + n > len) return false;
    if (n == plen && memcmp(p + i + 1, proto, n) == 0) return true;
    i += 1 + n;
  }
  return false;
}

// ---------------------------------------------------------------------------
// HTTP message framing

struct BodyFramer {
  enum Mode { kNone, kContentLength, kChunked, kUntilEof } mode = kNone;
  long long remaining = 0;  // kContentLength
  // chunked state
  enum CState { kSize, kData, kDataCrlf, kTrailer } cstate = kSize;
  std::string linebuf;
  bool done = false;
  bool bad = false;  // malformed framing: caller must refuse/close

  void reset_none() { *this = BodyFramer(); done = true; }
  void reset_cl(long long n) {
    *this = BodyFramer();
    mode = kContentLength;
    remaining = n;
    done = n == 0;
  }
  void reset_chunked() {
    *this = BodyFramer();
    mode = kChunked;
  }
  void reset_eof() {
    *this = BodyFramer();
    mode = kUntilEof;
  }

  // How many of data[0..len) belong to the current message. Sets done.
  // With `payload` set, the message's PAYLOAD bytes (de-chunked — no
  // chunk-size lines or trailers) are appended to it; the h2 bridge
  // re-frames upstream h1 responses and must not leak h1 framing.
  size_t consume(const char* data, size_t len, std::string* payload = nullptr) {
    if (done) return 0;
    switch (mode) {
      case kNone:
        done = true;
        return 0;
      case kUntilEof:
        if (payload) payload->append(data, len);
        return len;  // done only at EOF (caller decides)
      case kContentLength: {
        size_t take = static_cast<size_t>(remaining) < len
                          ? static_cast<size_t>(remaining)
                          : len;
        remaining -= static_cast<long long>(take);
        if (remaining == 0) done = true;
        if (payload) payload->append(data, take);
        return take;
      }
      case kChunked:
        return consume_chunked(data, len, payload);
    }
    return 0;
  }

  size_t consume_chunked(const char* data, size_t len,
                         std::string* payload = nullptr) {
    size_t used = 0;
    while (used < len && !done) {
      char c = data[used];
      switch (cstate) {
        case kSize:
          linebuf.push_back(c);
          ++used;
          if (linebuf.size() > 1024) {  // junk flood
            bad = true;
            done = true;
            return used;
          }
          if (linebuf.size() >= 2 &&
              linebuf.compare(linebuf.size() - 2, 2, "\r\n") == 0) {
            // Chunk size must be plain hex (extensions after ';' are
            // tolerated); a leading '-' or garbage would make
            // `remaining` negative and the cast in kData wrap to ~2^64.
            // Every byte of the size field before ';' (extension) or CRLF
            // must be hex — strtoll would silently stop at garbage like
            // "1x3" and desync framing against a strict upstream.
            size_t hex_len = 0;
            while (hex_len + 2 < linebuf.size()) {
              char hc = linebuf[hex_len];
              bool is_hex = (hc >= '0' && hc <= '9') ||
                            (hc >= 'a' && hc <= 'f') ||
                            (hc >= 'A' && hc <= 'F');
              if (!is_hex) break;
              ++hex_len;
            }
            // BWS after the size (before ';' or CRLF) is tolerated —
            // h11 accepts "3 \r\n"/"3\t\r\n" and the two planes must
            // frame identically (differential fuzzer, ISSUE 11).
            size_t bws_end = hex_len;
            while (bws_end + 2 < linebuf.size() &&
                   (linebuf[bws_end] == ' ' || linebuf[bws_end] == '\t'))
              ++bws_end;
            bool valid_size =
                hex_len > 0 &&
                (bws_end + 2 == linebuf.size() || linebuf[bws_end] == ';');
            long long sz = valid_size ? strtoll(linebuf.c_str(), nullptr, 16)
                                      : -1;
            linebuf.clear();
            if (!valid_size || sz < 0 || sz > (1LL << 40)) {
              bad = true;
              done = true;
              return used;
            }
            if (sz == 0) {
              cstate = kTrailer;
            } else {
              remaining = sz;
              cstate = kData;
            }
          }
          break;
        case kData: {
          size_t take = static_cast<size_t>(remaining) < (len - used)
                            ? static_cast<size_t>(remaining)
                            : (len - used);
          remaining -= static_cast<long long>(take);
          if (payload) payload->append(data + used, take);
          used += take;
          if (remaining == 0) cstate = kDataCrlf;
          break;
        }
        case kDataCrlf:
          linebuf.push_back(c);
          ++used;
          if (linebuf.size() == 2) {
            if (linebuf != "\r\n") {  // chunk data must end with exact CRLF
              bad = true;
              done = true;
              linebuf.clear();
              return used;
            }
            linebuf.clear();
            cstate = kSize;
          }
          break;
        case kTrailer:
          linebuf.push_back(c);
          ++used;
          if (linebuf.size() >= 2 &&
              linebuf.compare(linebuf.size() - 2, 2, "\r\n") == 0) {
            if (linebuf == "\r\n") {
              done = true;  // empty line ends trailers
            }
            linebuf.clear();
          }
          break;
      }
    }
    return used;
  }
};

struct Parsed {
  std::string method, target, path, host, user_agent;
  std::string accept;           // Accept header (metrics content nego)
  std::string verified_cookie;  // __pingoo_captcha_verified value
  long long content_length = 0;
  bool has_content_length = false;
  bool bad_content_length = false;  // duplicate/garbage Content-Length
  bool obs_fold = false;  // obsolete line folding seen (RFC 7230 §3.2.4)
  bool bad_header = false;  // colonless line / ws before colon / bare LF
  bool has_host = false;    // first Host seen; a repeat sets bad_header
  bool chunked = false;
  bool has_transfer_encoding = false;
  bool keep_alive = true;  // HTTP/1.1 default
  bool conn_upgrade = false;    // Connection header listed "upgrade"
  std::string upgrade_value;    // Upgrade header token (e.g. websocket)
  bool ok = false;
  std::string raw_head;  // original head (h1; empty for h2 streams)

  bool is_upgrade() const {
    return conn_upgrade && !upgrade_value.empty();
  }
  // h2 streams carry their full header list here instead of raw_head.
  std::vector<std::pair<std::string, std::string>> h2_headers;
};

// A concrete upstream address plus its transport policy: a `tls`
// target gets a verified OpenSSL client connection (SNI + hostname
// check against `sni`), matching the reference's pooled hyper-rustls
// client (http_proxy_service.rs:54-71).
struct UpTarget {
  sockaddr_in sa{};
  bool tls = false;
  bool h2 = false;        // cleartext prior-knowledge h2 upstream (h2://)
  bool internal = false;  // the loopback control plane: identity headers
                          // (x-pingoo-internal) may be sent to it
  std::string sni;
};

// One multiplexed HTTP/2 request in flight on a connection.
struct SockRef;

struct H2Stream {
  Parsed p;
  std::string body;
  bool complete = false;
  // Per-stream proxy state: streams are serviced CONCURRENTLY, each
  // with its own upstream connection and de-framed response stream
  // (reference: hyper multiplexes + streams bodies, http_listener.rs:276).
  int up_fd = -1;
  bool up_connected = false;
  bool up_eof = false;
  bool up_trunc = false;        // upstream ended with an ERROR, not clean EOF
  UpH2Link* up_h2 = nullptr;    // non-null: upstream link speaks h2
  std::string up_head;          // synthesized h1 head (until ALPN decides)
  std::string up_body;          // request-body bytes pending the h2 link
  bool up_proto_pending = false;
  // Streamed request bodies (reference: hyper streams them): the
  // stream dispatches at END_HEADERS; DATA arriving after dispatch
  // forwards straight to the upstream instead of buffering in `body`.
  bool ready_queued = false;    // pushed to h2_ready once
  bool up_dispatched = false;   // upstream head synthesized
  bool up_body_chunked = false;  // forwarding with h1 chunked framing
  uint64_t window_debt = 0;     // received-but-unconsumed body bytes
                                // (released as the upstream drains)
  bool up_pooled = false;
  uint64_t up_key = 0;
  UpTarget up_target{};
  SSL* up_ssl = nullptr;        // non-null on TLS upstream links
  bool up_tcp_ok = false;       // TCP connect completed
  bool up_tls_hs = false;       // client handshake in progress
  bool up_hs_want_write = false;  // handshake blocked on EPOLLOUT
  bool up_rd_want_write = false;  // SSL_read wants the write event
  bool up_wr_want_read = false;   // SSL_write wants the read event
  std::string upbuf;       // request bytes awaiting the upstream socket
  std::string up_replay;   // pooled-retry replay copy
  std::string resp_head_buf;
  bool resp_head_done = false;
  BodyFramer resp_body;
  bool up_keep = false;
  bool up_junk = false;
  bool submitted = false;  // response HEADERS handed to nghttp2
  std::string pending;     // de-framed DATA bytes awaiting the session
  bool data_eof = false;   // response body complete
  bool verified = false;   // captcha cookie verified for this stream
  bool up_queued = false;  // verdicted; waiting for an upstream slot
  uint64_t ticket = UINT64_MAX;
  uint64_t enq_ms = 0;
  time_t verdict_at = 0;
  SockRef* up_ref = nullptr;  // heap ref handed to epoll (deferred free)
};

std::string strip_host_port(const std::string& value);
std::string extract_verified_cookie(const std::string& value);

// Parse a request head (request line + headers).
Parsed parse_head(const std::string& head) {
  Parsed p;
  // A bare LF (not preceded by CR) inside the head is invisible to the
  // CRLF line scan below: "ua\nx-smuggle: 1" would read as ONE header
  // value here while an LF-tolerant parser (h11 accepts bare-LF line
  // endings at the transport layer) sees TWO lines — exactly the
  // per-hop disagreement request smuggling needs. Reject the head.
  for (size_t i = 0; i < head.size(); ++i)
    if (head[i] == '\n' && (i == 0 || head[i - 1] != '\r'))
      p.bad_header = true;
  size_t line_end = head.find("\r\n");
  if (line_end == std::string::npos) return p;
  const std::string line = head.substr(0, line_end);
  size_t sp1 = line.find(' ');
  size_t sp2 = line.rfind(' ');
  if (sp1 == std::string::npos || sp2 == sp1) return p;
  p.method = line.substr(0, sp1);
  p.target = line.substr(sp1 + 1, sp2 - sp1 - 1);
  if (p.method.empty() || p.target.empty()) return p;
  if (line.compare(sp2 + 1, 8, "HTTP/1.1") == 0) {
    p.keep_alive = true;
  } else if (line.compare(sp2 + 1, 8, "HTTP/1.0") == 0) {
    p.keep_alive = false;
  } else {
    return p;
  }
  size_t q = p.target.find('?');
  p.path = q == std::string::npos ? p.target : p.target.substr(0, q);

  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos || eol == pos) break;
    if (head[pos] == ' ' || head[pos] == '\t') {
      // Obsolete line folding (RFC 7230 §3.2.4). Previously skipped
      // silently — but the Python plane's h11 parser REJECTS folds, so
      // a folded Transfer-Encoding read one way by this parser and
      // another by anything downstream is a smuggling vector the
      // differential fuzzer flags (ISSUE 11). Reject at admission.
      p.obs_fold = true;
      pos = eol + 2;
      continue;
    }
    size_t colon = head.find(':', pos);
    if (colon == std::string::npos || colon >= eol) {
      // A field line without a colon is not skippable noise: a parser
      // that drops it and one that rejects the message (h11 does)
      // disagree about every header that follows (RFC 9112 §2.2).
      p.bad_header = true;
      pos = eol + 2;
      continue;
    }
    {
      // RFC 7230 §3.2.4: whitespace between field-name and ":" MUST be
      // rejected — "Host : x" is a smuggling classic (one hop reads a
      // Host header, the next reads none).
      char last = colon > pos ? head[colon - 1] : '\0';
      if (last == ' ' || last == '\t') p.bad_header = true;
      std::string name = lower(head.substr(pos, colon - pos));
      std::string value = trim(head.substr(colon + 1, eol - colon - 1));
      if (name == "host") {
        // RFC 9112 §3.2: more than one Host is a MUST-reject (h11
        // refuses too). First-wins here + last-wins upstream would
        // route and verdict on different vhosts.
        if (p.has_host) p.bad_header = true;
        p.has_host = true;
        p.host = strip_host_port(value);
      } else if (name == "user-agent") {
        p.user_agent = value;
      } else if (name == "accept") {
        p.accept = lower(value);
      } else if (name == "content-length") {
        // RFC 7230 §3.3.3: reject non-numeric values and ANY repeat —
        // even value-identical duplicates (h11 refuses them too, and a
        // first-wins upstream may not treat them as identical after
        // its own normalization). Silent last-wins framing would
        // desync the proxy from the upstream (request smuggling).
        bool numeric = !value.empty();
        for (char ch : value)
          if (ch < '0' || ch > '9') numeric = false;
        long long v = numeric ? strtoll(value.c_str(), nullptr, 10) : -1;
        if (!numeric || v < 0 || p.has_content_length) {
          p.bad_content_length = true;
        } else {
          p.content_length = v;
          p.has_content_length = true;
        }
      } else if (name == "transfer-encoding") {
        p.has_transfer_encoding = true;
        if (lower(value).find("chunked") != std::string::npos)
          p.chunked = true;
      } else if (name == "connection") {
        std::string v = lower(value);
        if (v.find("close") != std::string::npos) p.keep_alive = false;
        if (v.find("keep-alive") != std::string::npos) p.keep_alive = true;
        if (v.find("upgrade") != std::string::npos) p.conn_upgrade = true;
      } else if (name == "upgrade") {
        p.upgrade_value = value;
      } else if (name == "cookie" && p.verified_cookie.empty()) {
        p.verified_cookie = extract_verified_cookie(value);
      }
    }
    pos = eol + 2;
  }
  p.raw_head = head;
  p.ok = true;
  return p;
}

// "name: value" lines of an h1 head (after the start line) -> pairs.
void parse_header_lines(
    const std::string& head,
    std::vector<std::pair<std::string, std::string>>* out) {
  size_t le = head.find("\r\n");
  size_t pos = le == std::string::npos ? head.size() : le + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos || eol == pos) break;
    size_t colon = head.find(':', pos);
    if (colon != std::string::npos && colon < eol) {
      out->emplace_back(head.substr(pos, colon - pos),
                        trim(head.substr(colon + 1, eol - colon - 1)));
    }
    pos = eol + 2;
  }
}

// Strip a :port (IPv6-bracket aware) — the shared host normalization
// for h1 Host headers and h2 :authority (get_host semantics).
std::string strip_host_port(const std::string& value) {
  if (!value.empty() && value[0] == '[') {
    size_t close = value.find(']');
    return close == std::string::npos ? value : value.substr(0, close + 1);
  }
  size_t port_colon = value.rfind(':');
  return port_colon == std::string::npos ? value
                                         : value.substr(0, port_colon);
}

// Extract __pingoo_captcha_verified from a Cookie header value.
std::string extract_verified_cookie(const std::string& value) {
  size_t cp = 0;
  while (cp < value.size()) {
    size_t semi = value.find(';', cp);
    std::string part = trim(value.substr(
        cp, semi == std::string::npos ? std::string::npos : semi - cp));
    size_t eq = part.find('=');
    if (eq != std::string::npos &&
        part.substr(0, eq) == "__pingoo_captcha_verified")
      return part.substr(eq + 1);
    if (semi == std::string::npos) break;
    cp = semi + 1;
  }
  return "";
}

bool is_hop_header(const std::string& lname) {
  return lname == "connection" || lname == "keep-alive" ||
         lname == "proxy-connection" || lname == "upgrade" ||
         lname == "te" || lname == "trailer" ||
         lname == "proxy-authenticate" || lname == "proxy-authorization";
}

bool drop_request_header(const std::string& lname, bool chunked);

// Rewrite the client's request head for the upstream: strip hop-by-hop
// headers, inject connection: close (one upstream connection per
// verdicted request — the enforced scope), add forwarding headers
// (reference http_proxy_service.rs:114-190).
std::string rewrite_request_head(const Parsed& p, const std::string& client_ip,
                                 bool tls,
                                 const std::string& internal_token) {
  const std::string& head = p.raw_head;
  size_t line_end = head.find("\r\n");
  std::string out = head.substr(0, line_end + 2);
  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos || eol == pos) break;
    size_t colon = head.find(':', pos);
    std::string lname = colon != std::string::npos && colon < eol
                            ? lower(head.substr(pos, colon - pos))
                            : "";
    if (!drop_request_header(lname, p.chunked)) {
      out.append(head, pos, eol + 2 - pos);
    }
    pos = eol + 2;
  }
  if (p.is_upgrade()) {
    // Protocol upgrade (WebSocket): preserve the upgrade intent — the
    // hop-header strip above removed the client's Connection/Upgrade
    // pair, re-emit it canonically (reference serves with upgrades,
    // http_listener.rs:277).
    out += "connection: upgrade\r\nupgrade: " + p.upgrade_value + "\r\n";
  } else {
    // keep-alive so the upstream connection can be pooled for reuse
    // (reference proxies over a pooled client, http_proxy_service.rs:54-71)
    out += "connection: keep-alive\r\n";
  }
  if (!p.chunked && p.has_content_length)
    out += "content-length: " + std::to_string(p.content_length) + "\r\n";
  out += "x-forwarded-for: " + client_ip + "\r\n";
  out += std::string("x-forwarded-proto: ") + (tls ? "https" : "http") + "\r\n";
  if (!p.host.empty()) out += "x-forwarded-host: " + p.host + "\r\n";
  out += "pingoo-client-ip: " + client_ip + "\r\n";
  // Hops to the loopback control plane carry the per-boot internal
  // token so the Python listener can bind x-forwarded-for trust to
  // THIS proxy rather than to anything that can dial 127.0.0.1
  // (spoofed client identity would defeat captcha binding + IP rules).
  if (!internal_token.empty())
    out += "x-pingoo-internal: " + internal_token + "\r\n";
  out += "\r\n";
  return out;
}

// is_hop_header, plus the request-smuggling hygiene rule (RFC 7230
// §3.3.3): when Transfer-Encoding frames the body, any Content-Length
// must NOT reach the upstream — the proxy framed by TE and a
// CL-trusting upstream would see a different body boundary.
bool drop_request_header(const std::string& lname, bool chunked) {
  if (is_hop_header(lname)) return true;
  // The proxy re-derives body framing and appends its own canonical
  // content-length; forwarding the client's copies verbatim would let
  // duplicate/odd values desync upstream framing (RFC 7230 §3.3.3).
  if (lname == "content-length") return true;
  (void)chunked;
  // Identity headers the upstream must only ever receive from THIS
  // proxy — client-supplied copies would spoof the trusted client IP
  // (reference strips and re-sets the same set,
  // http_proxy_service.rs:114-190).
  if (lname.compare(0, 7, "pingoo-") == 0) return true;
  if (lname == "x-pingoo-internal") return true;
  return lname == "x-forwarded-for" || lname == "x-forwarded-proto" ||
         lname == "x-forwarded-host";
}

// Parsed upstream response head.
struct RespHead {
  int status = 0;
  bool chunked = false;
  long long content_length = -1;  // -1 = absent
  std::string rewritten;          // head to send downstream
  bool ok = false;
  // The UPSTREAM connection may be pooled for reuse after this
  // response: explicit body framing and no connection: close (HTTP/1.0
  // defaults to close unless keep-alive is announced).
  bool upstream_keep = false;
};

// Response headers this proxy never forwards downstream: hop-by-hop
// headers plus upstream identity/behavior headers (reference
// http_proxy_service.rs:37-43,197-201). One predicate shared by final
// and interim (1xx) head rewriting so the strip policy cannot diverge.
bool strip_response_header(const std::string& lname) {
  return is_hop_header(lname) || lname == "server" ||
         lname == "x-accel-buffering" || lname == "alt-svc";
}

// Rewrite the upstream response head for the client: strip hop-by-hop
// headers and upstream server identity, set server: pingoo (reference
// http_proxy_service.rs:37-43,197-201), and pin the connection header
// to our keep-alive decision.
RespHead rewrite_response_head(const std::string& head, bool client_keep) {
  RespHead r;
  size_t line_end = head.find("\r\n");
  if (line_end == std::string::npos) return r;
  const std::string line = head.substr(0, line_end);
  // Shortest legal status line is "HTTP/1.x NNN" (12 chars); anything
  // shorter would index out of bounds below.
  if (line.size() < 12 || line.compare(0, 7, "HTTP/1.") != 0 ||
      line[8] != ' ')
    return r;
  r.status = atoi(line.c_str() + 9);
  if (r.status < 100 || r.status > 999) return r;
  bool http10 = line.compare(0, 8, "HTTP/1.0") == 0;
  bool conn_close = false, conn_keep = false;
  std::string out = "HTTP/1.1" + line.substr(8) + "\r\n";
  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos || eol == pos) break;
    size_t colon = head.find(':', pos);
    std::string lname = colon != std::string::npos && colon < eol
                            ? lower(head.substr(pos, colon - pos))
                            : "";
    std::string value = colon != std::string::npos && colon < eol
                            ? trim(head.substr(colon + 1, eol - colon - 1))
                            : "";
    if (lname == "connection") {
      std::string lv = lower(value);
      if (lv.find("close") != std::string::npos) conn_close = true;
      if (lv.find("keep-alive") != std::string::npos) conn_keep = true;
    }
    if (lname == "transfer-encoding") {
      if (lower(value).find("chunked") != std::string::npos) r.chunked = true;
      out.append(head, pos, eol + 2 - pos);
    } else if (lname == "content-length") {
      r.content_length = strtoll(value.c_str(), nullptr, 10);
      out.append(head, pos, eol + 2 - pos);
    } else if (strip_response_header(lname)) {
      // dropped
    } else {
      out.append(head, pos, eol + 2 - pos);
    }
    pos = eol + 2;
  }
  out += "server: pingoo\r\n";
  bool has_body_framing = r.chunked || r.content_length >= 0 ||
                          r.status == 204 || r.status == 304;
  bool keep = client_keep && has_body_framing;
  out += keep ? "connection: keep-alive\r\n" : "connection: close\r\n";
  out += "\r\n";
  r.rewritten = out;
  r.upstream_keep =
      has_body_framing && !conn_close && (!http10 || conn_keep);
  r.ok = true;
  return r;
}

// Rewrite a 1xx interim head with the same hop-header/server-identity
// stripping as final responses (keeping the status line; interim heads
// carry no body framing or connection semantics of their own).
std::string rewrite_interim_head(const std::string& head) {
  size_t line_end = head.find("\r\n");
  if (line_end == std::string::npos) return head;
  std::string out = head.substr(0, line_end) + "\r\n";
  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos || eol == pos) break;
    size_t colon = head.find(':', pos);
    std::string lname = colon != std::string::npos && colon < eol
                            ? lower(head.substr(pos, colon - pos))
                            : "";
    if (!strip_response_header(lname)) {
      out.append(head, pos, eol + 2 - pos);
    }
    pos = eol + 2;
  }
  out += "\r\n";
  return out;
}

// ---------------------------------------------------------------------------
// connections

enum class ConnState {
  kHandshake,
  kReadingHead,
  kAwaitingVerdict,
  kProxying,
  kTunnel,   // protocol upgrade accepted: raw bidirectional splice
  kH2,       // HTTP/2 connection (nghttp2 session owns framing)
  kClosing,  // drain outbuf, then close
};

struct Conn;

struct SockRef {
  Conn* conn = nullptr;  // nullptr = the listening socket
  bool is_upstream = false;
  int32_t h2_sid = 0;  // nonzero: a per-h2-stream upstream socket
};

struct Conn {
  int fd = -1;
  int upstream_fd = -1;
  SSL* ssl = nullptr;           // non-null on TLS connections
  SSL_CTX* owned_ctx = nullptr;  // per-conn challenge ctx (tls-alpn-01)
  bool ssl_want_write = false;
  bool acme_challenge = false;
  ConnState state = ConnState::kReadingHead;

  std::string inbuf;   // client bytes not yet consumed
  std::string outbuf;  // bytes pending to client
  std::string upbuf;   // bytes pending to upstream

  // current request cycle
  Parsed req;
  BodyFramer req_body;
  bool req_body_forwarded = false;  // all request bytes handed to upbuf
  bool captcha_verified = false;
  int requests_served = 0;

  // Streaming body inspection (ISSUE 13, docs/BODY_STREAMING.md) — h1
  // cycles only. The body de-frames through a SEPARATE scan framer so
  // inbuf keeps the raw bytes for the normal post-verdict forwarding
  // path; the body verdict ticket is the request ticket with bit 63
  // set (PINGOO_BODY_VERDICT_BIT).
  bool body_inspect = false;       // this cycle streams body windows
  uint64_t body_flow = UINT64_MAX; // ring ticket doubling as the flow id
  BodyFramer body_scan;            // de-framing copy (req_body untouched)
  std::string body_win;            // de-framed payload pending a window
  uint32_t body_win_seq = 0;       // next window sequence number
  uint64_t body_total = 0;         // de-framed payload bytes seen so far
  size_t body_raw_seen = 0;        // inbuf prefix already scan-framed
  bool body_final_sent = false;    // FINAL window enqueued
  uint64_t body_fin_ms = 0;        // monotonic ms at FINAL enqueue
  bool meta_pending = false;       // meta verdict stashed, awaiting body
  uint8_t meta_action = 0;         // stashed metadata verdict byte
  bool body_verdict_done = false;  // body verdict byte landed
  uint8_t body_action = 0;         // body verdict byte

  // upstream response
  std::string resp_head_buf;
  bool resp_head_done = false;
  BodyFramer resp_body;
  bool close_after_response = false;

  uint64_t ticket = UINT64_MAX;
  char peer_ip[INET6_ADDRSTRLEN] = {0};
  uint16_t peer_port = 0;
  bool dead = false;
  bool upstream_connected = false;
  bool upstream_eof = false;
  bool up_trunc = false;        // upstream ended with an ERROR, not clean EOF
  int tcp_attempts = 0;         // tcp-proxy mode: connect tries so far
  time_t tcp_connect_at = 0;    // tcp-proxy mode: when this try started
  bool down_shut = false;       // write side toward the CLIENT shut
                                // (tcp mode: upstream FIN propagated)
  UpH2Link* up_h2 = nullptr;    // non-null: upstream link speaks h2
  std::string up_head;          // rewritten h1 head (kept until the
                                // upstream protocol is decided by ALPN)
  bool up_proto_pending = false;  // TLS target: h1-vs-h2 awaits ALPN
  uint64_t up_key = 0;          // pool key of the connected target
  UpTarget up_target{};         // connected target (pooled-retry)
  SSL* up_ssl = nullptr;        // non-null on TLS upstream links
  bool up_tcp_ok = false;       // TCP connect completed
  bool up_tls_hs = false;       // client handshake in progress
  bool up_hs_want_write = false;  // handshake blocked on EPOLLOUT
  bool up_rd_want_write = false;  // SSL_read wants the write event
  bool up_wr_want_read = false;   // SSL_write wants the read event
  bool upstream_keep = false;   // response head allows connection reuse
  bool upstream_junk = false;   // upstream sent bytes past the response
  uint64_t enq_ms = 0;          // monotonic ms at ring enqueue (metrics)
  bool up_shut = false;         // tunnel: upstream write side FIN'd
  bool upstream_pooled = false; // current upstream fd came from the pool
  std::string up_replay;        // bytes sent upstream (pooled-retry replay)
  bool client_eof = false;
  time_t last_active = 0;
  SockRef client_ref;
  SockRef upstream_ref;

  // -- HTTP/2 mode (state == kH2) --
  nghttp2_session* h2 = nullptr;
  std::unordered_map<int32_t, H2Stream> h2_streams;
  std::vector<int32_t> h2_ready;   // completed requests awaiting service
  std::vector<int32_t> h2_proxy_wait;  // verdicted, waiting for a slot
  int h2_upstreams = 0;            // streams with an open upstream socket
  // Per-stream response bodies served through the data provider (a
  // client flow-control stall can defer DATA past the next stream).
  std::unordered_map<int32_t, std::pair<std::string, size_t>> h2_send;
  time_t verdict_at = 0;           // when the active ticket was enqueued
};

class Server;
Server* g_server = nullptr;
volatile sig_atomic_t g_sigterm = 0;

const char k403[] =
    "HTTP/1.1 403 Forbidden\r\nserver: pingoo\r\n"
    "content-type: text/plain\r\ncontent-length: 9\r\n"
    "connection: close\r\n\r\nForbidden";
const char kCaptcha[] =
    "HTTP/1.1 302 Found\r\nserver: pingoo\r\n"
    "location: /__pingoo/captcha\r\ncontent-length: 0\r\n"
    "connection: close\r\n\r\n";
const char k502[] =
    "HTTP/1.1 502 Bad Gateway\r\nserver: pingoo\r\n"
    "content-type: text/plain\r\ncontent-length: 11\r\n"
    "connection: close\r\n\r\nBad Gateway";
const char k400[] =
    "HTTP/1.1 400 Bad Request\r\nserver: pingoo\r\n"
    "content-length: 0\r\nconnection: close\r\n\r\n";
const char k413[] =
    "HTTP/1.1 413 Content Too Large\r\nserver: pingoo\r\n"
    "content-length: 0\r\nconnection: close\r\n\r\n";
const char k431[] =
    "HTTP/1.1 431 Request Header Fields Too Large\r\nserver: pingoo\r\n"
    "content-length: 0\r\nconnection: close\r\n\r\n";
const char k404[] =
    "HTTP/1.1 404 Not Found\r\nserver: pingoo\r\n"
    "content-type: text/plain\r\ncontent-length: 9\r\n"
    "connection: close\r\n\r\nNot Found";

// -- service routing table ---------------------------------------------------
//
// The reference selects the FIRST service whose route predicate matches
// the request and load-balances across that service's discovered
// upstreams (http_listener.rs:266-270, http_proxy_service.rs:101,118,
// service_registry.rs:54-103). Here the route decision is computed by
// the verdict sidecar ON DEVICE (the route predicates ride the same
// batched verdict as the WAF rules) and arrives in the verdict byte's
// bits 3-7: the winning service's order index, 31 = no service matched.
// This plane owns only the dispatch: service order -> upstream set ->
// random member.
//
// The table is a text file written by the control plane (registry
// snapshots, native_ring.write_services_file) and hot-reloaded on
// mtime change, the same freshness discipline as the JWKS gate:
//
//   pingoo-services v1
//   service 0 web
//   upstream 127.0.0.1 8081
//   upstream 127.0.0.1 8082
//   service 1 api
//   upstream 127.0.0.1 9001
//   upstream 10.0.0.9 8443 tls backend.example.com
//
// An `upstream <ip> <port> tls <server-name>` entry is proxied over a
// verified TLS client connection (SNI + hostname check against
// <server-name>), matching the reference's pooled hyper-rustls client
// (http_proxy_service.rs:54-71).
struct ServiceTable {
  std::string path;
  std::vector<std::string> names;
  std::vector<std::vector<UpTarget>> upstreams;  // by service order
  std::vector<std::string> static_roots;  // "" = not a static service
  bool loaded = false;
  time_t last_check_ = 0;
  time_t mtime_s_ = 0;
  long mtime_ns_ = 0;

  bool reload() {
    struct stat st;
    if (path.empty() || stat(path.c_str(), &st) != 0) return loaded;
    if (loaded && st.st_mtime == mtime_s_ &&
        st.st_mtim.tv_nsec == mtime_ns_)
      return true;
    FILE* f = fopen(path.c_str(), "r");
    if (f == nullptr) return loaded;
    std::vector<std::string> new_names;
    std::vector<std::vector<UpTarget>> new_ups;
    std::vector<std::string> new_static;
    int static_consumed = 0;
    char line[512];
    bool ok = true;
    while (fgets(line, sizeof(line), f) != nullptr) {
      char a[256], b[256], sni[256];
      int port = 0, order = 0;
      if (sscanf(line, "service %d %255s", &order, a) == 2) {
        if (order != static_cast<int>(new_names.size()) || order > 30) {
          // Orders must be dense and in file order, and fit the 5-bit
          // route field (0-30; 31 is the no-match sentinel).
          ok = false;
          break;
        }
        new_names.emplace_back(a);
        new_ups.emplace_back();
        new_static.emplace_back();
      } else if (char sroot[384];
                 sscanf(line, "static %383s%n", sroot,
                        &static_consumed) == 1) {
        // Static site root for the CURRENT service (reference
        // http_static_site_service.rs): files <= 500 KB are served
        // from this binary; bigger ones proxy to the service's
        // upstream list (the streaming control plane).
        const char* tail = line + static_consumed;
        while (*tail == ' ' || *tail == '\t') tail++;
        if (new_static.empty() ||
            (*tail != '\0' && *tail != '\n' && *tail != '\r')) {
          // trailing fields (version skew) or a root past the %383s
          // scan width: reject the table, keep the last good one —
          // the same fail-closed rule as the tls/h2/internal markers.
          ok = false;
          break;
        }
        new_static.back() = sroot;
      } else if (int consumed = 0;
                 sscanf(line, "upstream %255s %d%n", b, &port,
                        &consumed) == 2) {
        if (new_ups.empty() || port <= 0 || port > 65535) {
          ok = false;
          break;
        }
        UpTarget t;
        t.sa.sin_family = AF_INET;
        t.sa.sin_port = htons(static_cast<uint16_t>(port));
        if (inet_pton(AF_INET, b, &t.sa.sin_addr) != 1) {
          ok = false;
          break;
        }
        const char* rest = line + consumed;
        while (*rest == ' ' || *rest == '\t') rest++;
        if (strncmp(rest, "tls", 3) == 0 &&
            (rest[3] == ' ' || rest[3] == '\t')) {
          int used = 0;
          if (sscanf(rest, "tls %255s%n", sni, &used) == 1) {
            const char* tail = rest + used;
            while (*tail == ' ' || *tail == '\t') tail++;
            if (*tail != '\0' && *tail != '\n' && *tail != '\r') {
              ok = false;  // fields past the name (version skew, or an
              // over-long truncated name): reject, keep last good table
              break;
            }
            t.tls = true;
            t.sni = sni;
          } else {
            // `tls` with no server name must NOT fail open to a
            // plaintext hop: reject the table, keep the last good one.
            ok = false;
            break;
          }
        } else if (strncmp(rest, "h2", 2) == 0 &&
                   (rest[2] == '\0' || rest[2] == '\n' || rest[2] == '\r' ||
                    rest[2] == ' ' || rest[2] == '\t')) {
          const char* tail = rest + 2;
          while (*tail == ' ' || *tail == '\t') tail++;
          if (*tail != '\0' && *tail != '\n' && *tail != '\r') {
            ok = false;  // fields past the marker: version skew
            break;
          }
          t.h2 = true;  // cleartext prior-knowledge h2 target
        } else if (strncmp(rest, "internal", 8) == 0 &&
                   (rest[8] == '\0' || rest[8] == '\n' || rest[8] == '\r' ||
                    rest[8] == ' ' || rest[8] == '\t')) {
          const char* tail = rest + 8;
          while (*tail == ' ' || *tail == '\t') tail++;
          if (*tail != '\0' && *tail != '\n' && *tail != '\r') {
            ok = false;  // fields past the marker: version skew
            break;
          }
          t.internal = true;  // loopback control-plane target
        } else if (*rest != '\0' && *rest != '\n' && *rest != '\r') {
          ok = false;  // unknown trailing fields: same fail-closed rule
          break;
        }
        new_ups.back().push_back(std::move(t));
      }
      // other lines (header, comments, blank) are ignored
    }
    fclose(f);
    if (!ok || new_names.empty()) return loaded;  // keep last good table
    names = std::move(new_names);
    upstreams = std::move(new_ups);
    static_roots = std::move(new_static);
    loaded = true;
    mtime_s_ = st.st_mtime;
    mtime_ns_ = st.st_mtim.tv_nsec;
    return true;
  }

  void maybe_reload(time_t now) {
    if (path.empty() || now == last_check_) return;
    last_check_ = now;
    reload();
  }
};

class Server {
 public:
  Server(int ep, void* ring, const sockaddr_in& upstream,
         const sockaddr_in* captcha_upstream, CaptchaGate* gate,
         TlsStore* tls, ServiceTable* services = nullptr,
         SSL_CTX* up_ctx = nullptr, std::string internal_token = "",
         bool tcp_mode = false)
      : ep_(ep),
        ring_(ring),
        upstream_(upstream),
        gate_(gate),
        tls_(tls),
        services_(services),
        up_ctx_(up_ctx),
        internal_token_(std::move(internal_token)),
        tcp_mode_(tcp_mode) {
    if (captcha_upstream) {
      captcha_upstream_ = *captcha_upstream;
      has_captcha_upstream_ = true;
    }
  }

  // -- service routing -------------------------------------------------------

  enum class Route { kOk, kNoService, kNoUpstream };

  // Resolve the verdict byte's route bits (bits 3-7: service order,
  // 31 = none matched) to a concrete upstream address. Without a
  // services table every request goes to the single argv upstream
  // (the pre-routing deployment shape).
  Route pick_route_target(uint8_t route, UpTarget* out) {
    if (services_ == nullptr || !services_->loaded) {
      out->sa = upstream_;
      out->internal = true;  // the argv upstream is the loopback plane
      return Route::kOk;
    }
    if (route >= services_->upstreams.size()) return Route::kNoService;
    const auto& set = services_->upstreams[route];
    if (set.empty()) return Route::kNoUpstream;
    // xorshift32: cheap per-request random member selection, matching
    // the reference's random upstream pick (http_proxy_service.rs:101).
    rng_ ^= rng_ << 13;
    rng_ ^= rng_ >> 17;
    rng_ ^= rng_ << 5;
    *out = set[rng_ % set.size()];
    return Route::kOk;
  }

  // Fail-open target (ring full / verdict timeout): no route decision
  // exists, so fall back to the FIRST service — the same default the
  // argv upstream provides without a table.
  bool default_target(UpTarget* out) {
    if (services_ == nullptr || !services_->loaded) {
      out->sa = upstream_;
      out->internal = true;  // the argv upstream is the loopback plane
      return true;
    }
    if (!services_->upstreams.empty() && !services_->upstreams[0].empty()) {
      return pick_route_target(0, out) == Route::kOk;
    }
    return false;
  }

  // -- native static site serving -------------------------------------------
  // Reference http_static_site_service.rs:83-257: GET/HEAD only (405),
  // traversal guard (404), dir -> index.html, extensionless -> .html
  // prettify, ETag = SHA256(path, size, mtime_ns) with If-None-Match
  // -> 304, <= 500 KB files cached (500 entries); larger files proxy
  // to the service's upstream list (the control plane streams them —
  // the one delta from the reference, which streams in-binary).

  struct StaticFile {
    uint64_t size = 0;
    uint64_t mtime_ns = 0;
    std::string data;
  };
  static constexpr uint64_t kStaticCacheFileLimit = 500000;  // 500 KB
  static constexpr size_t kStaticCacheEntries = 500;

  static const char* mime_for(const std::string& path) {
    size_t dot = path.rfind('.');
    std::string ext = dot == std::string::npos ? "" : path.substr(dot + 1);
    for (auto& ch : ext) ch = static_cast<char>(tolower(ch));
    if (ext == "html" || ext == "htm") return "text/html";
    if (ext == "css") return "text/css";
    if (ext == "js" || ext == "mjs") return "text/javascript";
    if (ext == "json") return "application/json";
    if (ext == "png") return "image/png";
    if (ext == "jpg" || ext == "jpeg") return "image/jpeg";
    if (ext == "gif") return "image/gif";
    if (ext == "svg") return "image/svg+xml";
    if (ext == "webp") return "image/webp";
    if (ext == "ico") return "image/vnd.microsoft.icon";
    if (ext == "txt") return "text/plain";
    if (ext == "xml") return "application/xml";
    if (ext == "pdf") return "application/pdf";
    if (ext == "wasm") return "application/wasm";
    if (ext == "woff2") return "font/woff2";
    if (ext == "woff") return "font/woff";
    if (ext == "mp4") return "video/mp4";
    return "application/octet-stream";
  }

  struct StaticResult {
    int status = 0;         // 200 / 304 / 404 / 405 / 500
    bool oversized = false;  // caller proxies to the upstream list
    std::string body;
    std::vector<std::pair<std::string, std::string>> headers;
    uint64_t file_size = 0;  // entity size (HEAD advertises it)
  };

  StaticResult static_lookup(const std::string& root,
                             const std::string& method,
                             const std::string& target,
                             const std::string& if_none_match) {
    StaticResult out;
    auto plain = [&out](int status, const char* body) -> StaticResult& {
      out.status = status;
      out.body = body;
      out.headers.emplace_back("content-type", "text/plain");
      out.file_size = out.body.size();
      return out;
    };
    if (method != "GET" && method != "HEAD")
      return plain(405, "Method Not Allowed");
    std::string path = target.substr(0, target.find('?'));
    // trim leading/trailing '/' like the reference, then guard
    size_t b = path.find_first_not_of('/');
    size_t e = path.find_last_not_of('/');
    path = b == std::string::npos ? "" : path.substr(b, e - b + 1);
    if (path.find("/..") != std::string::npos ||
        path.find("../") != std::string::npos || path == ".." ||
        path.find("//") != std::string::npos)
      return plain(404, "Not Found");
    std::string full = root + "/" + path;
    struct stat st;
    if (stat(full.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
      full += path.empty() ? "index.html" : "/index.html";
      if (stat(full.c_str(), &st) != 0 || S_ISDIR(st.st_mode))
        return plain(404, "Not Found");
    } else if (stat(full.c_str(), &st) != 0) {
      // prettify: extensionless /page -> /page.html
      size_t slash = full.rfind('/');
      if (full.find('.', slash + 1) != std::string::npos)
        return plain(404, "Not Found");
      full += ".html";
      if (stat(full.c_str(), &st) != 0 || S_ISDIR(st.st_mode))
        return plain(404, "Not Found");
    }
    uint64_t size = static_cast<uint64_t>(st.st_size);
    uint64_t mtime_ns = static_cast<uint64_t>(st.st_mtim.tv_sec) *
                            1000000000ull +
                        static_cast<uint64_t>(st.st_mtim.tv_nsec);
    // ETag = sha256(path, size_le, mtime_le) (reference :150-160)
    unsigned char md[32];
    unsigned int mdlen = 0;
    std::string etag_src = full;
    etag_src.append(reinterpret_cast<const char*>(&size), 8);
    etag_src.append(reinterpret_cast<const char*>(&mtime_ns), 8);
    EVP_Digest(etag_src.data(), etag_src.size(), md, &mdlen, EVP_sha256(),
               nullptr);
    static const char hexd[] = "0123456789abcdef";
    std::string etag = "\"";
    for (unsigned i = 0; i < mdlen; ++i) {
      etag += hexd[md[i] >> 4];
      etag += hexd[md[i] & 15];
    }
    etag += "\"";
    std::vector<std::pair<std::string, std::string>> base_headers = {
        {"content-type", mime_for(full)},
        {"cache-control", "public, max-age=0, must-revalidate"},
        {"etag", etag},
    };
    // If-None-Match (W/ prefix + quotes stripped, reference :161-183)
    std::string inm = if_none_match;
    size_t s0 = inm.find_first_not_of(" \t");
    if (s0 != std::string::npos) inm = inm.substr(s0);
    if (inm.compare(0, 2, "W/") == 0) inm = inm.substr(2);
    while (!inm.empty() && (inm.front() == '"')) inm.erase(0, 1);
    while (!inm.empty() && (inm.back() == '"' || inm.back() == ' '))
      inm.pop_back();
    if (!inm.empty() && etag == "\"" + inm + "\"") {
      out.status = 304;
      out.headers = base_headers;
      out.file_size = size;
      return out;
    }
    if (size > kStaticCacheFileLimit) {
      out.oversized = true;  // control plane streams it
      return out;
    }
    auto it = file_cache_.find(full);
    if (it != file_cache_.end() && it->second.size == size &&
        it->second.mtime_ns == mtime_ns) {
      out.status = 200;
      out.body = it->second.data;
      out.headers = base_headers;
      out.file_size = size;
      return out;
    }
    FILE* f = fopen(full.c_str(), "rb");
    if (f == nullptr)
      return plain(500, "Internal Server Error");
    std::string data;
    data.resize(size);
    size_t got = fread(data.data(), 1, size, f);
    fclose(f);
    if (got != size) {
      // stat-then-read race: the file was truncated/replaced between
      // the stat and the read. Serving `got` bytes under the stat'd
      // content-length would corrupt the client's framing, and caching
      // the short body would pin the corruption until the mtime
      // changes again — fail the request and cache nothing.
      return plain(500, "Internal Server Error");
    }
    if (file_cache_.size() >= kStaticCacheEntries)
      file_cache_.erase(file_cache_.begin());
    file_cache_[full] = StaticFile{size, mtime_ns, data};
    out.status = 200;
    out.body = std::move(data);
    out.headers = base_headers;
    out.file_size = size;
    return out;
  }

  // Generic keep-alive-aware h1 response for natively served content.
  // content_length < 0 omits the header entirely (304: RFC 9110 §8.6 —
  // a stated length must match the SELECTED representation, and the
  // 304 carries no body to derive it from).
  void respond_h1(Conn* c, int status, const char* reason,
                  const std::vector<std::pair<std::string, std::string>>&
                      extra_headers,
                  const std::string& body, bool head_only,
                  long long content_length) {
    bool keep = c->req.keep_alive && c->req_body.done;
    c->outbuf += "HTTP/1.1 " + std::to_string(status) + " " + reason +
                 "\r\nserver: pingoo\r\n";
    if (content_length >= 0)
      c->outbuf += "content-length: " + std::to_string(content_length) +
                   "\r\n";
    for (const auto& kv : extra_headers)
      c->outbuf += kv.first + ": " + kv.second + "\r\n";
    c->outbuf += keep ? "connection: keep-alive\r\n\r\n"
                      : "connection: close\r\n\r\n";
    if (!head_only) c->outbuf += body;
    if (!flush_out(c)) {
      mark_close(c);
      return;
    }
    if (!keep) {
      c->state = ConnState::kClosing;
      if (c->outbuf.empty()) mark_close(c);
      else update_client_events(c);
      return;
    }
    begin_request_cycle(c);
  }

  static const char* reason_for(int status) {
    switch (status) {
      case 200: return "OK";
      case 304: return "Not Modified";
      case 404: return "Not Found";
      case 405: return "Method Not Allowed";
      default: return "Internal Server Error";
    }
  }

  // True when the request was fully answered natively; false -> the
  // caller proxies to the service's upstream list (oversized file).
  bool try_static_h1(Conn* c, const std::string& root) {
    std::string inm;
    const std::string& head = c->req.raw_head;
    size_t pos = head.find("\r\n");
    pos = pos == std::string::npos ? head.size() : pos + 2;
    while (pos < head.size()) {
      size_t eol = head.find("\r\n", pos);
      if (eol == std::string::npos || eol == pos) break;
      size_t colon = head.find(':', pos);
      if (colon != std::string::npos && colon < eol) {
        std::string nm = lower(head.substr(pos, colon - pos));
        if (nm == "if-none-match") {
          size_t vs = colon + 1;
          while (vs < eol && head[vs] == ' ') vs++;
          inm = head.substr(vs, eol - vs);
          break;
        }
      }
      pos = eol + 2;
    }
    StaticResult r = static_lookup(root, c->req.method, c->req.target, inm);
    if (r.oversized) return false;
    bool head_only = c->req.method == "HEAD" || r.status == 304;
    long long cl = r.status == 304
                       ? -1
                       : static_cast<long long>(r.file_size);
    respond_h1(c, r.status, reason_for(r.status), r.headers, r.body,
               head_only, cl);
    return true;
  }

  bool try_static_h2(Conn* c, int32_t sid, H2Stream& st,
                     const std::string& root) {
    std::string inm;
    for (const auto& kv : st.p.h2_headers) {
      if (kv.first == "if-none-match") {
        inm = kv.second;
        break;
      }
    }
    StaticResult r = static_lookup(root, st.p.method, st.p.target, inm);
    if (r.oversized) return false;
    bool head_only = st.p.method == "HEAD" || r.status == 304;
    // 304 omits content-length (RFC 9110 §8.6); HEAD advertises the
    // full entity size while sending no body.
    long long cl = r.status == 304
                       ? -1
                       : static_cast<long long>(r.file_size);
    h2_submit(c, sid, r.status, r.headers,
              head_only ? std::string() : r.body, cl);
    h2_process_next(c);
    return true;
  }

  void dispatch_route(Conn* c, uint8_t route) {
    if (services_ != nullptr && services_->loaded &&
        route < services_->static_roots.size() &&
        !services_->static_roots[route].empty()) {
      if (try_static_h1(c, services_->static_roots[route])) return;
      // oversized file: fall through to the service's upstream list
    }
    UpTarget target;
    switch (pick_route_target(route, &target)) {
      case Route::kOk:
        start_proxy(c, target);
        return;
      case Route::kNoService:
        // Reference: no service matched -> 404 (http_listener.rs:270).
        stats_.no_service++;
        respond_close(c, k404);
        return;
      case Route::kNoUpstream:
        respond_502(c);
        return;
    }
  }

  void h2_dispatch_route(Conn* c, int32_t sid, uint8_t route) {
    if (services_ != nullptr && services_->loaded &&
        route < services_->static_roots.size() &&
        !services_->static_roots[route].empty()) {
      auto it = c->h2_streams.find(sid);
      if (it != c->h2_streams.end() &&
          try_static_h2(c, sid, it->second,
                        services_->static_roots[route]))
        return;
    }
    UpTarget target;
    switch (pick_route_target(route, &target)) {
      case Route::kOk:
        h2_start_stream_proxy(c, sid, target);
        return;
      case Route::kNoService:
        stats_.no_service++;
        h2_respond_simple(c, sid, 404, "Not Found");
        return;
      case Route::kNoUpstream:
        stats_.upstream_fail++;
        h2_respond_simple(c, sid, 502, "Bad Gateway");
        return;
    }
  }

  void fail_open_proxy(Conn* c) {
    UpTarget target;
    if (default_target(&target)) {
      start_proxy(c, target);
    } else {
      respond_502(c);
    }
  }

  void h2_stream_fail_open(Conn* c, int32_t sid) {
    UpTarget target;
    if (default_target(&target)) {
      h2_start_stream_proxy(c, sid, target);
    } else {
      stats_.upstream_fail++;
      h2_respond_simple(c, sid, 502, "Bad Gateway");
    }
  }

  TlsStore* tls() { return tls_; }

  void add_client(int cfd, const sockaddr_in& peer, SSL_CTX* base_ctx) {
    Conn* c = new Conn();
    c->fd = cfd;
    c->last_active = now_;
    c->client_ref.conn = c;
    c->upstream_ref.conn = c;
    c->upstream_ref.is_upstream = true;
    inet_ntop(AF_INET, &peer.sin_addr, c->peer_ip, sizeof(c->peer_ip));
    c->peer_port = ntohs(peer.sin_port);
    if (base_ctx != nullptr) {
      c->ssl = SSL_new(base_ctx);
      SSL_set_fd(c->ssl, cfd);
      SSL_set_accept_state(c->ssl);
      c->state = ConnState::kHandshake;
      // The client-hello callback needs the Conn to stash challenge
      // state; OpenSSL gives us per-SSL ex_data, but a side map is
      // simpler with the shim surface we declare.
      ssl_conn_[c->ssl] = c;
    }
    conns_.insert(c);
    epoll_event ce{};
    ce.events = EPOLLIN;
    ce.data.ptr = &c->client_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, cfd, &ce);
    if (tcp_mode_ && c->ssl == nullptr) start_tcp_proxy(c);
    // tcp+tls: the handshake completes first (SNI cert store +
    // acme-tls/1 interception run exactly as for https — reference
    // accept_tls_connection serves both listener kinds,
    // listeners/mod.rs:112-154), then on_handshake starts the pump.
  }

  // -- raw TCP(+TLS) fronting (reference tcp_listener.rs:39-70 +
  //    tcp_proxy_service.rs:30-84): accept -> pick a random upstream
  //    (3 tries, 3 s connect timeout) -> bidirectional byte splice.
  //    Reuses the kTunnel state machine (the WebSocket splice path).

  void start_tcp_proxy(Conn* c) {
    UpTarget target;
    if (!default_target(&target)) {
      // Empty table (discovery warm-up / all upstreams gone): park and
      // let the retry ladder ride through the outage instead of
      // dropping the client on first sight.
      tcp_proxy_fail(c);
      return;
    }
    if (target.tls && up_ctx_ == nullptr) {
      stats_.upstream_fail++;
      mark_close(c);
      return;
    }
    int ufd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (ufd < 0 ||
        (connect(ufd, reinterpret_cast<const sockaddr*>(&target.sa),
                 sizeof(target.sa)) != 0 &&
         errno != EINPROGRESS)) {
      if (ufd >= 0) close(ufd);
      tcp_proxy_fail(c);
      return;
    }
    c->upstream_fd = ufd;
    c->up_key = 0;
    c->up_target = target;
    c->upstream_pooled = false;
    reset_up_link(c);
    c->tcp_connect_at = now_;
    c->state = ConnState::kTunnel;
    epoll_event ue{};
    ue.events = EPOLLOUT | EPOLLIN;
    ue.data.ptr = &c->upstream_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, ufd, &ue);
    update_client_events(c);
  }

  void tcp_proxy_fail(Conn* c) {
    // Retry CONNECT only — once bytes may have flowed, a re-dial would
    // splice two different upstream streams together.
    bool mid_stream = c->upstream_connected;
    close_upstream(c);
    if (!mid_stream && ++c->tcp_attempts < tcp_connect_retries()) {
      if (c->tcp_attempts == 1) {
        // First failure: immediate re-dial (fresh random member).
        start_tcp_proxy(c);
      } else {
        // Later failures: PARK (state kTunnel, no upstream fd); the
        // per-second sweep re-dials, so retries span real upstream
        // recovery time (container restart, discovery refresh) instead
        // of burning all tries in one ECONNREFUSED microsecond — the
        // reference sleeps between tries and re-snapshots upstreams
        // for the same reason (tcp_proxy_service.rs:86-112).
        c->state = ConnState::kTunnel;
        c->tcp_connect_at = now_;
        update_client_events(c);
      }
      return;
    }
    stats_.upstream_fail++;
    mark_close(c);
  }

  Conn* conn_for_ssl(SSL* ssl) {
    auto it = ssl_conn_.find(ssl);
    return it == ssl_conn_.end() ? nullptr : it->second;
  }

  void mark_close(Conn* c) {
    if (c->dead) return;
    c->dead = true;
    doomed_.push_back(c);
  }

  void flush_doomed() {
    for (Conn* c : doomed_) {
      if (c->h2 != nullptr) {
        nghttp2_session_del(c->h2);
        c->h2 = nullptr;
      }
      if (c->ssl) {
        SSL_shutdown(c->ssl);
        ssl_conn_.erase(c->ssl);
        SSL_free(c->ssl);
        ERR_clear_error();
      }
      if (c->owned_ctx) SSL_CTX_free(c->owned_ctx);
      if (c->fd >= 0) {
        epoll_ctl(ep_, EPOLL_CTL_DEL, c->fd, nullptr);
        close(c->fd);
      }
      close_upstream(c);
      for (auto& kv : c->h2_streams)
        h2_release_stream_resources(c, kv.second);
      if (c->ticket != UINT64_MAX) awaiting_.erase(c->ticket);
      body_abort(c);  // frees the sidecar flow + the demux entry
      conns_.erase(c);
      delete c;
    }
    doomed_.clear();
    for (SockRef* r : doomed_refs_) {
      r->conn = nullptr;
      delete r;
    }
    doomed_refs_.clear();
  }

  void set_now(time_t t) { now_ = t; }

  void queue_ssl_resume(Conn* c, int32_t sid) {
    for (const auto& e : ssl_resume_)
      if (e.first == c && e.second == sid) return;
    ssl_resume_.emplace_back(c, sid);
  }

  // Deliver reads for data already decrypted inside SSL objects: epoll
  // cannot signal it (nothing is on the fd), so update_*_events queues
  // the link and the main loop drains the queue after each batch.
  void process_ssl_resume() {
    if (ssl_resume_.empty()) return;
    std::vector<std::pair<Conn*, int32_t>> work;
    work.swap(ssl_resume_);
    for (const auto& e : work) {
      Conn* c = e.first;
      if (conns_.find(c) == conns_.end() || c->dead) continue;
      if (e.second == 0) {
        if (c->upstream_fd >= 0 && proxy_live(c))
          on_upstream_event(c, EPOLLIN);
      } else {
        h2_stream_upstream_event(c, e.second, EPOLLIN);
      }
    }
  }

  bool awaiting_verdicts() const {
    return !awaiting_.empty() || !body_awaiting_.empty();
  }

  // -- metrics ---------------------------------------------------------------
  // The serving path must be observable where the traffic actually is
  // (SURVEY §5 calls the metrics surface a build requirement): counters
  // + a verdict-wait histogram, served at /__pingoo/metrics on both
  // protocols. The reference ships no metrics endpoint at all.

  struct Stats {
    uint64_t requests = 0;        // parsed requests (h1 cycles + h2 streams)
    uint64_t blocked = 0;         // 403 verdicts applied
    uint64_t captcha = 0;         // challenge redirects served
    uint64_t ua_rejected = 0;     // empty/oversized UA pre-ring 403s
    uint64_t fail_open = 0;       // ring-full + verdict-timeout proxies
    uint64_t no_service = 0;      // route bits said no service (404)
    uint64_t upstream_fail = 0;   // 502s
    uint64_t upstream_tls_fail = 0;  // client handshake/verify failures
    uint64_t verdicts = 0;        // verdict bytes applied
    uint64_t degraded_entered = 0;  // degraded-mode transitions (enter)
    // Streaming body inspection (ISSUE 13, PINGOO_BODY_INSPECT=on).
    uint64_t body_flows = 0;      // h1 cycles armed for inspection
    uint64_t body_windows = 0;    // body windows enqueued to the ring
    uint64_t body_bytes = 0;      // de-framed payload bytes enqueued
    uint64_t body_verdicts = 0;   // body verdict bytes consumed
    uint64_t body_fail_open = 0;  // flows degraded to metadata-only
                                  // (ring full / hold cap / deadline /
                                  // degraded mode / bad framing)
    uint64_t body_h2_skipped = 0; // h2 streams left metadata-only
    // log-scale verdict wait histogram (enqueue -> apply), upper bounds
    // in ms: 1, 2, 5, 10, 50, 100, 1000, +inf — the SHARED bucket set
    // (pingoo_tpu/obs/schema.py SHARED_WAIT_BUCKETS_MS); the JSON
    // surface folds the last two into its legacy "inf" key.
    uint64_t wait_hist[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    uint64_t wait_sum_ms = 0;     // for the histogram _sum series
  };

  static uint64_t now_ms() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000 +
           static_cast<uint64_t>(ts.tv_nsec) / 1000000;
  }

  void record_wait(uint64_t ms) {
    static const uint64_t bounds[7] = {1, 2, 5, 10, 50, 100, 1000};
    int b = 7;
    for (int i = 0; i < 7; ++i) {
      if (ms < bounds[i]) {
        b = i;
        break;
      }
    }
    stats_.wait_hist[b]++;
    stats_.wait_sum_ms += ms;
  }

  // JSON body, built with std::string: the old fixed 1024-byte snprintf
  // buffer was ~100 bytes from silent truncation (= invalid JSON on the
  // wire) and every new field raised the risk. Schema is back-compat:
  // the legacy keys keep their names, the ring telemetry block rides
  // under "ring", and the legacy 7-bucket "verdict_wait_ms_hist" folds
  // the new le1000 bucket into its "inf" key.
  std::string metrics_body() {
    uint64_t tel[PINGOO_TELEMETRY_WORDS];
    pingoo_ring_telemetry_snapshot(ring_, tel);
    uint64_t ring_pending = tel[3];
    size_t pooled = 0;
    for (const auto& kv : upstream_pool_) pooled += kv.second.size();
    std::string out = "{";
    auto kv_u64 = [&out](const char* key, uint64_t v, bool first = false) {
      if (!first) out += ", ";
      out += "\"";
      out += key;
      out += "\": ";
      out += std::to_string(v);
    };
    kv_u64("requests", stats_.requests, true);
    kv_u64("blocked", stats_.blocked);
    kv_u64("captcha", stats_.captcha);
    kv_u64("ua_rejected", stats_.ua_rejected);
    kv_u64("fail_open", stats_.fail_open);
    kv_u64("no_service", stats_.no_service);
    kv_u64("upstream_fail", stats_.upstream_fail);
    kv_u64("upstream_tls_fail", stats_.upstream_tls_fail);
    kv_u64("verdicts", stats_.verdicts);
    out += ", \"verdict_wait_ms_hist\": {";
    static const char* kHistKeys[6] = {"le1",  "le2",  "le5",
                                       "le10", "le50", "le100"};
    for (int i = 0; i < 6; ++i) {
      if (i) out += ", ";
      out += "\"";
      out += kHistKeys[i];
      out += "\": ";
      out += std::to_string(stats_.wait_hist[i]);
    }
    out += ", \"inf\": " +
           std::to_string(stats_.wait_hist[6] + stats_.wait_hist[7]);
    out += "}";
    kv_u64("ring_pending", ring_pending);
    kv_u64("awaiting", awaiting_.size());
    kv_u64("connections", conns_.size());
    kv_u64("pooled_upstreams", pooled);
    kv_u64("degraded", degraded_ ? 1 : 0);
    kv_u64("degraded_entered", stats_.degraded_entered);
    kv_u64("sidecar_up", (sidecar_seen_ && !degraded_) ? 1 : 0);
    kv_u64("sidecar_epoch", sidecar_epoch_);
    out += ", \"body\": {";
    kv_u64("flows", stats_.body_flows, true);
    kv_u64("windows", stats_.body_windows);
    kv_u64("bytes", stats_.body_bytes);
    kv_u64("verdicts", stats_.body_verdicts);
    kv_u64("fail_open", stats_.body_fail_open);
    kv_u64("h2_skipped", stats_.body_h2_skipped);
    kv_u64("awaiting", body_awaiting_.size());
    out += "}";
    out += ", \"ring\": {";
    kv_u64("enqueued", tel[0], true);
    kv_u64("enqueue_full", tel[1]);
    kv_u64("dequeued", tel[2]);
    kv_u64("depth", tel[3]);
    kv_u64("depth_hwm", tel[4]);
    kv_u64("verdicts_posted", tel[5]);
    kv_u64("verdict_post_full", tel[6]);
    kv_u64("wait_sum_ms", tel[7]);
    out += "}}";
    return out;
  }

  // Prometheus text exposition, metric names shared with the Python
  // plane (pingoo_tpu/obs/schema.py — the parity test's contract).
  std::string metrics_prometheus() {
    uint64_t tel[PINGOO_TELEMETRY_WORDS];
    pingoo_ring_telemetry_snapshot(ring_, tel);
    size_t pooled = 0;
    for (const auto& kv : upstream_pool_) pooled += kv.second.size();
    const std::string plane = "{plane=\"native\"}";
    std::string out;
    auto metric = [&out, &plane](const char* type, const char* name,
                                 uint64_t v) {
      out += "# TYPE ";
      out += name;
      out += " ";
      out += type;
      out += "\n";
      out += name;
      out += plane;
      out += " " + std::to_string(v) + "\n";
    };
    metric("counter", "pingoo_requests_total", stats_.requests);
    metric("counter", "pingoo_blocked_total", stats_.blocked);
    metric("counter", "pingoo_captcha_total", stats_.captcha);
    metric("counter", "pingoo_fail_open_total", stats_.fail_open);
    metric("counter", "pingoo_ua_rejected_total", stats_.ua_rejected);
    metric("counter", "pingoo_no_service_total", stats_.no_service);
    metric("counter", "pingoo_upstream_fail_total", stats_.upstream_fail);
    metric("counter", "pingoo_upstream_tls_fail_total",
           stats_.upstream_tls_fail);
    metric("counter", "pingoo_verdicts_total", stats_.verdicts);
    metric("gauge", "pingoo_connections", conns_.size());
    metric("gauge", "pingoo_pooled_upstreams", pooled);
    // Sidecar supervision (ISSUE 10): sidecar_up stays 0 until a
    // heartbeat has ever landed, so "no sidecar yet" and "sidecar
    // died" alert the same way; epoch counts (re)attaches.
    metric("gauge", "pingoo_sidecar_up",
           (sidecar_seen_ && !degraded_) ? 1 : 0);
    metric("gauge", "pingoo_degraded_mode", degraded_ ? 1 : 0);
    metric("gauge", "pingoo_sidecar_epoch", sidecar_epoch_);
    metric("counter", "pingoo_degraded_entered_total",
           stats_.degraded_entered);
    // Streaming body inspection (ISSUE 13, obs/schema.py BODY_METRICS;
    // the carry-depth histogram is scanner-side and lives on the
    // sidecar's exposition). Degrades carry the caller-side reasons.
    metric("counter", "pingoo_body_windows_total", stats_.body_windows);
    metric("counter", "pingoo_body_bytes_total", stats_.body_bytes);
    metric("gauge", "pingoo_body_flows_active", body_awaiting_.size());
    out += "# TYPE pingoo_body_degrade_total counter\n";
    out += "pingoo_body_degrade_total{plane=\"native\",reason=\"fail_open\"} " +
           std::to_string(stats_.body_fail_open) + "\n";
    out += "pingoo_body_degrade_total{plane=\"native\",reason=\"h2\"} " +
           std::to_string(stats_.body_h2_skipped) + "\n";
    metric("counter", "pingoo_ring_enqueued_total", tel[0]);
    metric("counter", "pingoo_ring_enqueue_full_total", tel[1]);
    metric("counter", "pingoo_ring_dequeued_total", tel[2]);
    metric("gauge", "pingoo_ring_depth", tel[3]);
    metric("gauge", "pingoo_ring_depth_hwm", tel[4]);
    metric("counter", "pingoo_ring_verdicts_posted_total", tel[5]);
    metric("counter", "pingoo_ring_verdict_post_full_total", tel[6]);
    // Verdict wait histogram (enqueue -> verdict-apply), shared bucket
    // bounds with the Python plane's pingoo_verdict_wait_ms.
    static const char* kLe[7] = {"1", "2", "5", "10", "50", "100", "1000"};
    out += "# TYPE pingoo_verdict_wait_ms histogram\n";
    uint64_t cum = 0, total = 0;
    for (int i = 0; i < 8; ++i) total += stats_.wait_hist[i];
    for (int i = 0; i < 7; ++i) {
      cum += stats_.wait_hist[i];
      out += "pingoo_verdict_wait_ms_bucket{plane=\"native\",le=\"";
      out += kLe[i];
      out += "\"} " + std::to_string(cum) + "\n";
    }
    out += "pingoo_verdict_wait_ms_bucket{plane=\"native\",le=\"+Inf\"} " +
           std::to_string(total) + "\n";
    out += "pingoo_verdict_wait_ms_sum" + plane + " " +
           std::to_string(stats_.wait_sum_ms) + "\n";
    out += "pingoo_verdict_wait_ms_count" + plane + " " +
           std::to_string(total) + "\n";
    return out;
  }

  // Accept-negotiated body + content type: Prometheus text by default
  // (what a scraper's GET or plain curl sees), the back-compat JSON
  // under Accept: application/json.
  static bool accept_wants_json(const Parsed& p) {
    return p.accept.find("application/json") != std::string::npos;
  }

  std::string metrics_negotiated(const Parsed& p, const char** ctype) {
    if (accept_wants_json(p)) {
      *ctype = "application/json";
      return metrics_body();
    }
    *ctype = "text/plain; version=0.0.4; charset=utf-8";
    return metrics_prometheus();
  }

  std::string metrics_response(const Parsed& p) {
    const char* ctype = nullptr;
    std::string body = metrics_negotiated(p, &ctype);
    return "HTTP/1.1 200 OK\r\nserver: pingoo\r\ncontent-type: " +
           std::string(ctype) + "\r\ncontent-length: " +
           std::to_string(body.size()) + "\r\nconnection: close\r\n\r\n" +
           body;
  }

  // -- flight recorder -------------------------------------------------------
  // Bounded ring of the last kFlightN requests that reached a verdict
  // decision (ISSUE 5): the ring ticket (this plane's correlation id,
  // joins sidecar-side records at trace id "t-<ticket>"), the enqueue
  // -> apply wait, the raw verdict byte, the decided action, and a
  // sanitized method/path prefix with an FNV-1a digest over the tuple
  // fields. Served as JSON at /__pingoo/flightrecorder (h1 + h2) and
  // dumped to stderr when the SIGTERM drain starts — the native-plane
  // counterpart of pingoo_tpu/obs/flightrecorder.py.

  struct FlightEntry {
    uint64_t ticket = UINT64_MAX;  // UINT64_MAX = no ring ticket
    uint64_t wait_ms = 0;          // enqueue -> verdict apply (0 = n/a)
    uint64_t ts_ms = 0;            // CLOCK_MONOTONIC ms at record time
    uint32_t digest = 0;           // FNV-1a over method|host|path|ua
    uint8_t verdict = 0;           // raw verdict byte from the ring
    uint8_t decided = 0;           // 0 proxy 1 block 2 captcha 3 fail-open
    char method[8] = {0};
    char path[48] = {0};           // sanitized prefix, for humans
  };
  static constexpr size_t kFlightN = 256;
  FlightEntry flight_[kFlightN];
  uint64_t flight_next_ = 0;

  static uint32_t fnv1a(uint32_t h, const std::string& s) {
    for (unsigned char ch : s) {
      h ^= ch;
      h *= 16777619u;
    }
    return h;
  }

  void flight_record(const Parsed& req, uint64_t ticket, uint64_t enq_ms,
                     uint8_t verdict, uint8_t decided) {
    FlightEntry& e = flight_[flight_next_++ % kFlightN];
    uint64_t now = now_ms();
    e.ticket = ticket;
    e.wait_ms = enq_ms ? now - enq_ms : 0;
    e.ts_ms = now;
    uint32_t h = 2166136261u;
    h = fnv1a(h, req.method);
    h = fnv1a(h, req.host);
    h = fnv1a(h, req.path);
    h = fnv1a(h, req.user_agent);
    e.digest = h;
    std::snprintf(e.method, sizeof(e.method), "%s", req.method.c_str());
    // The stored path is display-only: JSON-hostile bytes (quotes,
    // backslash, controls, non-ASCII) become '_' at record time so the
    // dump below can emit it verbatim.
    size_t n = 0;
    for (char ch : req.path) {
      if (n + 1 >= sizeof(e.path)) break;
      e.path[n++] =
          (ch >= 0x20 && ch < 0x7f && ch != '"' && ch != '\\') ? ch : '_';
    }
    e.path[n] = 0;
    e.verdict = verdict;
    e.decided = decided;
  }

  std::string flightrecorder_json() {
    uint64_t total = flight_next_;
    size_t live = total < kFlightN ? static_cast<size_t>(total) : kFlightN;
    uint64_t start = total - live;
    std::string out = "{\"plane\": \"native\", \"capacity\": " +
                      std::to_string(kFlightN) +
                      ", \"recorded_total\": " + std::to_string(total) +
                      ", \"entries\": [";
    for (size_t i = 0; i < live; ++i) {
      const FlightEntry& e = flight_[(start + i) % kFlightN];
      if (i) out += ", ";
      out += "{\"ticket\": ";
      out += e.ticket == UINT64_MAX ? std::string("null")
                                    : std::to_string(e.ticket);
      char digest_hex[16];
      std::snprintf(digest_hex, sizeof(digest_hex), "%08x", e.digest);
      out += ", \"digest\": \"";
      out += digest_hex;
      out += "\", \"wait_ms\": " + std::to_string(e.wait_ms) +
             ", \"ts_ms\": " + std::to_string(e.ts_ms) +
             ", \"verdict\": " + std::to_string(e.verdict) +
             ", \"decided\": " + std::to_string(e.decided) +
             ", \"method\": \"" + e.method + "\", \"path\": \"" + e.path +
             "\"}";
    }
    out += "]}";
    return out;
  }

  std::string flightrecorder_response() {
    std::string body = flightrecorder_json();
    return "HTTP/1.1 200 OK\r\nserver: pingoo\r\ncontent-type: "
           "application/json\r\ncontent-length: " +
           std::to_string(body.size()) + "\r\nconnection: close\r\n\r\n" +
           body;
  }

  // -- cross-plane timeline (ISSUE 17) ---------------------------------------
  // Chrome-trace JSON synthesized from the SAME FlightEntry stamps the
  // flight recorder keeps: one "verdict_wait" span per recorded request,
  // [ts_ms - wait_ms, ts_ms] on the CLOCK_MONOTONIC timebase the ring
  // and both Python planes share, so tools/timeline_capture.py can
  // merge this dump with /__pingoo/timeline from the Python plane by
  // plain concatenation (same clock; the `clock` block pins it to wall
  // time for offline viewing). No extra hot-path stamps: this endpoint
  // only re-reads what flight_record() already wrote.

  std::string timeline_json() {
    uint64_t total = flight_next_;
    size_t live = total < kFlightN ? static_cast<size_t>(total) : kFlightN;
    uint64_t start = total - live;
    std::string out =
        "{\"displayTimeUnit\": \"ms\", \"clock\": {\"unit\": "
        "\"monotonic_us\", \"monotonic_now_us\": " +
        std::to_string(now_ms() * 1000) +
        ", \"wall_now_s\": " + std::to_string(::time(nullptr)) +
        "}, \"traceEvents\": [{\"ph\": \"M\", \"name\": \"process_name\", "
        "\"pid\": 3, \"tid\": 0, \"args\": {\"name\": \"pingoo:native\"}}";
    for (size_t i = 0; i < live; ++i) {
      const FlightEntry& e = flight_[(start + i) % kFlightN];
      if (!e.ts_ms) continue;
      uint64_t t0_us = (e.ts_ms - e.wait_ms) * 1000;
      out += ", {\"ph\": \"X\", \"pid\": 3, \"tid\": 1, \"name\": "
             "\"verdict_wait\", \"cat\": \"native\", \"ts\": " +
             std::to_string(t0_us) +
             ", \"dur\": " + std::to_string(e.wait_ms * 1000) +
             ", \"args\": {\"trace_id\": ";
      out += e.ticket == UINT64_MAX
                 ? std::string("null")
                 : "\"t-" + std::to_string(e.ticket) + "\"";
      out += ", \"decided\": " + std::to_string(e.decided) +
             ", \"path\": \"" + e.path + "\"}}";
    }
    out += "]}";
    return out;
  }

  std::string timeline_response() {
    std::string body = timeline_json();
    return "HTTP/1.1 200 OK\r\nserver: pingoo\r\ncontent-type: "
           "application/json\r\ncontent-length: " +
           std::to_string(body.size()) + "\r\nconnection: close\r\n\r\n" +
           body;
  }

  // -- graceful drain --------------------------------------------------------
  // SIGTERM stops accepting and drains in-flight requests with a hard
  // cap (reference drains with a 20 s limit, listeners/mod.rs:28 +
  // http_listener.rs:111-116). main() owns the drain state and calls
  // this every loop iteration once the listener is closed.

  // Close connections with no request in flight; returns live count.
  // Busy connections finish their response, return to kReadingHead,
  // and are collected on the next tick.
  size_t drain_tick() {
    for (Conn* c : conns_) {
      if (c->dead) continue;
      if (c->state == ConnState::kReadingHead && c->inbuf.empty() &&
          c->outbuf.empty())
        mark_close(c);
      else if (c->state == ConnState::kH2 && c->h2_streams.empty() &&
               c->h2_ready.empty() && c->outbuf.empty())
        // Idle h2 connection: no stream being serviced or queued. An
        // abrupt close (no GOAWAY) is within spec for shutdown; clients
        // retry idempotent requests on a fresh connection.
        mark_close(c);
    }
    flush_doomed();
    return conns_.size();
  }

  void sweep_idle() {
    for (Conn* c : conns_) {
      if (c->dead) continue;
      time_t idle = now_ - c->last_active;
      switch (c->state) {
        case ConnState::kHandshake:
        case ConnState::kReadingHead:
        case ConnState::kClosing:
          if (idle > kIdleTimeoutS) mark_close(c);
          break;
        case ConnState::kAwaitingVerdict:
          // Verdict deadlines are ms-granularity and handled by
          // sweep_verdict_deadlines() every event-loop pass; nothing
          // to do on the 1 s tick.
          break;
        case ConnState::kProxying:
          if (idle > kProxyIdleTimeoutS) mark_close(c);
          break;
        case ConnState::kTunnel:
          if (tcp_mode_ && !c->upstream_connected && c->upstream_fd < 0) {
            start_tcp_proxy(c);  // parked retry: re-dial this sweep
            break;
          }
          if (tcp_mode_ && !c->upstream_connected && c->upstream_fd >= 0 &&
              now_ - c->tcp_connect_at > kTcpConnectTimeoutS) {
            tcp_proxy_fail(c);  // reference: 3 s connect timeout/try
            break;
          }
          // WebSockets idle legitimately (pings may be minutes apart).
          if (idle > kTunnelIdleS) mark_close(c);
          break;
        case ConnState::kH2:
          // Streams stuck awaiting verdicts fail open on their own
          // ms-granularity timers in sweep_verdict_deadlines().
          if (idle > kProxyIdleTimeoutS) mark_close(c);
          break;
      }
    }
  }

  // -- transport (plain / TLS) ----------------------------------------------

  // >0 bytes, 0 clean EOF, -1 would-block, -2 error.
  ssize_t t_read(Conn* c, char* buf, size_t n) {
    if (c->ssl == nullptr) {
      ssize_t r = read(c->fd, buf, n);
      if (r > 0) return r;
      if (r == 0) return 0;
      return (errno == EAGAIN || errno == EWOULDBLOCK) ? -1 : -2;
    }
    int r = SSL_read(c->ssl, buf, static_cast<int>(n));
    if (r > 0) return r;
    int err = SSL_get_error(c->ssl, r);
    ERR_clear_error();
    if (err == SSL_ERROR_ZERO_RETURN) return 0;
    if (err == SSL_ERROR_WANT_READ) return -1;
    if (err == SSL_ERROR_WANT_WRITE) {
      c->ssl_want_write = true;
      return -1;
    }
    return -2;
  }

  ssize_t t_write(Conn* c, const char* buf, size_t n) {
    if (c->ssl == nullptr) {
      ssize_t w = send(c->fd, buf, n, MSG_NOSIGNAL);
      if (w >= 0) return w;
      return (errno == EAGAIN || errno == EWOULDBLOCK) ? -1 : -2;
    }
    int w = SSL_write(c->ssl, buf, static_cast<int>(n));
    if (w > 0) return w;
    int err = SSL_get_error(c->ssl, w);
    ERR_clear_error();
    if (err == SSL_ERROR_WANT_WRITE) {
      c->ssl_want_write = true;
      return -1;
    }
    if (err == SSL_ERROR_WANT_READ) return -1;
    return -2;
  }

  // Flush c->outbuf to the client; false = connection error.
  bool flush_out(Conn* c) {
    while (!c->outbuf.empty()) {
      ssize_t w = t_write(c, c->outbuf.data(), c->outbuf.size());
      if (w > 0) {
        c->outbuf.erase(0, static_cast<size_t>(w));
      } else if (w == -1) {
        break;
      } else {
        return false;
      }
    }
    return true;
  }

  void update_client_events(Conn* c) {
    uint32_t ev = 0;
    switch (c->state) {
      case ConnState::kHandshake:
      case ConnState::kReadingHead:
        ev = EPOLLIN;
        break;
      case ConnState::kAwaitingVerdict:
        // Verdict quiesce — except under streaming body inspection
        // (ISSUE 13), which keeps pulling body bytes (bounded by the
        // hold cap) while the verdicts compute.
        if (c->body_inspect && !c->body_final_sent && !c->client_eof &&
            c->inbuf.size() < kMaxBuffered)
          ev = EPOLLIN;
        break;
      case ConnState::kProxying:
        // Level-triggered epoll: a half-closed or backpressured client
        // with EPOLLIN armed would wake the loop forever — disarm the
        // read side at EOF / at the buffered cap.
        if (!c->client_eof && c->inbuf.size() < kMaxBuffered) ev = EPOLLIN;
        break;
      case ConnState::kTunnel:
        if (!c->client_eof && c->upbuf.size() < kMaxBuffered) ev = EPOLLIN;
        break;
      case ConnState::kH2:
        // Frame ingest continues while a stream verdicts/proxies (other
        // streams keep multiplexing in).
        if (!c->client_eof) ev = EPOLLIN;
        break;
      case ConnState::kClosing:
        ev = 0;
        break;
    }
    if (!c->outbuf.empty() || c->ssl_want_write) ev |= EPOLLOUT;
    epoll_event e{};
    e.events = ev;
    e.data.ptr = &c->client_ref;
    epoll_ctl(ep_, EPOLL_CTL_MOD, c->fd, &e);
  }

  void update_upstream_events(Conn* c) {
    if (c->upstream_fd < 0) return;
    uint32_t ev = 0;
    if (c->up_tls_hs) {
      // Arm exactly the wanted direction: EPOLLOUT is level-triggered
      // "almost always ready", so arming it while the handshake wants
      // bytes would spin the loop.
      ev = c->up_hs_want_write ? EPOLLOUT : EPOLLIN;
    } else {
      // Same level-trigger discipline: stop reading an EOF'd upstream
      // and pause reads while the client-side buffer is at its cap.
      bool can_read = !c->upstream_eof && c->outbuf.size() < kMaxBuffered;
      if (can_read) ev = EPOLLIN;
      if (!c->upbuf.empty() || !c->upstream_connected) ev |= EPOLLOUT;
      if (c->up_rd_want_write) ev |= EPOLLOUT;
      if (c->up_wr_want_read) ev |= EPOLLIN;
      // Records already decrypted inside the SSL object do not show on
      // the fd, so epoll alone cannot resume a read paused for
      // backpressure: queue an explicit resume once there is room.
      if (can_read && c->up_ssl != nullptr && SSL_pending(c->up_ssl) > 0)
        queue_ssl_resume(c, 0);
    }
    epoll_event e{};
    e.events = ev;
    e.data.ptr = &c->upstream_ref;
    epoll_ctl(ep_, EPOLL_CTL_MOD, c->upstream_fd, &e);
  }

  // Queue a canned response and switch to drain-then-close.
  void respond_close(Conn* c, const char* response) {
    c->outbuf.append(response);
    c->state = ConnState::kClosing;
    if (!flush_out(c)) {
      mark_close(c);
      return;
    }
    if (c->outbuf.empty()) {
      mark_close(c);
      return;
    }
    update_client_events(c);
  }

  void close_upstream(Conn* c) {
    if (c->up_h2 != nullptr) {
      delete c->up_h2;
      c->up_h2 = nullptr;
    }
    if (c->up_ssl != nullptr) {
      SSL_shutdown(c->up_ssl);  // best-effort close_notify (nonblocking)
      SSL_free(c->up_ssl);
      ERR_clear_error();
      c->up_ssl = nullptr;
    }
    if (c->upstream_fd >= 0) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, c->upstream_fd, nullptr);
      close(c->upstream_fd);
      c->upstream_fd = -1;
    }
    reset_up_link(c);
  }

  void reset_up_link(Conn* c) {
    c->up_proto_pending = false;
    c->up_head.clear();
    c->upstream_connected = false;
    c->upstream_eof = false;
    c->up_trunc = false;
    c->up_tcp_ok = false;
    c->up_tls_hs = false;
    c->up_hs_want_write = false;
    c->up_rd_want_write = false;
    c->up_wr_want_read = false;
  }

  // -- upstream TLS client ---------------------------------------------------
  // The connector's client side of the reference's pooled hyper-rustls
  // client (http_proxy_service.rs:54-71): verified-by-default TLS with
  // SNI + hostname (or IP-SAN) checks against the table's server name.

  static constexpr ssize_t kIoAgain = -1;  // would block (want flags set)
  static constexpr ssize_t kIoErr = -2;    // fatal transport error

  bool up_tls_begin(const UpTarget& t, int fd, SSL** out,
                    bool offer_h2 = true) {
    if (up_ctx_ == nullptr) return false;
    SSL* ssl = SSL_new(up_ctx_);
    if (ssl == nullptr) return false;
    SSL_set_fd(ssl, fd);
    SSL_set_connect_state(ssl);
    const char* name = t.sni.c_str();
    in_addr probe{};
    bool name_ok;
    if (inet_pton(AF_INET, name, &probe) == 1) {
      // Literal-address target: verify against an IP SAN, no SNI
      // (RFC 6066 §3 forbids literal addresses in server_name).
      name_ok = X509_VERIFY_PARAM_set1_ip_asc(SSL_get0_param(ssl), name) == 1;
    } else {
      name_ok = SSL_set1_host(ssl, name) == 1 &&
                SSL_set_tlsext_host_name_shim(ssl, name) == 1;
    }
    if (!name_ok) {
      // Proceeding would handshake with chain-but-no-name verification
      // — a silent downgrade; fail the hop instead (502).
      SSL_free(ssl);
      ERR_clear_error();
      return false;
    }
    if (!tcp_mode_ && offer_h2) {
      // Offer h2 like the reference's hyper-rustls client
      // (http_proxy_service.rs:54-71); the upstream picks. tcp mode
      // splices raw bytes, where ALPN is not ours to negotiate, and
      // upgrade (WebSocket) requests must stay h1 — a 101 tunnel
      // cannot ride an h2 hop, so the caller pins h1 for those.
      static const unsigned char kAlpn[] = "\x02h2\x08http/1.1";
      SSL_set_alpn_protos(ssl, kAlpn, sizeof(kAlpn) - 1);
    }
    *out = ssl;
    return true;
  }

  // Drive the client handshake: 1 done, 0 in progress, -1 fatal (which
  // includes certificate verification failures; SSL_VERIFY_PEER makes
  // OpenSSL abort the handshake on an untrusted or name-mismatched
  // chain).
  static int up_tls_step(SSL* ssl, bool* want_write) {
    ERR_clear_error();
    int r = SSL_do_handshake(ssl);
    if (r == 1) return 1;
    int e = SSL_get_error(ssl, r);
    if (e == SSL_ERROR_WANT_READ) {
      *want_write = false;
      return 0;
    }
    if (e == SSL_ERROR_WANT_WRITE) {
      *want_write = true;
      return 0;
    }
    return -1;
  }

  // send/recv with the same EAGAIN discipline whether the link is
  // plaintext or TLS. Cross-direction wants (renegotiation-free TLS 1.3
  // still hits them on KeyUpdate) are surfaced through the flags so the
  // event mask can arm the other direction.
  static ssize_t up_send_raw(int fd, SSL* ssl, const void* p, size_t n,
                             bool* wr_want_read) {
    if (ssl == nullptr) {
      ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
      if (w >= 0) return w;
      if (errno == EAGAIN || errno == EWOULDBLOCK) return kIoAgain;
      return kIoErr;
    }
    ERR_clear_error();
    int w = SSL_write(ssl, p, static_cast<int>(n));
    if (w > 0) return w;
    int e = SSL_get_error(ssl, w);
    if (e == SSL_ERROR_WANT_WRITE) return kIoAgain;
    if (e == SSL_ERROR_WANT_READ) {
      *wr_want_read = true;
      return kIoAgain;
    }
    return kIoErr;
  }

  static ssize_t up_recv_raw(int fd, SSL* ssl, void* p, size_t n,
                             bool* rd_want_write) {
    if (ssl == nullptr) {
      ssize_t r = read(fd, p, n);
      if (r >= 0) return r;
      if (errno == EAGAIN || errno == EWOULDBLOCK) return kIoAgain;
      return kIoErr;
    }
    ERR_clear_error();
    int r = SSL_read(ssl, p, static_cast<int>(n));
    if (r > 0) return r;
    int e = SSL_get_error(ssl, r);
    if (e == SSL_ERROR_ZERO_RETURN) return 0;  // clean close_notify
    if (e == SSL_ERROR_WANT_READ) return kIoAgain;
    if (e == SSL_ERROR_WANT_WRITE) {
      *rd_want_write = true;
      return kIoAgain;
    }
    // SSL_ERROR_SYSCALL with ret==0 is a TCP FIN without close_notify:
    // an unauthenticated party able to inject a FIN could otherwise
    // truncate a response and have it forwarded as a complete one.
    // Treat it as an error so it 502s / aborts instead (rustls surfaces
    // the same condition as UnexpectedEof).
    return kIoErr;
  }

  // A pooled upstream died before sending ANY response bytes: replay

  // the request once on a fresh connection (false when not applicable).
  bool try_pooled_retry(Conn* c) {
    if (!c->upstream_pooled || c->up_replay.empty()) return false;
    if (!c->resp_head_buf.empty() || c->resp_head_done)
      return false;  // response started: not safe to replay
    close_upstream(c);
    int ufd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (ufd < 0 ||
        (connect(ufd, reinterpret_cast<const sockaddr*>(&c->up_target.sa),
                 sizeof(c->up_target.sa)) != 0 &&
         errno != EINPROGRESS)) {
      if (ufd >= 0) close(ufd);
      return false;
    }
    c->upstream_fd = ufd;
    c->upstream_pooled = false;  // one retry only
    reset_up_link(c);  // a TLS target re-handshakes on the fresh socket
    c->upbuf = c->up_replay;
    epoll_event ue{};
    ue.events = EPOLLOUT | EPOLLIN;
    ue.data.ptr = &c->upstream_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, ufd, &ue);
    update_client_events(c);
    return true;
  }

  // h1 502 (h2 streams fail through h2_respond_simple). Tears the
  // failed upstream down FIRST so a retry/new proxy never races an fd
  // still registered in epoll.
  void respond_502(Conn* c) {
    if (tcp_mode_) {
      // No HTTP on this plane: connect-phase failures retry, mid-
      // stream failures drop the connection (the reference's
      // copy_bidirectional just ends on error).
      tcp_proxy_fail(c);
      return;
    }
    if (try_pooled_retry(c)) return;
    stats_.upstream_fail++;
    close_upstream(c);
    respond_close(c, k502);
  }

  // Abort one h2 stream without fabricating a response (e.g. a
  // truncated upstream body must NOT become a well-formed short 200).
  void h2_abort_stream(Conn* c, int32_t sid) {
    nghttp2_submit_rst_stream(c->h2, 0, sid, NGHTTP2_INTERNAL_ERROR);
    h2_flush(c);
  }

  // -- upstream connection pool ----------------------------------------------
  // Completed keep-alive upstream responses park their connection here
  // for reuse by the next request to the same target — the reference
  // proxies through a pooled client (http_proxy_service.rs:54-71);
  // connection-per-request measurably caps the whole data plane at the
  // loopback connect rate. Idle entries are validated with a MSG_PEEK
  // probe on pop (a server that closed the idle conn is detected before
  // any request bytes are risked) and expired by the sweep.

  struct PooledUpstream {
    int fd;
    SSL* ssl;  // non-null: an established TLS client session
    std::string sni;  // the name the session was verified for
    time_t since;
    UpH2Link* h2link = nullptr;  // non-null: an established h2 session
  };
  static constexpr size_t kPoolPerTarget = 256;
  static constexpr time_t kPoolIdleS = 30;

  static uint64_t target_key(const UpTarget& t) {
    uint64_t key =
        (static_cast<uint64_t>(t.sa.sin_addr.s_addr) << 16) | t.sa.sin_port;
    if (t.tls) {
      key |= 1ULL << 63;
      key ^= std::hash<std::string>{}(t.sni) & 0x7FFF000000000000ULL;
    }
    if (t.h2) key |= 1ULL << 62;  // h1 and h2:// pools must never mix:
    // a pooled h1 keep-alive socket handed to an h2 request would get
    // a client preface mid-session (and vice versa)
    return key;
  }

  // Drain whatever session frames an idle pooled h2 connection has
  // pending (PING, SETTINGS, GOAWAY) through its nghttp2 session.
  // Returns false when the session is no longer usable.
  static bool h2_pool_prefeed(PooledUpstream* pc) {
    char buf[4096];
    std::string sink;  // no stream is open: nothing synthesizes
    for (;;) {
      ssize_t r;
      if (pc->ssl != nullptr) {
        ERR_clear_error();
        int rr = SSL_read(pc->ssl, buf, sizeof(buf));
        if (rr <= 0) {
          int e = SSL_get_error(pc->ssl, rr);
          if (e == SSL_ERROR_WANT_READ) break;  // drained
          return false;  // close_notify / FIN / error
        }
        r = rr;
      } else {
        r = recv(pc->fd, buf, sizeof(buf), MSG_DONTWAIT);
        if (r == 0) return false;
        if (r < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          return false;
        }
      }
      if (!pc->h2link->feed(buf, static_cast<size_t>(r), &sink))
        return false;
    }
    return !pc->h2link->goaway && !pc->h2link->failed;
  }

  bool pop_pooled(const UpTarget& t, PooledUpstream* out) {
    auto it = upstream_pool_.find(target_key(t));
    if (it == upstream_pool_.end()) return false;
    auto& vec = it->second;
    while (!vec.empty()) {
      // The 64-bit key folds the SNI lossily; a hash alias must never
      // hand out a session verified for a different name, so entries
      // are matched exactly (LIFO over the matching entries).
      size_t pick = vec.size();
      for (size_t i = vec.size(); i-- > 0;) {
        if (vec[i].sni == t.sni) {
          pick = i;
          break;
        }
      }
      if (pick == vec.size()) return false;
      PooledUpstream pc = vec[pick];
      vec.erase(vec.begin() + pick);
      if (pc.ssl != nullptr) {
        // SSL_peek processes buffered records (quietly consuming
        // TLS 1.3 session tickets): on an h1 link app data means a
        // poisoned connection; on an h2 link pending bytes are session
        // frames — feed them through the session NOW so an idle-drain
        // GOAWAY is detected here instead of 502ing the next request
        // (the h1 path covers the same race with pooled replay, which
        // h2 links do not carry).
        char probe;
        ERR_clear_error();
        int r = SSL_peek(pc.ssl, &probe, 1);
        bool alive =
            r <= 0 && SSL_get_error(pc.ssl, r) == SSL_ERROR_WANT_READ;
        if (!alive && r > 0 && pc.h2link != nullptr)
          alive = h2_pool_prefeed(&pc);
        ERR_clear_error();
        if (alive) {
          *out = pc;
          return true;
        }
        SSL_free(pc.ssl);
        ERR_clear_error();
        close(pc.fd);
        if (pc.h2link != nullptr) delete pc.h2link;
        continue;
      }
      char probe;
      ssize_t r = recv(pc.fd, &probe, 1, MSG_PEEK | MSG_DONTWAIT);
      bool alive = r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK);
      if (!alive && r > 0 && pc.h2link != nullptr)
        alive = h2_pool_prefeed(&pc);
      if (alive) {
        *out = pc;
        return true;
      }
      close(pc.fd);  // closed by the server, or stray bytes: unusable
      if (pc.h2link != nullptr) delete pc.h2link;
    }
    return false;
  }

  void release_upstream(Conn* c) {
    auto& vec = upstream_pool_[c->up_key];
    if (c->up_key == 0 || vec.size() >= kPoolPerTarget) {
      close_upstream(c);
      return;
    }
    epoll_ctl(ep_, EPOLL_CTL_DEL, c->upstream_fd, nullptr);
    vec.push_back(PooledUpstream{c->upstream_fd, c->up_ssl,
                                 c->up_target.sni, now_, c->up_h2});
    c->upstream_fd = -1;
    c->up_ssl = nullptr;
    c->up_h2 = nullptr;  // session ownership moved into the pool entry
    reset_up_link(c);
  }

  void sweep_pool() {
    for (auto& kv : upstream_pool_) {
      auto& vec = kv.second;
      size_t keep = 0;
      for (size_t i = 0; i < vec.size(); ++i) {
        if (now_ - vec[i].since > kPoolIdleS) {
          if (vec[i].ssl != nullptr) {
            SSL_shutdown(vec[i].ssl);
            SSL_free(vec[i].ssl);
            ERR_clear_error();
          }
          close(vec[i].fd);
          if (vec[i].h2link != nullptr) delete vec[i].h2link;
        } else {
          vec[keep++] = vec[i];
        }
      }
      vec.resize(keep);
    }
  }

  // Adopt (or create) an h2 session for this connection's upstream
  // link and frame the rewritten request onto it.
  bool begin_upstream_h2(Conn* c, UpH2Link* link) {
    if (c->req.is_upgrade()) {
      // Protocol upgrades (WebSocket) cannot ride an h2 upstream hop.
      if (link != nullptr) delete link;
      stats_.upstream_fail++;
      close_upstream(c);
      respond_close(c, k502);
      return false;
    }
    if (link == nullptr) {
      link = new UpH2Link();
      if (!link->init()) {
        delete link;
        stats_.upstream_fail++;
        close_upstream(c);
        respond_close(c, k502);
        return false;
      }
    } else {
      link->reset_for_reuse();
    }
    c->up_h2 = link;
    bool has_body = !c->req_body.done;
    if (!link->submit(c->up_head, c->up_target.tls, has_body) ||
        !link->pump_send(&c->upbuf)) {
      stats_.upstream_fail++;
      close_upstream(c);  // deletes the link
      respond_close(c, k502);
      return false;
    }
    // Pooled-retry replay is h1-shaped (raw byte replay); an h2 link
    // would need a fresh stream submission instead — disabled.
    c->up_replay.clear();
    c->upstream_pooled = false;
    return true;
  }

  void finish_upstream_send_setup(Conn* c) {
    pump_request_body(c);
    if (c->up_h2 == nullptr) {
      // A POOLED connection can die between the liveness probe and our
      // write (server idle-timeout race). Keep the sent bytes around
      // so the request can be replayed once on a FRESH connection
      // instead of surfacing a spurious 502 (the reference's pooled
      // client retries the same way). Oversized bodies disable it.
      c->up_replay = c->upbuf;
      if (c->up_replay.size() > kMaxReplay) {
        c->up_replay.clear();
        c->upstream_pooled = false;
      }
    }
  }

  void start_proxy(Conn* c, const UpTarget& target) {
    uint64_t key = target_key(target);
    if (target.tls && up_ctx_ == nullptr) {
      stats_.upstream_fail++;
      close_upstream(c);
      respond_close(c, k502);
      return;
    }
    PooledUpstream pc{-1, nullptr, std::string(), 0};
    bool pooled = pop_pooled(target, &pc);
    if (pooled && pc.h2link != nullptr && c->req.is_upgrade()) {
      // Upgrades must ride h1: hand the h2 session back and dial a
      // fresh connection whose ALPN offer is pinned to http/1.1.
      upstream_pool_[key].push_back(pc);
      pooled = false;
      pc = PooledUpstream{-1, nullptr, std::string(), 0};
    }
    int ufd = pc.fd;
    if (!pooled) {
      ufd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
      if (ufd < 0 ||
          (connect(ufd, reinterpret_cast<const sockaddr*>(&target.sa),
                   sizeof(target.sa)) != 0 &&
           errno != EINPROGRESS)) {
        if (ufd >= 0) close(ufd);
        respond_502(c);
        return;
      }
    }
    c->upstream_fd = ufd;
    c->up_key = key;
    c->up_target = target;
    c->upstream_pooled = pooled;
    reset_up_link(c);
    c->up_ssl = pooled ? pc.ssl : nullptr;
    c->upstream_connected = pooled;  // pooled TLS links are post-handshake
    c->up_tcp_ok = pooled;
    c->upstream_keep = false;
    c->upstream_junk = false;
    c->up_shut = false;
    c->resp_head_buf.clear();
    c->resp_head_done = false;
    c->last_active = now_;

    c->state = ConnState::kProxying;
    c->up_head = rewrite_request_head(
        c->req, c->peer_ip, c->ssl != nullptr,
        target.internal ? internal_token_ : std::string());
    // Upstream protocol: h2 for table-marked h2:// targets and pooled
    // h2 sessions; ALPN decides fresh TLS links after the handshake
    // (reference hyper client, http_proxy_service.rs:54-71).
    if (pooled && pc.h2link != nullptr) {
      if (!begin_upstream_h2(c, pc.h2link)) return;
    } else if (target.h2) {
      if (!begin_upstream_h2(c, nullptr)) return;
    } else if (target.tls && !pooled) {
      c->up_proto_pending = true;  // decided at handshake completion
    } else {
      c->upbuf = c->up_head;
    }
    if (!c->up_proto_pending) finish_upstream_send_setup(c);

    epoll_event ue{};
    ue.events = EPOLLOUT | EPOLLIN;
    ue.data.ptr = &c->upstream_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, ufd, &ue);
    update_client_events(c);
  }

  // Raw client->upstream splice for an accepted protocol upgrade.
  void on_tunnel_client_event(Conn* c, uint32_t events) {
    c->last_active = now_;
    if (events & EPOLLIN) {
      char buf[16384];
      for (;;) {
        if (c->upbuf.size() > kMaxBuffered) break;  // backpressure
        ssize_t r = t_read(c, buf, sizeof(buf));
        if (r > 0) {
          c->upbuf.append(buf, static_cast<size_t>(r));
        } else if (r == 0) {
          c->client_eof = true;
          break;
        } else if (r == -1) {
          break;
        } else {
          mark_close(c);
          return;
        }
      }
      flush_upstream(c);
    }
    if (events & EPOLLOUT) {
      c->ssl_want_write = false;
      if (!flush_out(c)) {
        mark_close(c);
        return;
      }
    }
    // Half-close propagation in both directions (tcp mode closes only
    // when both sides finished; WebSocket tunnels close as a unit).
    tunnel_check_done(c);
    if (c->dead) return;
    update_client_events(c);
    update_upstream_events(c);
  }

  // Move request-body bytes from inbuf into upbuf per the framer.
  void pump_request_body(Conn* c) {
    if (c->up_proto_pending) return;  // body buffers raw in inbuf until
                                      // ALPN picks the upstream framing
    if (c->req_body_forwarded) return;
    if (c->up_h2 != nullptr) {
      if (!c->inbuf.empty() && !c->req_body.done &&
          c->up_h2->body.size() < kMaxBuffered) {
        // The bound: nghttp2 flow control (64 KB windows) holds body
        // bytes in the link, not upbuf, so the upbuf cap alone cannot
        // backpressure a slow h2 upstream. Leaving bytes in inbuf
        // engages the client-read gate (kProxying arms EPOLLIN only
        // below the inbuf cap).
        std::string payload;  // h2 DATA carries the DE-FRAMED body
        size_t take =
            c->req_body.consume(c->inbuf.data(), c->inbuf.size(), &payload);
        if (!payload.empty())
          c->up_h2->append_body(payload.data(), payload.size());
        c->inbuf.erase(0, take);
      }
      if (c->req_body.bad) {
        mark_close(c);
        return;
      }
      if (c->req_body.done && !c->req_body_forwarded) {
        c->req_body_forwarded = true;
        c->up_h2->finish_body();
      }
      c->up_h2->pump_send(&c->upbuf);
      return;
    }
    if (!c->inbuf.empty() && !c->req_body.done) {
      size_t take = c->req_body.consume(c->inbuf.data(), c->inbuf.size());
      c->upbuf.append(c->inbuf, 0, take);
      if (c->upstream_pooled) {
        c->up_replay.append(c->inbuf, 0, take);
        if (c->up_replay.size() > kMaxReplay) {
          c->up_replay.clear();
          c->upstream_pooled = false;  // too big to replay: no retry
        }
      }
      c->inbuf.erase(0, take);
    }
    if (c->req_body.bad) {  // malformed chunked framing mid-stream
      mark_close(c);
      return;
    }
    if (c->req_body.done) c->req_body_forwarded = true;
  }

  // -- streaming body inspection (ISSUE 13, docs/BODY_STREAMING.md) ----------
  //
  // With PINGOO_BODY_INSPECT=on, an h1 request whose head enqueued a
  // ring ticket ALSO streams its de-framed body to the ring's body
  // slots as bounded windows while the connection holds in
  // kAwaitingVerdict (the verdict quiesce normally disarms client
  // reads; inspection re-arms them under the kMaxBuffered hold cap).
  // The raw bytes stay in inbuf untouched — the post-dispatch
  // pump_request_body path forwards them exactly as before — so a
  // failed inspection degrades coverage, never framing. The sidecar
  // posts the flow's verdict on the SHARED verdict ring with bit 63
  // set; apply_verdict holds a proxy-decided metadata verdict until it
  // lands, then merges (engine/bodyscan.py merge_actions semantics).
  // Every error path — body ring full, hold cap overflow, degraded
  // mode, verdict deadline, malformed framing — fails OPEN to
  // metadata-only verdicts. h2 client streams are not inspected this
  // iteration (counted: body_h2_skipped).

  // Twin of engine/bodyscan.py merge_actions: the metadata plane's
  // nonzero unverified lane (bits 0-1) wins, verified-block (bit 2)
  // ORs, route bits (3-7) ride the metadata verdict unchanged.
  static uint8_t merge_body_action(uint8_t meta, uint8_t body) {
    uint8_t unverified = (meta & 3) ? (meta & 3) : (body & 3);
    return static_cast<uint8_t>((meta & 0xf8) | ((meta | body) & 4) |
                                unverified);
  }

  // Reset inspection state and drop the verdict-demux entry.
  void body_clear(Conn* c) {
    if (c->body_flow != UINT64_MAX) body_awaiting_.erase(c->body_flow);
    c->body_inspect = false;
    c->body_flow = UINT64_MAX;
    c->body_scan = BodyFramer();
    c->body_win.clear();
    c->body_win_seq = 0;
    c->body_total = 0;
    c->body_raw_seen = 0;
    c->body_final_sent = false;
    c->body_fin_ms = 0;
    c->meta_pending = false;
    c->meta_action = 0;
    c->body_verdict_done = false;
    c->body_action = 0;
  }

  // Tear down inspection; a best-effort ABORT window lets the sidecar
  // free its per-flow carry state immediately instead of waiting out
  // the flow TTL. Safe on conns that were never armed.
  void body_abort(Conn* c) {
    if (!c->body_inspect) return;
    if (!c->body_final_sent)
      pingoo_ring_enqueue_body(ring_, c->body_flow, c->body_win_seq,
                               c->body_total, nullptr, 0,
                               PINGOO_BODY_FLAG_ABORT);
    body_clear(c);
  }

  // Stop inspecting this flow and unblock the request: the stashed
  // metadata verdict (if any) applies alone — fail open, never stall.
  void body_fail_open(Conn* c) {
    stats_.body_fail_open++;
    uint64_t ticket = c->body_flow;
    bool meta = c->meta_pending;
    uint8_t action = c->meta_action;
    body_abort(c);
    if (meta && !c->dead) apply_verdict(c, action, ticket);
  }

  // Degraded-mode entry: no sidecar is alive to answer FINAL windows.
  void body_fail_open_all() {
    if (body_awaiting_.empty()) return;
    std::vector<Conn*> flows;
    flows.reserve(body_awaiting_.size());
    for (const auto& kv : body_awaiting_) flows.push_back(kv.second);
    for (Conn* c : flows)
      if (!c->dead && c->body_inspect) body_fail_open(c);
  }

  // Feed raw inbuf bytes past body_raw_seen through the scan framer,
  // window the de-framed payload, and enqueue full windows. The framer
  // stops at the message boundary, so pipelined next-request bytes are
  // never scanned.
  void body_scan_pump(Conn* c) {
    if (!c->body_inspect || c->body_final_sent) return;
    if (c->body_raw_seen < c->inbuf.size() && !c->body_scan.done) {
      std::string payload;
      size_t take = c->body_scan.consume(c->inbuf.data() + c->body_raw_seen,
                                         c->inbuf.size() - c->body_raw_seen,
                                         &payload);
      c->body_raw_seen += take;
      if (!payload.empty()) {
        c->body_win.append(payload);
        c->body_total += payload.size();
      }
    }
    if (c->body_scan.bad) {
      // Malformed framing: the real framer hits the same bytes after
      // dispatch and closes the connection — just stop inspecting.
      body_fail_open(c);
      return;
    }
    while (c->body_inspect &&
           (c->body_win.size() >= PINGOO_BODY_WINDOW_CAP ||
            (c->body_scan.done && !c->body_final_sent))) {
      uint32_t n = static_cast<uint32_t>(
          std::min<size_t>(c->body_win.size(), PINGOO_BODY_WINDOW_CAP));
      bool fin = c->body_scan.done && n == c->body_win.size();
      int rc = pingoo_ring_enqueue_body(
          ring_, c->body_flow, c->body_win_seq, c->body_total,
          c->body_win.data(), n, fin ? PINGOO_BODY_FLAG_FINAL : 0);
      if (rc != 0) {  // body ring full: degrade this flow
        body_fail_open(c);
        return;
      }
      c->body_win_seq++;
      stats_.body_windows++;
      stats_.body_bytes += n;
      c->body_win.erase(0, n);
      if (fin) {
        c->body_final_sent = true;
        c->body_fin_ms = now_ms();
      }
    }
  }

  // Arm inspection for this h1 cycle: the head already enqueued the
  // ring ticket (flow id), pipelined body bytes may already sit in
  // inbuf. Called only under kBodyInspect && !degraded_.
  void body_arm(Conn* c) {
    c->body_inspect = true;
    c->body_flow = c->ticket;
    if (c->req.chunked) c->body_scan.reset_chunked();
    else c->body_scan.reset_cl(c->req.content_length);
    body_awaiting_[c->body_flow] = c;
    stats_.body_flows++;
    body_scan_pump(c);
    // EOF already seen with the body incomplete: it can never finish.
    if (c->body_inspect && c->client_eof && !c->body_scan.done)
      body_fail_open(c);
  }

  // Client readable while kAwaitingVerdict with inspection armed: pull
  // body bytes into inbuf (they stay there for the post-verdict pump)
  // and stream windows. Distinct from on_client_readable: no head
  // parsing, and the hold cap fails inspection open instead of closing
  // the connection.
  void on_body_readable(Conn* c) {
    c->last_active = now_;
    char buf[16384];
    while (c->body_inspect && !c->body_final_sent &&
           c->inbuf.size() < kMaxBuffered) {
      ssize_t r = t_read(c, buf, sizeof(buf));
      if (r > 0) {
        c->inbuf.append(buf, static_cast<size_t>(r));
        body_scan_pump(c);
      } else if (r == 0) {
        c->client_eof = true;
        if (c->body_inspect && !c->body_scan.done) body_fail_open(c);
        break;
      } else if (r == -1) {
        break;
      } else {
        mark_close(c);
        return;
      }
    }
    // Hold cap reached with the body still incomplete: the remainder
    // cannot buffer pre-verdict — degrade and let the proxy
    // backpressure gates stream it after dispatch.
    if (c->body_inspect && !c->body_scan.done &&
        c->inbuf.size() >= kMaxBuffered)
      body_fail_open(c);
    if (!c->dead) update_client_events(c);
  }

  // A bit-63 verdict from the shared ring: record it; if the metadata
  // verdict is already stashed, merge and finish the request.
  void on_body_verdict(uint64_t flow, uint8_t action) {
    auto it = body_awaiting_.find(flow);
    if (it == body_awaiting_.end()) return;  // died / degraded meanwhile
    Conn* c = it->second;
    body_awaiting_.erase(it);
    if (c->dead || !c->body_inspect) return;
    stats_.body_verdicts++;
    c->body_verdict_done = true;
    c->body_action = action;
    c->body_flow = UINT64_MAX;  // demux entry gone
    if (c->meta_pending) {
      uint8_t meta = c->meta_action;
      c->meta_pending = false;
      apply_verdict(c, meta, flow);  // merges via body_verdict_done
    }
    // else: the metadata verdict is still in flight; apply_verdict
    // merges when it lands.
  }

  // -- verdict flow ---------------------------------------------------------

  void drain_verdicts() {
    uint64_t ticket;
    uint8_t action;
    float score;
    while (pingoo_ring_poll_verdict(ring_, &ticket, &action, &score) == 0) {
      if (ticket & PINGOO_BODY_VERDICT_BIT) {
        on_body_verdict(ticket & ~PINGOO_BODY_VERDICT_BIT, action);
        continue;
      }
      auto it = awaiting_.find(ticket);
      if (it == awaiting_.end()) continue;  // connection died meanwhile
      Conn* c = it->second.conn;
      int32_t sid = it->second.sid;
      awaiting_.erase(it);
      if (c->dead) continue;
      if (sid != 0) {
        auto sit = c->h2_streams.find(sid);
        if (sit == c->h2_streams.end()) continue;  // stream reset meanwhile
        sit->second.ticket = UINT64_MAX;
        apply_h2_verdict(c, sid, action, ticket);
        h2_flush(c);
      } else {
        c->ticket = UINT64_MAX;
        apply_verdict(c, action, ticket);
      }
    }
  }

  // -- sidecar supervision (ISSUE 10, docs/RESILIENCE.md) --------------------
  // Two independent fail-open layers above the ring-full path:
  //   1. sweep_verdict_deadlines(): per-ticket ms-granularity deadline
  //      (kVerdictTimeoutMs) checked every event-loop pass — replaces
  //      the old once-a-second kVerdictTimeoutS sweep whose coarse
  //      clock added up to ~1 s of detection slop.
  //   2. check_sidecar_liveness(): ring-header heartbeat (v5). A stamp
  //      older than kSidecarTimeoutMs flips degraded mode: every
  //      awaiting ticket fails open NOW and run_policy bypasses the
  //      ring entirely, so a dead sidecar costs one detection window
  //      instead of one verdict timeout per request. A fresh heartbeat
  //      (the restarted sidecar's attach bumps the epoch) lifts it.

  // Fail one awaiting ticket open and record it. The awaiting_ entry
  // must already be erased (or never inserted) by the caller.
  void fail_open_ticket(Conn* c, int32_t sid, uint64_t ticket) {
    stats_.fail_open++;
    if (sid != 0) {
      auto sit = c->h2_streams.find(sid);
      if (sit == c->h2_streams.end()) return;  // stream reset meanwhile
      sit->second.ticket = UINT64_MAX;
      flight_record(sit->second.p, ticket, sit->second.enq_ms, 0, 3);
      h2_stream_fail_open(c, sid);
      h2_flush(c);
    } else {
      c->ticket = UINT64_MAX;
      body_abort(c);  // dispatching without a verdict: stop inspecting
      flight_record(c->req, ticket, c->enq_ms, 0, 3);
      fail_open_proxy(c);
    }
  }

  void sweep_verdict_deadlines() {
    sweep_body_deadlines();
    if (awaiting_.empty()) return;
    uint64_t now = now_ms();
    if (now == last_deadline_sweep_ms_) return;  // at most one pass per ms
    last_deadline_sweep_ms_ = now;
    // Collect first: fail_open_ticket mutates conns/streams and must
    // not run under the awaiting_ iterator.
    expired_.clear();
    for (const auto& kv : awaiting_) {
      const Awaiting& aw = kv.second;
      uint64_t enq = 0;
      if (aw.sid != 0) {
        auto sit = aw.conn->h2_streams.find(aw.sid);
        if (sit != aw.conn->h2_streams.end()) enq = sit->second.enq_ms;
      } else {
        enq = aw.conn->enq_ms;
      }
      if (enq != 0 && now - enq > kVerdictTimeoutMs)
        expired_.push_back(kv.first);
    }
    for (uint64_t ticket : expired_) {
      auto it = awaiting_.find(ticket);
      if (it == awaiting_.end()) continue;
      Awaiting aw = it->second;
      awaiting_.erase(it);
      if (aw.conn->dead) continue;
      fail_open_ticket(aw.conn, aw.sid, ticket);
    }
  }

  // A request whose metadata verdict already said "proxy" is blocked
  // solely on the body verdict once its FINAL window is enqueued; the
  // same kVerdictTimeoutMs budget bounds that wait (ISSUE 13).
  void sweep_body_deadlines() {
    if (body_awaiting_.empty()) return;
    uint64_t now = now_ms();
    body_expired_.clear();
    for (const auto& kv : body_awaiting_) {
      Conn* c = kv.second;
      if (c->meta_pending && c->body_fin_ms != 0 &&
          now - c->body_fin_ms > kVerdictTimeoutMs)
        body_expired_.push_back(c);
    }
    for (Conn* c : body_expired_)
      if (!c->dead && c->body_inspect) body_fail_open(c);
  }

  void fail_open_all_awaiting() {
    std::vector<std::pair<uint64_t, Awaiting>> inflight;
    inflight.reserve(awaiting_.size());
    for (const auto& kv : awaiting_) inflight.push_back(kv);
    awaiting_.clear();
    for (const auto& kv : inflight) {
      if (kv.second.conn->dead) continue;
      fail_open_ticket(kv.second.conn, kv.second.sid, kv.first);
    }
  }

  bool degraded() const { return degraded_; }

  void check_sidecar_liveness() {
    if (kSidecarTimeoutMs == 0 || tcp_mode_) return;
    uint64_t lv[5];  // epoch, heartbeat_ms, posted_floor, req_tail, now_ms
    pingoo_ring_liveness(ring_, lv);
    sidecar_epoch_ = lv[0];
    // Bootstrap: until a sidecar has ever attached (heartbeat 0) the
    // per-request deadline governs — flipping degraded here would only
    // mask a missing sidecar during bring-up.
    if (lv[1] == 0) return;
    sidecar_seen_ = true;
    uint64_t age = lv[4] > lv[1] ? lv[4] - lv[1] : 0;
    bool stale = age > kSidecarTimeoutMs;
    if (stale && !degraded_) {
      degraded_ = true;
      stats_.degraded_entered++;
      std::fprintf(stderr,
                   "pingoo-httpd: DEGRADED (sidecar heartbeat %llu ms stale, "
                   "epoch %llu); failing %zu awaiting ticket(s) open\n",
                   static_cast<unsigned long long>(age),
                   static_cast<unsigned long long>(lv[0]),
                   awaiting_.size());
      flight_record_transition("degraded-enter");
      fail_open_all_awaiting();
      body_fail_open_all();  // no sidecar will answer FINAL windows
    } else if (!stale && degraded_) {
      degraded_ = false;
      std::fprintf(stderr,
                   "pingoo-httpd: RECOVERED (sidecar epoch %llu heartbeat "
                   "fresh); resuming ring enqueues\n",
                   static_cast<unsigned long long>(lv[0]));
      flight_record_transition("degraded-exit");
    }
  }

  // Degrade/recover transitions land in the flight recorder as
  // synthetic SYS entries so /__pingoo/flightrecorder shows them
  // inline with the requests they affected.
  void flight_record_transition(const char* what) {
    Parsed p;
    p.method = "SYS";
    p.path = std::string("/") + what;
    flight_record(p, UINT64_MAX, 0, 0, 3);
  }

  // Verdict byte: bits 0-1 unverified action, bit 2 verified-block
  // (native_ring.py RingSidecar) — the reference loop skips Captcha
  // actions for verified clients but still blocks on Block
  // (http_listener.rs:251-264). Applies to the h1 cycle or the h2
  // connection's active stream.
  void apply_verdict(Conn* c, uint8_t action, uint64_t ticket = UINT64_MAX) {
    if (c->body_inspect) {
      if (c->body_verdict_done) {
        action = merge_body_action(action, c->body_action);
        body_clear(c);
      } else {
        uint8_t meta_decided =
            c->captcha_verified ? ((action & 4) ? 1 : 0) : (action & 3);
        if (meta_decided == 0) {
          // Metadata says proxy: hold the request until the body
          // verdict (or its fail-open) completes the picture; body
          // windows keep streaming meanwhile.
          c->meta_pending = true;
          c->meta_action = action;
          return;
        }
        body_abort(c);  // metadata alone decides: cancel inspection
      }
    }
    stats_.verdicts++;
    if (c->enq_ms) record_wait(now_ms() - c->enq_ms);
    uint8_t decided;  // 0 proxy, 1 block, 2 captcha
    if (c->captcha_verified) {
      decided = (action & 4) ? 1 : 0;
    } else {
      decided = action & 3;
    }
    flight_record(c->req, ticket, c->enq_ms, action, decided);
    if (decided == 1) {
      stats_.blocked++;
      respond_close(c, k403);
    } else if (decided == 2) {
      stats_.captcha++;
      respond_close(c, kCaptcha);
    } else {
      dispatch_route(c, (action >> 3) & 0x1f);
    }
  }

  void apply_h2_verdict(Conn* c, int32_t sid, uint8_t action,
                        uint64_t ticket = UINT64_MAX) {
    stats_.verdicts++;
    H2Stream& st = c->h2_streams[sid];
    if (st.enq_ms) record_wait(now_ms() - st.enq_ms);
    uint8_t decided = st.verified ? ((action & 4) ? 1 : 0) : (action & 3);
    flight_record(st.p, ticket, st.enq_ms, action, decided);
    if (decided == 1) {
      stats_.blocked++;
      h2_respond_simple(c, sid, 403, "Forbidden");
    } else if (decided == 2) {
      stats_.captcha++;
      h2_respond_redirect(c, sid);
    } else {
      h2_dispatch_route(c, sid, (action >> 3) & 0x1f);
    }
  }

  // -- request cycle --------------------------------------------------------

  void begin_request_cycle(Conn* c) {
    body_abort(c);  // stray inspection state never crosses cycles
    c->state = ConnState::kReadingHead;
    c->req = Parsed();
    c->req_body = BodyFramer();
    c->req_body_forwarded = false;
    c->captcha_verified = false;
    c->resp_head_buf.clear();
    c->resp_head_done = false;
    c->resp_body = BodyFramer();
    c->close_after_response = false;
    // Pipelined bytes may already hold the next request.
    if (!c->inbuf.empty() || c->client_eof) try_process_head(c, c->client_eof);
    if (!c->dead && c->state == ConnState::kReadingHead)
      update_client_events(c);
  }

  void on_client_readable(Conn* c) {
    c->last_active = now_;
    char buf[16384];
    bool eof = false;
    for (;;) {
      ssize_t r = t_read(c, buf, sizeof(buf));
      if (r > 0) {
        size_t old = c->inbuf.size();
        c->inbuf.append(buf, static_cast<size_t>(r));
        if (c->inbuf.size() > kMaxReqHead + kMaxBuffered) {
          mark_close(c);
          return;
        }
        // Stop draining once a full head is buffered: the request
        // BODY must flow under the proxy states' backpressure gates —
        // a fast client front-loading a multi-MB upload would
        // otherwise blow the inbuf cap before proxying even starts.
        // (The h2 preface contains its own CRLFCRLF, so h2 handoff
        // breaks here too and the h2 machinery takes over.)
        if (c->inbuf.find("\r\n\r\n", old > 3 ? old - 3 : 0) !=
            std::string::npos)
          break;
      } else if (r == 0) {
        eof = true;
        break;
      } else if (r == -1) {
        break;
      } else {
        mark_close(c);
        return;
      }
    }
    try_process_head(c, eof);
  }

  void try_process_head(Conn* c, bool eof) {
    if (c->state != ConnState::kReadingHead) {
      if (eof && c->state != ConnState::kProxying &&
          c->state != ConnState::kH2)
        mark_close(c);
      return;
    }
    // HTTP/2 detection: every h2 client (ALPN-negotiated or cleartext
    // prior knowledge) opens with the 24-byte preface (RFC 7540 §3.5),
    // mirroring the reference's hyper auto h1/h2 builder.
    size_t cmp = std::min(c->inbuf.size(), kH2PrefaceLen);
    if (cmp > 0 && std::memcmp(c->inbuf.data(), kH2Preface, cmp) == 0) {
      if (c->inbuf.size() < kH2PrefaceLen) {
        if (eof) mark_close(c);
        return;  // wait for the full preface
      }
      if (!start_h2(c)) {
        mark_close(c);
        return;
      }
      std::string initial;
      initial.swap(c->inbuf);
      h2_pump(c, initial.data(), initial.size());
      return;
    }
    size_t head_end = c->inbuf.find("\r\n\r\n");
    if (head_end == std::string::npos) {
      if (c->inbuf.size() > kMaxReqHead) {
        // 431, not 400: the Python listener plane answers its
        // PINGOO_MAX_HEADER_BYTES breach the same way (parity test in
        // tests/test_fuzz_corpus.py).
        respond_close(c, k431);
        return;
      }
      if (eof) mark_close(c);  // EOF before a complete head
      return;
    }
    if (head_end + 4 > kMaxReqHead) {
      respond_close(c, k431);
      return;
    }
    Parsed p = parse_head(c->inbuf.substr(0, head_end + 4));
    if (!p.ok) {
      respond_close(c, k400);
      return;
    }
    c->inbuf.erase(0, head_end + 4);
    c->req = p;
    if (++c->requests_served > kMaxRequestsPerConn) c->req.keep_alive = false;

    // A Transfer-Encoding we cannot frame (anything but chunked), a
    // malformed/duplicated Content-Length, TE and CL together, obsolete
    // header folding, or a malformed field line would desync the proxy
    // from the upstream: refuse them (RFC 9112 §6.1/§6.3 smuggling
    // rules; RFC 7230 §3.2.4). The Python listener plane applies the
    // identical gate (host/httpd.py strict_head_violation) so the
    // differential fuzzer holds both to one behavior.
    if ((p.has_transfer_encoding && !p.chunked) || p.bad_content_length ||
        (p.has_transfer_encoding && p.has_content_length) || p.obs_fold ||
        p.bad_header) {
      respond_close(c, k400);
      return;
    }
    // Declared body beyond the cap: refuse before framing starts (the
    // Python plane enforces the same PINGOO_MAX_BODY_BYTES with 413).
    if (p.has_content_length && p.content_length > kMaxBodyBytes) {
      respond_close(c, k413);
      return;
    }
    // Request body framing (bytes beyond it are the NEXT request and
    // are never forwarded with this one).
    if (p.chunked) {
      c->req_body.reset_chunked();
    } else if (p.content_length > 0) {
      c->req_body.reset_cl(p.content_length);
    } else {
      c->req_body.reset_none();
    }
    c->req_body_forwarded = c->req_body.done;

    if (c->req.path == "/__pingoo/metrics") {
      respond_close(c, metrics_response(c->req).c_str());
      return;
    }
    if (c->req.path == "/__pingoo/flightrecorder") {
      respond_close(c, flightrecorder_response().c_str());
      return;
    }
    if (c->req.path == "/__pingoo/timeline") {
      respond_close(c, timeline_response().c_str());
      return;
    }
    Policy outcome = run_policy(c);
    switch (outcome) {
      case Policy::kBlock:
        respond_close(c, k403);
        return;
      case Policy::kCaptchaRedirect:
        respond_close(c, kCaptcha);
        return;
      case Policy::kCaptchaUpstream:
        {
          UpTarget t;
          t.sa = captcha_upstream_;
          t.internal = true;
          start_proxy(c, t);
        }
        return;
      case Policy::kFailOpenProxy:
        stats_.fail_open++;
        flight_record(c->req, UINT64_MAX, 0, 0, 3);  // 3 = fail-open
        fail_open_proxy(c);
        return;
      case Policy::kAwaitVerdict:
        c->state = ConnState::kAwaitingVerdict;
        // Streaming body inspection (ISSUE 13): a body-bearing request
        // also streams windows to the sidecar while it holds here.
        if (kBodyInspect && !degraded_ && !c->req_body.done) body_arm(c);
        update_client_events(c);  // quiesce until the verdict arrives
        return;
    }
  }

  // The shared per-request WAF policy (reference hot path,
  // http_listener.rs:196-264): UA gate, host cap, captcha-path routing,
  // cookie verification, ring enqueue. Protocol-agnostic — the h1 cycle
  // and the h2 stream loop both act on the returned decision. Reads
  // c->req; sets c->captcha_verified and, for kAwaitVerdict,
  // c->ticket + the awaiting_ map entry.
  enum class Policy {
    kBlock,            // 403 (UA gate or captcha upstream missing)
    kCaptchaRedirect,  // redirect to the challenge
    kCaptchaUpstream,  // proxy to the control plane
    kFailOpenProxy,    // ring full: proxy without a verdict
    kAwaitVerdict,     // enqueued; verdict callback decides
  };

  Policy run_policy(Conn* c, int32_t sid = 0) {
    stats_.requests++;
    Parsed& req = sid != 0 ? c->h2_streams[sid].p : c->req;
    // Empty or oversized UA -> 403 before the ring. The >= is the
    // reference's own explicit check (http_listener.rs:196).
    if (req.user_agent.empty() || req.user_agent.size() >= 256) {
      stats_.ua_rejected++;
      return Policy::kBlock;
    }
    // Over-long host becomes EMPTY, not truncated (get_host,
    // http_listener.rs:284-296).
    if (req.host.size() > 256) req.host.clear();

    // Captcha endpoints bypass rules and go to the control plane — and
    // they come BEFORE the cookie gate, exactly like the reference
    // (http_listener.rs:200-204 precede :222-236), or a client with a
    // stale cookie could never reach the challenge to clear it.
    if (req.path.compare(0, 17, "/__pingoo/captcha") == 0)
      return has_captcha_upstream_ ? Policy::kCaptchaUpstream
                                   : Policy::kBlock;

    // Captcha-verified cookie (Ed25519 JWT against the shared JWKS).
    // An INVALID present cookie serves the challenge immediately
    // (reference http_listener.rs:222-236) — here: redirect.
    std::string client_id = captcha_client_id(
        c->peer_ip, req.user_agent, req.host);
    if (gate_ != nullptr) gate_->maybe_reload(now_);
    bool verified = false;
    if (!req.verified_cookie.empty() && gate_ != nullptr &&
        gate_->available()) {
      if (gate_->verify(req.verified_cookie, client_id, now_)) {
        verified = true;
      } else {
        return Policy::kCaptchaRedirect;
      }
    }
    if (sid != 0) c->h2_streams[sid].verified = verified;
    else c->captcha_verified = verified;

    // Degraded fast-path (stale sidecar heartbeat): don't enqueue a
    // ticket no one will answer — fail open immediately instead of
    // stalling the request for a verdict timeout.
    if (degraded_) return Policy::kFailOpenProxy;

    uint8_t ip[16] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0xff, 0xff, 0, 0, 0, 0};
    in_addr v4{};
    inet_pton(AF_INET, c->peer_ip, &v4);
    std::memcpy(ip + 12, &v4, 4);
    char country[2] = {'X', 'X'};
    uint64_t ticket = pingoo_ring_enqueue_request(
        ring_, req.method.data(), req.method.size(), req.host.data(),
        req.host.size(), req.path.data(), req.path.size(),
        req.target.data(), req.target.size(), req.user_agent.data(),
        req.user_agent.size(), ip, c->peer_port, 0, country);
    if (ticket == UINT64_MAX) {
      // Verdict ring full (sidecar stalled): FAIL OPEN — proxy without
      // a verdict (pingoo/rules.rs:41-44).
      return Policy::kFailOpenProxy;
    }
    if (sid != 0) {
      H2Stream& st = c->h2_streams[sid];
      st.ticket = ticket;
      st.verdict_at = now_;
      st.enq_ms = now_ms();
    } else {
      c->ticket = ticket;
      c->verdict_at = now_;
      c->enq_ms = now_ms();
    }
    awaiting_[ticket] = Awaiting{c, sid};
    return Policy::kAwaitVerdict;
  }

  // -- HTTP/2 mode -----------------------------------------------------------
  //
  // nghttp2 owns framing/HPACK/flow control; requests surface through
  // the callbacks below and run the SAME run_policy/ring path as h1.
  // Streams are serviced CONCURRENTLY — each proxied stream owns an
  // upstream socket and a streaming DATA provider, so responses flow
  // as the upstream delivers them (no whole-body buffering) and a slow
  // stream never blocks its siblings (reference: hyper auto builder,
  // http_listener.rs:276-278).

  bool start_h2(Conn* c) {
    nghttp2_session_callbacks* cbs = nullptr;
    if (nghttp2_session_callbacks_new(&cbs) != 0) return false;
    nghttp2_session_callbacks_set_on_header_callback(cbs, h2_on_header);
    nghttp2_session_callbacks_set_on_frame_recv_callback(cbs,
                                                         h2_on_frame_recv);
    nghttp2_session_callbacks_set_on_data_chunk_recv_callback(
        cbs, h2_on_data_chunk);
    nghttp2_session_callbacks_set_on_stream_close_callback(
        cbs, h2_on_stream_close);
    // MANUAL receive-window management (no_auto_window_update +
    // nghttp2_session_consume): streamed request bodies only open the
    // client's send window as the UPSTREAM drains, so a slow upstream
    // backpressures the client through h2 flow control instead of
    // forcing a buffer-or-reset choice here.
    nghttp2_option* opt = nullptr;
    if (nghttp2_option_new(&opt) != 0) {
      nghttp2_session_callbacks_del(cbs);
      return false;
    }
    nghttp2_option_set_no_auto_window_update(opt, 1);
    int rv = nghttp2_session_server_new2(&c->h2, cbs, c, opt);
    nghttp2_option_del(opt);
    nghttp2_session_callbacks_del(cbs);
    if (rv != 0) return false;
    // Bound per-connection stream state: without this SETTINGS entry
    // RFC 7540 defaults to UNLIMITED concurrent streams — one client
    // could park thousands of buffered requests (the h1 plane's
    // kMaxHead/kMaxRequestsPerConn caps would be bypassed).
    nghttp2_settings_entry iv[] = {
        {NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS, 128}};
    nghttp2_submit_settings(c->h2, 0, iv, 1);
    // Upload head-of-line blocking: with manual window management, one
    // stream whose body is debt-parked behind a slow upstream holds its
    // received-but-unconsumed bytes against BOTH windows — and the
    // connection-level window defaults to the same 64KB as one stream,
    // so a single parked upload could close the shared window for every
    // other stream on the connection. Raise the connection window to
    // several per-stream windows so per-stream flow control is the
    // binding limit and siblings keep flowing.
    nghttp2_session_set_local_window_size(c->h2, NGHTTP2_FLAG_NONE, 0,
                                          kH2ConnRecvWindow);
    c->state = ConnState::kH2;
    return true;
  }

  // Feed bytes to the session, service ready streams, flush output.
  void h2_pump(Conn* c, const char* data, size_t len) {
    if (len > 0) {
      ssize_t n = nghttp2_session_mem_recv(
          c->h2, reinterpret_cast<const uint8_t*>(data), len);
      if (n < 0 || static_cast<size_t>(n) != len) {
        mark_close(c);
        return;
      }
    }
    h2_process_next(c);
    h2_flush(c);
    if (!c->dead && !nghttp2_session_want_read(c->h2) &&
        !nghttp2_session_want_write(c->h2))
      mark_close(c);  // session finished (GOAWAY processed)
  }

  void h2_flush(Conn* c) {
    // Client-side backpressure: stop pulling frames out of nghttp2 once
    // outbuf is at the cap — a client that raises its flow-control
    // windows but never reads its socket must not grow outbuf without
    // bound (streamed DATA bypasses the per-stream pending cap the
    // moment it leaves `pending`). nghttp2 keeps the frames queued;
    // the client-socket EPOLLOUT path resumes the drain.
    while (c->outbuf.size() < kMaxBuffered) {
      const uint8_t* out = nullptr;
      ssize_t n = nghttp2_session_mem_send(c->h2, &out);
      if (n <= 0) break;
      c->outbuf.append(reinterpret_cast<const char*>(out),
                       static_cast<size_t>(n));
    }
    if (!flush_out(c)) {
      mark_close(c);
      return;
    }
    update_client_events(c);
    // outbuf may have drained below the cap: re-arm upstream reads that
    // h2_update_stream_events paused on the outbuf gate.
    if (c->outbuf.size() < kMaxBuffered) {
      for (auto& [sid, st] : c->h2_streams) {
        if (st.up_fd >= 0 && st.up_ref != nullptr)
          h2_update_stream_events(c, st);
      }
    }
  }

  // Service every completed stream CONCURRENTLY — each proxied stream
  // gets its own upstream socket, so a slow stream never head-of-line
  // blocks the connection (reference: hyper multiplexes streams,
  // http_listener.rs:276). The upstream-socket count per connection is
  // capped; excess ready streams wait their turn in h2_ready.
  void h2_process_next(Conn* c) {
    // First hand freed upstream slots to streams whose verdict already
    // said proxy.
    while (!c->h2_proxy_wait.empty() &&
           c->h2_upstreams < kH2MaxStreamUpstreams) {
      int32_t sid = c->h2_proxy_wait.front();
      c->h2_proxy_wait.erase(c->h2_proxy_wait.begin());
      auto it = c->h2_streams.find(sid);
      if (it == c->h2_streams.end() || !it->second.up_queued) continue;
      it->second.up_queued = false;
      h2_start_stream_proxy(c, sid, it->second.up_target);
    }
    // Policy runs for EVERY ready stream regardless of upstream-slot
    // availability: 403s, captcha redirects, and the metrics endpoint
    // need no upstream, and kAwaitVerdict must enqueue to the verdict
    // ring promptly. Proxy outcomes that hit the per-connection slot
    // cap are parked by h2_start_stream_proxy (h2_proxy_wait) and
    // dispatched as slots free.
    size_t i = 0;
    while (i < c->h2_ready.size()) {
      int32_t sid = c->h2_ready[i];
      c->h2_ready.erase(c->h2_ready.begin() + i);
      auto it = c->h2_streams.find(sid);
      if (it == c->h2_streams.end()) continue;  // reset meanwhile
      if (it->second.p.path == "/__pingoo/metrics") {
        const char* ctype = nullptr;
        std::string body = metrics_negotiated(it->second.p, &ctype);
        h2_submit(c, sid, 200, {{"content-type", ctype}}, std::move(body));
        continue;
      }
      if (it->second.p.path == "/__pingoo/flightrecorder") {
        h2_submit(c, sid, 200, {{"content-type", "application/json"}},
                  flightrecorder_json());
        continue;
      }
      if (it->second.p.path == "/__pingoo/timeline") {
        h2_submit(c, sid, 200, {{"content-type", "application/json"}},
                  timeline_json());
        continue;
      }
      // h2 client streams are not body-inspected this iteration
      // (ISSUE 13, docs/BODY_STREAMING.md): DATA can arrive after the
      // stream dispatches, so a held-verdict design needs per-stream
      // flow accounting first. Counted, metadata-only.
      if (kBodyInspect &&
          (!it->second.body.empty() || !it->second.complete))
        stats_.body_h2_skipped++;
      Policy outcome = run_policy(c, sid);
      switch (outcome) {
        case Policy::kBlock:
          h2_respond_simple(c, sid, 403, "Forbidden");
          break;
        case Policy::kCaptchaRedirect:
          h2_respond_redirect(c, sid);
          break;
        case Policy::kCaptchaUpstream:
          {
            UpTarget t;
            t.sa = captcha_upstream_;
          t.internal = true;
            h2_start_stream_proxy(c, sid, t);
          }
          break;
        case Policy::kFailOpenProxy:
          stats_.fail_open++;
          flight_record(it->second.p, UINT64_MAX, 0, 0, 3);  // fail-open
          h2_stream_fail_open(c, sid);
          break;
        case Policy::kAwaitVerdict:
          break;  // the verdict callback services this stream
      }
    }
  }


  // -- per-stream upstream proxying (concurrent h2) --------------------------

  void h2_close_stream_upstream(Conn* c, H2Stream& st) {
    if (st.up_h2 != nullptr) {
      delete st.up_h2;
      st.up_h2 = nullptr;
    }
    st.up_proto_pending = false;
    if (st.up_ssl != nullptr) {
      SSL_shutdown(st.up_ssl);
      SSL_free(st.up_ssl);
      ERR_clear_error();
      st.up_ssl = nullptr;
    }
    st.up_tcp_ok = false;
    st.up_tls_hs = false;
    st.up_hs_want_write = false;
    st.up_rd_want_write = false;
    st.up_wr_want_read = false;
    if (st.up_fd >= 0) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, st.up_fd, nullptr);
      close(st.up_fd);
      st.up_fd = -1;
      c->h2_upstreams--;
    }
    if (st.up_ref != nullptr) {
      // Events already harvested this batch may still hold the ref:
      // mark it dead and free it after the batch (like doomed conns).
      st.up_ref->h2_sid = -1;
      doomed_refs_.push_back(st.up_ref);
      st.up_ref = nullptr;
    }
    st.up_connected = false;
  }

  void h2_release_stream_resources(Conn* c, H2Stream& st) {
    if (st.ticket != UINT64_MAX) {
      awaiting_.erase(st.ticket);
      st.ticket = UINT64_MAX;
    }
    h2_close_stream_upstream(c, st);
  }

  // Response complete: pool the upstream connection when it is clean,
  // then service streams that were waiting for an upstream slot.
  void h2_stream_finish_upstream(Conn* c, H2Stream& st) {
    bool can_pool = st.resp_body.done &&
                    st.resp_body.mode != BodyFramer::kUntilEof &&
                    !st.up_eof && st.up_keep && !st.up_junk &&
                    st.complete &&  // streamed request body fully in
                    st.upbuf.empty() &&  // request fully sent: an early
                    // response over unsent body bytes would poison the
                    // pooled connection for its next user
                    st.up_key != 0 && st.up_fd >= 0 &&
                    (st.up_h2 == nullptr ||
                     (!st.up_h2->goaway && !st.up_h2->failed)) &&
                    upstream_pool_[st.up_key].size() < kPoolPerTarget;
    if (can_pool) {
      epoll_ctl(ep_, EPOLL_CTL_DEL, st.up_fd, nullptr);
      upstream_pool_[st.up_key].push_back(
          PooledUpstream{st.up_fd, st.up_ssl, st.up_target.sni, now_,
                         st.up_h2});
      st.up_fd = -1;
      st.up_ssl = nullptr;
      st.up_h2 = nullptr;  // ownership moved into the pool entry
      c->h2_upstreams--;
      if (st.up_ref != nullptr) {
        st.up_ref->h2_sid = -1;
        doomed_refs_.push_back(st.up_ref);
        st.up_ref = nullptr;
      }
      st.up_connected = false;
    } else {
      h2_close_stream_upstream(c, st);
    }
    h2_process_next(c);
  }

  void h2_update_stream_events(Conn* c, H2Stream& st) {
    if (st.up_fd < 0 || st.up_ref == nullptr) return;
    uint32_t ev = 0;
    if (st.up_tls_hs) {
      ev = st.up_hs_want_write ? EPOLLOUT : EPOLLIN;
    } else {
      // Read from the upstream only while BOTH buffers have room: the
      // per-stream pending cap bounds de-framed bytes awaiting nghttp2,
      // and the connection outbuf cap bounds bytes a non-reading client
      // has already been framed (h2_flush re-arms when it drains).
      bool can_read = !st.up_eof && st.pending.size() < kH2PendingCap &&
                      c->outbuf.size() < kMaxBuffered;
      if (can_read) ev = EPOLLIN;
      if (!st.upbuf.empty() || !st.up_connected) ev |= EPOLLOUT;
      if (st.up_rd_want_write) ev |= EPOLLOUT;
      if (st.up_wr_want_read) ev |= EPOLLIN;
      if (can_read && st.up_ssl != nullptr && SSL_pending(st.up_ssl) > 0)
        queue_ssl_resume(c, st.up_ref->h2_sid);
    }
    epoll_event e{};
    e.events = ev;
    e.data.ptr = st.up_ref;
    epoll_ctl(ep_, EPOLL_CTL_MOD, st.up_fd, &e);
  }

  // Put the head + whatever body bytes are buffered onto an h1
  // upstream link, with the stream's framing mode applied.
  void h2_stream_attach_h1_body(H2Stream& st) {
    st.upbuf = st.up_head;
    if (st.up_body_chunked) {
      h1_chunk_wrap(&st.upbuf, st.up_body.data(), st.up_body.size());
      if (st.complete) st.upbuf += "0\r\n\r\n";
    } else {
      st.upbuf += st.up_body;
    }
    st.up_body.clear();
  }

  // Adopt (or create) an h2 session for one downstream stream's
  // upstream link; buffered body bytes attach now, later ones stream
  // via h2_stream_body_chunk.
  bool h2_stream_begin_up_h2(Conn* c, int32_t sid, H2Stream& st,
                             UpH2Link* link) {
    if (link == nullptr) {
      link = new UpH2Link();
      if (!link->init()) {
        delete link;
        stats_.upstream_fail++;
        h2_close_stream_upstream(c, st);
        h2_respond_simple(c, sid, 502, "Bad Gateway");
        return false;
      }
    } else {
      link->reset_for_reuse();
    }
    st.up_h2 = link;
    bool has_body = !st.up_body.empty() || !st.complete;
    bool ok = link->submit(st.up_head, st.up_target.tls, has_body);
    if (ok && !st.up_body.empty()) {
      link->append_body(st.up_body.data(), st.up_body.size());
      st.up_body.clear();
    }
    if (ok && st.complete) link->finish_body();
    if (!ok || !link->pump_send(&st.upbuf)) {
      stats_.upstream_fail++;
      h2_close_stream_upstream(c, st);  // deletes the link
      h2_respond_simple(c, sid, 502, "Bad Gateway");
      return false;
    }
    st.up_replay.clear();  // raw-byte replay is h1-shaped: disabled
    st.up_pooled = false;
    return true;
  }

  void h2_start_stream_proxy(Conn* c, int32_t sid,
                             const UpTarget& target) {
    auto it = c->h2_streams.find(sid);
    if (it == c->h2_streams.end()) return;
    H2Stream& st = it->second;
    if (c->h2_upstreams >= kH2MaxStreamUpstreams) {
      // The per-connection upstream cap binds on EVERY dispatch path
      // (verdicts arrive for all ready streams at once): park the
      // stream until a slot frees (h2_process_next drains the queue).
      st.up_target = target;
      st.up_queued = true;
      c->h2_proxy_wait.push_back(sid);
      return;
    }
    uint64_t key = target_key(target);
    if (target.tls && up_ctx_ == nullptr) {
      stats_.upstream_fail++;
      h2_respond_simple(c, sid, 502, "Bad Gateway");
      return;
    }
    PooledUpstream pc{-1, nullptr, std::string(), 0};
    bool pooled = pop_pooled(target, &pc);
    int ufd = pc.fd;
    if (!pooled) {
      ufd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
      if (ufd < 0 ||
          (connect(ufd, reinterpret_cast<const sockaddr*>(&target.sa),
                   sizeof(target.sa)) != 0 &&
           errno != EINPROGRESS)) {
        if (ufd >= 0) close(ufd);
        stats_.upstream_fail++;
        h2_respond_simple(c, sid, 502, "Bad Gateway");
        return;
      }
    }
    st.up_fd = ufd;
    c->h2_upstreams++;  // before any failure path: h2_close_stream_
    // upstream decrements whenever up_fd >= 0, so counting after a
    // fallible step would underflow the cap counter
    st.up_key = key;
    st.up_target = target;
    st.up_pooled = pooled;
    st.up_ssl = pooled ? pc.ssl : nullptr;
    st.up_connected = pooled;
    st.up_tcp_ok = pooled;
    st.up_tls_hs = false;
    st.up_hs_want_write = false;
    st.up_rd_want_write = false;
    st.up_wr_want_read = false;
    st.up_eof = false;
    st.up_trunc = false;
    st.up_keep = false;
    st.up_junk = false;
    st.resp_head_buf.clear();
    st.resp_head_done = false;
    st.resp_body = BodyFramer();
    st.pending.clear();
    st.data_eof = false;
    st.submitted = false;
    // Body framing mode: complete bodies get a derived length;
    // streaming ones pass the client's content-length through or fall
    // back to chunked (decided BEFORE head synthesis).
    st.up_body_chunked = false;
    if (!st.complete) {
      bool has_cl = false;
      for (const auto& kv : st.p.h2_headers)
        if (kv.first == "content-length") has_cl = true;
      st.up_body_chunked = !has_cl;
    }
    st.up_dispatched = true;
    st.up_head = h2_upstream_head(c, st);
    st.up_body = std::move(st.body);  // raw bytes buffered so far
    st.body.clear();
    st.up_proto_pending = false;
    if (pooled && pc.h2link != nullptr) {
      if (!h2_stream_begin_up_h2(c, sid, st, pc.h2link)) return;
    } else if (target.h2) {
      if (!h2_stream_begin_up_h2(c, sid, st, nullptr)) return;
    } else if (target.tls && !pooled) {
      st.up_proto_pending = true;  // ALPN decides after the handshake
    } else {
      h2_stream_attach_h1_body(st);
    }
    if (!st.up_proto_pending && st.up_h2 == nullptr && st.complete) {
      // Replay is a raw byte copy: only a FULLY-KNOWN body can replay.
      st.up_replay = st.upbuf;
      if (st.up_replay.size() > kMaxReplay) {
        st.up_replay.clear();
        st.up_pooled = false;
      }
    } else if (st.up_h2 == nullptr && !st.complete) {
      st.up_replay.clear();
      st.up_pooled = false;
    }
    st.up_ref = new SockRef{c, true, sid};
    epoll_event ue{};
    ue.events = EPOLLOUT | EPOLLIN;
    ue.data.ptr = st.up_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, ufd, &ue);
    // Pre-dispatch bytes may have closed the client's window; now that
    // they are on the forwarding path the drain hook will reopen it —
    // kick once for the case where everything already fits.
    h2_stream_release_window(c, sid, st);
  }

  bool h2_try_stream_retry(Conn* c, int32_t sid, H2Stream& st) {
    if (!st.up_pooled || st.up_replay.empty()) return false;
    if (!st.resp_head_buf.empty() || st.resp_head_done) return false;
    h2_close_stream_upstream(c, st);
    int ufd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (ufd < 0 ||
        (connect(ufd, reinterpret_cast<const sockaddr*>(&st.up_target.sa),
                 sizeof(st.up_target.sa)) != 0 &&
         errno != EINPROGRESS)) {
      if (ufd >= 0) close(ufd);
      return false;
    }
    st.up_fd = ufd;
    st.up_pooled = false;  // one retry only
    st.up_connected = false;  // close already reset the TLS link state
    st.up_eof = false;
    st.up_trunc = false;
    st.upbuf = st.up_replay;
    st.up_ref = new SockRef{c, true, sid};
    c->h2_upstreams++;
    epoll_event ue{};
    ue.events = EPOLLOUT | EPOLLIN;
    ue.data.ptr = st.up_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, ufd, &ue);
    return true;
  }

  // Shared response-header build for the canned and streamed submit
  // paths: ONE copy of the connection-specific-header filter, so the
  // two paths cannot drift (connection-specific headers are illegal in
  // h2, RFC 9113 §8.2.2).
  void h2_submit_response_nva(Conn* c, int32_t sid,
                              const std::string& status,
                              const std::vector<std::pair<std::string,
                                                          std::string>>& hdrs,
                              long long content_length,
                              nghttp2_data_provider* prd) {
    std::vector<nghttp2_nv> nva;
    std::vector<std::string> keep;
    keep.reserve(hdrs.size() * 2 + 8);
    nva.reserve(hdrs.size() + 4);
    auto push = [&](const std::string& n, const std::string& v) {
      keep.push_back(n);
      const std::string& nn = keep.back();
      keep.push_back(v);
      const std::string& vv = keep.back();
      nghttp2_nv nv{};
      nv.name = reinterpret_cast<uint8_t*>(const_cast<char*>(nn.data()));
      nv.value = reinterpret_cast<uint8_t*>(const_cast<char*>(vv.data()));
      nv.namelen = nn.size();
      nv.valuelen = vv.size();
      nv.flags = NGHTTP2_NV_FLAG_NONE;
      nva.push_back(nv);
    };
    push(":status", status);
    for (const auto& kv : hdrs) {
      std::string lname = lower(kv.first);
      if (is_hop_header(lname) || lname == "content-length" ||
          lname == "transfer-encoding" || lname == "server" ||
          lname == "alt-svc" || lname.compare(0, 8, "x-accel-") == 0)
        continue;
      push(lname, kv.second);
    }
    push("server", "pingoo");
    if (content_length >= 0)
      push("content-length", std::to_string(content_length));
    if (nghttp2_submit_response(c->h2, sid, nva.data(), nva.size(), prd) !=
        0)
      c->h2_send.erase(sid);
  }

  // Non-final (1xx) HEADERS: no data provider, stream stays open for
  // the final response. Headers go through the same strip policy as
  // final heads (strip_response_header via parse of the rewritten
  // interim head).
  void h2_submit_interim(Conn* c, int32_t sid, int status,
                         const std::string& head) {
    std::string clean = rewrite_interim_head(head);
    std::vector<std::pair<std::string, std::string>> hdrs;
    parse_header_lines(clean, &hdrs);
    std::vector<nghttp2_nv> nva;
    std::vector<std::string> keep;
    keep.reserve(hdrs.size() * 2 + 2);
    nva.reserve(hdrs.size() + 1);
    auto push = [&](const std::string& n, const std::string& v) {
      keep.push_back(n);
      const std::string& nn = keep.back();
      keep.push_back(v);
      const std::string& vv = keep.back();
      nghttp2_nv nv{};
      nv.name = reinterpret_cast<uint8_t*>(const_cast<char*>(nn.data()));
      nv.value = reinterpret_cast<uint8_t*>(const_cast<char*>(vv.data()));
      nv.namelen = nn.size();
      nv.valuelen = vv.size();
      nv.flags = NGHTTP2_NV_FLAG_NONE;
      nva.push_back(nv);
    };
    push(":status", std::to_string(status));
    for (const auto& kv : hdrs) push(lower(kv.first), kv.second);
    nghttp2_submit_headers(c->h2, 0, sid, nullptr, nva.data(), nva.size(),
                           nullptr);
  }

  // Submit the response HEADERS with a STREAMING data provider: DATA
  // frames flow from st.pending as the upstream delivers bytes (no
  // whole-body buffering; responses larger than memory stream through).
  void h2_submit_streaming(Conn* c, int32_t sid, const RespHead& rh,
                           const std::string& head) {
    std::vector<std::pair<std::string, std::string>> hdrs;
    parse_header_lines(head, &hdrs);
    nghttp2_data_provider prd{};
    prd.read_callback = h2_data_read;
    h2_submit_response_nva(c, sid, std::to_string(rh.status), hdrs,
                           rh.content_length, &prd);
  }

  // Returns false when the stream was aborted/serviced and reading
  // must stop (the H2Stream reference may no longer be valid).
  bool h2_stream_upstream_data(Conn* c, int32_t sid, H2Stream& st,
                               const char* data, size_t len) {
    if (!st.resp_head_done) {
      st.resp_head_buf.append(data, len);
      for (;;) {
        size_t he = st.resp_head_buf.find("\r\n\r\n");
        if (he == std::string::npos) {
          if (st.resp_head_buf.size() > kMaxHead) {
            h2_close_stream_upstream(c, st);
            h2_abort_stream(c, sid);
            return false;
          }
          return true;
        }
        std::string head = st.resp_head_buf.substr(0, he + 4);
        int status = 0;
        if (head.size() >= 12 && head.compare(0, 7, "HTTP/1.") == 0 &&
            head[8] == ' ')
          status = atoi(head.c_str() + 9);
        if (status >= 100 && status < 200) {
          // Forward interim responses as non-final h2 HEADERS (hyper
          // relays them; reference http_listener.rs:276-278), with the
          // same hop-header/identity stripping as final heads. 101 is
          // not representable in h2 — drop it like nghttp2 would.
          if (status != 101) h2_submit_interim(c, sid, status, head);
          st.resp_head_buf.erase(0, he + 4);
          continue;
        }
        std::string rest = st.resp_head_buf.substr(he + 4);
        st.resp_head_buf.clear();
        RespHead rh = rewrite_response_head(head, false);
        if (!rh.ok) {
          h2_close_stream_upstream(c, st);
          stats_.upstream_fail++;
          h2_respond_simple(c, sid, 502, "Bad Gateway");
          h2_process_next(c);
          return false;
        }
        st.up_keep = rh.upstream_keep;
        bool head_only = st.p.method == "HEAD" || rh.status == 204 ||
                         rh.status == 304;
        if (head_only) st.resp_body.reset_none();
        else if (rh.chunked) st.resp_body.reset_chunked();
        else if (rh.content_length >= 0)
          st.resp_body.reset_cl(rh.content_length);
        else st.resp_body.reset_eof();
        st.resp_head_done = true;
        h2_submit_streaming(c, sid, rh, head);
        st.submitted = true;
        if (!rest.empty()) {
          size_t take = st.resp_body.consume(rest.data(), rest.size(),
                                             &st.pending);
          if (take < rest.size()) st.up_junk = true;
          if (st.resp_body.bad) {
            h2_close_stream_upstream(c, st);
            h2_abort_stream(c, sid);
            return false;
          }
          nghttp2_session_resume_data(c->h2, sid);
        }
        return true;
      }
    }
    if (!st.resp_body.done) {
      size_t take = st.resp_body.consume(data, len, &st.pending);
      if (take < len && st.resp_body.done) st.up_junk = true;
      if (st.resp_body.bad) {
        h2_close_stream_upstream(c, st);
        h2_abort_stream(c, sid);
        return false;
      }
      if (st.submitted && !st.pending.empty())
        nghttp2_session_resume_data(c->h2, sid);
    } else if (len > 0) {
      st.up_junk = true;
    }
    return true;
  }

  void h2_stream_check_done(Conn* c, int32_t sid, H2Stream& st) {
    if (!st.resp_head_done) {
      if (st.up_eof) {
        if (h2_try_stream_retry(c, sid, st)) return;
        h2_close_stream_upstream(c, st);
        stats_.upstream_fail++;
        h2_respond_simple(c, sid, 502, "Bad Gateway");
        h2_process_next(c);
      }
      return;
    }
    bool done = st.resp_body.done ||
                (st.resp_body.mode == BodyFramer::kUntilEof && st.up_eof &&
                 !st.up_trunc);
    if (done && !st.data_eof) {
      st.data_eof = true;
      if (st.resp_body.mode == BodyFramer::kUntilEof)
        st.resp_body.done = true;  // EOF framing: input ended the body
      nghttp2_session_resume_data(c->h2, sid);
      h2_stream_finish_upstream(c, st);
      return;
    }
    if (st.up_eof && !st.resp_body.done && !st.data_eof &&
        (st.resp_body.mode != BodyFramer::kUntilEof || st.up_trunc)) {
      // Truncated CL/chunked response — or an EOF-delimited body ended
      // by a transport ERROR (TLS: FIN without close_notify, which an
      // attacker can inject) rather than a clean close: reset the
      // stream so the client sees the failure instead of a
      // certified-complete short body (rustls: UnexpectedEof).
      h2_close_stream_upstream(c, st);
      h2_abort_stream(c, sid);
      h2_process_next(c);
    }
  }

  void h2_stream_upstream_event(Conn* c, int32_t sid, uint32_t events) {
    auto it = c->h2_streams.find(sid);
    if (it == c->h2_streams.end()) return;
    H2Stream& st = it->second;
    if (st.up_fd < 0) return;
    c->last_active = now_;
    if (!st.up_connected) {
      if (!st.up_tcp_ok && (events & (EPOLLOUT | EPOLLERR))) {
        int err = 0;
        socklen_t elen = sizeof(err);
        getsockopt(st.up_fd, SOL_SOCKET, SO_ERROR, &err, &elen);
        if (err != 0) {
          if (!h2_try_stream_retry(c, sid, st)) {
            h2_close_stream_upstream(c, st);
            stats_.upstream_fail++;
            h2_respond_simple(c, sid, 502, "Bad Gateway");
            h2_process_next(c);
          }
          h2_flush(c);
          return;
        }
        st.up_tcp_ok = true;
        if (st.up_target.tls) {
          if (!up_tls_begin(st.up_target, st.up_fd, &st.up_ssl)) {
            h2_close_stream_upstream(c, st);
            stats_.upstream_fail++;
            h2_respond_simple(c, sid, 502, "Bad Gateway");
            h2_process_next(c);
            h2_flush(c);
            return;
          }
          st.up_tls_hs = true;
        } else {
          st.up_connected = true;
        }
      }
      if (st.up_tls_hs) {
        int hs = up_tls_step(st.up_ssl, &st.up_hs_want_write);
        if (hs < 0) {
          stats_.upstream_tls_fail++;
          h2_close_stream_upstream(c, st);
          stats_.upstream_fail++;
          h2_respond_simple(c, sid, 502, "Bad Gateway");
          h2_process_next(c);
          h2_flush(c);
          return;
        }
        if (hs == 0) {
          h2_update_stream_events(c, st);
          return;
        }
        st.up_tls_hs = false;
        st.up_connected = true;
        if (st.up_proto_pending) {
          st.up_proto_pending = false;
          const unsigned char* ap = nullptr;
          unsigned aplen = 0;
          SSL_get0_alpn_selected(st.up_ssl, &ap, &aplen);
          if (aplen == 2 && memcmp(ap, "h2", 2) == 0) {
            if (!h2_stream_begin_up_h2(c, sid, st, nullptr)) {
              h2_flush(c);
              return;
            }
          } else {
            h2_stream_attach_h1_body(st);
            if (st.complete) {
              st.up_replay = st.upbuf;
              if (st.up_replay.size() > kMaxReplay) {
                st.up_replay.clear();
                st.up_pooled = false;
              }
            } else {
              st.up_replay.clear();
              st.up_pooled = false;
            }
          }
        }
      }
      if (!st.up_connected) return;  // TCP connect still pending
    }
    if ((events & EPOLLOUT) || st.up_wr_want_read) {
      while (!st.upbuf.empty() && st.up_connected) {
        st.up_wr_want_read = false;
        ssize_t w = up_send_raw(st.up_fd, st.up_ssl, st.upbuf.data(),
                                st.upbuf.size(), &st.up_wr_want_read);
        if (w > 0) {
          st.upbuf.erase(0, static_cast<size_t>(w));
        } else if (w == kIoAgain) {
          break;
        } else {
          if (!h2_try_stream_retry(c, sid, st)) {
            h2_close_stream_upstream(c, st);
            if (!st.resp_head_done) {
              stats_.upstream_fail++;
              h2_respond_simple(c, sid, 502, "Bad Gateway");
            } else {
              h2_abort_stream(c, sid);
            }
            h2_process_next(c);
          }
          h2_flush(c);
          return;
        }
      }
      // upstream writes drained some backlog: reopen the client's
      // send window if debt was parked on this stream
      h2_stream_release_window(c, sid, st);
    }
    if ((events & EPOLLIN) || st.up_rd_want_write) {
      char buf[16384];
      while (st.up_fd >= 0) {
        if (st.pending.size() > kH2PendingCap) break;  // backpressure
        st.up_rd_want_write = false;
        ssize_t r = up_recv_raw(st.up_fd, st.up_ssl, buf, sizeof(buf),
                                &st.up_rd_want_write);
        if (r > 0 && st.up_h2 != nullptr) {
          std::string synth;
          if (!st.up_h2->feed(buf, static_cast<size_t>(r), &synth)) {
            h2_close_stream_upstream(c, st);
            if (!st.resp_head_done) {
              stats_.upstream_fail++;
              h2_respond_simple(c, sid, 502, "Bad Gateway");
            } else {
              h2_abort_stream(c, sid);
            }
            h2_process_next(c);
            h2_flush(c);
            return;
          }
          st.up_h2->pump_send(&st.upbuf);
          if (!synth.empty() &&
              !h2_stream_upstream_data(c, sid, st, synth.data(),
                                       synth.size())) {
            h2_flush(c);
            return;  // stream aborted/serviced: st may be gone
          }
        } else if (r > 0) {
          if (!h2_stream_upstream_data(c, sid, st, buf,
                                       static_cast<size_t>(r))) {
            h2_flush(c);
            return;  // stream aborted/serviced: st may be gone
          }
        } else if (r == kIoAgain) {
          break;
        } else {
          st.up_eof = true;
          if (r == kIoErr) st.up_trunc = true;  // FIN sans close_notify /
          break;                                // transport error
        }
      }
    }
    if (events & (EPOLLHUP | EPOLLERR)) st.up_eof = true;
    h2_stream_check_done(c, sid, st);
    // After check_done the stream's upstream may be released; the map
    // entry itself survives until nghttp2 closes the stream.
    auto again = c->h2_streams.find(sid);
    if (again != c->h2_streams.end() && again->second.up_fd >= 0)
      h2_update_stream_events(c, again->second);
    h2_flush(c);
  }

  static constexpr long long kClFromBody = -2;  // derive from body.size()

  void h2_submit(Conn* c, int32_t sid, int status,
                 const std::vector<std::pair<std::string, std::string>>&
                     headers,
                 std::string body, long long content_length = kClFromBody) {
    // kClFromBody derives the length from the body; >= 0 overrides it
    // (HEAD advertises the entity size while sending no body); -1
    // omits the header entirely (304 responses).
    if (content_length == kClFromBody)
      content_length = static_cast<long long>(body.size());
    c->h2_send[sid] = {std::move(body), 0};
    nghttp2_data_provider prd{};
    prd.read_callback = h2_data_read;
    h2_submit_response_nva(c, sid, std::to_string(status), headers,
                           content_length, &prd);
  }

  void h2_respond_simple(Conn* c, int32_t sid, int status,
                         const char* text) {
    h2_submit(c, sid, status,
              {{"content-type", "text/plain"}}, text);
  }

  void h2_respond_redirect(Conn* c, int32_t sid) {
    h2_submit(c, sid, 302, {{"location", "/__pingoo/captcha"}}, "");
  }

  // Synthesized upstream h1 request head for the active h2 stream
  // (h2 streams have no raw h1 head to rewrite). HEAD ONLY — the body
  // is framed by the caller per st's streaming mode: complete bodies
  // get a derived content-length, streamed ones pass the client's
  // content-length through or fall back to chunked.
  std::string h2_upstream_head(Conn* c, const H2Stream& st) {
    const Parsed& p = st.p;
    std::string out = p.method + " " + p.target + " HTTP/1.1\r\n";
    if (!p.host.empty()) out += "host: " + p.host + "\r\n";
    const std::string* client_cl = nullptr;
    for (const auto& kv : p.h2_headers) {
      if (kv.first == "content-length") client_cl = &kv.second;
      if (drop_request_header(kv.first, false) || kv.first == "host")
        continue;
      out += kv.first + ": " + kv.second + "\r\n";
    }
    out += "connection: keep-alive\r\n";
    if (st.complete) {
      if (!st.body.empty())
        out += "content-length: " + std::to_string(st.body.size()) + "\r\n";
    } else if (client_cl != nullptr) {
      out += "content-length: " + *client_cl + "\r\n";
    } else if (st.up_body_chunked) {
      out += "transfer-encoding: chunked\r\n";
    }
    out += "x-forwarded-for: " + std::string(c->peer_ip) + "\r\n";
    out += std::string("x-forwarded-proto: ") +
           (c->ssl != nullptr ? "https" : "http") + "\r\n";
    if (!p.host.empty()) out += "x-forwarded-host: " + p.host + "\r\n";
    if (st.up_target.internal && !internal_token_.empty())
      out += "x-pingoo-internal: " + internal_token_ + "\r\n";
    out += "pingoo-client-ip: " + std::string(c->peer_ip) + "\r\n\r\n";
    return out;
  }

  static void h1_chunk_wrap(std::string* out, const char* d, size_t n) {
    if (n == 0) return;  // a zero-size chunk would terminate the body
    char sz[32];
    snprintf(sz, sizeof(sz), "%zx\r\n", n);
    out->append(sz);
    out->append(d, n);
    out->append("\r\n");
  }

  // Forward one streamed request-body chunk / the end-of-body mark to
  // the stream's upstream (called from the nghttp2 receive callbacks).
  void h2_stream_body_chunk(Conn* c, H2Stream& st, const char* d,
                            size_t n) {
    if (st.up_proto_pending || st.up_queued || st.up_fd < 0) {
      st.up_body.append(d, n);  // framed at adoption/dispatch
      return;
    }
    if (st.up_h2 != nullptr) {
      st.up_h2->append_body(d, n);
      st.up_h2->pump_send(&st.upbuf);
    } else if (st.up_body_chunked) {
      h1_chunk_wrap(&st.upbuf, d, n);
    } else {
      st.upbuf.append(d, n);
    }
    h2_update_stream_events(c, st);
  }

  // Reopen the client's send window once the upstream has drained the
  // backlog below half the cap (manual flow control: window debt
  // accrued in h2_on_data_chunk). Must run from every path that
  // shrinks the stream's pending bytes.
  void h2_stream_release_window(Conn* c, int32_t sid, H2Stream& st) {
    if (st.window_debt == 0 || c->h2 == nullptr) return;
    size_t pending = st.upbuf.size() + st.up_body.size() +
                     (st.up_h2 != nullptr ? st.up_h2->body.size() : 0);
    if (pending >= kMaxBuffered / 2) return;
    nghttp2_session_consume(c->h2, sid,
                            static_cast<size_t>(st.window_debt));
    st.window_debt = 0;
    h2_flush(c);  // the WINDOW_UPDATE frames must reach the wire
  }

  void h2_stream_body_finish(Conn* c, H2Stream& st) {
    if (st.up_proto_pending || st.up_queued || st.up_fd < 0)
      return;  // adoption/dispatch sees st.complete and finishes
    if (st.up_h2 != nullptr) {
      st.up_h2->finish_body();
      st.up_h2->pump_send(&st.upbuf);
    } else if (st.up_body_chunked) {
      st.upbuf += "0\r\n\r\n";
    }
    h2_update_stream_events(c, st);
  }

  static int h2_on_header(nghttp2_session*, const void* frame,
                          const uint8_t* name, size_t namelen,
                          const uint8_t* value, size_t valuelen, uint8_t,
                          void* user_data) {
    Conn* c = static_cast<Conn*>(user_data);
    const auto* hd = static_cast<const nghttp2_frame_hd*>(frame);
    H2Stream& st = c->h2_streams[hd->stream_id];
    std::string n(reinterpret_cast<const char*>(name), namelen);
    std::string v(reinterpret_cast<const char*>(value), valuelen);
    Parsed& p = st.p;
    if (n == ":method") {
      p.method = v;
    } else if (n == ":path") {
      p.target = v;
      size_t q = v.find('?');
      p.path = q == std::string::npos ? v : v.substr(0, q);
    } else if (n == ":authority") {
      p.host = strip_host_port(v);
    } else if (!n.empty() && n[0] == ':') {
      // other pseudo-headers ignored
    } else {
      if (n == "user-agent") p.user_agent = trim(v);
      if (n == "accept") p.accept = lower(trim(v));
      if (n == "cookie" && p.verified_cookie.empty())
        p.verified_cookie = extract_verified_cookie(v);
      p.h2_headers.emplace_back(lower(n), v);
    }
    return 0;
  }

  static int h2_on_frame_recv(nghttp2_session*, const void* frame,
                              void* user_data) {
    Conn* c = static_cast<Conn*>(user_data);
    const auto* hd = static_cast<const nghttp2_frame_hd*>(frame);
    bool end_stream = (hd->flags & NGHTTP2_FLAG_END_STREAM) != 0;
    if (hd->type == NGHTTP2_FRAME_HEADERS &&
        (hd->flags & NGHTTP2_FLAG_END_HEADERS) != 0) {
      auto it = c->h2_streams.find(hd->stream_id);
      if (it == c->h2_streams.end()) return 0;
      H2Stream& st = it->second;
      if (!st.ready_queued) {
        // Dispatch at END_HEADERS (the verdict tuple needs no body):
        // request bodies STREAM to the upstream as DATA arrives, like
        // the reference's hyper service (http_listener.rs:276).
        st.ready_queued = true;
        st.complete = end_stream;
        st.p.ok = !st.p.method.empty() && !st.p.target.empty();
        c->h2_ready.push_back(hd->stream_id);
      } else if (end_stream && !st.complete) {
        // TRAILERS: a second HEADERS frame carrying END_STREAM ends
        // the body exactly like a final DATA frame would.
        st.complete = true;
        if (st.up_dispatched && g_server != nullptr)
          g_server->h2_stream_body_finish(c, st);
      }
      return 0;
    }
    if (hd->type == NGHTTP2_FRAME_DATA && end_stream) {
      auto it = c->h2_streams.find(hd->stream_id);
      if (it != c->h2_streams.end() && !it->second.complete) {
        H2Stream& st = it->second;
        st.complete = true;
        if (st.up_dispatched && g_server != nullptr)
          g_server->h2_stream_body_finish(c, st);
      }
    }
    return 0;
  }

  static int h2_on_data_chunk(nghttp2_session* sess, uint8_t,
                              int32_t stream_id, const uint8_t* data,
                              size_t len, void* user_data) {
    Conn* c = static_cast<Conn*>(user_data);
    H2Stream& st = c->h2_streams[stream_id];
    if (st.up_dispatched && g_server != nullptr) {
      // Streamed forwarding under manual flow control: bytes are
      // CONSUMED (window reopened) only while the pending backlog is
      // under half the cap; past that they accrue window debt, the
      // client's send window closes, and the debt is released as the
      // upstream drains (h2_stream_release_window). Bodies of ANY
      // size stream through at the pace of the slowest hop.
      g_server->h2_stream_body_chunk(
          c, st, reinterpret_cast<const char*>(data), len);
      size_t pending = st.upbuf.size() + st.up_body.size() +
                       (st.up_h2 != nullptr ? st.up_h2->body.size() : 0);
      if (pending < kMaxBuffered / 2) {
        nghttp2_session_consume(sess, stream_id, len);
      } else {
        st.window_debt += len;
      }
      return 0;
    }
    // Pre-dispatch (or non-proxy outcome) bytes buffer in st.body
    // under the same debt-based window withholding: small bodies
    // consume freely (the verdict round-trip must not stall the
    // client), larger ones close the window until dispatch drains the
    // buffer — st.body stays bounded by cap/2 plus the client's
    // in-flight window, with no resets. Debt parked on a stream that
    // never proxies (403/captcha) is returned to the connection
    // window at stream close.
    st.body.append(reinterpret_cast<const char*>(data), len);
    if (st.body.size() < kMaxBuffered / 2) {
      nghttp2_session_consume(sess, stream_id, len);
    } else {
      st.window_debt += len;
    }
    return 0;
  }

  static int h2_on_stream_close(nghttp2_session* sess, int32_t stream_id,
                                uint32_t, void* user_data) {
    Conn* c = static_cast<Conn*>(user_data);
    auto it = c->h2_streams.find(stream_id);
    if (it != c->h2_streams.end()) {
      if (it->second.window_debt > 0) {
        // the stream window dies with the stream, but unconsumed bytes
        // still hold CONNECTION window — leak enough of them and every
        // other stream on the session stalls
        nghttp2_session_consume_connection(
            sess, static_cast<size_t>(it->second.window_debt));
        it->second.window_debt = 0;
      }
      if (g_server != nullptr)
        g_server->h2_release_stream_resources(c, it->second);
      c->h2_streams.erase(it);
    }
    c->h2_send.erase(stream_id);
    if (g_server != nullptr) g_server->h2_process_next(c);
    return 0;
  }

  static ssize_t h2_data_read(nghttp2_session*, int32_t stream_id,
                              uint8_t* buf, size_t length,
                              uint32_t* data_flags, nghttp2_data_source*,
                              void* user_data) {
    Conn* c = static_cast<Conn*>(user_data);
    auto it = c->h2_send.find(stream_id);
    if (it != c->h2_send.end()) {  // canned (non-proxied) response
      const std::string& body = it->second.first;
      size_t& off = it->second.second;
      size_t n = std::min(body.size() - off, length);
      if (n > 0) {
        std::memcpy(buf, body.data() + off, n);
        off += n;
      }
      if (off >= body.size()) {
        *data_flags = NGHTTP2_DATA_FLAG_EOF;
        c->h2_send.erase(it);
      }
      return static_cast<ssize_t>(n);
    }
    // Streamed proxied response: DATA flows as the upstream delivers it.
    auto sit = c->h2_streams.find(stream_id);
    if (sit == c->h2_streams.end()) {
      *data_flags = NGHTTP2_DATA_FLAG_EOF;
      return 0;
    }
    H2Stream& st = sit->second;
    if (st.pending.empty()) {
      if (st.data_eof) {
        *data_flags = NGHTTP2_DATA_FLAG_EOF;
        return 0;
      }
      return kNghttp2ErrDeferred;  // resumed when more bytes arrive
    }
    size_t n = std::min(st.pending.size(), length);
    std::memcpy(buf, st.pending.data(), n);
    st.pending.erase(0, n);
    if (st.pending.empty() && st.data_eof)
      *data_flags = NGHTTP2_DATA_FLAG_EOF;
    // Draining below the cap re-arms the paused upstream read side.
    if (g_server != nullptr && st.up_fd >= 0)
      g_server->h2_update_stream_events(c, st);
    return static_cast<ssize_t>(n);
  }

  void drop_ticket(Conn* c) {
    if (c->ticket != UINT64_MAX) {
      awaiting_.erase(c->ticket);
      c->ticket = UINT64_MAX;
    }
  }

  void on_h2_event(Conn* c, uint32_t events) {
    c->last_active = now_;
    if (events & EPOLLIN) {
      char buf[16384];
      for (;;) {
        ssize_t r = t_read(c, buf, sizeof(buf));
        if (r > 0) {
          h2_pump(c, buf, static_cast<size_t>(r));
          if (c->dead) return;
        } else if (r == 0) {
          mark_close(c);
          return;
        } else if (r == -1) {
          break;
        } else {
          mark_close(c);
          return;
        }
      }
    }
    if (events & EPOLLOUT) {
      c->ssl_want_write = false;
      h2_flush(c);
    }
  }

  // -- proxy phase ----------------------------------------------------------

  void on_proxy_client_event(Conn* c, uint32_t events) {
    c->last_active = now_;
    if (events & EPOLLIN) {
      char buf[16384];
      for (;;) {
        ssize_t r = t_read(c, buf, sizeof(buf));
        if (r > 0) {
          c->inbuf.append(buf, static_cast<size_t>(r));
          if (c->inbuf.size() > kMaxBuffered) break;  // backpressure
        } else if (r == 0) {
          // Half-close: remember it (update_client_events disarms the
          // read side) — the response direction may continue.
          c->client_eof = true;
          if (!c->req_body.done && c->req_body.mode == BodyFramer::kUntilEof)
            c->req_body.done = true;
          break;
        } else if (r == -1) {
          break;
        } else {
          mark_close(c);
          return;
        }
      }
      pump_request_body(c);
      flush_upstream(c);
    }
    if (events & EPOLLOUT) {
      c->ssl_want_write = false;
      if (!flush_out(c)) {
        mark_close(c);
        return;
      }
      maybe_finish_response(c);
      if (c->dead || c->state != ConnState::kProxying) return;
    }
    update_client_events(c);
    update_upstream_events(c);
  }

  void flush_upstream(Conn* c) {
    while (!c->upbuf.empty() && c->upstream_fd >= 0 && c->upstream_connected) {
      c->up_wr_want_read = false;
      ssize_t w = up_send_raw(c->upstream_fd, c->up_ssl, c->upbuf.data(),
                              c->upbuf.size(), &c->up_wr_want_read);
      if (w > 0) {
        c->upbuf.erase(0, static_cast<size_t>(w));
      } else if (w == kIoAgain) {
        break;
      } else {
        // Upstream write failure mid-request: 502 if nothing sent yet,
        // else close.
        if (c->resp_head_done && c->state != ConnState::kH2) mark_close(c);
        else respond_502(c);
        return;
      }
    }
  }

  bool proxy_live(Conn* c) const {
    return c->state == ConnState::kProxying ||
           c->state == ConnState::kTunnel;
  }

  void on_upstream_event(Conn* c, uint32_t events) {
    c->last_active = now_;
    if (!c->upstream_connected) {
      if (!c->up_tcp_ok && (events & (EPOLLOUT | EPOLLERR))) {
        int err = 0;
        socklen_t len = sizeof(err);
        getsockopt(c->upstream_fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) {
          close_upstream(c);
          respond_502(c);
          return;
        }
        c->up_tcp_ok = true;
        if (c->up_target.tls) {
          if (!up_tls_begin(c->up_target, c->upstream_fd, &c->up_ssl,
                               !c->req.is_upgrade())) {
            close_upstream(c);
            respond_502(c);
            return;
          }
          c->up_tls_hs = true;
        } else {
          c->upstream_connected = true;
        }
      }
      if (c->up_tls_hs) {
        int hs = up_tls_step(c->up_ssl, &c->up_hs_want_write);
        if (hs < 0) {
          stats_.upstream_tls_fail++;
          close_upstream(c);
          respond_502(c);
          return;
        }
        if (hs == 0) {
          update_upstream_events(c);
          return;
        }
        c->up_tls_hs = false;
        c->upstream_connected = true;
        if (c->up_proto_pending) {
          c->up_proto_pending = false;
          const unsigned char* ap = nullptr;
          unsigned aplen = 0;
          SSL_get0_alpn_selected(c->up_ssl, &ap, &aplen);
          if (aplen == 2 && memcmp(ap, "h2", 2) == 0) {
            if (!begin_upstream_h2(c, nullptr)) return;
          } else {
            c->upbuf = c->up_head;
          }
          finish_upstream_send_setup(c);
        }
      }
      if (!c->upstream_connected) return;  // TCP connect still pending
    }
    if (events & EPOLLOUT || c->up_wr_want_read) flush_upstream(c);
    if (c->dead || !proxy_live(c)) return;
    if ((events & EPOLLIN) || c->up_rd_want_write) {
      char buf[16384];
      for (;;) {
        if (c->outbuf.size() > kMaxBuffered) break;  // backpressure
        c->up_rd_want_write = false;
        ssize_t r = up_recv_raw(c->upstream_fd, c->up_ssl, buf, sizeof(buf),
                                &c->up_rd_want_write);
        if (r > 0 && c->up_h2 != nullptr) {
          std::string synth;
          if (!c->up_h2->feed(buf, static_cast<size_t>(r), &synth)) {
            if (!c->resp_head_done) {
              respond_502(c);
            } else {
              mark_close(c);
            }
            return;
          }
          if (!synth.empty()) {
            on_upstream_data(c, synth.data(), synth.size());
            // The synthesized bytes may COMPLETE the response: the
            // link is then released/closed (up_h2 == nullptr) and the
            // connection may already be proxying a pipelined next
            // request (even over a fresh link) — this event context is
            // stale either way.
            if (c->dead || !proxy_live(c) || c->up_h2 == nullptr) return;
          }
          // acks/window updates the session owes after the feed, and
          // any request-body bytes the 1 MiB link cap left stranded in
          // inbuf — the client may be done sending (no more client
          // events), so the upstream's WINDOW_UPDATEs must re-drive
          // the pump or a large upload deadlocks here.
          pump_request_body(c);
          if (c->dead) return;
          c->up_h2->pump_send(&c->upbuf);
        } else if (r > 0) {
          on_upstream_data(c, buf, static_cast<size_t>(r));
          if (c->dead || !proxy_live(c)) return;
        } else if (r == 0) {
          c->upstream_eof = true;
          break;
        } else if (r == kIoAgain) {
          break;
        } else {
          c->upstream_eof = true;
          c->up_trunc = true;  // FIN sans close_notify / transport error
          break;
        }
      }
    }
    if (events & (EPOLLHUP | EPOLLERR)) c->upstream_eof = true;
    if (!flush_out(c)) {
      mark_close(c);
      return;
    }
    maybe_finish_response(c);
    if (c->dead || !proxy_live(c)) return;
    update_client_events(c);
    update_upstream_events(c);
  }

  // h1 proxy: stream the upstream response to the client, rewriting
  // the head (and entering raw-tunnel mode on an accepted upgrade).
  void on_upstream_data(Conn* c, const char* data, size_t len) {
    if (c->state == ConnState::kTunnel) {
      c->outbuf.append(data, len);  // raw splice after the 101
      return;
    }
    if (!c->resp_head_done) {
      c->resp_head_buf.append(data, len);
      // Parse heads in a loop: 1xx interim responses (e.g. 100
      // Continue for Expect: 100-continue POSTs) are relayed and the
      // FINAL response head follows on the same connection.
      for (;;) {
        size_t he = c->resp_head_buf.find("\r\n\r\n");
        if (he == std::string::npos) {
          if (c->resp_head_buf.size() > kMaxHead) mark_close(c);
          return;
        }
        std::string head = c->resp_head_buf.substr(0, he + 4);
        RespHead rh = rewrite_response_head(head, c->req.keep_alive);
        if (!rh.ok) {
          respond_502(c);
          return;
        }
        if (rh.status == 101 && c->req.is_upgrade()) {
          // Upgrade accepted: relay the 101 head VERBATIM — its
          // Connection/Upgrade/Sec-WebSocket-* headers are the
          // handshake — then splice raw bytes both ways until either
          // side closes (reference http_listener.rs:277
          // serve_connection_with_upgrades).
          c->outbuf += head;
          c->outbuf += c->resp_head_buf.substr(he + 4);
          c->resp_head_buf.clear();
          c->resp_head_done = true;
          c->close_after_response = true;
          c->state = ConnState::kTunnel;
          // Frames an optimistic client sent right after its upgrade
          // request are sitting in inbuf — splice them into the tunnel
          // (the Python plane forwards h11 trailing_data the same way).
          if (!c->inbuf.empty()) {
            c->upbuf += c->inbuf;
            c->inbuf.clear();
            flush_upstream(c);
          }
          update_client_events(c);
          update_upstream_events(c);
          return;
        }
        if (rh.status >= 100 && rh.status < 200) {
          // interim: strip hop/identity headers like final heads, keep
          // the 1xx status line, keep parsing for the final head
          c->outbuf += rewrite_interim_head(head);
          c->resp_head_buf.erase(0, he + 4);
          continue;
        }
        bool head_only = c->req.method == "HEAD" || rh.status == 204 ||
                         rh.status == 304;
        c->upstream_keep = rh.upstream_keep;
        if (head_only) {
          c->resp_body.reset_none();
        } else if (rh.chunked) {
          c->resp_body.reset_chunked();
        } else if (rh.content_length >= 0) {
          c->resp_body.reset_cl(rh.content_length);
        } else {
          c->resp_body.reset_eof();
          c->close_after_response = true;  // EOF-delimited: client closes too
        }
        if (!c->req.keep_alive) c->close_after_response = true;
        c->outbuf += rh.rewritten;
        // Remaining bytes after the head are body bytes.
        std::string rest = c->resp_head_buf.substr(he + 4);
        c->resp_head_buf.clear();
        c->resp_head_done = true;
        if (!rest.empty()) {
          size_t take = c->resp_body.consume(rest.data(), rest.size());
          c->outbuf.append(rest, 0, take);
          // bytes past the response end are junk; drop them (and never
          // pool a connection that sent them)
          if (take < rest.size()) c->upstream_junk = true;
          if (c->resp_body.bad) mark_close(c);
        }
        return;
      }
    }
    if (!c->resp_body.done) {
      size_t take = c->resp_body.consume(data, len);
      c->outbuf.append(data, take);
      if (take < len && c->resp_body.done) c->upstream_junk = true;
    } else if (len > 0) {
      c->upstream_junk = true;
    }
    if (c->resp_body.bad) mark_close(c);  // malformed upstream chunking
  }

  // Tunnel teardown policy. WebSocket tunnels close as a unit once the
  // upstream ends; raw TCP (tcp-proxy mode) propagates each side's FIN
  // independently like the reference's copy_bidirectional
  // (tcp_proxy_service.rs:74-82) and closes only when BOTH directions
  // are finished.
  void tunnel_check_done(Conn* c) {
    if (c->client_eof && c->upbuf.empty() && !c->up_shut &&
        c->upstream_fd >= 0) {
      if (c->up_ssl != nullptr) SSL_shutdown(c->up_ssl);
      shutdown(c->upstream_fd, SHUT_WR);
      c->up_shut = true;
    }
    if (c->upstream_eof && c->outbuf.empty()) {
      if (!tcp_mode_) {
        mark_close(c);
        return;
      }
      if (!c->down_shut) {
        if (c->ssl != nullptr) SSL_shutdown(c->ssl);
        shutdown(c->fd, SHUT_WR);
        c->down_shut = true;
      }
      // half-open: keep relaying client -> upstream until the client
      // finishes too (or the idle sweep reaps the connection)
      if (c->client_eof && c->upbuf.empty()) mark_close(c);
    }
  }

  void maybe_finish_response(Conn* c) {
    if (c->state == ConnState::kTunnel) {
      tunnel_check_done(c);
      return;
    }
    if (c->state != ConnState::kProxying || !c->resp_head_done) {
      // EOF from upstream before any response head -> 502
      if (c->state == ConnState::kProxying && c->upstream_eof &&
          !c->resp_head_done) {
        if (try_pooled_retry(c)) return;
        stats_.upstream_fail++;
        respond_close(c, k502);
      }
      return;
    }
    bool body_done = c->resp_body.done ||
                     (c->resp_body.mode == BodyFramer::kUntilEof &&
                      c->upstream_eof && !c->up_trunc);
    if (!body_done) {
      if (c->upstream_eof && !c->resp_body.done &&
          (c->resp_body.mode != BodyFramer::kUntilEof || c->up_trunc)) {
        // Truncated upstream response (explicit framing cut short, or
        // an EOF-delimited TLS body ended by FIN without close_notify):
        // relay what we have, then close — never pool, and for
        // explicitly framed bodies the client sees the short read.
        c->close_after_response = true;
        body_done = true;
      } else {
        return;
      }
    }
    if (!c->outbuf.empty()) return;  // keep draining first
    // Reuse the upstream connection when the response left it in a
    // known-clean state: explicit framing fully consumed, no EOF, no
    // bytes past the response end, and the upstream allows keep-alive.
    if (c->resp_body.done && c->resp_body.mode != BodyFramer::kUntilEof &&
        !c->upstream_eof && c->upstream_keep && !c->upstream_junk &&
        c->upbuf.empty() && c->req_body_forwarded &&
        (c->up_h2 == nullptr ||
         (!c->up_h2->goaway && !c->up_h2->failed))) {
      release_upstream(c);
    } else {
      close_upstream(c);
    }
    if (c->close_after_response) {
      mark_close(c);
      return;
    }
    begin_request_cycle(c);
  }

  // -- TLS handshake --------------------------------------------------------

  void on_handshake(Conn* c) {
    c->last_active = now_;
    c->ssl_want_write = false;
    int r = SSL_do_handshake(c->ssl);
    if (r == 1) {
      if (c->acme_challenge) {
        // tls-alpn-01: the validation server only needs the handshake
        // (RFC 8737 §3); close once it completes.
        mark_close(c);
        return;
      }
      if (tcp_mode_) {
        start_tcp_proxy(c);
        return;
      }
      c->state = ConnState::kReadingHead;
      update_client_events(c);
      return;
    }
    int err = SSL_get_error(c->ssl, r);
    ERR_clear_error();
    if (err == SSL_ERROR_WANT_READ) {
      update_client_events(c);
      return;
    }
    if (err == SSL_ERROR_WANT_WRITE) {
      c->ssl_want_write = true;
      update_client_events(c);
      return;
    }
    mark_close(c);
  }

  void handle(SockRef* ref, uint32_t events) {
    Conn* c = ref->conn;
    if (c == nullptr || ref->h2_sid < 0) return;  // dead stream ref
    if (c->dead) return;  // stale event within this batch
    if (ref->is_upstream) {
      if (ref->h2_sid > 0) {
        h2_stream_upstream_event(c, ref->h2_sid, events);
      } else if (proxy_live(c)) {
        on_upstream_event(c, events);
      }
      return;
    }
    switch (c->state) {
      case ConnState::kHandshake:
        if (events & (EPOLLHUP | EPOLLERR)) mark_close(c);
        else on_handshake(c);
        break;
      case ConnState::kReadingHead:
        if (events & (EPOLLIN | EPOLLHUP)) on_client_readable(c);
        else if (events & EPOLLOUT) {
          c->ssl_want_write = false;
          if (!flush_out(c)) mark_close(c);
          else update_client_events(c);
        }
        break;
      case ConnState::kAwaitingVerdict:
        if ((events & EPOLLIN) && c->body_inspect) on_body_readable(c);
        if (!c->dead && (events & (EPOLLHUP | EPOLLERR))) mark_close(c);
        break;
      case ConnState::kProxying:
        if (events & (EPOLLHUP | EPOLLERR)) {
          // client side error/hangup
          mark_close(c);
          return;
        }
        on_proxy_client_event(c, events);
        break;
      case ConnState::kTunnel:
        if (events & EPOLLERR) {
          mark_close(c);
          return;
        }
        // EPOLLHUP fires once BOTH directions are shut — pending bytes
        // are still readable, so drain first (the read loop's r==0
        // sets client_eof). HUP cannot be masked by a 0 event mask, so
        // an ALREADY-drained client is handled here: close when its
        // relay backlog is through; otherwise stop watching the client
        // fd entirely (nothing can arrive or be delivered) and let
        // upstream EPOLLOUT drain the remaining upbuf tail.
        if ((events & EPOLLHUP) && c->client_eof) {
          if (c->upbuf.empty()) {
            mark_close(c);
          } else {
            epoll_ctl(ep_, EPOLL_CTL_DEL, c->fd, nullptr);
            update_upstream_events(c);
          }
          return;
        }
        on_tunnel_client_event(
            c, events | ((events & EPOLLHUP) ? EPOLLIN : 0u));
        break;
      case ConnState::kH2:
        if (events & (EPOLLHUP | EPOLLERR)) {
          mark_close(c);
          return;
        }
        on_h2_event(c, events);
        break;
      case ConnState::kClosing:
        if (events & (EPOLLHUP | EPOLLERR)) mark_close(c);
        else if (events & EPOLLOUT) {
          c->ssl_want_write = false;
          if (!flush_out(c) || c->outbuf.empty()) mark_close(c);
        }
        break;
    }
  }

 private:
  int ep_;
  void* ring_;
  sockaddr_in upstream_;
  sockaddr_in captcha_upstream_{};
  bool has_captcha_upstream_ = false;
  CaptchaGate* gate_;
  TlsStore* tls_;
  ServiceTable* services_ = nullptr;
  SSL_CTX* up_ctx_ = nullptr;  // upstream TLS client context
  std::unordered_map<std::string, StaticFile> file_cache_;  // static sites
  std::string internal_token_;  // per-boot control-plane trust token
  bool tcp_mode_ = false;  // raw TCP(+TLS) fronting: no HTTP, no verdicts
  // Links whose SSL object holds decrypted-but-undelivered bytes (no fd
  // readiness will fire for them); drained after each event batch.
  std::vector<std::pair<Conn*, int32_t>> ssl_resume_;
  uint32_t rng_ = 0x9e3779b9;  // xorshift32 state for upstream choice
  std::unordered_map<uint64_t, std::vector<PooledUpstream>> upstream_pool_;
  Stats stats_;
  std::unordered_set<Conn*> conns_;
  struct Awaiting {
    Conn* conn;
    int32_t sid;  // 0 = the h1 request cycle, else an h2 stream
  };
  std::unordered_map<uint64_t, Awaiting> awaiting_;
  // Streaming body inspection (ISSUE 13): flow id (= the plain ring
  // ticket) -> inspecting conn, for bit-63 verdict demux.
  std::unordered_map<uint64_t, Conn*> body_awaiting_;
  std::vector<Conn*> body_expired_;  // sweep_body_deadlines scratch
  // Sidecar supervision state (ISSUE 10, docs/RESILIENCE.md).
  bool degraded_ = false;        // heartbeat stale: bypass the ring
  bool sidecar_seen_ = false;    // a sidecar heartbeat has ever landed
  uint64_t sidecar_epoch_ = 0;   // last epoch read from the ring header
  uint64_t last_deadline_sweep_ms_ = 0;
  std::vector<uint64_t> expired_;  // sweep_verdict_deadlines scratch
  std::vector<SockRef*> doomed_refs_;  // per-stream refs freed after the batch
  std::unordered_map<SSL*, Conn*> ssl_conn_;
  std::vector<Conn*> doomed_;
  time_t now_ = 0;
};

int alpn_select_cb(SSL* ssl, const unsigned char** out, unsigned char* outlen,
                   const unsigned char* in, unsigned int inlen, void* arg);

// ClientHello callback: inspect SNI + ALPN BEFORE any config decision
// (the reference's LazyConfigAcceptor, listeners/mod.rs:112-154).
// acme-tls/1 -> swap in the ephemeral challenge cert for the domain.
int client_hello_cb(SSL* ssl, int* al, void* arg) {
  (void)al;
  TlsStore* store = static_cast<TlsStore*>(arg);
  const unsigned char* ext = nullptr;
  size_t ext_len = 0;
  std::string sni;
  if (SSL_client_hello_get0_ext(ssl, TLSEXT_TYPE_server_name, &ext,
                                &ext_len) == 1)
    sni = parse_sni_ext(ext, ext_len);
  bool acme = false;
  if (SSL_client_hello_get0_ext(ssl, TLSEXT_TYPE_alpn, &ext, &ext_len) == 1)
    acme = alpn_ext_offers(ext, ext_len, "acme-tls/1");

  Conn* c = g_server ? g_server->conn_for_ssl(ssl) : nullptr;
  if (acme && !sni.empty() && !store->alpn_dir.empty()) {
    // Challenge certs are ephemeral files written by the ACME client
    // (host/acme.py); load fresh per handshake.
    std::string cert = store->alpn_dir + "/" + sni + ".pem";
    std::string key = store->alpn_dir + "/" + sni + ".key";
    SSL_CTX* ch = make_server_ctx(cert, key);
    if (ch != nullptr && c != nullptr) {
      c->acme_challenge = true;
      c->owned_ctx = ch;
      // ALPN selection runs against the swapped-in context, which must
      // therefore carry the callback too — RFC 8737 requires acme-tls/1
      // to actually be negotiated, not just tolerated.
      SSL_CTX_set_alpn_select_cb(ch, alpn_select_cb, nullptr);
      SSL_set_SSL_CTX(ssl, ch);
      return SSL_CLIENT_HELLO_SUCCESS;
    }
    if (ch) SSL_CTX_free(ch);
    return SSL_CLIENT_HELLO_ERROR;  // no challenge staged for this name
  }
  SSL_CTX* chosen = store->match(sni);
  if (chosen != nullptr) SSL_set_SSL_CTX(ssl, chosen);
  return SSL_CLIENT_HELLO_SUCCESS;
}

// ALPN negotiation: acme-tls/1 for challenge handshakes (RFC 8737
// REQUIRES the protocol be negotiated), http/1.1 otherwise.
int alpn_select_cb(SSL* ssl, const unsigned char** out, unsigned char* outlen,
                   const unsigned char* in, unsigned int inlen, void* arg) {
  (void)arg;
  Conn* c = g_server ? g_server->conn_for_ssl(ssl) : nullptr;
  bool acme = c != nullptr && c->acme_challenge;
  // Server preference order (the reference's hyper auto builder serves
  // h1+h2, http_listener.rs:276-278); every h2 client still sends the
  // RFC 7540 preface, which is what actually switches the connection.
  const char* prefs_normal[] = {"h2", "http/1.1"};
  const char* prefs_acme[] = {"acme-tls/1"};
  const char** prefs = acme ? prefs_acme : prefs_normal;
  size_t nprefs = acme ? 1 : 2;
  for (size_t p = 0; p < nprefs; ++p) {
    const char* want = prefs[p];
    size_t wlen = strlen(want);
    unsigned int i = 0;
    while (i < inlen) {
      unsigned int n = in[i];
      if (i + 1 + n > inlen) break;
      if (n == wlen && memcmp(in + i + 1, want, n) == 0) {
        *out = in + i + 1;
        *outlen = static_cast<unsigned char>(n);
        return SSL_TLSEXT_ERR_OK;
      }
      i += 1 + n;
    }
  }
  return SSL_TLSEXT_ERR_NOACK;  // no overlap: proceed without ALPN
}

bool parse_hostport(const char* s, sockaddr_in* out) {
  std::string hp = s;
  size_t colon = hp.rfind(':');
  if (colon == std::string::npos) return false;
  std::string host = hp.substr(0, colon);
  std::string port = hp.substr(colon + 1);
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 ||
      res == nullptr)
    return false;
  std::memcpy(out, res->ai_addr, sizeof(*out));
  freeaddrinfo(res);
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <listen-port> <ring-file> <upstream-host> "
                 "<upstream-port> [--captcha-upstream host:port] "
                 "[--jwks path] [--tls-dir dir] [--alpn-dir dir] "
                 "[--services path] [--bind addr] [--upstream-ca pem] "
                 "[--internal-token-file path] [--tcp-proxy]\n",
                 argv[0]);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);  // peer resets must not kill the data plane
  int listen_port = std::atoi(argv[1]);
  const char* ring_path = argv[2];
  const char* up_host = argv[3];
  const char* up_port = argv[4];

  const char* jwks_path = nullptr;
  const char* tls_dir = nullptr;
  const char* alpn_dir = nullptr;
  const char* services_path = nullptr;
  const char* bind_addr = nullptr;
  const char* upstream_ca = nullptr;
  const char* internal_token_file = nullptr;
  bool tcp_mode = false;
  sockaddr_in captcha_upstream{};
  bool has_captcha = false;
  for (int i = 5; i < argc; i += 2) {
    if (strcmp(argv[i], "--tcp-proxy") == 0) {
      tcp_mode = true;
      i -= 1;  // flag takes no operand
      continue;
    }
    if (i + 1 >= argc) break;  // every remaining option takes a value
    if (strcmp(argv[i], "--captcha-upstream") == 0) {
      if (!parse_hostport(argv[i + 1], &captcha_upstream)) {
        std::fprintf(stderr, "bad --captcha-upstream\n");
        return 2;
      }
      has_captcha = true;
    } else if (strcmp(argv[i], "--jwks") == 0) {
      jwks_path = argv[i + 1];
    } else if (strcmp(argv[i], "--tls-dir") == 0) {
      tls_dir = argv[i + 1];
    } else if (strcmp(argv[i], "--alpn-dir") == 0) {
      alpn_dir = argv[i + 1];
    } else if (strcmp(argv[i], "--services") == 0) {
      services_path = argv[i + 1];
    } else if (strcmp(argv[i], "--bind") == 0) {
      bind_addr = argv[i + 1];
    } else if (strcmp(argv[i], "--upstream-ca") == 0) {
      upstream_ca = argv[i + 1];
    } else if (strcmp(argv[i], "--internal-token-file") == 0) {
      internal_token_file = argv[i + 1];
    }
  }
  // Per-boot token authenticating this proxy to the loopback control
  // plane (file, not argv: /proc/<pid>/cmdline is world-readable).
  std::string internal_token;
  if (internal_token_file != nullptr) {
    FILE* tf = fopen(internal_token_file, "r");
    if (tf == nullptr) {
      std::fprintf(stderr, "cannot read --internal-token-file %s\n",
                   internal_token_file);
      return 2;
    }
    char tok[256] = {0};
    size_t tn = fread(tok, 1, sizeof(tok) - 1, tf);
    fclose(tf);
    while (tn > 0 && (tok[tn - 1] == '\n' || tok[tn - 1] == '\r' ||
                      tok[tn - 1] == ' '))
      tok[--tn] = '\0';
    internal_token.assign(tok, tn);
  }

  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(up_host, up_port, &hints, &res) != 0 || res == nullptr) {
    std::fprintf(stderr, "cannot resolve upstream %s:%s\n", up_host, up_port);
    return 1;
  }
  sockaddr_in upstream{};
  std::memcpy(&upstream, res->ai_addr, sizeof(upstream));
  freeaddrinfo(res);

  int rfd = open(ring_path, O_RDWR);
  if (rfd < 0) {
    std::perror("open ring");
    return 1;
  }
  struct stat st;
  fstat(rfd, &st);
  void* ring =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, rfd, 0);
  if (ring == MAP_FAILED || pingoo_ring_attach(ring, nullptr) != 0) {
    std::fprintf(stderr, "ring attach failed\n");
    return 1;
  }

  CaptchaGate gate;
  if (jwks_path != nullptr && !gate.load(jwks_path)) {
    std::fprintf(stderr,
                 "warning: JWKS unavailable at %s; all clients treated as "
                 "unverified\n",
                 jwks_path);
  }

  TlsStore tls_store;
  SSL_CTX* base_ctx = nullptr;
  if (tls_dir != nullptr) {
    if (alpn_dir != nullptr) tls_store.alpn_dir = alpn_dir;
    if (!load_tls_store(tls_dir, &tls_store)) {
      std::fprintf(stderr, "no usable certificates in %s\n", tls_dir);
      return 1;
    }
    base_ctx = tls_store.fallback != nullptr
                   ? tls_store.fallback
                   : (!tls_store.exact.empty()
                          ? tls_store.exact.begin()->second
                          : tls_store.wildcard.begin()->second);
    // Install inspection callbacks on every loaded context (the
    // connection's context can be swapped by the client-hello cb).
    auto install = [&](SSL_CTX* ctx) {
      SSL_CTX_set_client_hello_cb(ctx, client_hello_cb, &tls_store);
      SSL_CTX_set_alpn_select_cb(ctx, alpn_select_cb, nullptr);
    };
    if (tls_store.fallback) install(tls_store.fallback);
    for (auto& kv : tls_store.exact) install(kv.second);
    for (auto& kv : tls_store.wildcard) install(kv.second);
  }

  ServiceTable services;
  if (services_path != nullptr) {
    services.path = services_path;
    services.reload();  // absent file is fine: table loads when written
  }

  // Upstream TLS client context: verification is mandatory (the
  // reference's hyper-rustls client has no insecure mode,
  // http_proxy_service.rs:54-71) against either the system roots or an
  // explicit --upstream-ca bundle (private-CA deployments, tests).
  SSL_CTX* up_ctx = SSL_CTX_new(TLS_client_method());
  if (up_ctx != nullptr) {
    SSL_CTX_set_min_proto_version_shim(up_ctx, TLS1_2_VERSION);
    SSL_CTX_set_mode_shim(up_ctx, SSL_MODE_ENABLE_PARTIAL_WRITE |
                                      SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER);
    SSL_CTX_set_verify(up_ctx, SSL_VERIFY_PEER, nullptr);
    int roots_ok;
    if (upstream_ca != nullptr) {
      roots_ok = SSL_CTX_load_verify_locations(up_ctx, upstream_ca, nullptr);
    } else {
      roots_ok = SSL_CTX_set_default_verify_paths(up_ctx);
    }
    if (!roots_ok) {
      std::fprintf(stderr, "cannot load upstream trust roots%s%s\n",
                   upstream_ca ? " from " : "", upstream_ca ? upstream_ca : "");
      return 1;
    }
    static const unsigned char kAlpn[] = "\x08http/1.1";
    SSL_CTX_set_alpn_protos(up_ctx, kAlpn, sizeof(kAlpn) - 1);
  }

  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  setsockopt(lfd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // Default bind stays loopback (the co-located control-plane shape);
  // --bind makes the native plane the public front door.
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (bind_addr != nullptr &&
      inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) {
    std::fprintf(stderr, "bad --bind address %s\n", bind_addr);
    return 2;
  }
  addr.sin_port = htons(static_cast<uint16_t>(listen_port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(lfd, 2048) != 0) {
    std::perror("bind/listen");
    return 1;
  }

  int ep = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;  // nullptr marks the listening socket
  epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);

  Server server(ep, ring, upstream, has_captcha ? &captcha_upstream : nullptr,
                &gate, tls_dir ? &tls_store : nullptr,
                services_path ? &services : nullptr, up_ctx,
                internal_token, tcp_mode);
  g_server = &server;
  // SIGTERM starts a graceful drain: stop accepting, finish in-flight
  // requests, exit when idle or after the 20 s cap (the reference's
  // drain bound, listeners/mod.rs:28 + http_listener.rs:111-116).
  struct sigaction sa {};
  sa.sa_handler = [](int) { g_sigterm = 1; };
  sigaction(SIGTERM, &sa, nullptr);
  std::printf("{\"listening\": %d, \"tls\": %s, \"services\": %s}\n",
              listen_port, tls_dir ? "true" : "false",
              services_path ? "true" : "false");
  std::fflush(stdout);

  constexpr time_t kDrainCapS = 20;
  bool draining = false;
  time_t drain_start = 0;
  time_t last_sweep = time(nullptr);
  while (true) {
    epoll_event events[256];
    // Busy-poll while requests are awaiting verdicts: the sidecar posts
    // to the shared-memory ring without any fd to wake us, so sleeping
    // the epoll timeout would add up to 1 ms to EVERY verdict. With no
    // verdicts outstanding, 1 ms keeps the idle loop cheap.
    int n = epoll_wait(ep, events, 256,
                       server.awaiting_verdicts() ? 0 : 1);
    time_t now = time(nullptr);
    server.set_now(now);
    server.drain_verdicts();
    // Sidecar supervision (ISSUE 10): heartbeat check (a few shm
    // loads) + ms-granularity verdict deadlines (self-throttled to one
    // pass per ms) run every iteration, so a dead sidecar costs one
    // detection window, not a seconds-long stall.
    server.check_sidecar_liveness();
    server.sweep_verdict_deadlines();

    if (g_sigterm && !draining) {
      draining = true;
      drain_start = now;
      epoll_ctl(ep, EPOLL_CTL_DEL, lfd, nullptr);
      close(lfd);
      lfd = -1;
      std::printf("{\"draining\": true}\n");
      std::fflush(stdout);
      // SIGTERM drain auto-dump (ISSUE 5): the flight recorder lives
      // only in memory; stderr keeps the stdout protocol lines
      // ("draining"/"drained") parseable for the harness scripts.
      std::fprintf(stderr, "%s\n", server.flightrecorder_json().c_str());
      std::fflush(stderr);
    }

    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {
        if (lfd < 0) continue;  // stale accept event during drain
        while (true) {
          sockaddr_in peer{};
          socklen_t plen = sizeof(peer);
          int cfd = accept4(lfd, reinterpret_cast<sockaddr*>(&peer), &plen,
                            SOCK_NONBLOCK);
          if (cfd < 0) break;
          int nd = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
          server.add_client(cfd, peer, base_ctx);
        }
        continue;
      }
      SockRef* ref = static_cast<SockRef*>(events[i].data.ptr);
      server.handle(ref, events[i].events);
    }
    server.process_ssl_resume();
    server.flush_doomed();
    if (draining) {
      size_t live = server.drain_tick();
      if (live == 0 || now - drain_start >= kDrainCapS) {
        std::printf("{\"drained\": true, \"remaining\": %zu}\n", live);
        std::fflush(stdout);
        return 0;
      }
    }
    if (now != last_sweep) {
      server.sweep_idle();
      server.sweep_pool();
      server.flush_doomed();
      services.maybe_reload(now);
      last_sweep = now;
    }
  }
  return 0;
}
