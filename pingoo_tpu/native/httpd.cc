// Native HTTP data plane: epoll listener -> verdict ring -> 403/proxy.
//
// The C++ half of the architecture split (SURVEY.md §7 item 1: "Host
// data plane (C++): listeners ... proxying"): a non-blocking epoll event
// loop accepts connections, parses HTTP/1.1 request heads, enqueues the
// request tuple into the shared-memory verdict ring (pingoo_ring.h), and
// on the TPU sidecar's verdict either serves 403 / a captcha redirect or
// proxies the buffered request to the upstream and relays bytes both
// ways. SO_REUSEPORT allows N listener processes on one port (the
// reference's zero-downtime upgrade mechanism, listeners/mod.rs:57-61).
//
// Event-loop invariants:
//   * epoll data carries Conn* (nullptr = the listening socket); closes
//     are deferred to the end of the batch so stale events for a reused
//     fd can never touch a fresh connection.
//   * SIGPIPE is ignored; every short/EAGAIN write buffers the
//     remainder and arms EPOLLOUT, so relayed bytes are never dropped.
//   * A sidecar stall (verdict ring full) fails OPEN: the request is
//     proxied without a verdict, mirroring the reference's rule-error
//     fail-open (pingoo/rules.rs:41-44).
//   * Idle connections (no complete head, half-open peers) are swept
//     after kIdleTimeoutS.
//
// Scope: HTTP/1.1, Connection: close semantics downstream+upstream.
// TLS and h2 stay in the Python plane for now.
//
// Usage: httpd <listen-port> <ring-file> <upstream-host> <upstream-port>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "pingoo_ring.h"

namespace {

constexpr size_t kMaxHead = 32 * 1024;
constexpr size_t kMaxBuffered = 1 << 20;  // per-direction relay backlog cap
constexpr time_t kIdleTimeoutS = 30;

enum class ConnState { kReadingHead, kAwaitingVerdict, kProxying, kClosing };

struct Conn;

struct SockRef {
  Conn* conn = nullptr;  // nullptr = the listening socket
  bool is_upstream = false;
};

struct Conn {
  int fd = -1;
  int upstream_fd = -1;
  ConnState state = ConnState::kReadingHead;
  std::string inbuf;    // buffered request bytes (head + any body read)
  std::string outbuf;   // bytes pending to client
  std::string upbuf;    // bytes pending to upstream
  uint64_t ticket = UINT64_MAX;
  char peer_ip[INET6_ADDRSTRLEN] = {0};
  uint16_t peer_port = 0;
  bool dead = false;           // queued for deferred deletion
  bool upstream_connected = false;
  bool client_eof = false;
  bool upstream_eof = false;
  time_t last_active = 0;
  SockRef client_ref;
  SockRef upstream_ref;
};

const char k403[] =
    "HTTP/1.1 403 Forbidden\r\nserver: pingoo\r\n"
    "content-type: text/plain\r\ncontent-length: 9\r\n"
    "connection: close\r\n\r\nForbidden";
const char kCaptcha[] =
    "HTTP/1.1 302 Found\r\nserver: pingoo\r\n"
    "location: /__pingoo/captcha\r\ncontent-length: 0\r\n"
    "connection: close\r\n\r\n";
const char k502[] =
    "HTTP/1.1 502 Bad Gateway\r\nserver: pingoo\r\n"
    "content-type: text/plain\r\ncontent-length: 11\r\n"
    "connection: close\r\n\r\nBad Gateway";
const char k400[] =
    "HTTP/1.1 400 Bad Request\r\nserver: pingoo\r\n"
    "content-length: 0\r\nconnection: close\r\n\r\n";

struct Parsed {
  std::string method, target, path, host, user_agent;
  bool ok = false;
};

// Minimal HTTP/1.1 head parser: request line + the headers the verdict
// tuple needs (reference hot path extracts the same fields,
// http_listener.rs:140-165).
Parsed parse_head(const std::string& head) {
  Parsed p;
  size_t line_end = head.find("\r\n");
  if (line_end == std::string::npos) return p;
  const std::string line = head.substr(0, line_end);
  size_t sp1 = line.find(' ');
  size_t sp2 = line.rfind(' ');
  if (sp1 == std::string::npos || sp2 == sp1) return p;
  p.method = line.substr(0, sp1);
  p.target = line.substr(sp1 + 1, sp2 - sp1 - 1);
  if (p.method.empty() || p.target.empty() ||
      line.compare(sp2 + 1, 8, "HTTP/1.1") != 0)
    return p;
  size_t q = p.target.find('?');
  p.path = q == std::string::npos ? p.target : p.target.substr(0, q);

  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos || eol == pos) break;
    size_t colon = head.find(':', pos);
    if (colon != std::string::npos && colon < eol) {
      std::string name = head.substr(pos, colon - pos);
      for (auto& ch : name) ch = static_cast<char>(tolower(ch));
      size_t vstart = colon + 1;
      while (vstart < eol && head[vstart] == ' ') ++vstart;
      std::string value = head.substr(vstart, eol - vstart);
      if (name == "host") {
        size_t port_colon = value.rfind(':');
        p.host = port_colon == std::string::npos ? value
                                                 : value.substr(0, port_colon);
      } else if (name == "user-agent") {
        p.user_agent = value;
      }
    }
    pos = eol + 2;
  }
  p.ok = true;
  return p;
}

class Server {
 public:
  Server(int ep, void* ring, const sockaddr_in& upstream)
      : ep_(ep), ring_(ring), upstream_(upstream) {}

  void add_client(int cfd, const sockaddr_in& peer) {
    Conn* c = new Conn();
    c->fd = cfd;
    c->last_active = now_;
    c->client_ref.conn = c;
    c->upstream_ref.conn = c;
    c->upstream_ref.is_upstream = true;
    inet_ntop(AF_INET, &peer.sin_addr, c->peer_ip, sizeof(c->peer_ip));
    c->peer_port = ntohs(peer.sin_port);
    conns_.insert(c);
    epoll_event ce{};
    ce.events = EPOLLIN;
    ce.data.ptr = &c->client_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, cfd, &ce);
  }

  void mark_close(Conn* c) {
    if (c->dead) return;
    c->dead = true;
    doomed_.push_back(c);
  }

  void flush_doomed() {
    for (Conn* c : doomed_) {
      if (c->fd >= 0) { epoll_ctl(ep_, EPOLL_CTL_DEL, c->fd, nullptr);
                        close(c->fd); }
      if (c->upstream_fd >= 0) { epoll_ctl(ep_, EPOLL_CTL_DEL,
                                           c->upstream_fd, nullptr);
                                 close(c->upstream_fd); }
      if (c->ticket != UINT64_MAX) awaiting_.erase(c->ticket);
      conns_.erase(c);
      delete c;
    }
    doomed_.clear();
  }

  void set_now(time_t t) { now_ = t; }

  void sweep_idle() {
    for (Conn* c : conns_) {
      if (!c->dead && c->state == ConnState::kReadingHead &&
          now_ - c->last_active > kIdleTimeoutS) {
        mark_close(c);
      }
    }
  }

  void arm(Conn* c, int fd, uint32_t events) {
    epoll_event e{};
    e.events = events;
    e.data.ptr = fd == c->upstream_fd ? &c->upstream_ref : &c->client_ref;
    epoll_ctl(ep_, EPOLL_CTL_MOD, fd, &e);
  }

  // Queue a canned response and switch to drain-then-close.
  void respond_close(Conn* c, const char* response) {
    c->outbuf.append(response);
    c->state = ConnState::kClosing;
    arm(c, c->fd, EPOLLOUT);
  }

  void start_proxy(Conn* c) {
    int ufd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (ufd < 0 ||
        (connect(ufd, reinterpret_cast<const sockaddr*>(&upstream_),
                 sizeof(upstream_)) != 0 &&
         errno != EINPROGRESS)) {
      if (ufd >= 0) close(ufd);
      respond_close(c, k502);
      return;
    }
    c->upstream_fd = ufd;
    c->upbuf = c->inbuf;
    c->state = ConnState::kProxying;
    upstream_conn_[ufd] = c;
    epoll_event ue{};
    ue.events = EPOLLOUT | EPOLLIN;
    ue.data.ptr = &c->upstream_ref;
    epoll_ctl(ep_, EPOLL_CTL_ADD, ufd, &ue);
    arm(c, c->fd, EPOLLIN);
  }

  void drain_verdicts() {
    uint64_t ticket;
    uint8_t action;
    float score;
    while (pingoo_ring_poll_verdict(ring_, &ticket, &action, &score) == 0) {
      auto it = awaiting_.find(ticket);
      if (it == awaiting_.end()) continue;  // connection died meanwhile
      Conn* c = it->second;
      awaiting_.erase(it);
      c->ticket = UINT64_MAX;
      if (c->dead) continue;
      // Verdict byte: bits 0-1 = unverified-client action, bit 2 =
      // verified-client block (native_ring.py RingSidecar). Clients are
      // treated as unverified until the cookie gate lands here.
      uint8_t unverified = action & 3;
      if (unverified == 1) respond_close(c, k403);
      else if (unverified == 2) respond_close(c, kCaptcha);
      else start_proxy(c);
    }
  }

  void on_client_readable(Conn* c) {
    c->last_active = now_;
    char buf[16384];
    ssize_t r;
    while ((r = read(c->fd, buf, sizeof(buf))) > 0) {
      c->inbuf.append(buf, static_cast<size_t>(r));
      if (c->inbuf.size() > kMaxHead) { mark_close(c); return; }
    }
    bool eof = (r == 0);
    size_t head_end = c->inbuf.find("\r\n\r\n");
    if (head_end == std::string::npos) {
      // EOF before a complete head: nothing more will arrive.
      if (eof) mark_close(c);
      return;
    }
    Parsed p = parse_head(c->inbuf.substr(0, head_end + 4));
    if (!p.ok) { respond_close(c, k400); return; }
    // Empty or oversized UA -> 403 before the ring. The >= is the
    // reference's own explicit check (http_listener.rs:196: len >=
    // USER_AGENT_MAX_LENGTH blocks an exactly-256-byte UA); the host
    // cap below is the different, implicit heapless-overflow rule.
    if (p.user_agent.empty() || p.user_agent.size() >= 256) {
      respond_close(c, k403);
      return;
    }
    // Over-long host becomes EMPTY, not truncated (reference get_host,
    // http_listener.rs:284-296).
    if (p.host.size() > 256) p.host.clear();
    uint8_t ip[16] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0xff, 0xff, 0, 0, 0, 0};
    in_addr v4{};
    inet_pton(AF_INET, c->peer_ip, &v4);
    std::memcpy(ip + 12, &v4, 4);
    char country[2] = {'X', 'X'};
    uint64_t ticket = pingoo_ring_enqueue_request(
        ring_, p.method.data(), p.method.size(), p.host.data(), p.host.size(),
        p.path.data(), p.path.size(), p.target.data(), p.target.size(),
        p.user_agent.data(), p.user_agent.size(), ip, c->peer_port, 0,
        country);
    if (ticket == UINT64_MAX) {
      // Verdict ring full (sidecar stalled): FAIL OPEN — proxy without a
      // verdict, like rule-execution errors in the reference
      // (pingoo/rules.rs:41-44).
      start_proxy(c);
      return;
    }
    c->ticket = ticket;
    c->state = ConnState::kAwaitingVerdict;
    awaiting_[ticket] = c;
    arm(c, c->fd, 0);  // quiesce until the verdict arrives
  }

  // Relay src -> pending-buffer/dst without ever dropping bytes.
  // Returns false if the connection should close.
  bool relay(int src, int dst, std::string* pending, bool* src_eof) {
    // Flush pending first.
    while (!pending->empty()) {
      ssize_t w = send(dst, pending->data(), pending->size(), MSG_NOSIGNAL);
      if (w > 0) {
        pending->erase(0, static_cast<size_t>(w));
      } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        break;
      } else {
        return false;
      }
    }
    if (!*src_eof && pending->size() < kMaxBuffered) {
      char buf[16384];
      ssize_t r;
      while ((r = read(src, buf, sizeof(buf))) > 0) {
        size_t off = 0;
        while (off < static_cast<size_t>(r)) {
          ssize_t w = send(dst, buf + off, static_cast<size_t>(r) - off,
                           MSG_NOSIGNAL);
          if (w > 0) {
            off += static_cast<size_t>(w);
          } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            pending->append(buf + off, static_cast<size_t>(r) - off);
            break;
          } else {
            return false;
          }
        }
        if (!pending->empty()) break;  // backpressure: stop reading
      }
      if (r == 0) *src_eof = true;
    }
    if (*src_eof && pending->empty()) return false;  // finished this way
    return true;
  }

  void on_proxy_event(Conn* c, int fd, uint32_t events) {
    c->last_active = now_;
    if (fd == c->upstream_fd && !c->upstream_connected &&
        (events & (EPOLLOUT | EPOLLERR))) {
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(c->upstream_fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0) {  // async connect failed -> 502, not an empty reset
        epoll_ctl(ep_, EPOLL_CTL_DEL, c->upstream_fd, nullptr);
        close(c->upstream_fd);
        upstream_conn_.erase(c->upstream_fd);
        c->upstream_fd = -1;
        respond_close(c, k502);
        return;
      }
      c->upstream_connected = true;
    }
    if (events & (EPOLLHUP | EPOLLERR)) { mark_close(c); return; }
    // Request direction: client -> upstream (upbuf holds the head).
    if (!relay(c->fd, c->upstream_fd, &c->upbuf, &c->client_eof)) {
      if (!c->client_eof) { mark_close(c); return; }
      // client done sending; keep response direction alive
    }
    // Response direction: upstream -> client.
    if (!relay(c->upstream_fd, c->fd, &c->outbuf, &c->upstream_eof)) {
      mark_close(c);
      return;
    }
    uint32_t cl_ev = EPOLLIN;
    if (!c->outbuf.empty()) cl_ev |= EPOLLOUT;
    arm(c, c->fd, cl_ev);
    uint32_t up_ev = EPOLLIN;
    if (!c->upbuf.empty()) up_ev |= EPOLLOUT;
    arm(c, c->upstream_fd, up_ev);
  }

  void on_closing_writable(Conn* c) {
    while (!c->outbuf.empty()) {
      ssize_t w = send(c->fd, c->outbuf.data(), c->outbuf.size(),
                       MSG_NOSIGNAL);
      if (w > 0) {
        c->outbuf.erase(0, static_cast<size_t>(w));
      } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        return;
      } else {
        break;
      }
    }
    mark_close(c);
  }

  void handle(Conn* c, int fd, uint32_t events) {
    if (c->dead) return;  // stale event within this batch
    switch (c->state) {
      case ConnState::kReadingHead:
        if (fd == c->fd && (events & (EPOLLIN | EPOLLHUP)))
          on_client_readable(c);
        break;
      case ConnState::kAwaitingVerdict:
        if (events & (EPOLLHUP | EPOLLERR)) mark_close(c);
        break;
      case ConnState::kProxying:
        on_proxy_event(c, fd, events);
        break;
      case ConnState::kClosing:
        if (events & (EPOLLHUP | EPOLLERR)) mark_close(c);
        else if (fd == c->fd && (events & EPOLLOUT)) on_closing_writable(c);
        break;
    }
  }

 private:
  int ep_;
  void* ring_;
  sockaddr_in upstream_;
  std::unordered_set<Conn*> conns_;
  std::unordered_map<uint64_t, Conn*> awaiting_;
  std::unordered_map<int, Conn*> upstream_conn_;
  std::vector<Conn*> doomed_;
  time_t now_ = 0;
};

}  // namespace

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <listen-port> <ring-file> <upstream-host> "
                 "<upstream-port>\n",
                 argv[0]);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);  // peer resets must not kill the data plane
  int listen_port = std::atoi(argv[1]);
  const char* ring_path = argv[2];
  const char* up_host = argv[3];
  const char* up_port = argv[4];

  // Resolve the upstream (numeric or hostname) up front; fail fast.
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(up_host, up_port, &hints, &res) != 0 || res == nullptr) {
    std::fprintf(stderr, "cannot resolve upstream %s:%s\n", up_host, up_port);
    return 1;
  }
  sockaddr_in upstream{};
  std::memcpy(&upstream, res->ai_addr, sizeof(upstream));
  freeaddrinfo(res);

  int rfd = open(ring_path, O_RDWR);
  if (rfd < 0) { std::perror("open ring"); return 1; }
  struct stat st;
  fstat(rfd, &st);
  void* ring = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                    rfd, 0);
  if (ring == MAP_FAILED || pingoo_ring_attach(ring, nullptr) != 0) {
    std::fprintf(stderr, "ring attach failed\n");
    return 1;
  }

  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  setsockopt(lfd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(listen_port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(lfd, 2048) != 0) {
    std::perror("bind/listen");
    return 1;
  }

  int ep = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;  // nullptr marks the listening socket
  epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);

  Server server(ep, ring, upstream);
  std::printf("{\"listening\": %d}\n", listen_port);
  std::fflush(stdout);

  time_t last_sweep = time(nullptr);
  while (true) {
    epoll_event events[256];
    // Short timeout so verdicts are polled even while sockets are idle.
    int n = epoll_wait(ep, events, 256, 1);
    time_t now = time(nullptr);
    server.set_now(now);
    server.drain_verdicts();

    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {
        while (true) {
          sockaddr_in peer{};
          socklen_t plen = sizeof(peer);
          int cfd = accept4(lfd, reinterpret_cast<sockaddr*>(&peer), &plen,
                            SOCK_NONBLOCK);
          if (cfd < 0) break;
          int nd = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
          server.add_client(cfd, peer);
        }
        continue;
      }
      SockRef* ref = static_cast<SockRef*>(events[i].data.ptr);
      Conn* c = ref->conn;
      int fd = ref->is_upstream ? c->upstream_fd : c->fd;
      server.handle(c, fd, events[i].events);
    }
    server.flush_doomed();
    if (now != last_sweep) {
      server.sweep_idle();
      server.flush_doomed();
      last_sweep = now;
    }
  }
  return 0;
}
