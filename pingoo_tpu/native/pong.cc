// pong: minimal keep-alive HTTP upstream for benchmarks and tests —
// the native equivalent of the reference's pong test server
// (/root/reference/pong/pong.rs: "a Simple HTTP server to test
// Pingoo's capabilities"). Single-threaded epoll, fixed 200 response,
// keep-alive; fast enough that the proxy under test, not the upstream,
// is always the bottleneck.
//
// Usage: pong <port>   (binds 127.0.0.1; prints {"listening": port})

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>

namespace {

const char kResponse[] =
    "HTTP/1.1 200 OK\r\ncontent-type: text/plain\r\n"
    "content-length: 4\r\nconnection: keep-alive\r\n\r\npong";

struct Conn {
  std::string inbuf;
  std::string outbuf;
};

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <port>\n", argv[0]);
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);
  int port = std::atoi(argv[1]);

  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(lfd, 2048) != 0) {
    std::perror("bind/listen");
    return 1;
  }
  if (port == 0) {
    socklen_t alen = sizeof(addr);
    getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
  }

  int ep = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = lfd;
  epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);
  std::unordered_map<int, Conn> conns;

  std::printf("{\"listening\": %d}\n", port);
  std::fflush(stdout);

  while (true) {
    epoll_event events[256];
    int n = epoll_wait(ep, events, 256, -1);
    for (int i = 0; i < n; ++i) {
      int fd = events[i].data.fd;
      if (fd == lfd) {
        while (true) {
          int cfd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          conns[cfd] = Conn();
          epoll_event ce{};
          ce.events = EPOLLIN;
          ce.data.fd = cfd;
          epoll_ctl(ep, EPOLL_CTL_ADD, cfd, &ce);
        }
        continue;
      }
      auto it = conns.find(fd);
      if (it == conns.end()) continue;
      Conn& c = it->second;
      bool closed = false;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) {
        closed = true;
      } else if (events[i].events & EPOLLIN) {
        char buf[16384];
        ssize_t r;
        while ((r = read(fd, buf, sizeof(buf))) > 0)
          c.inbuf.append(buf, static_cast<size_t>(r));
        if (r == 0) closed = true;
        // GET/HEAD requests only: each head is one request.
        size_t he;
        while ((he = c.inbuf.find("\r\n\r\n")) != std::string::npos) {
          c.inbuf.erase(0, he + 4);
          c.outbuf.append(kResponse, sizeof(kResponse) - 1);
        }
        if (c.inbuf.size() > 65536) closed = true;  // junk flood
      }
      if (!closed && !c.outbuf.empty()) {
        ssize_t w = send(fd, c.outbuf.data(), c.outbuf.size(), MSG_NOSIGNAL);
        if (w > 0) c.outbuf.erase(0, static_cast<size_t>(w));
        else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK)
          closed = true;
        epoll_event ce{};
        ce.events = EPOLLIN | (c.outbuf.empty() ? 0 : EPOLLOUT);
        ce.data.fd = fd;
        epoll_ctl(ep, EPOLL_CTL_MOD, fd, &ce);
      }
      if (closed) {
        epoll_ctl(ep, EPOLL_CTL_DEL, fd, nullptr);
        close(fd);
        conns.erase(it);
      }
    }
  }
  return 0;
}
