// Minimal OpenSSL 3 ABI declarations for the native data plane.
//
// This environment ships the OpenSSL 3 RUNTIME (libssl.so.3 /
// libcrypto.so.3) but not the development headers, so the handful of
// functions and constants the TLS transport and JWT verification need
// are declared here against the stable OpenSSL 3.0 ABI (all types are
// opaque pointers; the numeric constants below are fixed ABI values,
// cross-checked against openssl/ssl.h 3.0). Linked with
// -l:libssl.so.3 -l:libcrypto.so.3 (see Makefile).

#ifndef PINGOO_OSSL_SHIM_H_
#define PINGOO_OSSL_SHIM_H_

#include <stddef.h>

extern "C" {

typedef struct ssl_ctx_st SSL_CTX;
typedef struct ssl_st SSL;
typedef struct ssl_method_st SSL_METHOD;
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;
typedef struct x509_verify_param_st X509_VERIFY_PARAM;

// ---- libssl ----
const SSL_METHOD* TLS_server_method(void);
const SSL_METHOD* TLS_client_method(void);
SSL_CTX* SSL_CTX_new(const SSL_METHOD* method);
void SSL_CTX_free(SSL_CTX* ctx);
int SSL_CTX_use_certificate_chain_file(SSL_CTX* ctx, const char* file);
int SSL_CTX_use_PrivateKey_file(SSL_CTX* ctx, const char* file, int type);
int SSL_CTX_check_private_key(const SSL_CTX* ctx);
long SSL_CTX_ctrl(SSL_CTX* ctx, int cmd, long larg, void* parg);
void SSL_CTX_set_client_hello_cb(SSL_CTX* ctx,
                                 int (*cb)(SSL*, int*, void*), void* arg);
void SSL_CTX_set_alpn_select_cb(
    SSL_CTX* ctx,
    int (*cb)(SSL*, const unsigned char**, unsigned char*,
              const unsigned char*, unsigned int, void*),
    void* arg);

// Client-side (upstream connector) surface: verification policy,
// hostname/IP checks, SNI, ALPN offer, buffered-data probes.
int SSL_CTX_set_default_verify_paths(SSL_CTX* ctx);
int SSL_CTX_load_verify_locations(SSL_CTX* ctx, const char* CAfile,
                                  const char* CApath);
void SSL_CTX_set_verify(SSL_CTX* ctx, int mode,
                        int (*verify_callback)(int, void*));
int SSL_CTX_set_alpn_protos(SSL_CTX* ctx, const unsigned char* protos,
                            unsigned int protos_len);

SSL* SSL_new(SSL_CTX* ctx);
void SSL_free(SSL* ssl);
int SSL_set_fd(SSL* ssl, int fd);
void SSL_set_accept_state(SSL* ssl);
void SSL_set_connect_state(SSL* ssl);
int SSL_set1_host(SSL* ssl, const char* hostname);
long SSL_ctrl(SSL* ssl, int cmd, long larg, void* parg);
long SSL_get_verify_result(const SSL* ssl);
int SSL_peek(SSL* ssl, void* buf, int num);
int SSL_pending(const SSL* ssl);
int SSL_has_pending(const SSL* ssl);
X509_VERIFY_PARAM* SSL_get0_param(SSL* ssl);
int X509_VERIFY_PARAM_set1_ip_asc(X509_VERIFY_PARAM* param,
                                  const char* ipasc);
int SSL_do_handshake(SSL* ssl);
int SSL_read(SSL* ssl, void* buf, int num);
int SSL_write(SSL* ssl, const void* buf, int num);
int SSL_shutdown(SSL* ssl);
int SSL_get_error(const SSL* ssl, int ret);
int SSL_is_init_finished(const SSL* ssl);
SSL_CTX* SSL_set_SSL_CTX(SSL* ssl, SSL_CTX* ctx);
const char* SSL_get_servername(const SSL* ssl, const int type);
int SSL_set_alpn_protos(SSL* ssl, const unsigned char* protos,
                        unsigned int protos_len);
void SSL_get0_alpn_selected(const SSL* ssl, const unsigned char** data,
                            unsigned int* len);
int SSL_client_hello_get0_ext(SSL* ssl, unsigned int type,
                              const unsigned char** out, size_t* outlen);
unsigned long ERR_get_error(void);
void ERR_clear_error(void);

#define SSL_FILETYPE_PEM 1
#define SSL_ERROR_NONE 0
#define SSL_ERROR_SSL 1
#define SSL_ERROR_WANT_READ 2
#define SSL_ERROR_WANT_WRITE 3
#define SSL_ERROR_SYSCALL 5
#define SSL_ERROR_ZERO_RETURN 6
#define SSL_CTRL_SET_MIN_PROTO_VERSION 123
#define SSL_CTRL_SET_TLSEXT_HOSTNAME 55
#define SSL_CTRL_MODE 33
#define SSL_MODE_ENABLE_PARTIAL_WRITE 0x1L
#define SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER 0x2L
#define SSL_VERIFY_NONE 0
#define SSL_VERIFY_PEER 1
#define X509_V_OK 0
#define TLS1_2_VERSION 0x0303
#define TLS1_3_VERSION 0x0304
#define TLSEXT_NAMETYPE_host_name 0
#define TLSEXT_TYPE_server_name 0
#define TLSEXT_TYPE_alpn 16
#define SSL_TLSEXT_ERR_OK 0
#define SSL_TLSEXT_ERR_ALERT_FATAL 2
#define SSL_TLSEXT_ERR_NOACK 3
#define SSL_CLIENT_HELLO_SUCCESS 1
#define SSL_CLIENT_HELLO_ERROR 0

static inline long SSL_CTX_set_min_proto_version_shim(SSL_CTX* ctx, int ver) {
  return SSL_CTX_ctrl(ctx, SSL_CTRL_SET_MIN_PROTO_VERSION, ver, nullptr);
}

static inline long SSL_set_tlsext_host_name_shim(SSL* ssl, const char* name) {
  return SSL_ctrl(ssl, SSL_CTRL_SET_TLSEXT_HOSTNAME, TLSEXT_NAMETYPE_host_name,
                  const_cast<char*>(name));
}

static inline long SSL_CTX_set_mode_shim(SSL_CTX* ctx, long mode) {
  return SSL_CTX_ctrl(ctx, SSL_CTRL_MODE, mode, nullptr);
}

// ---- libcrypto ----
int EVP_Digest(const void* data, size_t count, unsigned char* md,
               unsigned int* size, const EVP_MD* type, ENGINE* impl);
const EVP_MD* EVP_sha256(void);

EVP_PKEY* EVP_PKEY_new_raw_public_key(int type, ENGINE* e,
                                      const unsigned char* key, size_t keylen);
void EVP_PKEY_free(EVP_PKEY* pkey);
EVP_MD_CTX* EVP_MD_CTX_new(void);
void EVP_MD_CTX_free(EVP_MD_CTX* ctx);
int EVP_DigestVerifyInit(EVP_MD_CTX* ctx, void** pctx, const EVP_MD* type,
                         ENGINE* e, EVP_PKEY* pkey);
int EVP_DigestVerify(EVP_MD_CTX* ctx, const unsigned char* sig, size_t siglen,
                     const unsigned char* tbs, size_t tbslen);
int CRYPTO_memcmp(const void* a, const void* b, size_t len);

#define EVP_PKEY_ED25519 1087

}  // extern "C"

#endif  // PINGOO_OSSL_SHIM_H_
