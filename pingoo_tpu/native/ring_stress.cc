// Concurrency stress for the verdict ring, built with -fsanitize=thread
// (`make tsan`, run by `make analyze-tsan`): the reference gets its
// data-race guarantees from the Rust type system (SURVEY.md §5 "race
// detection"); the C++ plane gets them from this TSAN job.
//
// Three phases, all self-checking (abort on any violated invariant):
//
//   1. MPMC soak: N producers hammer enqueue while M consumers drain
//      batches, post verdicts, and feed enq_ms back through
//      pingoo_ring_record_waits; M waiters poll verdicts concurrently
//      and a scraper thread reads pingoo_ring_telemetry_snapshot the
//      whole time (the v4 atomic telemetry block added by PR 2 must be
//      race-free under concurrent scrape). The small capacity forces
//      thousands of wrap-arounds of both rings.
//   2. Full-ring: two producers fill the drained request ring to
//      capacity with no consumer — exactly `cap` must fit, the
//      enqueue_full stall counter must move, depth and the high-water
//      mark must read exactly `cap`, and a full drain must zero depth.
//   3. Verdict-ring full: fill the verdict ring, verify the
//      verdict_post_full stall counter moves, drain it back.
//
// After the soak the telemetry identities are checked exactly:
// enqueued == dequeued == verdicts_posted == produced, the wait
// histogram buckets sum to one entry per request, and depth returns
// to zero.

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include "pingoo_ring.h"

namespace {

#define CHECK(cond, ...)                                     \
  do {                                                       \
    if (!(cond)) {                                           \
      std::fprintf(stderr, "ring_stress CHECK failed: %s — ", #cond); \
      std::fprintf(stderr, __VA_ARGS__);                     \
      std::fprintf(stderr, "\n");                            \
      std::abort();                                          \
    }                                                        \
  } while (0)

struct Telemetry {
  uint64_t v[PINGOO_TELEMETRY_WORDS];
  uint64_t enqueued() const { return v[0]; }
  uint64_t enqueue_full() const { return v[1]; }
  uint64_t dequeued() const { return v[2]; }
  uint64_t depth() const { return v[3]; }
  uint64_t depth_hwm() const { return v[4]; }
  uint64_t verdicts_posted() const { return v[5]; }
  uint64_t verdict_post_full() const { return v[6]; }
  uint64_t wait_sum_ms() const { return v[7]; }
  uint64_t wait_hist_total() const {
    uint64_t t = 0;
    for (uint32_t b = 0; b < PINGOO_WAIT_BUCKETS; ++b) t += v[8 + b];
    return t;
  }
};

Telemetry snap(void* ring) {
  Telemetry t;
  pingoo_ring_telemetry_snapshot(ring, t.v);
  return t;
}

long env_long(const char* name, long fallback) {
  const char* s = std::getenv(name);
  return s && *s ? std::atol(s) : fallback;
}

}  // namespace

int main() {
  const uint32_t cap = 256;
  const int kProducers = 4;
  const int kConsumers = 2;
  const int kWaiters = 2;
  const long kPerProducer = env_long("PINGOO_STRESS_PER_PRODUCER", 20000);
  const long kTotal = kProducers * kPerProducer;
  std::vector<char> mem(pingoo_ring_bytes(cap));
  pingoo_ring_init(mem.data(), cap);
  void* ring = mem.data();

  std::atomic<long> produced{0}, consumed{0}, verdicts{0};
  std::atomic<bool> stop_scraper{false};

  // -- phase 1: MPMC soak -------------------------------------------------

  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      uint8_t ip[16] = {0};
      char country[2] = {'U', 'S'};
      for (long i = 0; i < kPerProducer;) {
        uint64_t t = pingoo_ring_enqueue_request(
            ring, "GET", 3, "h", 1, "/p", 2, "/p?x", 4, "UA", 2, ip,
            static_cast<uint16_t>(p), 1, country);
        if (t != UINT64_MAX) { ++i; produced.fetch_add(1); }
        else std::this_thread::yield();
      }
    });
  }

  std::vector<std::thread> consumers;
  for (int c = 0; c < kConsumers; ++c) {
    consumers.emplace_back([&] {
      std::vector<PingooRequestSlot> batch(cap);
      std::vector<uint64_t> enq_ms(cap);
      while (consumed.load() < kTotal) {
        uint32_t n = pingoo_ring_dequeue_requests(ring, batch.data(), cap);
        for (uint32_t i = 0; i < n; ++i) {
          if (batch[i].path_len != 2 ||
              std::memcmp(batch[i].path, "/p", 2) != 0) {
            std::fprintf(stderr, "corrupt slot!\n");
            std::abort();
          }
          enq_ms[i] = batch[i].enq_ms;
          while (pingoo_ring_post_verdict(ring, batch[i].ticket,
                                          batch[i].ticket % 3, 0.5f) != 0)
            std::this_thread::yield();
        }
        if (n) {
          // Feed enqueue->verdict-post waits into the shared wait
          // histogram exactly once per dequeued slot, like the sidecar.
          pingoo_ring_record_waits(ring, enq_ms.data(), n);
          consumed.fetch_add(n);
        } else {
          std::this_thread::yield();
        }
      }
    });
  }

  std::vector<std::thread> waiters;
  for (int w = 0; w < kWaiters; ++w) {
    waiters.emplace_back([&] {
      uint64_t t; uint8_t a; float s;
      while (verdicts.load() < kTotal) {
        if (pingoo_ring_poll_verdict(ring, &t, &a, &s) == 0) {
          if (a != t % 3) {
            std::fprintf(stderr, "verdict mismatch\n");
            std::abort();
          }
          verdicts.fetch_add(1);
        } else {
          std::this_thread::yield();
        }
      }
    });
  }

  // Concurrent scraper: the telemetry block must be readable while
  // every counter is being hammered (TSAN proves the loads race-free;
  // the asserts prove the snapshot is never wildly inconsistent).
  std::thread scraper([&] {
    uint64_t last_enqueued = 0;
    while (!stop_scraper.load()) {
      Telemetry t = snap(ring);
      CHECK(t.depth() <= cap, "live depth %llu > cap",
            (unsigned long long)t.depth());
      CHECK(t.depth_hwm() <= cap, "live hwm %llu > cap",
            (unsigned long long)t.depth_hwm());
      CHECK(t.enqueued() >= last_enqueued,
            "enqueued went backwards: %llu < %llu",
            (unsigned long long)t.enqueued(),
            (unsigned long long)last_enqueued);
      last_enqueued = t.enqueued();
      std::this_thread::yield();
    }
  });

  for (auto& th : producers) th.join();
  for (auto& th : consumers) th.join();
  for (auto& th : waiters) th.join();
  stop_scraper.store(true);
  scraper.join();

  CHECK(produced.load() == kTotal, "produced %ld", produced.load());
  CHECK(consumed.load() == kTotal, "consumed %ld", consumed.load());
  CHECK(verdicts.load() == kTotal, "verdicts %ld", verdicts.load());

  Telemetry t1 = snap(ring);
  CHECK(t1.enqueued() == (uint64_t)kTotal, "enqueued %llu != %ld",
        (unsigned long long)t1.enqueued(), kTotal);
  CHECK(t1.dequeued() == (uint64_t)kTotal, "dequeued %llu",
        (unsigned long long)t1.dequeued());
  CHECK(t1.verdicts_posted() == (uint64_t)kTotal, "posted %llu",
        (unsigned long long)t1.verdicts_posted());
  CHECK(t1.depth() == 0, "depth %llu after drain",
        (unsigned long long)t1.depth());
  CHECK(t1.depth_hwm() >= 1 && t1.depth_hwm() <= cap, "hwm %llu",
        (unsigned long long)t1.depth_hwm());
  CHECK(t1.wait_hist_total() == (uint64_t)kTotal,
        "wait hist holds %llu entries, want %ld",
        (unsigned long long)t1.wait_hist_total(), kTotal);

  // -- phase 2: request ring full / wrap-around ---------------------------

  {
    std::atomic<long> fit{0};
    std::vector<std::thread> fillers;
    for (int p = 0; p < 2; ++p) {
      fillers.emplace_back([&, p] {
        uint8_t ip[16] = {0};
        char country[2] = {'D', 'E'};
        for (;;) {
          uint64_t t = pingoo_ring_enqueue_request(
              ring, "GET", 3, "h", 1, "/f", 2, "/f", 2, "UA", 2, ip,
              static_cast<uint16_t>(p), 2, country);
          if (t == UINT64_MAX) break;  // ring full: this thread is done
          fit.fetch_add(1);
        }
      });
    }
    for (auto& th : fillers) th.join();
    Telemetry t2 = snap(ring);
    CHECK(fit.load() == (long)cap, "full ring accepted %ld != cap %u",
          fit.load(), cap);
    CHECK(t2.depth() == cap, "full depth %llu",
          (unsigned long long)t2.depth());
    CHECK(t2.depth_hwm() == cap, "hwm %llu after deliberate fill",
          (unsigned long long)t2.depth_hwm());
    CHECK(t2.enqueue_full() >= t1.enqueue_full() + 2,
          "enqueue_full did not move: %llu -> %llu",
          (unsigned long long)t1.enqueue_full(),
          (unsigned long long)t2.enqueue_full());

    std::vector<PingooRequestSlot> batch(cap);
    uint32_t drained = 0;
    while (drained < cap)
      drained += pingoo_ring_dequeue_requests(ring, batch.data(), cap);
    Telemetry t3 = snap(ring);
    CHECK(drained == cap, "drained %u", drained);
    CHECK(t3.depth() == 0, "depth %llu after full drain",
          (unsigned long long)t3.depth());
  }

  // -- phase 3: verdict ring full -----------------------------------------

  {
    Telemetry before = snap(ring);
    for (uint32_t i = 0; i < cap; ++i)
      CHECK(pingoo_ring_post_verdict(ring, i, 1, 0.0f) == 0,
            "verdict ring refused slot %u of cap", i);
    CHECK(pingoo_ring_post_verdict(ring, cap, 1, 0.0f) == -1,
          "post into a full verdict ring must fail");
    Telemetry after = snap(ring);
    CHECK(after.verdict_post_full() >= before.verdict_post_full() + 1,
          "verdict_post_full did not move");
    uint64_t t; uint8_t a; float s;
    for (uint32_t i = 0; i < cap; ++i)
      CHECK(pingoo_ring_poll_verdict(ring, &t, &a, &s) == 0,
            "poll %u of cap failed", i);
    CHECK(pingoo_ring_poll_verdict(ring, &t, &a, &s) == -1,
          "drained verdict ring must read empty");
  }

  Telemetry tf = snap(ring);
  std::printf(
      "{\"produced\": %ld, \"consumed\": %ld, \"verdicts\": %ld, "
      "\"depth_hwm\": %llu, \"enqueue_full\": %llu, "
      "\"verdict_post_full\": %llu, \"wait_hist_total\": %llu}\n",
      produced.load(), consumed.load(), verdicts.load(),
      (unsigned long long)tf.depth_hwm(),
      (unsigned long long)tf.enqueue_full(),
      (unsigned long long)tf.verdict_post_full(),
      (unsigned long long)tf.wait_hist_total());
  return 0;
}
