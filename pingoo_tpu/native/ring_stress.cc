// Concurrency stress for the verdict ring, built with -fsanitize=thread
// (`make tsan`): N producer threads hammer enqueue while one consumer
// drains and posts verdicts and M waiters poll them. The reference gets
// its data-race guarantees from the Rust type system (SURVEY.md §5
// "race detection"); the C++ plane gets them from this TSAN job.

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include "pingoo_ring.h"

int main() {
  const uint32_t cap = 256;
  const int kProducers = 4;
  const long kPerProducer = 20000;
  std::vector<char> mem(pingoo_ring_bytes(cap));
  pingoo_ring_init(mem.data(), cap);
  void* ring = mem.data();

  std::atomic<long> produced{0}, consumed{0}, verdicts{0};
  std::atomic<bool> done{false};

  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      uint8_t ip[16] = {0};
      char country[2] = {'U', 'S'};
      for (long i = 0; i < kPerProducer;) {
        uint64_t t = pingoo_ring_enqueue_request(
            ring, "GET", 3, "h", 1, "/p", 2, "/p?x", 4, "UA", 2, ip,
            static_cast<uint16_t>(p), 1, country);
        if (t != UINT64_MAX) { ++i; produced.fetch_add(1); }
        else std::this_thread::yield();
      }
    });
  }

  std::thread consumer([&] {
    std::vector<PingooRequestSlot> batch(cap);
    while (consumed.load() < kProducers * kPerProducer) {
      uint32_t n = pingoo_ring_dequeue_requests(ring, batch.data(), cap);
      for (uint32_t i = 0; i < n; ++i) {
        if (batch[i].path_len != 2 || std::memcmp(batch[i].path, "/p", 2)) {
          std::fprintf(stderr, "corrupt slot!\n");
          std::abort();
        }
        while (pingoo_ring_post_verdict(ring, batch[i].ticket,
                                        batch[i].ticket % 3, 0.5f) != 0)
          std::this_thread::yield();
      }
      consumed.fetch_add(n);
      if (n == 0) std::this_thread::yield();
    }
    done.store(true);
  });

  std::thread waiter([&] {
    uint64_t t; uint8_t a; float s;
    while (!done.load() || verdicts.load() < kProducers * kPerProducer) {
      if (pingoo_ring_poll_verdict(ring, &t, &a, &s) == 0) {
        if (a != t % 3) { std::fprintf(stderr, "verdict mismatch\n");
                          std::abort(); }
        verdicts.fetch_add(1);
      } else {
        std::this_thread::yield();
      }
    }
  });

  for (auto& th : producers) th.join();
  consumer.join();
  waiter.join();
  std::printf("{\"produced\": %ld, \"consumed\": %ld, \"verdicts\": %ld}\n",
              produced.load(), consumed.load(), verdicts.load());
  return 0;
}
