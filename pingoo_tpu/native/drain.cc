// Canned-verdict drain: the NATIVE stand-in for the TPU sidecar in the
// dataplane bench (bench.py bench_dataplane; VERDICT r3 item 5 / r4
// item 6). Dequeues request batches from N worker rings, decides
// block/none with a memmem scan over the url bytes (matching
// loadgen_http's attack markers), and posts verdicts back batched —
// the same transport path as native_ring.RingSidecar with the device
// verdict replaced by a content check, so `dataplane_req_per_s`
// measures the C++ plane + ring, not a Python drain thread sharing the
// core.
//
// usage: drain <ring-file> [<ring-file> ...]
// Prints "draining <n>" once attached; exits on SIGTERM/SIGINT after a
// final JSON stats line on stdout.

#include <fcntl.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <ctime>
#include <vector>

#include "pingoo_ring.h"

static volatile sig_atomic_t g_stop = 0;
static void on_sig(int) { g_stop = 1; }

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <ring-file> [...]\n", argv[0]);
    return 2;
  }
  signal(SIGTERM, on_sig);
  signal(SIGINT, on_sig);

  std::vector<void*> rings;
  uint32_t cap_max = 0;
  for (int i = 1; i < argc; ++i) {
    int fd = open(argv[i], O_RDWR);
    if (fd < 0) {
      std::fprintf(stderr, "cannot open %s\n", argv[i]);
      return 1;
    }
    struct stat st;
    fstat(fd, &st);
    void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    uint32_t cap = 0;
    if (mem == MAP_FAILED || pingoo_ring_attach(mem, &cap) != 0) {
      std::fprintf(stderr, "bad ring %s\n", argv[i]);
      return 1;
    }
    if (cap > cap_max) cap_max = cap;
    rings.push_back(mem);
  }
  std::printf("draining %zu\n", rings.size());
  std::fflush(stdout);

  std::vector<PingooRequestSlot> slots(cap_max);
  std::vector<uint64_t> tickets(cap_max);
  std::vector<uint64_t> enq_ms(cap_max);
  std::vector<uint8_t> actions(cap_max);
  static const char* kMarkers[] = {"<script", "eval("};
  unsigned long long drained = 0, blocked = 0;

  while (!g_stop) {
    uint32_t total = 0;
    for (void* ring : rings) {
      uint32_t n = pingoo_ring_dequeue_requests(ring, slots.data(), 2048);
      if (n == 0) continue;
      total += n;
      for (uint32_t j = 0; j < n; ++j) {
        const PingooRequestSlot& s = slots[j];
        tickets[j] = s.ticket;
        enq_ms[j] = s.enq_ms;
        uint8_t act = 0;
        for (const char* m : kMarkers) {
          if (memmem(s.url, s.url_len, m, strlen(m)) != nullptr) {
            act = 1;
            break;
          }
        }
        actions[j] = act;
        blocked += act;
      }
      uint32_t done = 0;
      while (done < n && !g_stop) {
        done += pingoo_ring_post_verdicts(ring, tickets.data() + done,
                                          actions.data() + done, n - done);
        if (done < n) {
          struct timespec ts {0, 200000};  // 200 us: verdict ring full
          nanosleep(&ts, nullptr);
        }
      }
      // Feed the telemetry block's enqueue->post wait histogram so the
      // dataplane bench's scrape carries ring waits too.
      pingoo_ring_record_waits(ring, enq_ms.data(), n);
      drained += n;
    }
    if (total == 0) {
      struct timespec ts {0, 200000};
      nanosleep(&ts, nullptr);
    }
  }
  std::printf("{\"drained\": %llu, \"blocked\": %llu}\n", drained, blocked);
  return 0;
}
