// -- upstream HTTP/2 link ----------------------------------------------------
// The reference's pooled hyper client speaks h2 to upstreams — via ALPN
// on TLS hops or cleartext prior knowledge for h2:// targets
// (http_proxy_service.rs:54-71). This bridge keeps the rest of the
// proxy h1-shaped: the request side parses the ALREADY-REWRITTEN h1
// head (rewrite_request_head / h2_upstream_head output) into h2
// frames, and the response side synthesizes well-formed h1 bytes from
// the h2 response, which the existing RespHead/BodyFramer machinery
// consumes unchanged on both downstream paths.

#ifndef PINGOO_UP_H2_LINK_H_
#define PINGOO_UP_H2_LINK_H_

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

#include "nghttp2_shim.h"

struct UpH2Link {
  nghttp2_session* sess = nullptr;
  int32_t sid = -1;
  std::string body;  // de-framed request body pending DATA frames
  bool body_eof = false;
  bool data_deferred = false;
  int status = 0;
  std::vector<std::pair<std::string, std::string>> resp_headers;
  // Response TRAILERS (a HEADERS frame after the head): forwarded as h1
  // chunked trailers after the 0-chunk — gRPC's grpc-status/-message
  // live there and must survive the h2->h1 bridge.
  std::vector<std::pair<std::string, std::string>> resp_trailers;
  bool resp_headers_done = false;
  bool resp_done = false;  // END_STREAM seen
  bool failed = false;     // stream/session error: caller 502s/aborts
  bool goaway = false;     // session not reusable after this response
  bool head_emitted = false;
  bool chunked_out = false;
  std::string synth;  // synthesized h1 response bytes

  ~UpH2Link() {
    if (sess != nullptr) nghttp2_session_del(sess);
  }

  static ssize_t read_body(nghttp2_session*, int32_t, uint8_t* buf,
                           size_t length, uint32_t* data_flags,
                           nghttp2_data_source* source, void*) {
    UpH2Link* l = static_cast<UpH2Link*>(source->ptr);
    if (l->body.empty()) {
      if (l->body_eof) {
        *data_flags = NGHTTP2_DATA_FLAG_EOF;
        return 0;
      }
      l->data_deferred = true;
      return NGHTTP2_ERR_DEFERRED;
    }
    size_t n = l->body.size() < length ? l->body.size() : length;
    memcpy(buf, l->body.data(), n);
    l->body.erase(0, n);
    return static_cast<ssize_t>(n);
  }

  static int on_header(nghttp2_session*, const void* frame,
                       const uint8_t* name, size_t namelen,
                       const uint8_t* value, size_t valuelen, uint8_t,
                       void* user_data) {
    UpH2Link* l = static_cast<UpH2Link*>(user_data);
    const auto* hd = static_cast<const nghttp2_frame_hd*>(frame);
    if (hd->type != NGHTTP2_FRAME_HEADERS || hd->stream_id != l->sid)
      return 0;
    std::string n(reinterpret_cast<const char*>(name), namelen);
    std::string v(reinterpret_cast<const char*>(value), valuelen);
    if (l->head_emitted) {
      // A HEADERS frame after the emitted head is the trailer section.
      if (!n.empty() && n[0] != ':')
        l->resp_trailers.emplace_back(std::move(n), std::move(v));
      return 0;
    }
    if (n == ":status") {
      l->status = atoi(v.c_str());
    } else if (!n.empty() && n[0] != ':') {
      l->resp_headers.emplace_back(std::move(n), std::move(v));
    }
    return 0;
  }

  void emit_head(bool end_stream) {
    // Interim (1xx) responses re-arm for the final head; the existing
    // h1 response parser relays them the same way it does for h1
    // upstreams.
    bool interim = status >= 100 && status < 200;
    synth += "HTTP/1.1 " + std::to_string(status) + " \r\n";
    bool have_cl = false;
    for (const auto& kv : resp_headers) {
      // h2 carries no connection-specific headers, but defensively
      // skip any the peer smuggled (they would corrupt h1 framing).
      if (kv.first == "connection" || kv.first == "transfer-encoding" ||
          kv.first == "keep-alive" || kv.first == "upgrade")
        continue;
      if (kv.first == "content-length") have_cl = true;
      synth += kv.first + ": " + kv.second + "\r\n";
    }
    if (!interim) {
      if (end_stream && !have_cl) {
        synth += "content-length: 0\r\n";
      } else if (!have_cl) {
        chunked_out = true;
        synth += "transfer-encoding: chunked\r\n";
      }
    }
    synth += "\r\n";
    if (interim) {
      status = 0;
      resp_headers.clear();
    } else {
      head_emitted = true;
      resp_headers_done = true;
    }
  }

  static int on_frame_recv(nghttp2_session*, const void* frame,
                           void* user_data) {
    UpH2Link* l = static_cast<UpH2Link*>(user_data);
    const auto* hd = static_cast<const nghttp2_frame_hd*>(frame);
    if (hd->type == NGHTTP2_FRAME_GOAWAY) {
      l->goaway = true;
      return 0;
    }
    if (hd->stream_id != l->sid) return 0;
    bool end_stream = (hd->flags & NGHTTP2_FLAG_END_STREAM) != 0;
    if (hd->type == NGHTTP2_FRAME_HEADERS && !l->head_emitted &&
        (hd->flags & NGHTTP2_FLAG_END_HEADERS) != 0) {
      l->emit_head(end_stream);
    }
    if (end_stream && l->head_emitted && !l->resp_done) {
      if (l->chunked_out) {
        // h1 chunked framing carries trailers between the 0-chunk and
        // the final CRLF; the downstream h1 relay passes the raw bytes
        // through, and the BodyFramer's kTrailer state consumes them
        // where the body is de-chunked (h2 downstream re-framing drops
        // them — recorded in COMPONENTS.md Known deltas).
        l->synth += "0\r\n";
        for (const auto& kv : l->resp_trailers)
          l->synth += kv.first + ": " + kv.second + "\r\n";
        l->synth += "\r\n";
      }
      l->resp_done = true;
    }
    return 0;
  }

  static int on_data_chunk(nghttp2_session*, uint8_t, int32_t stream_id,
                           const uint8_t* data, size_t len,
                           void* user_data) {
    UpH2Link* l = static_cast<UpH2Link*>(user_data);
    if (stream_id != l->sid || !l->head_emitted) return 0;
    if (l->chunked_out) {
      char sz[32];
      snprintf(sz, sizeof(sz), "%zx\r\n", len);
      l->synth += sz;
      l->synth.append(reinterpret_cast<const char*>(data), len);
      l->synth += "\r\n";
    } else {
      l->synth.append(reinterpret_cast<const char*>(data), len);
    }
    return 0;
  }

  static int on_stream_close(nghttp2_session*, int32_t stream_id,
                             uint32_t error_code, void* user_data) {
    UpH2Link* l = static_cast<UpH2Link*>(user_data);
    if (stream_id != l->sid) return 0;
    if (error_code != 0 || !l->resp_done) l->failed = true;
    return 0;
  }

  bool init() {
    nghttp2_session_callbacks* cbs = nullptr;
    if (nghttp2_session_callbacks_new(&cbs) != 0) return false;
    nghttp2_session_callbacks_set_on_header_callback(cbs, on_header);
    nghttp2_session_callbacks_set_on_frame_recv_callback(cbs,
                                                         on_frame_recv);
    nghttp2_session_callbacks_set_on_data_chunk_recv_callback(
        cbs, on_data_chunk);
    nghttp2_session_callbacks_set_on_stream_close_callback(
        cbs, on_stream_close);
    int rv = nghttp2_session_client_new(&sess, cbs, this);
    nghttp2_session_callbacks_del(cbs);
    if (rv != 0) return false;
    nghttp2_settings_entry iv[] = {
        {NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS, 64}};
    return nghttp2_submit_settings(sess, 0, iv, 1) == 0;
  }

  // Re-arm per-request state for a POOLED session's next request.
  void reset_for_reuse() {
    sid = -1;
    body.clear();
    body_eof = false;
    data_deferred = false;
    status = 0;
    resp_headers.clear();
    resp_trailers.clear();
    resp_headers_done = false;
    resp_done = false;
    head_emitted = false;
    chunked_out = false;
    synth.clear();
  }

  // Parse the proxy's own rewritten h1 request head (well-formed by
  // construction) into an h2 request. `tls` picks :scheme.
  bool submit(const std::string& h1_head, bool tls, bool has_body) {
    size_t line_end = h1_head.find("\r\n");
    if (line_end == std::string::npos) return false;
    std::string first = h1_head.substr(0, line_end);
    size_t sp1 = first.find(' ');
    size_t sp2 = first.rfind(' ');
    if (sp1 == std::string::npos || sp2 <= sp1) return false;
    std::string method = first.substr(0, sp1);
    std::string target = first.substr(sp1 + 1, sp2 - sp1 - 1);
    std::string scheme = tls ? "https" : "http";
    std::string authority;
    std::vector<std::pair<std::string, std::string>> hdrs;
    size_t pos = line_end + 2;
    while (pos < h1_head.size()) {
      size_t eol = h1_head.find("\r\n", pos);
      if (eol == std::string::npos || eol == pos) break;
      size_t colon = h1_head.find(':', pos);
      if (colon == std::string::npos || colon >= eol) return false;
      std::string nm = h1_head.substr(pos, colon - pos);
      for (auto& ch : nm)
        ch = static_cast<char>(tolower(static_cast<unsigned char>(ch)));
      size_t vs = colon + 1;
      while (vs < eol && h1_head[vs] == ' ') vs++;
      std::string val = h1_head.substr(vs, eol - vs);
      pos = eol + 2;
      if (nm == "host") {
        authority = val;
        continue;
      }
      // connection-specific headers are forbidden on h2
      if (nm == "connection" || nm == "keep-alive" ||
          nm == "transfer-encoding" || nm == "upgrade" || nm == "te")
        continue;
      hdrs.emplace_back(std::move(nm), std::move(val));
    }
    std::vector<nghttp2_nv> nva;
    auto nv = [&](const std::string& n, const std::string& v) {
      nghttp2_nv e;
      e.name = reinterpret_cast<uint8_t*>(const_cast<char*>(n.data()));
      e.namelen = n.size();
      e.value = reinterpret_cast<uint8_t*>(const_cast<char*>(v.data()));
      e.valuelen = v.size();
      e.flags = NGHTTP2_NV_FLAG_NONE;
      nva.push_back(e);
    };
    static const std::string kM = ":method", kP = ":path", kS = ":scheme",
                             kA = ":authority";
    nv(kM, method);
    nv(kS, scheme);
    if (!authority.empty()) nv(kA, authority);
    nv(kP, target);
    for (const auto& kv : hdrs) nv(kv.first, kv.second);
    nghttp2_data_provider prd{};
    prd.source.ptr = this;
    prd.read_callback = read_body;
    sid = nghttp2_submit_request(sess, nullptr, nva.data(), nva.size(),
                                 has_body ? &prd : nullptr, nullptr);
    return sid > 0;
  }

  void append_body(const char* d, size_t n) {
    body.append(d, n);
    if (data_deferred && sess != nullptr && sid > 0) {
      data_deferred = false;
      nghttp2_session_resume_data(sess, sid);
    }
  }

  void finish_body() {
    body_eof = true;
    if (data_deferred && sess != nullptr && sid > 0) {
      data_deferred = false;
      nghttp2_session_resume_data(sess, sid);
    }
  }

  // Frames the session wants on the wire -> append to *out.
  bool pump_send(std::string* out) {
    for (;;) {
      const uint8_t* data = nullptr;
      ssize_t n = nghttp2_session_mem_send(sess, &data);
      if (n < 0) return false;
      if (n == 0) return true;
      out->append(reinterpret_cast<const char*>(data),
                  static_cast<size_t>(n));
    }
  }

  // Bytes off the wire -> synthesized h1 into *out. False on fatal.
  bool feed(const char* d, size_t n, std::string* out) {
    ssize_t rv = nghttp2_session_mem_recv(
        sess, reinterpret_cast<const uint8_t*>(d), n);
    if (rv < 0 || static_cast<size_t>(rv) != n) return false;
    if (!synth.empty()) {
      out->append(synth);
      synth.clear();
    }
    return !failed;
  }
};

#endif  // PINGOO_UP_H2_LINK_H_
