"""Verdict provenance: per-rule attribution lanes and the shadow-parity
auditor (ISSUE 5, docs/OBSERVABILITY.md).

Three pieces, shared by both verdict-engine planes (the Python listener
service, plane="python", and the ring sidecar backing the native data
plane, plane="sidecar"):

  * `RuleAttribution` — cardinality-bounded per-rule hit counters. The
    fold input is either the host-side match matrix sum (the Python
    plane already ships the [B, R] matrix back for finish_batch, so the
    fold is one vector add) or the on-device [R_dev] hit-count aux lane
    that rides the sidecar's existing lane dispatch (engine/verdict.py
    make_lane_fn(with_rule_hits=True) — no extra transfer beyond R_dev
    int32s). Exposition is bounded: the top-K rules by cumulative hits
    get labelled `pingoo_rule_hits_total{rule=...}` series, everything
    else folds into one `rule="_overflow"` series, so a 500-rule plan
    costs K+1 series, not 500.

  * `PrefilterAttribution` — per-gated-bank candidate rates and skip
    counters from the Stage-A aux vector (engine/verdict.py
    make_prefilter_fn), labelled by bank key. Bank cardinality is small
    by construction (a handful of byte fields x at most three sub-banks
    each).

  * `ParityAuditor` — the always-on sampler: a configurable fraction
    (PINGOO_PARITY_SAMPLE, a 0..1 batch fraction) of live batches is
    re-evaluated through the host expression interpreter on a dedicated
    worker thread, OFF the dispatch hot path (the hot-path side of the
    auditor only flips a sampling accumulator and enqueues a reference;
    tools/analyze lint registers it hot so a bare device sync there
    fails `make analyze`). Verdict-bitmap diffs feed
    pingoo_parity_checked_total / pingoo_parity_mismatch_total plus a
    bounded per-rule breakdown, and mismatching requests are marked in
    the flight recorder with full provenance.

Fault injection (chaos/testing only): PINGOO_PARITY_FAULT_INJECT=<path
prefix> makes the auditor's ORACLE flip rule 0's bit for matching
requests — the served verdict is untouched; the knob exists so
`make metrics-smoke` and tests can prove an injected divergence is
observable end to end (metrics + flight-recorder dump).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from .flightrecorder import PARITY_MISMATCH, PARITY_OK

DEFAULT_TOP_K = 20
# Hard cap on distinct rule-labelled series EVER created per family:
# registry instruments cannot be removed, so top-K churn is allowed to
# create at most this many before new entrants stay in "_overflow".
RULE_SERIES_CAP = 64
OVERFLOW_LABEL = "_overflow"


def provenance_enabled() -> bool:
    return os.environ.get("PINGOO_PROVENANCE", "1") != "0"


def attribution_top_k() -> int:
    try:
        return max(1, int(os.environ.get("PINGOO_ATTR_TOP_K",
                                         str(DEFAULT_TOP_K))))
    except ValueError:
        return DEFAULT_TOP_K


def parity_sample_fraction() -> float:
    try:
        frac = float(os.environ.get("PINGOO_PARITY_SAMPLE", "0"))
    except ValueError:
        return 0.0
    return min(max(frac, 0.0), 1.0)


class RuleAttribution:
    """Per-rule hit counters with bounded exposition cardinality.

    Counts accumulate per ORIGINAL rule index in a flat int64 vector;
    the registry collector (runs at scrape time, off the hot path)
    materializes the top-K labelled series. A labelled series exports
    hits SINCE ITS CREATION (base-offset subtraction) so the
    "_overflow" remainder stays a monotone counter even as rules are
    promoted into the labelled set."""

    def __init__(self, rule_names, plane: str, registry=None,
                 top_k: Optional[int] = None):
        from . import schema

        if registry is None:
            from . import REGISTRY as registry  # noqa: N813
        self.rule_names = tuple(rule_names)
        self.plane = plane
        self.top_k = top_k or attribution_top_k()
        self._registry = registry
        self._counts = np.zeros(len(self.rule_names), dtype=np.int64)
        self._bases: dict[int, int] = {}  # rule idx -> count at creation
        self._series: dict[int, object] = {}  # rule idx -> Counter
        help_text = schema.PROVENANCE_METRICS["pingoo_rule_hits_total"]
        self._overflow = registry.counter(
            "pingoo_rule_hits_total", help_text,
            labels={"plane": plane, "rule": OVERFLOW_LABEL})
        self._help = help_text
        registry.register_collector(self._export)

    def close(self) -> None:
        self._registry.unregister_collector(self._export)

    def fold_batch(self, hit_counts, indices=None) -> None:
        """Fold one batch's per-rule hit counts (hot path: one vector
        add). `hit_counts` is [R] int (original-index order) or — on the
        lane plane — the device aux lane in device-column order with
        `indices` mapping columns to original rule indices; the
        materialization below lands AFTER the batch's lane sync, so it
        never blocks on the device."""
        # pingoo: allow(sync-asarray-hot): aux lane resolved with the batch's lane sync
        vals = np.asarray(hit_counts, dtype=np.int64)
        if indices is not None:
            np.add.at(self._counts, indices, vals)
        else:
            self._counts += vals

    @property
    def total_hits(self) -> int:
        return int(self._counts.sum())

    def snapshot(self, k: Optional[int] = None) -> dict:
        """Top-k rules by cumulative hits + the remainder (JSON view)."""
        k = k or self.top_k
        order = np.argsort(self._counts)[::-1][:k]
        top = [(self.rule_names[int(i)], int(self._counts[int(i)]))
               for i in order if self._counts[int(i)] > 0]
        covered = sum(c for _, c in top)
        return {"top": top, "other": self.total_hits - covered,
                "total": self.total_hits}

    def _export(self) -> None:
        """Registry collector: keep every existing labelled series
        current, promote new top-K entrants (bounded by
        RULE_SERIES_CAP), and fold the rest into "_overflow"."""
        if not len(self._counts):
            return
        order = np.argsort(self._counts)[::-1][: self.top_k]
        for i in order:
            i = int(i)
            if (self._counts[i] > 0 and i not in self._series
                    and len(self._series) < RULE_SERIES_CAP):
                self._bases[i] = int(self._counts[i])
                self._series[i] = self._registry.counter(
                    "pingoo_rule_hits_total", self._help,
                    labels={"plane": self.plane,
                            "rule": self.rule_names[i]})
                # The promoted rule's PAST hits stay in _overflow (its
                # base), so both series remain monotone.
        exported = 0
        for i, counter in self._series.items():
            since = int(self._counts[i]) - self._bases[i]
            counter.set_total(since)
            exported += self._bases[i] + since
        self._overflow.set_total(self.total_hits - exported
                                 + sum(self._bases.values()))


class PrefilterAttribution:
    """Per-gated-bank candidate rates + skip counters from the Stage-A
    aux vector (layout: [cand_total, skip_total, per-bank candidate
    counts..., per-bank skip flags...], engine/verdict.make_prefilter_fn)."""

    def __init__(self, masked_keys, plane: str, registry=None):
        from . import schema

        if registry is None:
            from . import REGISTRY as registry  # noqa: N813
        self.masked_keys = tuple(masked_keys)
        self._rate_gauges = [registry.gauge(
            "pingoo_prefilter_bank_candidate_rate",
            schema.PROVENANCE_METRICS[
                "pingoo_prefilter_bank_candidate_rate"],
            labels={"plane": plane, "bank": key})
            for key in self.masked_keys]
        self._skip_counters = [registry.counter(
            "pingoo_scan_bank_skipped_total",
            schema.PROVENANCE_METRICS["pingoo_scan_bank_skipped_total"],
            labels={"plane": plane, "bank": key})
            for key in self.masked_keys]

    def observe(self, aux_vals: np.ndarray, batch_rows: int) -> None:
        """`aux_vals` is the already-materialized host aux vector (the
        caller owns the one sanctioned sync for it)."""
        m = len(self.masked_keys)
        if m == 0 or len(aux_vals) < 2 + 2 * m or not batch_rows:
            return
        cand = aux_vals[2:2 + m]
        skip = aux_vals[2 + m:2 + 2 * m]
        for j in range(m):
            self._rate_gauges[j].set(round(int(cand[j]) / batch_rows, 4))
            self._skip_counters[j].inc(int(skip[j]))


class ParityAuditor:
    """Always-on shadow-parity sampler (see module docstring).

    Hot-path surface: `submit_matrix` / `submit_lanes` — O(1) sampling
    decision + a non-blocking bounded-queue put. All interpreter work
    happens on the auditor's worker thread."""

    def __init__(self, plan, lists, plane: str, recorder=None,
                 registry=None, sample: Optional[float] = None,
                 queue_max: int = 4):
        from . import schema

        if registry is None:
            from . import REGISTRY as registry  # noqa: N813
        self.plan = plan
        self.lists = lists
        self.plane = plane
        self.recorder = recorder
        self.sample = (parity_sample_fraction()
                       if sample is None else min(max(sample, 0.0), 1.0))
        self._acc = 0.0
        self._registry = registry
        lab = {"plane": plane}
        self.checked_total = registry.counter(
            "pingoo_parity_checked_total",
            schema.PARITY_METRICS["pingoo_parity_checked_total"],
            labels=lab)
        self.mismatch_total = registry.counter(
            "pingoo_parity_mismatch_total",
            schema.PARITY_METRICS["pingoo_parity_mismatch_total"],
            labels=lab)
        self.dropped_total = registry.counter(
            "pingoo_parity_dropped_total",
            schema.PARITY_METRICS["pingoo_parity_dropped_total"],
            labels=lab)
        self._rule_help = schema.PARITY_METRICS[
            "pingoo_parity_rule_mismatch_total"]
        self._rule_series: dict[str, object] = {}
        self._rule_overflow = registry.counter(
            "pingoo_parity_rule_mismatch_total", self._rule_help,
            labels={"plane": plane, "rule": OVERFLOW_LABEL})
        self._queue: queue.Queue = queue.Queue(maxsize=queue_max)
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._stop = False
        self._fault_prefix = os.environ.get("PINGOO_PARITY_FAULT_INJECT")

    # -- hot-path side -------------------------------------------------------

    def _sampled(self) -> bool:
        if self.sample <= 0.0:
            return False
        self._acc += self.sample
        if self._acc < 1.0:
            return False
        self._acc -= 1.0
        return True

    def _enqueue(self, kind: str, payload: tuple) -> bool:
        with self._pending_lock:
            self._pending += 1
        try:
            self._queue.put_nowait((kind, payload))
        except queue.Full:
            with self._pending_lock:
                self._pending -= 1
            self.dropped_total.inc()
            return False
        self._ensure_worker()
        return True

    def submit_matrix(self, reqs, matched, trace_ids=None) -> bool:
        """Python-plane batch: full [n, R] match matrix vs the
        interpreter oracle. Sampling decision + queue put only — the
        lint registry keeps this free of device syncs."""
        if not self._sampled():
            return False
        return self._enqueue("matrix", (tuple(reqs), matched, trace_ids))

    def submit_lanes(self, contexts_builder: Callable, unverified,
                     verified_block, skip_mask=None,
                     trace_ids=None) -> bool:
        """Lane-plane batch (the sidecar ships no matrix off device):
        the oracle recomputes action lanes per row and diffs those.
        `contexts_builder` runs on the WORKER thread (building
        interpreter contexts is itself too dear for the drain loop);
        `skip_mask` excludes rows whose served verdict legitimately
        used a different view (truncated/spilled slots)."""
        if not self._sampled():
            return False
        return self._enqueue("lanes", (contexts_builder, unverified,
                                       verified_block, skip_mask,
                                       trace_ids))

    # -- worker side ---------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name=f"parity-audit-{self.plane}",
                daemon=True)
            self._worker.start()

    def _run(self) -> None:
        while not self._stop:
            try:
                kind, payload = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                if kind == "matrix":
                    self._audit_matrix(*payload)
                else:
                    self._audit_lanes(*payload)
            except Exception:
                # A broken audit must never take the worker down; the
                # batch simply goes un-audited.
                pass
            finally:
                with self._pending_lock:
                    self._pending -= 1

    def stop(self) -> None:
        self._stop = True

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Wait until every submitted batch has been audited (tests and
        the metrics smoke use this for determinism)."""
        self._ensure_worker()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._pending_lock:
                if self._pending == 0:
                    return True
            time.sleep(0.005)
        return False

    def _oracle_row(self, ctx, path: str) -> np.ndarray:
        from ..engine.verdict import interpret_rules_row

        row = interpret_rules_row(self.plan, ctx)
        if self._fault_prefix and path.startswith(self._fault_prefix) \
                and len(row):
            row[0] = not row[0]  # chaos knob: oracle-only divergence
        return row

    def _record_rule_mismatches(self, names) -> None:
        for name in names:
            series = self._rule_series.get(name)
            if series is None:
                if len(self._rule_series) < RULE_SERIES_CAP:
                    series = self._registry.counter(
                        "pingoo_parity_rule_mismatch_total",
                        self._rule_help,
                        labels={"plane": self.plane, "rule": name})
                    self._rule_series[name] = series
                else:
                    self._rule_overflow.inc()
                    continue
            series.inc()

    def _mark(self, trace_id, status: str, detail=None) -> None:
        if self.recorder is not None and trace_id:
            self.recorder.mark_parity(trace_id, status, detail)

    def _audit_matrix(self, reqs, matched, trace_ids) -> None:
        from ..engine.batch import tuple_to_context

        rule_names = [r.name for r in self.plan.rules]
        for i, req in enumerate(reqs):
            ctx = tuple_to_context(req, self.lists)
            want = self._oracle_row(ctx, req.path)
            got = np.asarray(matched[i], dtype=bool)
            self.checked_total.inc()
            trace_id = (trace_ids[i] if trace_ids is not None
                        else req.trace_id)
            diff = np.nonzero(want != got)[0]
            if len(diff) == 0:
                self._mark(trace_id, PARITY_OK)
                continue
            self.mismatch_total.inc()
            names = [rule_names[int(j)] for j in diff]
            self._record_rule_mismatches(names)
            self._mark(trace_id, PARITY_MISMATCH, {
                "rules": names,
                "interpreter": [bool(want[int(j)]) for j in diff],
                "device": [bool(got[int(j)]) for j in diff],
            })

    def _audit_lanes(self, contexts_builder, unverified, verified_block,
                     skip_mask, trace_ids) -> None:
        from ..engine.verdict import action_lanes

        contexts, paths = contexts_builder()
        for i, ctx in enumerate(contexts):
            if skip_mask is not None and skip_mask[i]:
                continue
            want_row = self._oracle_row(ctx, paths[i])[None, :]
            want_unv, want_vblk = action_lanes(self.plan, want_row)
            self.checked_total.inc()
            trace_id = trace_ids[i] if trace_ids is not None else None
            ok = (int(want_unv[0]) == int(unverified[i])
                  and bool(want_vblk[0]) == bool(verified_block[i]))
            if ok:
                self._mark(trace_id, PARITY_OK)
                continue
            self.mismatch_total.inc()
            # Lane audits attribute the divergence to the interpreter's
            # first acting matched rule (the lanes carry no bitmap).
            acting = [r.name for r in self.plan.rules
                      if r.actions and want_row[0, r.index]]
            names = acting[:1] or [OVERFLOW_LABEL]
            self._record_rule_mismatches(names)
            self._mark(trace_id, PARITY_MISMATCH, {
                "rules": names,
                "interpreter_action": int(want_unv[0]),
                "served_action": int(unverified[i]),
                "interpreter_verified_block": bool(want_vblk[0]),
                "served_verified_block": bool(verified_block[i]),
            })
