"""Unified telemetry: metric registry, Prometheus/JSON exposition,
trace ids, and the shared metric inventory (ISSUE 2 / SURVEY.md §5).

Import surface:
    from pingoo_tpu.obs import REGISTRY, get_registry
    from pingoo_tpu.obs.trace import new_trace_id, AccessLogSampler
    from pingoo_tpu.obs import schema
"""

from .registry import (  # noqa: F401
    LATENCY_BUCKETS_MS,
    WAIT_BUCKETS_MS,
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    REGISTRY,
    get_registry,
)
from . import schema  # noqa: F401
