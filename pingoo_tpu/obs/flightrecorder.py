"""Cross-plane flight recorder: a bounded in-memory ring of the last N
requests' provenance (ISSUE 5, docs/OBSERVABILITY.md).

Each record carries the request's trace id, a stable request-tuple
digest (crc32 over the verdict-relevant fields — cheap enough to
compute per request on the hot path), the per-stage timing picture from
enqueue through prefilter/scan to post, the matched-rule ids, and the
shadow-parity status. The ring is fixed-size (PINGOO_FLIGHT_RECORDER_N,
default 256) and append-only; wrap-around overwrites the oldest entry,
so memory is bounded no matter the request rate.

Surfaces:
  * `GET /__pingoo/flightrecorder` on the Python listener dumps every
    recorder registered in this process (the listener plane's and, when
    the ring sidecar is co-resident, the sidecar plane's). The native
    C++ httpd serves its own recorder at the same path.
  * SIGTERM drain auto-dumps via `dump_on_drain` (host/server.py) — to
    PINGOO_FLIGHT_DUMP_DIR as a JSON file when set, and always as one
    structured log line — so the last seconds before a shutdown are
    never lost.

Thread-safety: records come from the collector event loop, the sidecar
drain thread, and parity-audit worker threads; a plain lock guards the
ring (O(1) hold time per record).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Optional

from ..logging_utils import get_logger

DEFAULT_CAPACITY = 256

# Parity status values a record can carry.
PARITY_UNCHECKED = "unchecked"
PARITY_OK = "ok"
PARITY_MISMATCH = "mismatch"


def recorder_capacity() -> int:
    try:
        n = int(os.environ.get("PINGOO_FLIGHT_RECORDER_N",
                               str(DEFAULT_CAPACITY)))
    except ValueError:
        return DEFAULT_CAPACITY
    return max(8, min(n, 65536))


def tuple_digest(method: str, host: str, path: str, url: str,
                 user_agent: str, ip: str) -> str:
    """Stable 8-hex digest of the verdict-relevant request fields.
    crc32, not a cryptographic hash: this is a correlation key for
    joining recorder entries against logs/replays, computed once per
    request on the hot path."""
    raw = "\x00".join((method, host, path, url, user_agent, ip))
    return f"{zlib.crc32(raw.encode('latin-1', 'replace')) & 0xFFFFFFFF:08x}"


class FlightRecorder:
    """Bounded ring of per-request provenance records."""

    def __init__(self, plane: str, capacity: Optional[int] = None,
                 rule_names: Optional[tuple] = None, registry=None):
        self.plane = plane
        self.capacity = capacity or recorder_capacity()
        self.rule_names = tuple(rule_names or ())
        self._ring: list = [None] * self.capacity
        self._next = 0  # monotonically increasing record sequence
        self._lock = threading.Lock()
        if registry is None:
            from . import REGISTRY as registry  # noqa: N813
        from . import schema

        self._records_total = registry.counter(
            "pingoo_flightrecorder_records_total",
            schema.PROVENANCE_METRICS[
                "pingoo_flightrecorder_records_total"],
            labels={"plane": plane})

    # -- hot path ------------------------------------------------------------

    def record(self, *, trace_id: str, digest: str, stages: dict,
               matched_rules, action: int,
               parity: str = PARITY_UNCHECKED,
               ticket: Optional[int] = None) -> None:
        """Append one request's provenance. `stages` is shared per batch
        (the caller builds ONE dict and passes it for every row), so the
        per-record cost is a tuple + one ring store under the lock."""
        entry = [trace_id, digest, stages, matched_rules, action, parity,
                 ticket, time.time(), None]  # [-1]: parity detail
        with self._lock:
            self._ring[self._next % self.capacity] = entry
            self._next += 1
        self._records_total.inc()

    # -- audit / introspection -----------------------------------------------

    def mark_parity(self, trace_id: str, status: str,
                    detail: Optional[dict] = None) -> bool:
        """Attach a parity verdict to the entry with `trace_id` (audit
        worker path — a linear scan over <= capacity entries)."""
        with self._lock:
            for entry in self._ring:
                if entry is not None and entry[0] == trace_id:
                    entry[5] = status
                    if detail is not None:
                        entry[8] = detail
                    return True
        return False

    def __len__(self) -> int:
        with self._lock:
            return min(self._next, self.capacity)

    @property
    def recorded_total(self) -> int:
        return self._next

    def snapshot(self) -> list[dict]:
        """Oldest -> newest view of the live ring as JSON-able dicts."""
        with self._lock:
            n = min(self._next, self.capacity)
            start = self._next - n
            entries = [self._ring[(start + i) % self.capacity]
                       for i in range(n)]
        out = []
        for e in entries:
            if e is None:
                continue
            rules = e[3]
            rec = {
                "trace_id": e[0],
                "digest": e[1],
                "stages_ms": e[2],
                "matched_rules": [int(r) for r in rules]
                if rules is not None else [],
                "action": int(e[4]),
                "parity": e[5],
                "ts": round(e[7], 3),
            }
            if self.rule_names and rules is not None:
                rec["matched_rule_names"] = [
                    self.rule_names[int(r)] for r in rules
                    if 0 <= int(r) < len(self.rule_names)]
            if e[6] is not None:
                rec["ticket"] = int(e[6])
            if e[8] is not None:
                rec["parity_detail"] = e[8]
            out.append(rec)
        return out

    def dump(self) -> dict:
        return {
            "plane": self.plane,
            "capacity": self.capacity,
            "recorded_total": self.recorded_total,
            "entries": self.snapshot(),
        }


# -- process-global recorder registry ----------------------------------------
# The Python listener's /__pingoo/flightrecorder endpoint dumps every
# recorder in the process: the listener plane's own, and the sidecar
# plane's when a RingSidecar is co-resident (host/native_plane.py runs
# both in one control-plane process).

_RECORDERS: dict[str, FlightRecorder] = {}
_REG_LOCK = threading.Lock()


def register_recorder(recorder: FlightRecorder) -> FlightRecorder:
    with _REG_LOCK:
        _RECORDERS[recorder.plane] = recorder
    return recorder


def unregister_recorder(recorder: FlightRecorder) -> None:
    with _REG_LOCK:
        if _RECORDERS.get(recorder.plane) is recorder:
            del _RECORDERS[recorder.plane]


def registered_recorders() -> list[FlightRecorder]:
    with _REG_LOCK:
        return list(_RECORDERS.values())


def dump_all() -> dict:
    return {"planes": {r.plane: r.dump() for r in registered_recorders()}}


def dump_on_drain(reason: str = "sigterm") -> Optional[str]:
    """SIGTERM-drain auto-dump: write the full dump to
    PINGOO_FLIGHT_DUMP_DIR (one timestamped file) when configured, and
    always emit a structured summary log line. Returns the file path
    written, or None. Never raises — this runs on the shutdown path."""
    log = get_logger("pingoo_tpu.flightrecorder")
    payload = dump_all()
    counts = {plane: len(d["entries"])
              for plane, d in payload["planes"].items()}
    path = None
    out_dir = os.environ.get("PINGOO_FLIGHT_DUMP_DIR")
    if out_dir:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"flightrecorder-{int(time.time())}.json")
            with open(path, "w") as f:
                json.dump({"reason": reason, **payload}, f)
        except OSError:
            path = None
    try:
        log.info("flight recorder drain dump", extra={"fields": {
            "reason": reason, "entries": counts, "dump_path": path}})
    except Exception:
        pass
    return path
