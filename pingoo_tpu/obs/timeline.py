"""Cross-plane span timeline (ISSUE 17): per-request and per-window
spans joined across the C++ listener, the shm ring, and both Python
planes, exported as Chrome-trace (catapult) JSON.

The join works because every plane already stamps the SAME clock:
native httpd `now_ms()`, the ring's `pingoo_ring_now_ms()` (both
clock_gettime(CLOCK_MONOTONIC), see pingoo_ring.cc), and Python's
`time.monotonic()` (CLOCK_MONOTONIC on Linux) are one timebase per
machine. So a ring slot's `enq_ms` (stamped by the native producer)
and the sidecar's `time.monotonic()` resolve stamp subtract directly —
no epoch conversion, no skew estimation. All spans are stored in
monotonic MICROseconds (Chrome-trace's native unit); the export
carries a `clock` block (monotonic now + wall now) so an offline
merger (tools/timeline_capture.py) can pin the trace to wall time.

Span layout (Perfetto rows):
  * pid = plane ("native" | "sidecar" | "python"): ring-wait spans are
    emitted under pid "native" because their start stamp is the native
    enqueue clock — that row IS the cross-plane join.
  * tid = per-request lane (derived from the trace id / ring ticket)
    for request/hold spans, or a per-plane "batch" lane for the batch
    pipeline span and its stage children. Stage children are clamped
    inside their parent's bounds, so nesting holds by construction.

Gating + hot-path contract: `PINGOO_TIMELINE_SAMPLE` (a rate in
(0, 1]; unset/0 = off) decides per BATCH with a deterministic stride
accumulator — no RNG, one float add + compare on the unsampled path.
The record methods below are registered hot in
tools/analyze/lint_config.py: pure float math over already-host stage
numbers, never an array allocation or a device sync. Retention is a
bounded deque (`PINGOO_TIMELINE_N` spans, default 4096); the export at
`/__pingoo/timeline` drains nothing (snapshot semantics).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

DEFAULT_SPAN_CAP = 4096
# Per-request lanes emitted per sampled batch (the batch-lane pipeline
# span always goes out; request lanes are the expensive part).
DEFAULT_ROWS_PER_BATCH = 8

_PLANES = ("python", "sidecar", "native")


def timeline_sample_rate() -> float:
    """PINGOO_TIMELINE_SAMPLE as a clamped rate; 0.0 = disabled."""
    raw = os.environ.get("PINGOO_TIMELINE_SAMPLE", "").strip()
    if not raw:
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    if rate <= 0.0:
        return 0.0
    return min(rate, 1.0)


class Timeline:
    """Process-global bounded span store + deterministic batch sampler
    shared by the co-resident Python planes."""

    def __init__(self, rate: Optional[float] = None, registry=None):
        self.rate = timeline_sample_rate() if rate is None else rate
        self._acc = 0.0
        self._lock = threading.Lock()
        cap = int(os.environ.get("PINGOO_TIMELINE_N", DEFAULT_SPAN_CAP))
        self.spans: deque = deque(maxlen=max(64, cap))
        self.rows_per_batch = int(os.environ.get(
            "PINGOO_TIMELINE_ROWS", DEFAULT_ROWS_PER_BATCH))
        self._counters: dict[str, object] = {}
        self._registry = registry
        self._seq = 0

    @property
    def enabled(self) -> bool:
        return self.rate > 0.0

    def _reg(self):
        if self._registry is None:
            from . import REGISTRY

            self._registry = REGISTRY
        return self._registry

    def ensure_instruments(self, plane: str) -> None:
        """Create pingoo_timeline_spans_total{plane} at zero at boot
        (and the native series, which the join rows emit under)."""
        self._counter(plane)
        self._counter("native")

    def _counter(self, plane: str):
        ctr = self._counters.get(plane)
        if ctr is None:
            from . import schema

            ctr = self._reg().counter(
                "pingoo_timeline_spans_total",
                schema.PERF_METRICS["pingoo_timeline_spans_total"],
                labels={"plane": plane})
            self._counters[plane] = ctr
        return ctr

    def sample(self) -> bool:
        """Per-batch sampling decision — the ONLY per-batch work when
        a batch is not sampled: one add, one compare (stride sampling,
        deterministic, no RNG)."""
        if self.rate <= 0.0:
            return False
        self._acc += self.rate
        if self._acc >= 1.0:
            self._acc -= 1.0
            return True
        return False

    # ------------------------------------------------------------------
    # Span recording (only runs for SAMPLED batches).

    def add_span(self, plane: str, tid: str, name: str,
                 t0_us: float, dur_us: float,
                 trace_id: str = "", args: Optional[dict] = None) -> None:
        span = (plane, tid, name, float(t0_us), max(0.0, float(dur_us)),
                trace_id, args or {})
        self._counter(plane).inc()
        with self._lock:
            self.spans.append(span)

    def _stage_children(self, plane: str, tid: str, t0_us: float,
                        t_end_us: float, stages_us: list,
                        trace_id: str, args: dict) -> None:
        """Lay consecutive stage spans inside [t0_us, t_end_us] from
        (name, dur_us) pairs, clamped so nesting always holds."""
        cursor = t0_us
        for name, dur in stages_us:
            if dur <= 0.0:
                continue
            start = min(cursor, t_end_us)
            end = min(start + dur, t_end_us)
            self.add_span(plane, tid, name, start, end - start,
                          trace_id, args)
            cursor = end

    def batch_python(self, *, stages_ms: dict, t_launch: float,
                     t_resolve: float, t_end: float,
                     rows: Optional[list] = None,
                     args: Optional[dict] = None) -> None:
        """One sampled python-plane batch: the batch-lane pipeline
        span with stage children reconstructed from the already-stamped
        `<stage>_ms` wall times (engine/service's per-batch stage
        dict), an explicit resolve span, plus bounded per-request
        lanes.

        `rows` entries: (trace_id, t_enq_mono_s, t_admit_mono_s) — the
        request span covers enqueue -> batch end; sched_hold covers
        admit -> launch.
        """
        base_args = dict(args or {})
        t0_us = t_launch * 1e6
        t_end_us = t_end * 1e6
        with self._lock:
            self._seq += 1
            seq = self._seq
        tid = "python/batch"
        self.add_span("python", tid, "batch", t0_us,
                      max(0.0, t_end_us - t0_us), f"b-{seq}", base_args)
        order = ("encode", "prefilter", "device_dispatch",
                 "device_compute")
        stage_pairs = [
            (name, float(stages_ms.get(f"{name}_ms", 0.0)) * 1e3)
            for name in order]
        self._stage_children("python", tid, t0_us, t_resolve * 1e6,
                             stage_pairs, f"b-{seq}", base_args)
        if t_end > t_resolve:
            self.add_span("python", tid, "resolve", t_resolve * 1e6,
                          (t_end - t_resolve) * 1e6, f"b-{seq}",
                          base_args)
        for trace_id, t_enq, t_admit in (rows or [])[:self.rows_per_batch]:
            lane = f"python/req:{trace_id[-6:] if trace_id else seq}"
            enq_us = t_enq * 1e6
            self.add_span("python", lane, "request", enq_us,
                          max(0.0, t_end_us - enq_us), trace_id,
                          base_args)
            adm_us = t_admit * 1e6
            self.add_span("python", lane, "sched_hold", adm_us,
                          max(0.0, min(t0_us, t_end_us) - adm_us),
                          trace_id, base_args)

    def batch_sidecar(self, *, t0: float, t1: float, tpf: float,
                      t2: float, t_sync: float, t_resolve: float,
                      t_end: float, rows: Optional[list] = None,
                      args: Optional[dict] = None) -> None:
        """One sampled sidecar batch from native_ring._complete's time
        points (all time.monotonic() seconds): encode [t0,t1],
        prefilter [t1,tpf], dispatch [tpf,t2], compute [t2,t_sync],
        resolve [t_resolve,t_end].

        `rows` entries: (trace_id, enq_ms) with enq_ms the NATIVE
        producer's ring-clock stamp — the ring-wait span is emitted
        under pid "native" ending at t0 (sidecar pickup). Same
        monotonic timebase, so the subtraction is the cross-plane join.
        """
        base_args = dict(args or {})
        with self._lock:
            self._seq += 1
            seq = self._seq
        tid = "sidecar/batch"
        if t0 <= 0.0:
            # Megastep slices carry no per-slice dispatch points — the
            # batch span covers the slice's resolve window instead.
            t0 = t_resolve if 0.0 < t_resolve < t_end else t_end
        t0_us = t0 * 1e6
        t_end_us = t_end * 1e6
        self.add_span("sidecar", tid, "batch", t0_us,
                      max(0.0, t_end_us - t0_us), f"b-{seq}", base_args)
        bounds = (("encode", t0, t1), ("prefilter", t1, tpf),
                  ("device_dispatch", tpf, t2),
                  ("device_compute", t2, t_sync),
                  ("resolve", t_resolve, t_end))
        for name, a, b in bounds:
            if b > a > 0.0:
                self.add_span("sidecar", tid, name, a * 1e6,
                              (b - a) * 1e6, f"b-{seq}", base_args)
        for trace_id, enq_ms in (rows or [])[:self.rows_per_batch]:
            lane = f"ring/req:{trace_id[-6:] if trace_id else seq}"
            enq_us = float(enq_ms) * 1e3
            self.add_span("native", lane, "ring_wait", enq_us,
                          max(0.0, t0_us - enq_us), trace_id, base_args)
            self.add_span("sidecar", lane, "request", t0_us,
                          max(0.0, t_end_us - t0_us), trace_id,
                          base_args)

    # ------------------------------------------------------------------
    # Export.

    def chrome_trace(self) -> dict:
        """Chrome-trace (catapult) JSON object for /__pingoo/timeline:
        loads directly in Perfetto. `clock` pins the monotonic span
        timebase to wall time for offline merging."""
        with self._lock:
            spans = list(self.spans)
        pids = {}
        events = []
        for plane in _PLANES:
            pids[plane] = len(pids) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pids[plane],
                "tid": 0, "args": {"name": f"pingoo:{plane}"},
            })
        tids: dict[tuple, int] = {}
        for plane, tid, name, t0_us, dur_us, trace_id, args in spans:
            pid = pids.setdefault(plane, len(pids) + 1)
            tkey = (plane, tid)
            if tkey not in tids:
                tids[tkey] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tids[tkey], "args": {"name": tid},
                })
            ev_args = {"trace_id": trace_id}
            ev_args.update(args)
            events.append({
                "ph": "X", "pid": pid, "tid": tids[tkey], "name": name,
                "cat": plane, "ts": round(t0_us, 1),
                "dur": round(dur_us, 1), "args": ev_args,
            })
        return {
            "displayTimeUnit": "ms",
            "clock": {
                "unit": "monotonic_us",
                "monotonic_now_us": time.monotonic() * 1e6,
                "wall_now_s": time.time(),
            },
            "otherData": {
                "sample_rate": self.rate,
                "spans": len(spans),
                "cap": self.spans.maxlen,
            },
            "traceEvents": events,
        }

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())

    def snapshot(self) -> dict:
        with self._lock:
            n = len(self.spans)
        return {"enabled": self.enabled, "rate": self.rate,
                "spans": n, "cap": self.spans.maxlen}


_TIMELINE: Optional[Timeline] = None
_TIMELINE_LOCK = threading.Lock()


def get_timeline() -> Timeline:
    global _TIMELINE
    if _TIMELINE is None:
        with _TIMELINE_LOCK:
            if _TIMELINE is None:
                _TIMELINE = Timeline()
    return _TIMELINE


def reset_timeline_for_tests() -> None:
    """Drop the singleton so a test can re-read the sampling env."""
    global _TIMELINE
    with _TIMELINE_LOCK:
        _TIMELINE = None
