"""PINGOO_CHAOS fault injector (ISSUE 10, docs/RESILIENCE.md).

Deterministic fault injection for the supervision machinery: the
chaos harness (tools/chaos_smoke.py, tests/test_resilience.py) needs
to kill, pause and corrupt the sidecar at EXACT points in the batch
lifecycle to prove the liveness protocol's bounds, and doing that
from outside the process races the very windows under test. The
injector is dormant unless PINGOO_CHAOS is set — the parse itself is
the only cost on the serving path (one attribute check per hook).

Spec grammar — comma-separated faults, each ``name[:arg[:arg]]``::

  kill[:N]          SIGKILL this process after N completed batches
                    (default 1) — the crash-reattach scenario.
  pause:MS[:N]      sleep MS ms in the drain loop after N completed
                    batches (default 1), once — freezes the heartbeat
                    AND the in-flight batches, the "hung sidecar"
                    scenario (detection, not crash).
  heartbeat_freeze  never stamp the ring heartbeat — isolates the
                    liveness detector from real drain-loop health.
  stall:STAGE:MS    sleep MS ms inside pipeline stage STAGE
                    (encode|dispatch|resolve), every batch — bounded
                    per-stage latency injection.
  xla_error[:N]     raise ChaosXlaError from device dispatch on the
                    Nth batch (default 1), once — drives the
                    degradation ladder's device rung.
  verdict_full:N    report the verdict ring full for the next N post
                    attempts — exercises the post-retry loop.
  swap_storm[:N]    request a same-plan ruleset hot-swap every N
                    completed batches (default 5) — hammers the
                    epoch-switch drain/flip boundary under live load
                    (ISSUE 11); same plan, so any verdict drift the
                    storm produces is a swap-protocol bug by
                    construction.

Every injected fault increments
``pingoo_chaos_injected_total{fault=}`` so a chaos run's metrics
surface shows exactly what was injected where.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional


class ChaosXlaError(RuntimeError):
    """Injected stand-in for jaxlib's XlaRuntimeError (the real class
    only exists when jax is importable; ladder handlers catch broad
    Exception either way)."""


class ChaosInjector:
    """Parsed PINGOO_CHAOS faults + the hook points the sidecar calls.

    All hooks are cheap no-ops when the spec is empty (`self.active`
    is False and every hook checks it first).
    """

    def __init__(self, spec: str = ""):
        self.spec = (spec or "").strip()
        self.active = bool(self.spec)
        self.kill_after: Optional[int] = None
        self.pause_ms = 0
        self.pause_after: Optional[int] = None
        self.freeze_heartbeat = False
        self.stalls: dict[str, float] = {}   # stage -> ms
        self.xla_error_at: Optional[int] = None
        self.verdict_full_budget = 0
        self.swap_every: Optional[int] = None
        self._last_swap_batch = 0
        self._fired: set[str] = set()
        self._counters: dict[str, object] = {}
        if not self.active:
            return
        for part in self.spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, rest = part.partition(":")
            args = rest.split(":") if rest else []
            try:
                if name == "kill":
                    self.kill_after = int(args[0]) if args else 1
                elif name == "pause":
                    self.pause_ms = int(args[0])
                    self.pause_after = int(args[1]) if len(args) > 1 else 1
                elif name == "heartbeat_freeze":
                    self.freeze_heartbeat = True
                elif name == "stall":
                    self.stalls[args[0]] = float(args[1])
                elif name == "xla_error":
                    self.xla_error_at = int(args[0]) if args else 1
                elif name == "verdict_full":
                    self.verdict_full_budget = int(args[0])
                elif name == "swap_storm":
                    self.swap_every = int(args[0]) if args else 5
                    if self.swap_every < 1:
                        raise ValueError(part)
                else:
                    raise ValueError(name)
            except (IndexError, ValueError):
                raise ValueError(
                    f"PINGOO_CHAOS: malformed fault {part!r}") from None

    @classmethod
    def from_env(cls) -> "ChaosInjector":
        return cls(os.environ.get("PINGOO_CHAOS", ""))

    def _count(self, fault: str) -> None:
        ctr = self._counters.get(fault)
        if ctr is None:
            from . import REGISTRY
            from .schema import RESILIENCE_METRICS

            ctr = REGISTRY.counter(
                "pingoo_chaos_injected_total",
                RESILIENCE_METRICS["pingoo_chaos_injected_total"],
                labels={"plane": "sidecar", "fault": fault})
            self._counters[fault] = ctr
        ctr.inc()

    # -- hook points (called by RingSidecar) ----------------------------------

    def heartbeat_frozen(self) -> bool:
        return self.active and self.freeze_heartbeat

    def on_batch_done(self, batches: int) -> None:
        """After a batch fully resolves: the kill / pause triggers.
        SIGKILL (not sys.exit) on purpose — the reattach protocol must
        survive a consumer that never ran ANY cleanup."""
        if not self.active:
            return
        if self.pause_after is not None and batches >= self.pause_after \
                and "pause" not in self._fired:
            self._fired.add("pause")
            self._count("pause")
            time.sleep(self.pause_ms / 1e3)
        if self.kill_after is not None and batches >= self.kill_after:
            self._count("kill")
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_xla_error(self, batches: int) -> None:
        """Inside device dispatch: one injected device failure."""
        if not self.active or self.xla_error_at is None:
            return
        if batches + 1 >= self.xla_error_at and "xla" not in self._fired:
            self._fired.add("xla")
            self._count("xla_error")
            raise ChaosXlaError("PINGOO_CHAOS: injected XlaRuntimeError")

    def stage(self, stage: str) -> None:
        """Inside a pipeline stage: bounded injected stall."""
        if not self.active:
            return
        ms = self.stalls.get(stage)
        if ms:
            self._count(f"stall_{stage}")
            time.sleep(ms / 1e3)

    def swap_due(self, batches: int) -> bool:
        """At the drain-loop top: True = the storm wants a hot-swap at
        this batch boundary. Fires at most once per completed-batch
        count (the loop passes the same count many times)."""
        if not self.active or not self.swap_every or batches <= 0:
            return False
        if batches == self._last_swap_batch or batches % self.swap_every:
            return False
        self._last_swap_batch = batches
        self._count("swap_storm")
        return True

    def verdict_full(self) -> bool:
        """Before a verdict post attempt: True = pretend the ring is
        full (the caller's retry loop backs off and re-tries)."""
        if not self.active or self.verdict_full_budget <= 0:
            return False
        self.verdict_full_budget -= 1
        self._count("verdict_full")
        return True
