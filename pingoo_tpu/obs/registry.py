"""Shared metric registry: counters, gauges, fixed-bucket histograms.

One registry backs every telemetry surface in the repo (SURVEY.md §5;
ISSUE 2): the Python listener (host/httpd.py), the verdict pipeline
(engine/service.py per-stage histograms), the ring sidecar
(native_ring.RingSidecar), and bench.py's stage-latency snapshot. The
native C++ plane keeps its own counters (native/httpd.cc Stats) but
exposes them under the SAME metric names — pingoo_tpu/obs/schema.py is
the inventory both sides are tested against (tests/test_obs.py,
tools/check_metrics_schema.py).

Design constraints, in order:
  * hot-path cheap: Counter.inc is one integer add; Histogram.observe
    is a bisect into <=12 static bucket bounds. No locks — every writer
    runs on either the event loop or the single sidecar drain thread,
    and torn reads of a Python int are impossible under the GIL.
  * two expositions from one source: Prometheus text (the scrape
    format) and JSON (back-compatible with the pre-registry surfaces).
  * external sources: collectors registered via `register_collector`
    run right before exposition so values owned elsewhere (the shm ring
    telemetry block, sidecar counters) appear in the same scrape.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Callable, Iterable, Optional

# Shared latency bucket bounds (milliseconds). The first seven match the
# native plane's verdict-wait histogram (native/httpd.cc record_wait:
# 1, 2, 5, 10, 50, 100, +inf) so the two planes' wait histograms are
# comparable bucket-for-bucket; 0.25/0.5 add sub-ms resolution for the
# on-chip stages and 1000 bounds the tail.
LATENCY_BUCKETS_MS = (0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 50.0, 100.0, 1000.0)

# The 7-bucket subset the native plane and the shm ring telemetry block
# use (upper bounds in ms; the last bucket is +inf).
WAIT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 50.0, 100.0, 1000.0)

_VALID_FIRST = set("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ_:")
_VALID_REST = _VALID_FIRST | set("0123456789")


def _check_name(name: str) -> str:
    if not name or name[0] not in _VALID_FIRST or any(
            ch not in _VALID_REST for ch in name):
        raise ValueError(f"invalid prometheus metric name {name!r}")
    return name


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", "\\\\").replace(
            '"', '\\"').replace("\n", "\\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f.is_integer():
        # "1", not "1.0": keeps le= labels identical across the Python
        # and native planes (the C++ exposition prints integers).
        return str(int(f))
    return repr(f)


class Counter:
    """Monotonic counter. `set_total` exists for mirroring a counter
    owned by an external source (the shm telemetry block): collectors
    overwrite the absolute total at scrape time."""

    __slots__ = ("name", "labels", "_value")

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    def set_total(self, total) -> None:
        self._value = total

    @property
    def value(self):
        return self._value


class Gauge:
    __slots__ = ("name", "labels", "_value")

    def __init__(self, name: str, labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0

    def set(self, v) -> None:
        self._value = v

    def inc(self, n=1) -> None:
        self._value += n

    def dec(self, n=1) -> None:
        self._value -= n

    @property
    def value(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative exposition, Prometheus
    `le` semantics). Bounds are upper bounds; the +Inf bucket is
    implicit. `observe` is O(log n_buckets) with no allocation."""

    __slots__ = ("name", "labels", "bounds", "counts", "_count", "_sum")

    def __init__(self, name: str, bounds: Iterable[float],
                 labels: Optional[dict] = None):
        self.name = name
        self.labels = dict(labels or {})
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError(f"histogram bounds must be sorted: {bounds}")
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self._count += 1
        self._sum += v

    def observe_n(self, v: float, n: int) -> None:
        """Record n identical observations (bucket-mirroring helper)."""
        self.counts[bisect_left(self.bounds, v)] += n
        self._count += n
        self._sum += v * n

    def set_bucket_counts(self, counts: Iterable[int],
                          total_sum: Optional[float] = None) -> None:
        """Overwrite from an external cumulative-free bucket array (the
        shm telemetry block ships per-bucket counts, not observations).
        `counts` must have len(bounds) + 1 entries (last = +Inf)."""
        counts = [int(c) for c in counts]
        if len(counts) != len(self.counts):
            raise ValueError(
                f"{self.name}: got {len(counts)} buckets, "
                f"want {len(self.counts)}")
        self.counts = counts
        self._count = sum(counts)
        if total_sum is not None:
            self._sum = float(total_sum)
        else:
            # Approximate the sum from bucket midpoints (upper bound for
            # the +Inf bucket) so rate math stays plausible.
            s = 0.0
            lo = 0.0
            for b, c in zip(self.bounds, counts):
                s += c * (lo + b) / 2.0
                lo = b
            s += counts[-1] * self.bounds[-1]
            self._sum = s

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile (0..1) from the
        cumulative buckets; the +Inf bucket reports the largest finite
        bound (the same convention bench.py's `_hist_percentiles` uses —
        Infinity is not valid JSON)."""
        if self._count == 0:
            return 0.0
        need = q * self._count
        run = 0
        for bound, c in zip(self.bounds, self.counts):
            run += c
            if run >= need:
                return bound
        return self.bounds[-1]

    def snapshot(self) -> dict:
        cum = 0
        buckets = {}
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            buckets[_fmt_value(bound)] = cum
        buckets["+Inf"] = self._count
        return {"count": self._count, "sum": round(self._sum, 6),
                "buckets": buckets,
                "p50": self.percentile(0.50),
                "p99": self.percentile(0.99)}


class MetricRegistry:
    """Get-or-create instrument registry with Prometheus + JSON
    exposition. Instruments are keyed by (name, sorted labels); help
    text is per metric family."""

    def __init__(self):
        self._metrics: dict[tuple, object] = {}
        self._help: dict[str, tuple[str, str]] = {}  # name -> (type, help)
        self._collectors: list[Callable[[], None]] = []
        # Instrument creation can race (listener thread vs sidecar
        # thread first touch); mutation of live instruments does not.
        self._create_lock = threading.Lock()

    # -- instrument factories ------------------------------------------------

    def _get(self, cls, name, help_text, labels, **kw):
        _check_name(name)
        key = (name, tuple(sorted((labels or {}).items())))
        inst = self._metrics.get(key)
        if inst is None:
            with self._create_lock:
                inst = self._metrics.get(key)
                if inst is None:
                    inst = cls(name, labels=labels, **kw)
                    self._metrics[key] = inst
                    self._help.setdefault(
                        name, (cls.__name__.lower(), help_text))
        return inst

    def counter(self, name: str, help_text: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Iterable[float] = LATENCY_BUCKETS_MS,
                  labels: Optional[dict] = None) -> Histogram:
        return self._get(Histogram, name, help_text, labels,
                         bounds=buckets)

    def register_collector(self, fn: Callable[[], None]) -> None:
        """`fn` runs before every exposition to pull external values
        (shm ring telemetry, sidecar counters) into the registry."""
        with self._create_lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._create_lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _collect(self) -> None:
        for fn in list(self._collectors):
            try:
                fn()
            except Exception:
                # A broken external source must never take down the
                # scrape surface of everything else.
                pass

    # -- exposition ----------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._collect()
        by_name: dict[str, list] = {}
        for (name, _), inst in sorted(self._metrics.items()):
            by_name.setdefault(name, []).append(inst)
        out: list[str] = []
        for name, insts in by_name.items():
            kind, help_text = self._help.get(name, ("gauge", ""))
            if help_text:
                out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} {kind}")
            for inst in insts:
                if isinstance(inst, Histogram):
                    cum = 0
                    for bound, c in zip(inst.bounds, inst.counts):
                        cum += c
                        lab = dict(inst.labels)
                        lab["le"] = _fmt_value(bound)
                        out.append(
                            f"{name}_bucket{_fmt_labels(lab)} {cum}")
                    lab = dict(inst.labels)
                    lab["le"] = "+Inf"
                    out.append(
                        f"{name}_bucket{_fmt_labels(lab)} {inst.count}")
                    out.append(f"{name}_sum{_fmt_labels(inst.labels)} "
                               f"{_fmt_value(inst.sum)}")
                    out.append(f"{name}_count{_fmt_labels(inst.labels)} "
                               f"{inst.count}")
                else:
                    out.append(f"{name}{_fmt_labels(inst.labels)} "
                               f"{_fmt_value(inst.value)}")
        return "\n".join(out) + "\n"

    def json_snapshot(self) -> dict:
        """{name: value | {labels-key: value} | histogram snapshot}."""
        self._collect()
        out: dict = {}
        for (name, labkey), inst in sorted(self._metrics.items()):
            val = (inst.snapshot() if isinstance(inst, Histogram)
                   else inst.value)
            if not labkey:
                out[name] = val
            else:
                slot = out.setdefault(name, {})
                if not isinstance(slot, dict) or "buckets" in slot:
                    out[name] = slot = {"": slot}
                slot[",".join(f"{k}={v}" for k, v in labkey)] = val
        return out

    def stage_snapshot(self, prefix: str = "pingoo_verdict_stage_ms") \
            -> dict:
        """Compact per-stage latency view (bench.py artifact embed and
        ServiceStats.snapshot): {stage: {count, p50_ms, p99_ms,
        mean_ms}} for every histogram in the `prefix` family."""
        out: dict = {}
        for (name, labkey), inst in self._metrics.items():
            if name != prefix or not isinstance(inst, Histogram):
                continue
            labs = dict(labkey)
            stage = labs.get("stage", "")
            plane = labs.get("plane", "")
            key = f"{plane}:{stage}" if plane else stage
            if inst.count:
                mean = inst.sum / inst.count
            else:
                mean = 0.0
            out[key or "all"] = {
                "count": inst.count,
                "p50_ms": inst.percentile(0.50),
                "p99_ms": inst.percentile(0.99),
                "mean_ms": round(mean, 4),
            }
        return out


_PROM_LINE = None  # compiled lazily (re import only when linting)


def lint_prometheus_text(text: str) -> list[str]:
    """Exposition-format lint shared by tests/test_obs.py and
    tools/check_metrics_schema.py. Checks line syntax, TYPE declarations
    preceding samples, histogram bucket monotonicity and the mandatory
    +Inf bucket / _sum / _count triple. Returns a list of problems
    (empty = clean)."""
    import re

    global _PROM_LINE
    if _PROM_LINE is None:
        _PROM_LINE = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
            r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
            r' (?:[+-]?(?:\d+(?:\.\d+)?(?:e[+-]?\d+)?|Inf|NaN))$')
    problems: list[str] = []
    typed: dict[str, str] = {}
    hist_buckets: dict[str, list[tuple[float, int]]] = {}
    hist_series: dict[str, set] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                problems.append(f"line {i}: bad TYPE declaration: {line}")
            else:
                typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            problems.append(f"line {i}: unknown comment form: {line}")
            continue
        if not _PROM_LINE.match(line):
            problems.append(f"line {i}: malformed sample: {line}")
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        if name not in typed and base not in typed:
            problems.append(f"line {i}: sample without TYPE: {name}")
        if typed.get(base) == "histogram" and name.endswith("_bucket"):
            m = re.search(r'le="([^"]+)"', line)
            if not m:
                problems.append(f"line {i}: histogram bucket missing le=")
                continue
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            series = re.sub(r',?le="[^"]+"', "", line.split(" ")[0])
            hist_buckets.setdefault(series, []).append(
                (le, int(float(line.rsplit(" ", 1)[1]))))
            hist_series.setdefault(base, set()).add(series)
    for series, buckets in hist_buckets.items():
        les = [b[0] for b in buckets]
        counts = [b[1] for b in buckets]
        if les != sorted(les):
            problems.append(f"{series}: le bounds not sorted")
        if counts != sorted(counts):
            problems.append(f"{series}: bucket counts not cumulative")
        if not les or les[-1] != float("inf"):
            problems.append(f"{series}: missing +Inf bucket")
    for base in hist_series:
        if f"{base}_sum" not in text:
            problems.append(f"{base}: missing _sum series")
        if f"{base}_count" not in text:
            problems.append(f"{base}: missing _count series")
    return problems


# The process-global registry every component shares. Tests that need
# isolation construct their own MetricRegistry.
REGISTRY = MetricRegistry()


def get_registry() -> MetricRegistry:
    return REGISTRY
