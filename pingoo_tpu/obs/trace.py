"""Trace ids + sampled structured access logging.

Every request gets a 16-hex-char trace id at the edge. On the Python
plane it is generated in HttpListener.handle_request, rides the
RequestTuple through the batch (so engine-side logging can correlate),
returns in the `x-pingoo-trace-id` response header, and lands in the
sampled JSON access log. On the native plane the ring TICKET is the
correlation id: the C++ httpd echoes `x-pingoo-trace-id:
t-<ring ticket>` so a response can be joined against sidecar-side
telemetry without a new slot field.

Sampling: PINGOO_ACCESS_LOG_SAMPLE = N logs every Nth request per
listener (1 = every request, 0 = disabled). Default 128 — cheap enough
to leave on, dense enough to carry real latency evidence.
"""

from __future__ import annotations

import itertools
import os
import secrets
import time

from ..logging_utils import get_logger

TRACE_HEADER = "x-pingoo-trace-id"

_counter = itertools.count()
_prefix = None


def new_trace_id() -> str:
    """16 hex chars: 8 random per-process prefix + 8 sequence. Unique
    across restarts and across co-resident listeners, no per-request
    entropy syscall."""
    global _prefix
    if _prefix is None:
        _prefix = secrets.token_hex(4)
    return f"{_prefix}{next(_counter) & 0xFFFFFFFF:08x}"


def access_log_sample_every() -> int:
    try:
        return max(0, int(os.environ.get("PINGOO_ACCESS_LOG_SAMPLE", "128")))
    except ValueError:
        return 128


class AccessLogSampler:
    """Every-Nth sampler emitting one structured access-log line with
    the request's trace id (logging_utils JSON shape)."""

    def __init__(self, listener: str, sample_every: int | None = None):
        self.listener = listener
        self.sample_every = (access_log_sample_every()
                             if sample_every is None else sample_every)
        self._seen = 0
        self._log = get_logger("pingoo_tpu.access")

    def maybe_log(self, *, trace_id: str, method: str, path: str,
                  status: int, client_ip: str, duration_ms: float,
                  **extra) -> bool:
        if self.sample_every <= 0:
            return False
        self._seen += 1
        if self._seen % self.sample_every:
            return False
        fields = {
            "trace_id": trace_id,
            "listener": self.listener,
            "method": method,
            "path": path,
            "status": status,
            "client_ip": client_ip,
            "duration_ms": round(duration_ms, 3),
            "sampled_1_in": self.sample_every,
            "ts": round(time.time(), 3),
        }
        fields.update(extra)
        self._log.info("access", extra={"fields": fields})
        return True
