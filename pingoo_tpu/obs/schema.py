"""The documented metric inventory — the parity contract between the
three telemetry surfaces (Python listener, native httpd, ring sidecar).

WAFFLED (PAPERS.md) turns parsing discrepancies between WAF planes into
bypasses; the counter-measure on the telemetry side is that both planes
export the SAME metric names for shared concepts so divergence (e.g.
native `requests` minus sidecar `processed`) is one subtraction on one
scrape, not a join across incompatible schemas. tests/test_obs.py and
tools/check_metrics_schema.py enforce this inventory against the actual
expositions; docs/OBSERVABILITY.md is the human-readable copy.
"""

from __future__ import annotations

# Metric names every plane that handles requests must expose (with a
# `plane` label distinguishing the source: python | native).
SHARED_METRICS = {
    "pingoo_requests_total": "requests entering the WAF hot path",
    "pingoo_blocked_total": "requests answered 403 by a verdict",
    "pingoo_captcha_total": "captcha challenges served/redirected",
    "pingoo_fail_open_total":
        "requests released without a verdict (ring full, verdict "
        "deadline, engine error)",
}

# Shared verdict-wait histogram: identical bucket upper bounds (ms) on
# every surface. Native plane: enqueue -> verdict-apply wall time
# (httpd.cc record_wait). Python plane: evaluate() -> resolve wall time
# (the pre-registry `verdict_ms`). Ring telemetry block: enqueue ->
# verdict-post (pingoo_ring.cc record_waits).
SHARED_WAIT_HISTOGRAM = "pingoo_verdict_wait_ms"
SHARED_WAIT_BUCKETS_MS = (1, 2, 5, 10, 50, 100, 1000)

# Python-plane verdict pipeline stages, in hot-path order
# (engine/service.py): each is a pingoo_verdict_stage_ms{stage=...}
# histogram.
VERDICT_STAGES = (
    "queue_wait",      # evaluate() enqueue -> collector pop
    "batch_assembly",  # PER-REQUEST admit -> batch launch (ISSUE 6:
                       # stamped from each request's own admit
                       # timestamp, not the batch's first pop)
    "sched",           # scheduler hold: first admit -> launch decision
    "encode",          # RequestTuple list -> fixed-shape arrays
    "prefilter",       # Stage-A factor pass dispatch (async; ISSUE 4)
    "device_dispatch", # jitted call issue (async) incl. host->device
    "device_compute",  # block_until_ready on the device result
    "resolve",         # lanes/actions + future resolution
    "provenance",      # attribution fold + flight record + parity submit
)

# Literal-prefilter cascade metrics (docs/PREFILTER.md): exported by
# every plane that runs the batched verdict engine — the Python
# listener plane (engine/service.py, plane="python") and the ring
# sidecar serving the native plane (native_ring.py, plane="sidecar").
# The "prefilter" entry in VERDICT_STAGES above is the matching
# prefilter_ms stage histogram.
PREFILTER_METRICS = {
    "pingoo_prefilter_candidate_rate":
        "fraction of request x gated-NFA-bank pairs the literal "
        "prefilter left as candidates in the last batch",
    "pingoo_scan_banks_skipped_total":
        "NFA bank scans skipped because no request in the batch held "
        "any of the bank's necessary literal factors",
}

# Bitsplit-DFA lowering metrics (ISSUE 8, docs/DFA.md): exported by
# every plane that runs the batched verdict engine (plane="python"
# listener service, plane="sidecar" ring drainer). Both are host-static
# per plan+env — counted once per batch from the plan's scan_plans and
# the resolved PINGOO_DFA mode (engine/verdict.dfa_dispatch_counts),
# not from device results. `pingoo_dfa_banks_total` carries a `mode`
# label (auto | force) naming how the dispatch was selected.
DFA_METRICS = {
    "pingoo_dfa_banks_total":
        "NFA bank evaluations dispatched to a lowered bitsplit DFA "
        "(mode label: auto = cost-model selected, force = env pinned)",
    "pingoo_dfa_recheck_total":
        "DFA bank dispatches that took the approximate-lowering path "
        "(merged states) and rechecked candidate rows through the "
        "exact NFA bank",
}

# Verdict-provenance metrics (ISSUE 5, docs/OBSERVABILITY.md
# Provenance/Parity sections): exported by every plane that runs the
# batched verdict engine (plane="python" listener service,
# plane="sidecar" ring drainer). Per-rule families carry a `rule` label
# bounded to the top-K hitters (PINGOO_ATTR_TOP_K) plus one "_overflow"
# series so a 500-rule plan cannot blow up Prometheus cardinality;
# per-bank families carry a `bank` label (one per gated scan bank — at
# most a handful per ruleset by construction).
PROVENANCE_METRICS = {
    "pingoo_rule_hits_total":
        "requests matching each rule (top-K labelled series + the "
        "\"_overflow\" remainder bucket)",
    "pingoo_prefilter_bank_candidate_rate":
        "fraction of the last batch's rows Stage A left as candidates "
        "for this gated scan bank",
    "pingoo_scan_bank_skipped_total":
        "batches in which this gated scan bank was skipped entirely",
    "pingoo_flightrecorder_records_total":
        "requests written into the in-memory flight-recorder ring",
}

# Shadow-parity auditor metrics (ISSUE 5): the always-on sampler that
# re-evaluates PINGOO_PARITY_SAMPLE of live batches through the host
# expression interpreter off the hot path and diffs the verdicts.
PARITY_METRICS = {
    "pingoo_parity_checked_total":
        "requests re-evaluated by the shadow-parity auditor",
    "pingoo_parity_mismatch_total":
        "audited requests whose device verdict diverged from the host "
        "interpreter",
    "pingoo_parity_rule_mismatch_total":
        "per-rule breakdown of parity divergences (bounded rule label "
        "+ \"_overflow\")",
    "pingoo_parity_dropped_total":
        "sampled batches dropped because the audit queue was full",
}

# Overlapped-executor pipeline metrics (ISSUE 9, docs/EXECUTOR.md):
# exported by every plane that runs the batched verdict engine
# (plane="python" listener service, plane="sidecar" ring drainer). The
# instrument bundle lives in obs/pipeline.PipelineStats — both planes
# construct one at boot, which is what makes the pingoo_pipeline_*
# series exist under both plane labels. `stage_occupancy` carries a
# `stage` label over obs/pipeline.PIPELINE_EXEC_STAGES;
# `batches_total` carries a `mode` label (on = staged overlap,
# off = legacy lockstep — the PINGOO_PIPELINE A/B arms).
PIPELINE_METRICS = {
    "pingoo_pipeline_inflight":
        "batches currently in flight in the overlapped executor "
        "(bounded by PINGOO_PIPELINE_DEPTH)",
    "pingoo_pipeline_depth":
        "configured executor in-flight bound (PINGOO_PIPELINE_DEPTH)",
    "pingoo_pipeline_stage_occupancy":
        "fraction of wall time this executor stage has been busy "
        "since boot (stages summing past 1.0 prove overlap)",
    "pingoo_pipeline_overlap_ratio":
        "EWMA fraction of each batch's device-compute window that a "
        "different in-flight batch spent in host-side encode/dispatch",
    "pingoo_pipeline_batches_total":
        "batches served by the executor, split by mode (on = staged "
        "overlap, off = legacy lockstep)",
    # Device-resident megastep (ISSUE 12, docs/EXECUTOR.md
    # "Device-resident loop"): one jitted lax.scan dispatch covering K
    # batch slices. `batches_total` carries a `mode` label over the
    # PINGOO_MEGASTEP arms that actually launch (auto / force).
    "pingoo_megastep_k":
        "K of the most recently launched megastep window (batch "
        "slices per device dispatch)",
    "pingoo_megastep_batches_total":
        "batch slices served device-resident, split by PINGOO_MEGASTEP "
        "mode (auto = backlog-engaged, force = pinned)",
    "pingoo_megastep_amortization":
        "EWMA batch slices amortized per device dispatch (1.0 = "
        "per-batch dispatch, K = fully amortized megastep windows)",
}

# Continuous-batching scheduler + serving-mesh metrics (ISSUE 6,
# docs/SCHEDULER.md): exported by every plane that runs the batched
# verdict engine (plane="python" listener service, plane="sidecar"
# ring drainer). `pingoo_sched_batch_size` is a histogram over the
# pow2 launch-size ladder (sched/scheduler.BATCH_SIZE_BUCKETS); the
# rest are counters/gauges. The matching `sched` entry in
# VERDICT_STAGES above is the scheduler's hold-time stage histogram.
SCHED_METRICS = {
    "pingoo_sched_queue_depth":
        "requests waiting in the admission queue at the last launch",
    "pingoo_sched_batch_size":
        "per-launch batch occupancy (histogram over the pow2 ladder)",
    "pingoo_sched_deadline_miss_total":
        "requests resolved after their PINGOO_DEADLINE_MS budget",
    "pingoo_sched_failopen_total":
        "requests failed open by the scheduler because their deadline "
        "was unmeetable (PINGOO_SCHED_FAILOPEN policy)",
    "pingoo_mesh_devices":
        "devices in this plane's serving mesh (dp*tp*sp; 1 = "
        "single-device)",
}

# Ring telemetry block metrics (source: the shm header's atomic
# telemetry block, pingoo_ring.h PingooRingTelemetry), exported by BOTH
# the native httpd (it maps the ring) and the sidecar drainer (so the
# Python control-plane scrape carries native-plane queue state).
RING_METRICS = {
    "pingoo_ring_enqueued_total": "request slots enqueued",
    "pingoo_ring_dequeued_total": "request slots dequeued",
    "pingoo_ring_enqueue_full_total":
        "enqueue attempts refused because the request ring was full",
    "pingoo_ring_verdicts_posted_total": "verdict slots posted",
    "pingoo_ring_verdict_post_full_total":
        "verdict posts that hit a full verdict ring (retried)",
    "pingoo_ring_depth": "request slots currently queued",
    "pingoo_ring_depth_hwm": "high-water mark of queued request slots",
}

# Sidecar supervision + degradation-ladder metrics (ISSUE 10,
# docs/RESILIENCE.md). The liveness trio (sidecar_up / degraded_mode /
# sidecar_epoch) is exported by BOTH planes from the same ring-header
# liveness block (v5): the native httpd reads it to decide the
# degraded fast-path, the sidecar writes it. pingoo_degrade_total is
# the ladder's per-rung demotion counter (engine/ladder.py), exported
# wherever a ladder runs (plane="python" and plane="sidecar");
# reattach/chaos counters are sidecar-plane.
RESILIENCE_METRICS = {
    "pingoo_sidecar_up":
        "1 while a sidecar heartbeat is fresh (0 before any sidecar "
        "ever attached AND while degraded — both alert the same way)",
    "pingoo_degraded_mode":
        "1 while the native plane bypasses the ring (stale heartbeat "
        "past PINGOO_SIDECAR_TIMEOUT_MS): every request fails open",
    "pingoo_sidecar_epoch":
        "monotonic sidecar attach count from the ring header (a bump "
        "= a sidecar restart; reconciliation ran)",
    "pingoo_degraded_entered_total":
        "degraded-mode entries (each one failed every awaiting ticket "
        "open at once)",
    "pingoo_reattach_reconciled_total":
        "tickets a restarting sidecar reconciled from the dead epoch, "
        "by action (reeval = slot bytes intact, re-evaluated; "
        "failopen = bytes recycled, allow posted)",
    "pingoo_degrade_total":
        "degradation-ladder demotions by rung (pipeline|megastep|dfa|"
        "mesh|device|body; engine/ladder.py)",
    "pingoo_chaos_injected_total":
        "faults injected by the PINGOO_CHAOS harness, by fault "
        "(obs/chaos.py; absent in production)",
}

# Ruleset hot-swap + differential-fuzzer metrics (ISSUE 11,
# docs/RESILIENCE.md Hot-swap section / docs/FUZZING.md). The epoch
# gauge and swap counter are exported by every plane that runs the
# batched verdict engine (plane="python" listener service,
# plane="sidecar" ring drainer): the epoch is the count of plan swaps
# this plane has applied (0 = the boot plan; every verdict is
# attributable to exactly one epoch), and the swap counter carries
# {tenant, result} labels (result: ok | rejected). The fuzz counter is
# emitted by the differential fuzzer (tools/analyze/fuzz.py) when a
# run's registry is scraped — absent in production serving.
HOTSWAP_METRICS = {
    "pingoo_ruleset_epoch":
        "ruleset plan epoch on this plane (bumps once per applied "
        "hot-swap; in-flight batches always finish on their epoch)",
    "pingoo_ruleset_swap_total":
        "ruleset hot-swap attempts by {tenant, result} (ok = flipped "
        "at a batch boundary, rejected = build/validation failed)",
    "pingoo_fuzz_discrepancy_total":
        "differential-fuzzer parse discrepancies by class (not a "
        "documented known-delta; tools/analyze/fuzz.py)",
}

# Streaming body-inspection metrics (ISSUE 13, docs/BODY_STREAMING.md
# / docs/OBSERVABILITY.md). Exported by BOTH planes when
# PINGOO_BODY_INSPECT=on: the sidecar (plane="sidecar") runs the
# windowed scanner over ring body slots, the Python listener
# (plane="python") over its buffered bodies, and the native httpd
# (plane="native") counts the producer side — windows enqueued, flows
# failed open, h2 streams skipped.
BODY_METRICS = {
    "pingoo_body_windows_total":
        "body windows scanned (sidecar/python) or enqueued (native)",
    "pingoo_body_flows_active":
        "flows with live carry-over state in the scanner table",
    "pingoo_body_carry_depth":
        "windows a finished flow's verdict waited for, i.e. carry-over "
        "chain length (histogram)",
    "pingoo_body_bytes_total": "body payload bytes scanned",
    "pingoo_body_degrade_total":
        "flows degraded to metadata-only verdicts, by reason (evict = "
        "state-table pressure, ttl = stalled flow reaped, gap = window "
        "sequence gap, abort = client reset, ring_full = body ring "
        "back-pressure, ladder = body rung demoted, h2 = native h2 "
        "stream not inspected this PR)",
}

# Compact-staging metrics (ISSUE 15, docs/EXECUTOR.md "Compact
# staging"). Exported by every plane that runs the batched verdict
# engine (plane="python" listener service, plane="sidecar" ring
# drainer). `staged_bytes_total` carries a `mode` label over the
# PINGOO_STAGING arms (full = per-field staging, compact = packed
# one-copy buffer) so the bytes-per-request reduction is one division
# on one scrape; `staging_field_cap` is host-static per adopted plan —
# the plan-derived per-field staging width (equal to the field spec
# under PINGOO_STAGING=full or when the ruleset pins the field).
STAGING_METRICS = {
    "pingoo_staged_bytes_total":
        "request bytes staged to the device for verdict batches, by "
        "mode (full = per-field arrays, compact = packed buffer)",
    "pingoo_staging_field_cap":
        "per-field staging width in bytes under the adopted plan "
        "(plan-derived cap, quantized to the pow2 rung ladder)",
}

# Perf ledger + cross-plane timeline + durable cost ledger (ISSUE 17,
# docs/OBSERVABILITY.md "Compile ledger"/"Timeline"/"Cost ledger").
# Exported by every plane that runs the batched verdict engine
# (plane="python" listener service, plane="sidecar" ring drainer).
# `pingoo_compile_total` carries {plane, fn, kind} — fn over
# obs/perf.COMPILE_FN_KINDS (verdict|lanes|prefilter|megastep|score;
# the packed-staging twins report under the same fn label), kind
# cold|warm (warm = a retrace under live traffic, the recompile-storm
# alert series); `pingoo_compile_ms` is a {plane, fn} histogram over
# obs/perf.COMPILE_BUCKETS_MS. `pingoo_timeline_spans_total{plane}`
# counts spans the sampler actually recorded (plane also takes the
# value "native" for ring-wait spans stamped from native enqueue
# clocks). `pingoo_costmodel_reload_total{plane, result}` counts boot
# reload attempts of the durable cost ledger (result: ok | stale |
# missing | error).
PERF_METRICS = {
    "pingoo_compile_total":
        "XLA trace/compile events observed by the compile ledger, by "
        "{fn, kind} (cold = a wrapper's first compile, warm = a later "
        "retrace — the recompile-storm signal)",
    "pingoo_compile_ms":
        "wall time of observed XLA trace/compile events (histogram "
        "per {plane, fn})",
    "pingoo_timeline_spans_total":
        "spans recorded by the cross-plane timeline sampler "
        "(PINGOO_TIMELINE_SAMPLE-gated; bounded in-memory ring)",
    "pingoo_costmodel_reload_total":
        "durable cost-ledger reload attempts at boot, by result (ok = "
        "EWMAs restored, stale = fingerprint/version mismatch "
        "discarded, missing = no snapshot for this backend+plane, "
        "error = unreadable file)",
    "pingoo_compile_unexpected_total":
        "compile events OUTSIDE the statically-proved admissible "
        "surface (COMPILE_SURFACE.json via PINGOO_COMPILE_SURFACE), by "
        "{plane, fn} — any nonzero value means an unquantized shape "
        "axis reached a jitted dispatch; fails make timeline-smoke",
}

# Native-plane-only counters (httpd.cc Stats), exported with
# plane="native" under these names.
NATIVE_METRICS = {
    "pingoo_ua_rejected_total": "empty/oversized UA pre-ring 403s",
    "pingoo_no_service_total": "route bits said no service (404)",
    "pingoo_upstream_fail_total": "upstream connect/response failures (502)",
    "pingoo_upstream_tls_fail_total":
        "upstream TLS handshake/verify failures",
    "pingoo_verdicts_total": "verdict bytes applied",
    "pingoo_connections": "open client connections",
    "pingoo_pooled_upstreams": "idle pooled upstream connections",
}

# JSON back-compat keys (the pre-registry schemas, still served under
# Accept: application/json). Maps JSON key -> metric name, per plane.
PYTHON_JSON_KEYS = {
    "requests": "pingoo_requests_total",
    "blocked": "pingoo_blocked_total",
    "captcha_served": "pingoo_captcha_total",
}
NATIVE_JSON_KEYS = {
    "requests": "pingoo_requests_total",
    "blocked": "pingoo_blocked_total",
    "captcha": "pingoo_captcha_total",
    "fail_open": "pingoo_fail_open_total",
    "verdict_wait_ms_hist": "pingoo_verdict_wait_ms",
}


def all_metric_names() -> set[str]:
    return (set(SHARED_METRICS) | set(RING_METRICS) | set(NATIVE_METRICS)
            | set(PREFILTER_METRICS) | set(DFA_METRICS)
            | set(PROVENANCE_METRICS)
            | set(PARITY_METRICS) | set(SCHED_METRICS)
            | set(PIPELINE_METRICS) | set(RESILIENCE_METRICS)
            | set(HOTSWAP_METRICS) | set(BODY_METRICS)
            | set(STAGING_METRICS) | set(PERF_METRICS)
            | {SHARED_WAIT_HISTOGRAM, "pingoo_verdict_stage_ms"})
