"""The documented metric inventory — the parity contract between the
three telemetry surfaces (Python listener, native httpd, ring sidecar).

WAFFLED (PAPERS.md) turns parsing discrepancies between WAF planes into
bypasses; the counter-measure on the telemetry side is that both planes
export the SAME metric names for shared concepts so divergence (e.g.
native `requests` minus sidecar `processed`) is one subtraction on one
scrape, not a join across incompatible schemas. tests/test_obs.py and
tools/check_metrics_schema.py enforce this inventory against the actual
expositions; docs/OBSERVABILITY.md is the human-readable copy.
"""

from __future__ import annotations

# Metric names every plane that handles requests must expose (with a
# `plane` label distinguishing the source: python | native).
SHARED_METRICS = {
    "pingoo_requests_total": "requests entering the WAF hot path",
    "pingoo_blocked_total": "requests answered 403 by a verdict",
    "pingoo_captcha_total": "captcha challenges served/redirected",
    "pingoo_fail_open_total":
        "requests released without a verdict (ring full, verdict "
        "deadline, engine error)",
}

# Shared verdict-wait histogram: identical bucket upper bounds (ms) on
# every surface. Native plane: enqueue -> verdict-apply wall time
# (httpd.cc record_wait). Python plane: evaluate() -> resolve wall time
# (the pre-registry `verdict_ms`). Ring telemetry block: enqueue ->
# verdict-post (pingoo_ring.cc record_waits).
SHARED_WAIT_HISTOGRAM = "pingoo_verdict_wait_ms"
SHARED_WAIT_BUCKETS_MS = (1, 2, 5, 10, 50, 100, 1000)

# Python-plane verdict pipeline stages, in hot-path order
# (engine/service.py): each is a pingoo_verdict_stage_ms{stage=...}
# histogram.
VERDICT_STAGES = (
    "queue_wait",      # evaluate() enqueue -> collector pop
    "batch_assembly",  # collector pop -> batch dispatch (the wait window)
    "encode",          # RequestTuple list -> fixed-shape arrays
    "prefilter",       # Stage-A factor pass dispatch (async; ISSUE 4)
    "device_dispatch", # jitted call issue (async) incl. host->device
    "device_compute",  # block_until_ready on the device result
    "resolve",         # lanes/actions + future resolution
)

# Literal-prefilter cascade metrics (docs/PREFILTER.md): exported by
# every plane that runs the batched verdict engine — the Python
# listener plane (engine/service.py, plane="python") and the ring
# sidecar serving the native plane (native_ring.py, plane="sidecar").
# The "prefilter" entry in VERDICT_STAGES above is the matching
# prefilter_ms stage histogram.
PREFILTER_METRICS = {
    "pingoo_prefilter_candidate_rate":
        "fraction of request x gated-NFA-bank pairs the literal "
        "prefilter left as candidates in the last batch",
    "pingoo_scan_banks_skipped_total":
        "NFA bank scans skipped because no request in the batch held "
        "any of the bank's necessary literal factors",
}

# Ring telemetry block metrics (source: the shm header's atomic
# telemetry block, pingoo_ring.h PingooRingTelemetry), exported by BOTH
# the native httpd (it maps the ring) and the sidecar drainer (so the
# Python control-plane scrape carries native-plane queue state).
RING_METRICS = {
    "pingoo_ring_enqueued_total": "request slots enqueued",
    "pingoo_ring_dequeued_total": "request slots dequeued",
    "pingoo_ring_enqueue_full_total":
        "enqueue attempts refused because the request ring was full",
    "pingoo_ring_verdicts_posted_total": "verdict slots posted",
    "pingoo_ring_verdict_post_full_total":
        "verdict posts that hit a full verdict ring (retried)",
    "pingoo_ring_depth": "request slots currently queued",
    "pingoo_ring_depth_hwm": "high-water mark of queued request slots",
}

# Native-plane-only counters (httpd.cc Stats), exported with
# plane="native" under these names.
NATIVE_METRICS = {
    "pingoo_ua_rejected_total": "empty/oversized UA pre-ring 403s",
    "pingoo_no_service_total": "route bits said no service (404)",
    "pingoo_upstream_fail_total": "upstream connect/response failures (502)",
    "pingoo_upstream_tls_fail_total":
        "upstream TLS handshake/verify failures",
    "pingoo_verdicts_total": "verdict bytes applied",
    "pingoo_connections": "open client connections",
    "pingoo_pooled_upstreams": "idle pooled upstream connections",
}

# JSON back-compat keys (the pre-registry schemas, still served under
# Accept: application/json). Maps JSON key -> metric name, per plane.
PYTHON_JSON_KEYS = {
    "requests": "pingoo_requests_total",
    "blocked": "pingoo_blocked_total",
    "captcha_served": "pingoo_captcha_total",
}
NATIVE_JSON_KEYS = {
    "requests": "pingoo_requests_total",
    "blocked": "pingoo_blocked_total",
    "captcha": "pingoo_captcha_total",
    "fail_open": "pingoo_fail_open_total",
    "verdict_wait_ms_hist": "pingoo_verdict_wait_ms",
}


def all_metric_names() -> set[str]:
    return (set(SHARED_METRICS) | set(RING_METRICS) | set(NATIVE_METRICS)
            | set(PREFILTER_METRICS)
            | {SHARED_WAIT_HISTOGRAM, "pingoo_verdict_stage_ms"})
