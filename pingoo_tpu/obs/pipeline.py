"""Overlapped-executor pipeline telemetry (ISSUE 9, docs/EXECUTOR.md).

Both engine planes run the zero-copy pipelined executor — the Python
listener service (engine/service.py, plane="python") and the ring
sidecar (native_ring.RingSidecar, plane="sidecar") — and each owns one
`PipelineStats` bundle exporting the obs/schema.PIPELINE_METRICS
family on its plane label:

  * pingoo_pipeline_inflight{plane}: batches currently between stage
    entry and final resolve (the executor's live depth; bounded by
    PINGOO_PIPELINE_DEPTH).
  * pingoo_pipeline_depth{plane}: the configured in-flight bound.
  * pingoo_pipeline_stage_occupancy{plane,stage}: fraction of wall
    time each stage has been busy since boot — a stage near 1.0 is the
    pipeline's bottleneck, stages summing past 1.0 prove overlap.
  * pingoo_pipeline_overlap_ratio{plane}: EWMA fraction of each
    batch's device-compute window that a DIFFERENT in-flight batch
    spent in host-side encode/dispatch — the acceptance number for
    "batch N+1 encodes while batch N scans" (> 0 means the executor is
    actually overlapping, not just queueing).
  * pingoo_pipeline_batches_total{plane,mode}: batches served, split
    by executor mode (on = staged overlap, off = legacy lockstep), so
    an A/B drive can attribute throughput to the arm that produced it.

Interval bookkeeping is host-side float math on the plane's own
serial context (event loop / drain thread): no locks, no arrays, no
device access. Overlap is computed from (monotonic) stage wall
intervals kept in a small ring: whichever of the two intervals in an
(other-batch host stage, compute) pair is recorded second finds the
first, so each pair is counted exactly once.
"""

from __future__ import annotations

import time
from collections import deque

# Executor stage names, in hot-path order. "encode" and "dispatch" are
# host-side (staging fill, jit call issue); "compute" is the device
# wall (the window other batches should overlap); "resolve" is the
# host-side fan-out after results land.
PIPELINE_EXEC_STAGES = ("encode", "dispatch", "compute", "resolve")

# Host-side stages whose wall overlapping a DIFFERENT batch's compute
# window is the overlap the executor exists to create.
_HOST_STAGES = frozenset(("encode", "dispatch"))

_EWMA_ALPHA = 0.2
_RECENT_INTERVALS = 32


class PipelineStats:
    """One plane's pipeline instrument bundle + overlap bookkeeping.

    Created eagerly at plane boot (like sched.SchedMetrics) so the full
    PIPELINE_METRICS inventory exists from the first scrape; the mode
    counters are created lazily per observed mode label.
    """

    def __init__(self, plane: str, depth: int, registry=None):
        if registry is None:
            from . import REGISTRY as registry  # noqa: N813
        from . import schema

        self.plane = plane
        self._registry = registry
        labels = {"plane": plane}
        self.inflight = registry.gauge(
            "pingoo_pipeline_inflight",
            schema.PIPELINE_METRICS["pingoo_pipeline_inflight"],
            labels=labels)
        self.depth = registry.gauge(
            "pingoo_pipeline_depth",
            schema.PIPELINE_METRICS["pingoo_pipeline_depth"],
            labels=labels)
        self.depth.set(max(1, int(depth)))
        self.overlap_ratio = registry.gauge(
            "pingoo_pipeline_overlap_ratio",
            schema.PIPELINE_METRICS["pingoo_pipeline_overlap_ratio"],
            labels=labels)
        self._occupancy = {
            stage: registry.gauge(
                "pingoo_pipeline_stage_occupancy",
                schema.PIPELINE_METRICS["pingoo_pipeline_stage_occupancy"],
                labels={"plane": plane, "stage": stage})
            for stage in PIPELINE_EXEC_STAGES}
        # Device-resident megastep instruments (ISSUE 12): K of the
        # latest window, slices served per PINGOO_MEGASTEP mode, and
        # the EWMA dispatch-amortization factor (slices per device
        # dispatch; 1.0 means the plane is back to per-batch dispatch).
        self.megastep_k = registry.gauge(
            "pingoo_megastep_k",
            schema.PIPELINE_METRICS["pingoo_megastep_k"], labels=labels)
        self.megastep_amortization = registry.gauge(
            "pingoo_megastep_amortization",
            schema.PIPELINE_METRICS["pingoo_megastep_amortization"],
            labels=labels)
        self._megastep_batches: dict[str, object] = {}
        self._amort_ewma: float | None = None
        self.megastep_windows = 0
        self.megastep_slices = 0
        self._batches: dict[str, object] = {}
        self._slot_seq = 0
        self._t_boot = time.monotonic()
        self._busy = dict.fromkeys(PIPELINE_EXEC_STAGES, 0.0)
        # (slot, stage, t_start, t_end) of recent stage walls; 32 spans
        # several pipeline depths of history on both planes.
        self._recent: deque = deque(maxlen=_RECENT_INTERVALS)
        self._overlap_ewma: float | None = None
        self.overlap_events = 0

    # -- batch lifecycle (hot) ----------------------------------------------

    def enter(self, mode: str = "on") -> int:
        """A batch entered the executor; returns its pipeline slot id
        (monotonic per plane — flight-recorder rows carry it so an
        explain/debug session can line batches up against the overlap
        series)."""
        self._slot_seq += 1
        self.inflight.inc()
        counter = self._batches.get(mode)
        if counter is None:
            from . import schema

            counter = self._registry.counter(
                "pingoo_pipeline_batches_total",
                schema.PIPELINE_METRICS["pingoo_pipeline_batches_total"],
                labels={"plane": self.plane, "mode": mode})
            self._batches[mode] = counter
        counter.inc()
        return self._slot_seq

    def exit(self) -> None:
        self.inflight.dec()

    def note_megastep(self, k: int, mode: str) -> None:
        """One K-slice megastep window launched under PINGOO_MEGASTEP
        `mode` (hot; ISSUE 12): updates the K gauge, the per-mode slice
        counter, and the EWMA dispatch-amortization factor."""
        k = max(1, int(k))
        self.megastep_k.set(k)
        counter = self._megastep_batches.get(mode)
        if counter is None:
            from . import schema

            counter = self._registry.counter(
                "pingoo_megastep_batches_total",
                schema.PIPELINE_METRICS["pingoo_megastep_batches_total"],
                labels={"plane": self.plane, "mode": mode})
            self._megastep_batches[mode] = counter
        counter.inc(k)
        self.megastep_windows += 1
        self.megastep_slices += k
        prev = self._amort_ewma
        self._amort_ewma = (float(k) if prev is None
                            else prev + _EWMA_ALPHA * (k - prev))
        self.megastep_amortization.set(round(self._amort_ewma, 6))

    def note_stage(self, slot: int, stage: str, t_start: float,
                   t_end: float) -> None:
        """Record one stage's wall interval (monotonic seconds) for the
        given pipeline slot: updates the stage's occupancy gauge and,
        when the interval pairs with a different slot's interval of the
        opposite kind (host stage x compute), the overlap ratio."""
        dur = t_end - t_start
        if dur < 0.0:
            return
        busy = self._busy.get(stage)
        if busy is None:  # unknown stage: occupancy only tracks the
            return        # canonical four
        self._busy[stage] = busy + dur
        wall = t_end - self._t_boot
        if wall > 0.0:
            self._occupancy[stage].set(
                min(1.0, round(self._busy[stage] / wall, 6)))
        if stage == "compute":
            self._score_overlap(slot, t_start, t_end,
                                want_host=True, compute_dur=dur)
        elif stage in _HOST_STAGES:
            self._score_overlap(slot, t_start, t_end, want_host=False)
        self._recent.append((slot, stage, t_start, t_end))

    # -- overlap bookkeeping -------------------------------------------------

    def _score_overlap(self, slot: int, t0: float, t1: float,
                       want_host: bool,
                       compute_dur: float = 0.0) -> None:
        """Pair the just-finished interval against stored intervals of
        the opposite kind from OTHER slots; the ratio denominator is
        always the compute window (the thing being hidden)."""
        for o_slot, o_stage, o_t0, o_t1 in self._recent:
            if o_slot == slot:
                continue
            if want_host != (o_stage in _HOST_STAGES):
                continue
            ov = min(t1, o_t1) - max(t0, o_t0)
            if ov <= 0.0:
                continue
            denom = compute_dur if want_host else (o_t1 - o_t0)
            if denom <= 0.0:
                continue
            self._note_overlap(min(1.0, ov / denom))

    def _note_overlap(self, ratio: float) -> None:
        self.overlap_events += 1
        prev = self._overlap_ewma
        if prev is None:
            self._overlap_ewma = ratio
        else:
            self._overlap_ewma = prev + _EWMA_ALPHA * (ratio - prev)
        self.overlap_ratio.set(round(self._overlap_ewma, 6))

    def snapshot(self) -> dict:
        wall = max(time.monotonic() - self._t_boot, 1e-9)
        return {
            "plane": self.plane,
            "depth": self.depth.value,
            "inflight": self.inflight.value,
            "batches": {mode: c.value
                        for mode, c in sorted(self._batches.items())},
            "overlap_ratio": (round(self._overlap_ewma, 4)
                              if self._overlap_ewma is not None else None),
            "overlap_events": self.overlap_events,
            "stage_occupancy": {
                stage: round(self._busy[stage] / wall, 4)
                for stage in PIPELINE_EXEC_STAGES},
            "megastep": {
                "k": self.megastep_k.value,
                "windows": self.megastep_windows,
                "slices": self.megastep_slices,
                "amortization": (round(self._amort_ewma, 4)
                                 if self._amort_ewma is not None
                                 else None),
                "slices_by_mode": {
                    mode: c.value for mode, c in sorted(
                        self._megastep_batches.items())},
            },
        }
