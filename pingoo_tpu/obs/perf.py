"""Compile ledger (ISSUE 17): every jit trace/compile event, counted
and durable.

The engine's latency cliffs are XLA compiles: the first call of every
jitted program per abstract signature (pow2 batch bucket, megastep K
rung, staging widths-tuple) blocks for seconds, and a *recompile storm*
— a plan swap or a bucket ladder walking shapes under live traffic —
is the difference between a 2 ms p99 and a multi-second outage. The
stage histograms can't see it (they attribute the stall to whatever
stage the call sat in); this module makes each compile a first-class
event:

  * `instrument_jit(fn, ...)` wraps a jitted callable returned by the
    `engine/verdict.make_*_fn` factories (the wrapper composes AFTER
    jax.jit, so donation and static_argnums semantics are untouched).
    Each call probes the pjit executable cache size before/after — two
    O(1) C calls, no device sync — and a growth means THIS call paid a
    trace+compile: the call wall is the compile wall (jit compiles
    synchronously before the async dispatch returns).
  * every event lands in the process-global `CompileLedger`: a bounded
    in-memory ring (`/__pingoo/compileledger` dumps it), the
    `pingoo_compile_total{plane,fn,kind}` counter +
    `pingoo_compile_ms{plane,fn}` histogram, and — when
    `PINGOO_PERF_LEDGER` names a file — one JSONL line per event in
    `PERF_LEDGER.jsonl`, so compile counts survive the process and
    cross-check against the counter.

Gating: unset/0 `PINGOO_PERF_LEDGER` makes `instrument_jit` return the
callable UNCHANGED — zero added work on the hot path (the metric
instruments are still created eagerly at zero so the inventory is
scrapeable either way). `1`/`on` enables with the default
`PERF_LEDGER.jsonl`; any other value is the ledger path.

`kind` classifies the event: `cold` = the wrapper's first compile (the
expected warm-up), `warm` = a later retrace (new shape under live
traffic — the alertable series).

`_InstrumentedJit.__call__` is registered hot in
tools/analyze/lint_config.py: nothing on the per-call path may
allocate arrays or sync the device — event assembly only runs on the
(rare) compile branch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

# The fn-kind label values the wrappers emit (verdict/lane/prefilter
# programs, their packed-staging twins under the same label, the
# megastep scan, and the bot-score program).
COMPILE_FN_KINDS = ("verdict", "lanes", "prefilter", "megastep", "score")

# pingoo_compile_ms histogram bounds: sub-ms cache refreshes up to the
# multi-second cold megastep compiles BENCH_pipeline measured (~9.5 s).
COMPILE_BUCKETS_MS = (1.0, 5.0, 25.0, 100.0, 250.0, 500.0, 1000.0,
                      2500.0, 5000.0, 10000.0, 30000.0)

DEFAULT_LEDGER_FILE = "PERF_LEDGER.jsonl"
_EVENTS_CAP = 1024


def perf_ledger_path() -> Optional[str]:
    """The PINGOO_PERF_LEDGER gate: None = off (default), otherwise
    the JSONL path compile events persist to."""
    raw = os.environ.get("PINGOO_PERF_LEDGER", "").strip()
    if not raw or raw.lower() in ("0", "off", "false"):
        return None
    if raw.lower() in ("1", "on", "true"):
        return DEFAULT_LEDGER_FILE
    return raw


def plan_fingerprint(plan) -> str:
    """Cheap plan-derived ruleset-epoch fingerprint: hashes the
    plan-static content that changes a compiled program's identity
    (rule names, staging caps, DFA dispatch default) — NOT the full
    compiler cache key, but stable per adopted plan and computable
    without re-walking the ruleset. Versions both the compile ledger
    events and the durable cost ledger (sched/scheduler.py)."""
    import hashlib

    h = hashlib.sha256()
    for name in getattr(plan, "rule_names", None) or ():
        h.update(str(name).encode("utf-8", "replace"))
        h.update(b"\x00")
    caps = getattr(plan, "staging_caps", None) or {}
    for field in sorted(caps):
        h.update(f"{field}={caps[field]}".encode())
    h.update(str(getattr(plan, "dfa_default_mode", "")).encode())
    h.update(str(getattr(plan, "field_specs", "")).encode())
    return h.hexdigest()[:16]


def staging_widths(plan) -> tuple:
    """The plan's staging widths-tuple (sorted field -> cap), the
    shape-identity component of a compiled program's signature."""
    caps = getattr(plan, "staging_caps", None) or {}
    return tuple((f, int(caps[f])) for f in sorted(caps))


def _arg_shapes(args) -> list:
    """Array shapes across the call's pytree — only evaluated on the
    compile branch (rare), never per call."""
    shapes = []
    try:
        from jax import tree_util

        for leaf in tree_util.tree_leaves(args):
            shp = getattr(leaf, "shape", None)
            if shp is not None and len(shp):
                shapes.append(tuple(int(d) for d in shp))
                if len(shapes) >= 24:
                    break
    except Exception:
        pass
    return shapes


def _shape_context(shapes: list) -> tuple:
    """(batch_bucket, k) best-effort from the compile-time arg shapes:
    the batch bucket is the most common leading dim of the 2-D request
    arrays; K is the leading dim of a 3-D stacked megastep input."""
    from collections import Counter

    lead2 = Counter(s[0] for s in shapes if len(s) == 2)
    bucket = lead2.most_common(1)[0][0] if lead2 else None
    lead3 = Counter(s[0] for s in shapes if len(s) == 3)
    k = lead3.most_common(1)[0][0] if lead3 else None
    return bucket, k


def load_compile_surface(path: str) -> Optional[dict]:
    """Read a COMPILE_SURFACE.json (tools/analyze/surface.py); None on
    an unreadable/malformed file — the ledger then skips surface checks
    rather than flagging every event."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) and "fns" in doc else None
    except (OSError, ValueError):
        return None


def event_in_surface(event: dict, surface: dict) -> Optional[str]:
    """None when a compile event lies inside the statically-proved
    admissible surface; else the axis that escaped it."""
    if event.get("plane") not in surface.get("planes", ()):
        return f"plane={event.get('plane')!r}"
    if event.get("fn") not in surface.get("fns", ()):
        return f"fn={event.get('fn')!r}"
    if event.get("kind") not in surface.get("kinds", ()):
        return f"kind={event.get('kind')!r}"
    bucket = event.get("batch_bucket")
    if bucket is not None and bucket not in surface.get(
            "batch_buckets", ()):
        return f"batch_bucket={bucket}"
    k = event.get("k")
    if k is not None and k not in surface.get("k_rungs", ()):
        return f"k={k}"
    widths = [list(w) for w in event.get("widths") or ()]
    if widths and "widths" in surface and widths not in surface["widths"]:
        return "widths"
    return None


_SURFACE_UNSET = object()

# Dispatchers stamp the TRUE padded launch shape here right before an
# instrumented call: the compact one-copy path bakes the batch into a
# flat packed blob + static layout, so arg-shape inspection alone
# recovers a rule-table dim, not the batch axis. Thread-local because
# the listener service and the ring sidecar dispatch on their own
# threads within one process.
_DISPATCH_TLS = threading.local()


def set_dispatch_context(batch: Optional[int] = None,
                         k: Optional[int] = None) -> None:
    _DISPATCH_TLS.batch = batch
    _DISPATCH_TLS.k = k


def dispatch_context() -> tuple:
    return (getattr(_DISPATCH_TLS, "batch", None),
            getattr(_DISPATCH_TLS, "k", None))


def batch_leading_dim(arrays) -> Optional[int]:
    """Padded launch batch from a per-field arrays mapping (the leading
    dim of any 2-D request array)."""
    for a in arrays.values():
        shape = getattr(a, "shape", ())
        if len(shape) >= 2:
            return int(shape[0])
    return None


class CompileLedger:
    """Process-global compile-event sink shared by both Python planes
    (the listener service and the ring sidecar are co-resident)."""

    def __init__(self, path: Optional[str] = None, registry=None):
        self.path = path
        self._lock = threading.Lock()
        self.events: deque = deque(maxlen=int(
            os.environ.get("PINGOO_PERF_LEDGER_N", _EVENTS_CAP)))
        self.totals: dict[tuple, int] = {}
        self._counters: dict[tuple, Any] = {}
        self._hists: dict[tuple, Any] = {}
        self._registry = registry
        self._io_errors = 0
        self._surface_doc: Any = _SURFACE_UNSET
        self._unexpected_ctrs: dict[tuple, Any] = {}
        self.unexpected_total = 0

    def _surface(self) -> Optional[dict]:
        # Resolved once per ledger: surface membership runs only on the
        # rare compile branch, but env/file reads still don't belong
        # there per-event.
        if self._surface_doc is _SURFACE_UNSET:
            path = os.environ.get("PINGOO_COMPILE_SURFACE")
            self._surface_doc = load_compile_surface(path) if path else None
        return self._surface_doc

    def _unexpected_counter(self, plane: str, fn: str):
        key = (plane, fn)
        ctr = self._unexpected_ctrs.get(key)
        if ctr is None:
            from . import schema

            ctr = self._reg().counter(
                "pingoo_compile_unexpected_total",
                schema.PERF_METRICS["pingoo_compile_unexpected_total"],
                labels={"plane": plane, "fn": fn})
            self._unexpected_ctrs[key] = ctr
        return ctr

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def _reg(self):
        if self._registry is None:
            from . import REGISTRY

            self._registry = REGISTRY
        return self._registry

    def ensure_instruments(self, plane: str) -> None:
        """Create the plane's compile metric series at zero (boot-time,
        so the inventory is scrapeable before any compile event)."""
        for fn in COMPILE_FN_KINDS:
            for kind in ("cold", "warm"):
                self._counter(plane, fn, kind)
            self._hist(plane, fn)

    def _counter(self, plane: str, fn: str, kind: str):
        key = (plane, fn, kind)
        ctr = self._counters.get(key)
        if ctr is None:
            from . import schema

            ctr = self._reg().counter(
                "pingoo_compile_total",
                schema.PERF_METRICS["pingoo_compile_total"],
                labels={"plane": plane, "fn": fn, "kind": kind})
            self._counters[key] = ctr
        return ctr

    def _hist(self, plane: str, fn: str):
        key = (plane, fn)
        h = self._hists.get(key)
        if h is None:
            from . import schema

            h = self._reg().histogram(
                "pingoo_compile_ms",
                schema.PERF_METRICS["pingoo_compile_ms"],
                buckets=COMPILE_BUCKETS_MS,
                labels={"plane": plane, "fn": fn})
            self._hists[key] = h
        return h

    def note(self, *, plane: str, fn: str, kind: str, wall_ms: float,
             fingerprint: str = "", widths: tuple = (),
             shapes: Optional[list] = None,
             batch_bucket: Optional[int] = None,
             k: Optional[int] = None) -> None:
        """One trace/compile event (called from the compile branch of
        an instrumented call — rare by construction). Explicit
        batch_bucket/k (from set_dispatch_context) win over the
        arg-shape heuristic, which cannot see through packed blobs."""
        h_bucket, h_k = _shape_context(shapes or [])
        bucket = batch_bucket if batch_bucket is not None else h_bucket
        k = k if k is not None else h_k
        event = {
            "ts": round(time.time(), 3),
            "plane": plane,
            "fn": fn,
            "kind": kind,
            "wall_ms": round(wall_ms, 3),
            "batch_bucket": bucket,
            "k": k,
            "widths": [list(w) for w in widths],
            "fingerprint": fingerprint,
            "shapes": [list(s) for s in (shapes or [])[:12]],
        }
        surface = self._surface()
        if surface is not None:
            reason = event_in_surface(event, surface)
            if reason is not None:
                event["unexpected"] = reason
                self._unexpected_counter(plane, fn).inc()
        self._counter(plane, fn, kind).inc()
        self._hist(plane, fn).observe(wall_ms)
        with self._lock:
            self.events.append(event)
            tkey = (plane, fn, kind)
            self.totals[tkey] = self.totals.get(tkey, 0) + 1
            if event.get("unexpected"):
                self.unexpected_total += 1
        if self.path:
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(event) + "\n")
            except OSError:
                self._io_errors += 1

    def snapshot(self) -> dict:
        """The /__pingoo/compileledger payload."""
        with self._lock:
            events = list(self.events)
            totals = {f"{p}/{fn}/{kind}": n
                      for (p, fn, kind), n in sorted(self.totals.items())}
        return {
            "enabled": self.enabled,
            "path": self.path,
            "compiles_total": sum(totals.values()),
            "totals": totals,
            "io_errors": self._io_errors,
            "surface_loaded": self._surface() is not None,
            "unexpected_total": self.unexpected_total,
            "events": events,
        }


_LEDGER: Optional[CompileLedger] = None
_LEDGER_LOCK = threading.Lock()


def get_compile_ledger() -> CompileLedger:
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = CompileLedger(path=perf_ledger_path())
    return _LEDGER


def reset_compile_ledger_for_tests() -> None:
    """Drop the singleton so a test can re-read PINGOO_PERF_LEDGER."""
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = None


class _InstrumentedJit:
    """Transparent wrapper over one jitted callable: per call, two
    executable-cache-size probes decide whether THIS call paid a
    trace+compile; the event branch runs only when it did. Attribute
    access (e.g. `.clear_cache`) delegates to the wrapped callable."""

    __slots__ = ("_fn", "_probe", "_plane", "_name", "_fingerprint",
                 "_widths", "_ledger", "_compiles")

    def __init__(self, fn: Callable, name: str, plane: str,
                 fingerprint: str, widths: tuple,
                 ledger: CompileLedger):
        self._fn = fn
        probe = getattr(fn, "_cache_size", None)
        self._probe = probe if callable(probe) else None
        self._plane = plane
        self._name = name
        self._fingerprint = fingerprint
        self._widths = widths
        self._ledger = ledger
        self._compiles = 0

    def __call__(self, *args):
        probe = self._probe
        if probe is not None:
            try:
                before = probe()
            except Exception:
                before = -1
        else:
            # No cache probe on this jax build: only the first call is
            # attributable (it is always a compile); later retraces go
            # uncounted rather than mis-counted.
            before = -1 if self._compiles else 0
        t0 = time.monotonic()
        out = self._fn(*args)
        if before >= 0:
            if probe is not None:
                try:
                    grew = probe() > before
                except Exception:
                    grew = False
            else:
                grew = True
            if grew:
                wall_ms = (time.monotonic() - t0) * 1e3
                kind = "cold" if self._compiles == 0 else "warm"
                self._compiles += 1
                ctx_batch, ctx_k = dispatch_context()
                self._ledger.note(
                    plane=self._plane, fn=self._name, kind=kind,
                    wall_ms=wall_ms, fingerprint=self._fingerprint,
                    widths=self._widths, shapes=_arg_shapes(args),
                    batch_bucket=ctx_batch, k=ctx_k)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument_jit(fn, name: str, *, plane: str, fingerprint: str = "",
                   widths: tuple = (), ledger=None):
    """Wrap one jitted callable for compile tracking. With the
    PINGOO_PERF_LEDGER gate off this returns `fn` UNCHANGED (zero
    hot-path delta); None passes through so optional programs
    (prefilter may be absent) wrap with no branching at call sites."""
    if fn is None:
        return None
    if ledger is None:
        ledger = get_compile_ledger()
    ledger.ensure_instruments(plane)
    if not ledger.enabled:
        return fn
    return _InstrumentedJit(fn, name, plane, fingerprint, widths, ledger)


class _InstrumentedMegastep:
    """Shape-preserving wrapper for make_megastep_fn's program record:
    `.fn` is the instrumented callable, everything else delegates."""

    __slots__ = ("_prog", "fn")

    def __init__(self, prog, fn):
        self._prog = prog
        self.fn = fn

    def __getattr__(self, item):
        return getattr(self._prog, item)


def instrument_megastep(prog, *, plane: str, fingerprint: str = "",
                        widths: tuple = (), ledger=None):
    """instrument_jit for the megastep program object (callable at
    `.fn`, metadata like `.aux_len` preserved)."""
    if prog is None:
        return None
    fn = instrument_jit(prog.fn, "megastep", plane=plane,
                        fingerprint=fingerprint, widths=widths,
                        ledger=ledger)
    if fn is prog.fn:
        return prog
    return _InstrumentedMegastep(prog, fn)
