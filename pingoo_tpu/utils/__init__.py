"""Shared utilities (ruleset/traffic generators, observability)."""
