"""OWASP-CRS-style ruleset + traffic generators for benchmarks and tests.

BASELINE.md measures the engine against a "500-rule OWASP-CRS-style regex
ruleset over path+headers" (config 2), a 1M-entry IP/ASN blocklist
(config 3), GeoIP predicate mixes (config 4), and a bot-score head
(config 5). The reference ships no rule corpus (its assets/pingoo.yml has
one demo rule), so this module synthesizes a deterministic CRS-flavored
corpus: attack-detection regexes (SQLi/XSS/LFI/RCE/scanner signatures,
including \\b word-boundary and >31-position multi-word patterns — the
corpus is NOT filtered to the device subset; whatever the compiler
cannot lower falls back to host interpretation, and benches report the
device-residency fraction), prefix/suffix/eq path hygiene rules, UA
rules, and list/geo predicates.

Everything is seeded and pure so benches are reproducible.
"""

from __future__ import annotations

import random

from ..config.schema import Action, ListConfig, ListType, RuleConfig
from ..engine.batch import RequestTuple
from ..expr import Ip, compile_expression

SQLI_CORES = [
    r"(?i)\bunion\s+select\b", r"(?i)select\s+.{0,10}from", r"(?i)insert\s+into",
    r"(?i)delete\s+from", r"(?i)drop\s+table", r"(?i)\bor\b\s+1=1",
    r"(?i)\band\b\s+1=1", r"(?i)sleep\(\d+\)", r"(?i)benchmark\(",
    r"(?i)waitfor\s+delay", r"(?i)group\s+by.{0,8}having", r"(?i)into\s+outfile",
    r"(?i)load_file\(", r"(?i)information_schema", r"'\s*--", r"(?i)xp_cmdshell",
    r"(?i)\bexec\b", r"(?i)\bcast\(", r"(?i)\bconcat\(",
]
XSS_CORES = [
    r"(?i)<script", r"(?i)javascript:", r"(?i)onerror\s*=", r"(?i)onload\s*=",
    r"(?i)<iframe", r"(?i)document\.cookie", r"(?i)alert\(", r"%3[Cc]script",
    r"(?i)<svg[^>]{0,20}onload", r"(?i)eval\(", r"(?i)expression\(",
    r"(?i)vbscript:", r"(?i)src\s*=\s*data:",
    # Real CRS signatures routinely exceed 31 NFA positions (multi-word
    # packing, compiler/nfa.py pack_span):
    r"(?i)<svg[^>]{0,40}on(load|error)\s{0,8}=",
    r"(?i)<(img|input|body)[^>]{0,40}on[a-z]{4,12}\s{0,4}=",
    r"(?i)String\.fromCharCode\([0-9, ]{0,40}\)",
]
LFI_RCE_CORES = [
    r"\.\./", r"\.\.%2[fF]", r"/etc/passwd", r"/etc/shadow", r"(?i)c:\\windows",
    r"(?i)cmd\.exe", r"(?i)/bin/(ba)?sh", r"%00", r"(?i)php://input",
    r"(?i)file://", r"(?i)expect://", r"(?i)proc/self/environ",
    r"(?i)wget\s+http", r"(?i)curl\s+http", r";\s*cat\s", r"\|\s*id\s*$",
    r"(?i)(\.\./){3,12}etc/(passwd|shadow|group)",  # deep traversal chains
    r"(?i)union[\s/\*]{1,20}(all[\s/\*]{1,20})?select",  # comment-evasion SQLi
]
SCANNER_UAS = [
    r"(?i)sqlmap", r"(?i)nikto", r"(?i)nessus", r"(?i)masscan", r"(?i)nmap",
    r"(?i)dirbuster", r"(?i)gobuster", r"(?i)wpscan", r"(?i)acunetix",
    r"(?i)zgrab", r"(?i)python-requests/1\.", r"(?i)go-http-client",
]
BAD_PREFIXES = [
    "/.env", "/.git", "/.svn", "/.hg", "/.aws", "/wp-admin", "/wp-login",
    "/phpmyadmin", "/pma", "/admin/config", "/cgi-bin", "/.well-known/../",
    "/vendor/phpunit", "/solr/admin", "/jenkins", "/manager/html",
    "/actuator", "/.DS_Store", "/server-status", "/debug/pprof",
]
BAD_SUFFIXES = [
    ".php.bak", ".sql", ".sqlite", ".pem", ".key", ".p12", ".bak", ".old",
    ".swp", "~", ".config", ".ini", ".log", ".tar.gz", ".zip.enc",
]
BAD_EXACT = [
    "/config.json", "/backup.zip", "/dump.sql", "/id_rsa", "/.htpasswd",
    "/web.config", "/composer.lock", "/package-lock.json.orig",
]


def generate_ruleset(
    num_rules: int = 500,
    seed: int = 20260728,
    with_lists: bool = True,
    list_sizes: tuple[int, int] = (4096, 512),
) -> tuple[list[RuleConfig], dict[str, list]]:
    """Deterministic CRS-style corpus of ~num_rules rules + lists."""
    rng = random.Random(seed)
    sources: list[tuple[str, str]] = []  # (name, expression)

    def add(name, src):
        sources.append((f"{name}_{len(sources):04d}", src))

    fields = ["http_request.url", "http_request.path"]
    regex_cores = (
        [("sqli", c) for c in SQLI_CORES]
        + [("xss", c) for c in XSS_CORES]
        + [("lfi", c) for c in LFI_RCE_CORES]
    )
    # Expand cores with suffix/prefix variations to reach scale, CRS-style
    # (many rules per attack class, each a distinct signature).
    variations = ["", r"\s*\(", r"\s*=", r"[%+]", r"\d", r"['\"]", r"/",
                  r"\s+[a-z]+", r"[a-z]{0,4}\("]
    target_regex = int(num_rules * 0.55)
    i = 0
    while sum(1 for n, _ in sources if not n.startswith("ua_")) < target_regex:
        klass, core = regex_cores[i % len(regex_cores)]
        var = variations[(i // len(regex_cores)) % len(variations)]
        field = fields[i % 2]
        pattern = core + var if (i // len(regex_cores)) else core
        i += 1
        add(klass, f'{field}.matches("{_escape(pattern)}")')

    for ua in SCANNER_UAS:
        add("ua", f'http_request.user_agent.matches("{_escape(ua)}")')

    for p in BAD_PREFIXES:
        add("prefix", f'http_request.path.starts_with("{p}")')
    for s in BAD_SUFFIXES:
        add("suffix", f'http_request.path.ends_with("{s}")')
    for e in BAD_EXACT:
        add("exact", f'http_request.path == "{e}"')

    # contains() keyword rules
    for kw in ["passwd", "boot.ini", "win.ini", "/../..", "base64,",
               "<?php", "${jndi:", "{{7*7}}", "__proto__", "ognl."]:
        add("kw", f'http_request.url.contains("{kw}")')

    # numeric / metadata rules (geo + asn + shape, BASELINE config 4)
    add("geo", 'client.country == "KP"')
    add("geo", '(client.country == "RU" || client.country == "IR") && '
               'http_request.path.starts_with("/admin")')
    add("shape", "http_request.path.length() > 200")
    add("shape", "http_request.user_agent.length() == 0")
    add("shape", "client.remote_port < 1024 && client.remote_port != 80 && "
                 "client.remote_port != 443")

    lists: dict[str, list] = {}
    if with_lists:
        n_ips, n_asns = list_sizes
        lists["blocked_ips"] = _random_ip_list(rng, n_ips)
        lists["blocked_asns"] = sorted(rng.sample(range(1000, 400000), n_asns))
        add("list", 'lists["blocked_ips"].contains(client.ip)')
        add("list", 'lists["blocked_asns"].contains(client.asn)')

    # Top up to num_rules with generated literal-keyword rules.
    sig = 0
    while len(sources) < num_rules:
        token = "".join(rng.choice("abcdefghijklmnopqrstuvwxyz_")
                        for _ in range(rng.randint(5, 10)))
        which = sig % 3
        if which == 0:
            add("gen", f'http_request.url.contains("{token}")')
        elif which == 1:
            add("gen", f'http_request.path.starts_with("/{token}")')
        else:
            add("gen", f'http_request.url.matches("(?i){token}[0-9a-f]*")')
        sig += 1
    sources = sources[:num_rules]

    rules = [
        RuleConfig(name=name, expression=compile_expression(src),
                   actions=(Action.BLOCK,))
        for name, src in sources
    ]
    return rules, lists


def _escape(pattern: str) -> str:
    return pattern.replace("\\", "\\\\").replace('"', '\\"')


def _random_ip_list(rng: random.Random, n: int) -> list[Ip]:
    out = []
    for _ in range(n - n // 16):
        out.append(Ip(f"{rng.randrange(1, 224)}.{rng.randrange(256)}."
                      f"{rng.randrange(256)}.{rng.randrange(256)}"))
    for _ in range(n // 16):
        out.append(Ip(f"{rng.randrange(1, 224)}.{rng.randrange(256)}."
                      f"{rng.randrange(256)}.0/24"))
    return out


CLEAN_PATHS = [
    "/", "/index.html", "/about", "/products/widget-2000", "/api/v1/users",
    "/api/v1/orders/12345", "/static/app.9f3c2.js", "/static/style.css",
    "/images/logo.png", "/blog/2026/07/scaling-wafs", "/search", "/health",
    "/favicon.ico", "/robots.txt", "/docs/getting-started", "/cart",
]
CLEAN_QUERIES = ["", "?page=2", "?q=blue+widget", "?utm_source=news",
                 "?id=12345", "?sort=price&dir=asc", "?lang=en"]
ATTACK_URLS = [
    "/search?q=1%27%20UNION%20SELECT%20password%20FROM%20users",
    "/search?q=1' UNION SELECT pass --",
    "/item?id=1 OR 1=1",
    "/page?x=<script>alert(1)</script>",
    "/page?x=%3Cscript%3Ealert(1)%3C/script%3E",
    "/download?file=../../../../etc/passwd",
    "/download?file=..%2f..%2fetc%2fshadow",
    "/exec?cmd=;cat /etc/passwd",
    "/api?payload=${jndi:ldap://evil}",
    "/upload.php?x=php://input",
    "/?b=eval(atob('x'))",
    "/admin/config.php",
]
ATTACK_PATHS = ["/.env", "/.git/config", "/wp-login.php", "/phpmyadmin/",
                "/vendor/phpunit/x", "/backup.zip", "/dump.sql", "/id_rsa",
                "/cgi-bin/test.cgi", "/actuator/env"]
NORMAL_UAS = [
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 10_15_7) Safari/605.1.15",
    "Mozilla/5.0 (X11; Linux x86_64; rv:126.0) Gecko/20100101 Firefox/126.0",
    "Mozilla/5.0 (iPhone; CPU iPhone OS 17_5 like Mac OS X) Mobile/15E148",
]
BOT_UAS = ["sqlmap/1.8", "Nikto/2.5.0", "masscan/1.3", "python-requests/1.9",
           "gobuster/3.6", ""]


def generate_traffic(
    n: int,
    attack_fraction: float = 0.05,
    seed: int = 7,
    lists: dict | None = None,
) -> list[RequestTuple]:
    """Replayed-log-style traffic: mostly clean, a slice of attacks —
    the shape the reference's pong-replay setup would produce
    (BASELINE.md config 1)."""
    rng = random.Random(seed)
    out = []
    blocked_ips = (lists or {}).get("blocked_ips") or []
    for _ in range(n):
        attack = rng.random() < attack_fraction
        if attack:
            kind = rng.random()
            if kind < 0.5:
                url = rng.choice(ATTACK_URLS)
                path = url.split("?")[0]
                ua = rng.choice(NORMAL_UAS)
            elif kind < 0.8:
                path = rng.choice(ATTACK_PATHS)
                url = path
                ua = rng.choice(NORMAL_UAS)
            else:
                path = rng.choice(CLEAN_PATHS)
                url = path
                ua = rng.choice(BOT_UAS)
            ip = (str(rng.choice(blocked_ips)) if blocked_ips and
                  rng.random() < 0.1 else _rand_ip(rng))
            if "/" in ip:
                ip = ip.split("/")[0]
        else:
            path = rng.choice(CLEAN_PATHS)
            url = path + rng.choice(CLEAN_QUERIES)
            ua = rng.choice(NORMAL_UAS)
            ip = _rand_ip(rng)
        out.append(
            RequestTuple(
                host="www.example.com",
                url=url,
                path=path,
                method=rng.choice(["GET"] * 8 + ["POST", "HEAD"]),
                user_agent=ua,
                ip=ip,
                remote_port=rng.randrange(1024, 65536),
                asn=rng.choice([13335, 15169, 7922, 3320, 9009, 64500]),
                country=rng.choice(["US", "DE", "FR", "JP", "BR", "RU", "KP"]),
            )
        )
    return out


def _rand_ip(rng: random.Random) -> str:
    return (f"{rng.randrange(1, 224)}.{rng.randrange(256)}."
            f"{rng.randrange(256)}.{rng.randrange(1, 255)}")
