#!/usr/bin/env python
"""Streaming body-inspection smoke (make body-smoke; ISSUE 13).

Proves, offline and in well under a minute, that streaming request
bodies through the ring (docs/BODY_STREAMING.md) is a framing change
and never a semantic one:

  * scanner parity: for a deterministic mini-corpus covering every
    DEFAULT_BODY_RULES literal, streaming the payload as windows with
    the seam INSIDE the literal — including one straddling the
    4096-byte ring-window flush — yields verdicts bit-identical to
    the contiguous one-shot scan AND the interpreter oracle;
  * degrade lane: a window-sequence gap degrades that flow to
    metadata-only (degraded FINAL verdict, action 0) instead of
    wedging the flow table;
  * native plane (skips with a warning when the toolchain is
    unavailable): the real httpd under PINGOO_BODY_INSPECT=on blocks
    a torn-literal POST (TCP segment boundaries inside the literal),
    allows its benign twin, exports nonzero pingoo_body_* telemetry
    at /__pingoo/metrics — and with the gate OFF the same malicious
    body is allowed, bit-exact status quo.

Offline-safe like mesh-smoke: when jax is unavailable the smoke SKIPS
WITH A WARNING (exit 0) instead of failing the gate.
"""

import json
import os
import socket
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def scanner_parity() -> None:
    from pingoo_tpu.engine import bodyscan

    plan = bodyscan.compile_body_plan()
    scanner = bodyscan.BodyScanner(plan)
    window = bodyscan.body_window_bytes()
    flow = 1
    for rule in bodyscan.DEFAULT_BODY_RULES:
        lit = rule.pattern.encode()
        for pre_n in (0, 17, window - len(lit) // 2):
            payload = (b"k=v&q=" + b"z" * pre_n + lit
                       + b"&tail=" + b"y" * 23)
            cut = len(b"k=v&q=") + pre_n + len(lit) // 2  # mid-literal
            pieces = [payload[:cut], payload[cut:]]
            windows, seq = [], 0
            for piece in pieces:
                for part in bodyscan.split_payload(piece, window):
                    windows.append(bodyscan.BodyWindow(
                        flow_id=flow, win_seq=seq, data=part))
                    seq += 1
            windows[-1].final = True
            streamed = [v for v in scanner.scan_windows(windows)
                        if v.flow_id == flow]
            contig = scanner.scan_buffered(payload)
            unv, vb, _names = bodyscan.body_lanes_oracle(plan, payload)
            ok = (len(streamed) == 1 and not streamed[0].degraded
                  and streamed[0].unverified == contig.unverified == unv
                  and streamed[0].verified_block
                  == contig.verified_block == vb)
            check(ok, f"stream==contig==oracle {rule.name} pre={pre_n}")
            flow += 1
    check(scanner.flows_active == 0, "all smoke flows finished")


def degrade_lane() -> None:
    from pingoo_tpu.engine import bodyscan

    scanner = bodyscan.BodyScanner()
    flow = 9001
    first = bodyscan.BodyWindow(flow_id=flow, win_seq=0, data=b"abc")
    # win_seq jumps 0 -> 2: the carry is broken, the flow must fail
    # open (degraded FINAL, action 0), never block or wedge.
    gap = bodyscan.BodyWindow(flow_id=flow, win_seq=2,
                              data=b"union select", final=True)
    out = [v for v in scanner.scan_windows([first, gap])
           if v.flow_id == flow]
    check(len(out) == 1 and out[0].degraded and out[0].action_byte() == 0,
          "win_seq gap degrades to metadata-only (action 0)")
    check(scanner.flows_active == 0, "degraded flow evicted")


def _metrics_json(port: int) -> dict:
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    try:
        s.sendall(b"GET /__pingoo/metrics HTTP/1.1\r\n"
                  b"host: smoke\r\nuser-agent: body-smoke\r\n"
                  b"accept: application/json\r\n"
                  b"connection: close\r\n\r\n")
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    return json.loads(data.split(b"\r\n\r\n", 1)[1])


def _post(cls, body_literal: bool, chunked: bool, splits_in_literal: bool):
    """Build one POST Mutant for the fuzz harness."""
    from tools.analyze.fuzz import Mutant

    lit = b"union select" if body_literal else b"unionselect"
    body = b"q=1&msg=" + lit + b"&tail=9"
    if chunked:
        cut = len(b"q=1&msg=") + len(lit) // 2
        payload = b""
        for c in (body[:cut], body[cut:]):
            payload += b"%x\r\n" % len(c) + c + b"\r\n"
        payload += b"0\r\n\r\n"
        head = (b"POST /search HTTP/1.1\r\nhost: smoke.test\r\n"
                b"user-agent: body-smoke\r\n"
                b"transfer-encoding: chunked\r\n"
                b"connection: close\r\n\r\n")
        return Mutant(cls, head + payload)
    head = (b"POST /search HTTP/1.1\r\nhost: smoke.test\r\n"
            b"user-agent: body-smoke\r\n"
            b"content-length: %d\r\nconnection: close\r\n\r\n" % len(body))
    raw = head + body
    splits = ()
    if splits_in_literal:
        at = len(head) + len(b"q=1&msg=")
        splits = (at + 3, at + 8)
    return Mutant(cls, raw, splits=splits)


def native_plane() -> None:
    import tempfile

    from pingoo_tpu import native_ring
    from tools.analyze import fuzz

    if not native_ring.ensure_built():
        print("  skip native plane: toolchain unavailable")
        return
    plan = fuzz._fuzz_plan()

    tmp = tempfile.mkdtemp(prefix="pingoo_body_smoke_on_")
    h = fuzz.NativeHarness(plan, tmp, body_inspect=True)
    try:
        cls, _ = h.roundtrip(_post("benign", False, False, False))
        check(cls == "allow", f"gate on: benign body allowed ({cls})")
        cls, _ = h.roundtrip(_post("torn", True, False, True))
        check(cls == "block",
              f"gate on: literal torn across TCP segments blocked ({cls})")
        cls, _ = h.roundtrip(_post("seam", True, True, False))
        check(cls == "block",
              f"gate on: literal across chunk seam blocked ({cls})")
        m = _metrics_json(h.port)
        body = m.get("body", {})
        check(body.get("windows", 0) > 0 and body.get("flows", 0) > 0,
              f"gate on: pingoo_body_* telemetry nonzero ({body})")
        check(body.get("fail_open", 0) == 0,
              f"gate on: no fail-opens in clean run ({body})")
    finally:
        h.close()

    tmp = tempfile.mkdtemp(prefix="pingoo_body_smoke_off_")
    h = fuzz.NativeHarness(plan, tmp, body_inspect=False)
    try:
        cls, _ = h.roundtrip(_post("off-status-quo", True, False, True))
        check(cls == "allow",
              f"gate off: same malicious body rides status quo ({cls})")
        body = _metrics_json(h.port).get("body", {})
        check(body.get("windows", -1) == 0 and body.get("flows", -1) == 0,
              f"gate off: zero body windows/flows ({body})")
    finally:
        h.close()


def main() -> int:
    try:
        import jax  # noqa: F401
    except Exception as exc:
        print(f"body smoke SKIPPED: jax unavailable ({exc!r})")
        return 0
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print("-- scanner parity (stream == contiguous == oracle) --")
    scanner_parity()
    print("-- degrade lane --")
    degrade_lane()
    print("-- native plane (PINGOO_BODY_INSPECT on/off) --")
    native_plane()
    if FAILURES:
        print(f"body smoke: {len(FAILURES)} FAILURE(S)")
        return 1
    print("body smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
