#!/usr/bin/env python
"""Metrics-schema parity audit (make audit; ISSUE 2 satellite).

Fast, no-accelerator checks that the three telemetry surfaces agree on
the documented inventory (pingoo_tpu/obs/schema.py):

  1. The native plane's C++ exposition (native/httpd.cc) emits every
     shared/native/ring metric name and keeps the legacy JSON keys —
     checked against the SOURCE (the exposition is string literals, so
     a renamed or dropped metric is visible without booting the plane).
  2. The Python listener (host/httpd.py) and sidecar (native_ring.py)
     reference the same names through obs/schema.py.
  3. A synthetic registry populated with the full inventory passes the
     Prometheus exposition lint (obs/registry.lint_prometheus_text).
  4. docs/OBSERVABILITY.md documents every inventory name.

Exit 0 clean, 1 with a problem list on stderr. The live-boot version of
this check is `make metrics-smoke` (tools/metrics_smoke.py).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from pingoo_tpu.obs import schema  # noqa: E402
from pingoo_tpu.obs.registry import (  # noqa: E402
    MetricRegistry,
    WAIT_BUCKETS_MS,
    lint_prometheus_text,
)


def _read(rel):
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def main() -> int:
    problems = []

    native_src = _read("pingoo_tpu/native/httpd.cc")
    native_names = (set(schema.SHARED_METRICS) | set(schema.RING_METRICS)
                    | set(schema.NATIVE_METRICS)
                    | {schema.SHARED_WAIT_HISTOGRAM})
    for name in sorted(native_names):
        if f'"{name}' not in native_src and name not in native_src:
            problems.append(f"native/httpd.cc: missing metric {name}")
    for key in schema.NATIVE_JSON_KEYS:
        if f'"{key}"' not in native_src:
            problems.append(
                f"native/httpd.cc: missing legacy JSON key {key!r}")

    py_listener = _read("pingoo_tpu/host/httpd.py")
    for name in schema.SHARED_METRICS:
        if name not in py_listener:
            problems.append(f"host/httpd.py: missing metric {name}")
    for key in schema.PYTHON_JSON_KEYS:
        if f'"{key}"' not in py_listener:
            problems.append(
                f"host/httpd.py: missing legacy JSON key {key!r}")

    sidecar_src = _read("pingoo_tpu/native_ring.py")
    for name in schema.RING_METRICS:
        if name not in sidecar_src:
            problems.append(f"native_ring.py: missing metric {name}")

    service_src = _read("pingoo_tpu/engine/service.py")
    if schema.SHARED_WAIT_HISTOGRAM not in service_src:
        problems.append("engine/service.py: missing shared wait histogram")
    for stage in schema.VERDICT_STAGES:
        if f'"{stage}"' not in service_src:
            problems.append(
                f"engine/service.py: stage {stage!r} not instrumented")

    # Prefilter cascade metrics: both engine planes (the Python listener
    # service and the ring sidecar backing the native plane) must export
    # the documented names.
    for name in schema.PREFILTER_METRICS:
        if name not in service_src:
            problems.append(f"engine/service.py: missing metric {name}")
        if name not in sidecar_src:
            problems.append(f"native_ring.py: missing metric {name}")

    # Bitsplit-DFA dispatch metrics (ISSUE 8): like the prefilter
    # family, both engine planes must export the documented names (the
    # counts themselves are host-static, engine/verdict
    # dfa_dispatch_counts).
    for name in schema.DFA_METRICS:
        if name not in service_src:
            problems.append(f"engine/service.py: missing metric {name}")
        if name not in sidecar_src:
            problems.append(f"native_ring.py: missing metric {name}")

    # Streaming body inspection (ISSUE 13, docs/BODY_STREAMING.md): the
    # scanner-side metric-name literals live in engine/bodyscan.py
    # (attach_metrics, shared by both scanning planes); the native
    # plane exports the producer-side subset as C++ string literals
    # (the carry-depth histogram is scanner-only); both consuming
    # planes must wire a BodyScanner — the sidecar drains ring body
    # slots, the Python listener scans its buffered bodies through
    # scan_buffered.
    body_src = _read("pingoo_tpu/engine/bodyscan.py")
    for name in schema.BODY_METRICS:
        if name not in body_src:
            problems.append(f"engine/bodyscan.py: missing metric {name}")
    for name in ("pingoo_body_windows_total", "pingoo_body_bytes_total",
                 "pingoo_body_flows_active", "pingoo_body_degrade_total"):
        if name not in native_src:
            problems.append(f"native/httpd.cc: missing metric {name}")
    for plane_src, label in ((py_listener, "host/httpd.py"),
                             (sidecar_src, "native_ring.py")):
        if "BodyScanner" not in plane_src:
            problems.append(f"{label}: body wiring missing BodyScanner")
    if "scan_buffered" not in py_listener:
        problems.append("host/httpd.py: body wiring missing scan_buffered")
    if "PINGOO_BODY_INSPECT" not in native_src:
        problems.append("native/httpd.cc: missing PINGOO_BODY_INSPECT gate")

    # Verdict provenance (ISSUE 5): the metric-name literals live in
    # obs/provenance.py + obs/flightrecorder.py (shared by both engine
    # planes), so check those sources for the names and both plane
    # sources for the wiring symbols.
    prov_src = (_read("pingoo_tpu/obs/provenance.py")
                + _read("pingoo_tpu/obs/flightrecorder.py"))
    for name in {**schema.PROVENANCE_METRICS, **schema.PARITY_METRICS}:
        if name not in prov_src:
            problems.append(f"obs provenance layer: missing metric {name}")
    for symbol in ("RuleAttribution", "ParityAuditor", "FlightRecorder"):
        if symbol not in service_src:
            problems.append(f"engine/service.py: provenance wiring "
                            f"missing {symbol}")
        if symbol not in sidecar_src:
            problems.append(f"native_ring.py: provenance wiring "
                            f"missing {symbol}")

    # Continuous-batching scheduler + serving mesh (ISSUE 6): the
    # metric-name literals live in sched/scheduler.py (shared by both
    # engine planes; the mesh gauge is set through the same
    # SchedMetrics bundle), and both planes must wire the Scheduler —
    # the Python listener service and the ring sidecar each construct
    # one, which is what makes the pingoo_sched_* series exist under
    # both plane labels.
    sched_src = _read("pingoo_tpu/sched/scheduler.py")
    for name in schema.SCHED_METRICS:
        if name not in sched_src:
            problems.append(f"sched/scheduler.py: missing metric {name}")
    for plane_src, label in ((service_src, "engine/service.py"),
                             (sidecar_src, "native_ring.py")):
        for symbol in ("Scheduler", "SchedulerConfig", "MeshExecutor"):
            if symbol not in plane_src:
                problems.append(
                    f"{label}: scheduler wiring missing {symbol}")

    # Compact staging (ISSUE 15): both engine planes must export the
    # staged-bytes counter and the per-field cap gauge — the counter is
    # what makes the full-vs-compact byte savings visible per plane,
    # and the gauge publishes the adopted plan's staging widths.
    for name in schema.STAGING_METRICS:
        if name not in service_src:
            problems.append(f"engine/service.py: missing metric {name}")
        if name not in sidecar_src:
            problems.append(f"native_ring.py: missing metric {name}")

    # Pipelined-executor telemetry (ISSUE 9): the metric-name literals
    # live in obs/pipeline.py (shared by both engine planes), and both
    # planes must construct a PipelineStats — that is what makes the
    # pingoo_pipeline_* series exist under both plane labels.
    pipe_src = _read("pingoo_tpu/obs/pipeline.py")
    for name in schema.PIPELINE_METRICS:
        if name not in pipe_src:
            problems.append(f"obs/pipeline.py: missing metric {name}")
    for plane_src, label in ((service_src, "engine/service.py"),
                             (sidecar_src, "native_ring.py")):
        if "PipelineStats" not in plane_src:
            problems.append(
                f"{label}: pipeline wiring missing PipelineStats")

    # Sidecar supervision (ISSUE 10, docs/RESILIENCE.md): the liveness
    # gauges/counter are C++ string literals in the native exposition;
    # the reattach/epoch names live in the sidecar, the ladder counter
    # in engine/ladder.py, the chaos counter in obs/chaos.py. Both
    # engine planes must wire a DegradationLadder — that is what makes
    # the pingoo_degrade_total series exist under both plane labels —
    # and the native plane must carry the liveness detector itself.
    for name in ("pingoo_sidecar_up", "pingoo_degraded_mode",
                 "pingoo_sidecar_epoch", "pingoo_degraded_entered_total"):
        if name not in native_src:
            problems.append(f"native/httpd.cc: missing metric {name}")
    if "check_sidecar_liveness" not in native_src:
        problems.append(
            "native/httpd.cc: liveness detector check_sidecar_liveness "
            "missing")
    for name in ("pingoo_reattach_reconciled_total",
                 "pingoo_sidecar_epoch"):
        if name not in sidecar_src:
            problems.append(f"native_ring.py: missing metric {name}")
    ladder_src = _read("pingoo_tpu/engine/ladder.py")
    if "pingoo_degrade_total" not in ladder_src:
        problems.append(
            "engine/ladder.py: missing metric pingoo_degrade_total")
    chaos_src = _read("pingoo_tpu/obs/chaos.py")
    if "pingoo_chaos_injected_total" not in chaos_src:
        problems.append(
            "obs/chaos.py: missing metric pingoo_chaos_injected_total")
    for plane_src, label in ((service_src, "engine/service.py"),
                             (sidecar_src, "native_ring.py")):
        if "DegradationLadder" not in plane_src:
            problems.append(
                f"{label}: ladder wiring missing DegradationLadder")
    if "ChaosInjector" not in sidecar_src:
        problems.append(
            "native_ring.py: chaos wiring missing ChaosInjector")

    # Perf ledger + timeline (ISSUE 17): the compile/timeline metric
    # literals live in obs/perf.py + obs/timeline.py, the cost-ledger
    # reload counter in sched/scheduler.py; both engine planes must
    # wire the instrumentation (instrument_jit for compile tracking,
    # get_timeline for span emission, load_cost_ledger for the durable
    # cost reload) — that is what makes the series exist under both
    # plane labels.
    perf_src = (_read("pingoo_tpu/obs/perf.py")
                + _read("pingoo_tpu/obs/timeline.py"))
    for name in ("pingoo_compile_total", "pingoo_compile_ms",
                 "pingoo_timeline_spans_total"):
        if name not in perf_src:
            problems.append(f"obs perf layer: missing metric {name}")
    if "pingoo_costmodel_reload_total" not in sched_src:
        problems.append("sched/scheduler.py: missing metric "
                        "pingoo_costmodel_reload_total")
    for plane_src, label in ((service_src, "engine/service.py"),
                             (sidecar_src, "native_ring.py")):
        for symbol in ("instrument_jit", "get_timeline",
                       "load_cost_ledger", "save_cost_ledger"):
            if symbol not in plane_src:
                problems.append(
                    f"{label}: perf wiring missing {symbol}")

    # Flight-recorder + explain endpoints: the Python listener serves
    # both; the native plane serves its own flightrecorder dump (the
    # C++ exposition is string literals, so the source is the schema).
    for endpoint in ("/__pingoo/flightrecorder", "/__pingoo/explain",
                     "/__pingoo/compileledger", "/__pingoo/timeline"):
        if endpoint not in py_listener:
            problems.append(f"host/httpd.py: missing endpoint {endpoint}")
    for endpoint in ("/__pingoo/flightrecorder", "/__pingoo/timeline"):
        if endpoint not in native_src:
            problems.append(
                f"native/httpd.cc: missing endpoint {endpoint}")

    docs = _read("docs/OBSERVABILITY.md") if os.path.exists(
        os.path.join(REPO, "docs/OBSERVABILITY.md")) else ""
    if not docs:
        problems.append("docs/OBSERVABILITY.md missing")
    else:
        for name in sorted(schema.all_metric_names()):
            if name not in docs:
                problems.append(f"docs/OBSERVABILITY.md: undocumented {name}")

    # Synthetic full-inventory registry must pass the exposition lint.
    reg = MetricRegistry()
    for name, help_text in {**schema.SHARED_METRICS,
                            **schema.RING_METRICS,
                            **schema.PREFILTER_METRICS,
                            **schema.DFA_METRICS,
                            **schema.PROVENANCE_METRICS,
                            **schema.PARITY_METRICS,
                            **schema.SCHED_METRICS,
                            **schema.PIPELINE_METRICS,
                            **schema.RESILIENCE_METRICS,
                            **schema.BODY_METRICS,
                            **schema.STAGING_METRICS,
                            **schema.PERF_METRICS}.items():
        if name == "pingoo_compile_ms":
            from pingoo_tpu.obs.perf import COMPILE_BUCKETS_MS

            hb = reg.histogram(name, help_text,
                               buckets=COMPILE_BUCKETS_MS,
                               labels={"plane": "audit", "fn": "verdict"})
            for v in (0.5, 120, 9500):
                hb.observe(v)
        elif name == "pingoo_body_carry_depth":
            hb = reg.histogram(name, help_text,
                               buckets=(1, 2, 4, 8, 16, 64, 256),
                               labels={"plane": "audit"})
            for v in (1, 3, 500):
                hb.observe(v)
        elif name == "pingoo_sched_batch_size":
            # The one histogram in the sched family: lint it with its
            # real pow2 bucket ladder.
            from pingoo_tpu.sched import BATCH_SIZE_BUCKETS

            hb = reg.histogram(name, help_text,
                               buckets=BATCH_SIZE_BUCKETS,
                               labels={"plane": "audit"})
            for v in (1, 64, 2048, 100000):
                hb.observe(v)
        elif name.endswith("_total"):
            reg.counter(name, help_text, labels={"plane": "audit"}).inc()
        else:
            reg.gauge(name, help_text, labels={"plane": "audit"}).set(1)
    # The rule/bank-labelled provenance families must lint with their
    # real label shapes too (a rule name can carry exposition-hostile
    # characters; the formatter escapes them).
    reg.counter("pingoo_rule_hits_total", "", labels={
        "plane": "audit", "rule": 'r"quoted\\rule'}).inc()
    reg.gauge("pingoo_prefilter_bank_candidate_rate", "", labels={
        "plane": "audit", "bank": "nfa_url@short"}).set(0.5)
    reg.counter("pingoo_dfa_banks_total", "", labels={
        "plane": "audit", "mode": "auto"}).inc()
    reg.gauge("pingoo_pipeline_stage_occupancy", "", labels={
        "plane": "audit", "stage": "encode"}).set(0.5)
    reg.counter("pingoo_pipeline_batches_total", "", labels={
        "plane": "audit", "mode": "on"}).inc()
    reg.counter("pingoo_reattach_reconciled_total", "", labels={
        "plane": "audit", "action": "reeval"}).inc()
    reg.counter("pingoo_degrade_total", "", labels={
        "plane": "audit", "rung": "device"}).inc()
    reg.counter("pingoo_chaos_injected_total", "", labels={
        "plane": "audit", "fault": "verdict_full"}).inc()
    reg.counter("pingoo_body_degrade_total", "", labels={
        "plane": "audit", "reason": "ring_full"}).inc()
    reg.counter("pingoo_staged_bytes_total", "", labels={
        "plane": "audit", "mode": "compact"}).inc()
    reg.gauge("pingoo_staging_field_cap", "", labels={
        "field": "url"}).set(256)
    reg.counter("pingoo_compile_total", "", labels={
        "plane": "audit", "fn": "verdict", "kind": "cold"}).inc()
    reg.counter("pingoo_timeline_spans_total", "", labels={
        "plane": "audit"}).inc()
    reg.counter("pingoo_costmodel_reload_total", "", labels={
        "plane": "audit", "result": "stale"}).inc()
    h = reg.histogram(schema.SHARED_WAIT_HISTOGRAM, "wait",
                      buckets=WAIT_BUCKETS_MS, labels={"plane": "audit"})
    for v in (0.5, 3, 70, 2000):
        h.observe(v)
    problems += [f"lint: {p}" for p in
                 lint_prometheus_text(reg.prometheus_text())]

    if problems:
        print("metrics schema audit FAILED:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(f"metrics schema audit OK "
          f"({len(schema.all_metric_names())} inventory names)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
