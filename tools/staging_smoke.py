#!/usr/bin/env python
"""Compact-staging smoke (make staging-smoke; ISSUE 15).

Proves, offline and in ~a minute, that compact staging (docs/EXECUTOR.md
"Compact staging") is a transport change and never a semantic one — on
BOTH planes:

  * python plane: VerdictService verdicts under PINGOO_STAGING=compact
    are bit-identical to PINGOO_STAGING=full (the per-field oracle),
    with the ParityAuditor sampling the compact path and finding it
    clean, and the compact arm staging FEWER bytes per request than
    full on a long-URL stream;
  * sidecar plane: RingSidecar over a real shm ring, the same
    full-vs-compact bit-identity (this half skips with a warning when
    the native toolchain is unavailable);
  * the `pingoo_staged_bytes_total` / `pingoo_staging_field_cap`
    series export through the shared registry and the exposition
    passes the Prometheus lint.

Offline-safe like megastep-smoke: when jax is unavailable the smoke
SKIPS WITH A WARNING (exit 0) instead of failing the gate. The work
happens in a re-exec'd child under a controlled environment so a parent
shell pinning PINGOO_STAGING cannot skew the A/B.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []

N_PY = 72       # python-plane requests
N_RING = 96     # sidecar-plane requests
MAX_BATCH = 16


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def parent() -> int:
    try:
        import jax  # noqa: F401
    except Exception as exc:
        print(f"staging smoke SKIPPED: jax unavailable ({exc!r})")
        return 0
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for k in ("PINGOO_STAGING", "PINGOO_STAGING_DEPTH", "PINGOO_PIPELINE",
              "PINGOO_PIPELINE_DEPTH", "PINGOO_MEGASTEP",
              "PINGOO_MEGASTEP_K", "PINGOO_MESH", "PINGOO_DFA",
              "PINGOO_DEADLINE_MS", "PINGOO_SCHED_MODE",
              "PINGOO_SCHED_FAILOPEN", "PINGOO_CHAOS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, cwd=REPO, timeout=900)
    return proc.returncode


def _staged_bytes(svc, mode):
    return float(svc.stats.staged_bytes_counter[mode]._value)


def _python_plane() -> dict:
    """VerdictService full-vs-compact bit-identity + auditor + byte
    savings on a long-URL-tail stream."""
    import asyncio
    import dataclasses
    import random

    import numpy as np

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine.service import VerdictService
    from test_parity import LISTS, RULE_SOURCES, make_rules, \
        random_requests

    reqs = random_requests(random.Random(1501), N_PY)
    # A long-URL tail: the rows that make full mode's content
    # bucketing balloon while compact stays at the clamped cap.
    for i in range(0, N_PY, 24):
        reqs[i] = dataclasses.replace(
            reqs[i], url="/deep?q=" + "x" * 1500,
            path="/deep/" + "y" * 1500)

    def serve(mode):
        os.environ["PINGOO_STAGING"] = mode
        os.environ["PINGOO_STAGING_DEPTH"] = "256"
        os.environ["PINGOO_PIPELINE"] = "on"
        os.environ["PINGOO_PARITY_SAMPLE"] = "1"
        os.environ["PINGOO_PROVENANCE"] = "1"
        try:
            plan = compile_ruleset(make_rules(RULE_SOURCES), LISTS)
            svc = VerdictService(plan, LISTS, use_device=True,
                                 max_batch=32)

            async def flow():
                await svc.start()
                try:
                    return await asyncio.gather(
                        *[svc.evaluate(r) for r in reqs])
                finally:
                    await svc.stop()

            verdicts = asyncio.run(flow())
            parity = svc.parity
            if parity is not None:
                parity.flush(30)
            return svc, verdicts
        finally:
            for k in ("PINGOO_STAGING", "PINGOO_STAGING_DEPTH",
                      "PINGOO_PIPELINE", "PINGOO_PARITY_SAMPLE",
                      "PINGOO_PROVENANCE"):
                del os.environ[k]

    svc_f, want = serve("full")
    full_bytes = _staged_bytes(svc_f, "full")
    svc_c, got = serve("compact")
    compact_bytes = _staged_bytes(svc_c, "compact")
    identical = all(
        w.action == g.action and w.verified_block == g.verified_block
        and np.array_equal(w.matched, g.matched)
        for w, g in zip(want, got))
    check(identical,
          "python-plane verdicts bit-identical (compact vs full oracle)")
    check(full_bytes > 0 and compact_bytes > 0,
          f"both modes accounted staged bytes "
          f"(full={full_bytes:.0f} compact={compact_bytes:.0f})")
    check(compact_bytes < full_bytes,
          f"compact staged FEWER bytes ({compact_bytes:.0f} < "
          f"{full_bytes:.0f})")
    parity = svc_c.parity
    if parity is not None:
        check(parity.checked_total.value > 0,
              "auditor sampled the compact path")
        check(parity.mismatch_total.value == 0,
              "auditor found the compact path clean")
    return {"python_full_bytes": full_bytes,
            "python_compact_bytes": compact_bytes}


def _sidecar_plane() -> dict:
    """RingSidecar full-vs-compact bit-identity over a real shm ring."""
    import tempfile
    import threading

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression
    from pingoo_tpu.native_ring import Ring, RingSidecar

    rules = [
        RuleConfig(name="blk", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.path.starts_with("/evil")')),
        RuleConfig(name="ua", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.user_agent.contains("stagebot")')),
    ]
    plan = compile_ruleset(rules, {})

    def fields(i):
        if i % 11 == 0:  # long-URL tail rows
            path = (b"/fine/%d/" % i) + b"q" * 1500
        else:
            path = (f"/evil/{i}" if i % 3 == 0
                    else f"/fine/{i}").encode()
        return {"method": b"GET", "host": b"stage.test", "path": path,
                "url": path,
                "user_agent": b"stagebot" if i % 7 == 0 else b"ua",
                "ip": b"\x00" * 15 + bytes([i % 251 + 1])}

    def drive(tmp, mode):
        os.environ["PINGOO_STAGING"] = mode
        os.environ["PINGOO_STAGING_DEPTH"] = "256"
        try:
            ring = Ring(os.path.join(tmp, f"ring_{mode}"),
                        capacity=256, create=True)
            sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
        finally:
            del os.environ["PINGOO_STAGING"]
            del os.environ["PINGOO_STAGING_DEPTH"]
        enq = {}
        for i in range(N_RING):
            enq[ring.enqueue(**fields(i))] = i
        worker = threading.Thread(
            target=sidecar.run, kwargs={"max_requests": N_RING},
            daemon=True)
        worker.start()
        got: dict = {}
        deadline = time.time() + 240
        while time.time() < deadline and len(got) < N_RING:
            v = ring.poll_verdict()
            if v is None:
                time.sleep(0.001)
                continue
            got.setdefault(v[0], []).append(v[1])
        sidecar.stop()
        worker.join(timeout=30)
        staged = float(sidecar._staged_bytes_counter[mode]._value)
        ring.close()
        check(len(got) == N_RING
              and all(len(v) == 1 for v in got.values()),
              f"{mode}: all verdicts exactly once ({len(got)}/{N_RING})")
        return {enq[t]: v[0] & 3 for t, v in got.items()}, staged

    with tempfile.TemporaryDirectory() as tmp:
        full, fb = drive(tmp, "full")
        compact, cb = drive(tmp, "compact")
    check(full == compact,
          "sidecar-plane verdicts bit-identical (compact vs full oracle)")
    check(fb > 0 and cb > 0,
          f"sidecar staged-bytes accounted (full={fb:.0f} "
          f"compact={cb:.0f})")
    return {"sidecar_full_bytes": fb, "sidecar_compact_bytes": cb}


def child() -> int:
    from pingoo_tpu import native_ring
    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.registry import lint_prometheus_text

    summary = _python_plane()
    if native_ring.ensure_built():
        summary.update(_sidecar_plane())
    else:
        print("  note sidecar plane skipped: native toolchain "
              "unavailable")

    text = REGISTRY.prometheus_text()
    problems = lint_prometheus_text(text)
    check(not problems, f"prometheus lint clean {problems[:3]}")
    for name in ("pingoo_staged_bytes_total", "pingoo_staging_field_cap"):
        check(name in text, f"scrape exposes {name}")

    if FAILURES:
        print(f"\nstaging smoke FAILED ({len(FAILURES)} problems)")
        return 1
    print(json.dumps(summary))
    print("\nstaging smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else parent())
