"""Differential HTTP-parsing fuzzer (`make fuzz`; ISSUE 11 tentpole).

Request smuggling is a PARSING-DISCREPANCY attack: it only works when
two components of the same deployment read one byte stream as two
different requests. This plane has three parsers that must agree —

  native   the C++ epoll listener (pingoo_tpu/native/httpd.cc),
           spawned here on loopback in front of a real verdict ring;
           the harness consumer dequeues the slots the listener
           enqueued, which ARE the natively-parsed RequestTuple fields
           verbatim, and posts back interpreter verdicts over them.
  python   host/httpd.py's `parse_request_bytes` one-shot oracle: the
           exact strict gate + h11 parse + `extract_request_fields`
           the python listener applies to live sockets.
  interp   engine-side extraction: `tuple_to_context` +
           `interpret_rules_row` + `action_lanes` over each plane's
           fields — the verdict bits a request actually earns.

Every mutant is a deterministic seed-driven perturbation of HTTP/1.1
framing: chunk-size extensions and hex casing, chunk/TCP boundary
splits mid-token, header folding/duplication/whitespace, percent- and
double-URL-encoding, path normalization shapes (`..`, `//`, `;`),
Content-Length vs Transfer-Encoding conflicts, bare-LF line endings.
Body-bearing classes (ISSUE 13) carry DEFAULT_BODY_RULES match
literals torn across TCP segments, chunk seams and the 4096-byte ring
window, driving the native streaming scanner (the harness runs the
listener with PINGOO_BODY_INSPECT=on and answers body windows with
the real scanner) against the python plane's contiguous scan; a
scanner-level h2 DATA fragmentation differential covers the frame
boundaries the h1 harness cannot express.
A DISCREPANCY is any mutant where (a) one plane evaluates a request
the other refuses, (b) both evaluate but the extracted RequestTuple
fields differ, or (c) the verdict bits differ — modulo the documented
KNOWN_DELTAS table (docs/FUZZING.md). Discrepancies increment
`pingoo_fuzz_discrepancy_total{class=...}` and fail the run.

Found-and-fixed cases live in tools/analyze/corpus/*.json and replay
first on every run (and in tests/test_fuzz_corpus.py) as regression
pins. Offline-safe: no native toolchain downgrades the native path to
skip-with-warning and the python/interp differential still runs.

    python -m tools.analyze fuzz [--mutants N] [--seed S]
                                 [--corpus-only] [--no-native]
"""

from __future__ import annotations

import base64
import json
import os
import random
import select
import socket
import subprocess
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
CORPUS_DIR = os.path.join(HERE, "corpus")
DEFAULT_MUTANTS = 5000
DEFAULT_SEED = 1106  # ISSUE 11; fixed so CI runs are reproducible

if REPO not in sys.path:  # `python tools/analyze/fuzz.py` convenience
    sys.path.insert(0, REPO)


# --------------------------------------------------------------------------
# mutants
# --------------------------------------------------------------------------

class Mutant:
    """One fuzz case: raw bytes + an optional TCP segmentation plan
    (byte offsets to split the send at — boundary splits mid-token are
    a mutation class of their own)."""

    __slots__ = ("cls", "raw", "splits", "note")

    def __init__(self, cls: str, raw: bytes, splits=(), note: str = ""):
        self.cls = cls
        self.raw = raw
        self.splits = tuple(splits)
        self.note = note

    def segments(self):
        if not self.splits:
            return [self.raw]
        out, prev = [], 0
        for cut in sorted(set(self.splits)):
            if 0 < cut < len(self.raw):
                out.append(self.raw[prev:cut])
                prev = cut
        out.append(self.raw[prev:])
        return [s for s in out if s]


UAS = [b"Mozilla/5.0", b"curl/8.5", b"sqlmap/1.8", b"pingoo-fuzz"]
HOSTS = [b"fuzz.test", b"evil.test", b"a.example"]
PATHS = [b"/", b"/index.html", b"/admin/panel", b"/api/v1/users",
         b"/static/app.js", b"/search"]
QUERIES = [b"", b"?q=1", b"?a=b&c=d", b"?x=<script>"]


def _head(rng, method=b"GET", path=None, extra=(), ua=None, host=None,
          version=b"HTTP/1.1"):
    path = path if path is not None else (
        rng.choice(PATHS) + rng.choice(QUERIES))
    lines = [method + b" " + path + b" " + version,
             b"host: " + (host if host is not None else rng.choice(HOSTS)),
             b"user-agent: " + (ua if ua is not None else rng.choice(UAS))]
    lines += list(extra)
    lines.append(b"connection: close")
    return b"\r\n".join(lines) + b"\r\n\r\n", path


def _chunked(body_chunks, sizeline=None, trailer=b""):
    out = b""
    for chunk in body_chunks:
        size = (b"%x" % len(chunk)) if sizeline is None else sizeline
        out += size + b"\r\n" + chunk + b"\r\n"
        sizeline = None  # custom size line applies to the first chunk
    return out + b"0\r\n" + trailer + b"\r\n"


def mut_chunk_ext(rng) -> Mutant:
    """Chunk-size extensions, hex casing, leading zeros."""
    chunk = bytes(rng.choice(b"abcdef") for _ in range(rng.randint(1, 30)))
    size = b"%x" % len(chunk)
    shape = rng.randrange(4)
    if shape == 0:
        size += b";" + rng.choice([b"ext", b"ext=v", b"a=1;b=2", b";"])
    elif shape == 1:
        size = (b"%X" % len(chunk))  # uppercase hex
    elif shape == 2:
        size = b"0" * rng.randint(1, 4) + size  # leading zeros
    else:
        size += b" "  # trailing space before CRLF
    head, _ = _head(rng, method=b"POST",
                    extra=[b"transfer-encoding: chunked"])
    return Mutant("chunk-ext", head + _chunked([chunk], sizeline=size))


def mut_chunk_bad(rng) -> Mutant:
    """Chunk framing both planes must refuse identically."""
    chunk = b"abc"
    size = rng.choice([b"0x3", b"3 3", b"g", b"-3", b"+3", b"3\x00"])
    head, _ = _head(rng, method=b"POST",
                    extra=[b"transfer-encoding: chunked"])
    return Mutant("chunk-bad", head + _chunked([chunk], sizeline=size))


def mut_chunk_split(rng) -> Mutant:
    """Valid message, TCP segment boundaries mid-token: the parsers
    must reassemble identically no matter where the wire splits."""
    chunks = [bytes(rng.choice(b"xyz") for _ in range(rng.randint(1, 20)))
              for _ in range(rng.randint(1, 3))]
    head, _ = _head(rng, method=b"POST",
                    extra=[b"transfer-encoding: chunked"])
    raw = head + _chunked(chunks)
    splits = sorted(rng.sample(range(1, len(raw)),
                               k=min(rng.randint(1, 4), len(raw) - 1)))
    return Mutant("chunk-split", raw, splits=splits)


def mut_trailer(rng) -> Mutant:
    trailer = rng.choice([b"x-check: 1\r\n", b"x-a: 1\r\nx-b: 2\r\n"])
    head, _ = _head(rng, method=b"POST",
                    extra=[b"transfer-encoding: chunked"])
    return Mutant("chunk-trailer", head + _chunked([b"data"],
                                                   trailer=trailer))


def mut_header_fold(rng) -> Mutant:
    """Obsolete line folding — strict gates on both planes reject."""
    cont = rng.choice([b" folded", b"\tfolded", b"  two  words"])
    head, _ = _head(rng, extra=[b"x-long: start", cont])
    return Mutant("header-fold", head)


def mut_header_dup(rng) -> Mutant:
    """Duplicate headers; duplicate Content-Length is the classic
    smuggling primitive and must 400 on both planes."""
    shape = rng.randrange(4)
    if shape == 0:
        extra = [b"content-length: 3", b"content-length: 3"]
        body = b"abc"
    elif shape == 1:
        extra = [b"content-length: 3", b"content-length: 30"]
        body = b"abc"
    elif shape == 2:
        extra = [b"x-dup: one", b"x-dup: two"]
        body = b""
    else:
        extra = [b"host: second.test"]  # second Host on top of _head's
        body = b""
    head, _ = _head(rng, method=b"POST" if body else b"GET", extra=extra)
    return Mutant("header-dup", head + body)


def mut_header_ws(rng) -> Mutant:
    shape = rng.randrange(3)
    if shape == 0:
        extra = [b"x-pad : v"]  # whitespace before colon -> 400
    elif shape == 1:
        extra = [b"x-pad:    spaced out   "]  # OWS around value: legal
    else:
        extra = [b"x-pad:\tv"]  # tab OWS: legal
    head, _ = _head(rng, extra=extra)
    return Mutant("header-ws", head)


def mut_pct_encode(rng) -> Mutant:
    """Percent/double encoding in the target: neither plane decodes, so
    the extracted url/path bytes must be identical on both."""
    core = rng.choice([b"%2e%2e%2f", b"%252e%252e", b"%2E%2E/", b"%c0%af",
                       b"%00", b"%zz", b"%"])
    path = b"/files/" + core + b"etc/passwd"
    head, _ = _head(rng, path=path)
    return Mutant("pct-encode", head)


def mut_path_norm(rng) -> Mutant:
    """Dot-segment / slash shapes that a normalizing parser would
    collapse — these planes must both pass them through raw."""
    path = rng.choice([b"/a/../b", b"/a/./b", b"//double//slash",
                       b"/a;param=1/b", b"/a/..", b"/.", b"/a\\b",
                       b"/%2e/secret", b"/a//../../b"])
    head, _ = _head(rng, path=path)
    return Mutant("path-norm", head)


def mut_cl_te(rng) -> Mutant:
    """Content-Length vs Transfer-Encoding conflicts (smuggling's
    bread and butter) and malformed CL values."""
    shape = rng.randrange(6)
    if shape == 0:
        extra = [b"content-length: 3", b"transfer-encoding: chunked"]
        body = _chunked([b"abc"])
    elif shape == 1:
        extra = [b"transfer-encoding: chunked", b"content-length: 3"]
        body = _chunked([b"abc"])
    elif shape == 2:
        extra = [b"transfer-encoding: gzip"]
        body = b""
    elif shape == 3:
        extra = [b"content-length: +3"]
        body = b"abc"
    elif shape == 4:
        extra = [b"content-length: 3, 3"]
        body = b"abc"
    else:
        extra = [b"content-length:  3  "]  # OWS-padded value: legal
        body = b"abc"
    head, _ = _head(rng, method=b"POST", extra=extra)
    return Mutant("cl-te", head + body)


def mut_bare_lf(rng) -> Mutant:
    head, _ = _head(rng)
    if rng.randrange(2):
        raw = head.replace(b"\r\n", b"\n")  # all-LF head
    else:  # one LF line amid CRLF
        lines = head.split(b"\r\n")
        i = rng.randrange(1, max(2, len(lines) - 2))
        raw = b"\r\n".join(lines[:i]) + b"\r\n" + lines[i] + b"\n" + \
            b"\r\n".join(lines[i + 1:])
    return Mutant("bare-lf", raw)


def mut_reqline(rng) -> Mutant:
    """Request-line shapes: method casing, versions, junk."""
    shape = rng.randrange(5)
    if shape == 0:
        head, _ = _head(rng, method=b"get")
    elif shape == 1:
        head, _ = _head(rng, version=b"HTTP/1.0")
    elif shape == 2:
        head, _ = _head(rng, version=b"HTTP/2.7")
    elif shape == 3:
        head, _ = _head(rng, method=b"DELETE")
    else:
        head = b"NONSENSE\r\n\r\n"
    return Mutant("reqline", head)


def mut_head_split(rng) -> Mutant:
    """Valid request, TCP boundaries inside the head (mid header name,
    mid CRLF) — reassembly must not change what is extracted."""
    head, _ = _head(rng, method=b"POST", extra=[b"content-length: 4"])
    raw = head + b"body"
    splits = sorted(rng.sample(range(1, len(raw)),
                               k=min(rng.randint(1, 5), len(raw) - 1)))
    return Mutant("head-split", raw, splits=splits)


def mut_ua_edge(rng) -> Mutant:
    """UA edge shapes around the 256-byte extraction cap and the
    empty-UA 403 — the caps must agree bit-exactly."""
    shape = rng.randrange(4)
    if shape == 0:
        ua = b""
    elif shape == 1:
        ua = b"a" * rng.choice([254, 255, 256, 257])
    elif shape == 2:
        ua = b"  padded  "
    else:
        head, _ = _head(rng)  # drop the UA header entirely
        return Mutant("ua-edge",
                      head.replace(b"user-agent: ", b"x-was-ua: ", 1))
    head, _ = _head(rng, ua=ua)
    return Mutant("ua-edge", head)


# -- body-bearing mutants (ISSUE 13: streaming body inspection) ------------
#
# The block-action literals from bodyscan.DEFAULT_BODY_RULES. The
# captcha-lane rule ("eval(") is deliberately absent: the fuzz
# differential classifies by status line and a captcha challenge is
# not a refusal, so it has no stable class on the python oracle side.

BODY_LITERALS = [b"union select", b"' or '1'='1", b"<script",
                 b"../../", b"/etc/passwd"]

#: Filler alphabet with NO space, quote, angle bracket, dot, slash or
#: paren — no run of filler (or filler touching a near-miss) can ever
#: complete a DEFAULT_BODY_RULES literal by accident.
_FILL = b"abcdefghijklmnop0123456789=&"


def _body_fill(rng, n: int) -> bytes:
    return bytes(rng.choices(_FILL, k=n))


def mut_body_literal_split(rng) -> Mutant:
    """Content-Length body carrying a match literal with the TCP
    segment boundaries placed INSIDE the literal: the native scanner
    sees the literal torn across reads and must still match via
    cross-window NFA/DFA carry, exactly like the python contiguous
    scan of the reassembled body."""
    lit = rng.choice(BODY_LITERALS)
    pre = _body_fill(rng, rng.randint(0, 40))
    body = pre + lit + _body_fill(rng, rng.randint(0, 40))
    head, _ = _head(rng, method=b"POST",
                    extra=[b"content-length: %d" % len(body)])
    lit_at = len(head) + len(pre)
    cuts = sorted(rng.sample(range(lit_at + 1, lit_at + len(lit)),
                             rng.randint(1, min(3, len(lit) - 1))))
    return Mutant("body-literal-split", head + body, splits=cuts,
                  note=f"literal {lit!r} torn at {cuts}")


def mut_body_chunk_carry(rng) -> Mutant:
    """Chunked body with the CHUNK boundary inside a match literal —
    after de-framing, the literal straddles ring windows and only the
    carried scanner state can complete the match."""
    lit = rng.choice(BODY_LITERALS)
    cuts = sorted(rng.sample(range(1, len(lit)),
                             rng.randint(1, min(3, len(lit) - 1))))
    parts = [lit[a:b] for a, b in zip((0, *cuts), (*cuts, len(lit)))]
    parts[0] = _body_fill(rng, rng.randint(0, 20)) + parts[0]
    parts[-1] = parts[-1] + _body_fill(rng, rng.randint(0, 20))
    head, _ = _head(rng, method=b"POST",
                    extra=[b"transfer-encoding: chunked"])
    raw = head + _chunked(parts)
    splits = ()
    if rng.random() < 0.5:
        # Additionally split the TCP send at a chunk seam, so the
        # framer resumes mid-message as well as mid-literal.
        off, seams = len(head), []
        for p in parts:
            off += len(b"%x" % len(p)) + 2 + len(p) + 2
            seams.append(off)
        splits = (rng.choice(seams),)
    return Mutant("body-chunk-carry", raw, splits=splits,
                  note=f"literal {lit!r} chunk-cut at {cuts}")


def mut_body_benign(rng) -> Mutant:
    """TE/CL bodies with NO matching literal — including near-miss
    shapes one byte away from a rule — must stay `allow` on both
    planes: the merge lane must not invent verdict bits."""
    near = [b"union  select", b"unionselect", b"<scr1pt", b"113'='1",
            b"=etc=passwd"]
    body = _body_fill(rng, rng.randint(1, 120))
    if rng.random() < 0.5:
        body += rng.choice(near) + _body_fill(rng, rng.randint(0, 20))
    if rng.random() < 0.5:
        head, _ = _head(rng, method=b"POST",
                        extra=[b"content-length: %d" % len(body)])
        raw = head + body
    else:
        k = min(rng.randint(0, 3), len(body) - 1)
        cuts = sorted(rng.sample(range(1, len(body)), k)) if k else []
        parts = [body[a:b]
                 for a, b in zip((0, *cuts), (*cuts, len(body)))]
        head, _ = _head(rng, method=b"POST",
                        extra=[b"transfer-encoding: chunked"])
        raw = head + _chunked(parts)
    splits = ()
    if rng.random() < 0.5 and len(raw) > 2:
        splits = tuple(sorted(rng.sample(range(1, len(raw)),
                                         rng.randint(1, 3))))
    return Mutant("body-benign", raw, splits=splits)


def mut_body_window_straddle(rng) -> Mutant:
    """Body larger than the 4096-byte ring window with the literal
    straddling the window-flush boundary: carry across FLUSHED ring
    windows (not just chunk seams) must match the contiguous scan."""
    lit = rng.choice(BODY_LITERALS)
    k = rng.randint(1, len(lit) - 1)  # literal bytes before the flush
    body = _body_fill(rng, 4096 - k) + lit \
        + _body_fill(rng, rng.randint(0, 64))
    head, _ = _head(rng, method=b"POST",
                    extra=[b"content-length: %d" % len(body)])
    return Mutant("body-window-straddle", head + body,
                  note=f"literal {lit!r} straddles byte 4096 at -{k}")


MUTATORS = [mut_chunk_ext, mut_chunk_bad, mut_chunk_split, mut_trailer,
            mut_header_fold, mut_header_dup, mut_header_ws,
            mut_pct_encode, mut_path_norm, mut_cl_te, mut_bare_lf,
            mut_reqline, mut_head_split, mut_ua_edge,
            mut_body_literal_split, mut_body_chunk_carry,
            mut_body_benign, mut_body_window_straddle]


def generate(n: int, seed: int):
    rng = random.Random(seed)
    return [MUTATORS[i % len(MUTATORS)](rng) for i in range(n)]


# --------------------------------------------------------------------------
# known deltas — every entry here is documented in docs/FUZZING.md
# --------------------------------------------------------------------------

def _delta_lf_drop(mutant, native_cls, python_cls):
    """LF-only heads: the native head scanner is CRLF-terminated, so a
    bare-LF head never completes and the connection drops on EOF with
    no status; the python gate answers 400. Both REFUSE the bytes."""
    return python_cls == "reject-400" and native_cls == "drop" and \
        b"\r\n\r\n" not in mutant.raw


def _python_head_ok(raw: bytes) -> bool:
    """True when the python plane accepts the HEAD (the reject, if any,
    was earned by the body). No EOF is fed: head-only acceptance is the
    question, not whether the body ever completes."""
    import h11

    from pingoo_tpu.host.httpd import (MAX_HEADER_BYTES, _HEAD_END_RE,
                                       strict_head_violation)

    m = _HEAD_END_RE.search(raw)
    if m is None or m.end() > MAX_HEADER_BYTES:
        return False
    head = raw[:m.end()]
    if strict_head_violation(head) is not None:
        return False
    conn = h11.Connection(h11.SERVER,
                          max_incomplete_event_size=MAX_HEADER_BYTES)
    try:
        conn.receive_data(head)
        while True:
            event = conn.next_event()
            if event is h11.NEED_DATA:
                return False
            if isinstance(event, h11.Request):
                return True
    except h11.RemoteProtocolError:
        return False


def _delta_head_first_verdict(mutant, native_cls, python_cls):
    """The native listener verdicts on the HEAD while the body still
    streams (that overlap is the data plane's point), so a message
    whose BODY framing is invalid can already have earned a 403 — or
    an abort mid-proxy (drop), or a 400 once the framer hits the bad
    chunk. The python plane buffers the whole message first and 400s.
    Every one of those outcomes refuses the message; only a completed
    200 proxy would be a real divergence (and stays one)."""
    return (python_cls in ("reject-400", "reject-413") and
            native_cls in ("block", "drop", "reject-400") and
            _python_head_ok(mutant.raw))


KNOWN_DELTAS = [
    ("bare-lf-drop-vs-400", _delta_lf_drop),
    ("head-first-verdict", _delta_head_first_verdict),
]


def known_delta(mutant, native_cls, python_cls):
    for name, pred in KNOWN_DELTAS:
        if pred(mutant, native_cls, python_cls):
            return name
    return None


# --------------------------------------------------------------------------
# the three parse paths
# --------------------------------------------------------------------------

REFUSED = ("drop",)  # plus any reject-*


def _is_refusal(cls: str) -> bool:
    return cls in REFUSED or cls.startswith("reject-")


def _fuzz_plan():
    """Small fixed ruleset whose verdicts flip on exactly the fields
    the mutators perturb, so extraction skew becomes a verdict skew."""
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    exprs = [
        'http_request.path.contains("../")',
        'http_request.path.starts_with("/admin")',
        'http_request.url.contains("%2e%2e")',
        'http_request.user_agent.contains("sqlmap")',
        'http_request.host.contains("evil")',
    ]
    rules = [RuleConfig(name=f"fuzz{i}", actions=(Action.BLOCK,),
                        expression=compile_expression(e))
             for i, e in enumerate(exprs)]
    return compile_ruleset(rules, {})


def _interp_action(plan, fields: dict) -> int:
    """Verdict bits via the interpreter over extracted fields — the
    third parse path. 0 allow / 1 block (the fuzz plan has no captcha
    or route rules, so lane 0 is the whole verdict)."""
    from pingoo_tpu.engine.batch import RequestTuple, tuple_to_context
    from pingoo_tpu.engine.verdict import action_lanes, interpret_rules_row

    tup = RequestTuple(
        host=fields["host"], url=fields["url"], path=fields["path"],
        method=fields["method"], user_agent=fields["user_agent"],
        ip="127.0.0.1", remote_port=0, asn=0, country="XX")
    row = interpret_rules_row(plan, tuple_to_context(tup, {}))
    lanes = action_lanes(plan, row[None, :])
    return int(lanes[0][0])


_BODY_SCAN = None  # lazy (bodyscan module, BodyScanner) singleton


def _body_scan():
    global _BODY_SCAN
    if _BODY_SCAN is None:
        from pingoo_tpu.engine import bodyscan
        _BODY_SCAN = (bodyscan, bodyscan.BodyScanner())
    return _BODY_SCAN


def classify_python(raw: bytes, plan) -> tuple:
    """-> (class, fields|None). Class is reject-400/413/431, drop,
    block, or allow — the python listener's observable behavior.
    Bodies ride the same DEFAULT_BODY_RULES merge as the listener:
    a metadata `allow` with a body is scanned contiguously and the
    body verdict merges in (ISSUE 13) — mirroring the native plane's
    streamed scan of the identical request set."""
    from pingoo_tpu.host.httpd import extract_request_fields, \
        parse_request_bytes

    status, detail = parse_request_bytes(raw)
    if status == "reject":
        return f"reject-{detail}", None
    if status == "incomplete":
        return "drop", None
    req = detail
    host, user_agent = extract_request_fields(req)
    if not user_agent:
        return "block", None  # empty/oversized UA 403s pre-ring
    fields = {"method": req.method, "host": host, "path": req.path,
              "url": req.target, "user_agent": user_agent}
    action = _interp_action(plan, fields)
    if action == 0 and req.body:
        bs, scanner = _body_scan()
        verdict = scanner.scan_buffered(bytes(req.body))
        if not verdict.degraded:
            action = bs.merge_actions(0, verdict.unverified,
                                      verdict.verified_block) & 0x3
    return ("block" if action == 1 else "allow"), fields


def diff_h2_frag(rng, rounds: int) -> list[str]:
    """h2 DATA fragmentation differential. h2 client bodies never ride
    the h1 byte-stream differential (the native listener skips them by
    design — metadata-only, counted in body_h2_skipped), so fragment
    at the DATA-frame layer directly: a payload sliced at arbitrary
    frame boundaries — 1-byte frames, empty frames, whole-tail frames
    — fed to the streaming scanner as windows must earn exactly the
    verdict the contiguous interpreter oracle earns. This is the same
    window stream the python listener's h2 path produces after
    buffering, so scanner-level agreement IS plane-level agreement."""
    from pingoo_tpu.engine import bodyscan

    plan = bodyscan.compile_body_plan()
    scanner = bodyscan.BodyScanner(plan)
    problems = []
    for i in range(rounds):
        lit = rng.choice(BODY_LITERALS + [b""])  # sometimes benign
        payload = (_body_fill(rng, rng.randint(0, 64)) + lit
                   + _body_fill(rng, rng.randint(0, 64)))
        frames, off = [], 0
        while off < len(payload):
            n = rng.choice((1, 2, 3, 7, 16, len(payload) - off))
            frames.append(payload[off:off + n])
            off += n
        if not frames or rng.random() < 0.3:
            frames.insert(rng.randrange(len(frames) + 1), b"")
        windows = [bodyscan.BodyWindow(flow_id=i, win_seq=s, data=d,
                                       final=(s == len(frames) - 1))
                   for s, d in enumerate(frames)]
        got = [v for v in scanner.scan_windows(windows)
               if v.flow_id == i]
        want_unv, want_vb, _ = bodyscan.body_lanes_oracle(plan, payload)
        if (len(got) != 1 or got[0].degraded
                or got[0].unverified != want_unv
                or got[0].verified_block != want_vb):
            problems.append(
                f"[h2-data-frag] round {i} ({len(frames)} frames, "
                f"{len(payload)}B): streamed={got!r} "
                f"oracle=({want_unv}, {want_vb})")
            _count_discrepancy("h2-data-frag")
    return problems


class NativeHarness:
    """Loopback stack: httpd + upstream + a ring consumer that records
    the natively-parsed fields per ticket and answers with interpreter
    verdicts over exactly those fields (so the ONLY free variable is
    the parse, never the rules).

    Body inspection runs ON by default (ISSUE 13): the listener is
    spawned with PINGOO_BODY_INSPECT=on, so it streams de-framed body
    windows through the ring and the consumer answers them with the
    real streaming scanner (flow carry and all) tagged with
    BODY_VERDICT_BIT — the same sidecar loop production runs. The
    differential then covers the whole body path: native BodyFramer
    windows + cross-window carry + in-C merge versus the python
    plane's contiguous scan + merge of the reassembled bytes."""

    def __init__(self, plan, tmpdir: str, body_inspect: bool = True):
        from pingoo_tpu import native_ring
        from pingoo_tpu.native_ring import Ring

        self.plan = plan
        self.slots: list[dict] = []  # consumer appends decoded fields
        self._stop = threading.Event()
        self._sync = 0  # sentinel counter for _sync_barrier

        self._bodyscan = None
        if body_inspect:
            from pingoo_tpu.engine import bodyscan
            self._bodyscan = bodyscan
            self._body_scanner = bodyscan.BodyScanner()
            # Warm the chunk kernels off the clock: the first scan per
            # row bucket compiles, and roundtrip() timeouts are short.
            self._body_scanner.scan_buffered(b"warmup")

        # Raw-socket upstream: unlike http.server it DRAINS the proxied
        # body (Content-Length and chunked) before answering and keeps
        # the connection alive — an upstream that answers early and
        # closes RSTs the native proxy mid-stream and poisons the
        # differential with transport noise.
        self._up_sock = socket.socket()
        self._up_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._up_sock.bind(("127.0.0.1", 0))
        self._up_sock.listen(64)
        self.up_port = self._up_sock.getsockname()[1]
        threading.Thread(target=self._upstream_accept, daemon=True).start()

        ring_path = os.path.join(tmpdir, "fuzz_ring")
        self.ring = Ring(ring_path, capacity=4096, create=True)
        self.ring.sidecar_attach()
        self._consumer = threading.Thread(target=self._consume,
                                          daemon=True)
        self._consumer.start()

        httpd_bin = os.path.join(native_ring.NATIVE_DIR, "httpd")
        port = _free_port()
        env = dict(os.environ)
        env.pop("PINGOO_BODY_INSPECT", None)
        if body_inspect:
            env["PINGOO_BODY_INSPECT"] = "on"
        self.proc = subprocess.Popen(
            [httpd_bin, str(port), ring_path, "127.0.0.1",
             str(self.up_port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        line = self.proc.stdout.readline()
        if b"listening" not in line:
            raise RuntimeError(f"native httpd failed to start: {line!r}")
        self.port = port
        time.sleep(0.2)

    def _upstream_accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._up_sock.accept()
            except OSError:
                return
            threading.Thread(target=self._upstream_serve, args=(conn,),
                             daemon=True).start()

    def _upstream_serve(self, conn):
        conn.settimeout(10)
        buf = b""
        try:
            while True:
                while b"\r\n\r\n" not in buf:
                    data = conn.recv(65536)
                    if not data:
                        return
                    buf += data
                head, _, buf = buf.partition(b"\r\n\r\n")
                path = head.split(b" ", 2)[1] if b" " in head else b"?"
                low = head.lower()
                if b"transfer-encoding:" in low:
                    while b"\r\n0\r\n" not in b"\r\n" + buf and \
                            not buf.startswith(b"0\r\n"):
                        data = conn.recv(65536)
                        if not data:
                            return
                        buf += data
                    # swallow through the terminating CRLFCRLF
                    while not buf.endswith(b"\r\n\r\n"):
                        data = conn.recv(65536)
                        if not data:
                            return
                        buf += data
                    buf = b""
                else:
                    clen = 0
                    for line in low.split(b"\r\n"):
                        if line.startswith(b"content-length:"):
                            try:
                                clen = int(line.split(b":", 1)[1])
                            except ValueError:
                                clen = 0
                    while len(buf) < clen:
                        data = conn.recv(65536)
                        if not data:
                            return
                        buf += data
                    buf = buf[clen:]
                body = b"upstream:" + path
                conn.sendall(b"HTTP/1.1 200 OK\r\ncontent-length: " +
                             b"%d" % len(body) + b"\r\n\r\n" + body)
        except OSError:
            pass
        finally:
            conn.close()

    def _drain_bodies(self):
        """The sidecar's body loop in miniature: dequeue de-framed
        windows, run the streaming scanner (per-flow carry), post each
        FINAL flow's verdict back tagged BODY_VERDICT_BIT. A scanner
        fault fails every live flow open (action 0) so the listener's
        held requests never stall the differential."""
        from pingoo_tpu.native_ring import (BODY_FLAG_ABORT,
                                            BODY_FLAG_FINAL,
                                            BODY_VERDICT_BIT)

        bs = self._bodyscan
        slots = self.ring.dequeue_bodies()
        if not len(slots):
            return
        windows = [bs.BodyWindow(
            flow_id=int(s["flow"]), win_seq=int(s["win_seq"]),
            data=s["data"][:int(s["win_len"])].tobytes(),
            final=bool(s["flags"] & BODY_FLAG_FINAL),
            abort=bool(s["flags"] & BODY_FLAG_ABORT)) for s in slots]
        try:
            verdicts = self._body_scanner.scan_windows(windows)
        except Exception:  # noqa: BLE001 — fail open, never stall
            self._body_scanner.flows.clear()
            verdicts = [bs.BodyVerdict(w.flow_id, degraded=True)
                        for w in windows if w.final]
        for v in verdicts:
            self.ring.post_verdict(
                v.flow_id | BODY_VERDICT_BIT,
                0 if v.degraded else v.action_byte())

    def _consume(self):
        while not self._stop.is_set():
            self.ring.heartbeat()
            if self._bodyscan is not None:
                self._drain_bodies()
            slots = self.ring.dequeue_batch(256)
            if not len(slots):
                time.sleep(0.0005)
                continue
            for slot in slots:
                fields = _decode_slot(slot)
                action = _interp_action(self.plan, fields)
                # Record BEFORE posting: the client-visible response
                # needs the verdict, so post-then-record would let
                # roundtrip() read the list before the append lands.
                self.slots.append(fields)
                self.ring.post_verdict(int(slot["ticket"]), action)
            self.ring.set_posted_floor(int(slots["ticket"].max()))

    def _sync_barrier(self, seen: int, timeout: float) -> int:
        """Serial-attribution barrier. The listener can answer an
        early 400/403 BEFORE the consumer's poll dequeues the head
        slot it already enqueued (wider still while a body scan or a
        chunk-kernel compile holds the consumer loop), so "latest
        slot" attribution can smear one mutant's fields onto the
        next. A uniquely-pathed sentinel GET pins it down: the ring
        is FIFO, so once the sentinel's slot lands, every slot the
        mutant enqueued has landed too. -> sentinel slot index, or
        len(self.slots) on timeout (fields then read as None)."""
        self._sync += 1
        tag = "/__fuzz_sync_%d" % self._sync
        try:
            s = socket.create_connection(("127.0.0.1", self.port),
                                         timeout=timeout)
            s.sendall(b"GET " + tag.encode() + b" HTTP/1.1\r\n"
                      b"host: sync.test\r\nuser-agent: fuzz-sync\r\n"
                      b"connection: close\r\n\r\n")
            while s.recv(65536):
                pass
        except OSError:
            pass
        finally:
            try:
                s.close()
            except (OSError, UnboundLocalError):
                pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            n = len(self.slots)
            for j in range(seen, n):
                if self.slots[j]["path"] == tag:
                    return j
            time.sleep(0.001)
        return len(self.slots)

    def roundtrip(self, mutant: Mutant, timeout=5.0) -> tuple:
        """Send one mutant, -> (class, fields|None) mirroring
        classify_python. Fields come from the ring slot the listener
        enqueued (None when the request never reached the ring)."""
        seen = len(self.slots)
        s = socket.create_connection(("127.0.0.1", self.port),
                                     timeout=timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        data = b""
        try:
            segments = mutant.segments()
            for i, seg in enumerate(segments):
                s.sendall(seg)
                if i + 1 < len(segments):
                    # The 1ms pause forces a distinct TCP segment; read
                    # anything that already arrived so an early 403/400
                    # is not lost to the RST a late segment triggers on
                    # the listener's closed socket.
                    readable, _, _ = select.select([s], [], [], 0.001)
                    if readable:
                        chunk = s.recv(65536)
                        if not chunk:
                            break
                        data += chunk
            s.shutdown(socket.SHUT_WR)
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    chunk = s.recv(65536)
                except socket.timeout:
                    break
                if not chunk:
                    break
                data += chunk
        except OSError:
            # Reset mid-send/mid-read: the listener already refused and
            # tore the connection down — keep whatever status arrived.
            pass
        finally:
            s.close()
        fence = self._sync_barrier(seen, timeout)
        if not data:
            return "drop", None
        status = data.split(b"\r\n", 1)[0].split(b" ")
        code = status[1].decode("latin-1") if len(status) > 1 else "???"
        fields = None
        if fence > seen:
            # Last slot the mutant enqueued before the sentinel fence
            # (a smuggling mutant can enqueue more than one; "last"
            # matches the python oracle, which parses one message).
            fields = self.slots[fence - 1]
        if code in ("400", "413", "431"):
            return f"reject-{code}", fields
        if code == "403":
            return "block", fields
        if code == "200":
            return "allow", fields
        return f"status-{code}", fields

    def close(self):
        self._stop.set()
        self.proc.terminate()
        self.proc.wait(timeout=5)
        self._consumer.join(timeout=2)
        self._up_sock.close()
        self.ring.close()


def _decode_slot(slot) -> dict:
    def field(name, ln):
        return bytes(slot[name])[:int(slot[ln])].decode("latin-1")

    return {"method": field("method", "method_len"),
            "host": field("host", "host_len"),
            "path": field("path", "path_len"),
            "url": field("url", "url_len"),
            "user_agent": field("user_agent", "ua_len")}


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------------------------------------------------------
# differential + corpus
# --------------------------------------------------------------------------

def _count_discrepancy(cls: str):
    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.schema import HOTSWAP_METRICS

    REGISTRY.counter(
        "pingoo_fuzz_discrepancy_total",
        HOTSWAP_METRICS["pingoo_fuzz_discrepancy_total"],
        labels={"class": cls}).inc()


def diff_one(mutant: Mutant, plan, harness) -> list[str]:
    """-> discrepancy descriptions for one mutant ([] = agreement)."""
    python_cls, python_fields = classify_python(mutant.raw, plan)
    if harness is None:
        return []
    native_cls, native_fields = harness.roundtrip(mutant)
    problems = []
    if _is_refusal(python_cls) != _is_refusal(native_cls) or (
            _is_refusal(python_cls) and python_cls != native_cls):
        if known_delta(mutant, native_cls, python_cls) is None:
            problems.append(f"verdict-class native={native_cls} "
                            f"python={python_cls}")
    if python_fields is not None and native_fields is not None:
        for key in ("method", "host", "path", "url", "user_agent"):
            if python_fields[key] != native_fields[key]:
                problems.append(
                    f"field {key}: native={native_fields[key]!r} "
                    f"python={python_fields[key]!r}")
    for p in problems:
        _count_discrepancy(mutant.cls)
    return [f"[{mutant.cls}] {p}" for p in problems]


def load_corpus() -> list[dict]:
    cases = []
    if not os.path.isdir(CORPUS_DIR):
        return cases
    for name in sorted(os.listdir(CORPUS_DIR)):
        if name.endswith(".json"):
            with open(os.path.join(CORPUS_DIR, name)) as f:
                case = json.load(f)
            case["_file"] = name
            cases.append(case)
    return cases


def corpus_mutant(case: dict) -> Mutant:
    return Mutant(case.get("cls", "corpus"),
                  base64.b64decode(case["raw_b64"]),
                  splits=case.get("splits") or (),
                  note=case.get("desc", ""))


def replay_corpus(plan, harness) -> list[str]:
    """Pinned found-and-fixed cases: each expects an exact per-plane
    class. -> failure descriptions."""
    failures = []
    for case in load_corpus():
        mutant = corpus_mutant(case)
        python_cls, _ = classify_python(mutant.raw, plan)
        if python_cls != case["python"]:
            failures.append(f"{case['_file']}: python={python_cls} "
                            f"expected {case['python']}")
        if harness is not None and case.get("native"):
            native_cls, _ = harness.roundtrip(mutant)
            if native_cls != case["native"]:
                failures.append(f"{case['_file']}: native={native_cls} "
                                f"expected {case['native']}")
    return failures


def run(mutants: int = DEFAULT_MUTANTS, seed: int = DEFAULT_SEED,
        corpus_only: bool = False, no_native: bool = False) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from pingoo_tpu import native_ring

    t0 = time.monotonic()
    plan = _fuzz_plan()
    harness = None
    if not no_native and native_ring.ensure_built():
        import tempfile

        tmpdir = tempfile.mkdtemp(prefix="pingoo_fuzz_")
        try:
            harness = NativeHarness(plan, tmpdir)
        except Exception as exc:  # noqa: BLE001 — downgrade, never block
            print(f"fuzz: WARNING native harness unavailable ({exc}); "
                  f"python/interp differential only")
            harness = None
    elif not no_native:
        print("fuzz: WARNING native toolchain unavailable; "
              "python/interp differential only")

    try:
        corpus_failures = replay_corpus(plan, harness)
        for failure in corpus_failures:
            print(f"fuzz: CORPUS REGRESSION {failure}")
        n_corpus = len(load_corpus())
        print(f"fuzz: corpus {n_corpus} case(s), "
              f"{len(corpus_failures)} regression(s)")
        if corpus_only:
            return 1 if corpus_failures else 0

        discrepancies: list[str] = []
        per_class: dict[str, int] = {}
        for mutant in generate(mutants, seed):
            per_class[mutant.cls] = per_class.get(mutant.cls, 0) + 1
            discrepancies += diff_one(mutant, plan, harness)
            if len(discrepancies) >= 25:
                print("fuzz: stopping early — 25+ discrepancies")
                break
        h2_rounds = max(25, mutants // 50)
        discrepancies += diff_h2_frag(random.Random(seed ^ 0x6832),
                                      h2_rounds)
        per_class["h2-data-frag"] = h2_rounds
        wall = time.monotonic() - t0
        print(f"fuzz: {mutants} mutants over {len(MUTATORS)} classes, "
              f"seed {seed}, {wall:.1f}s "
              f"({'3-path' if harness else '2-path'})")
        for cls in sorted(per_class):
            print(f"  {per_class[cls]:>5}  {cls}")
        for d in discrepancies:
            print(f"fuzz: DISCREPANCY {d}")
        if discrepancies or corpus_failures:
            print(f"fuzz: FAIL — {len(discrepancies)} discrepancy(ies), "
                  f"{len(corpus_failures)} corpus regression(s)")
            return 1
        print("fuzz: OK — all parse paths agree")
        return 0
    finally:
        if harness is not None:
            harness.close()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mutants", type=int, default=DEFAULT_MUTANTS)
    ap.add_argument("--seed", type=int, default=DEFAULT_SEED)
    ap.add_argument("--corpus-only", action="store_true",
                    help="replay the pinned corpus only")
    ap.add_argument("--no-native", action="store_true",
                    help="skip the native plane (python/interp only)")
    args = ap.parse_args(argv)
    return run(mutants=args.mutants, seed=args.seed,
               corpus_only=args.corpus_only, no_native=args.no_native)


if __name__ == "__main__":
    sys.exit(main())
