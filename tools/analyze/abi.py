"""Cross-plane ABI/layout checker (make analyze-abi).

The shm verdict ring's slot layout exists twice: as C structs in
pingoo_tpu/native/pingoo_ring.h and as numpy structured dtypes in
pingoo_tpu/native_ring.py. Until this checker the two were "mirrored by
construction" — a field added on one side silently corrupted every slot
decode. Now three tables are diffed pairwise:

  C        abi_emit.cc compiled against the real header: the COMPILER'S
           sizeof/offsetof/alignof answer (absent without a toolchain).
  python   derived from the native_ring.py dtypes and constants.
  golden   tools/analyze/abi_golden.json, committed — so the check
           still bites in containers with no C++ compiler.

Any mismatch (missing field, moved offset, resized struct, drifted
constant or format version) is a failure. After an INTENTIONAL layout
change (which must bump PINGOO_RING_VERSION) regenerate the golden:

    python -m tools.analyze abi --regen
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

from . import REPO_ROOT

EMITTER_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "abi_emit.cc")
GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "abi_golden.json")
NATIVE_DIR = os.path.join(REPO_ROOT, "pingoo_tpu", "native")

# numpy dtype name -> C struct name
STRUCT_OF_DTYPE = {
    "REQUEST_SLOT_DTYPE": "PingooRequestSlot",
    "VERDICT_SLOT_DTYPE": "PingooVerdictSlot",
    "TELEMETRY_DTYPE": "PingooRingTelemetry",
    "RING_HEADER_DTYPE": "PingooRingHeader",
    "SPILL_SLOT_DTYPE": "PingooSpillSlot",
    "BODY_SLOT_DTYPE": "PingooBodySlot",
}


def python_table() -> dict:
    """The Python plane's view of the ABI, shaped like the emitter JSON
    (structs carry no "align": numpy dtypes don't model C alignment)."""
    from pingoo_tpu import native_ring as nr

    structs = {}
    for dtype_name, struct_name in STRUCT_OF_DTYPE.items():
        dt = getattr(nr, dtype_name)
        fields = [
            {"name": name,
             "offset": int(dt.fields[name][1]),
             "size": int(dt.fields[name][0].itemsize)}
            for name in dt.names
        ]
        structs[struct_name] = {"size": int(dt.itemsize), "fields": fields}
    return {
        "format_version": nr.RING_FORMAT_VERSION,
        "constants": {
            "PINGOO_RING_MAGIC": nr.RING_MAGIC,
            "PINGOO_RING_VERSION": nr.RING_FORMAT_VERSION,
            "PINGOO_METHOD_CAP": nr.FIELD_CAPS["method"],
            "PINGOO_HOST_CAP": nr.FIELD_CAPS["host"],
            "PINGOO_PATH_CAP": nr.FIELD_CAPS["path"],
            "PINGOO_URL_CAP": nr.FIELD_CAPS["url"],
            "PINGOO_UA_CAP": nr.FIELD_CAPS["user_agent"],
            "PINGOO_SLOT_FLAG_TRUNCATED": nr.SLOT_FLAG_TRUNCATED,
            "PINGOO_SPILL_SLOTS": nr.SPILL_SLOTS,
            "PINGOO_SPILL_DATA_CAP": nr.SPILL_DATA_CAP,
            "PINGOO_SPILL_NONE": nr.SPILL_NONE,
            "PINGOO_WAIT_BUCKETS": nr.WAIT_BUCKETS,
            "PINGOO_TELEMETRY_WORDS": nr.TELEMETRY_WORDS,
            "PINGOO_BODY_SLOTS": nr.BODY_SLOTS,
            "PINGOO_BODY_WINDOW_CAP": nr.BODY_WINDOW_CAP,
            "PINGOO_BODY_FLAG_FINAL": nr.BODY_FLAG_FINAL,
            "PINGOO_BODY_FLAG_ABORT": nr.BODY_FLAG_ABORT,
            "PINGOO_BODY_VERDICT_BIT": nr.BODY_VERDICT_BIT,
        },
        "structs": structs,
    }


def compiler() -> str | None:
    for cxx in (os.environ.get("CXX") or "", "g++", "clang++", "c++"):
        if cxx and shutil.which(cxx):
            return cxx
    return None


def emitter_table(header_dir: str = NATIVE_DIR,
                  emitter_src: str = EMITTER_SRC) -> dict | None:
    """Compile and run the C emitter; None when no toolchain exists.
    `header_dir` is overridable so the negative tests can point the
    same emitter at a MUTATED copy of pingoo_ring.h."""
    cxx = compiler()
    if cxx is None:
        return None
    with tempfile.TemporaryDirectory(prefix="pingoo-abi-") as tmp:
        binary = os.path.join(tmp, "abi_emit")
        subprocess.run(
            [cxx, "-O0", "-std=c++17", "-I", header_dir, "-o", binary,
             emitter_src],
            check=True, capture_output=True)
        out = subprocess.run([binary], check=True, capture_output=True)
    return json.loads(out.stdout)


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as f:
        return json.load(f)


def diff_tables(a: dict, b: dict, a_name: str, b_name: str) -> list[str]:
    """Symmetric diff of two ABI tables -> list of human mismatches
    (empty == identical layout). "align" is compared only when both
    sides define it (the python table doesn't)."""
    out: list[str] = []
    if a.get("format_version") != b.get("format_version"):
        out.append(f"format_version: {a_name}={a.get('format_version')} "
                   f"{b_name}={b.get('format_version')}")
    ca, cb = a.get("constants", {}), b.get("constants", {})
    for k in sorted(set(ca) | set(cb)):
        if ca.get(k) != cb.get(k):
            out.append(f"constant {k}: {a_name}={ca.get(k)} "
                       f"{b_name}={cb.get(k)}")
    sa, sb = a.get("structs", {}), b.get("structs", {})
    for name in sorted(set(sa) | set(sb)):
        if name not in sa or name not in sb:
            missing = a_name if name not in sa else b_name
            out.append(f"struct {name}: missing from {missing}")
            continue
        ta, tb = sa[name], sb[name]
        if ta["size"] != tb["size"]:
            out.append(f"struct {name}: sizeof {a_name}={ta['size']} "
                       f"{b_name}={tb['size']}")
        if "align" in ta and "align" in tb and ta["align"] != tb["align"]:
            out.append(f"struct {name}: alignof {a_name}={ta['align']} "
                       f"{b_name}={tb['align']}")
        fa = {f["name"]: f for f in ta["fields"]}
        fb = {f["name"]: f for f in tb["fields"]}
        for fname in sorted(set(fa) | set(fb)):
            if fname not in fa or fname not in fb:
                missing = a_name if fname not in fa else b_name
                out.append(f"struct {name}.{fname}: missing from {missing}")
                continue
            for attr in ("offset", "size"):
                if fa[fname][attr] != fb[fname][attr]:
                    out.append(
                        f"struct {name}.{fname}: {attr} "
                        f"{a_name}={fa[fname][attr]} "
                        f"{b_name}={fb[fname][attr]}")
    return out


def run(regen: bool = False) -> int:
    """The analyze-abi pass. Exit 0 clean, 1 on any layout drift."""
    py = python_table()
    try:
        c = emitter_table()
    except subprocess.CalledProcessError as exc:
        print("analyze-abi: FAIL — emitter did not compile against "
              "pingoo_ring.h (header syntax drift?):\n"
              f"{exc.stderr.decode(errors='replace')[-2000:]}",
              file=sys.stderr)
        return 1

    if regen:
        if c is None:
            print("analyze-abi: cannot --regen without a C++ compiler",
                  file=sys.stderr)
            return 1
        with open(GOLDEN_PATH, "w") as f:
            json.dump(c, f, indent=4, sort_keys=False)
            f.write("\n")
        print(f"analyze-abi: regenerated {os.path.relpath(GOLDEN_PATH, REPO_ROOT)}")

    golden = load_golden()
    problems = diff_tables(py, golden, "python", "golden")
    if c is None:
        print("analyze-abi: WARNING — no C++ compiler; checked python "
              "dtypes against the committed golden only", file=sys.stderr)
    else:
        problems += diff_tables(c, golden, "C", "golden")
        problems += diff_tables(c, py, "C", "python")
    if problems:
        print("analyze-abi: FAIL — cross-plane ABI drift:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        print("  (intentional layout change? bump PINGOO_RING_VERSION, "
              "mirror the dtypes, then `python -m tools.analyze abi "
              "--regen`)", file=sys.stderr)
        return 1
    n_structs = len(golden["structs"])
    n_fields = sum(len(s["fields"]) for s in golden["structs"].values())
    sides = "python==golden" if c is None else "C==python==golden"
    print(f"analyze-abi: OK ({sides}; ring format v"
          f"{golden['format_version']}, {n_structs} structs, "
          f"{n_fields} fields)")
    return 0
