"""pingoo-analyze: project-native static analysis (make analyze).

Four offline-safe passes over the two-plane serving stack
(docs/STATIC_ANALYSIS.md has the full inventory):

  abi   cross-plane ABI/layout checker: C++ emitter compiled from
        native/pingoo_ring.h vs the numpy dtypes in native_ring.py vs
        the committed golden table (tools/analyze/abi_golden.json).
  lint  JAX hot-path AST linter over engine/, ops/, compiler/:
        host-sync calls, jit-recompilation hazards, per-request
        allocation in registered hot functions.
  tidy  clang-tidy (bugprone/concurrency) over native/*.cc against a
        tracked baseline; skip-with-warning when clang-tidy is absent.
  tsan  the extended ring_stress concurrency gate built with
        -fsanitize=thread; skip-with-warning when the toolchain can't
        build TSAN binaries.

Every pass is individually invocable (`python -m tools.analyze <pass>`,
`make analyze-abi` etc.) and exits 0 clean / 1 with findings.
"""

import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Skip registry: a pass that downgrades to skip-with-warning records
# (pass_name, reason) here so the `all` summary table can show WHY a
# pass didn't really run instead of a green PASS that proved nothing.
SKIP_NOTES: list = []


def note_skip(pass_name: str, reason: str) -> None:
    SKIP_NOTES.append((pass_name, reason))
