"""JAX hot-path linter (make analyze-lint).

An AST pass over the serving-path Python (lint_config.LINT_DIRS) that
makes the latency invariants PR 1 bought machine-checked: the verdict
path must stay free of hidden host-device synchronization points,
jit-recompilation hazards, and per-batch allocation churn. PAPERS.md
(ModSec-Learn) argues WAF correctness must be checked mechanically, not
by convention; this extends that to the performance contract.

Rule inventory (docs/STATIC_ANALYSIS.md):

  sync-item            .item() forces a blocking device->host transfer
  sync-tolist          .tolist() forces a blocking transfer + pyobj churn
  sync-device-get      jax.device_get() is an explicit blocking transfer
  sync-block           block_until_ready outside the explicit allowlist
                       (lint_config.BLOCK_UNTIL_READY_ALLOW)
  sync-asarray-hot     np.asarray/np.array/np.ascontiguousarray inside a
                       registered hot function (device input -> implicit
                       sync; host input -> a copy per batch)
  sync-scalar-cast     float()/int()/bool() over the result of a jitted
                       dispatch callable (blocks per call)
  hot-alloc            fresh numpy allocation inside a hot function
  recompile-jit-in-loop    jax.jit(...) constructed inside a loop (fresh
                           cache entry per iteration)
  recompile-const-upload   jnp.asarray/jnp.array of a host constant
                           captured from OUTSIDE the traced region
                           (re-staged on every retrace; hoist it)
  suppression-missing-reason   # pingoo: allow(...) without a reason
  stale-suppression    a reasoned allow() that no longer matches any
                       finding — dead suppressions hide future
                       regressions on their line, so they must go
  unbounded-compile-axis   a len()/.shape-derived expression reaching a
                           jitted dispatch without passing through a
                           registered quantizer (SHAPE_QUANTIZERS) —
                           every raw size value is a fresh XLA compile
                           outside the proved COMPILE_SURFACE bound

Suppression syntax — the rule name AND a reason are mandatory:

    x = np.asarray(dev)  # pingoo: allow(sync-asarray-hot): the one
                         # deliberate sync point for this plane

A standalone `# pingoo: allow(rule): reason` comment line suppresses
the line below it. Multiple rules: allow(rule-a, rule-b): reason.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass

from . import REPO_ROOT
from . import lint_config as cfg

RULES = {
    "sync-item": "blocking .item() device->host sync",
    "sync-tolist": "blocking .tolist() device->host sync",
    "sync-device-get": "blocking jax.device_get()",
    "sync-block": "block_until_ready outside the allowlist",
    "sync-asarray-hot": "numpy materialization inside a hot function",
    "sync-scalar-cast": "python scalar cast of a jitted-dispatch result",
    "hot-alloc": "numpy allocation inside a hot function",
    "recompile-jit-in-loop": "jax.jit constructed inside a loop",
    "recompile-const-upload":
        "jnp constant captured from outside the traced region",
    "suppression-missing-reason": "allow() without a reason",
    "stale-suppression": "suppression no longer matches any finding",
    "unbounded-compile-axis":
        "shape-derived jit argument outside a registered quantizer",
}

_NP_NAMES = frozenset({"np", "numpy"})
_JNP_NAMES = frozenset({"jnp"})

_ALLOW_RE = re.compile(
    r"#\s*pingoo:\s*allow\(([^)]*)\)(?:\s*:\s*(\S.*))?")


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class _Suppression:
    line: int  # line the comment sits on
    rules: tuple[str, ...]
    has_reason: bool
    used: bool = False

    def covers(self, line: int) -> bool:
        # Same line, or a standalone comment suppressing the line below.
        return line in (self.line, self.line + 1)


def _parse_suppressions(source: str) -> list[_Suppression]:
    out = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = _ALLOW_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        out.append(_Suppression(line=i, rules=rules,
                                has_reason=bool(m.group(2))))
    return out


def _attr_chain_root(node: ast.AST):
    """Root Name(s) feeding an expression — Attribute/Subscript chains,
    containers and comprehensions unwrap; Call results and literals are
    locally produced and yield nothing."""
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        yield from _attr_chain_root(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            yield from _attr_chain_root(elt)
    elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        for gen in node.generators:
            yield from _attr_chain_root(gen.iter)
    elif isinstance(node, ast.BinOp):
        yield from _attr_chain_root(node.left)
        yield from _attr_chain_root(node.right)
    elif isinstance(node, ast.UnaryOp):
        yield from _attr_chain_root(node.operand)


def _unquantized_shape_expr(node: ast.AST):
    """Depth-first hunt for a len()/.shape-derived subexpression that
    does NOT pass through a registered quantizer (cfg.SHAPE_QUANTIZERS)
    — a quantizer call makes its whole subtree admissible, because its
    output lands on a rung ladder by construction. Returns a short
    description of the raw source, or None."""
    if isinstance(node, ast.Call):
        f = node.func
        callee = f.attr if isinstance(f, ast.Attribute) \
            else getattr(f, "id", None)
        if callee in cfg.SHAPE_QUANTIZERS:
            return None
        if callee == "len":
            return "len()"
        for sub in list(node.args) + [kw.value for kw in node.keywords]:
            got = _unquantized_shape_expr(sub)
            if got:
                return got
        return None
    if isinstance(node, ast.Attribute) and node.attr == "shape":
        return ".shape"
    for child in ast.iter_child_nodes(node):
        got = _unquantized_shape_expr(child)
        if got:
            return got
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit") or (
        isinstance(node, ast.Name) and node.id == "jit")


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            return True  # @jax.jit(...)
        is_partial = (isinstance(dec.func, ast.Name)
                      and dec.func.id == "partial") or (
            isinstance(dec.func, ast.Attribute)
            and dec.func.attr == "partial")
        if is_partial and dec.args and _is_jit_expr(dec.args[0]):
            return True  # @partial(jax.jit, ...)
    return False


def _bound_names(fn: ast.AST) -> set[str]:
    """Every name bound anywhere inside `fn`: params, assignments, loop
    targets, comprehension targets, withitems, nested def/class names."""
    bound: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
            a = node.args
            for arg in (list(a.posonlyargs) + list(a.args)
                        + list(a.kwonlyargs)):
                bound.add(arg.arg)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
        elif isinstance(node, ast.ClassDef):
            bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    return bound


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self._scope: list[str] = []  # ClassDef/FunctionDef names
        self._hot_depth = 0
        self._traced_depth = 0
        self._loop_depth = 0
        self._trace_locals: set[str] | None = None
        self._device_names: list[set[str]] = []  # per function frame

    # -- helpers -------------------------------------------------------------

    def _qualname(self) -> str:
        return f"{self.path}::{'.'.join(self._scope)}"

    def _flag(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(self.path, node.lineno, rule, message))

    def _in_registry(self, registry) -> bool:
        return self._qualname() in registry

    # -- scope tracking ------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _visit_function(self, node) -> None:
        self._scope.append(node.name)
        qual = self._qualname()
        hot = qual in cfg.HOT_FUNCTIONS
        traced = (qual in cfg.TRACED_FUNCTIONS
                  or any(_is_jit_decorator(d) for d in node.decorator_list))
        self._hot_depth += hot
        entered_trace = traced and self._traced_depth == 0
        self._traced_depth += traced
        if entered_trace:
            self._trace_locals = _bound_names(node)
        loop_depth, self._loop_depth = self._loop_depth, 0
        self._device_names.append(set())
        self.generic_visit(node)
        self._device_names.pop()
        self._loop_depth = loop_depth
        if entered_trace:
            self._trace_locals = None
        self._traced_depth -= traced
        self._hot_depth -= hot
        self._scope.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _visit_loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_Assign(self, node: ast.Assign) -> None:
        # Dataflow-lite for sync-scalar-cast: names assigned from a
        # jitted dispatch call hold unmaterialized device values.
        if self._device_names and isinstance(node.value, ast.Call):
            f = node.value.func
            callee = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if callee in cfg.JITTED_DISPATCH_NAMES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._device_names[-1].add(tgt.id)
        self.generic_visit(node)

    # -- the rules -----------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "block_until_ready":
            self._check_block(node)
        self.generic_visit(node)

    def _check_block(self, node: ast.AST) -> None:
        scopes = {f"{self.path}::{'.'.join(self._scope[:i + 1])}"
                  for i in range(len(self._scope))}
        if not scopes & cfg.BLOCK_UNTIL_READY_ALLOW:
            self._flag(node, "sync-block",
                       "block_until_ready outside the allowlist "
                       "(BLOCK_UNTIL_READY_ALLOW) serializes the pipeline")

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        # getattr(x, "block_until_ready", ...) counts as a block ref.
        if (isinstance(f, ast.Name) and f.id == "getattr" and node.args
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value == "block_until_ready"):
            self._check_block(node)
        if isinstance(f, ast.Attribute):
            self._call_on_attribute(node, f)
        elif isinstance(f, ast.Name):
            self._call_on_name(node, f)
        callee = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if callee in cfg.JITTED_DISPATCH_NAMES:
            for arg in (list(node.args)
                        + [kw.value for kw in node.keywords]):
                raw = _unquantized_shape_expr(arg)
                if raw:
                    self._flag(
                        node, "unbounded-compile-axis",
                        f"{raw} flows into jitted dispatch {callee} "
                        "without a registered quantizer "
                        "(SHAPE_QUANTIZERS); every raw value is a "
                        "fresh XLA compile outside COMPILE_SURFACE")
                    break
        self.generic_visit(node)

    def _call_on_attribute(self, node: ast.Call, f: ast.Attribute) -> None:
        if f.attr == "item" and not node.args and not node.keywords:
            self._flag(node, "sync-item",
                       ".item() blocks on the device result; keep the "
                       "value as an array or sync once per batch")
        elif f.attr == "tolist" and not node.args:
            self._flag(node, "sync-tolist",
                       ".tolist() blocks and builds python objects per "
                       "element; slice the array instead")
        elif f.attr == "device_get":
            self._flag(node, "sync-device-get",
                       "jax.device_get() is a blocking transfer")
        elif f.attr == "jit" and self._loop_depth:
            self._flag(node, "recompile-jit-in-loop",
                       "jax.jit(...) inside a loop creates a fresh "
                       "compilation cache entry per iteration")
        root = f.value.id if isinstance(f.value, ast.Name) else None
        if root in _NP_NAMES and self._hot_depth:
            if f.attr in cfg.NP_MATERIALIZERS:
                self._flag(node, "sync-asarray-hot",
                           f"np.{f.attr} in hot function "
                           f"{'.'.join(self._scope)}: an implicit sync "
                           "on device input, a copy per batch on host "
                           "input")
            elif f.attr in cfg.NP_ALLOCATORS:
                self._flag(node, "hot-alloc",
                           f"np.{f.attr} allocates per call in hot "
                           f"function {'.'.join(self._scope)}; hoist or "
                           "reuse a scratch buffer")
        if (root in _JNP_NAMES and f.attr in ("asarray", "array")
                and self._traced_depth and self._trace_locals is not None
                and node.args):
            captured = [r for r in _attr_chain_root(node.args[0])
                        if r not in self._trace_locals
                        and r not in ("jnp", "np", "jax")]
            if captured:
                self._flag(node, "recompile-const-upload",
                           f"jnp.{f.attr}({', '.join(sorted(set(captured)))}"
                           ") captures a host constant inside the traced "
                           "region; hoist the device array out of the "
                           "jitted function")

    def _call_on_name(self, node: ast.Call, f: ast.Name) -> None:
        if f.id == "device_get":
            self._flag(node, "sync-device-get",
                       "device_get() is a blocking transfer")
        elif f.id == "jit" and self._loop_depth:
            self._flag(node, "recompile-jit-in-loop",
                       "jit(...) inside a loop creates a fresh "
                       "compilation cache entry per iteration")
        elif f.id in ("float", "int", "bool") and len(node.args) == 1:
            arg = node.args[0]
            is_dispatch_call = (
                isinstance(arg, ast.Call)
                and ((isinstance(arg.func, ast.Attribute)
                      and arg.func.attr in cfg.JITTED_DISPATCH_NAMES)
                     or (isinstance(arg.func, ast.Name)
                         and arg.func.id in cfg.JITTED_DISPATCH_NAMES)))
            is_device_name = (
                isinstance(arg, ast.Name) and self._device_names
                and arg.id in self._device_names[-1])
            if is_dispatch_call or is_device_name:
                self._flag(node, "sync-scalar-cast",
                           f"{f.id}() over a jitted-dispatch result "
                           "blocks per call; batch the sync instead")


def lint_source(source: str, path: str) -> tuple[list[Finding],
                                                 list[str]]:
    """Lint one file's source -> (unsuppressed findings, warnings).

    `path` is the repo-relative label used for registry lookups and
    reporting; it need not exist on disk (tests lint mutated copies)."""
    suppressions = _parse_suppressions(source)
    findings: list[Finding] = []
    for sup in suppressions:
        unknown = [r for r in sup.rules if r not in RULES]
        if unknown:
            findings.append(Finding(
                path, sup.line, "suppression-missing-reason",
                f"allow() names unknown rule(s): {', '.join(unknown)}"))
        if not sup.has_reason:
            findings.append(Finding(
                path, sup.line, "suppression-missing-reason",
                "suppression must carry a reason: "
                "# pingoo: allow(rule): why this is safe"))
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, "sync-item",
                        f"file does not parse: {exc.msg}")], []
    linter = _FileLinter(path)
    linter.visit(tree)

    kept: list[Finding] = []
    for finding in findings + linter.findings:
        suppressed = False
        if finding.rule != "suppression-missing-reason":
            for sup in suppressions:
                if (sup.has_reason and finding.rule in sup.rules
                        and sup.covers(finding.line)):
                    sup.used = True
                    suppressed = True
                    break
        if not suppressed:
            kept.append(finding)
    # A reasoned suppression that matched nothing is dead weight that
    # silently swallows the NEXT real finding on its line: a FINDING,
    # not a warning (and deliberately not itself suppressible). One
    # naming an unknown rule is already suppression-missing-reason —
    # "stale" would misdiagnose the typo as dead code.
    for sup in suppressions:
        if sup.has_reason and not sup.used \
                and all(r in RULES for r in sup.rules):
            kept.append(Finding(
                path, sup.line, "stale-suppression",
                f"allow({', '.join(sup.rules)}) no longer matches any "
                "finding; delete the suppression"))
    return kept, []


def iter_lint_files(repo_root: str = REPO_ROOT):
    for rel_dir in cfg.LINT_DIRS:
        base = os.path.join(repo_root, rel_dir)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in cfg.EXCLUDE_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_paths(paths=None, repo_root: str = REPO_ROOT):
    """Lint files (default: the configured dirs) ->
    (findings, warnings)."""
    findings: list[Finding] = []
    warnings: list[str] = []
    for full in (paths if paths is not None
                 else iter_lint_files(repo_root)):
        rel = os.path.relpath(full, repo_root)
        try:
            with open(full, encoding="utf-8") as fh:
                source = fh.read()
        except (UnicodeDecodeError, OSError):
            continue  # binary/cache noise is not source
        got, warn = lint_source(source, rel)
        findings += got
        warnings += warn
    return findings, warnings


def run(paths=None) -> int:
    findings, warnings = lint_paths(paths)
    for w in warnings:
        print(f"analyze-lint: warning: {w}", file=sys.stderr)
    if findings:
        print(f"analyze-lint: FAIL — {len(findings)} finding(s):",
              file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print("  (false positive? suppress inline with "
              "`# pingoo: allow(<rule>): <reason>` — the reason is "
              "mandatory; see docs/STATIC_ANALYSIS.md)", file=sys.stderr)
        return 1
    n = sum(1 for _ in iter_lint_files()) if paths is None else len(paths)
    print(f"analyze-lint: OK ({n} files, {len(RULES)} rules, "
          f"0 unsuppressed findings)")
    return 0
