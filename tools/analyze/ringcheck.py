"""Bounded exhaustive model checker for the v6 ring protocol.

The TSAN gate (tools/analyze/native.py) proves the ring's MEMORY model
— no data races under real thread interleavings.  This pass proves the
PROTOCOL logic: a faithful small Python model of the request/verdict
ring state machine (pingoo_tpu/native_ring.py + native/pingoo_ring.cc)
is explored over EVERY interleaving of its atomic actions up to a
configurable ticket/crash bound, and protocol properties are checked in
every reachable state:

  exactly-once     every enqueued ticket ends applied exactly once in
                   every quiescent state (no lost verdict, and the data
                   plane's unknown-ticket check makes duplicate posts
                   from crash-reattach reconciliation harmless)
  no-double-apply  applied count never exceeds 1 anywhere (invariant)
  floor-safety     every ticket below posted_floor has been posted —
                   the invariant _reconcile_orphans's orphan window
                   [max(posted_floor, tail - capacity), req_tail)
                   depends on (its docstring's "posted_floor only
                   advances once a part's verdicts are all posted")

Modeled actions: enqueue, bulk-drain (dequeue), verdict post,
posted-floor advance (the CAS), SIGKILL crash (in-flight knowledge
lost, shm survives), epoch bump + orphan reconcile on reattach
(re-posts the whole orphan window; duplicates are dropped downstream),
and the streaming body ring as a second small model (window enqueue /
scan / carry-losing crash / FINAL verdict) proving no body window is
ever lost SILENTLY: a FINAL verdict may be `clean` only when every
window was scanned on an unbroken carry chain (gap => degrade, the
ABORT/fail-open posture).  Heartbeat-freeze handling is subsumed by the
crash/reattach actions — the supervisor's response to a frozen
heartbeat is exactly a kill + reattach.

`mutate=` knobs deliberately break the model the way a regression in
the sidecar would, proving the checker bites (make prove runs the
broken-reclaim one as a self-test):

  floor_before_post   advance posted_floor to the consumed cursor
                      before the part's verdicts are posted — a crash
                      in the gap strands a drained ticket below the
                      reconcile window (lost verdict)
  silent_gap          the body FINAL verdict ignores a carry break and
                      reports clean over a torn scan
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class RingConfig:
    tickets: int = 3
    capacity: int = 4  # >= tickets: no slot recycling inside the bound
    max_crashes: int = 1
    mutate: Optional[str] = None  # None | 'floor_before_post'


@dataclass
class ModelResult:
    ok: bool
    states: int
    violations: list = field(default_factory=list)  # (property, trace)

    def describe(self) -> str:
        if self.ok:
            return f"{self.states} states, all properties hold"
        prop, trace = self.violations[0]
        return (f"{self.states} states, {len(self.violations)} "
                f"violation(s); first: {prop} after " + " -> ".join(trace))


# ---------------------------------------------------------------------------
# request/verdict ring model
#
# State (immutable, hashable):
#   tail      tickets enqueued so far
#   drained   consumer cursor (tickets dequeued by any epoch)
#   inflight  drained-but-unposted tickets the LIVE sidecar knows about
#   posts     per-ticket total posts (any epoch, incl. reconcile)
#   vring     per-ticket verdicts posted but not yet consumed downstream
#   pending   per-ticket: data plane still awaits a verdict
#   applied   per-ticket: verdicts the data plane accepted
#   floor     posted_floor
#   crashed   sidecar down (SIGKILL'd, not yet reattached)
#   crashes   crashes used


def _ring_actions(state: tuple, cfg: RingConfig):
    (tail, drained, inflight, posts, vring, pending, applied,
     floor, crashed, crashes) = state
    N = cfg.tickets

    if tail < N and (tail < cfg.capacity or tail - cfg.capacity < drained):
        t = tail
        yield (f"enqueue({t})", (
            tail + 1, drained, inflight, posts, vring,
            pending[:t] + (1,) + pending[t + 1:], applied,
            floor, crashed, crashes))

    if not crashed and drained < tail:
        t = drained
        yield (f"drain({t})", (
            tail, drained + 1, tuple(sorted(set(inflight) | {t})), posts,
            vring, pending, applied, floor, crashed, crashes))

    if not crashed:
        for t in inflight:
            yield (f"post({t})", (
                tail, drained, tuple(x for x in inflight if x != t),
                posts[:t] + (posts[t] + 1,) + posts[t + 1:],
                vring[:t] + (vring[t] + 1,) + vring[t + 1:],
                pending, applied, floor, crashed, crashes))

    if not crashed:
        if cfg.mutate == "floor_before_post":
            f2 = drained  # BROKEN: floor covers drained-but-unposted
        else:
            f2 = floor
            while f2 < drained and posts[f2] >= 1:
                f2 += 1
        if f2 > floor:
            yield (f"floor->{f2}", (
                tail, drained, inflight, posts, vring, pending, applied,
                f2, crashed, crashes))

    for t in range(cfg.tickets):
        if vring[t] > 0:
            dup = not pending[t]
            yield ((f"apply({t})" if not dup else f"drop-dup({t})"), (
                tail, drained, inflight, posts,
                vring[:t] + (vring[t] - 1,) + vring[t + 1:],
                pending if dup else pending[:t] + (0,) + pending[t + 1:],
                applied if dup else
                applied[:t] + (applied[t] + 1,) + applied[t + 1:],
                floor, crashed, crashes))

    if not crashed and crashes < cfg.max_crashes:
        yield ("SIGKILL", (
            tail, drained, (), posts, vring, pending, applied,
            floor, True, crashes + 1))

    if crashed:
        # epoch bump + _reconcile_orphans: re-post EVERY ticket in
        # [max(floor, tail - capacity), tail), then floor = tail.
        p2, v2 = list(posts), list(vring)
        for t in range(max(floor, tail - cfg.capacity), tail):
            p2[t] += 1
            v2[t] += 1
        yield ("reattach+reconcile", (
            tail, drained, (), tuple(p2), tuple(v2), pending, applied,
            tail, False, crashes))


def _check_ring_state(state: tuple, cfg: RingConfig,
                      quiescent: bool) -> list[str]:
    (tail, drained, inflight, posts, vring, pending, applied,
     floor, crashed, crashes) = state
    bad = []
    for t in range(cfg.tickets):
        if applied[t] > 1:
            bad.append(f"no-double-apply: ticket {t} applied {applied[t]}x")
    for t in range(floor):
        if t < cfg.tickets and posts[t] < 1 and not crashed:
            bad.append(f"floor-safety: floor={floor} covers unposted "
                       f"ticket {t}")
    if quiescent and not crashed:
        for t in range(tail):
            if applied[t] != 1:
                bad.append(f"exactly-once: ticket {t} applied "
                           f"{applied[t]}x at quiescence")
    return bad


def check_ring(cfg: RingConfig | None = None) -> ModelResult:
    """Exhaustive BFS over every interleaving up to the config bound."""
    cfg = cfg or RingConfig()
    N = cfg.tickets
    zeros = (0,) * N
    init = (0, 0, (), zeros, zeros, zeros, zeros, 0, False, 0)
    seen = {init: ()}
    frontier = [init]
    violations = []
    while frontier:
        nxt = []
        for state in frontier:
            trace = seen[state]
            succ = list(_ring_actions(state, cfg))
            quiescent = all(name == "SIGKILL" for name, _ in succ)
            for prop in _check_ring_state(state, cfg, quiescent):
                violations.append((prop, trace))
                if len(violations) >= 8:
                    return ModelResult(False, len(seen), violations)
            for name, s2 in succ:
                if s2 not in seen:
                    seen[s2] = trace + (name,)
                    nxt.append(s2)
        frontier = nxt
    return ModelResult(not violations, len(seen), violations)


# ---------------------------------------------------------------------------
# body ring model
#
# State: (enq, scanned, final_enq, lost, verdict, crashes)
#   enq      windows enqueued (0..windows)
#   scanned  windows consumed by the scanner on the current carry chain
#   final_enq  FINAL marker enqueued
#   lost     a crash broke the carry chain mid-flow (windows consumed
#            before the crash cannot be re-scanned — their bytes left
#            the ring)
#   verdict  None | 'clean' | 'degraded'


@dataclass(frozen=True)
class BodyConfig:
    windows: int = 3
    max_crashes: int = 1
    mutate: Optional[str] = None  # None | 'silent_gap'


def _body_actions(state: tuple, cfg: BodyConfig):
    enq, scanned, final_enq, lost, verdict, crashes = state
    if verdict is not None:
        return
    if enq < cfg.windows:
        yield ("enqueue", (enq + 1, scanned, final_enq, lost, verdict,
                           crashes))
    if enq == cfg.windows and not final_enq:
        yield ("FINAL", (enq, scanned, True, lost, verdict, crashes))
    if scanned < enq:
        yield ("scan", (enq, scanned + 1, final_enq, lost, verdict,
                        crashes))
    if crashes < cfg.max_crashes:
        # SIGKILL mid-flow: the carry (and any scanned windows' bytes)
        # are gone; scanning a partially-scanned flow can never be made
        # whole again, which the reattached sidecar must record.
        yield ("SIGKILL", (enq, scanned, final_enq,
                           lost or scanned > 0, verdict, crashes + 1))
    if final_enq and (scanned == cfg.windows or lost):
        if cfg.mutate == "silent_gap":
            v = "clean"  # BROKEN: ignores the carry break
        else:
            v = "degraded" if lost else "clean"
        yield ("verdict", (enq, scanned, final_enq, lost, v, crashes))


def check_body(cfg: BodyConfig | None = None) -> ModelResult:
    cfg = cfg or BodyConfig()
    init = (0, 0, False, False, None, 0)
    seen = {init: ()}
    frontier = [init]
    violations = []
    while frontier:
        nxt = []
        for state in frontier:
            enq, scanned, final_enq, lost, verdict, crashes = state
            if verdict == "clean" and (lost or scanned != cfg.windows):
                violations.append((
                    f"no-lost-window: clean verdict with scanned="
                    f"{scanned}/{cfg.windows} lost={lost}", seen[state]))
                if len(violations) >= 8:
                    return ModelResult(False, len(seen), violations)
            for name, s2 in _body_actions(state, cfg):
                if s2 not in seen:
                    seen[s2] = seen[state] + (name,)
                    nxt.append(s2)
        frontier = nxt
    return ModelResult(not violations, len(seen), violations)


# ---------------------------------------------------------------------------


def run(tickets: int = 3, max_crashes: int = 2,
        mutate: Optional[str] = None, quiet: bool = False) -> int:
    """Model-check the ring + body protocols; 0 = all properties hold."""
    rc = 0
    ring = check_ring(RingConfig(tickets=tickets, max_crashes=max_crashes,
                                 mutate=mutate))
    body = check_body(BodyConfig(windows=tickets,
                                 max_crashes=max_crashes, mutate=mutate))
    for name, res in (("ring", ring), ("body", body)):
        if not quiet or not res.ok:
            print(f"ringcheck[{name}]: "
                  f"{'OK' if res.ok else 'FAIL'} — {res.describe()}")
        rc |= 0 if res.ok else 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tickets", type=int, default=3)
    ap.add_argument("--max-crashes", type=int, default=2)
    ap.add_argument("--mutate", default=None,
                    choices=["floor_before_post", "silent_gap"])
    args = ap.parse_args(argv)
    return run(args.tickets, args.max_crashes, args.mutate)


if __name__ == "__main__":
    raise SystemExit(main())
