"""pingoo-prove: machine-checked lowering soundness (make prove).

One offline-safe entry point over the three ISSUE-18 pillars
(docs/STATIC_ANALYSIS.md "Prove"):

  plan proof      compiler/obligations.py discharges every lowering
                  obligation on the deterministic 500-rule CRS seed
                  plan (prefilter necessity, approximate-DFA
                  containment + exactness, staging caps, footprint
                  extension) and on the streaming body plan (table
                  reconstruction, tail cap, lazy gate, cross-window
                  carry closure). These are the SAME checks the
                  artifact cache runs at compile time (cache.py v12);
                  running them here proves the prover itself still
                  discharges on the seed corpus in bounded wall time.
  compile surface surface.py re-walks the jit entry points, refreshes
                  the committed COMPILE_SURFACE.json, and cross-checks
                  its jax-free K-rung mirror against the live
                  engine ladder (megastep_k_ladder(megastep_k_cap())).
  ring protocol   ringcheck.py explores every interleaving of the ring
                  + body models up to the bound; all properties hold.

Mutation self-tests (on by default; --skip-mutations): five deliberate
regressions must each FAIL their checker, proving the gates bite —
a weakened prefilter factor, approximate DFA tables flipped to
exact=True, a narrowed staging cap, an unquantized jit argument
(lint unbounded-compile-axis), and a broken reclaim ordering
(ringcheck floor_before_post) plus the body silent-gap twin.

Offline-safe: when jax is unavailable the pass SKIPS WITH A WARNING
(exit 0) — the plan proof needs the compiler stack, and the surface /
ring pillars alone would be a green that proved the wrong thing.

`--history` appends prove_wall_s to BENCH_history.jsonl under
backend="prove-<jax backend>" so tools/bench_regress.py tracks the
proof budget like any other measured cost.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import os
import time

from . import REPO_ROOT, note_skip, ringcheck, surface


def _check(ok: bool, what: str, failures: list) -> None:
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        failures.append(what)


def _mutation_weakened_factor(plan, ob):
    """Append a bogus 'ZZZ' necessary factor and repoint a gated slot
    at it: the necessity proof must find an accepting run that never
    completes the factor (and the mask recompute must disagree)."""
    mplan = copy.copy(plan)
    pf = copy.deepcopy(plan.prefilter)
    key = next(k for k, cs in pf.slot_codes.items()
               if any(c >= 0 for c in cs) and "@" not in k)
    field = pf.bank_field[key]
    ff = pf.fields[field]
    bogus = (frozenset({0x5A}),) * 3  # "ZZZ"
    pf.fields[field] = dataclasses.replace(
        ff, num_factors=ff.num_factors + 1, factors=ff.factors + (bogus,))
    codes = list(pf.slot_codes[key])
    codes[next(i for i, c in enumerate(codes) if c >= 0)] = ff.num_factors
    pf.slot_codes = dict(pf.slot_codes)
    pf.slot_codes[key] = tuple(codes)
    mplan.prefilter = pf
    return not ob.prove_plan(mplan).ok


def _mutation_approx_as_exact(plan, ob):
    """Flip a REAL approximate (budget-merged) DFA bank to exact=True:
    the post-fixpoint exactness pass must catch the merged subset
    masks. Returns None when the seed plan has no approximate bank
    (it does — treat that as a failure upstream, the self-test would
    be vacuous)."""
    banks, _ = ob.bank_source_patterns(plan)
    for key, entry in plan.scan_plans.items():
        if not entry.dfa_key:
            continue
        t = plan.np_tables[entry.dfa_key]
        if not bool(t.exact):
            lied = dataclasses.replace(t, exact=True)
            return bool(ob.check_dfa_containment(banks[key], lied))
    return None


def _mutation_narrowed_cap(plan, ob):
    m2 = copy.copy(plan)
    m2.staging_caps = dict(plan.staging_caps)
    f = next(f for f, c in m2.staging_caps.items() if c > 16)
    m2.staging_caps[f] = 16 if plan.staging_required[f] > 16 else 8
    return not ob.prove_plan(m2).ok


def _mutation_unquantized_arg():
    from . import lint
    src = ("class S:\n"
           "    def go(self, data, x):\n"
           "        return self._verdict_fn(data, len(x))\n")
    findings, _ = lint.lint_source(src, "pingoo_tpu/engine/service.py")
    return any(f.rule == "unbounded-compile-axis" for f in findings)


def _append_history(wall_s: float, backend: str) -> None:
    """Mirror bench.py _append_history's schema-2 stamping; the
    backend is namespaced so prove runs only compare to prove runs."""
    path = os.environ.get("BENCH_HISTORY_FILE",
                          os.path.join(REPO_ROOT, "BENCH_history.jsonl"))
    entry = {"ts": round(time.time(), 3), "history_schema": 2,
             "backend": f"prove-{backend}",
             "prove_wall_s": round(wall_s, 3)}
    try:
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass  # read-only tree must not fail a finished prove


def run(history: bool = False, mutations: bool = True) -> int:
    t_start = time.perf_counter()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
    except Exception as exc:
        note_skip("prove", "jax unavailable")
        print(f"analyze-prove: SKIP — jax unavailable ({exc!r}); the "
              "lowering obligations need the compiler stack (tier-1 "
              "stays green; run in the jax container for the full "
              "gate)")
        return 0

    from pingoo_tpu.compiler import obligations as ob
    from pingoo_tpu.compiler.plan import compile_ruleset
    from pingoo_tpu.engine.bodyscan import compile_body_plan
    from pingoo_tpu.engine.verdict import megastep_k_cap, \
        megastep_k_ladder
    from pingoo_tpu.utils import crs

    failures: list = []

    # -- pillar 1: plan proofs on the seed corpus ----------------------
    rules, lists = crs.generate_ruleset(500)
    plan = compile_ruleset(rules, lists)
    t0 = time.perf_counter()
    proof = ob.prove_plan(plan)
    plan_s = time.perf_counter() - t0
    counts = proof.counts()
    _check(proof.ok,
           f"seed 500-rule plan: {counts.get('proved', 0)} obligations "
           f"proved in {plan_s:.2f}s "
           + (f"(failures: {[o.name for o in proof.failures()][:3]})"
              if not proof.ok else ""), failures)

    bplan = compile_body_plan()
    bproof = ob.prove_body_plan(bplan)
    _check(bproof.ok,
           f"body plan: {bproof.counts().get('proved', 0)} obligations "
           f"proved (windowed carry closure over every seam)"
           + (f" FAILURES {[o.name for o in bproof.failures()][:3]}"
              if not bproof.ok else ""), failures)

    # -- pillar 2: compile surface -------------------------------------
    try:
        surf = surface.build_surface()
        surface.write_surface(surf)
        _check(True, f"compile surface: "
                     f"{len(surf['entry_points'])} entry points all "
                     f"registered -> COMPILE_SURFACE.json", failures)
    except ValueError as exc:
        _check(False, f"compile surface: {exc}", failures)
        surf = None
    if surf is not None:
        live = megastep_k_ladder(megastep_k_cap())
        _check(list(surf["k_rungs"]) == list(live),
               f"surface K rungs match the live engine ladder "
               f"({surf['k_rungs']} vs {live})", failures)

    # -- pillar 3: ring-protocol model checker -------------------------
    _check(ringcheck.run(quiet=True) == 0,
           "ring + body protocol models: all properties hold over "
           "every interleaving", failures)

    # -- mutation self-tests: every checker must bite ------------------
    if mutations:
        _check(_mutation_weakened_factor(plan, ob),
               "mutation: weakened prefilter factor refused", failures)
        got = _mutation_approx_as_exact(plan, ob)
        _check(bool(got),
               "mutation: approximate DFA flipped exact=True refused"
               + ("" if got is not None
                  else " (NO approximate bank in seed plan — "
                       "self-test vacuous)"), failures)
        _check(_mutation_narrowed_cap(plan, ob),
               "mutation: narrowed staging cap refused", failures)
        _check(_mutation_unquantized_arg(),
               "mutation: unquantized jit argument flagged "
               "(unbounded-compile-axis)", failures)
        _check(ringcheck.run(mutate="floor_before_post",
                             quiet=True) != 0,
               "mutation: broken reclaim ordering caught by the model "
               "checker", failures)
        _check(ringcheck.run(mutate="silent_gap", quiet=True) != 0,
               "mutation: silent body-scan gap caught by the model "
               "checker", failures)

    wall_s = time.perf_counter() - t_start
    if history:
        _append_history(wall_s, jax.default_backend())
    if failures:
        print(f"analyze-prove: FAIL — {len(failures)} problem(s) in "
              f"{wall_s:.2f}s")
        return 1
    print(f"analyze-prove: OK ({wall_s:.2f}s wall; plan proof "
          f"{plan_s:.2f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(run())
