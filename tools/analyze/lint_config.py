"""Configuration for the JAX hot-path linter (tools/analyze/lint.py).

Registries are repo-relative `path::qualname` strings; a method's
qualname is `Class.method`, nested functions join with dots
(`outer.inner`). docs/STATIC_ANALYSIS.md documents how to extend them.
"""

# Directories the linter walks (repo-relative). These hold the code
# that runs per batch on the serving path; host/ and the offline
# tooling are deliberately out of scope.
LINT_DIRS = (
    "pingoo_tpu/engine",
    "pingoo_tpu/ops",
    "pingoo_tpu/compiler",
    # The provenance layer (ISSUE 5) folds device aux lanes per batch;
    # its hot functions are registered below so a bare host-device sync
    # there fails `make analyze`.
    "pingoo_tpu/obs",
    # The admission scheduler + mesh executor (ISSUE 6) sit between
    # the queues and the compiled programs on every batch.
    "pingoo_tpu/sched",
)

# Never descend into these directory names, and never read non-.py
# files: caches and build outputs are not source (ISSUE 3 satellite —
# grep-based tools must not trip over __pycache__/ or binaries).
EXCLUDE_DIRS = frozenset({
    "__pycache__", ".git", ".pytest_cache", "build", "dist",
    ".mypy_cache", ".ruff_cache", "node_modules",
})

# Functions REGISTERED AS HOT: they run per batch with the request
# latency budget on the line, so host-device syncs (sync-asarray-hot)
# and fresh numpy allocations (hot-alloc) inside them must be either
# eliminated or individually justified with an inline suppression.
HOT_FUNCTIONS = frozenset({
    "pingoo_tpu/engine/service.py::VerdictService._evaluate_sync",
    "pingoo_tpu/engine/service.py::VerdictService._evaluate_with_scores",
    "pingoo_tpu/engine/service.py::VerdictService._run_batch",
    "pingoo_tpu/engine/service.py::VerdictService._observe_prefilter",
    # Bitsplit-DFA dispatch accounting (ISSUE 8): host-static counter
    # folds per batch — pure int math, no arrays, never a device sync.
    "pingoo_tpu/engine/service.py::VerdictService._observe_dfa",
    "pingoo_tpu/engine/verdict.py::finish_batch",
    "pingoo_tpu/engine/verdict.py::merge_lanes",
    # Verdict provenance (ISSUE 5): the attribution fold runs per batch
    # on the collector/drain path (the one sanctioned materialization of
    # the device aux lane is suppressed inline), and the parity
    # sampler's submit side must stay a pure sampling-decision +
    # queue-put — the interpreter re-evaluation belongs on the audit
    # worker thread, never the dispatch hot path.
    "pingoo_tpu/engine/service.py::VerdictService._observe_provenance",
    "pingoo_tpu/obs/provenance.py::RuleAttribution.fold_batch",
    "pingoo_tpu/obs/provenance.py::ParityAuditor.submit_matrix",
    "pingoo_tpu/obs/provenance.py::ParityAuditor.submit_lanes",
    "pingoo_tpu/obs/flightrecorder.py::FlightRecorder.record",
    # Continuous-batching scheduler (ISSUE 6): the launch policy and
    # the EWMA cost update run per batch on the collector/drain
    # threads between dispatch and resolve — pure float math, no
    # arrays, and NEVER a host-device sync. The mesh executor's batch
    # placement runs per batch too: async device_put issues only.
    "pingoo_tpu/sched/scheduler.py::Scheduler.wait_budget_s",
    "pingoo_tpu/sched/scheduler.py::Scheduler.should_launch",
    "pingoo_tpu/sched/scheduler.py::Scheduler.note_launch",
    "pingoo_tpu/sched/scheduler.py::CostModel.observe",
    "pingoo_tpu/sched/scheduler.py::CostModel.estimate",
    "pingoo_tpu/sched/mesh_exec.py::MeshExecutor.shard_batch",
    # Zero-copy pipelined executor (ISSUE 9): the staging encoders run
    # per batch under the encode token — they must FILL the reused
    # buffers, never allocate fresh ones; the per-stage budget check
    # and the stage cost/telemetry feeds are pure float math between
    # dispatch and resolve.
    "pingoo_tpu/engine/batch.py::StagingEncoder.encode_requests",
    "pingoo_tpu/engine/batch.py::StagingEncoder.encode_slots",
    "pingoo_tpu/engine/service.py::VerdictService._check_stage_budget",
    "pingoo_tpu/sched/scheduler.py::CostModel.observe_stage",
    "pingoo_tpu/sched/scheduler.py::CostModel.estimate_stage",
    "pingoo_tpu/sched/scheduler.py::Scheduler.observe_stage_cost",
    "pingoo_tpu/obs/pipeline.py::PipelineStats.note_stage",
    # Device-resident megastep (ISSUE 12): the double-buffered input
    # queue's fill runs per slice on the drain path (strided copies
    # into REUSED host stacks, never fresh allocations), device_stack
    # issues the ASYNC device_put copy for the next buffer while the
    # current megastep computes (it must never sync), the per-slice
    # resolve unpacks one already-synced numpy stack, and the megastep
    # cost EWMAs are pure float math on the admission path.
    "pingoo_tpu/engine/batch.py::DeviceInputQueue.fill_slice",
    "pingoo_tpu/engine/batch.py::DeviceInputQueue.device_stack",
    # Compact staging (ISSUE 15): the packed encoders fill the single
    # reused [B, width] staging buffer per batch (one strided copy per
    # field into REUSED memory, never a fresh matrix), and the meta
    # tail pack is pure byte stores into the same buffer.
    "pingoo_tpu/engine/batch.py::StagingEncoder._encode_requests_packed",
    "pingoo_tpu/engine/batch.py::StagingEncoder._encode_slots_packed",
    "pingoo_tpu/engine/batch.py::StagingEncoder._pack_meta",
    "pingoo_tpu/engine/verdict.py::finish_megastep",
    "pingoo_tpu/engine/service.py::VerdictService._evaluate_megastep",
    "pingoo_tpu/sched/scheduler.py::CostModel.observe_megastep",
    "pingoo_tpu/sched/scheduler.py::CostModel.estimate_megastep",
    "pingoo_tpu/obs/pipeline.py::PipelineStats.note_megastep",
    # Perf ledger + timeline (ISSUE 17): the compile probe wraps EVERY
    # jitted dispatch (two O(1) cache-size calls per invocation; event
    # assembly only on the rare compile branch), the stride sampler is
    # one float add+compare per batch, and the span-record methods are
    # pure float math over already-host stage numbers into a bounded
    # deque — no arrays, never a device sync.
    "pingoo_tpu/obs/perf.py::_InstrumentedJit.__call__",
    "pingoo_tpu/obs/timeline.py::Timeline.sample",
    "pingoo_tpu/obs/timeline.py::Timeline.add_span",
    "pingoo_tpu/obs/timeline.py::Timeline.batch_python",
    "pingoo_tpu/obs/timeline.py::Timeline.batch_sidecar",
})

# Functions traced by jax.jit that the AST cannot see are jitted (they
# are CALLED from a jit-decorated function rather than decorated
# themselves). Their bodies execute at trace time: jnp.asarray of a
# captured host constant there is re-staged on every retrace
# (recompile-const-upload). Nested defs inherit traced-ness.
TRACED_FUNCTIONS = frozenset({
    "pingoo_tpu/engine/verdict.py::_matched_cols",
    "pingoo_tpu/engine/verdict.py::_eval_leaves",
    "pingoo_tpu/engine/verdict.py::_eval_bool",
    "pingoo_tpu/engine/verdict.py::_eval_num",
    # Stage-A prefilter kernel (ISSUE 4): traced per batch from the
    # verdict/lane programs and from make_prefilter_fn.
    "pingoo_tpu/ops/prefilter.py::prefilter_scan",
    "pingoo_tpu/ops/prefilter.py::_fused_prefilter",
    # Bitsplit-DFA byte ladder (ISSUE 8): traced from the verdict
    # program's bank dispatch (engine/verdict run_packed_scans).
    "pingoo_tpu/ops/bitsplit_dfa.py::dfa_scan",
    "pingoo_tpu/ops/bitsplit_dfa.py::_fused_dfa",
    # Device-resident megastep driver (ISSUE 12): the K-slice lax.scan
    # body and its per-slice step execute at trace time from
    # make_megastep_fn's jit — captured host constants there re-stage
    # on every retrace.
    "pingoo_tpu/engine/verdict.py::make_megastep_fn.slice_step",
    "pingoo_tpu/engine/verdict.py::make_megastep_fn.megastep",
})

# The explicit blessing list for block_until_ready: the ONE deliberate
# device sync point per plane. Everything else must go through these.
# (_await_device is the shared wait primitive finish_batch /
# finish_megastep route their single sanctioned sync through.)
BLOCK_UNTIL_READY_ALLOW = frozenset({
    "pingoo_tpu/engine/verdict.py::_await_device",
})

# Attribute/function names that hold jitted dispatch callables: casting
# their result to a Python scalar (float()/int()/bool()) forces a
# blocking device round-trip per call (sync-scalar-cast).
JITTED_DISPATCH_NAMES = frozenset({
    "_verdict_fn", "_score_fn", "_lane_fn", "_pf_fn", "verdict_fn",
    "lane_fn", "_mega_fn", "mega_fn",
})

# Registered shape quantizers (unbounded-compile-axis): the ONLY
# sanctioned routes from a raw size (len(x), arr.shape) to a jitted
# dispatch argument. Each lands its input on a closed rung ladder, so
# the reachable compile set stays inside the statically-proved
# COMPILE_SURFACE.json bound (tools/analyze/surface.py).
SHAPE_QUANTIZERS = frozenset({
    "pow2_batch_size",   # engine/batch.py: pow2 batch ladder, floor 8
    "bucket_len",        # engine/batch.py: field-axis length buckets
    "bucket_arrays",     # engine/batch.py: bucket every field axis
    "pad_batch",         # engine/batch.py: pad batch axis to a rung
    "quantize_stage_cap",  # compiler/plan.py: staging-width rungs
    "megastep_k_ladder",   # engine/verdict.py: pow2 megastep K rungs
    "_pow2_size",        # service wrapper over pow2_batch_size
})

# numpy allocators flagged inside hot functions (hot-alloc).
NP_ALLOCATORS = frozenset({
    "zeros", "ones", "empty", "full", "zeros_like", "ones_like",
    "empty_like", "full_like", "concatenate", "stack", "vstack",
    "hstack", "tile", "repeat",
})

# numpy materializers that force a device->host copy when handed a jax
# array (sync-asarray-hot, flagged inside hot functions).
NP_MATERIALIZERS = frozenset({"asarray", "array", "ascontiguousarray"})
