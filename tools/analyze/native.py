"""Native-plane analysis passes: TSAN concurrency gate + clang-tidy.

Both passes degrade to skip-with-warning (exit 0) when the toolchain is
missing so `make check` stays green in minimal containers — but they
HARD-FAIL when the toolchain exists and finds something.

tsan   builds pingoo_tpu/native/ring_stress.cc with -fsanitize=thread
       (`make -C pingoo_tpu/native tsan`) and runs it with halt-on-
       error. The stress hammers the Vyukov rings AND the v4 telemetry
       atomics (depth HWM CAS-max, wrap-around, full-ring stalls,
       concurrent snapshot scrapes) and self-checks counter identities.

tidy   runs clang-tidy (bugprone-*, concurrency-*, .clang-tidy at the
       repo root) over native/*.cc and diffs normalized findings
       against the tracked suppression file
       tools/analyze/tidy_baseline.txt: new findings fail; entries in
       the baseline are accepted tech-debt with a recorded reason.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sys
import tempfile

from . import REPO_ROOT, note_skip

NATIVE_DIR = os.path.join(REPO_ROOT, "pingoo_tpu", "native")
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tidy_baseline.txt")
TIDY_SOURCES = ("pingoo_ring.cc", "ring_stress.cc", "drain.cc",
                "loadgen.cc", "loadgen_http.cc", "pong.cc")

TSAN_EXIT_CODE = 66  # distinct from the stress's own abort()s


def _toolchain_supports_tsan() -> str | None:
    """Compiler name if it can link -fsanitize=thread, else None."""
    cxx = os.environ.get("CXX") or "g++"
    if not shutil.which(cxx):
        return None
    with tempfile.TemporaryDirectory(prefix="pingoo-tsan-") as tmp:
        src = os.path.join(tmp, "probe.cc")
        with open(src, "w") as f:
            f.write("int main() { return 0; }\n")
        probe = subprocess.run(
            [cxx, "-fsanitize=thread", "-pthread", "-o",
             os.path.join(tmp, "probe"), src],
            capture_output=True)
    return cxx if probe.returncode == 0 else None


def run_tsan() -> int:
    if _toolchain_supports_tsan() is None:
        note_skip("tsan", "toolchain cannot build -fsanitize=thread "
                          "binaries")
        print("analyze-tsan: SKIP — toolchain cannot build "
              "-fsanitize=thread binaries (tier-1 stays green; run in "
              "the dev container for the full gate)", file=sys.stderr)
        return 0
    build = subprocess.run(["make", "-C", NATIVE_DIR, "tsan"],
                           capture_output=True)
    if build.returncode != 0:
        print("analyze-tsan: FAIL — tsan build broke:\n"
              f"{build.stderr.decode(errors='replace')[-2000:]}",
              file=sys.stderr)
        return 1
    env = dict(os.environ)
    env["TSAN_OPTIONS"] = (env.get("TSAN_OPTIONS", "") +
                           f" halt_on_error=1 exitcode={TSAN_EXIT_CODE}"
                           ).strip()
    proc = subprocess.run([os.path.join(NATIVE_DIR, "ring_stress_tsan")],
                          capture_output=True, env=env, timeout=600)
    sys.stdout.write(proc.stdout.decode(errors="replace"))
    if proc.returncode != 0:
        kind = ("TSAN report" if proc.returncode == TSAN_EXIT_CODE
                else f"self-check failure (exit {proc.returncode})")
        print(f"analyze-tsan: FAIL — {kind}:\n"
              f"{proc.stderr.decode(errors='replace')[-4000:]}",
              file=sys.stderr)
        return 1
    print("analyze-tsan: OK (ring_stress_tsan clean: MPMC rings, "
          "telemetry atomics, wrap-around, full-ring stalls)")
    return 0


# -- clang-tidy ----------------------------------------------------------

_TIDY_LINE = re.compile(
    r"^(?P<path>[^:\s][^:]*):(?P<line>\d+):\d+:\s+"
    r"(?:warning|error):\s+(?P<msg>.*?)\s+\[(?P<check>[^\]]+)\]\s*$")


def normalize_tidy_output(text: str, repo_root: str = REPO_ROOT
                          ) -> list[str]:
    """clang-tidy stdout -> sorted unique `file:check: message` keys.
    Line numbers are dropped so unrelated edits don't churn the
    baseline; system-header noise has no repo-relative path and is
    dropped too."""
    keys = set()
    for raw in text.splitlines():
        m = _TIDY_LINE.match(raw.strip())
        if not m:
            continue
        path = m.group("path")
        if os.path.isabs(path):
            try:
                rel = os.path.relpath(path, repo_root)
            except ValueError:
                continue
            if rel.startswith(".."):
                continue  # outside the repo: system headers
            path = rel
        keys.add(f"{path}:{m.group('check')}: {m.group('msg')}")
    return sorted(keys)


def load_baseline(path: str = BASELINE_PATH) -> list[str]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(line)
    return out


def write_baseline(findings: list[str], path: str = BASELINE_PATH
                   ) -> None:
    """`tidy --regen`: rewrite the baseline from the current findings.
    Regenerated entries carry a TODO reason — the contract is that each
    accepted line gets a real `# reason` comment before it lands."""
    with open(path, "w") as f:
        f.write("# clang-tidy accepted-findings baseline "
                "(tools/analyze/native.py run_tidy).\n"
                "# One normalized `file:check: message` key per line; "
                "new findings not listed here fail make analyze.\n"
                "# Regenerate with: python -m tools.analyze tidy "
                "--regen\n")
        for key in findings:
            f.write("# TODO: record why this finding is accepted\n")
            f.write(key + "\n")


def diff_against_baseline(findings: list[str], baseline: list[str]
                          ) -> tuple[list[str], list[str]]:
    """-> (new findings not in the baseline, stale baseline entries)."""
    fset, bset = set(findings), set(baseline)
    return sorted(fset - bset), sorted(bset - fset)


def run_tidy(regen: bool = False) -> int:
    tidy = shutil.which("clang-tidy")
    if tidy is None:
        note_skip("tidy", "clang-tidy not installed")
        print("analyze-tidy: SKIP — clang-tidy not installed (tier-1 "
              "stays green; run in a container with clang-tools for "
              "the full gate)", file=sys.stderr)
        return 0
    sources = [os.path.join(NATIVE_DIR, s) for s in TIDY_SOURCES
               if os.path.exists(os.path.join(NATIVE_DIR, s))]
    proc = subprocess.run(
        [tidy, "--quiet", *sources, "--", "-std=c++17", "-I", NATIVE_DIR],
        capture_output=True, cwd=REPO_ROOT, timeout=900)
    findings = normalize_tidy_output(proc.stdout.decode(errors="replace"))
    if regen:
        write_baseline(findings)
        print(f"analyze-tidy: baseline regenerated "
              f"({len(findings)} finding(s) -> "
              f"{os.path.relpath(BASELINE_PATH, REPO_ROOT)})")
        return 0
    fresh, stale = diff_against_baseline(findings, load_baseline())
    for s in stale:
        print(f"analyze-tidy: warning: stale baseline entry (fixed? "
              f"remove it): {s}", file=sys.stderr)
    if fresh:
        print(f"analyze-tidy: FAIL — {len(fresh)} new finding(s) not in "
              f"tools/analyze/tidy_baseline.txt:", file=sys.stderr)
        for f in fresh:
            print(f"  {f}", file=sys.stderr)
        print("  (real but accepted? add the line to the baseline WITH "
              "a trailing `# reason` comment above it)", file=sys.stderr)
        return 1
    print(f"analyze-tidy: OK ({len(sources)} sources, "
          f"{len(findings)} finding(s), all baselined)")
    return 0
