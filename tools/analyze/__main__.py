"""CLI for the pingoo-analyze suite.

    python -m tools.analyze all            # every pass (make analyze)
    python -m tools.analyze abi [--regen]  # cross-plane ABI checker
    python -m tools.analyze lint [files…]  # JAX hot-path linter
    python -m tools.analyze tidy           # clang-tidy vs baseline
    python -m tools.analyze tsan           # ring_stress concurrency gate
    python -m tools.analyze fuzz           # differential parsing fuzzer

Passes are offline-safe; missing toolchains (C++ compiler, clang-tidy,
TSAN runtime) downgrade the affected pass to skip-with-warning.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m tools.analyze")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_abi = sub.add_parser("abi", help="cross-plane ABI/layout checker")
    p_abi.add_argument("--regen", action="store_true",
                       help="regenerate abi_golden.json from the header")
    p_lint = sub.add_parser("lint", help="JAX hot-path linter")
    p_lint.add_argument("files", nargs="*",
                        help="files to lint (default: configured dirs)")
    sub.add_parser("tidy", help="clang-tidy (bugprone/concurrency)")
    sub.add_parser("tsan", help="ring_stress thread-sanitizer gate")
    p_fuzz = sub.add_parser(
        "fuzz", help="differential HTTP-parsing fuzzer (ISSUE 11)")
    p_fuzz.add_argument("--mutants", type=int, default=None)
    p_fuzz.add_argument("--seed", type=int, default=None)
    p_fuzz.add_argument("--corpus-only", action="store_true")
    p_fuzz.add_argument("--no-native", action="store_true")
    sub.add_parser("all", help="run every pass")
    args = parser.parse_args(argv)

    from . import abi, fuzz, lint, native

    if args.cmd == "abi":
        return abi.run(regen=args.regen)
    if args.cmd == "lint":
        return lint.run(paths=args.files or None)
    if args.cmd == "tidy":
        return native.run_tidy()
    if args.cmd == "tsan":
        return native.run_tsan()
    if args.cmd == "fuzz":
        kwargs = {}
        if args.mutants is not None:
            kwargs["mutants"] = args.mutants
        if args.seed is not None:
            kwargs["seed"] = args.seed
        return fuzz.run(corpus_only=args.corpus_only,
                        no_native=args.no_native, **kwargs)
    rc = 0
    rc |= abi.run()
    rc |= lint.run()
    rc |= native.run_tidy()
    rc |= native.run_tsan()
    rc |= fuzz.run()
    return rc


if __name__ == "__main__":
    sys.exit(main())
