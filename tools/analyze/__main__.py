"""CLI for the pingoo-analyze suite.

    python -m tools.analyze all            # every pass (make analyze)
    python -m tools.analyze abi [--regen]  # cross-plane ABI checker
    python -m tools.analyze lint [files…]  # JAX hot-path linter
    python -m tools.analyze tidy           # clang-tidy vs baseline
    python -m tools.analyze tsan           # ring_stress concurrency gate

Passes are offline-safe; missing toolchains (C++ compiler, clang-tidy,
TSAN runtime) downgrade the affected pass to skip-with-warning.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m tools.analyze")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_abi = sub.add_parser("abi", help="cross-plane ABI/layout checker")
    p_abi.add_argument("--regen", action="store_true",
                       help="regenerate abi_golden.json from the header")
    p_lint = sub.add_parser("lint", help="JAX hot-path linter")
    p_lint.add_argument("files", nargs="*",
                        help="files to lint (default: configured dirs)")
    sub.add_parser("tidy", help="clang-tidy (bugprone/concurrency)")
    sub.add_parser("tsan", help="ring_stress thread-sanitizer gate")
    sub.add_parser("all", help="run every pass")
    args = parser.parse_args(argv)

    from . import abi, lint, native

    if args.cmd == "abi":
        return abi.run(regen=args.regen)
    if args.cmd == "lint":
        return lint.run(paths=args.files or None)
    if args.cmd == "tidy":
        return native.run_tidy()
    if args.cmd == "tsan":
        return native.run_tsan()
    rc = 0
    rc |= abi.run()
    rc |= lint.run()
    rc |= native.run_tidy()
    rc |= native.run_tsan()
    return rc


if __name__ == "__main__":
    sys.exit(main())
