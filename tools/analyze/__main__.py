"""CLI for the pingoo-analyze suite.

    python -m tools.analyze all            # every pass (make analyze)
    python -m tools.analyze abi [--regen]  # cross-plane ABI checker
    python -m tools.analyze lint [files…]  # JAX hot-path linter
    python -m tools.analyze tidy [--regen] # clang-tidy vs baseline
    python -m tools.analyze tsan           # ring_stress concurrency gate
    python -m tools.analyze fuzz           # differential parsing fuzzer
    python -m tools.analyze prove          # lowering-soundness prover +
                                           # compile surface + ringcheck
    python -m tools.analyze ringcheck      # ring-protocol model checker
    python -m tools.analyze surface        # emit COMPILE_SURFACE.json

Passes are offline-safe; missing toolchains (C++ compiler, clang-tidy,
TSAN runtime, jax for `prove`) downgrade the affected pass to
skip-with-warning. `all` ends with a per-pass summary table —
pass/fail, or skip with the recorded reason.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m tools.analyze")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_abi = sub.add_parser("abi", help="cross-plane ABI/layout checker")
    p_abi.add_argument("--regen", action="store_true",
                       help="regenerate abi_golden.json from the header")
    p_lint = sub.add_parser("lint", help="JAX hot-path linter")
    p_lint.add_argument("files", nargs="*",
                        help="files to lint (default: configured dirs)")
    p_tidy = sub.add_parser("tidy", help="clang-tidy "
                                         "(bugprone/concurrency)")
    p_tidy.add_argument("--regen", action="store_true",
                        help="rewrite tidy_baseline.txt from the "
                             "current findings")
    sub.add_parser("tsan", help="ring_stress thread-sanitizer gate")
    p_prove = sub.add_parser(
        "prove", help="machine-check the lowering obligations, compile "
                      "surface, and ring protocol (ISSUE 18)")
    p_prove.add_argument("--history", action="store_true",
                         help="append prove_wall_s to BENCH_history.jsonl")
    p_prove.add_argument("--skip-mutations", action="store_true",
                         help="skip the checker self-tests (faster)")
    sub.add_parser("ringcheck", help="ring-protocol model checker only")
    sub.add_parser("surface", help="emit COMPILE_SURFACE.json only")
    p_fuzz = sub.add_parser(
        "fuzz", help="differential HTTP-parsing fuzzer (ISSUE 11)")
    p_fuzz.add_argument("--mutants", type=int, default=None)
    p_fuzz.add_argument("--seed", type=int, default=None)
    p_fuzz.add_argument("--corpus-only", action="store_true")
    p_fuzz.add_argument("--no-native", action="store_true")
    sub.add_parser("all", help="run every pass")
    args = parser.parse_args(argv)

    from . import abi, fuzz, lint, native

    if args.cmd == "abi":
        return abi.run(regen=args.regen)
    if args.cmd == "lint":
        return lint.run(paths=args.files or None)
    if args.cmd == "tidy":
        return native.run_tidy(regen=args.regen)
    if args.cmd == "tsan":
        return native.run_tsan()
    if args.cmd == "prove":
        from . import prove
        return prove.run(history=args.history,
                         mutations=not args.skip_mutations)
    if args.cmd == "ringcheck":
        from . import ringcheck
        return ringcheck.run()
    if args.cmd == "surface":
        from . import surface
        return surface.run()
    if args.cmd == "fuzz":
        kwargs = {}
        if args.mutants is not None:
            kwargs["mutants"] = args.mutants
        if args.seed is not None:
            kwargs["seed"] = args.seed
        return fuzz.run(corpus_only=args.corpus_only,
                        no_native=args.no_native, **kwargs)
    from . import SKIP_NOTES, prove

    rc = 0
    results = []
    for name, pass_fn in (("abi", abi.run), ("lint", lint.run),
                          ("tidy", native.run_tidy),
                          ("tsan", native.run_tsan), ("fuzz", fuzz.run),
                          ("prove", prove.run)):
        before = len(SKIP_NOTES)
        try:
            prc = pass_fn()
        except Exception as exc:
            # A crashed pass is a FAIL for that row, not an abort of
            # the remaining passes.
            print(f"analyze-{name}: FAIL — pass crashed: {exc!r}",
                  file=sys.stderr)
            prc = 1
        reasons = [r for _, r in SKIP_NOTES[before:]]
        status = "FAIL" if prc else ("SKIP" if reasons else "PASS")
        results.append((name, status, "; ".join(reasons)))
        rc |= prc
    print("\nanalyze summary:")
    for name, status, reason in results:
        print(f"  {name:<6} {status}" + (f"  — {reason}" if reason
                                         else ""))
    return rc


if __name__ == "__main__":
    sys.exit(main())
