// ABI/layout emitter for the shared-memory verdict ring (make
// analyze-abi). Compiled against pingoo_tpu/native/pingoo_ring.h, it
// prints the COMPILER'S answer — sizeof/offsetof/alignof for every
// struct the Python plane mirrors, plus the wire constants — as JSON on
// stdout. tools/analyze/abi.py diffs this against the numpy structured
// dtypes in pingoo_tpu/native_ring.py and the committed golden table
// (tools/analyze/abi_golden.json), so a field added on one side without
// the other (and the golden) is a hard failure, not a latent slot-
// corruption bug. Regenerate the golden with:
//   python -m tools.analyze abi --regen

#include <cstddef>
#include <cstdio>

#include "pingoo_ring.h"

namespace {

bool first_item = true;

void sep() {
  if (!first_item) std::printf(",\n");
  first_item = false;
}

#define FIELD(S, f)                                                      \
  do {                                                                   \
    sep();                                                               \
    std::printf("      {\"name\": \"%s\", \"offset\": %zu, \"size\": %zu}", \
                #f, offsetof(S, f), sizeof(S{}.f));                      \
  } while (0)

#define STRUCT_OPEN(S)                                                  \
  do {                                                                  \
    sep();                                                              \
    std::printf("    \"%s\": {\"size\": %zu, \"align\": %zu,\n"         \
                "     \"fields\": [\n",                                 \
                #S, sizeof(S), alignof(S));                             \
    first_item = true;                                                  \
  } while (0)

#define STRUCT_CLOSE()           \
  do {                           \
    std::printf("\n    ]}");     \
    first_item = false;          \
  } while (0)

#define CONSTANT(name)                                      \
  do {                                                      \
    sep();                                                  \
    std::printf("    \"%s\": %llu", #name,                  \
                static_cast<unsigned long long>(name));     \
  } while (0)

}  // namespace

int main() {
  std::printf("{\n");
  std::printf("  \"format_version\": %u,\n",
              static_cast<unsigned>(PINGOO_RING_VERSION));

  std::printf("  \"constants\": {\n");
  first_item = true;
  CONSTANT(PINGOO_RING_MAGIC);
  CONSTANT(PINGOO_RING_VERSION);
  CONSTANT(PINGOO_METHOD_CAP);
  CONSTANT(PINGOO_HOST_CAP);
  CONSTANT(PINGOO_PATH_CAP);
  CONSTANT(PINGOO_URL_CAP);
  CONSTANT(PINGOO_UA_CAP);
  CONSTANT(PINGOO_SLOT_FLAG_TRUNCATED);
  CONSTANT(PINGOO_SPILL_SLOTS);
  CONSTANT(PINGOO_SPILL_DATA_CAP);
  CONSTANT(PINGOO_SPILL_NONE);
  CONSTANT(PINGOO_WAIT_BUCKETS);
  CONSTANT(PINGOO_TELEMETRY_WORDS);
  CONSTANT(PINGOO_BODY_SLOTS);
  CONSTANT(PINGOO_BODY_WINDOW_CAP);
  CONSTANT(PINGOO_BODY_FLAG_FINAL);
  CONSTANT(PINGOO_BODY_FLAG_ABORT);
  CONSTANT(PINGOO_BODY_VERDICT_BIT);
  std::printf("\n  },\n");
  first_item = false;

  std::printf("  \"structs\": {\n");
  first_item = true;

  STRUCT_OPEN(PingooRequestSlot);
  FIELD(PingooRequestSlot, seq);
  FIELD(PingooRequestSlot, ticket);
  FIELD(PingooRequestSlot, enq_ms);
  FIELD(PingooRequestSlot, method_len);
  FIELD(PingooRequestSlot, host_len);
  FIELD(PingooRequestSlot, path_len);
  FIELD(PingooRequestSlot, url_len);
  FIELD(PingooRequestSlot, ua_len);
  FIELD(PingooRequestSlot, remote_port);
  FIELD(PingooRequestSlot, ip);
  FIELD(PingooRequestSlot, asn);
  FIELD(PingooRequestSlot, country);
  FIELD(PingooRequestSlot, flags);
  FIELD(PingooRequestSlot, spill_idx);
  FIELD(PingooRequestSlot, method);
  FIELD(PingooRequestSlot, host);
  FIELD(PingooRequestSlot, path);
  FIELD(PingooRequestSlot, url);
  FIELD(PingooRequestSlot, user_agent);
  STRUCT_CLOSE();

  STRUCT_OPEN(PingooVerdictSlot);
  FIELD(PingooVerdictSlot, seq);
  FIELD(PingooVerdictSlot, ticket);
  FIELD(PingooVerdictSlot, action);
  FIELD(PingooVerdictSlot, _pad);
  FIELD(PingooVerdictSlot, bot_score);
  STRUCT_CLOSE();

  STRUCT_OPEN(PingooRingTelemetry);
  FIELD(PingooRingTelemetry, enqueued);
  FIELD(PingooRingTelemetry, enqueue_full);
  FIELD(PingooRingTelemetry, dequeued);
  FIELD(PingooRingTelemetry, depth_hwm);
  FIELD(PingooRingTelemetry, verdicts_posted);
  FIELD(PingooRingTelemetry, verdict_post_full);
  FIELD(PingooRingTelemetry, wait_sum_ms);
  FIELD(PingooRingTelemetry, wait_hist);
  STRUCT_CLOSE();

  STRUCT_OPEN(PingooRingHeader);
  FIELD(PingooRingHeader, magic);
  FIELD(PingooRingHeader, version);
  FIELD(PingooRingHeader, capacity);
  FIELD(PingooRingHeader, request_slot_size);
  FIELD(PingooRingHeader, verdict_slot_size);
  FIELD(PingooRingHeader, body_slot_size);
  FIELD(PingooRingHeader, body_capacity);
  FIELD(PingooRingHeader, req_head);
  FIELD(PingooRingHeader, req_tail);
  FIELD(PingooRingHeader, ver_head);
  FIELD(PingooRingHeader, ver_tail);
  FIELD(PingooRingHeader, telemetry);
  FIELD(PingooRingHeader, sidecar_epoch);
  FIELD(PingooRingHeader, sidecar_heartbeat_ms);
  FIELD(PingooRingHeader, posted_floor);
  FIELD(PingooRingHeader, body_head);
  FIELD(PingooRingHeader, body_tail);
  STRUCT_CLOSE();

  STRUCT_OPEN(PingooSpillSlot);
  FIELD(PingooSpillSlot, state);
  FIELD(PingooSpillSlot, url_len);
  FIELD(PingooSpillSlot, path_len);
  FIELD(PingooSpillSlot, data);
  STRUCT_CLOSE();

  STRUCT_OPEN(PingooBodySlot);
  FIELD(PingooBodySlot, seq);
  FIELD(PingooBodySlot, flow);
  FIELD(PingooBodySlot, win_seq);
  FIELD(PingooBodySlot, win_len);
  FIELD(PingooBodySlot, total_len);
  FIELD(PingooBodySlot, flags);
  FIELD(PingooBodySlot, _pad);
  FIELD(PingooBodySlot, data);
  STRUCT_CLOSE();

  std::printf("\n  }\n}\n");
  return 0;
}
