"""Static compile-surface analyzer (ISSUE 18, docs/STATIC_ANALYSIS.md).

XLA compilation is the one unbounded latency hazard the serving path
has: any NEW (fn, shape) pair that reaches a jitted dispatch stalls a
live batch for seconds.  Every shape axis the engine exposes is
deliberately rung-quantized — pow2 batch buckets (engine/batch.py
pow2_batch_size, floor 8), pow2 megastep K rungs (engine/verdict.py
megastep_k_ladder), quantized staging widths (compiler/plan.py
STAGING_RUNGS), and the DFA mode ladder — so the set of admissible
compilations per plan is CLOSED and statically enumerable.

This pass walks every `make_*_fn` / `instrument_jit` entry point in the
tree (AST, no imports), checks each against the registered label maps
(an unregistered entry point fails the pass — register it below or it
escapes the surface bound), and emits the closed admissible set as
COMPILE_SURFACE.json.  The runtime compile ledger (obs/perf.py) loads
that file via PINGOO_COMPILE_SURFACE and verifies every recorded
compile event is inside the surface — an out-of-surface compile flips
`pingoo_compile_unexpected_total` and fails `make timeline-smoke`.  The
AST linter's `unbounded-compile-axis` rule (lint.py) closes the loop at
review time: a len()/.shape-derived expression reaching a jitted
dispatch without passing through a registered quantizer fails lint.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Optional

from . import REPO_ROOT

SURFACE_VERSION = 1
DEFAULT_PATH = os.path.join(REPO_ROOT, "COMPILE_SURFACE.json")

# Every make_*_fn factory must map to its ledger fn label; scanning an
# unregistered factory fails the pass so a new entry point cannot ship
# outside the surface bound.
MAKE_FN_LABELS = {
    "make_verdict_fn": "verdict",
    "make_packed_verdict_fn": "verdict",
    "make_prefilter_fn": "prefilter",
    "make_packed_prefilter_fn": "prefilter",
    "make_lane_fn": "lanes",
    "make_packed_lane_fn": "lanes",
    "make_megastep_fn": "megastep",
}

PLANES = ("python", "sidecar")
KINDS = ("cold", "warm")
DFA_MODES = ("off", "auto", "force")

_SCAN_DIRS = ("pingoo_tpu",)
_EXCLUDE = {"__pycache__", ".git", "build", "dist", "native"}


def _pow2_ladder(lo: int, hi: int) -> list[int]:
    out, v = [], lo
    while v <= hi:
        out.append(v)
        v *= 2
    return out


def _k_ladder() -> list[int]:
    """Mirror of engine/verdict.megastep_k_ladder(megastep_k_cap())
    without importing jax; tools/analyze/prove.py cross-checks the two
    whenever the engine is importable."""
    try:
        cap = max(1, int(os.environ.get("PINGOO_MEGASTEP_K", "4")))
    except ValueError:
        cap = 4
    return _pow2_ladder(1, cap)


def scan_entry_points(repo_root: str = REPO_ROOT):
    """AST-walk the tree for jit entry points.

    Returns (entry_points, problems): entry_points are provenance rows
    {file, line, kind, name, plane}; problems are strings — an
    unregistered make_*_fn, a non-literal/unknown instrument_jit name,
    or an unknown plane literal."""
    entries: list[dict] = []
    problems: list[str] = []
    for scan_dir in _SCAN_DIRS:
        base = os.path.join(repo_root, scan_dir)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, repo_root)
                try:
                    with open(path, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=rel)
                except (OSError, SyntaxError) as exc:
                    problems.append(f"{rel}: unparseable ({exc})")
                    continue
                _scan_module(tree, rel, entries, problems)
    return entries, problems


def _scan_module(tree: ast.AST, rel: str, entries: list,
                 problems: list) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            name = node.name
            if name.startswith("make_") and name.endswith("_fn"):
                label = MAKE_FN_LABELS.get(name)
                if label is None:
                    problems.append(
                        f"{rel}:{node.lineno}: unregistered jit factory "
                        f"{name} (add it to surface.MAKE_FN_LABELS)")
                else:
                    entries.append({"file": rel, "line": node.lineno,
                                    "kind": "factory", "name": name,
                                    "fn": label, "plane": None})
        elif isinstance(node, ast.Call):
            callee = node.func
            cname = callee.attr if isinstance(callee, ast.Attribute) \
                else getattr(callee, "id", "")
            if cname not in ("instrument_jit", "instrument_megastep"):
                continue
            if rel.replace(os.sep, "/") == "pingoo_tpu/obs/perf.py":
                continue  # the instrument layer itself
            fn_label: Optional[str] = "megastep" \
                if cname == "instrument_megastep" else None
            if cname == "instrument_jit" and len(node.args) >= 2:
                arg = node.args[1]
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str):
                    fn_label = arg.value
                elif isinstance(arg, ast.Name):
                    fn_label = f"<var:{arg.id}>"
            plane = None
            for kw in node.keywords:
                if kw.arg == "plane" and isinstance(kw.value, ast.Constant):
                    plane = kw.value.value
            if isinstance(fn_label, str) and not fn_label.startswith("<") \
                    and fn_label not in MAKE_FN_LABELS.values() \
                    and fn_label != "score":
                problems.append(
                    f"{rel}:{node.lineno}: instrument_jit label "
                    f"{fn_label!r} is not a registered fn kind")
            if plane is not None and plane not in PLANES:
                problems.append(
                    f"{rel}:{node.lineno}: unknown plane {plane!r}")
            entries.append({"file": rel, "line": node.lineno,
                            "kind": "site", "name": cname,
                            "fn": fn_label, "plane": plane})


def build_surface(plan: Any = None, max_batch: int = 8192,
                  repo_root: str = REPO_ROOT) -> dict:
    """Enumerate the closed admissible compile set; raises ValueError
    when the entry-point walk finds an unregistered factory/label (the
    surface would silently under-approximate otherwise)."""
    entries, problems = scan_entry_points(repo_root)
    if problems:
        raise ValueError("compile surface incomplete:\n  "
                         + "\n  ".join(problems))
    fns = sorted(set(MAKE_FN_LABELS.values()) | {"score"})
    surface = {
        "version": SURFACE_VERSION,
        "planes": list(PLANES),
        "fns": fns,
        "kinds": list(KINDS),
        # pow2_batch_size floors direct batches at 8, but a megastep
        # window's per-slice rows can be any pow2 below it (size/K), so
        # the admissible bucket set is the full pow2 ladder.
        "batch_buckets": _pow2_ladder(1, max(8, max_batch)),
        "k_rungs": _k_ladder(),
        "dfa_modes": list(DFA_MODES),
        "entry_points": entries,
    }
    if plan is not None:
        from pingoo_tpu.compiler.plan import STAGING_RUNGS
        from pingoo_tpu.obs.perf import staging_widths

        surface["staging_rungs"] = list(STAGING_RUNGS)
        surface["widths"] = [list(map(list, staging_widths(plan)))]
    return surface


def write_surface(surface: dict, path: str = DEFAULT_PATH) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(surface, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def run(out_path: str = DEFAULT_PATH) -> int:
    """Emit COMPILE_SURFACE.json for the static (plan-agnostic) axes."""
    try:
        surface = build_surface()
    except ValueError as exc:
        print(f"surface: FAIL — {exc}")
        return 1
    write_surface(surface, out_path)
    sites = sum(1 for e in surface["entry_points"] if e["kind"] == "site")
    factories = sum(1 for e in surface["entry_points"]
                    if e["kind"] == "factory")
    print(f"surface: OK — {factories} factories + {sites} instrumented "
          f"sites -> {os.path.relpath(out_path, REPO_ROOT)} "
          f"({len(surface['batch_buckets'])} buckets x "
          f"{len(surface['k_rungs'])} K rungs x "
          f"{len(surface['fns'])} fns)")
    return 0


if __name__ == "__main__":
    raise SystemExit(run())
