#!/usr/bin/env python
"""Bench trajectory regression gate (make bench-regress; ISSUE 5
satellite).

`bench.py --history` appends every emitted result line to
BENCH_history.jsonl (one JSON object per run, wall-clock stamped).
This tool compares the LATEST run against the most recent previous run
with the SAME backend label (a cpu-diagnostic floor is never comparable
to a device number) under a configurable relative threshold:

    python tools/bench_regress.py [--threshold 0.10] [--file PATH]

Exit codes: 0 = no regression (or nothing comparable yet), 1 = at
least one tracked metric regressed past the threshold, 2 = usage/IO
error. Tracked metrics and their directions:

    value                higher is better (headline req/s/chip)
    p_batch_ms           lower  is better (the <2 ms budget)
    e2e_req_per_s        higher is better
    dataplane_req_per_s  higher is better
    blocklist_lookups_per_s  higher is better
    sched_continuous_req_per_s  higher is better (ISSUE 6 serving bench)
    sched_continuous_p99_ms     lower  is better
    sched_p99_slack_ms          higher is better (deadline headroom)
    sched_deadline_miss_rate    lower  is better
    dfa_auto_req_per_s   higher is better (ISSUE 8 bitsplit-DFA arm)
    pipeline_on_req_per_s  higher is better (ISSUE 9 pipelined executor)
    pipeline_on_p99_ms     lower  is better
    megastep_req_per_s   higher is better (ISSUE 12 megastep arm)
    swap_pause_p99_ms    lower  is better (ISSUE 11 hot-swap pause)
    body_stream_mb_per_s higher is better (ISSUE 13 streaming body scan)
    staging_compact_req_per_s higher is better (ISSUE 15 compact staging)
    staged_bytes_per_req lower  is better

Metrics missing from either run are skipped (partial/error lines are
trajectory too, but only shared keys gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# (key, higher_is_better)
TRACKED = (
    ("value", True),
    ("p_batch_ms", False),
    ("e2e_req_per_s", True),
    ("dataplane_req_per_s", True),
    ("blocklist_lookups_per_s", True),
    # Continuous-batching serving bench (ISSUE 6, bench.py --mesh).
    ("sched_continuous_req_per_s", True),
    ("sched_continuous_p99_ms", False),
    ("sched_p99_slack_ms", True),
    ("sched_deadline_miss_rate", False),
    # Bitsplit-DFA lowering A/B (ISSUE 8, bench.py --dfa).
    ("dfa_auto_req_per_s", True),
    # Zero-copy pipelined executor A/B (ISSUE 9, bench.py --pipeline).
    ("pipeline_on_req_per_s", True),
    ("pipeline_on_p99_ms", False),
    # Device-resident megastep arm (ISSUE 12, bench.py --pipeline).
    ("megastep_req_per_s", True),
    # Sidecar supervision chaos smoke (ISSUE 10, tools/chaos_smoke.py):
    # p99 enqueue->resolution during a sidecar outage must stay within
    # the degraded fail-open bound.
    ("degraded_failopen_p99_ms", False),
    # Ruleset hot-swap storm (ISSUE 11, tools/chaos_smoke.py): the
    # drain+flip admission pause a swap costs at a batch boundary.
    ("swap_pause_p99_ms", False),
    # Streaming body-scan arm (ISSUE 13, bench.py --body): interleaved
    # multi-flow windowed scan throughput, verdict-identical to the
    # contiguous scan by construction.
    ("body_stream_mb_per_s", True),
    # Compact staging A/B (ISSUE 15, bench.py --staging): compact-arm
    # throughput and the staged bytes/request it exists to shrink.
    ("staging_compact_req_per_s", True),
    ("staged_bytes_per_req", False),
    # Lowering-soundness prover (ISSUE 18, python -m tools.analyze
    # prove --history): wall time to discharge every obligation on the
    # seed 500-rule plan — the compile-time proof budget. Prove runs
    # stamp backend="prove-<jax backend>" so they only ever compare
    # against other prove runs.
    ("prove_wall_s", False),
)

DEFAULT_THRESHOLD = 0.10


def load_history(path: str) -> list[dict]:
    entries = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"bench-regress: warning: {path}:{i}: "
                      f"unparseable line skipped", file=sys.stderr)
    return entries


def pick_baseline(entries: list[dict]) -> tuple[dict, dict | None]:
    """(latest, baseline) where baseline is the most recent PRIOR entry
    with the same backend label; None when no comparable prior run."""
    latest = entries[-1]
    backend = latest.get("backend")
    for prev in reversed(entries[:-1]):
        if prev.get("backend") == backend:
            return latest, prev
    return latest, None


def compare(latest: dict, baseline: dict,
            threshold: float) -> tuple[list[str], list[str]]:
    """-> (regressions, report lines)."""
    regressions: list[str] = []
    report: list[str] = []
    for key, higher_better in TRACKED:
        a, b = baseline.get(key), latest.get(key)
        if not isinstance(a, (int, float)) or not isinstance(
                b, (int, float)) or a <= 0:
            continue
        ratio = b / a
        delta_pct = (ratio - 1.0) * 100.0
        worse = ratio < (1.0 - threshold) if higher_better \
            else ratio > (1.0 + threshold)
        marker = "REGRESSION" if worse else "ok"
        report.append(
            f"  {marker:>10}  {key}: {a} -> {b} ({delta_pct:+.1f}%, "
            f"{'higher' if higher_better else 'lower'} is better)")
        if worse:
            regressions.append(key)
    return regressions, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--threshold", type=float, default=float(
        os.environ.get("BENCH_REGRESS_THRESHOLD", DEFAULT_THRESHOLD)),
        help="relative regression threshold (default 0.10 = 10%%)")
    ap.add_argument("--file", default=os.environ.get(
        "BENCH_HISTORY_FILE", "BENCH_history.jsonl"))
    args = ap.parse_args(argv)
    if args.threshold <= 0 or args.threshold >= 1:
        print("bench-regress: threshold must be in (0, 1)",
              file=sys.stderr)
        return 2
    if not os.path.exists(args.file):
        print(f"bench-regress: no history at {args.file} "
              f"(run `python bench.py --history` first); nothing to "
              f"compare")
        return 0
    try:
        entries = load_history(args.file)
    except OSError as exc:
        print(f"bench-regress: cannot read {args.file}: {exc}",
              file=sys.stderr)
        return 2
    if len(entries) < 2:
        print(f"bench-regress: {len(entries)} run(s) in {args.file}; "
              f"need 2 comparable runs")
        return 0
    latest, baseline = pick_baseline(entries)
    if baseline is None:
        # Explicit cross-backend refusal (ISSUE 17 satellite): name
        # BOTH backends so "nothing comparable" is diagnosable from the
        # message alone, and treat a latest entry with no backend stamp
        # at all as an error — history_schema>=2 lines (bench.py
        # _append_history) always carry one, so its absence means the
        # file predates the stamp or was hand-edited.
        if latest.get("backend") is None:
            print(f"bench-regress: latest entry in {args.file} has no "
                  f"'backend' stamp (pre-schema-2 history?); refusing "
                  f"to guess a baseline — re-run `python bench.py "
                  f"--history` to append a stamped run", file=sys.stderr)
            return 2
        others = sorted({str(e.get("backend")) for e in entries[:-1]})
        print(f"bench-regress: REFUSED — latest run is backend="
              f"{latest.get('backend')!r} but every prior run is "
              f"backend in {others}; cross-backend numbers are not "
              f"comparable (a cpu-diagnostic floor vs a device run "
              f"measures the host, not the change)")
        return 0
    regressions, report = compare(latest, baseline, args.threshold)
    print(f"bench-regress: latest ts={latest.get('ts')} vs baseline "
          f"ts={baseline.get('ts')} (backend={latest.get('backend')!r}, "
          f"threshold {args.threshold:.0%})")
    for line in report:
        print(line)
    if not report:
        print("  (no shared tracked metrics between the two runs)")
    if regressions:
        print(f"bench-regress: FAIL — {len(regressions)} metric(s) "
              f"regressed: {', '.join(regressions)}", file=sys.stderr)
        return 1
    print("bench-regress: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
