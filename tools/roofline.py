#!/usr/bin/env python
"""Architectural roofline for the headline WAF verdict (VERDICT r4
item 9): is the committed 1.45M req/s @ batch 2048 close to what a
TPU v5e-1 can do for this workload, and what is the binding resource?

Method: compile the SAME 500-rule corpus + traffic the bench uses,
pull the real bank geometry (word widths, byte-class counts, bucketed
field lengths, pass counts) out of the plan, and bound the per-batch
time three ways from public v5e-1 specs:

  * HBM:  bytes that must cross HBM per batch / 819 GB/s
  * MXU:  matmul MACs per batch (one-hot lookups, window correlators,
          span-reduction matmuls) / 197 TFLOP/s bf16
  * VPU:  elementwise lane-ops of the bit-parallel NFA advance
          (the serial per-byte loop) / (8x128 lanes x ~4 issue x 940 MHz)

The serial-step structure matters more than raw totals: each NFA scan
step is a dependent loop iteration, so its latency floors the batch
time no matter how idle the units are. Run:  python tools/roofline.py
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# public TPU v5e specs (per chip)
HBM_GBPS = 819e9
MXU_FLOPS = 197e12  # bf16 MAC/s x2
VPU_LANEOPS = 8 * 128 * 4 * 940e6  # sublanes x lanes x issue x clock
CLOCK = 940e6

BATCH = 2048
MEASURED_REQ_S = 1.45e6
MEASURED_MS = 1.41


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.compiler.plan import ScanStrategy, strategy_steps
    from pingoo_tpu.engine import encode_requests
    from pingoo_tpu.engine.batch import bucket_arrays
    from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

    rules, lists = generate_ruleset(500, with_lists=True,
                                    list_sizes=(131072, 4096))
    plan = compile_ruleset(rules, lists)
    reqs = generate_traffic(BATCH, lists=lists, seed=100)
    arrays = bucket_arrays(encode_requests(reqs).arrays)
    blen = {f: arrays[f + "_bytes"].shape[1]
            for f in ("url", "path", "user_agent", "host", "method")}

    # -- per-batch work ------------------------------------------------------
    hbm_bytes = 0
    mxu_macs = 0
    vpu_ops = 0
    serial_steps = 0
    detail = {}

    # request bytes in (the only per-batch HBM traffic that scales with
    # B; the 2.08 MiB of tables are resident and re-read from VMEM/CMEM)
    in_bytes = BATCH * sum(blen.values())
    hbm_bytes += in_bytes
    # verdict lanes out: [3 + G, B] int32
    hbm_bytes += 4 * BATCH * 4

    selected_steps = 0
    for key, val in plan.np_tables.items():
        leaves = jax.tree_util.tree_leaves(val)
        tbytes = sum(np.asarray(x).nbytes for x in leaves)
        if key.startswith("nfa_") and "@" not in key:
            field = key[4:]
            W = val.byte_table.shape[1]
            C = val.cls_table.shape[0]
            L = blen.get(field, 0)
            passes = 1 + val.extra_passes
            steps = L * passes
            serial_steps += steps
            # lookup: one-hot [B, C] x [C, 2W] f32 matmul per step
            mxu_macs += steps * BATCH * C * 2 * W
            # advance: ~8 u32 lane-ops over [B, W] per step
            # (shift, or, and, opt, rep, carry x2, accumulate)
            vpu_ops += steps * BATCH * W * 8
            # accept extraction: [B, J] x [J, P]
            J, P = val.accept_member.shape
            mxu_macs += BATCH * J * P
            detail[key] = {"W": W, "classes": C, "len": L,
                           "passes": passes, "steps": steps,
                           "table_KiB": round(tbytes / 1024, 1)}
            # Per-strategy dependent-step counts at THIS bucketed length
            # (loop iterations x passes — the roofline's serial unit),
            # plus the plan's selected strategy (compiler/plan.py;
            # persisted through the ruleset artifact cache).
            entry = plan.scan_plans.get(key)
            variants = {
                "scan": strategy_steps(val, L, ScanStrategy()),
                "pair": strategy_steps(val, L, ScanStrategy(pair=True)),
                "pallas": strategy_steps(
                    val, L, ScanStrategy(kind="pallas", pair=True)),
                "halo": strategy_steps(
                    val, L, ScanStrategy(halo_k=8)),
                # Bitsplit DFA (ISSUE 8): L single-gather dependent
                # steps, no matmul in the chain (~4 ops/byte).
                "dfa": strategy_steps(val, L, ScanStrategy(kind="dfa")),
            }
            detail[key]["strategy_steps"] = variants
            dfa_active = False
            if entry is not None and entry.dfa_key in plan.np_tables:
                dtab = plan.np_tables[entry.dfa_key]
                mode = getattr(plan, "dfa_default_mode", "auto")
                dfa_active = entry.split is None and (
                    mode == "force" or (mode == "auto" and entry.dfa_auto))
                detail[key]["dfa"] = {
                    "states": int(dtab.num_states),
                    "classes": int(dtab.num_classes),
                    "exact": bool(dtab.exact),
                    "auto": bool(entry.dfa_auto),
                    "active": dfa_active,
                }
            if dfa_active:
                # The lowered chain: L dependent [S,C]-row gathers —
                # the dependent MATMUL chain is gone on this bank (an
                # approximate lowering rechecks candidate rows through
                # the exact NFA, off the common path).
                detail[key]["selected"] = {
                    "kind": "dfa" + ("" if dtab.exact else "+recheck"),
                    "source": (entry.dfa_strategy.source
                               if entry.dfa_strategy else "default"),
                }
                detail[key]["selected_steps"] = variants["dfa"]
                selected_steps += variants["dfa"]
            elif entry is not None:
                if entry.split is not None:
                    short_t = plan.np_tables[entry.split[0]]
                    rest_t = plan.np_tables[entry.split[1]]
                    sel = (strategy_steps(short_t, L, entry.short_strategy)
                           + strategy_steps(rest_t, L, entry.rest_strategy))
                    sel_desc = {
                        "kind": "split",
                        "short": entry.short_strategy.kind
                        + ("+pair" if entry.short_strategy.pair else "")
                        + (f"+halo{entry.short_strategy.halo_k}"
                           if entry.short_strategy.halo_k > 1 else ""),
                        "rest": entry.rest_strategy.kind
                        + ("+pair" if entry.rest_strategy.pair else ""),
                    }
                else:
                    sel = strategy_steps(val, L, entry.strategy)
                    sel_desc = {
                        "kind": entry.strategy.kind
                        + ("+pair" if entry.strategy.pair else ""),
                        "source": entry.strategy.source,
                    }
                detail[key]["selected"] = sel_desc
                detail[key]["selected_steps"] = sel
                selected_steps += sel
        elif key.startswith("win_"):
            # windowed correlation: [B, L] bytes against K signatures of
            # width 8 (nibble-SSD): [B*L, 8*2] x [16, K] -ish
            arr = val[0] if isinstance(val, tuple) else None
            K = np.asarray(arr).shape[0] if arr is not None else 0
            field = key[4:]
            L = blen.get(field, 0)
            mxu_macs += BATCH * L * 16 * K
            detail[key] = {"signatures": K, "len": L,
                           "table_KiB": round(tbytes / 1024, 1)}
            # Window-bank DFA lowering (ISSUE 8): the conv is
            # serial-free on the MXU, so the gather ladder is only
            # taken where per-row work dominates — the CPU diagnostic
            # backend under auto, everywhere under force
            # (engine/verdict._dfa_win_active). It trades BATCH*L*16*K
            # MXU MACs for L dependent row-gathers (~4 ops/byte).
            dkey = getattr(plan, "win_dfa", {}).get(key)
            if dkey and dkey in plan.np_tables:
                dtab = plan.np_tables[dkey]
                mode = getattr(plan, "dfa_default_mode", "auto")
                detail[key]["dfa"] = {
                    "states": int(dtab.num_states),
                    "classes": int(dtab.num_classes),
                    "exact": bool(dtab.exact),
                    "auto": "cpu-only",
                    "active_on_tpu": mode == "force",
                    "dependent_steps_if_taken": L,
                }
        elif key.startswith("iplist_"):
            hbm_bytes += tbytes  # 1.4 MiB bucket table streamed per batch
            vpu_ops += BATCH * 64  # bucket probe + compares
        else:
            vpu_ops += BATCH * 256

    t_hbm = hbm_bytes / HBM_GBPS
    t_mxu = 2 * mxu_macs / MXU_FLOPS
    t_vpu = vpu_ops / VPU_LANEOPS
    # Serial floor: each NFA step is a dependent iteration; even at 1 us
    # of fixed overhead (gather issue + vector op latency + loop
    # carry) the scan chain floors the batch. Use two bounds:
    t_serial_opt = serial_steps * 0.5e-6   # optimistic 0.5 us/step
    t_serial_meas = MEASURED_MS * 1e-3     # what the chip actually did

    out = {
        "measured_req_s": MEASURED_REQ_S,
        "measured_ms_per_batch": MEASURED_MS,
        "batch": BATCH,
        "bucketed_lens": blen,
        "serial_nfa_steps": serial_steps,
        # dependent steps under the PLAN-SELECTED strategies (pair /
        # pallas / halo-split; see per-bank strategy_steps): the serial
        # chain the selected kernels actually execute.
        "selected_serial_steps": selected_steps,
        "per_batch": {
            "hbm_bytes": int(hbm_bytes),
            "mxu_macs": int(mxu_macs),
            "vpu_lane_ops": int(vpu_ops),
        },
        "ceilings_req_s": {
            "hbm": round(BATCH / t_hbm),
            "mxu": round(BATCH / t_mxu),
            "vpu": round(BATCH / t_vpu),
            "serial_0p5us_per_step": round(BATCH / t_serial_opt),
            # same 0.5 us dependent-step floor, under the SELECTED
            # per-bank strategies (pair/pallas/halo): the ceiling the
            # step-count reduction pipeline actually unlocks.
            "serial_0p5us_selected": round(
                BATCH / (max(selected_steps, 1) * 0.5e-6)),
        },
        "banks": detail,
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
