#!/usr/bin/env python
"""One-shot cross-plane timeline capture (ISSUE 17).

Fetches /__pingoo/timeline from the Python listener plane and —
optionally — the native C++ httpd, merges the two Chrome-trace dumps
into ONE file, and writes it to disk, ready for Perfetto
(https://ui.perfetto.dev) or chrome://tracing.

The merge is plain traceEvents concatenation: every plane stamps the
same CLOCK_MONOTONIC timebase (obs/timeline.py module docstring), so
spans from both dumps already share the x-axis on the same machine.
Each dump carries a `clock` block (monotonic now + wall now); the
merged file keeps both blocks under `clocks` plus the derived
wall-time offset so a post-processor can pin spans to UTC.

Usage:
    python tools/timeline_capture.py [--port 8080] [--native-port N]
                                     [--out timeline.json]

Sampling must be on (PINGOO_TIMELINE_SAMPLE > 0) for the Python dump
to carry spans; the native dump always carries the last-256-requests
flight window regardless.
"""

import argparse
import json
import sys
import urllib.error
import urllib.request


def fetch(port: int, host: str = "127.0.0.1") -> dict:
    url = f"http://{host}:{port}/__pingoo/timeline"
    req = urllib.request.Request(url,
                                 headers={"user-agent": "timeline-capture"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def merge(python_dump: dict, native_dump: dict | None) -> dict:
    events = list(python_dump.get("traceEvents", []))
    clocks = {"python": python_dump.get("clock", {})}
    if native_dump is not None:
        events.extend(native_dump.get("traceEvents", []))
        clocks["native"] = native_dump.get("clock", {})
    out = {
        "displayTimeUnit": "ms",
        "clocks": clocks,
        "otherData": python_dump.get("otherData", {}),
        "traceEvents": events,
    }
    py_clock, na_clock = clocks.get("python"), clocks.get("native")
    if py_clock and na_clock and py_clock.get("wall_now_s") \
            and na_clock.get("wall_now_s"):
        # Both clocks read CLOCK_MONOTONIC; on one machine the offset
        # between the two dumps' (monotonic, wall) pairs is just the
        # capture skew — report it so a reader can sanity-check the
        # shared-timebase assumption (should be ~the fetch gap).
        skew_s = (
            (py_clock["monotonic_now_us"] - na_clock["monotonic_now_us"])
            / 1e6 - (py_clock["wall_now_s"] - na_clock["wall_now_s"]))
        out["clocks"]["capture_skew_s"] = round(skew_s, 3)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080,
                    help="python listener plane port")
    ap.add_argument("--native-port", type=int, default=0,
                    help="native httpd port (0 = skip the native dump)")
    ap.add_argument("--out", default="timeline.json")
    args = ap.parse_args(argv)

    try:
        python_dump = fetch(args.port, args.host)
    except (urllib.error.URLError, OSError, ValueError) as exc:
        print(f"timeline-capture: python plane at :{args.port} "
              f"unreachable: {exc}", file=sys.stderr)
        return 1
    native_dump = None
    if args.native_port:
        try:
            native_dump = fetch(args.native_port, args.host)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            print(f"timeline-capture: warning: native plane at "
                  f":{args.native_port} unreachable ({exc}); python-"
                  f"plane-only capture", file=sys.stderr)

    merged = merge(python_dump, native_dump)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    spans = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
    planes = "python+native" if native_dump is not None else "python"
    print(f"timeline-capture: wrote {args.out} ({spans} spans, "
          f"{planes}); open in https://ui.perfetto.dev")
    if spans == 0:
        print("timeline-capture: note: 0 spans — is "
              "PINGOO_TIMELINE_SAMPLE set on the server?",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
