#!/usr/bin/env python
"""Mesh-serving smoke (make mesh-smoke; ISSUE 6 satellite).

Boots the LIVE serving path on an 8-fake-device CPU backend
(`XLA_FLAGS=--xla_force_host_platform_device_count=8`) with
`PINGOO_MESH=2x2x2` and proves, offline and in ~a minute:

  * mesh-served verdicts are bit-identical to the single-device path
    (the shadow-parity auditor runs over the mesh batches too and its
    mismatch counter stays 0);
  * the continuous-batching scheduler drives the launches, and an
    artificially tight PINGOO_DEADLINE_MS moves the deadline-miss
    counter;
  * the `pingoo_sched_*` + `pingoo_mesh_devices` series export through
    the shared registry and the exposition passes the Prometheus lint.

Offline-safe like the analyze passes: when jax is unavailable the
smoke SKIPS WITH A WARNING (exit 0) instead of failing the gate. The
work happens in a re-exec'd child so the forced virtual-device count
is set before jax initializes, whatever the parent environment pinned.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def parent() -> int:
    try:
        import jax  # noqa: F401
    except Exception as exc:
        print(f"mesh smoke SKIPPED: jax unavailable ({exc!r})")
        return 0
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PINGOO_MESH"] = "2x2x2"
    env["PINGOO_PARITY_SAMPLE"] = "1"
    env.pop("PINGOO_DEADLINE_MS", None)
    env.pop("PINGOO_SCHED_MODE", None)
    env.pop("PINGOO_SCHED_FAILOPEN", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, cwd=REPO, timeout=900)
    return proc.returncode


def child() -> int:
    import asyncio
    import random

    import numpy as np

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine.service import VerdictService
    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.registry import lint_prometheus_text
    from test_parity import LISTS, RULE_SOURCES, make_rules, \
        random_requests

    reqs = random_requests(random.Random(2026), 48)

    def serve(mesh, deadline_ms=None):
        os.environ["PINGOO_MESH"] = mesh
        if deadline_ms is not None:
            os.environ["PINGOO_DEADLINE_MS"] = deadline_ms
        plan = compile_ruleset(make_rules(RULE_SOURCES), LISTS)
        svc = VerdictService(plan, LISTS, use_device=True, max_batch=64)

        async def flow():
            await svc.start()
            try:
                return await asyncio.gather(
                    *[svc.evaluate(r) for r in reqs])
            finally:
                await svc.stop()

        return svc, asyncio.run(flow())

    ref_svc, want = serve("1x1x1")
    check(not ref_svc.mesh.active, "single-device reference served")
    svc, got = serve("2x2x2")
    check(svc.mesh.active and svc.mesh.devices == 8,
          "2x2x2 mesh active on 8 fake devices")
    check(svc.sched.metrics.mesh_devices.value == 8,
          "pingoo_mesh_devices gauge reports 8")
    identical = all(
        w.action == g.action and w.verified_block == g.verified_block
        and np.array_equal(w.matched, g.matched)
        for w, g in zip(want, got))
    check(identical, "mesh-served verdicts bit-identical to "
                     "single-device")
    check(svc.sched.launches > 0, "scheduler drove the mesh launches")
    check(svc.parity is not None and svc.parity.flush(30),
          "parity auditor drained over mesh batches")
    check(svc.parity.checked_total.value > 0,
          "parity auditor audited mesh-served traffic")
    check(svc.parity.mismatch_total.value == 0,
          "parity mismatch counter stayed 0 under dp/tp sharding")

    # Tight-deadline burst: the miss counter must move (a CPU backend
    # cannot verdict a batch inside 1 microsecond).
    miss_svc, _ = serve("2x2x2", deadline_ms="0.001")
    check(miss_svc.sched.deadline_misses > 0,
          "deadline-miss counter moves under a tight "
          "PINGOO_DEADLINE_MS")

    text = REGISTRY.prometheus_text()
    problems = lint_prometheus_text(text)
    check(not problems, f"prometheus lint clean {problems[:3]}")
    for name in ("pingoo_sched_queue_depth", "pingoo_sched_batch_size",
                 "pingoo_sched_deadline_miss_total",
                 "pingoo_sched_failopen_total", "pingoo_mesh_devices"):
        check(f'{name}' in text
              and f'plane="python"' in text,
              f"scrape exposes {name}")

    if FAILURES:
        print(f"\nmesh smoke FAILED ({len(FAILURES)} problems)")
        return 1
    print(json.dumps({
        "mesh": "2x2x2", "devices": 8,
        "launches": svc.sched.launches,
        "parity_checked": svc.parity.checked_total.value,
        "deadline_misses_tight": miss_svc.sched.deadline_misses,
    }))
    print("\nmesh smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else parent())
