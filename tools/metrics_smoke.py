#!/usr/bin/env python
"""Live metrics smoke (make metrics-smoke; ISSUE 2 satellite).

Boots the real serving pieces on loopback — native C++ httpd + shm ring
+ Python ring sidecar, plus an in-process Python HttpListener — drives
a few requests through both planes, scrapes BOTH /__pingoo/metrics
endpoints in BOTH formats, and validates:

  * Prometheus text passes the exposition lint on both planes;
  * every shared metric name (obs/schema.py) appears on both planes;
  * JSON (Accept: application/json) parses and keeps the legacy keys;
  * the native JSON carries the shm ring telemetry block;
  * a normal response carries x-pingoo-trace-id.

ISSUE 5 additions (verdict provenance): the shadow-parity auditor runs
against the live traffic on BOTH engine planes (PINGOO_PARITY_SAMPLE=1
below), a fault-injected path proves an oracle divergence is observable
via the mismatch counters AND the flight-recorder dump, the
/__pingoo/flightrecorder endpoints answer on both the Python listener
and the native httpd, and /__pingoo/explain returns per-rule provenance
that agrees with the interpreter.

Runs on the CPU backend (JAX_PLATFORMS=cpu) in ~a minute; exits 0/1.
"""

import asyncio
import http.server
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Provenance live checks: audit every batch, and let the chaos knob
# inject an ORACLE-side divergence for this one path (the served
# verdicts stay correct — that is the point of the auditor).
os.environ.setdefault("PINGOO_PARITY_SAMPLE", "1")
FAULT_PATH = "/__parity-fault"
os.environ.setdefault("PINGOO_PARITY_FAULT_INJECT", FAULT_PATH)
# Perf ledger + timeline live checks (ISSUE 17 satellite): sample every
# batch and append compile events to a throwaway JSONL so the smoke can
# assert the /__pingoo/compileledger + /__pingoo/timeline endpoints see
# real traffic. Must be set before the pingoo imports (the singletons
# read the env once at construction).
_PERF_TMP = tempfile.mkdtemp(prefix="pingoo-perf-smoke-")
os.environ.setdefault("PINGOO_TIMELINE_SAMPLE", "1")
os.environ.setdefault("PINGOO_PERF_LEDGER",
                      os.path.join(_PERF_TMP, "PERF_LEDGER.jsonl"))
os.environ.setdefault("PINGOO_COST_LEDGER",
                      os.path.join(_PERF_TMP, "COST_LEDGER.json"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, accept=None, ua="smoke/1.0", timeout=10):
    headers = {"user-agent": ua}
    if accept:
        headers["accept"] = accept
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return (r.status, {k.lower(): v for k, v in r.headers.items()},
                r.read())


def validate_plane(label, port, shared_names, lint):
    status, headers, body = _get(port, "/__pingoo/metrics")
    check(status == 200, f"{label}: scrape status 200")
    check("text/plain" in headers.get("content-type", ""),
          f"{label}: default exposition is Prometheus text")
    text = body.decode()
    problems = lint(text)
    check(not problems, f"{label}: prometheus lint clean {problems[:3]}")
    for name in sorted(shared_names):
        check(name in text, f"{label}: exposes {name}")
    status, headers, body = _get(port, "/__pingoo/metrics",
                                 accept="application/json")
    check("application/json" in headers.get("content-type", ""),
          f"{label}: JSON under Accept: application/json")
    payload = json.loads(body)
    return text, payload


def main() -> int:
    from pingoo_tpu import native_ring
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.engine.service import VerdictService
    from pingoo_tpu.expr import compile_expression
    from pingoo_tpu.host.httpd import HttpListener
    from pingoo_tpu.native_ring import Ring, RingSidecar
    from pingoo_tpu.obs import schema
    from pingoo_tpu.obs.registry import lint_prometheus_text
    from pingoo_tpu.obs.trace import TRACE_HEADER

    if not native_ring.ensure_built():
        print("native toolchain unavailable; smoke needs g++")
        return 1
    subprocess.run(["make", "-C", native_ring.NATIVE_DIR, "httpd"],
                   check=True, capture_output=True)

    import tempfile

    tmp = tempfile.mkdtemp(prefix="pingoo-metrics-smoke-")

    class Upstream(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"up"
            self.send_response(200)
            self.send_header("content-length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    upstream = http.server.HTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=upstream.serve_forever, daemon=True).start()

    rules = [RuleConfig(name="waf", actions=(Action.BLOCK,),
                        expression=compile_expression(
                            'http_request.path.starts_with("/.env")'))]
    plan = compile_ruleset(rules, {})

    ring_path = os.path.join(tmp, "ring")
    ring = Ring(ring_path, capacity=1024, create=True)
    sidecar = RingSidecar(ring, plan, {}, max_batch=128)
    threading.Thread(target=sidecar.run, daemon=True).start()

    nport = _free_port()
    httpd = subprocess.Popen(
        [os.path.join(native_ring.NATIVE_DIR, "httpd"), str(nport),
         ring_path, "127.0.0.1", str(upstream.server_address[1])],
        stdout=subprocess.PIPE)
    assert b"listening" in httpd.stdout.readline()
    time.sleep(0.3)

    shared = set(schema.SHARED_METRICS) | {schema.SHARED_WAIT_HISTOGRAM}

    class _NoCaptcha:
        # The smoke drives no captcha flow; a stub avoids requiring the
        # 'cryptography' package (CaptchaManager generates an Ed25519
        # key at construction).
        def serve(self, *a):
            return 404, [], b""

        def is_verified(self, *a):
            return False

    async def python_plane():
        svc = VerdictService(plan, {}, use_device=True)
        await svc.start()
        listener = HttpListener(
            name="smoke", host="127.0.0.1", port=0, services=[],
            verdict=svc, lists={}, rules_meta=plan.rules,
            captcha=_NoCaptcha())
        await listener.bind()
        port = listener.bound_port
        serve = asyncio.create_task(listener.serve_forever())

        def drive():
            try:
                _get(port, "/hello")
                check(False, "python: plain request served (404, no svc)")
            except urllib.error.HTTPError as e:
                check(e.code == 404,
                      "python: plain request served (404, no svc)")
                check(e.headers.get(TRACE_HEADER) is not None,
                      "python: response carries x-pingoo-trace-id")
            try:
                _get(port, "/.env")
                check(False, "python: /.env blocked")
            except urllib.error.HTTPError as e:
                check(e.code == 403, "python: /.env blocked 403")
            # Parity fault path: the ORACLE diverges here, the served
            # verdict stays correct (404: no service routes it).
            try:
                _get(port, FAULT_PATH)
            except urllib.error.HTTPError:
                pass
            # Both engine planes' auditors run off the hot path; drain
            # them so the counters below are deterministic.
            check(svc.parity is not None and svc.parity.flush(30),
                  "python: parity auditor drained")
            check(sidecar.parity is not None
                  and sidecar.parity.flush(30),
                  "sidecar: parity auditor drained")
            text, payload = validate_plane(
                "python", port, shared, lint_prometheus_text)
            for key in schema.PYTHON_JSON_KEYS:
                check(key in payload, f"python JSON: legacy key {key}")
            check("stages" in payload.get("verdict", {}),
                  "python JSON: per-stage verdict breakdown")
            check("provenance" in payload["verdict"]["stages"],
                  "python JSON: provenance stage instrumented")
            check("pingoo_ring_depth" in text,
                  "python scrape carries shm ring telemetry (sidecar)")
            # ISSUE 5 acceptance: with PINGOO_PARITY_SAMPLE>0 the
            # auditor ran against the live traffic and the mismatch
            # counters exist on BOTH planes under identical names.
            for plane in ("python", "sidecar"):
                check(f'pingoo_parity_checked_total{{plane="{plane}"}}'
                      in text, f"{plane}: parity checked counter")
                check(f'pingoo_parity_mismatch_total{{plane="{plane}"}}'
                      in text, f"{plane}: parity mismatch counter")
            check(svc.parity.checked_total.value > 0,
                  "python: auditor audited live traffic")
            check(sidecar.parity.checked_total.value > 0,
                  "sidecar: auditor audited live traffic")
            check(svc.parity.mismatch_total.value > 0,
                  "python: injected divergence observable via metrics")
            check("pingoo_rule_hits_total" in text,
                  "scrape carries per-rule attribution series")
            # ISSUE 6: the continuous-batching scheduler + mesh gauge
            # export on BOTH engine planes under identical names.
            for plane in ("python", "sidecar"):
                for name in ("pingoo_sched_queue_depth",
                             "pingoo_sched_deadline_miss_total",
                             "pingoo_sched_failopen_total",
                             "pingoo_mesh_devices"):
                    check(f'{name}{{plane="{plane}"}}' in text,
                          f"{plane}: sched metric {name}")
                check(f'pingoo_sched_batch_size_bucket{{le="1",'
                      f'plane="{plane}"}}' in text,
                      f"{plane}: sched batch-size histogram")
            check(svc.sched.launches > 0,
                  "python: scheduler drove live launches")
            check(sidecar.sched.launches > 0,
                  "sidecar: scheduler drove live launches")
            check(svc.sched.metrics.mesh_devices.value == 1
                  and sidecar.sched.metrics.mesh_devices.value == 1,
                  "mesh gauge reports single-device serving (no "
                  "PINGOO_MESH)")
            check("sched" in payload["verdict"]["stages"],
                  "python JSON: sched stage instrumented")
            # Flight recorder: the listener dumps every co-resident
            # plane; the injected divergence must appear in it with
            # full provenance.
            status, _hdrs, body = _get(port, "/__pingoo/flightrecorder")
            check(status == 200, "python: flightrecorder endpoint 200")
            fr = json.loads(body)
            check({"python", "sidecar"} <= set(fr.get("planes", {})),
                  "flightrecorder dump covers python + sidecar planes")
            mismatches = [
                e for e in fr["planes"]["python"]["entries"]
                if e["parity"] == "mismatch"]
            check(bool(mismatches),
                  "injected divergence observable in flightrecorder dump")
            check(mismatches and "parity_detail" in mismatches[0],
                  "flightrecorder mismatch carries provenance detail")
            # Explain endpoint: per-rule provenance for one request.
            status, _hdrs, body = _get(
                port, "/__pingoo/explain?path=/.env")
            check(status == 200, "python: explain endpoint 200")
            ex = json.loads(body)
            check(ex.get("action") == 1 and "waf" in ex.get(
                "matched_rules", []),
                "explain: device verdict + matched rule names")
            check(ex.get("parity", {}).get("consistent") is True,
                  "explain: interpreter agrees with device path")
            # Perf metric series (ISSUE 17): present on BOTH planes at
            # boot (ensure_instruments), moving where traffic ran.
            for plane in ("python", "sidecar"):
                for name in ("pingoo_compile_total",
                             "pingoo_timeline_spans_total",
                             "pingoo_costmodel_reload_total"):
                    check(f'plane="{plane}"' in "".join(
                        ln for ln in text.splitlines()
                        if ln.startswith(name)),
                        f"{plane}: perf metric {name}")
            # Compile ledger endpoint: the warm-up compile of the
            # verdict fn must be on it (PINGOO_PERF_LEDGER set above).
            status, _hdrs, body = _get(port, "/__pingoo/compileledger")
            check(status == 200, "python: compileledger endpoint 200")
            ledger = json.loads(body)
            check(ledger.get("enabled") is True
                  and ledger.get("compiles_total", 0) >= 1,
                  "compileledger: warm-up compile recorded")
            check(any(e.get("fn") == "verdict"
                      for e in ledger.get("events", [])),
                  "compileledger: verdict fn compile event present")
            # Timeline endpoint: Chrome-trace JSON with real spans
            # (PINGOO_TIMELINE_SAMPLE=1 above samples every batch).
            status, _hdrs, body = _get(port, "/__pingoo/timeline")
            check(status == 200, "python: timeline endpoint 200")
            trace = json.loads(body)
            spans = [e for e in trace.get("traceEvents", [])
                     if e.get("ph") == "X"]
            check(bool(spans), "timeline: sampled batch spans exported")
            check("clock" in trace and "monotonic_now_us"
                  in trace["clock"], "timeline: clock pin block present")
            # On-demand profiler window (ISSUE 17 satellite): a bounded
            # capture starts, reports its trace dir, and refuses a
            # second concurrent window with 409.
            # First-ever start_trace pays a multi-second one-time
            # profiler init; give it headroom.
            status, _hdrs, body = _get(port,
                                       "/__pingoo/profile?seconds=1",
                                       timeout=90)
            check(status == 200, "python: profile endpoint 200")
            prof = json.loads(body)
            check(prof.get("profiling") is True and prof.get("dir"),
                  "profile: bounded window started with trace dir")
            try:
                _get(port, "/__pingoo/profile?seconds=1")
                check(False, "profile: concurrent window refused 409")
            except urllib.error.HTTPError as e:
                check(e.code == 409,
                      "profile: concurrent window refused 409")
            # SIGTERM drain path: ensure_trace_stopped flushes the live
            # window synchronously and is idempotent (host/server.py
            # calls it unconditionally from the drain finally block).
            svc.ensure_trace_stopped()
            svc.ensure_trace_stopped()
            check(not getattr(svc, "_tracing", True),
                  "profile: ensure_trace_stopped idempotent + flushed")
            check(os.path.isdir(prof["dir"])
                  and bool(os.listdir(prof["dir"])),
                  "profile: flushed trace dir is non-empty")

        await asyncio.get_running_loop().run_in_executor(None, drive)
        serve.cancel()
        await listener.close()
        await svc.stop()

    try:
        # Drive the native plane first so counters are non-zero (the
        # parity fault path rides along: its oracle-side divergence
        # lands on the SIDECAR plane's auditor).
        for path in ("/ok", "/.env", "/ok2", FAULT_PATH):
            try:
                _get(nport, path)
            except urllib.error.HTTPError:
                pass
        text, payload = validate_plane(
            "native", nport, shared, lint_prometheus_text)
        for key in schema.NATIVE_JSON_KEYS:
            check(key in payload, f"native JSON: legacy key {key}")
        check("ring" in payload and "depth_hwm" in payload["ring"],
              "native JSON: shm ring telemetry block")
        check(payload["ring"]["enqueued"] >= 2,
              "native JSON: ring enqueued counter moved")
        check(text.rstrip().endswith(tuple("0123456789")),
              "native prometheus body complete (no truncation)")
        # Native-plane flight recorder: its own C++ ring at the same
        # endpoint path both Python planes use.
        status, _hdrs, body = _get(nport, "/__pingoo/flightrecorder")
        check(status == 200, "native: flightrecorder endpoint 200")
        nfr = json.loads(body)
        check(nfr.get("plane") == "native" and nfr.get("entries"),
              "native: flightrecorder carries verdict records")
        check(any(e.get("decided") == 1 for e in nfr.get("entries", [])),
              "native: flightrecorder recorded the /.env block")
        # Native-plane timeline (ISSUE 17): Chrome-trace JSON from the
        # same flight stamps, mergeable with the python dump.
        status, _hdrs, body = _get(nport, "/__pingoo/timeline")
        check(status == 200, "native: timeline endpoint 200")
        ntl = json.loads(body)
        nxs = [e for e in ntl.get("traceEvents", [])
               if e.get("ph") == "X"]
        check(bool(nxs) and all(e["name"] == "verdict_wait"
                                for e in nxs),
              f"native: timeline carries verdict_wait spans ({len(nxs)})")
        check(ntl.get("clock", {}).get("unit") == "monotonic_us",
              "native: timeline clock pin block present")

        asyncio.run(python_plane())
        check(sidecar.parity is not None
              and sidecar.parity.mismatch_total.value > 0,
              "sidecar: injected divergence observable via metrics")
    finally:
        httpd.terminate()
        sidecar.stop()
        upstream.shutdown()
        ring.close()

    if FAILURES:
        print(f"\nmetrics smoke FAILED ({len(FAILURES)} problems)")
        return 1
    print("\nmetrics smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
