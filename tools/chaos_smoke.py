#!/usr/bin/env python
"""Sidecar supervision chaos smoke (make chaos-smoke; ISSUE 10).

Drives the liveness/reattach protocol (docs/RESILIENCE.md) through
REAL failures, offline and in ~a minute:

  * SIGKILL the sidecar process mid-batch (PINGOO_CHAOS=kill) with
    batches in flight, restart it, and prove crash-reattach
    reconciliation: every orphaned ticket (dequeued by the dead epoch,
    never answered) resolves EXACTLY once, with the verdict the rules
    demand — zero lost tickets, zero double-posts, p99
    enqueue->resolution bounded through the outage
    (`degraded_failopen_p99_ms`);
  * SIGKILL mid-megastep (PINGOO_MEGASTEP=force; ISSUE 12): the victim
    dies with a K-slice device window in flight — more rows stranded
    than one batch can hold — and reattach re-evaluates every orphaned
    slice row exactly once while the new generation keeps serving in
    megastep mode;
  * heartbeat freeze (PINGOO_CHAOS=heartbeat_freeze): the ring
    heartbeat goes stale within the detection window while the drain
    loop itself keeps serving — the liveness detector reads the
    protocol, not process existence;
  * injected device failure + verdict-ring-full stalls
    (PINGOO_CHAOS=xla_error,verdict_full): the degradation ladder
    demotes instead of crashing, every verdict still bit-exact;
  * ruleset swap storm (PINGOO_CHAOS=swap_storm; ISSUE 11): hot-swaps
    hammered at batch boundaries under live load, plus explicit
    multi-tenant request_swap calls racing the storm — zero lost or
    double-posted verdicts, bit-exact across every epoch, swap pause
    p99 inside the configured deadline budget (`swap_pause_p99_ms`).

Offline-safe like mesh-smoke: skips with a warning (exit 0) when jax
or the native toolchain is unavailable. The work happens in a
re-exec'd child under a controlled environment; the killable sidecar
runs as its OWN process (`--sidecar`) so SIGKILL exercises the real
no-cleanup crash path.

With BENCH_HISTORY=1 the summary appends to BENCH_history.jsonl under
backend "chaos-cpu", so tools/bench_regress.py gates
degraded_failopen_p99_ms across runs.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []

N_KILL = 64        # scenario A requests
N_MEGA = 64        # scenario A2 pre-kill requests (ISSUE 12)
N_MEGA_EXTRA = 32  # scenario A2 post-reattach requests
N_LADDER = 48      # scenario C requests
N_SWAP = 96        # scenario D requests
MAX_BATCH = 16
P99_BOUND_MS = 30000.0  # hard outage bound (CI CPU: jit + restart)
# Swap-pause budget for CI CPU: the drain of in-flight batches inside
# the pause window runs jit'd computations on the host; on a real
# accelerator the default PINGOO_DEADLINE_MS (2ms) is the bound.
SWAP_P99_BOUND_MS = 1000.0


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def make_plan():
    """The shared ruleset BOTH sidecar generations compile — verdicts
    are deterministic, so the smoke can assert exact actions without a
    reference run."""
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    rules = [
        RuleConfig(name="blk", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.path.starts_with("/evil")')),
        RuleConfig(name="ua", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.user_agent.contains("chaosbot")')),
    ]
    return compile_ruleset(rules, {})


def req_fields(i: int) -> dict:
    evil = i % 3 == 0
    bot = i % 7 == 0
    path = (f"/evil/{i}" if evil else f"/fine/{i}").encode()
    return {"method": b"GET", "host": b"chaos.test", "path": path,
            "url": path, "user_agent": b"chaosbot" if bot else b"ua",
            "ip": b"\x00" * 15 + bytes([i % 251 + 1])}


def want_action(i: int) -> int:
    return 1 if (i % 3 == 0 or i % 7 == 0) else 0


def parent() -> int:
    try:
        import jax  # noqa: F401
    except Exception as exc:
        print(f"chaos smoke SKIPPED: jax unavailable ({exc!r})")
        return 0
    from pingoo_tpu import native_ring

    if not native_ring.ensure_built():
        print("chaos smoke SKIPPED: native toolchain unavailable")
        return 0
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PINGOO_PARITY_SAMPLE"] = "1"
    for k in ("PINGOO_CHAOS", "PINGOO_DFA", "PINGOO_MESH",
              "PINGOO_DEADLINE_MS", "PINGOO_SCHED_MODE",
              "PINGOO_SCHED_FAILOPEN", "PINGOO_PIPELINE",
              "PINGOO_PIPELINE_DEPTH", "PINGOO_MEGASTEP",
              "PINGOO_MEGASTEP_K"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, cwd=REPO, timeout=900)
    return proc.returncode


def sidecar_main(ring_path: str, ready_path: str) -> int:
    """The killable sidecar generation: attach to the existing ring,
    signal readiness, drain until PINGOO_CHAOS kills the process."""
    from pingoo_tpu.native_ring import Ring, RingSidecar

    ring = Ring(ring_path, capacity=256, create=False)
    plan = make_plan()
    sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
    with open(ready_path, "w") as f:
        f.write(f"epoch={sidecar.epoch}\n")
    sidecar.run()  # no request cap: PINGOO_CHAOS=kill ends this
    return 0


def _poller(ring, got: dict, stop, need: int):
    """Continuous verdict consumer: ticket -> list of (action, t_mono)
    so arrival latency is measured at arrival, and a double-post would
    surface as a second entry."""
    while not stop() and sum(len(v) for v in got.values()) < need:
        v = ring.poll_verdict()
        if v is None:
            time.sleep(0.001)
            continue
        got.setdefault(v[0], []).append((v[1], time.monotonic()))


def scenario_kill_reattach(tmp: str) -> dict:
    """SIGKILL mid-batch -> restart -> reconciliation, exactly once."""
    import threading

    from pingoo_tpu.native_ring import Ring, RingSidecar

    print("-- scenario: sidecar kill mid-batch + crash-reattach --")
    ring_path = os.path.join(tmp, "ring")
    ready_path = os.path.join(tmp, "ready")
    ring = Ring(ring_path, capacity=256, create=True)
    env = dict(os.environ)
    # pause briefly then SIGKILL after the first completed batch: the
    # run loop dispatches batch 2 BEFORE completing batch 1, so the
    # kill always strands dequeued-but-unposted tickets.
    env["PINGOO_CHAOS"] = "pause:100:1,kill:1"
    env["PINGOO_PIPELINE_DEPTH"] = "2"
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--sidecar",
         ring_path, ready_path], env=env, cwd=REPO)
    deadline = time.time() + 300
    while not os.path.exists(ready_path) and time.time() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    check(os.path.exists(ready_path), "victim sidecar came up (epoch 1)")

    got: dict = {}
    stop_poll = False
    poll = threading.Thread(target=_poller,
                            args=(ring, got, lambda: stop_poll, N_KILL),
                            daemon=True)
    poll.start()
    enq_t = {}
    for i in range(N_KILL):
        tk = ring.enqueue(**req_fields(i))
        if tk is None:
            check(False, f"enqueue {i} hit a full ring")
            continue
        enq_t[tk] = time.monotonic()
    proc.wait(timeout=240)
    check(proc.returncode == -9,
          f"victim sidecar died by SIGKILL (rc={proc.returncode})")
    lv = ring.liveness()
    orphans = lv["req_tail"] - lv["posted_floor"]
    check(lv["epoch"] == 1, f"epoch 1 before reattach ({lv['epoch']})")
    check(orphans >= 1,
          f"kill stranded dequeued-but-unposted tickets ({orphans})")

    # Restart: a new epoch reconciles the orphans in __init__, then
    # serves the still-queued remainder.
    plan = make_plan()
    sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
    check(sidecar.epoch == 2, f"reattach bumped epoch ({sidecar.epoch})")
    rec = dict(sidecar.reconciled)
    check(rec["reeval"] + rec["failopen"] == orphans,
          f"reconciled exactly the orphan window ({rec} vs {orphans})")
    check(rec["reeval"] == orphans,
          f"orphan bytes survived -> re-evaluated, not failed open "
          f"({rec})")
    remaining = N_KILL - lv["req_tail"]
    worker = threading.Thread(target=sidecar.run,
                              kwargs={"max_requests": remaining},
                              daemon=True)
    worker.start()
    deadline = time.time() + 240
    while time.time() < deadline and \
            sum(len(v) for v in got.values()) < N_KILL:
        time.sleep(0.01)
    stop_poll = True
    poll.join(timeout=5)
    sidecar.stop()
    worker.join(timeout=30)

    lost = [t for t in enq_t if t not in got]
    doubles = {t: [a for a, _ in v] for t, v in got.items() if len(v) > 1}
    check(not lost, f"zero lost tickets ({len(lost)} lost: {lost[:5]})")
    check(not doubles, f"zero double-posted tickets ({doubles})")
    wrong = [t for t, v in got.items()
             if (v[0][0] & 3) != want_action(t)]
    check(not wrong,
          f"verdicts bit-exact across crash+reattach ({wrong[:5]})")
    if sidecar.parity is not None:
        check(sidecar.parity.flush(30), "parity auditor drained")
        check(sidecar.parity.mismatch_total.value == 0,
              "parity clean over post-reattach batches")
    lats = sorted((v[0][1] - enq_t[t]) * 1e3 for t, v in got.items()
                  if t in enq_t)
    p99 = lats[max(0, int(len(lats) * 0.99) - 1)] if lats else -1.0
    check(0 < p99 < P99_BOUND_MS,
          f"p99 enqueue->resolution bounded through the outage "
          f"({p99:.0f}ms < {P99_BOUND_MS:.0f}ms)")
    ring.close()
    return {"orphans": orphans, "reconciled": rec,
            "degraded_failopen_p99_ms": round(p99, 1)}


def scenario_kill_mid_megastep(tmp: str) -> dict:
    """SIGKILL with a K-slice megastep window in flight (ISSUE 12):
    the chaos kill fires after the window's FIRST resolved slice, so
    the victim dies holding K-1 computed-but-unposted slices. The
    reattach must re-evaluate every stranded row exactly once, and the
    new generation must resume serving IN megastep mode."""
    import threading

    from pingoo_tpu.native_ring import Ring, RingSidecar

    print("-- scenario: SIGKILL mid-megastep window + crash-reattach --")
    ring_path = os.path.join(tmp, "ring_mega")
    ready_path = os.path.join(tmp, "ready_mega")
    ring = Ring(ring_path, capacity=256, create=True)
    enq_t = {}
    # Enqueue the whole pre-kill stream BEFORE the victim attaches: its
    # drain then fills a full K=4 window immediately, so the kill
    # deterministically lands with multiple slices in flight instead of
    # racing the enqueuer into a short idle-drain window.
    for i in range(N_MEGA):
        tk = ring.enqueue(**req_fields(i))
        if tk is None:
            check(False, f"enqueue {i} hit a full ring")
            continue
        enq_t[tk] = time.monotonic()
    need_total = N_MEGA + N_MEGA_EXTRA
    got: dict = {}
    stop_poll = False
    poll = threading.Thread(
        target=_poller, args=(ring, got, lambda: stop_poll, need_total),
        daemon=True)
    poll.start()
    env = dict(os.environ)
    env["PINGOO_CHAOS"] = "kill:1"
    env["PINGOO_MEGASTEP"] = "force"
    env["PINGOO_MEGASTEP_K"] = "4"
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--sidecar",
         ring_path, ready_path], env=env, cwd=REPO)
    proc.wait(timeout=300)
    check(proc.returncode == -9,
          f"victim died by SIGKILL mid-window (rc={proc.returncode})")
    lv = ring.liveness()
    orphans = lv["req_tail"] - lv["posted_floor"]
    check(lv["epoch"] == 1, f"epoch 1 before reattach ({lv['epoch']})")
    # The proof the kill landed MID-window: more rows stranded than a
    # single per-batch dispatch could ever hold in flight.
    check(orphans > MAX_BATCH,
          f"kill stranded multiple window slices ({orphans} rows > one "
          f"{MAX_BATCH}-row batch)")

    plan = make_plan()
    os.environ["PINGOO_MEGASTEP"] = "force"
    os.environ["PINGOO_MEGASTEP_K"] = "4"
    try:
        sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
    finally:
        del os.environ["PINGOO_MEGASTEP"]
        del os.environ["PINGOO_MEGASTEP_K"]
    check(sidecar.epoch == 2, f"reattach bumped epoch ({sidecar.epoch})")
    rec = dict(sidecar.reconciled)
    check(rec["reeval"] == orphans,
          f"every in-flight slice row re-evaluated exactly once "
          f"({rec} vs {orphans} orphans)")
    # Fresh load for the reattached generation: it must serve these
    # through megastep windows, not fall back to per-batch dispatch.
    for i in range(N_MEGA, need_total):
        tk = ring.enqueue(**req_fields(i))
        if tk is None:
            check(False, f"post-reattach enqueue {i} hit a full ring")
            continue
        enq_t[tk] = time.monotonic()
    remaining = need_total - lv["req_tail"]
    worker = threading.Thread(target=sidecar.run,
                              kwargs={"max_requests": remaining},
                              daemon=True)
    worker.start()
    deadline = time.time() + 240
    while time.time() < deadline and \
            sum(len(v) for v in got.values()) < need_total:
        time.sleep(0.01)
    stop_poll = True
    poll.join(timeout=5)
    sidecar.stop()
    worker.join(timeout=30)

    lost = [t for t in enq_t if t not in got]
    doubles = {t: [a for a, _ in v] for t, v in got.items()
               if len(v) > 1}
    check(not lost, f"zero lost tickets ({len(lost)} lost: {lost[:5]})")
    check(not doubles, f"zero double-posted tickets ({doubles})")
    wrong = [t for t, v in got.items()
             if (v[0][0] & 3) != want_action(t)]
    check(not wrong,
          f"verdicts bit-exact across the mid-window crash ({wrong[:5]})")
    mega = sidecar.stats()["megastep"]
    check(mega["windows"] >= 1,
          f"reattached generation resumed in megastep mode "
          f"({mega['windows']} windows)")
    check(mega["echo_mismatch"] == 0,
          f"zero ruleset-epoch echo mismatches after reattach ({mega})")
    lats = sorted((v[0][1] - enq_t[t]) * 1e3 for t, v in got.items()
                  if t in enq_t)
    p99 = lats[max(0, int(len(lats) * 0.99) - 1)] if lats else -1.0
    check(0 < p99 < P99_BOUND_MS,
          f"p99 enqueue->resolution bounded through the outage "
          f"({p99:.0f}ms < {P99_BOUND_MS:.0f}ms)")
    ring.close()
    return {"megastep_orphans": orphans,
            "megastep_windows_after_reattach": mega["windows"]}


def scenario_heartbeat_freeze(tmp: str) -> dict:
    """Frozen heartbeat goes stale within the detection window while
    the drain loop keeps serving — liveness is protocol, not ps."""
    import threading

    from pingoo_tpu.native_ring import Ring, RingSidecar

    print("-- scenario: heartbeat freeze detection --")
    ring = Ring(os.path.join(tmp, "ring_hb"), capacity=64, create=True)
    os.environ["PINGOO_CHAOS"] = "heartbeat_freeze"
    try:
        plan = make_plan()
        sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
    finally:
        del os.environ["PINGOO_CHAOS"]
    t0 = time.monotonic()
    worker = threading.Thread(target=sidecar.run, daemon=True)
    worker.start()
    for i in range(8):
        ring.enqueue(**req_fields(i))
    got: dict = {}
    deadline = time.time() + 120
    while time.time() < deadline and len(got) < 8:
        v = ring.poll_verdict()
        if v is not None:
            got[v[0]] = v[1]
        time.sleep(0.005)
    check(len(got) == 8, f"frozen-heartbeat sidecar still serves "
                         f"({len(got)}/8)")
    detect_ms = None
    deadline = time.time() + 30
    while time.time() < deadline:
        lv = ring.liveness()
        age = lv["now_ms"] - lv["heartbeat_ms"]
        if age > 500:  # the PINGOO_SIDECAR_TIMEOUT_MS default
            detect_ms = (time.monotonic() - t0) * 1e3
            break
        time.sleep(0.02)
    check(detect_ms is not None,
          f"heartbeat went stale past the 500ms detection window "
          f"({detect_ms and round(detect_ms)}ms after attach)")
    sidecar.stop()
    worker.join(timeout=30)
    ring.close()
    return {"heartbeat_detect_ms": round(detect_ms or -1, 1)}


def scenario_ladder(tmp: str) -> dict:
    """Injected device failure + verdict-ring-full: the ladder demotes
    (counted), the posts retry, every verdict stays exact."""
    import threading

    from pingoo_tpu.native_ring import Ring, RingSidecar

    print("-- scenario: ladder demotion under injected faults --")
    ring = Ring(os.path.join(tmp, "ring_lad"), capacity=64, create=True)
    os.environ["PINGOO_CHAOS"] = "xla_error:1,verdict_full:2"
    try:
        plan = make_plan()
        sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
    finally:
        del os.environ["PINGOO_CHAOS"]
    enq = {}
    for i in range(N_LADDER):
        enq[ring.enqueue(**req_fields(i))] = i
    worker = threading.Thread(target=sidecar.run,
                              kwargs={"max_requests": N_LADDER},
                              daemon=True)
    worker.start()
    got: dict = {}
    deadline = time.time() + 240
    while time.time() < deadline and len(got) < N_LADDER:
        v = ring.poll_verdict()
        if v is not None:
            got.setdefault(v[0], []).append(v[1])
        time.sleep(0.001)
    sidecar.stop()
    worker.join(timeout=30)
    snap = sidecar.ladder.snapshot()
    errs = {r: s["errors"] for r, s in snap.items() if s["errors"]}
    check("xla" in sidecar.chaos._fired,
          "chaos injected the device failure")
    check(sidecar.chaos.verdict_full_budget == 0,
          "verdict-ring-full stalls were exercised")
    check(sum(errs.values()) >= 1,
          f"ladder counted the demotion ({errs})")
    check(len(got) == N_LADDER and all(len(v) == 1 for v in got.values()),
          f"all verdicts, exactly once ({len(got)}/{N_LADDER})")
    wrong = [t for t, v in got.items()
             if (v[0] & 3) != want_action(enq[t])]
    check(not wrong, f"verdicts bit-exact through demotion ({wrong[:5]})")
    ring.close()
    return {"ladder_errors": errs,
            "ladder_demoted_rungs": sidecar.ladder.demoted()}


def scenario_swap_storm(tmp: str) -> dict:
    """PINGOO_CHAOS=swap_storm hammers hot-swaps at batch boundaries
    under live load, racing explicit multi-tenant request_swap calls.
    Every swap installs the SAME compiled plan, so any verdict drift
    is a swap-protocol bug by construction."""
    import threading

    from pingoo_tpu.native_ring import Ring, RingSidecar

    print("-- scenario: ruleset swap storm under live load --")
    ring = Ring(os.path.join(tmp, "ring_swap"), capacity=256,
                create=True)
    os.environ["PINGOO_CHAOS"] = "swap_storm:2"
    try:
        plan = make_plan()
        sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
    finally:
        del os.environ["PINGOO_CHAOS"]
    worker = threading.Thread(target=sidecar.run, daemon=True)
    worker.start()
    got: dict = {}
    stop_poll = False
    poll = threading.Thread(target=_poller,
                            args=(ring, got, lambda: stop_poll, N_SWAP),
                            daemon=True)
    poll.start()
    tenants = ("acme", "globex", "initech", "umbrella")
    enq = {}
    swaps = []
    for i in range(N_SWAP):
        tk = ring.enqueue(**req_fields(i))
        if tk is None:
            check(False, f"enqueue {i} hit a full ring")
            continue
        enq[tk] = i
        if i and i % 24 == 0:
            # Explicit cross-tenant swaps racing the storm's implicit
            # ones — the engine state builds HERE (requester thread,
            # compile-ahead), never in the drain loop.
            swaps.append(sidecar.request_swap(
                plan, tenant=tenants[(i // 24) % len(tenants)]))
        time.sleep(0.002)
    for h in swaps:
        check(h.wait(120) and h.result == "ok",
              f"explicit tenant swap applied ({h.tenant}: {h.result})")
    deadline = time.time() + 240
    while time.time() < deadline and \
            sum(len(v) for v in got.values()) < N_SWAP:
        time.sleep(0.01)
    stop_poll = True
    poll.join(timeout=5)
    sidecar.stop()
    worker.join(timeout=30)

    lost = [t for t in enq if t not in got]
    doubles = {t: v for t, v in got.items() if len(v) > 1}
    check(not lost, f"zero lost tickets across swaps ({len(lost)} lost)")
    check(not doubles,
          f"zero double-posted tickets ({len(doubles)} doubled)")
    wrong = [t for t, v in got.items()
             if (v[0][0] & 3) != want_action(enq[t])]
    check(not wrong,
          f"verdicts bit-exact across every swap epoch ({wrong[:5]})")
    nswaps = len(sidecar.swap_pauses_ms)
    check(sidecar.ruleset_epoch >= 3,
          f"storm + explicit swaps applied ({sidecar.ruleset_epoch} "
          f"epochs over {sidecar.batches} batches)")
    check(nswaps == sidecar.ruleset_epoch,
          f"every applied swap recorded a pause ({nswaps} vs epoch "
          f"{sidecar.ruleset_epoch})")
    pauses = sorted(sidecar.swap_pauses_ms)
    p99 = pauses[max(0, int(len(pauses) * 0.99) - 1)] if pauses else -1.0
    check(0 <= p99 < SWAP_P99_BOUND_MS,
          f"swap pause p99 within budget ({p99:.1f}ms < "
          f"{SWAP_P99_BOUND_MS:.0f}ms)")
    ring.close()
    return {"swap_epochs": sidecar.ruleset_epoch,
            "swap_pause_p99_ms": round(p99, 2)}


def child() -> int:
    import tempfile

    summary = {"backend": "chaos-cpu"}
    with tempfile.TemporaryDirectory() as tmp:
        summary.update(scenario_kill_reattach(tmp))
        summary.update(scenario_kill_mid_megastep(tmp))
        summary.update(scenario_heartbeat_freeze(tmp))
        summary.update(scenario_ladder(tmp))
        summary.update(scenario_swap_storm(tmp))

    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.registry import lint_prometheus_text

    text = REGISTRY.prometheus_text()
    problems = lint_prometheus_text(text)
    check(not problems, f"prometheus lint clean {problems[:3]}")
    for name in ("pingoo_sidecar_epoch", "pingoo_reattach_reconciled_total",
                 "pingoo_degrade_total", "pingoo_chaos_injected_total",
                 "pingoo_ruleset_epoch", "pingoo_ruleset_swap_total",
                 "pingoo_megastep_k"):
        check(name in text, f"scrape exposes {name}")

    if FAILURES:
        print(f"\nchaos smoke FAILED ({len(FAILURES)} problems)")
        return 1
    print(json.dumps(summary))
    if os.environ.get("BENCH_HISTORY") == "1":
        summary["ts"] = time.time()
        path = os.environ.get("BENCH_HISTORY_FILE",
                              "BENCH_history.jsonl")
        with open(path, "a") as f:
            f.write(json.dumps(summary) + "\n")
    print("\nchaos smoke OK")
    return 0


if __name__ == "__main__":
    if "--sidecar" in sys.argv:
        i = sys.argv.index("--sidecar")
        sys.exit(sidecar_main(sys.argv[i + 1], sys.argv[i + 2]))
    sys.exit(child() if "--child" in sys.argv else parent())
