#!/usr/bin/env python
"""Perf-ledger + timeline smoke (make timeline-smoke; ISSUE 17).

Proves, offline and in ~a minute, that the observability tentpole
actually observes:

  * python plane: VerdictService under PINGOO_TIMELINE_SAMPLE=1 emits
    batch spans whose stage children NEST inside the batch span, the
    Chrome-trace export parses and carries the clock-pin block, and the
    compile ledger recorded the warm-up compiles with the JSONL file
    agreeing line-for-line with the in-memory totals;
  * sidecar plane: RingSidecar over a real shm ring emits sidecar spans
    plus the cross-plane ring-wait join rows under pid "native" (this
    half skips with a warning when the native toolchain is missing);
  * durable cost ledger: persist -> fresh CostModel reload round-trips
    the measured EWMAs bit-for-bit (result "ok"), and a fingerprint
    mismatch is discarded as "stale";
  * hot-path overhead: the measured cost of recording one sampled
    batch's spans is <2% of the mean live batch wall, and the
    unsampled-path cost (one sample() call) is nanoseconds.

Offline-safe like staging-smoke: when jax is unavailable the smoke
SKIPS WITH A WARNING (exit 0). The work happens in a re-exec'd child
under a controlled environment so a parent shell's perf/timeline knobs
cannot skew the run.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []

N_PY = 64       # python-plane requests
N_RING = 64     # sidecar-plane requests
MAX_BATCH = 16
OVERHEAD_ITERS = 400


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def parent() -> int:
    try:
        import jax  # noqa: F401
    except Exception as exc:
        print(f"timeline smoke SKIPPED: jax unavailable ({exc!r})")
        return 0
    tmp = tempfile.mkdtemp(prefix="pingoo-timeline-smoke-")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PINGOO_TIMELINE_SAMPLE"] = "1"
    env["PINGOO_PERF_LEDGER"] = os.path.join(tmp, "PERF_LEDGER.jsonl")
    env["PINGOO_COST_LEDGER"] = os.path.join(tmp, "COST_LEDGER.json")
    env["PINGOO_COMPILE_SURFACE"] = os.path.join(
        tmp, "COMPILE_SURFACE.json")
    for k in ("PINGOO_TIMELINE_N", "PINGOO_TIMELINE_ROWS",
              "PINGOO_PERF_LEDGER_N", "PINGOO_STAGING", "PINGOO_PIPELINE",
              "PINGOO_MEGASTEP", "PINGOO_MESH", "PINGOO_CHAOS",
              "PINGOO_PARITY_SAMPLE", "PINGOO_PROFILE_DIR"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, cwd=REPO, timeout=900)
    return proc.returncode


def _nesting_holds(spans, batch_tid) -> tuple:
    """Every stage child on the batch lane must lie inside one of that
    lane's batch spans (1 us slack for float rounding)."""
    batches = [(t0, t0 + dur) for plane, tid, name, t0, dur, *_ in spans
               if tid == batch_tid and name == "batch"]
    children = [(name, t0, t0 + dur)
                for plane, tid, name, t0, dur, *_ in spans
                if tid == batch_tid and name != "batch"]
    orphans = [name for name, a, b in children
               if not any(a >= b0 - 1.0 and b <= b1 + 1.0
                          for b0, b1 in batches)]
    return len(batches), len(children), orphans


def _python_plane() -> dict:
    import asyncio
    import random

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine.service import VerdictService
    from pingoo_tpu.obs.perf import get_compile_ledger
    from pingoo_tpu.obs.timeline import Timeline, get_timeline
    from pingoo_tpu.sched.scheduler import CostModel, load_cost_ledger
    from test_parity import LISTS, RULE_SOURCES, make_rules, \
        random_requests

    reqs = random_requests(random.Random(1701), N_PY)
    plan = compile_ruleset(make_rules(RULE_SOURCES), LISTS)
    svc = VerdictService(plan, LISTS, use_device=True, max_batch=32)
    check(svc.cost_ledger_result == "missing",
          f"cost ledger: first boot reload is 'missing' "
          f"(got {svc.cost_ledger_result!r})")

    async def flow():
        await svc.start()
        t0 = time.monotonic()
        try:
            await asyncio.gather(*[svc.evaluate(r) for r in reqs])
        finally:
            elapsed = time.monotonic() - t0
            await svc.stop()
        return elapsed

    serve_wall_s = asyncio.run(flow())

    # -- timeline: export parses, spans nest ---------------------------
    tl = get_timeline()
    check(tl.enabled and tl.rate == 1.0, "timeline sampling enabled")
    trace = json.loads(tl.chrome_trace_json())
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    check(bool(xs), f"chrome trace parses with spans ({len(xs)})")
    check("clock" in trace and trace["clock"]["unit"] == "monotonic_us",
          "chrome trace carries the monotonic clock-pin block")
    with tl._lock:
        spans = list(tl.spans)
    n_b, n_c, orphans = _nesting_holds(spans, "python/batch")
    check(n_b > 0 and n_c > 0 and not orphans,
          f"python batch spans nest ({n_c} children in {n_b} batches, "
          f"orphans={orphans[:3]})")
    check(any(tid.startswith("python/req:")
              for _, tid, *_ in spans),
          "per-request lanes emitted on the python plane")

    # -- compile ledger: warm-up compiles + JSONL cross-check ----------
    ledger = get_compile_ledger()
    snap = ledger.snapshot()
    check(snap["enabled"], "compile ledger enabled")
    check(snap["totals"].get("python/verdict/cold", 0) >= 1,
          f"verdict warm-up compile on the ledger "
          f"(totals={snap['totals']})")
    with open(ledger.path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    check(len(lines) == snap["compiles_total"] and not snap["io_errors"],
          f"PERF_LEDGER.jsonl agrees with in-memory totals "
          f"({len(lines)} == {snap['compiles_total']})")
    check(all(ln.get("fingerprint") == svc._plan_fp for ln in lines
              if ln.get("plane") == "python"),
          "ledger events stamped with the plan fingerprint")

    # -- durable cost ledger: persist -> reload round trip -------------
    check(svc.persist_cost_ledger(), "cost ledger persisted on stop")
    fresh = CostModel()
    result = load_cost_ledger(
        fresh, backend=svc._backend_label, fingerprint=svc._plan_fp,
        plane="python")
    check(result == "ok", f"cost ledger reload result 'ok' "
                          f"(got {result!r})")
    check(fresh.snapshot() == svc.sched.cost.snapshot(),
          "reloaded CostModel EWMAs bit-identical to the live model")
    stale = CostModel()
    result = load_cost_ledger(
        stale, backend=svc._backend_label, fingerprint="deadbeef0000",
        plane="python")
    check(result == "stale" and stale.snapshot() == CostModel().snapshot(),
          f"fingerprint mismatch discarded as 'stale' (got {result!r})")

    # -- hot-path overhead ---------------------------------------------
    launches = max(1, svc.sched.launches)
    mean_batch_ms = serve_wall_s * 1e3 / launches
    probe = Timeline(rate=1.0)
    stages = {"encode_ms": 0.2, "prefilter_ms": 0.1,
              "device_dispatch_ms": 0.1, "device_compute_ms": 1.0}
    rows = [(f"trace{i}", 1.0, 1.5) for i in range(probe.rows_per_batch)]
    t0 = time.perf_counter()
    for i in range(OVERHEAD_ITERS):
        probe.batch_python(stages_ms=stages, t_launch=2.0, t_resolve=3.0,
                           t_end=3.5, rows=rows)
    record_ms = (time.perf_counter() - t0) * 1e3 / OVERHEAD_ITERS
    off = Timeline(rate=0.0)
    t0 = time.perf_counter()
    for i in range(OVERHEAD_ITERS * 100):
        off.sample()
    off_us = (time.perf_counter() - t0) * 1e6 / (OVERHEAD_ITERS * 100)
    check(record_ms < 0.02 * mean_batch_ms,
          f"sampled record path <2% of mean batch wall "
          f"({record_ms:.4f} ms vs batch {mean_batch_ms:.2f} ms)")
    check(off_us < 5.0,
          f"sampling-off path is one add+compare ({off_us:.3f} us/call)")
    return {"mean_batch_ms": round(mean_batch_ms, 3),
            "record_ms_per_batch": round(record_ms, 4),
            "compiles_total": snap["compiles_total"]}


def _sidecar_plane() -> dict:
    import threading

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression
    from pingoo_tpu.native_ring import Ring, RingSidecar
    from pingoo_tpu.obs.perf import get_compile_ledger
    from pingoo_tpu.obs.timeline import get_timeline
    from pingoo_tpu.sched.scheduler import CostModel, load_cost_ledger

    rules = [RuleConfig(name="blk", actions=(Action.BLOCK,),
                        expression=compile_expression(
                            'http_request.path.starts_with("/evil")'))]
    plan = compile_ruleset(rules, {})

    with tempfile.TemporaryDirectory() as tmp:
        ring = Ring(os.path.join(tmp, "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
        for i in range(N_RING):
            path = (f"/evil/{i}" if i % 3 == 0 else f"/fine/{i}").encode()
            ring.enqueue(method=b"GET", host=b"tl.test", path=path,
                         url=path, user_agent=b"ua",
                         ip=b"\x00" * 15 + bytes([i % 251 + 1]))
        worker = threading.Thread(
            target=sidecar.run, kwargs={"max_requests": N_RING},
            daemon=True)
        worker.start()
        got = 0
        deadline = time.time() + 240
        while time.time() < deadline and got < N_RING:
            if ring.poll_verdict() is None:
                time.sleep(0.001)
                continue
            got += 1
        sidecar.stop()
        worker.join(timeout=30)
        ring.close()
    check(got == N_RING, f"sidecar served all verdicts ({got}/{N_RING})")

    tl = get_timeline()
    with tl._lock:
        spans = list(tl.spans)
    n_b, n_c, orphans = _nesting_holds(spans, "sidecar/batch")
    check(n_b > 0 and n_c > 0 and not orphans,
          f"sidecar batch spans nest ({n_c} children in {n_b} batches, "
          f"orphans={orphans[:3]})")
    joins = [s for s in spans
             if s[0] == "native" and s[2] == "ring_wait"]
    check(bool(joins),
          f"cross-plane ring-wait join rows under pid native "
          f"({len(joins)})")
    check(all(dur >= 0.0 for _, _, _, _, dur, *_ in joins),
          "ring-wait durations non-negative (shared monotonic clock)")

    snap = get_compile_ledger().snapshot()
    check(snap["totals"].get("sidecar/lanes/cold", 0) >= 1,
          f"sidecar lane warm-up compile on the ledger "
          f"(totals={snap['totals']})")

    # Sidecar cost ledger rode the same file under its own plane key.
    fresh = CostModel()
    result = load_cost_ledger(
        fresh, backend=sidecar._backend_label,
        fingerprint=sidecar._plan_fp, plane="sidecar")
    check(result == "ok",
          f"sidecar cost-ledger entry reloads 'ok' (got {result!r})")
    return {"ring_join_spans": len(joins)}


def _surface_checks(summary: dict) -> None:
    """ISSUE 18: every ledger compile event must lie inside the
    statically-proved admissible surface, and an injected out-of-
    surface compile must be detected."""
    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.perf import event_in_surface, \
        get_compile_ledger, load_compile_surface

    ledger = get_compile_ledger()
    surface = load_compile_surface(os.environ["PINGOO_COMPILE_SURFACE"])
    snap = ledger.snapshot()
    check(surface is not None and snap["surface_loaded"],
          "compile surface loaded by the ledger")
    escapes = [(e["plane"], e["fn"], event_in_surface(e, surface))
               for e in snap["events"]
               if event_in_surface(e, surface)]
    check(snap["compiles_total"] > 0 and not escapes
          and snap["unexpected_total"] == 0,
          f"all {snap['compiles_total']} compile events inside "
          f"COMPILE_SURFACE.json (escapes={escapes[:3]})")
    # Inject an out-of-surface compile: the detector must bite.
    ledger.note(plane="python", fn="verdict", kind="cold", wall_ms=0.1,
                shapes=[(65, 128)])  # 65 is on no pow2 rung
    snap2 = ledger.snapshot()
    check(snap2["unexpected_total"] == 1,
          f"injected out-of-surface compile detected "
          f"(unexpected_total={snap2['unexpected_total']})")
    check("pingoo_compile_unexpected_total"
          in REGISTRY.prometheus_text(),
          "scrape exposes pingoo_compile_unexpected_total")
    summary["surface_events_checked"] = snap["compiles_total"]


def child() -> int:
    from pingoo_tpu import native_ring
    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.registry import lint_prometheus_text

    # The admissible compile surface must exist BEFORE the first
    # compile event — the ledger resolves PINGOO_COMPILE_SURFACE once.
    from tools.analyze import surface as surface_mod
    surface_mod.write_surface(surface_mod.build_surface(),
                              os.environ["PINGOO_COMPILE_SURFACE"])

    summary = _python_plane()
    if native_ring.ensure_built():
        summary.update(_sidecar_plane())
    else:
        print("  note sidecar plane skipped: native toolchain "
              "unavailable")
    _surface_checks(summary)

    text = REGISTRY.prometheus_text()
    problems = lint_prometheus_text(text)
    check(not problems, f"prometheus lint clean {problems[:3]}")
    for name in ("pingoo_compile_total", "pingoo_compile_ms",
                 "pingoo_timeline_spans_total",
                 "pingoo_costmodel_reload_total"):
        check(name in text, f"scrape exposes {name}")

    if FAILURES:
        print(f"\ntimeline smoke FAILED ({len(FAILURES)} problems)")
        return 1
    print(json.dumps(summary))
    print("\ntimeline smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else parent())
