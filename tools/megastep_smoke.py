#!/usr/bin/env python
"""Device-resident megastep smoke (make megastep-smoke; ISSUE 12).

Proves, offline and in ~a minute, that the jitted K-batch megastep
(docs/EXECUTOR.md, "Device-resident loop") is a scheduling change and
never a semantic one — on BOTH planes:

  * python plane: VerdictService verdicts under PINGOO_MEGASTEP=force
    are bit-identical to PINGOO_MEGASTEP=off (the per-batch oracle),
    with at least one K>1 window actually dispatched and zero
    ruleset-epoch echo mismatches;
  * sidecar plane: RingSidecar over a real shm ring, the same
    off-vs-force bit-identity with windows > 0 (this half skips with a
    warning when the native toolchain is unavailable);
  * the `pingoo_megastep_k` / `pingoo_megastep_batches_total` series
    export through the shared registry and the exposition passes the
    Prometheus lint.

Offline-safe like mesh-smoke: when jax is unavailable the smoke SKIPS
WITH A WARNING (exit 0) instead of failing the gate. The work happens
in a re-exec'd child under a controlled environment so a parent shell
pinning PINGOO_MEGASTEP cannot skew the A/B.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []

N_PY = 80       # python-plane requests
N_RING = 96     # sidecar-plane requests
MAX_BATCH = 16  # sidecar batch rows -> K=4 windows of 64 tickets


def check(ok, what):
    print(("  ok  " if ok else "  FAIL") + f" {what}")
    if not ok:
        FAILURES.append(what)


def parent() -> int:
    try:
        import jax  # noqa: F401
    except Exception as exc:
        print(f"megastep smoke SKIPPED: jax unavailable ({exc!r})")
        return 0
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for k in ("PINGOO_MEGASTEP", "PINGOO_MEGASTEP_K", "PINGOO_PIPELINE",
              "PINGOO_PIPELINE_DEPTH", "PINGOO_MESH", "PINGOO_DFA",
              "PINGOO_DEADLINE_MS", "PINGOO_SCHED_MODE",
              "PINGOO_SCHED_FAILOPEN", "PINGOO_CHAOS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, cwd=REPO, timeout=900)
    return proc.returncode


def _python_plane() -> dict:
    """VerdictService off-vs-force bit-identity with real K>1 windows."""
    import asyncio
    import random

    import numpy as np

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine.service import VerdictService
    from test_parity import LISTS, RULE_SOURCES, make_rules, \
        random_requests

    reqs = random_requests(random.Random(1207), N_PY)

    def serve(mode):
        os.environ["PINGOO_MEGASTEP"] = mode
        os.environ["PINGOO_MEGASTEP_K"] = "4"
        try:
            plan = compile_ruleset(make_rules(RULE_SOURCES), LISTS)
            svc = VerdictService(plan, LISTS, use_device=True,
                                 max_batch=32)

            async def flow():
                await svc.start()
                try:
                    return await asyncio.gather(
                        *[svc.evaluate(r) for r in reqs])
                finally:
                    await svc.stop()

            return svc, asyncio.run(flow())
        finally:
            del os.environ["PINGOO_MEGASTEP"]
            del os.environ["PINGOO_MEGASTEP_K"]

    _, want = serve("off")
    svc, got = serve("force")
    identical = all(
        w.action == g.action and w.verified_block == g.verified_block
        and np.array_equal(w.matched, g.matched)
        for w, g in zip(want, got))
    check(identical,
          "python-plane verdicts bit-identical (force vs off oracle)")
    mega = svc._pipe.snapshot().get("megastep") or {}
    check(mega.get("windows", 0) >= 1 and mega.get("k", 0) >= 2,
          f"force dispatched K>1 megastep windows ({mega})")
    check(svc.mega_echo_mismatch == 0,
          "zero ruleset-epoch echo mismatches (python plane)")
    return {"python_windows": mega.get("windows"),
            "python_k": mega.get("k")}


def _sidecar_plane() -> dict:
    """RingSidecar off-vs-force bit-identity over a real shm ring."""
    import tempfile
    import threading

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression
    from pingoo_tpu.native_ring import Ring, RingSidecar

    rules = [
        RuleConfig(name="blk", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.path.starts_with("/evil")')),
        RuleConfig(name="ua", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.user_agent.contains("megabot")')),
    ]
    plan = compile_ruleset(rules, {})

    def fields(i):
        path = (f"/evil/{i}" if i % 3 == 0 else f"/fine/{i}").encode()
        return {"method": b"GET", "host": b"mega.test", "path": path,
                "url": path,
                "user_agent": b"megabot" if i % 7 == 0 else b"ua",
                "ip": b"\x00" * 15 + bytes([i % 251 + 1])}

    def drive(tmp, mode):
        os.environ["PINGOO_MEGASTEP"] = mode
        os.environ["PINGOO_MEGASTEP_K"] = "4"
        try:
            ring = Ring(os.path.join(tmp, f"ring_{mode}"),
                        capacity=256, create=True)
            sidecar = RingSidecar(ring, plan, {}, max_batch=MAX_BATCH)
        finally:
            del os.environ["PINGOO_MEGASTEP"]
            del os.environ["PINGOO_MEGASTEP_K"]
        enq = {}
        for i in range(N_RING):
            enq[ring.enqueue(**fields(i))] = i
        worker = threading.Thread(
            target=sidecar.run, kwargs={"max_requests": N_RING},
            daemon=True)
        worker.start()
        got: dict = {}
        deadline = time.time() + 240
        while time.time() < deadline and len(got) < N_RING:
            v = ring.poll_verdict()
            if v is None:
                time.sleep(0.001)
                continue
            got.setdefault(v[0], []).append(v[1])
        sidecar.stop()
        worker.join(timeout=30)
        stats = sidecar.stats()
        ring.close()
        check(len(got) == N_RING
              and all(len(v) == 1 for v in got.values()),
              f"{mode}: all verdicts exactly once ({len(got)}/{N_RING})")
        return {enq[t]: v[0] & 3 for t, v in got.items()}, stats

    with tempfile.TemporaryDirectory() as tmp:
        off, _ = drive(tmp, "off")
        force, st = drive(tmp, "force")
    check(off == force,
          "sidecar-plane verdicts bit-identical (force vs off oracle)")
    mega = st.get("megastep", {})
    check(mega.get("windows", 0) >= 1,
          f"force dispatched megastep windows on the ring ({mega})")
    check(mega.get("echo_mismatch") == 0,
          "zero ruleset-epoch echo mismatches (sidecar plane)")
    return {"sidecar_windows": mega.get("windows")}


def child() -> int:
    from pingoo_tpu import native_ring
    from pingoo_tpu.obs import REGISTRY
    from pingoo_tpu.obs.registry import lint_prometheus_text

    summary = _python_plane()
    if native_ring.ensure_built():
        summary.update(_sidecar_plane())
    else:
        print("  note sidecar plane skipped: native toolchain "
              "unavailable")

    text = REGISTRY.prometheus_text()
    problems = lint_prometheus_text(text)
    check(not problems, f"prometheus lint clean {problems[:3]}")
    for name in ("pingoo_megastep_k", "pingoo_megastep_batches_total"):
        check(name in text, f"scrape exposes {name}")

    if FAILURES:
        print(f"\nmegastep smoke FAILED ({len(FAILURES)} problems)")
        return 1
    print(json.dumps(summary))
    print("\nmegastep smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else parent())
