"""GeoIP mmdb decoder tests (reference pingoo/geoip.rs behaviors)."""

import pytest

from pingoo_tpu.host.geoip import (
    AddressNotFound,
    GeoipDB,
    GeoipRecord,
    MmdbReader,
    build_mmdb,
    parse_asn,
    record_from_raw,
)

ENTRIES = {
    "8.8.8.0/24": {"asn": "AS15169", "country": "US"},
    "203.0.113.0/24": {"asn": 64500, "country": "FR"},
    "10.0.0.0/8": {"asn": "AS0", "country": "XX"},
}


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    raw = build_mmdb(ENTRIES)
    path = tmp_path_factory.mktemp("geoip") / "geoip.mmdb"
    path.write_bytes(raw)
    db = GeoipDB.load(paths=(str(path),))
    assert db is not None
    return db


class TestDecoder:
    def test_lookup_hits(self, db):
        assert db.lookup("8.8.8.8") == GeoipRecord(15169, "US")
        assert db.lookup("8.8.8.255") == GeoipRecord(15169, "US")
        assert db.lookup("203.0.113.77") == GeoipRecord(64500, "FR")
        assert db.lookup("10.200.1.1") == GeoipRecord(0, "XX")

    def test_miss_raises(self, db):
        with pytest.raises(AddressNotFound):
            db.lookup("9.9.9.9")
        with pytest.raises(AddressNotFound):
            db.lookup("2001:db8::1")

    def test_loopback_multicast_short_circuit(self, db):
        # geoip.rs:74-77
        with pytest.raises(AddressNotFound):
            db.lookup("127.0.0.1")
        with pytest.raises(AddressNotFound):
            db.lookup("224.0.0.1")

    def test_cache(self, db):
        r1 = db.lookup("8.8.8.8")
        r2 = db.lookup("8.8.8.8")
        assert r1 == r2

    def test_metadata(self, db):
        assert db.reader.metadata["database_type"] == "pingoo-tpu-test"

    def test_zst_loading(self, tmp_path):
        import zstandard

        raw = build_mmdb(ENTRIES)
        path = tmp_path / "geoip.mmdb.zst"
        path.write_bytes(zstandard.ZstdCompressor().compress(raw))
        db = GeoipDB.load(paths=(str(path),))
        assert db.lookup("8.8.8.8").asn == 15169

    def test_missing_db_disables(self, tmp_path):
        assert GeoipDB.load(paths=(str(tmp_path / "none.mmdb"),)) is None


class TestSchemas:
    def test_parse_asn(self):
        # serde_utils.rs:1-9: "AS123" -> 123
        assert parse_asn("AS15169") == 15169
        assert parse_asn("as15169") == 15169
        assert parse_asn(15169) == 15169
        assert parse_asn("junk") == 0

    def test_geolite2_schema(self):
        rec = record_from_raw(
            {"country": {"iso_code": "de"}, "autonomous_system_number": 3320})
        assert rec == GeoipRecord(3320, "DE")

    def test_flat_schema(self):
        assert record_from_raw({"asn": "AS1", "country": "jp"}) == GeoipRecord(1, "JP")

    def test_bad_country_falls_back(self):
        assert record_from_raw({"country": "LONG"}).country == "XX"
