"""Native data plane end-to-end: C++ epoll listener -> verdict ring ->
TPU sidecar -> 403/proxy, driven over real sockets."""

import http.server
import os
import socket
import subprocess
import threading
import time

import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.native_ring import Ring, RingSidecar

pytestmark = pytest.mark.skipif(
    not native_ring.ensure_built(), reason="native toolchain unavailable")

HTTPD = os.path.join(native_ring.NATIVE_DIR, "httpd")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _raw_get(port, path, ua="Mozilla/5.0", timeout=10, extra=""):
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    ua_line = f"user-agent: {ua}\r\n" if ua is not None else ""
    s.sendall(f"GET {path} HTTP/1.1\r\nhost: n.test\r\n{ua_line}{extra}"
              f"connection: close\r\n\r\n".encode())
    data = b""
    s.settimeout(timeout)
    try:
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    except socket.timeout:
        pass
    s.close()
    return data


@pytest.fixture(scope="module")
def native_stack(tmp_path_factory):
    if not os.path.exists(HTTPD):
        subprocess.run(["make", "-C", native_ring.NATIVE_DIR, "httpd"],
                       check=True, capture_output=True)
    tmp = tmp_path_factory.mktemp("native_httpd")

    class Upstream(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = f"upstream:{self.path}".encode()
            self.send_response(200)
            self.send_header("content-length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    upstream = http.server.HTTPServer(("127.0.0.1", 0), Upstream)
    up_port = upstream.server_address[1]
    threading.Thread(target=upstream.serve_forever, daemon=True).start()

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    rules = [
        RuleConfig(name="waf", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.path.starts_with("/.env")')),
        RuleConfig(name="bot", actions=(Action.CAPTCHA,),
                   expression=compile_expression(
                       'http_request.user_agent.contains("sqlmap")')),
    ]
    plan = compile_ruleset(rules, {})
    ring_path = str(tmp / "ring")
    ring = Ring(ring_path, capacity=1024, create=True)
    sidecar = RingSidecar(ring, plan, {}, max_batch=128)
    worker = threading.Thread(target=sidecar.run, daemon=True)
    worker.start()

    port = _free_port()
    proc = subprocess.Popen([HTTPD, str(port), ring_path, "127.0.0.1",
                             str(up_port)], stdout=subprocess.PIPE)
    line = proc.stdout.readline()
    assert b"listening" in line
    time.sleep(0.2)
    yield port
    proc.terminate()
    sidecar.stop()
    upstream.shutdown()
    ring.close()


class TestNativeHttpd:
    def test_allowed_request_proxied(self, native_stack):
        data = _raw_get(native_stack, "/hello")
        assert b"200" in data.split(b"\r\n", 1)[0]
        assert b"upstream:/hello" in data

    def test_waf_block(self, native_stack):
        data = _raw_get(native_stack, "/.env")
        assert data.startswith(b"HTTP/1.1 403")
        assert b"server: pingoo" in data

    def test_captcha_redirect(self, native_stack):
        data = _raw_get(native_stack, "/", ua="sqlmap/1.8")
        assert data.startswith(b"HTTP/1.1 302")
        assert b"/__pingoo/captcha" in data

    def test_empty_ua_blocked_without_ring(self, native_stack):
        data = _raw_get(native_stack, "/", ua="")
        assert data.startswith(b"HTTP/1.1 403")

    def test_malformed_request(self, native_stack):
        s = socket.create_connection(("127.0.0.1", native_stack), timeout=5)
        s.sendall(b"NONSENSE\r\n\r\n")
        data = s.recv(4096)
        s.close()
        assert data.startswith(b"HTTP/1.1 400")

    def test_metrics_json_complete(self, native_stack):
        """The truncation assertion for the metrics body: the old fixed
        1024-byte snprintf buffer could silently cut the JSON mid-field
        (invalid on the wire); the std::string builder must always emit
        a complete, parseable document with every schema field."""
        import json

        _raw_get(native_stack, "/warm")  # ensure counters are non-zero
        data = _raw_get(native_stack, "/__pingoo/metrics",
                        extra="accept: application/json\r\n")
        head, _, body = data.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.1 200")
        assert b"application/json" in head
        clen = int([line for line in head.split(b"\r\n")
                    if line.lower().startswith(b"content-length")][0]
                   .split(b":")[1])
        assert len(body) == clen  # body not truncated mid-flight
        m = json.loads(body)  # complete + valid (the assertion proper)
        from pingoo_tpu.obs import schema

        for key in schema.NATIVE_JSON_KEYS:
            assert key in m, key
        assert set(m["ring"]) >= {"enqueued", "dequeued", "depth",
                                  "depth_hwm", "enqueue_full",
                                  "verdicts_posted", "verdict_post_full"}
        assert m["ring"]["enqueued"] >= 1

    def test_metrics_prometheus_default(self, native_stack):
        from pingoo_tpu.obs import schema
        from pingoo_tpu.obs.registry import lint_prometheus_text

        data = _raw_get(native_stack, "/__pingoo/metrics")
        head, _, body = data.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.1 200")
        assert b"text/plain" in head
        text = body.decode()
        assert lint_prometheus_text(text) == []
        for name in schema.SHARED_METRICS:
            assert f'{name}{{plane="native"}}' in text, name
        assert 'pingoo_verdict_wait_ms_bucket{plane="native",le="+Inf"}' \
            in text
        assert 'pingoo_ring_depth{plane="native"}' in text

    def test_many_concurrent(self, native_stack):
        results = []

        def one(i):
            path = "/.env" if i % 3 == 0 else f"/ok{i}"
            results.append((i % 3 == 0, _raw_get(native_stack, path)))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(30)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 30
        for blocked, data in results:
            if blocked:
                assert data.startswith(b"HTTP/1.1 403")
            else:
                assert b"upstream:/ok" in data
