"""Config system + lists loader tests (reference behaviors from
pingoo/config/config.rs, config_file.rs, lists.rs)."""

import textwrap

import pytest

from pingoo_tpu.config import (
    Action,
    ConfigError,
    ListenerProtocol,
    ListType,
    load_and_validate,
    parse_config,
    parse_listener_address,
    parse_upstream,
)
from pingoo_tpu.expr import Ip
from pingoo_tpu.lists import load_lists, parse_list

MINIMAL = {
    "listeners": {"http": {"address": "http://0.0.0.0"}},
    "services": {"site": {"static": {"root": "/var/www"}}},
}


def test_reference_default_config(tmp_path):
    # The reference's shipped assets/pingoo.yml shape.
    cfg_file = tmp_path / "pingoo.yml"
    cfg_file.write_text(
        textwrap.dedent(
            """
            listeners:
              http:
                address: http://0.0.0.0
            services:
              static_site:
                static:
                  root: /var/wwww
            rules:
              basic_waf:
                expression: http_request.path.starts_with("/.env") || http_request.path.starts_with("/.git")
                actions:
                  - action: block
            """
        )
    )
    config = load_and_validate(str(cfg_file))
    assert len(config.listeners) == 1
    listener = config.listeners[0]
    assert (listener.host, listener.port) == ("0.0.0.0", 80)
    assert listener.protocol == ListenerProtocol.HTTP
    # listener with no explicit services gets all http services (config.rs:236-253)
    assert listener.services == ("static_site",)
    assert config.rules[0].name == "basic_waf"
    assert config.rules[0].actions == (Action.BLOCK,)
    assert config.rules[0].expression is not None


def test_rules_folder_merge_and_duplicates(tmp_path):
    cfg_file = tmp_path / "pingoo.yml"
    cfg_file.write_text(
        "listeners:\n  l: {address: http://0.0.0.0}\n"
        "services:\n  s: {static: {root: /w}}\n"
    )
    rules_dir = tmp_path / "rules"
    rules_dir.mkdir()
    (rules_dir / "extra.yml").write_text(
        'blocked:\n  expression: http_request.path == "/blocked"\n'
        "  actions: [{action: block}]\n"
    )
    (rules_dir / "ignored.yaml").write_text("nope: {actions: []}\n")
    config = load_and_validate(str(cfg_file))
    assert [r.name for r in config.rules] == ["blocked"]

    # Duplicate between folder files is an error.
    (rules_dir / "extra2.yml").write_text("blocked:\n  actions: []\n")
    with pytest.raises(ConfigError, match="duplicate rule name"):
        load_and_validate(str(cfg_file))


class TestListenerAddress:
    def test_defaults(self):
        assert parse_listener_address("http://0.0.0.0") == (
            "0.0.0.0", 80, ListenerProtocol.HTTP)
        assert parse_listener_address("https://127.0.0.1") == (
            "127.0.0.1", 443, ListenerProtocol.HTTPS)
        assert parse_listener_address("tcp://0.0.0.0:9000") == (
            "0.0.0.0", 9000, ListenerProtocol.TCP)
        assert parse_listener_address("tcp+tls://0.0.0.0:9000")[2] == (
            ListenerProtocol.TCP_AND_TLS)

    def test_scheme_defaults_to_http(self):
        assert parse_listener_address("0.0.0.0:8080") == (
            "0.0.0.0", 8080, ListenerProtocol.HTTP)

    def test_errors(self):
        with pytest.raises(ConfigError, match="port is missing"):
            parse_listener_address("tcp://0.0.0.0")
        with pytest.raises(ConfigError, match="not a valid protocol"):
            parse_listener_address("ftp://0.0.0.0:21")
        with pytest.raises(ConfigError, match="host must be an ip"):
            parse_listener_address("http://example.com")


class TestUpstream:
    def test_parse(self):
        up = parse_upstream("http://127.0.0.1:3000")
        assert (up.ip, up.port, up.tls) == ("127.0.0.1", 3000, False)
        up = parse_upstream("https://backend.internal")
        assert (up.ip, up.hostname, up.port, up.tls) == (
            None, "backend.internal", 443, True)
        up = parse_upstream("http://localhost:8080")
        assert up.ip == "127.0.0.1"
        up = parse_upstream("tcp://10.0.0.1:5432")
        assert (up.ip, up.port) == ("10.0.0.1", 5432)

    def test_errors(self):
        with pytest.raises(ConfigError, match="not a valid protocol"):
            parse_upstream("ftp://x:21")
        with pytest.raises(ConfigError, match="port is missing"):
            parse_upstream("tcp://10.0.0.1")
        with pytest.raises(ConfigError, match="host is missing"):
            parse_upstream("http://")


class TestValidation:
    def test_service_exactly_one_kind(self):
        raw = dict(MINIMAL, services={"bad": {"static": {"root": "/w"},
                                              "http_proxy": ["http://1.2.3.4"]}})
        with pytest.raises(ConfigError, match="exactly 1"):
            parse_config(raw)
        raw = dict(MINIMAL, services={"bad": {"route": "true"}})
        with pytest.raises(ConfigError, match="exactly 1"):
            parse_config(raw)

    def test_tcp_proxy_no_route(self):
        raw = {
            "listeners": {"t": {"address": "tcp://0.0.0.0:9000"}},
            "services": {"db": {"tcp_proxy": ["tcp://10.0.0.1:5432"],
                                 "route": "true"}},
        }
        with pytest.raises(ConfigError, match="TCP proxy can't have a route"):
            parse_config(raw)

    def test_duplicate_ports(self):
        raw = dict(
            MINIMAL,
            listeners={
                "a": {"address": "http://0.0.0.0:8080"},
                "b": {"address": "http://127.0.0.1:8080"},
            },
        )
        with pytest.raises(ConfigError, match="same port"):
            parse_config(raw)

    def test_unknown_service(self):
        raw = dict(
            MINIMAL,
            listeners={"a": {"address": "http://0.0.0.0", "services": ["nope"]}},
        )
        with pytest.raises(ConfigError, match="doesn't exist"):
            parse_config(raw)

    def test_tcp_listener_single_service(self):
        raw = {
            "listeners": {"t": {"address": "tcp://0.0.0.0:9000",
                                 "services": ["a", "b"]}},
            "services": {
                "a": {"tcp_proxy": ["tcp://10.0.0.1:1"]},
                "b": {"tcp_proxy": ["tcp://10.0.0.2:2"]},
            },
        }
        with pytest.raises(ConfigError, match="only have 1"):
            parse_config(raw)

    def test_bad_rule_expression_fails_at_load(self):
        raw = dict(MINIMAL, rules={"r": {"expression": "a ==", "actions": []}})
        with pytest.raises(ConfigError, match="error parsing rules"):
            parse_config(raw)

    def test_route_compiled_at_load(self):
        raw = dict(
            MINIMAL,
            services={
                "site": {
                    "static": {"root": "/w"},
                    "route": 'http_request.host == "example.com"',
                }
            },
        )
        config = parse_config(raw)
        assert config.services[0].route is not None

    def test_acme_validation(self):
        base = dict(MINIMAL)
        base["tls"] = {"acme": {"domains": ["example.com", "example.com"]}}
        with pytest.raises(ConfigError, match="duplicate domain"):
            parse_config(base)
        base["tls"] = {"acme": {"domains": ["*.example.com"]}}
        with pytest.raises(ConfigError, match="wildcard"):
            parse_config(base)
        base["tls"] = {"acme": {"domains": ["EXAMPLE.com"]}}
        with pytest.raises(ConfigError, match="invalid domain"):
            parse_config(base)
        base["tls"] = {"acme": {"domains": ["example.com"],
                                  "directory_url": "https://acme.example/dir/ "}}
        config = parse_config(base)
        assert config.tls.acme.directory_url == "https://acme.example/dir"

    def test_unknown_keys_rejected(self):
        raw = dict(MINIMAL)
        raw["nope"] = {}
        with pytest.raises(ConfigError, match="unknown keys"):
            parse_config(raw)


class TestLists:
    def test_parse_typed_lists(self):
        ips = parse_list('127.0.0.1,"really bad person"\n10.0.0.0/8,"corp"\n',
                         ListType.IP)
        assert ips[0] == Ip("127.0.0.1")
        assert ips[1].is_network
        ints = parse_list("64500\n64501,desc\n", ListType.INT)
        assert ints == [64500, 64501]
        strings = parse_list("/admin\n/.env, secret scan \n", ListType.STRING)
        assert strings == ["/admin", "/.env"]

    def test_values_trimmed(self):
        assert parse_list(" 42 ,x\n", ListType.INT) == [42]

    def test_errors(self):
        with pytest.raises(ConfigError, match="number of columns"):
            parse_list("a,b,c\n", ListType.STRING)
        with pytest.raises(ConfigError, match="parsing int"):
            parse_list("abc\n", ListType.INT)
        with pytest.raises(ConfigError, match="IP network"):
            parse_list("999.1.1.1\n", ListType.IP)

    def test_load_lists_end_to_end(self, tmp_path):
        f = tmp_path / "blocked.csv"
        f.write_text('127.0.0.1,"bad"\n192.0.2.0/24\n')
        from pingoo_tpu.config.schema import ListConfig

        lists = load_lists([ListConfig(name="blocked_ips", type=ListType.IP,
                                        file=str(f))])
        assert "blocked_ips" in lists and len(lists["blocked_ips"]) == 2
