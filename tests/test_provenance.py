"""Verdict provenance layer (ISSUE 5): per-rule attribution lanes, the
shadow-parity auditor, and the cross-plane flight recorder.

Acceptance properties pinned here:
  * per-rule hit counters agree with the host interpreter's per-rule
    trace on a randomized CRS-style ruleset (python fold AND the
    on-device lane-plane fold, batch padding masked);
  * a deliberate interpreter divergence (monkeypatched oracle) is
    reported by the auditor AND flight-recorded with provenance detail;
  * flight-recorder wrap-around keeps exactly the last N records, and
    the SIGTERM drain dump writes/returns the full payload;
  * /__pingoo/explain output matches the interpreter's rule trace;
  * a bare host sync inserted into the attribution fold / parity
    submit path fails the analyze lint (mutation proof);
  * bench trajectory: bench_regress flags a regression between the two
    latest comparable history entries and ignores incomparable ones.
"""

import asyncio
import json
import os
import queue

import numpy as np
import pytest

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine import RequestTuple, encode_requests, evaluate_batch, \
    make_verdict_fn
from pingoo_tpu.engine.batch import (RequestBatch, bucket_arrays, pad_batch,
                                     tuple_to_context)
from pingoo_tpu.engine.service import VerdictService
from pingoo_tpu.engine.verdict import (interpret_rules_row, make_lane_fn,
                                       make_prefilter_fn)
from pingoo_tpu.expr import compile_expression
from pingoo_tpu.obs import schema
from pingoo_tpu.obs.flightrecorder import (FlightRecorder, dump_all,
                                           dump_on_drain,
                                           register_recorder,
                                           tuple_digest,
                                           unregister_recorder)
from pingoo_tpu.obs.provenance import (ParityAuditor, RuleAttribution,
                                       OVERFLOW_LABEL)
from pingoo_tpu.obs.registry import MetricRegistry, lint_prometheus_text
from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic


def _basic_rules():
    return [
        RuleConfig(name="waf", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.path.starts_with("/.env")')),
        RuleConfig(name="sqli", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.url.contains("union select")')),
    ]


@pytest.fixture(scope="module")
def crs_setup():
    rules, lists = generate_ruleset(80, with_lists=True,
                                    list_sizes=(128, 32))
    plan = compile_ruleset(rules, lists)
    reqs = generate_traffic(96, lists=lists, seed=5, attack_fraction=0.4)
    return rules, lists, plan, reqs


# -- schema ------------------------------------------------------------------


class TestSchema:
    def test_provenance_stage_and_metric_inventory(self):
        assert "provenance" in schema.VERDICT_STAGES
        names = schema.all_metric_names()
        for family in (schema.PROVENANCE_METRICS, schema.PARITY_METRICS):
            for name in family:
                assert name in names, name

    def test_server_drain_wires_flight_dump(self):
        # Source-text check (importing host.server needs 'cryptography',
        # absent on this image): the SIGTERM drain path must call the
        # flight-recorder auto-dump.
        path = os.path.join(os.path.dirname(__file__), "..",
                            "pingoo_tpu", "host", "server.py")
        with open(path) as f:
            src = f.read()
        assert "dump_on_drain" in src
        finally_block = src.split("finally:")[-1]
        assert 'dump_on_drain("sigterm")' in finally_block


# -- attribution -------------------------------------------------------------


class TestRuleAttribution:
    def test_topk_bounded_exposition_and_monotone_overflow(self):
        from pingoo_tpu.obs.provenance import RULE_SERIES_CAP

        reg = MetricRegistry()
        names = tuple(f"rule_{i:03d}" for i in range(100))
        attr = RuleAttribution(names, plane="t", registry=reg, top_k=5)
        rng = np.random.default_rng(3)
        # Stable distribution: exactly the top-K + "_overflow" export.
        stable = np.arange(100)[::-1]
        attr.fold_batch(stable)
        text = reg.prometheus_text()
        series = [ln for ln in text.splitlines()
                  if ln.startswith("pingoo_rule_hits_total{")]
        assert len(series) == 5 + 1
        prev: dict = {}
        for _ in range(6):
            # Churny distributions promote new entrants, but the total
            # labelled cardinality stays hard-bounded and every series
            # (overflow included) stays a monotone counter.
            counts = rng.integers(0, 50, size=100)
            attr.fold_batch(counts)
            text = reg.prometheus_text()
            assert lint_prometheus_text(text) == []
            series = [ln for ln in text.splitlines()
                      if ln.startswith("pingoo_rule_hits_total{")]
            assert 1 <= len(series) <= RULE_SERIES_CAP + 1
            vals = {}
            for ln in series:
                label, val = ln.rsplit(" ", 1)
                vals[label] = int(val)
                assert int(val) >= prev.get(label, 0), ln
            # conservation: labelled + overflow == total hits
            assert sum(vals.values()) == attr.total_hits
            prev = vals
        snap = attr.snapshot()
        assert snap["total"] == attr.total_hits
        assert len(snap["top"]) <= 5

    def test_fold_with_device_column_indices(self):
        reg = MetricRegistry()
        attr = RuleAttribution(("a", "b", "c"), plane="t", registry=reg)
        attr.fold_batch(np.array([7, 9]), indices=np.array([2, 0]))
        assert attr._counts.tolist() == [9, 0, 7]


class TestAttributionParityProperty:
    def test_hit_counters_agree_with_interpreter_trace(self, crs_setup):
        """ISSUE 5 acceptance: per-rule hit counters == the host
        interpreter's per-rule trace, randomized CRS ruleset."""
        rules, lists, plan, reqs = crs_setup
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), b2, lists)
        want = np.stack([
            interpret_rules_row(plan, tuple_to_context(r, lists))
            for r in reqs])
        reg = MetricRegistry()
        attr = RuleAttribution(plan.rule_names, plane="t", registry=reg)
        attr.fold_batch(matched.sum(axis=0))
        np.testing.assert_array_equal(attr._counts, want.sum(axis=0))

    def test_on_device_lane_fold_masks_padding(self, crs_setup):
        """The sidecar's aux lane (folded ON DEVICE over a padded
        batch) must agree with the matrix fold over the REAL rows for
        every device-resident column."""
        rules, lists, plan, reqs = crs_setup
        n = len(reqs)
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        padded = pad_batch(b2, 128)
        tables = plan.device_tables()
        lanes, hits = make_lane_fn(plan, with_rule_hits=True)(
            tables, padded.arrays, None, np.int32(n))
        hits = np.asarray(hits)
        matched = evaluate_batch(plan, make_verdict_fn(plan), tables,
                                 b2, lists)
        dev_cols = plan.device_rule_indices
        np.testing.assert_array_equal(
            hits, matched[:, dev_cols].sum(axis=0))

    def test_prefilter_aux_per_bank_lanes(self, crs_setup):
        """Stage-A aux layout: the per-bank lanes sum to the aggregate
        lanes (banks-skipped attribution, obs/provenance)."""
        rules, lists, plan, reqs = crs_setup
        pf = make_prefilter_fn(plan)
        if pf is None:
            pytest.skip("ruleset extracted no factors")
        batch = encode_requests(reqs)
        arrays = bucket_arrays(batch.arrays)
        _, aux = pf.fn(plan.device_tables(), arrays)
        aux = np.asarray(aux)
        m = len(pf.masked)
        assert len(aux) == 2 + 2 * m
        assert int(aux[0]) == int(aux[2:2 + m].sum())
        never_only = len(pf.gated) - m
        assert int(aux[1]) == never_only + int(aux[2 + m:].sum())


# -- flight recorder ---------------------------------------------------------


class TestFlightRecorder:
    def test_wraparound_keeps_last_n(self):
        reg = MetricRegistry()
        rec = FlightRecorder("t", capacity=8, registry=reg)
        for i in range(20):
            rec.record(trace_id=f"t{i}", digest="d", stages={},
                       matched_rules=(), action=0)
        assert len(rec) == 8
        assert rec.recorded_total == 20
        ids = [e["trace_id"] for e in rec.snapshot()]
        assert ids == [f"t{i}" for i in range(12, 20)]  # oldest->newest
        assert reg.counter("pingoo_flightrecorder_records_total",
                           labels={"plane": "t"}).value == 20

    def test_mark_parity_and_rule_names(self):
        rec = FlightRecorder("t", capacity=4, registry=MetricRegistry(),
                             rule_names=("waf", "sqli"))
        rec.record(trace_id="x", digest="d", stages={"wait_ms": 1.0},
                   matched_rules=(1,), action=1)
        assert rec.mark_parity("x", "mismatch", {"rules": ["sqli"]})
        assert not rec.mark_parity("nope", "ok")
        (entry,) = rec.snapshot()
        assert entry["parity"] == "mismatch"
        assert entry["parity_detail"] == {"rules": ["sqli"]}
        assert entry["matched_rule_names"] == ["sqli"]

    def test_digest_stable_and_hex(self):
        a = tuple_digest("GET", "h", "/p", "/p?q", "ua", "1.2.3.4")
        b = tuple_digest("GET", "h", "/p", "/p?q", "ua", "1.2.3.4")
        c = tuple_digest("GET", "h", "/p2", "/p2", "ua", "1.2.3.4")
        assert a == b != c
        int(a, 16)

    def test_drain_dump_writes_file(self, tmp_path, monkeypatch):
        rec = FlightRecorder("t_drain", capacity=4,
                             registry=MetricRegistry())
        register_recorder(rec)
        try:
            rec.record(trace_id="x", digest="d", stages={},
                       matched_rules=(), action=0)
            monkeypatch.setenv("PINGOO_FLIGHT_DUMP_DIR", str(tmp_path))
            path = dump_on_drain("test")
            assert path is not None and os.path.exists(path)
            with open(path) as f:
                payload = json.load(f)
            assert payload["reason"] == "test"
            assert len(payload["planes"]["t_drain"]["entries"]) == 1
            assert "t_drain" in dump_all()["planes"]
        finally:
            unregister_recorder(rec)


# -- parity auditor ----------------------------------------------------------


def _auditor(plan, lists, recorder=None, sample=1.0, **kw):
    return ParityAuditor(plan, lists, plane="t_parity",
                         recorder=recorder, registry=MetricRegistry(),
                         sample=sample, **kw)


class TestParityAuditor:
    def test_clean_traffic_audits_without_mismatch(self):
        rules = _basic_rules()
        plan = compile_ruleset(rules, {})
        reqs = [RequestTuple(path="/.env", url="/.env", user_agent="x"),
                RequestTuple(path="/ok", url="/ok", user_agent="x")]
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), b2, {})
        aud = _auditor(plan, {})
        assert aud.submit_matrix(reqs, matched)
        assert aud.flush(20)
        assert aud.checked_total.value == 2
        assert aud.mismatch_total.value == 0
        aud.stop()

    def test_sampling_fraction_of_batches(self):
        plan = compile_ruleset(_basic_rules(), {})
        aud = _auditor(plan, {}, sample=0.25)
        decisions = [aud._sampled() for _ in range(100)]
        assert sum(decisions) == 25
        aud.stop()

    def test_monkeypatched_interpreter_divergence_reported(
            self, monkeypatch):
        """ISSUE 5 acceptance: a deliberate oracle divergence shows up
        in the mismatch counters, the per-rule breakdown, AND the
        flight record's parity status + detail."""
        import pingoo_tpu.engine.verdict as verdict_mod

        plan = compile_ruleset(_basic_rules(), {})
        reqs = [RequestTuple(path="/ok", url="/ok", user_agent="x",
                             trace_id="trace-mm")]
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), b2, {})
        real = verdict_mod.interpret_rules_row

        def broken(plan_, ctx):
            row = real(plan_, ctx)
            row[0] = not row[0]  # the injected engine bug
            return row

        monkeypatch.setattr(verdict_mod, "interpret_rules_row", broken)
        rec = FlightRecorder("t_parity", capacity=8,
                             registry=MetricRegistry(),
                             rule_names=plan.rule_names)
        rec.record(trace_id="trace-mm", digest="d", stages={},
                   matched_rules=(), action=0)
        aud = _auditor(plan, {}, recorder=rec)
        assert aud.submit_matrix(reqs, matched)
        assert aud.flush(20)
        assert aud.checked_total.value == 1
        assert aud.mismatch_total.value == 1
        assert aud._rule_series.get("waf") is not None
        assert aud._rule_series["waf"].value == 1
        (entry,) = rec.snapshot()
        assert entry["parity"] == "mismatch"
        assert entry["parity_detail"]["rules"] == ["waf"]
        assert entry["parity_detail"]["interpreter"] == [True]
        assert entry["parity_detail"]["device"] == [False]
        aud.stop()

    def test_fault_inject_knob_is_oracle_only(self, monkeypatch):
        monkeypatch.setenv("PINGOO_PARITY_FAULT_INJECT", "/faulty")
        plan = compile_ruleset(_basic_rules(), {})
        reqs = [RequestTuple(path="/faulty", url="/faulty",
                             user_agent="x")]
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), b2, {})
        assert not matched[0, 0]  # the SERVED verdict is untouched
        aud = _auditor(plan, {})
        aud.submit_matrix(reqs, matched)
        assert aud.flush(20)
        assert aud.mismatch_total.value == 1
        aud.stop()

    def test_full_queue_drops_and_counts(self):
        plan = compile_ruleset(_basic_rules(), {})
        aud = _auditor(plan, {}, queue_max=1)
        aud._ensure_worker = lambda: None  # keep the queue full
        assert aud.submit_matrix((), np.zeros((0, 2), dtype=bool))
        assert not aud.submit_matrix((), np.zeros((0, 2), dtype=bool))
        assert aud.dropped_total.value == 1
        aud.stop()

    def test_lane_audit_skips_masked_rows(self):
        plan = compile_ruleset(_basic_rules(), {})
        reqs = [RequestTuple(path="/.env", url="/.env", user_agent="x"),
                RequestTuple(path="/ok", url="/ok", user_agent="x")]

        def builder():
            contexts = [tuple_to_context(r, {}) for r in reqs]
            return contexts, [r.path for r in reqs]

        aud = _auditor(plan, {})
        # Served lanes deliberately WRONG for row 0 — but row 0 is
        # skip-masked (a truncated/spilled slot), so no mismatch.
        aud.submit_lanes(builder, np.array([0, 0]),
                         np.array([False, False]),
                         skip_mask=np.array([True, False]))
        assert aud.flush(20)
        assert aud.checked_total.value == 1
        assert aud.mismatch_total.value == 0
        aud.stop()


# -- service integration (python plane) --------------------------------------


class TestServiceProvenance:
    @pytest.fixture()
    def svc(self, loop_runner, monkeypatch):
        monkeypatch.setenv("PINGOO_PARITY_SAMPLE", "1")
        plan = compile_ruleset(_basic_rules(), {})
        service = VerdictService(plan, {}, use_device=True)
        loop_runner.run(service.start())
        yield service
        loop_runner.run(service.stop())

    def test_live_requests_attributed_and_recorded(self, svc,
                                                   loop_runner):
        before = svc.flight_recorder.recorded_total
        checked0 = svc.parity.checked_total.value
        v = loop_runner.run(svc.evaluate(RequestTuple(
            path="/.env", url="/.env", user_agent="x",
            trace_id="t-live-1")))
        assert v.action == 1
        assert svc.flight_recorder.recorded_total == before + 1
        entry = next(e for e in svc.flight_recorder.snapshot()
                     if e["trace_id"] == "t-live-1")
        assert entry["matched_rule_names"] == ["waf"]
        assert entry["action"] == 1
        assert "wait_ms" in entry["stages_ms"]
        assert svc._attribution._counts[0] >= 1
        assert svc.parity.flush(30)
        assert svc.parity.checked_total.value > checked0

    def test_explain_matches_interpreter_trace(self, svc, loop_runner):
        """ISSUE 5 acceptance: explain output validated against the
        interpreter's rule trace."""
        tup = RequestTuple(path="/.env", url="/.env?union select",
                           user_agent="x", trace_id="t-explain")
        out = loop_runner.run(svc.explain(tup))
        want = interpret_rules_row(svc.plan, tuple_to_context(tup, {}))
        assert out["action"] == 1
        assert out["parity"]["consistent"] is True
        for rule_row in out["rules"]:
            assert rule_row["interpreter"] == bool(
                want[rule_row["index"]])
            assert rule_row["device"] == bool(want[rule_row["index"]])
        assert out["matched_rules"] == ["waf", "sqli"]
        assert out["stages_ms"] is not None
        assert out["digest"] == tuple_digest(
            tup.method, tup.host, tup.path, tup.url, tup.user_agent,
            tup.ip)

    def test_injected_divergence_via_service(self, svc, loop_runner,
                                             monkeypatch):
        import pingoo_tpu.engine.verdict as verdict_mod

        real = verdict_mod.interpret_rules_row

        def broken(plan_, ctx):
            row = real(plan_, ctx)
            row[1] = not row[1]
            return row

        mm0 = svc.parity.mismatch_total.value
        monkeypatch.setattr(verdict_mod, "interpret_rules_row", broken)
        loop_runner.run(svc.evaluate(RequestTuple(
            path="/x", url="/x", user_agent="x", trace_id="t-div")))
        assert svc.parity.flush(30)
        assert svc.parity.mismatch_total.value > mm0
        entry = next(e for e in svc.flight_recorder.snapshot()
                     if e["trace_id"] == "t-div")
        assert entry["parity"] == "mismatch"
        assert "sqli" in entry["parity_detail"]["rules"]

    def test_provenance_stage_observed(self, svc, loop_runner):
        loop_runner.run(svc.evaluate(RequestTuple(
            path="/s", url="/s", user_agent="x")))
        snap = svc.stats.snapshot()
        assert snap["stages"]["provenance"]["count"] >= 1

    def test_provenance_disable_knob(self, loop_runner, monkeypatch):
        monkeypatch.setenv("PINGOO_PROVENANCE", "0")
        plan = compile_ruleset(_basic_rules(), {})
        service = VerdictService(plan, {}, use_device=True)
        assert service.flight_recorder is None
        assert service._attribution is None
        assert service.parity is None
        loop_runner.run(service.start())
        v = loop_runner.run(service.evaluate(RequestTuple(
            path="/.env", url="/.env", user_agent="x")))
        assert v.action == 1  # verdicts unaffected
        loop_runner.run(service.stop())


# -- sidecar integration (native/lane plane) ---------------------------------


class TestSidecarProvenance:
    def test_ring_drain_attributes_records_and_audits(
            self, tmp_path, monkeypatch):
        """The lane plane end to end: shm ring -> sidecar -> on-device
        attribution fold + flight records (ticket trace ids) + parity
        audit of the served lanes."""
        import threading

        from pingoo_tpu import native_ring
        from pingoo_tpu.native_ring import Ring, RingSidecar

        if not native_ring.ensure_built():
            pytest.skip("native toolchain unavailable")
        monkeypatch.setenv("PINGOO_PARITY_SAMPLE", "1")
        plan = compile_ruleset(_basic_rules(), {})
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=32)
        try:
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": 3},
                                 daemon=True)
            t.start()
            for path in (b"/.env", b"/ok", b"/.env/x"):
                assert ring.enqueue(path=path, url=path,
                                    user_agent=b"ua") is not None
            t.join(timeout=120)
            assert sidecar.processed == 3
            # on-device fold: the block rule hit twice
            assert sidecar._attribution._counts[0] == 2
            entries = sidecar.flight_recorder.snapshot()
            assert len(entries) == 3
            by_trace = {e["trace_id"]: e for e in entries}
            assert by_trace["t-0"]["matched_rule_names"] == ["waf"]
            assert by_trace["t-0"]["action"] == 1
            assert by_trace["t-1"]["matched_rules"] == []
            assert "enqueue_to_post_ms" in by_trace["t-0"]["stages_ms"]
            assert sidecar.parity.flush(60)
            assert sidecar.parity.checked_total.value >= 3
            assert sidecar.parity.mismatch_total.value == 0
            assert all(e["parity"] == "ok" for e in
                       sidecar.flight_recorder.snapshot())
        finally:
            sidecar.stop()
            ring.close()


# -- lint mutation proofs ----------------------------------------------------


class TestLintMutations:
    def _source(self):
        path = os.path.join(os.path.dirname(__file__), "..",
                            "pingoo_tpu", "obs", "provenance.py")
        with open(path) as f:
            return f.read()

    def test_bare_sync_in_attribution_fold_fails_lint(self):
        """ISSUE 5 satellite: strip the fold's sanctioned suppression
        and the hot-path lint must fail on the bare host sync."""
        from tools.analyze import lint

        src = self._source()
        marker = ("# pingoo: allow(sync-asarray-hot): aux lane "
                  "resolved with the batch's lane sync\n")
        assert marker.replace("\n", "") in src.replace("\n", "")
        mutated = "\n".join(
            ln for ln in src.splitlines()
            if "allow(sync-asarray-hot)" not in ln)
        findings, _ = lint.lint_source(mutated,
                                       "pingoo_tpu/obs/provenance.py")
        assert any(f.rule == "sync-asarray-hot"
                   and "fold_batch" in f.message for f in findings)

    def test_sync_in_parity_submit_fails_lint(self):
        """The parity sampler's hot side must stay sync-free: inserting
        a materialization into submit_matrix fails the lint."""
        from tools.analyze import lint

        src = self._source()
        marker = "    def submit_matrix(self, reqs, matched, trace_ids=None)"
        assert marker in src
        mutated = src.replace(
            marker,
            "    def submit_matrix(self, reqs, matched, trace_ids=None,"
            " _x=None):\n"
            "        matched = np.asarray(matched)\n"
            "        return self._submit_matrix(reqs, matched, trace_ids)\n"
            "    def _submit_matrix(self, reqs, matched, trace_ids=None)")
        findings, _ = lint.lint_source(mutated,
                                       "pingoo_tpu/obs/provenance.py")
        assert any(f.rule == "sync-asarray-hot"
                   and "submit_matrix" in f.message for f in findings)

    def test_current_tree_clean_including_obs(self):
        from tools.analyze import lint
        from tools.analyze import lint_config as cfg

        assert "pingoo_tpu/obs" in cfg.LINT_DIRS
        assert ("pingoo_tpu/obs/provenance.py::RuleAttribution"
                ".fold_batch") in cfg.HOT_FUNCTIONS
        findings, warnings = lint.lint_paths()
        assert findings == [], "\n".join(str(f) for f in findings)
        assert warnings == [], "\n".join(warnings)


# -- bench trajectory --------------------------------------------------------


class TestBenchRegress:
    def _write_history(self, tmp_path, entries):
        path = tmp_path / "BENCH_history.jsonl"
        path.write_text("\n".join(json.dumps(e) for e in entries) + "\n")
        return str(path)

    def test_regression_detected(self, tmp_path, capsys):
        from tools import bench_regress

        path = self._write_history(tmp_path, [
            {"ts": 1, "backend": "device", "value": 1000.0,
             "p_batch_ms": 1.0},
            {"ts": 2, "backend": "device", "value": 800.0,
             "p_batch_ms": 1.05},
        ])
        assert bench_regress.main(["--file", path]) == 1
        out = capsys.readouterr()
        assert "REGRESSION" in out.out
        assert "value" in out.err

    def test_improvement_and_threshold_pass(self, tmp_path):
        from tools import bench_regress

        path = self._write_history(tmp_path, [
            {"ts": 1, "backend": "device", "value": 1000.0,
             "p_batch_ms": 1.0},
            {"ts": 2, "backend": "device", "value": 950.0,
             "p_batch_ms": 1.02},
        ])
        assert bench_regress.main(["--file", path]) == 0
        # tighter threshold flips the same delta into a failure
        assert bench_regress.main(
            ["--file", path, "--threshold", "0.02"]) == 1

    def test_incomparable_backends_skipped(self, tmp_path):
        from tools import bench_regress

        path = self._write_history(tmp_path, [
            {"ts": 1, "backend": "device", "value": 1000.0},
            {"ts": 2, "backend": "cpu-diagnostic", "value": 5.0},
        ])
        # latest is cpu-diagnostic; only a device prior exists
        assert bench_regress.main(["--file", path]) == 0

    def test_baseline_picks_same_backend(self, tmp_path, capsys):
        from tools import bench_regress

        path = self._write_history(tmp_path, [
            {"ts": 1, "backend": "device", "value": 1000.0},
            {"ts": 2, "backend": "cpu-diagnostic", "value": 5.0},
            {"ts": 3, "backend": "device", "value": 990.0},
        ])
        assert bench_regress.main(["--file", path]) == 0
        assert "ts=1" in capsys.readouterr().out

    def test_missing_or_short_history_is_not_failure(self, tmp_path):
        from tools import bench_regress

        assert bench_regress.main(
            ["--file", str(tmp_path / "nope.jsonl")]) == 0
        path = self._write_history(tmp_path, [
            {"ts": 1, "backend": "device", "value": 1.0}])
        assert bench_regress.main(["--file", path]) == 0

    def test_bench_emit_appends_history(self, tmp_path, monkeypatch):
        import bench

        hist = tmp_path / "h.jsonl"
        monkeypatch.setenv("BENCH_HISTORY", "1")
        monkeypatch.setenv("BENCH_HISTORY_FILE", str(hist))
        monkeypatch.setattr(bench, "_EMITTED", False)
        bench._emit_once(json.dumps({"metric": "m", "value": 1}))
        monkeypatch.setattr(bench, "_EMITTED", False)
        bench._emit_once(json.dumps({"metric": "m", "value": 2}))
        lines = [json.loads(ln) for ln in
                 hist.read_text().strip().splitlines()]
        assert [e["value"] for e in lines] == [1, 2]
        assert all("ts" in e for e in lines)
