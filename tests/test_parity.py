"""FP/FN parity: the TPU verdict engine vs the interpreter oracle.

The BASELINE.md contract: exact verdict parity between the batched device
engine and the CPU rules engine over the encoded (truncated) request
view. Every rule here compiles through the full pipeline
(compile_ruleset -> make_verdict_fn -> evaluate_batch) and every verdict
is cross-checked against `execute_as_bool` on per-request contexts.
"""

import random

import numpy as np
import pytest

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine import (
    RequestTuple,
    batch_to_contexts,
    encode_requests,
    evaluate_batch,
    first_action,
    make_verdict_fn,
)
from pingoo_tpu.expr import Ip, compile_expression, execute_as_bool

ACTIONS = (Action.BLOCK,)


def make_rules(sources):
    return [
        RuleConfig(name=f"r{i}", expression=compile_expression(src),
                   actions=ACTIONS)
        for i, src in enumerate(sources)
    ]


LISTS = {
    "blocked_ips": [Ip("10.0.0.0/8"), Ip("192.0.2.1"), Ip("203.0.113.0/24")],
    "blocked_asns": [64500, 64501, 15169],
    "bad_paths": ["/admin", "/.env", "/wp-login.php"],
}

RULE_SOURCES = [
    # the reference's shipped default rule (assets/pingoo.yml)
    'http_request.path.starts_with("/.env") || http_request.path.starts_with("/.git")',
    'http_request.path == "/blocked"',
    'http_request.path.ends_with(".php")',
    'http_request.path.contains("passwd")',
    'http_request.url.matches("(?i)union\\s+select")',
    'http_request.url.matches("%3[Cc]script")',
    'http_request.user_agent.length() == 0 || http_request.user_agent.contains("curl")',
    'http_request.method == "POST" && http_request.path.starts_with("/api")',
    'lists["blocked_ips"].contains(client.ip)',
    'lists["blocked_asns"].contains(client.asn)',
    'lists["bad_paths"].contains(http_request.path)',
    'client.country == "RU" || client.country == "KP"',
    'client.ip == "198.51.100.7"',
    'client.remote_port > 40000 && client.asn != 0',
    'client.asn * 2 + 1 > 129000',
    'http_request.host.ends_with(".example.com") && !http_request.path.starts_with("/public")',
    'http_request.path.length() > 64',
    '!(http_request.method == "GET" || http_request.method == "HEAD")',
    'lists["missing"].contains(client.ip)',  # runtime error -> never matches
    'http_request.path.matches("^/(admin|wp-admin|phpmyadmin)")',
    'http_request.url.matches("(?i)\\bor\\b 1=1")',
    'http_request.url.matches("\\bselect\\b")',
    'http_request.path.matches("x\\.\\b$")',  # \b$ non-word-last: never matches
    'true',
    'false || http_request.path.contains("..")',
    '1 / 0 == 1 || http_request.path == "/x"',  # left error -> no-match
    'http_request.path == "/x" || 1 / 0 == 1',  # right error absorbed when left true
]

HOST_FALLBACK_SOURCES = [
    # outside the device subset -> host interpretation, still exact
    'http_request.path < http_request.url',
    'http_request.host + ":" == "example.com:"',
    'http_request.path.matches("x(abc)+")',  # repeat with prefix: no truncation
]


def random_requests(rng, n):
    paths = ["/", "/index.html", "/.env", "/.git/config", "/blocked",
             "/admin", "/wp-login.php", "/api/create", "/public/x",
             "/etc/passwd", "/x", "/a" * 80, "/search?q=union select",
             "/login.php", "/..%2f..", "/safe/path"]
    urls = ["/?q=1", "/?q=UNION  SELECT", "/?x=%3Cscript%3E", "/plain",
            "/search?q=union\tselect"]
    uas = ["", "Mozilla/5.0", "curl/8.0", "python-requests", "x" * 300]
    hosts = ["example.com", "api.example.com", "evil.test", "x.example.com"]
    methods = ["GET", "POST", "HEAD", "DELETE"]
    countries = ["US", "FR", "RU", "KP", "XX"]
    ips = ["8.8.8.8", "10.1.2.3", "192.0.2.1", "203.0.113.99",
           "198.51.100.7", "2001:db8::1", "172.16.0.1"]
    out = []
    for _ in range(n):
        out.append(
            RequestTuple(
                host=rng.choice(hosts),
                url=rng.choice(urls),
                path=rng.choice(paths),
                method=rng.choice(methods),
                user_agent=rng.choice(uas),
                ip=rng.choice(ips),
                remote_port=rng.randrange(1024, 65536),
                asn=rng.choice([0, 15169, 64500, 64501, 65000]),
                country=rng.choice(countries),
            )
        )
    return out


def assert_parity(sources, requests, lists=LISTS):
    rules = make_rules(sources)
    plan = compile_ruleset(rules, lists)
    verdict_fn = make_verdict_fn(plan)
    batch = encode_requests(requests)
    matched = evaluate_batch(plan, verdict_fn, plan.device_tables(), batch, lists)

    contexts = batch_to_contexts(batch, lists)
    for r, rule in enumerate(rules):
        for i, ctx in enumerate(contexts):
            want = execute_as_bool(rule.expression, ctx)
            got = bool(matched[i, r])
            assert got == want, (
                f"rule {rule.name} ({sources[r]!r}) on request {i} "
                f"({requests[i]!r}): device={got} interp={want}"
            )
    return plan, matched


class TestDeviceParity:
    def test_main_corpus(self):
        rng = random.Random(42)
        plan, _ = assert_parity(RULE_SOURCES, random_requests(rng, 64))
        # Everything in the main corpus must actually lower to device.
        assert plan.stats["host_rules"] == 0

    def test_host_fallback_rules(self):
        rng = random.Random(43)
        plan, _ = assert_parity(
            RULE_SOURCES[:4] + HOST_FALLBACK_SOURCES, random_requests(rng, 32))
        assert plan.stats["host_rules"] == len(HOST_FALLBACK_SOURCES)

    def test_corpus_fully_device_resident(self):
        """VERDICT r2 item 4: the unfiltered 500-rule CRS-style corpus
        compiles with zero host-fallback rules (device_residency 1.0).
        The three formerly-unsupported classes — wide alternation via
        leading-repeat truncation, \\b-adjacent optionals via case
        splitting, mid-pattern $ via end-anchor lowering — are covered
        pattern-by-pattern in tests/test_nfa.py."""
        from pingoo_tpu.utils.crs import generate_ruleset

        rules, lists = generate_ruleset(500)
        plan = compile_ruleset(rules, lists)
        assert plan.stats["host_rules"] == 0
        assert plan.stats["device_rules"] == 500

    def test_truncation_view_is_consistent(self):
        # Paths longer than the field cap: parity is over the truncated view.
        rng = random.Random(44)
        reqs = [RequestTuple(path="/long" + "a" * 500, url="/u"),
                RequestTuple(path="/short")]
        assert_parity(['http_request.path.length() > 256',
                       'http_request.path.ends_with("a")'], reqs)

    def test_always_match_rule_without_expression(self):
        rules = [RuleConfig(name="all", expression=None, actions=ACTIONS)]
        plan = compile_ruleset(rules, {})
        verdict_fn = make_verdict_fn(plan)
        batch = encode_requests([RequestTuple(), RequestTuple(path="/x")])
        matched = evaluate_batch(plan, verdict_fn, plan.device_tables(), batch, {})
        assert matched.all()

    def test_first_action_semantics(self):
        sources = ['http_request.path == "/a"', 'http_request.path.starts_with("/")']
        rules = [
            RuleConfig(name="r0", expression=compile_expression(sources[0]),
                       actions=(Action.CAPTCHA,)),
            RuleConfig(name="r1", expression=compile_expression(sources[1]),
                       actions=(Action.BLOCK,)),
        ]
        plan = compile_ruleset(rules, {})
        verdict_fn = make_verdict_fn(plan)
        batch = encode_requests([RequestTuple(path="/a"), RequestTuple(path="/b")])
        matched = evaluate_batch(plan, verdict_fn, plan.device_tables(), batch, {})
        acts = first_action(plan, matched)
        assert acts.tolist() == [2, 1]  # captcha first for /a, block for /b

    def test_action_lanes_verified_fallthrough(self):
        """Reference action loop (http_listener.rs:251-264): a verified
        client skips Captcha actions but must still hit Block actions —
        in the SAME rule ([Captcha, Block]) or in a LATER matched rule."""
        from pingoo_tpu.engine.verdict import action_lanes

        rules = [
            # /a: captcha-then-block rule — unverified gets captcha,
            # verified must be BLOCKED by the second action.
            RuleConfig(name="cb",
                       expression=compile_expression('http_request.path == "/a"'),
                       actions=(Action.CAPTCHA, Action.BLOCK)),
            # /b: captcha-only rule followed by a block rule — verified
            # clients fall through the first and hit the second.
            RuleConfig(name="c",
                       expression=compile_expression('http_request.path == "/b"'),
                       actions=(Action.CAPTCHA,)),
            RuleConfig(name="b",
                       expression=compile_expression('http_request.path == "/b"'),
                       actions=(Action.BLOCK,)),
            # /c: captcha-only — verified clients pass entirely.
            RuleConfig(name="conly",
                       expression=compile_expression('http_request.path == "/c"'),
                       actions=(Action.CAPTCHA,)),
        ]
        plan = compile_ruleset(rules, {})
        verdict_fn = make_verdict_fn(plan)
        batch = encode_requests([RequestTuple(path=p)
                                 for p in ("/a", "/b", "/c", "/d")])
        matched = evaluate_batch(plan, verdict_fn, plan.device_tables(),
                                 batch, {})
        unverified, verified_block = action_lanes(plan, matched)
        assert unverified.tolist() == [2, 2, 2, 0]
        assert verified_block.tolist() == [True, True, False, False]

    def test_fuzzed_numeric_rules(self):
        rng = random.Random(45)
        sources = []
        cols = ["client.asn", "client.remote_port",
                "http_request.path.length()"]
        ops = ["+", "-", "*", "/", "%"]
        cmps = ["==", "!=", "<", "<=", ">", ">="]
        for _ in range(25):
            lhs = rng.choice(cols)
            if rng.random() < 0.7:
                lhs = f"({lhs} {rng.choice(ops)} {rng.randint(-3, 3)})"
            src = f"{lhs} {rng.choice(cmps)} {rng.randint(-100, 70000)}"
            sources.append(src)
        # overflow / div-zero edges
        sources += [
            "client.asn * 9223372036854775807 > 0",
            "client.asn / 0 == 1",
            "client.asn % 0 == 0",
            "-9223372036854775808 - client.asn < 0",
            "client.remote_port - 9223372036854775807 - 9 < 0",
        ]
        plan, _ = assert_parity(sources, random_requests(rng, 48))
        assert plan.stats["host_rules"] == 0

    def test_fuzzed_boolean_compositions(self):
        rng = random.Random(46)
        atoms = [
            'http_request.path.starts_with("/a")',
            'http_request.path.contains("min")',
            'client.asn == 64500',
            'client.country == "RU"',
            'lists["blocked_asns"].contains(client.asn)',
            'lists["missing"].contains(client.asn)',  # error lane
            'http_request.method == "POST"',
            "true",
            "false",
            "1 / 0 == 1",  # error lane
        ]

        def gen(depth):
            if depth == 0 or rng.random() < 0.35:
                return rng.choice(atoms)
            a, b = gen(depth - 1), gen(depth - 1)
            op = rng.choice(["&&", "||"])
            node = f"({a} {op} {b})"
            if rng.random() < 0.25:
                node = "!" + node
            if rng.random() < 0.12:
                node = f"({node} == {gen(depth - 1)})"
            return node

        sources = [gen(3) for _ in range(40)]
        assert_parity(sources, random_requests(rng, 32))

    def test_review_regressions(self):
        """End-to-end parity on the exact divergences found in review:
        (?i) negated classes, unknown escapes, ip == CIDR, lazy bad list
        entries, empty lists, I64_MIN % -1, literal length."""
        rng = random.Random(48)
        lists = {
            "mixed_bad": ["10.0.0.0/8", "garbage", "192.0.2.1"],
            "all_bad": ["garbage"],
            "empty": [],
        }
        sources = [
            'http_request.path.matches("(?i)[^a]")',
            'http_request.path.matches("(?i)x[^qz]y")',
            'client.ip == "10.0.0.0/8"',
            'client.ip != "10.0.0.0/8"',
            'lists["mixed_bad"].contains(client.ip)',
            'lists["all_bad"].contains(client.ip)',
            'lists["empty"].contains(client.ip)',
            "client.asn % -1 == 0",
            "client.asn / -1 < 1",
        ]
        reqs = random_requests(rng, 24)
        reqs[0].path = "a"
        reqs[1].path = "A"
        reqs[2].path = "xby"
        reqs[3].ip = "10.1.2.3"
        reqs[4].ip = "255.255.255.255"
        reqs[5].ip = "ffff:ffff:ffff:ffff:ffff:ffff:ffff:ffff"
        reqs[6].asn = -(2**63)
        assert_parity(sources, reqs, lists=lists)
        # \q is a bad escape in the oracle -> must NOT lower as literal q.
        plan, _ = assert_parity(['http_request.path.matches("\\\\q")'],
                                [RequestTuple(path="/quote")], lists=lists)
        assert plan.stats["host_rules"] == 1

    def test_utf8_literal_canonicalization(self):
        """Non-ASCII rule literals compare against UTF-8 wire bytes like
        the Rust reference: "café" in a rule equals a path whose bytes are
        the UTF-8 encoding of café."""
        wire_path = "/café".encode("utf-8").decode("latin-1")
        reqs = [RequestTuple(path=wire_path), RequestTuple(path="/cafe")]
        plan, matched = assert_parity(
            ['http_request.path == "/café"',
             'http_request.path.contains("é")',
             '"é".length() == 2'],  # Rust str::len semantics
            reqs)
        assert matched[0, 0] and matched[0, 1] and matched[0, 2]
        assert not matched[1, 0]

    def test_bad_hex_escape_rejected(self):
        from pingoo_tpu.compiler.repat import Unsupported, compile_regex
        from pingoo_tpu.expr import CompileError

        for pat in (r"a\x-1", r"a\x+2", r"a\x 3"):
            with pytest.raises(Unsupported):
                compile_regex(pat)
        with pytest.raises(CompileError):
            compile_expression('http_request.path == "\\x-1"')

    def test_failed_rule_leaves_rolled_back(self):
        """A rule that half-lowers then falls back to host must not leave
        its partial leaves in the device tables."""
        ok = 'http_request.path.contains("safe")'
        bad = 'http_request.url.contains("attack") && http_request.url + "x" == "y"'
        plan_ok = compile_ruleset(make_rules([ok]), {})
        plan_both = compile_ruleset(make_rules([ok, bad]), {})
        assert plan_both.stats["host_rules"] == 1
        assert plan_both.stats["leaves"] == plan_ok.stats["leaves"]

    def test_first_action_vectorized_matches_reference_semantics(self):
        rules = [
            RuleConfig(name="no_action", expression=compile_expression("true"),
                       actions=()),
            RuleConfig(name="cap", expression=compile_expression(
                'http_request.path == "/a"'), actions=(Action.CAPTCHA,)),
            RuleConfig(name="blk", expression=compile_expression(
                'http_request.path.starts_with("/")'), actions=(Action.BLOCK,)),
        ]
        plan = compile_ruleset(rules, {})
        verdict_fn = make_verdict_fn(plan)
        batch = encode_requests(
            [RequestTuple(path="/a"), RequestTuple(path="/b"), RequestTuple(path="")])
        matched = evaluate_batch(plan, verdict_fn, plan.device_tables(), batch, {})
        acts = first_action(plan, matched)
        # Action-less matching rule is skipped; first *acting* rule wins.
        assert acts.tolist() == [2, 1, 0]

    def test_large_ip_list_buckets(self):
        rng = random.Random(47)
        entries = [Ip(f"{rng.randrange(1, 255)}.{rng.randrange(256)}."
                      f"{rng.randrange(256)}.{rng.randrange(256)}")
                   for _ in range(3000)]
        entries += [Ip("10.0.0.0/8"), Ip("203.0.113.0/24")]
        lists = {"big": entries}
        reqs = random_requests(rng, 40)
        # Make sure some probes hit exact entries.
        reqs[0].ip = str(entries[0])
        reqs[1].ip = str(entries[100])
        plan, _ = assert_parity(['lists["big"].contains(client.ip)'], reqs,
                                lists=lists)
        binding = plan.bindings[0]
        assert binding.kind == "ip_list_large"


class TestLaneReductionParity:
    def test_device_lane_fn_matches_full_matrix_oracle(self):
        """The transfer-thin on-device lane reduction (make_lane_fn +
        host_rule_lanes + merge_lanes — the ring sidecar's path) must
        produce exactly the lanes derived from the full match matrix."""
        import numpy as np

        from pingoo_tpu.engine.verdict import (
            action_lanes,
            evaluate_batch,
            host_rule_lanes,
            make_lane_fn,
            make_verdict_fn,
            merge_lanes,
        )
        from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

        rules, lists = generate_ruleset(200, with_lists=True,
                                        list_sizes=(256, 64))
        # The corpus is fully device-resident since round 3; append
        # explicit host-fallback rules so the merge path stays exercised.
        rules = list(rules) + [
            RuleConfig(name=f"hostfb_{i}", expression=compile_expression(src),
                       actions=(Action.BLOCK,))
            for i, src in enumerate(HOST_FALLBACK_SOURCES)
        ]
        plan = compile_ruleset(rules, lists)
        assert plan.host_rules, "ruleset must include host-fallback rules"
        tables = plan.device_tables()
        reqs = generate_traffic(512, lists=lists, seed=11,
                                attack_fraction=0.3)
        batch = encode_requests(reqs)

        matched = evaluate_batch(plan, make_verdict_fn(plan), tables,
                                 batch, lists)
        want_unv, want_vblk = action_lanes(plan, matched)
        dev = make_lane_fn(plan)(tables, batch.arrays)
        host = host_rule_lanes(plan, batch, lists)
        got_unv, got_vblk = merge_lanes(np.asarray(dev), host)
        np.testing.assert_array_equal(want_unv, got_unv)
        np.testing.assert_array_equal(want_vblk, got_vblk)
        assert (got_unv == 1).any()  # corpus actually blocks something


class TestRoutePseudoRules:
    def test_route_columns_match_interpreter(self):
        """Service route predicates compiled as verdict pseudo-columns
        must agree with per-request match_route interpretation —
        including a host-fallback route and a route-less service."""
        from pingoo_tpu.host.services import match_route

        sources = RULE_SOURCES[:6]
        rules = make_rules(sources)
        routes = [
            ("api", compile_expression(
                'http_request.path.starts_with("/api")')),
            ("geo", compile_expression(
                'client.country == "RU" && http_request.method == "GET"')),
            ("hostfb", compile_expression(
                'http_request.host + "" == "example.com"')),  # host-eval
            ("errroute", compile_expression(
                'lists["missing"].contains(client.ip)')),  # error -> false
            ("all", None),  # no route -> match everything
        ]
        plan = compile_ruleset(rules, LISTS, routes=routes)
        assert set(plan.route_index) == {"api", "geo", "hostfb", "errroute",
                                         "all"}
        rng = random.Random(77)
        reqs = random_requests(rng, 48)
        batch = encode_requests(reqs)
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), batch, LISTS)
        contexts = batch_to_contexts(batch, LISTS)
        for name, program in routes:
            col = plan.route_index[name]
            for i, ctx in enumerate(contexts):
                want = match_route(program, ctx)
                assert bool(matched[i, col]) == want, (name, i, reqs[i])

    def test_route_pseudo_rules_never_act(self):
        """Actionless route columns must not leak into action lanes."""
        from pingoo_tpu.engine.verdict import action_lanes

        rules = make_rules(['http_request.path == "/blocked"'])
        routes = [("all", None)]  # matches EVERY request
        plan = compile_ruleset(rules, LISTS, routes=routes)
        batch = encode_requests([RequestTuple(path="/blocked"),
                                 RequestTuple(path="/ok")])
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), batch, LISTS)
        unverified, verified_block = action_lanes(plan, matched)
        assert unverified.tolist() == [1, 0]
        assert verified_block.tolist() == [True, False]
        assert matched[:, plan.route_index["all"]].all()


class TestMultiSeedDifferential:
    """Randomized CRS-scale rulesets across several seeds: compiler
    bugs that depend on rule COMPOSITION (bank packing, span layout,
    class compression interactions) only surface when the generated
    set changes — the fixed corpus above cannot move those seams."""

    def test_generated_rulesets_exact_across_seeds(self):
        import numpy as np

        from pingoo_tpu.engine.batch import bucket_arrays
        from pingoo_tpu.engine.verdict import interpret_rules_row
        from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

        for seed in (7, 1234, 999983, 31337, 2026):
            rules, lists = generate_ruleset(
                80, with_lists=True, list_sizes=(512, 64), seed=seed)
            plan = compile_ruleset(rules, lists)
            verdict_fn = make_verdict_fn(plan)
            reqs = generate_traffic(192, lists=lists, seed=seed + 1,
                                    attack_fraction=0.3)
            from pingoo_tpu.engine.batch import RequestBatch

            batch = encode_requests(reqs)
            b2 = RequestBatch(size=batch.size,
                              arrays=bucket_arrays(batch.arrays))
            matched = evaluate_batch(plan, verdict_fn,
                                     plan.device_tables(), b2, lists)
            contexts = batch_to_contexts(batch, lists)
            for i, ctx in enumerate(contexts):
                want = interpret_rules_row(plan, ctx)
                assert np.array_equal(matched[i], want), (
                    f"seed {seed}: request {i} diverged: "
                    f"{np.nonzero(matched[i] != want)[0]}")
