"""Sharding tests on the 8-device virtual CPU mesh (SURVEY.md §4 item 4:
multi-node behavior without a cluster)."""

import random

import jax
import numpy as np
import pytest

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.compiler.nfa import build_bank
from pingoo_tpu.compiler.repat import compile_regex
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine import encode_requests, evaluate_batch, make_verdict_fn
from pingoo_tpu.expr import compile_expression
from pingoo_tpu.ops.nfa_scan import bank_to_tables, nfa_scan
from pingoo_tpu.parallel import (
    batch_shardings,
    make_mesh,
    pad_tables_for_tp,
    ring_nfa_scan,
    shard_batch_for_ring,
    table_shardings,
)

from test_parity import LISTS, RULE_SOURCES, make_rules, random_requests


@pytest.fixture(scope="module")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "tests need 8 virtual CPU devices (conftest)"
    return devs


class TestDpTpSharding:
    def test_sharded_verdict_matches_unsharded(self, devices):
        """GSPMD-sharded verdict (dp=2, tp=2): identical match matrix."""
        rng = random.Random(7)
        rules = make_rules(RULE_SOURCES)
        mesh = make_mesh(dp=2, tp=2, sp=1)
        plan = compile_ruleset(rules, LISTS)
        plan.np_tables = pad_tables_for_tp(plan.np_tables, tp=2)
        verdict_fn = make_verdict_fn(plan)
        batch = encode_requests(random_requests(rng, 32))
        tables = plan.device_tables()

        want = evaluate_batch(plan, verdict_fn, tables, batch, LISTS)

        # Shard tables + batch and re-evaluate.
        t_shard = table_shardings(mesh, tables)
        b_shard = batch_shardings(mesh, batch.arrays)
        tables_s = {
            k: jax.device_put(v, t_shard[k]) if not isinstance(t_shard[k], dict)
            else {kk: jax.device_put(vv, t_shard[k][kk]) for kk, vv in v.items()}
            for k, v in tables.items()
        }
        arrays_s = {k: jax.device_put(np.asarray(v), b_shard[k])
                    for k, v in batch.arrays.items()}

        class _B:
            size = batch.size
            arrays = arrays_s

        got = evaluate_batch(plan, verdict_fn, tables_s, _B(), LISTS)
        np.testing.assert_array_equal(got, want)

    def test_tp_actually_shards_pattern_tables(self, devices):
        mesh = make_mesh(dp=1, tp=4, sp=1)
        rules = make_rules(RULE_SOURCES)
        plan = compile_ruleset(rules, LISTS)
        plan.np_tables = pad_tables_for_tp(plan.np_tables, tp=4)
        tables = plan.device_tables()
        specs = table_shardings(mesh, tables)
        from pingoo_tpu.ops.match_ops import PatternTable

        sharded_any = False
        for key, val in tables.items():
            if isinstance(val, PatternTable) and val.bytes.shape[0] % 4 == 0:
                spec = specs[key]
                arr = jax.device_put(val.bytes, spec.bytes)
                if len(arr.sharding.device_set) == 4:
                    sharded_any = True
        assert sharded_any


class TestRingScan:
    @pytest.mark.parametrize("sp", [2, 4])
    def test_ring_matches_plain_scan(self, devices, sp):
        rng = random.Random(11)
        sources = [r"abc", r"^/api", r"\.php$", r"(?i)select", r"a.c$",
                   r"x{2,3}y", r"^GET /[a-z]+$", r"qq"]
        patterns = []
        for src in sources:
            patterns.extend(compile_regex(src))
        tables = bank_to_tables(build_bank(patterns))

        L = 64
        inputs = [b"/api/x.php", b"GET /abc", b"SELECT 1 union", b"xxy",
                  b"abcabc\n", b"", b"a" * 63, b"axc"]
        alphabet = b"abcqxy/GETselct."
        for _ in range(24):
            k = rng.randint(0, L)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        B = len(inputs)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, d in enumerate(inputs):
            data[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
            lens[i] = min(len(d), L)

        want = np.asarray(nfa_scan(tables, data, lens))

        mesh = make_mesh(dp=2, tp=1, sp=sp)
        data_s, lens_s = shard_batch_for_ring(mesh, data, lens)
        got = np.asarray(ring_nfa_scan(mesh, tables, data_s, lens_s))
        np.testing.assert_array_equal(got, want)

    def test_ring_handles_cross_chunk_matches(self, devices):
        """A pattern spanning a chunk boundary must still match."""
        patterns = compile_regex("abcdefgh")
        tables = bank_to_tables(build_bank(patterns))
        L = 16  # sp=4 -> chunks of 4; "abcdefgh" spans two boundaries
        data = np.zeros((4, L), dtype=np.uint8)
        payload = b"xxabcdefghxx"
        data[0, : len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        data[1, :8] = np.frombuffer(b"abcdefgh", dtype=np.uint8)
        lens = np.array([len(payload), 8, 0, 5], dtype=np.int32)
        mesh = make_mesh(dp=2, tp=1, sp=4)
        data_s, lens_s = shard_batch_for_ring(mesh, data, lens)
        got = np.asarray(ring_nfa_scan(mesh, tables, data_s, lens_s))
        assert got[0, 0] and got[1, 0]
        assert not got[2, 0] and not got[3, 0]


class TestRingScanMultiWord:
    def test_ring_matches_plain_scan_multiword(self, devices):
        """Multi-word banks (cross-word carry) compose across sp chunk
        boundaries exactly like single-word banks."""
        rng = random.Random(31)
        sources = ["x" * 40, r"<svg[^>]{0,40}onload", r"abc",
                   "b" * 45 + "$", "e{0,60}f"]
        patterns = []
        for src in sources:
            patterns.extend(compile_regex(src))
        bank = build_bank(patterns)
        assert bank.has_carry
        tables = bank_to_tables(bank)

        L = 128  # sp=4 -> 32-byte chunks; spans cross several boundaries
        inputs = [b"x" * 40, b"p" * 20 + b"x" * 40 + b"q" * 20,
                  b"<svg " + b"a" * 40 + b"onload", b"b" * 45,
                  b"z" * 80 + b"b" * 45, b"e" * 59 + b"f", b"", b"x" * 39]
        alphabet = b"xab<svg>onload ef"
        for _ in range(20):
            k = rng.randint(0, L)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        B = len(inputs)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, d in enumerate(inputs):
            data[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
            lens[i] = min(len(d), L)

        want = np.asarray(nfa_scan(tables, data, lens))
        mesh = make_mesh(dp=2, tp=1, sp=4)
        data_s, lens_s = shard_batch_for_ring(mesh, data, lens)
        got = np.asarray(ring_nfa_scan(mesh, tables, data_s, lens_s))
        np.testing.assert_array_equal(got, want)


class TestTpMultiWordHalo:
    @pytest.mark.parametrize("tp", [2, 4])
    def test_sharded_multiword_scan_matches_unsharded(self, devices, tp):
        """A multi-word span straddling a tp shard boundary must keep its
        cross-word carry (GSPMD halo) — verdicts identical to tp=1."""
        import re as _re

        from jax.sharding import NamedSharding, PartitionSpec as P

        # Three long literals: 4-word + 3-word + 2-word spans (W=9), so
        # after padding, a carry-enabled word lands exactly on a shard
        # cut for both tp=2 (cut at 5) and tp=4 (cut at 3) — the halo
        # case. Asserted from the carry mask itself below.
        sources = ["z" * 124, "y" * 88, "x" * 60]
        patterns = []
        for src in sources:
            patterns.extend(compile_regex(src))
        bank = build_bank(patterns)
        assert bank.has_carry
        tables_np = {"nfa": bank_to_tables(bank)}
        tables_np = pad_tables_for_tp(tables_np, tp=tp)
        tables = tables_np["nfa"]
        W = tables.opt.shape[0]
        assert W % tp == 0
        carry = np.asarray(tables.carry_mask)
        shard = W // tp
        assert any(w % shard == 0 and carry[w] for w in range(W)), (
            f"W={W}, tp={tp}: no span straddles a shard cut")

        rng = random.Random(77)
        inputs = [b"x" * 60, b"pad " + b"x" * 60, b"x" * 59,
                  b"y" * 88, b"z" * 124, b"q" + b"z" * 124,
                  b"z" * 123, b"y" * 87 + b"Y"]
        alphabet = b"xyzq "
        for _ in range(16):
            k = rng.randint(0, 80)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        B = len(inputs)
        L = 160
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, d in enumerate(inputs):
            data[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
            lens[i] = min(len(d), L)

        want = np.asarray(nfa_scan(tables, data, lens))

        mesh = make_mesh(dp=2, tp=tp, sp=1)
        specs = table_shardings(mesh, {"nfa": tables})["nfa"]
        tables_s = jax.tree_util.tree_map(
            lambda arr, s: jax.device_put(arr, s), tables, specs)
        data_s = jax.device_put(data, NamedSharding(mesh, P("dp", None)))
        lens_s = jax.device_put(lens, NamedSharding(mesh, P("dp")))
        got = np.asarray(jax.jit(nfa_scan)(tables_s, data_s, lens_s))
        np.testing.assert_array_equal(got, want)
        # Sanity vs re for each straddling literal.
        for col, src in [(0, b"z" * 124), (1, b"y" * 88), (2, b"x" * 60)]:
            gold = _re.compile(src)
            for i, d in enumerate(inputs):
                assert got[i, col] == (gold.search(d) is not None), (col, d)


class TestHaloScan:
    """halo_nfa_scan: TRUE concurrent sequence parallelism (one halo
    exchange, then every sp stage scans its own chunk at once)."""

    SOURCES = [r"abc", "x" * 40, r"<svg[^>]{0,40}onload", r"\.php$",
               "b" * 45 + "$", r"\babc\b", "e{0,60}f", r"^GET /[a-z]{1,8}$",
               r"qq", r"a{2,4}b"]

    def _bank(self):
        patterns = []
        for src in self.SOURCES:
            patterns.extend(compile_regex(src))
        bank = build_bank(patterns)
        tables = bank_to_tables(bank)
        assert tables.halo_ok, "corpus must be halo-eligible (no x*/x+)"
        assert bank.has_carry  # multi-word spans present
        return tables

    def _inputs(self, rng, L):
        inputs = [b"x" * 40, b"p" * 50 + b"x" * 40 + b"q" * 20,
                  b"<svg " + b"a" * 40 + b"onload", b"b" * 45,
                  b"z" * 70 + b"b" * 45, b"index.php", b"x/y.php",
                  b"GET /abc", b" abc ", b"xabc", b"e" * 59 + b"f",
                  b"aaab", b"", b"q" * L]
        alphabet = b"xab<svg>onload .phpGET/eqcf"
        for _ in range(18):
            k = rng.randint(0, L)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        return inputs

    @pytest.mark.parametrize("sp", [2, 4])
    def test_halo_matches_plain_scan(self, devices, sp):
        rng = random.Random(99)
        tables = self._bank()
        L = 256  # chunks >= the 64-bit max footprint at sp=4
        inputs = self._inputs(rng, L)
        B = len(inputs)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, d in enumerate(inputs):
            data[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
            lens[i] = min(len(d), L)

        want = np.asarray(nfa_scan(tables, data, lens))
        mesh = make_mesh(dp=2, tp=1, sp=sp)
        from pingoo_tpu.parallel import halo_nfa_scan

        data_s, lens_s = shard_batch_for_ring(mesh, data, lens)
        got = np.asarray(halo_nfa_scan(mesh, tables, data_s, lens_s))
        np.testing.assert_array_equal(got, want)

    def test_matches_straddling_chunk_boundaries(self, devices):
        """Matches whose span crosses chunk cuts must be caught by the
        halo warm-up; $-accepts must come from the chunk owner."""
        tables = self._bank()
        L = 256  # sp=4 -> 64-byte chunks (= the bank's max footprint)
        cases = [
            b"p" * 40 + b"x" * 40,            # literal across cut at 64
            b"p" * 100 + b"x" * 40,           # across cut at 128
            b"z" * 40 + b"<svg " + b"a" * 30 + b"onload",  # opt run across
            b"w" * 100 + b"b" * 45,           # $-accept at len 145 (chunk 2)
            b"w" * 211 + b"b" * 45,           # $-accept at exactly L
            b"n" * 90 + b"x" * 39,            # near-miss (39 < 40)
            b"p" * 63 + b"x" * 40,            # match starts 1 byte pre-cut
            b"x" * 40,                        # entirely in chunk 0
        ]
        B = len(cases)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, d in enumerate(cases):
            data[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
            lens[i] = min(len(d), L)
        want = np.asarray(nfa_scan(tables, data, lens))
        mesh = make_mesh(dp=2, tp=1, sp=4)
        from pingoo_tpu.parallel import halo_nfa_scan

        data_s, lens_s = shard_batch_for_ring(mesh, data, lens)
        got = np.asarray(halo_nfa_scan(mesh, tables, data_s, lens_s))
        np.testing.assert_array_equal(got, want)

    def test_sp_dispatch_falls_back_for_unbounded_loops(self, devices):
        """x+ / x* banks have unbounded state memory: sp_nfa_scan must
        use the sequential ring and still agree with the plain scan."""
        patterns = []
        for src in [r"ab+c", r"x[0-9]*y", r"abc"]:
            patterns.extend(compile_regex(src))
        tables = bank_to_tables(build_bank(patterns))
        assert not tables.halo_ok

        rng = random.Random(3)
        L = 64
        inputs = [b"abc", b"ab" + b"b" * 40 + b"c", b"x" + b"7" * 50 + b"y",
                  b"xy", b"abbbc", b""]
        alphabet = b"abcxy0123456789"
        for _ in range(10):
            k = rng.randint(0, L)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        B = len(inputs)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, d in enumerate(inputs):
            data[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
            lens[i] = min(len(d), L)
        want = np.asarray(nfa_scan(tables, data, lens))
        from pingoo_tpu.parallel import sp_nfa_scan

        mesh = make_mesh(dp=2, tp=1, sp=4)
        data_s, lens_s = shard_batch_for_ring(mesh, data, lens)
        got = np.asarray(sp_nfa_scan(mesh, tables, data_s, lens_s))
        np.testing.assert_array_equal(got, want)
