"""Compiled-ruleset artifact cache tests (SURVEY.md §5 checkpoint/resume
equivalent)."""

import numpy as np

from pingoo_tpu.compiler.cache import compile_ruleset_cached, ruleset_fingerprint
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine import encode_requests, evaluate_batch, make_verdict_fn
from pingoo_tpu.expr import Ip, compile_expression
from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic


def test_cache_roundtrip_same_verdicts(tmp_path):
    rules, lists = generate_ruleset(80, with_lists=True, list_sizes=(64, 16))
    cache = str(tmp_path / "cache")

    plan1 = compile_ruleset_cached(rules, lists, cache_dir=cache)
    # Structural cache-hit check (timing asserts flake on loaded machines):
    # the second call must not invoke the compiler at all.
    import pingoo_tpu.compiler.cache as cache_mod

    original = cache_mod.compile_ruleset
    calls = []
    cache_mod.compile_ruleset = lambda *a, **k: calls.append(1) or original(*a, **k)
    try:
        plan2 = compile_ruleset_cached(rules, lists, cache_dir=cache)
    finally:
        cache_mod.compile_ruleset = original
    assert calls == []  # artifact hit skipped compilation

    reqs = generate_traffic(32, lists=lists, seed=9)
    batch = encode_requests(reqs)
    m1 = evaluate_batch(plan1, make_verdict_fn(plan1), plan1.device_tables(),
                        batch, lists)
    m2 = evaluate_batch(plan2, make_verdict_fn(plan2), plan2.device_tables(),
                        batch, lists)
    np.testing.assert_array_equal(m1, m2)


def test_fingerprint_sensitivity(tmp_path):
    r1 = [RuleConfig(name="r", actions=(Action.BLOCK,),
                     expression=compile_expression('http_request.path == "/a"'))]
    r2 = [RuleConfig(name="r", actions=(Action.BLOCK,),
                     expression=compile_expression('http_request.path == "/b"'))]
    l1 = {"ips": [Ip("10.0.0.0/8")]}
    l2 = {"ips": [Ip("10.0.0.0/9")]}
    assert ruleset_fingerprint(r1, l1) != ruleset_fingerprint(r2, l1)
    assert ruleset_fingerprint(r1, l1) != ruleset_fingerprint(r1, l2)
    assert ruleset_fingerprint(r1, l1) == ruleset_fingerprint(r1, l1)


def test_corrupt_artifact_ignored(tmp_path):
    rules, lists = generate_ruleset(10, with_lists=False)
    cache = str(tmp_path / "cache")
    plan1 = compile_ruleset_cached(rules, lists, cache_dir=cache)
    # Corrupt every artifact; the loader must recompile, not crash.
    import os

    for fname in os.listdir(cache):
        with open(os.path.join(cache, fname), "wb") as f:
            f.write(b"garbage")
    plan2 = compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert plan2.stats == plan1.stats


# -- v12 plan_proof block (ISSUE 18): cache hit == proof hit ---------------


def _count_proves(monkeypatch):
    """Patch cache.prove_plan to count invocations while preserving
    behavior (the cache module imported the name directly)."""
    import pingoo_tpu.compiler.cache as cache_mod
    from pingoo_tpu.compiler.obligations import prove_plan as real

    calls = []

    def counted(plan, fingerprint=""):
        calls.append(fingerprint)
        return real(plan, fingerprint)

    monkeypatch.setattr(cache_mod, "prove_plan", counted)
    return calls


def test_valid_proof_block_skips_reprove(tmp_path, monkeypatch):
    rules, lists = generate_ruleset(10, with_lists=False)
    cache = str(tmp_path / "cache")
    calls = _count_proves(monkeypatch)
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert len(calls) == 1  # fresh compile proved once
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert len(calls) == 1  # hit with a valid proof block: no re-prove


def test_tampered_proof_block_forces_reprove(tmp_path, monkeypatch):
    import os
    import pickle

    rules, lists = generate_ruleset(10, with_lists=False)
    cache = str(tmp_path / "cache")
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    fname = os.listdir(cache)[0]
    path = os.path.join(cache, fname)
    with open(path, "rb") as f:
        doc = pickle.load(f)
    assert doc["plan_proof"]["ok"] is True
    doc["plan_proof"]["obligations"][0]["name"] = "tampered"
    with open(path, "wb") as f:
        pickle.dump(doc, f)
    calls = _count_proves(monkeypatch)
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert len(calls) == 1  # digest mismatch: loaded plan re-proved
    # ... and the re-proved block was re-persisted: next hit is clean.
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert len(calls) == 1


def test_absent_proof_block_forces_reprove(tmp_path, monkeypatch):
    import os
    import pickle

    rules, lists = generate_ruleset(10, with_lists=False)
    cache = str(tmp_path / "cache")
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    fname = os.listdir(cache)[0]
    path = os.path.join(cache, fname)
    with open(path, "rb") as f:
        doc = pickle.load(f)
    del doc["plan_proof"]
    with open(path, "wb") as f:
        pickle.dump(doc, f)
    calls = _count_proves(monkeypatch)
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert len(calls) == 1


def test_proof_block_pins_fingerprint(tmp_path):
    import os
    import pickle

    from pingoo_tpu.compiler.obligations import proof_block_valid

    rules, lists = generate_ruleset(10, with_lists=False)
    cache = str(tmp_path / "cache")
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    fname = os.listdir(cache)[0]
    with open(os.path.join(cache, fname), "rb") as f:
        doc = pickle.load(f)
    block = doc["plan_proof"]
    fp = doc["fingerprint"]
    assert proof_block_valid(block, fp)
    assert not proof_block_valid(block, "deadbeef" + fp[8:])
    assert not proof_block_valid(None, fp)


def test_prove_off_skips_proving(tmp_path, monkeypatch):
    monkeypatch.setenv("PINGOO_PROVE", "off")
    rules, lists = generate_ruleset(10, with_lists=False)
    cache = str(tmp_path / "cache")
    calls = _count_proves(monkeypatch)
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    compile_ruleset_cached(rules, lists, cache_dir=cache)
    assert calls == []
